"""Sharded-step scaling at the full 100k headline shape (VERDICT r3 #3).

Runs the peer-sharded network step on a virtual CPU mesh at 1/2/4/8
devices, at the REAL benchmark shape (the round-3 evidence stopped at 16k),
and prints per-device-count:
  - wall time per tick (virtual CPU devices — a thread-contention proxy,
    not a chip number; the INVENTORY is the evidence that transfers),
  - the compiled collective inventory (op counts + per-shard payload bytes),
  - the payload accounting the roofline model needs: how many bytes each
    device contributes to / receives from cross-shard exchanges per tick.

Must run with a scrubbed env (the axon wedge, see utils/platform_probe):
    python scripts/shard_scale.py [n_peers] [ticks]
re-execs itself in a forced-CPU child with 8 virtual devices.
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def child_main(n_peers: int, ticks: int) -> None:
    import jax
    import numpy as np

    from __graft_entry__ import _build, _collective_inventory
    from go_libp2p_pubsub_tpu.parallel.sharding import (
        make_mesh, make_sharded_step, shard_state)

    devs = jax.devices()
    print(f"platform={devs[0].platform} n_devices={len(devs)}", flush=True)
    cfg, tp, st0 = _build(n_peers=n_peers, k_slots=32, degree=12,
                          msg_window=64, publishers=8)

    for nd in (1, 2, 4, 8):
        if nd > len(devs) or n_peers % nd:
            continue
        mesh = make_mesh(devs[:nd])
        step = make_sharded_step(mesh, cfg, tp)
        st = shard_state(st0, mesh, cfg)
        key = jax.random.PRNGKey(0)
        lowered = step.lower(st, key)
        compiled = lowered.compile()
        txt = compiled.as_text()
        inv = _collective_inventory(txt)
        # drive the AOT executable directly — step() would re-trace and
        # re-compile through the jit dispatch cache, doubling the dominant
        # cost of this script per device count. The executable's signature
        # is (state, tp, key): tp rides as an argument, not a hoisted
        # closure constant (parallel/sharding.py note).
        for i in range(3):       # warm + converge so measured ticks are typical
            st = compiled(st, tp, jax.random.fold_in(key, i))
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        for i in range(ticks):
            st = compiled(st, tp, jax.random.fold_in(key, 100 + i))
        jax.block_until_ready(st)
        dt = (time.perf_counter() - t0) / ticks
        print(f"devices={nd}: {dt * 1e3:8.1f} ms/tick   {inv}", flush=True)


def main() -> None:
    n_peers = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    if os.environ.get("_SHARD_SCALE_CHILD") == "1":
        child_main(n_peers, ticks)
        return
    from go_libp2p_pubsub_tpu.utils.platform_probe import cpu_mesh_env
    env = cpu_mesh_env(dict(os.environ), 8)
    env["_SHARD_SCALE_CHILD"] = "1"
    raise SystemExit(subprocess.run(
        [sys.executable, "-u", __file__, str(n_peers), str(ticks)],
        env=env).returncode)


if __name__ == "__main__":
    main()
