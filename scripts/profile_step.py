"""Per-phase timing of the sim step + gather microbenchmarks on the TPU.

Usage: python scripts/profile_step.py [N]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from __graft_entry__ import _build
from go_libp2p_pubsub_tpu.ops.churn import churn_edges
from go_libp2p_pubsub_tpu.ops.heartbeat import heartbeat, edge_gather
from go_libp2p_pubsub_tpu.ops.propagate import forward_tick, publish
from go_libp2p_pubsub_tpu.ops.score_ops import decay_counters, compute_scores
from go_libp2p_pubsub_tpu.sim.engine import step


def timeit(fn, *args, reps=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg, tp, st = _build(n_peers=n, k_slots=32, degree=12, msg_window=64,
                         publishers=8)
    key = jax.random.PRNGKey(0)
    k_pub, k_hb, k_fwd = jax.random.split(key, 3)

    # converge a bit first
    st = jax.jit(step, static_argnames=("cfg",))(st, cfg, tp, key)
    jax.block_until_ready(st)

    print(f"== N={n} k={cfg.k_slots} T={cfg.n_topics} M={cfg.msg_window} "
          f"hops={cfg.prop_substeps} on {jax.devices()[0].platform} ==")

    t = timeit(jax.jit(step, static_argnames=("cfg",)), st, cfg, tp, key)
    print(f"full step:        {t*1e3:9.2f} ms")

    peers = jnp.zeros(8, jnp.int32)
    topics = jnp.zeros(8, jnp.int32)
    t = timeit(jax.jit(publish, static_argnames=("cfg",)), st, cfg, peers, topics)
    print(f"  publish:        {t*1e3:9.2f} ms")
    t = timeit(jax.jit(decay_counters, static_argnames=("cfg",)), st, cfg, tp)
    print(f"  decay_counters: {t*1e3:9.2f} ms")
    t = timeit(jax.jit(compute_scores, static_argnames=("cfg",)), st, cfg, tp)
    print(f"  compute_scores: {t*1e3:9.2f} ms")
    hb_jit = jax.jit(heartbeat, static_argnames=("cfg",))
    t = timeit(hb_jit, st, cfg, tp, k_hb)
    print(f"  heartbeat:      {t*1e3:9.2f} ms")
    hb = hb_jit(st, cfg, tp, k_hb)
    jax.block_until_ready(hb)
    t = timeit(jax.jit(forward_tick, static_argnames=("cfg",)),
               hb.state, cfg, tp, hb.inc_gossip, hb.scores, k_fwd)
    print(f"  forward_tick:   {t*1e3:9.2f} ms")

    # ---- gather microbenchmarks ----
    w, k = 2, cfg.k_slots
    keyr = jax.random.PRNGKey(1)
    x_w = jax.random.randint(keyr, (w, n), 0, 2**31 - 1, dtype=jnp.int32).astype(jnp.uint32)
    nbr_t = jax.random.randint(keyr, (k, n), 0, n, dtype=jnp.int32)
    nbr = nbr_t.T                                       # [N, K]
    x_nm = x_w.T                                        # [N, W] peer-major

    def g_loop(xw, nt):
        return jnp.stack([xw[i][nt] for i in range(w)])

    def g_take3d(xw, nt):
        return xw[:, nt]

    def g_rows(xnm, nb):
        return xnm[nb]                                  # [N, K, W]

    t = timeit(jax.jit(g_loop), x_w, nbr_t)
    print(f"gather per-word loop [W={w},K,N]:   {t*1e3:9.2f} ms")
    t = timeit(jax.jit(g_take3d), x_w, nbr_t)
    print(f"gather 3d take      [W={w},K,N]:   {t*1e3:9.2f} ms")
    t = timeit(jax.jit(g_rows), x_nm, nbr)
    print(f"gather rows [N,K,W] peer-major:    {t*1e3:9.2f} ms")

    # edge_gather on [N, T, K]
    x3 = jax.random.uniform(keyr, (n, cfg.n_topics, k)) > 0.5
    t = timeit(jax.jit(lambda x, s: edge_gather(x, s)), x3, st)
    print(f"edge_gather [N,T,K]:               {t*1e3:9.2f} ms")

    # row-based edge gather: flatten (n,t,k) -> rows by neighbor, then pick
    # reverse_slot via one-hot dot over K (K small) vs take_along_axis
    def edge_rows(x, s):
        jn = jnp.clip(s.neighbors, 0, n - 1)            # [N, K]
        rows = x[jn]                                    # [N, K, T, K'] row gather
        rk = jnp.clip(s.reverse_slot, 0, k - 1)
        picked = jnp.take_along_axis(
            rows, rk[:, :, None, None], axis=-1)[..., 0]  # [N, K, T]
        valid = ((s.neighbors >= 0) & (s.reverse_slot >= 0))[:, :, None]
        return jnp.where(valid, picked, False).transpose(0, 2, 1)

    t = timeit(jax.jit(edge_rows), x3, st)
    print(f"edge_gather row-form:              {t*1e3:9.2f} ms")

    # one-hot matmul edge pick: rows[N,K,T,K'] dot onehot(rk)[N,K,K']
    def edge_rows_oh(x, s):
        jn = jnp.clip(s.neighbors, 0, n - 1)
        rows = x[jn].astype(jnp.bfloat16)               # [N, K, T, K']
        oh = jax.nn.one_hot(jnp.clip(s.reverse_slot, 0, k - 1), k,
                            dtype=jnp.bfloat16)         # [N, K, K']
        picked = jnp.einsum('nktj,nkj->nkt', rows, oh)
        valid = ((s.neighbors >= 0) & (s.reverse_slot >= 0))[:, :, None]
        return (picked > 0.5) & valid

    t = timeit(jax.jit(edge_rows_oh), x3, st)
    print(f"edge_gather row+onehot:            {t*1e3:9.2f} ms")


if __name__ == "__main__":
    main()
