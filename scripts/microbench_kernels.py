"""Microbenchmarks of candidate kernel formulations on the live accelerator.

Each candidate runs inside a 10-iteration lax.scan in one jit call so the
remote-dispatch latency amortizes. Shapes default to the 10k-beacon scenario
(N=10000, T=9, K=48, M=64); pass N T K M to override.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from go_libp2p_pubsub_tpu.ops.selection import ranks_desc

ITERS = 10


def scan_time(fn, args, label):
    @jax.jit
    def many(a):
        def body(c, _):
            # optimization_barrier ties the inputs to the loop carry:
            # without it XLA hoists the (loop-invariant) computation out of
            # the scan and the harness under-reports by ~ITERS x
            c = jax.lax.optimization_barrier(c)
            out = fn(*c[1:]) if isinstance(c, tuple) else fn(c)
            # fold output back into carry position 0 to serialize iterations
            return (out, *c[1:]) if isinstance(c, tuple) else out, None
        (out, *_), _ = jax.lax.scan(body, a, None, length=ITERS)
        return out

    # carry: (accumulator, *inputs); accumulator must match fn output shape
    out0 = fn(*args[1:])
    carry = (out0, *args[1:])
    r = many(carry)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = many(carry)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{label:44s} {dt*1e3:9.3f} ms", flush=True)
    return dt


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    t = int(sys.argv[2]) if len(sys.argv) > 2 else 9
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 48
    m = int(sys.argv[4]) if len(sys.argv) > 4 else 64
    w = (m + 31) // 32
    print(f"== N={n} T={t} K={k} M={m} W={w} on "
          f"{jax.devices()[0].platform} ==", flush=True)
    key = jax.random.PRNGKey(0)
    kk = jax.random.split(key, 10)

    mask = jax.random.uniform(kk[0], (n, t, k)) < 0.5
    score = jax.random.normal(kk[1], (n, t, k))
    count = jax.random.randint(kk[2], (n, t), 0, k)
    nbr = jax.random.randint(kk[3], (n, k), 0, n, dtype=jnp.int32)
    rk = jax.random.randint(kk[4], (n, k), 0, k, dtype=jnp.int32)
    words = jax.random.randint(kk[5], (w, n), 0, 2**31 - 1,
                               dtype=jnp.int32).astype(jnp.uint32)
    planes = (jax.random.uniform(kk[6], (n, m)) < 0.3)   # unpacked messages

    # ---------- selection: ranks vs sort-threshold ----------
    def sel_ranks(score, mask, count):
        keys = jnp.where(mask, score, -1e30)
        r = ranks_desc(keys)
        return (r < count[..., None]) & mask

    def sel_sort(score, mask, count):
        tb = -jnp.arange(k, dtype=jnp.float32) * 1e-9
        keys = jnp.where(mask, score + tb, -1e30)
        srt = jnp.sort(keys, axis=-1)[..., ::-1]          # descending
        idx = jnp.clip(count - 1, 0, k - 1)
        thr = jnp.take_along_axis(srt, idx[..., None], axis=-1)
        return mask & (keys >= thr) & (count[..., None] > 0)

    from go_libp2p_pubsub_tpu.core.params import GOSSIPSUB_DHI

    def sel_iter(score, mask, count, max_count=GOSSIPSUB_DHI):
        # O(c*K) iterative argmax: c sequential first-occurrence maxima,
        # exact tie parity with ranks_desc (lower index wins). Candidate
        # for counts << K (heartbeat counts are <= Dhi vs K=48).
        keys = jnp.where(mask, score, -1e30)

        def body(i, carry):
            sel, rem = carry
            idx = jnp.argmax(rem, axis=-1)
            take = (i < count) & jnp.take_along_axis(
                mask, idx[..., None], axis=-1)[..., 0]
            onehot = (jnp.arange(k)[None, None, :] == idx[..., None]) \
                & take[..., None]
            return sel | onehot, jnp.where(onehot, -1e30, rem)

        sel, _ = jax.lax.fori_loop(
            0, max_count, body, (jnp.zeros_like(mask), keys))
        return sel

    a = sel_ranks(score, mask, count)
    b = sel_sort(score, mask, count)
    # the iterative form only applies when counts are bounded << K (true
    # for every heartbeat selection: counts <= Dhi=12); bench it at the
    # engine's real count regime
    count_small = jnp.minimum(count, GOSSIPSUB_DHI)
    a_small = sel_ranks(score, mask, count_small)
    c_ = sel_iter(score, mask, count_small)
    assert bool(jnp.all(a == b)), "sort-threshold != ranks selection"
    assert bool(jnp.all(a_small == c_)), "iterative != ranks selection"
    scan_time(sel_ranks, (a, score, mask, count), "select: O(K^2) ranks")
    scan_time(sel_sort, (a, score, mask, count), "select: sort+threshold")
    scan_time(sel_iter, (a_small, score, mask, count_small),
              f"select: O(c*K) iter c<={GOSSIPSUB_DHI}")

    # ---------- edge gather [N,T,K] ----------
    def eg_adv(x):
        j = nbr[:, None, :]
        r = rk[:, None, :]
        tt = jnp.arange(t)[None, :, None]
        return x[j, tt, r]

    def eg_packed(x):
        # pack T bools into one u32 per (n,k); gather [N,K] scalars; unpack
        tb = (jnp.uint32(1) << jnp.arange(t, dtype=jnp.uint32))
        packed = jnp.sum(jnp.where(x, tb[None, :, None], jnp.uint32(0)),
                         axis=1, dtype=jnp.uint32)          # [N, K]
        g = packed[nbr, rk]                                 # [N, K] scalars
        return (g[:, None, :] >> jnp.arange(t, dtype=jnp.uint32)[None, :, None]
                & 1).astype(bool)

    def eg_rows_pick(x):
        # pack T -> u32 [N,K]; ROW-gather each receiver's neighbor K'-rows
        # ([N,K,K'] u32); pick reverse_slot per edge via bitplane select
        tb = (jnp.uint32(1) << jnp.arange(t, dtype=jnp.uint32))
        packed = jnp.sum(jnp.where(x, tb[None, :, None], jnp.uint32(0)),
                         axis=1, dtype=jnp.uint32)          # [N, K]
        rows = packed[nbr]                                  # [N, K, K'] rows
        g = jnp.take_along_axis(rows, rk[:, :, None], axis=-1)[..., 0]
        return (g[:, None, :] >> jnp.arange(t, dtype=jnp.uint32)[None, :, None]
                & 1).astype(bool)

    from go_libp2p_pubsub_tpu.ops.permgather import (
        permutation_gather, resolve_mode)
    # what "pallas" actually resolves to at this shape (VMEM eligibility) —
    # printed so a fallback to rows can't masquerade as a pallas datapoint
    pallas_resolved = resolve_mode("pallas", jnp.uint32, n, k)

    def eg_pallas(x):
        # pack T -> u32 [N,K]; VMEM-resident pallas row-take + lane pick
        tb = (jnp.uint32(1) << jnp.arange(t, dtype=jnp.uint32))
        packed = jnp.sum(jnp.where(x, tb[None, :, None], jnp.uint32(0)),
                         axis=1, dtype=jnp.uint32)          # [N, K]
        g = permutation_gather(packed, nbr, rk, "pallas")
        return (g[:, None, :] >> jnp.arange(t, dtype=jnp.uint32)[None, :, None]
                & 1).astype(bool)

    x3 = mask
    a = eg_adv(x3)
    b = eg_packed(x3)
    c = eg_rows_pick(x3)
    d = eg_pallas(x3)
    assert bool(jnp.all(a == b)) and bool(jnp.all(a == c)) \
        and bool(jnp.all(a == d))
    scan_time(eg_adv, (a, x3), "edge_gather: advanced-index [N,T,K]")
    scan_time(eg_packed, (a, x3), "edge_gather: T-packed u32 [N,K]")
    scan_time(eg_rows_pick, (a, x3), "edge_gather: row-gather + lane pick")
    scan_time(eg_pallas, (a, x3),
              f"edge_gather: pallas (resolved: {pallas_resolved})")

    # ---------- neighbor message gather ----------
    nbr_t = nbr.T                                           # [K, N]

    def gw_words(wds):
        return jnp.stack([wds[i][nbr_t] for i in range(w)])  # [W,K,N]

    def gw_rows_i8(pl):
        g = pl.astype(jnp.int8)[nbr]                        # [N,K,M] row gather
        return g

    def gw_rows_u32(wds):
        rows = wds.T[nbr]                                   # [N,K,W]
        return rows

    from go_libp2p_pubsub_tpu.ops.permgather import (
        gather_words, resolve_words_mode)
    words_resolved = resolve_words_mode("pallas", w, n, k)

    def gw_pallas(wds):
        return gather_words(wds, nbr, m, "pallas")

    # the gather-free two-level MXU take (ops/mxutake.py) — the sort-vs-mxu
    # A/B datapoint the engine-level GRAFT_EDGE_GATHER=mxu sweep banks
    mxu_resolved = resolve_words_mode("mxu", w, n, k)

    def gw_mxu(wds):
        return gather_words(wds, nbr, m, "mxu")

    assert bool(jnp.all(gw_pallas(words) == gw_words(words)))
    assert bool(jnp.all(gw_mxu(words) == gw_words(words)))
    scan_time(gw_words, (gw_words(words), words),
              "msg gather: per-word scalar [W,K,N]")
    scan_time(gw_rows_i8, (gw_rows_i8(planes), planes),
              "msg gather: row-major i8 [N,K,M]")
    scan_time(gw_rows_u32, (gw_rows_u32(words), words),
              "msg gather: row-major u32 [N,K,W]")
    scan_time(gw_pallas, (gw_pallas(words), words),
              f"msg gather: pallas (resolved: {words_resolved})")
    scan_time(gw_mxu, (gw_mxu(words), words),
              f"msg gather: mxu two-level take (resolved: {mxu_resolved})")

    # ---------- OR-reduce over K after row gather ----------
    rows_i8 = gw_rows_i8(planes)

    def or_reduce(r):
        return jnp.max(r, axis=1)                           # [N, M]

    scan_time(or_reduce, (or_reduce(rows_i8), rows_i8),
              "OR-reduce over K (i8 rows)")

    # ---------- one-hot matmul gather (MXU) ----------
    def gw_onehot(pl):
        oh = jax.nn.one_hot(nbr, n, dtype=jnp.bfloat16)     # [N,K,N] -- huge
        return jnp.einsum('nkj,jm->nkm', oh, pl.astype(jnp.bfloat16))

    if n <= 4096:
        scan_time(gw_onehot, (gw_onehot(planes), planes),
                  "msg gather: one-hot MXU [N,K,N]@[N,M]")


if __name__ == "__main__":
    main()
