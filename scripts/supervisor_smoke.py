"""Tier-1-safe smoke of the full supervised-execution ladder on a tiny
config: deadline trip -> backoff -> degraded mode -> checkpoint/resume ->
crash dump -> replay, each stage asserting bit-identical trajectories
against the plain single-scan reference.

Prints one JSON line per stage; exit 0 iff every stage behaved. Run by
scripts/tpu_recheck.sh (``supervisor_smoke`` step) so every live window
re-proves the supervision plane on the real backend, and driven in-proc
by tests/test_supervisor.py::test_full_ladder_smoke for CI.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _states_equal(a, b) -> bool:
    import numpy as np
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(a, b))


def run_smoke(base_dir: str | None = None, emit=print) -> int:
    import dataclasses

    import jax
    import numpy as np

    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.engine import run
    from go_libp2p_pubsub_tpu.sim.supervisor import (
        SupervisorConfig, SupervisorCrash, supervised_run)
    from scripts.replay_crash import replay

    own_tmp = None
    if base_dir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="graft_sup_smoke_")
        base_dir = own_tmp.name
    ok = True

    def stage(name, passed, **info):
        nonlocal ok
        ok = ok and passed
        emit(json.dumps({"stage": name,
                         "status": "ok" if passed else "FAIL", **info}))

    try:
        n_ticks = 12
        kwargs = dict(n_peers=128, k_slots=16, degree=6)
        # edge_gather "sort" so the degrade rung has a non-default mode to
        # fall back from (all formulations are bit-identical, so parity
        # holds across the fallback — that IS the rung's safety argument)
        cfg, tp, st = scenarios.single_topic_1k(**kwargs)
        cfg = dataclasses.replace(cfg, edge_gather_mode="sort")
        key = jax.random.PRNGKey(11)
        t0 = time.perf_counter()
        ref = run(st, cfg, tp, key, n_ticks)
        np.asarray(ref.tick)
        ref_s = time.perf_counter() - t0

        # --- stage 1: deadline trip -> backoff -> degraded mode, parity
        # deadline scales with the measured reference (a 4-tick chunk is
        # ~ref_s/3) with a 0.6s floor, so a slow real backend (the ~66 ms
        # axon fetch RTT) cannot spuriously trip it; the hook then sleeps
        # PAST that deadline to force exactly one genuine trip
        deadline = max(0.6, 10 * ref_s / 3)

        def slow_first(info):
            if info["chunk_start"] == 0 and info["attempt"] == 0:
                time.sleep(deadline + 1.0)
        sup = SupervisorConfig(
            chunk_ticks=4, deadline_s=deadline,
            checkpoint_dir=os.path.join(base_dir, "ck"),
            backoff_base_s=0.01, scenario="1k_single_topic",
            scenario_kwargs=kwargs)
        out, rep = supervised_run(st, cfg, tp, key, n_ticks, sup,
                                  _chunk_hook=slow_first)
        evs = [e["event"] for e in rep.events]
        stage("deadline_backoff_degrade",
              _states_equal(out, ref) and rep.retries >= 1
              and "degrade" in evs and "backoff" in evs,
              retries=rep.retries, degrade_level=rep.degrade_level,
              events=evs[:8])

        # --- stage 2: kill mid-run, resume from checkpoint, parity
        def kill_late(info):
            if info["chunk_start"] >= 8:
                raise KeyboardInterrupt("smoke: simulated preemption")
        sup2 = SupervisorConfig(
            chunk_ticks=4, checkpoint_dir=os.path.join(base_dir, "ck2"))
        interrupted = False
        try:
            supervised_run(st, cfg, tp, key, n_ticks, sup2,
                           _chunk_hook=kill_late)
        except KeyboardInterrupt:
            interrupted = True
        out2, rep2 = supervised_run(st, cfg, tp, key, n_ticks, sup2)
        stage("checkpoint_resume",
              interrupted and rep2.resumed_tick == 8
              and _states_equal(out2, ref),
              resumed_tick=rep2.resumed_tick)

        # --- stage 3: permanent failure -> crash dump -> replay
        def boom(info):
            raise RuntimeError("smoke: injected permanent failure")
        sup3 = SupervisorConfig(
            chunk_ticks=4, max_retries=1, backoff_base_s=0.0,
            sleep=lambda s: None, crash_dir=os.path.join(base_dir, "crash"),
            scenario="1k_single_topic", scenario_kwargs=kwargs)
        dump = None
        try:
            supervised_run(st, cfg, tp, key, n_ticks, sup3,
                           _chunk_hook=boom)
        except SupervisorCrash as e:
            dump = e.dump_dir
        # replay the dumped window (the injected failure was host-side, so
        # the replay must come back CLEAN — flags 0, no trip). The scenario
        # was stamped, but its fingerprint differs from the sort-mode cfg
        # actually run, so hand the objects over directly.
        rep_result = None
        if dump:
            rep_result = replay(dump, like=st, cfg=cfg, tp=tp)
        stage("crash_dump_replay",
              dump is not None and rep_result is not None
              and rep_result["tripped"] is False
              and rep_result.get("fault_flags") == 0,
              dump=dump, replay=rep_result)

        # --- stage 4: GRAFT_CHAOS-style stall -> deadline trip -> retry,
        # once-only marker semantics, parity. A stall (not a kill: this
        # smoke runs IN-PROCESS under pytest) armed for chunk_start>=4
        # sleeps past the deadline exactly once — the durable marker file
        # in the run dir keeps the retry from refiring, which is the same
        # mechanism that lets mh_supervisor.py relaunch a chaos-killed
        # group without the chaos killing it again.
        from go_libp2p_pubsub_tpu.parallel.resilience import ChaosPlan
        chaos_dir = os.path.join(base_dir, "chaos")
        os.makedirs(chaos_dir, exist_ok=True)
        plan = ChaosPlan(ChaosPlan.parse(f"stall@0:4:{deadline + 1.0}"),
                         rank=0, run_dir=chaos_dir)
        sup4 = SupervisorConfig(
            chunk_ticks=4, deadline_s=deadline, backoff_base_s=0.01,
            scenario="1k_single_topic", scenario_kwargs=kwargs)
        out4, rep4 = supervised_run(st, cfg, tp, key, n_ticks, sup4,
                                    _chunk_hook=plan.fire)
        markers = [m for m in os.listdir(chaos_dir)
                   if m.startswith("chaos_") and m.endswith(".fired")]
        stage("chaos_stall_recovery",
              _states_equal(out4, ref) and rep4.retries >= 1
              and len(markers) == 1,
              retries=rep4.retries, markers=markers)
    finally:
        if own_tmp is not None:
            own_tmp.cleanup()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(run_smoke())
