#!/usr/bin/env python
"""Multihost relaunch supervisor: owns the process group, survives ranks.

``scripts/run_multihost.py`` launches ONE rank; a rank failure under
multi-process execution is deliberately FATAL there (the retry/degrade
ladder is rank-local and cannot be rank-symmetric — sim/supervisor.py
``handle_failure``). This driver is the recovery half the fail-fast
contract promised: it launches ALL ranks, watches them (exit codes +
heartbeat progress from the shared ``--run-dir``,
parallel/resilience.py), and on ANY rank death/stall tears the whole
group down and relaunches every rank from the last drained checkpoint —
bounded retries, exponential backoff, and a rank-SYMMETRIC degrade
ladder: the agreed rung is recorded (fsync'd) in the run journal BEFORE
the relaunch and handed to every rank via ``GRAFT_MH_RUNG``, so all
ranks compile the same program by construction.

Elastic resume rides the same loop: ``--procs`` takes a comma schedule
("8,8,4" = first two attempts at 8 processes, all later ones at 4), and
because multihost checkpoints are gathered host-complete
(sim/checkpoint.py stamps ``processes=P`` as provenance, not a refusal),
a relaunch at P' re-slices the same checkpoint — a preempted 8-host run
finishes on 4.

2-process CPU example (chaos-killed rank, elastic finish at 1):

    JAX_PLATFORMS=cpu GRAFT_CHAOS=kill@1:4 python scripts/mh_supervisor.py \
        --procs 2,1 --scenario frontier_250k --n 128 --ticks 6 \
        --chunk-ticks 2 --run-dir /tmp/mh --dump-state /tmp/mh/final.npz

Everything here is deliberately jax-free: the parent must stay cheap,
boot instantly, and never share backend state with its children.
"""

import argparse
import json
import os
import re
import shutil
import signal
import socket
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from go_libp2p_pubsub_tpu.parallel import resilience

_CKPT_RE = re.compile(r"^ckpt_t(\d+)")

# how long a rank may linger after a sibling exited cleanly before the
# group is judged wedged (teardown skew is seconds; a collective blocked
# on the exited rank is forever)
_EXIT_LINGER_S = 30.0


def parse_procs(text: str) -> list:
    """``"8,8,4"`` → ``[8, 8, 4]``: attempt i runs schedule[min(i, last)]
    processes. Raises ``ValueError`` naming --procs on junk."""
    out = []
    for part in filter(None, (p.strip() for p in text.split(","))):
        try:
            v = int(part)
        except ValueError as e:
            raise ValueError(
                f"--procs entry {part!r} is not an integer "
                "(expected a comma schedule like 8,8,4)") from e
        if v <= 0:
            raise ValueError(f"--procs entry {part!r} must be positive")
        out.append(v)
    if not out:
        raise ValueError("--procs schedule is empty")
    return out


def _newest_ckpt_tick(ckpt_dir: str) -> int | None:
    """Newest supervisor-checkpoint tick in ``ckpt_dir`` (None when
    empty). A local reimplementation of sim/supervisor.list_checkpoints'
    name scan: importing that module drags jax into this jax-free
    parent."""
    best = None
    if os.path.isdir(ckpt_dir):
        for name in os.listdir(ckpt_dir):
            m = _CKPT_RE.match(name)
            if m:
                t = int(m.group(1))
                best = t if best is None or t > best else best
    return best


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _heartbeat_ticks(run_dir: str, procs: int) -> dict:
    """``{rank: tick}`` progress from the heartbeat files (stall
    detection: a group whose every rank is alive but whose ticks stopped
    moving is wedged — the rank-side dead-PEER detector can't see that)."""
    out = {}
    for r in range(procs):
        try:
            with open(resilience.heartbeat_path(run_dir, r)) as f:
                out[r] = int(json.load(f).get("tick", -1))
        except (OSError, ValueError, json.JSONDecodeError):
            pass
    return out


class _Journal:
    """Append-only fsync'd NDJSON at ``run_dir/mh_journal.jsonl`` — the
    relaunch decisions OF RECORD. The rung line lands durably BEFORE the
    ranks it governs launch: a parent crash between the two can only
    replay the same decision, never hand different ranks different
    programs."""

    def __init__(self, path: str):
        self.path = path

    def record(self, **rec) -> None:
        rec.setdefault("wall", time.time())
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())


def _launch_rank(args, rank: int, procs: int, coordinator: str,
                 attempt: int, rung: int, run_dir: str):
    env = dict(os.environ)
    env["GRAFT_COORDINATOR"] = coordinator
    env["GRAFT_NUM_PROCESSES"] = str(procs)
    env["GRAFT_PROCESS_ID"] = str(rank)
    env["GRAFT_MH_RUN_DIR"] = run_dir
    env["GRAFT_MH_RUNG"] = str(rung)
    env["GRAFT_MH_RELAUNCHES"] = str(attempt)
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "run_multihost.py"),
           "--scenario", args.scenario, "--ticks", str(args.ticks),
           "--seed", str(args.seed),
           "--checkpoint-dir", os.path.join(run_dir, "ckpt")]
    if args.n:
        cmd += ["--n", str(args.n)]
    if args.engine:
        cmd += ["--engine", args.engine]
    if args.bucketed_rng:
        cmd += ["--bucketed-rng", args.bucketed_rng]
    if args.topology:
        cmd += ["--topology", args.topology]
    if args.chunk_ticks:
        cmd += ["--chunk-ticks", str(args.chunk_ticks)]
    if args.health:
        # --health changes the COMPILED program (run_multihost wires
        # telemetry= into the sharded run_fn), so EVERY rank must get it
        # — rank-0-only here would hand ranks different collective
        # sequences and wedge the group, the exact asymmetry hazard this
        # driver exists to close; write_files keeps the writing on rank 0
        cmd += ["--health", args.health]
    if args.source:
        # --source changes the COMPILED program too (the per-boundary
        # directive frame broadcast + replay apply are collectives every
        # rank must trace identically), so EVERY rank gets the flag;
        # only rank 0 actually tails the file
        cmd += ["--source", args.source,
                "--directive-slots", str(args.directive_slots),
                "--ingest-stall-timeout", str(args.ingest_stall_timeout),
                "--ingest-coast-poll", str(args.ingest_coast_poll)]
    if args.contracts:
        # EVERY rank folds the verdict monitors (the abort policy must
        # fire rank-symmetrically); only rank 0 journals the notes
        cmd += ["--contracts", args.contracts]
    if args.verdict_policy:
        cmd += ["--verdict-policy", args.verdict_policy]
    if rank == 0:
        if args.dump_state:
            cmd += ["--dump-state", args.dump_state]
        if args.journal:
            cmd += ["--journal", args.journal]
    log = open(os.path.join(run_dir, f"rank{rank}.attempt{attempt}.log"),
               "w")
    proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=log)
    return proc, log


def _teardown(procs: list) -> None:
    for p, _log in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.time() + 5.0
    for p, _log in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            try:
                p.kill()
                p.wait(timeout=5.0)
            except OSError:
                pass
    for _p, log in procs:
        try:
            log.close()
        except OSError:
            pass


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--procs", required=True,
                    help="comma process-count schedule: attempt i uses "
                         "entry min(i, last) — '8,8,4' relaunches twice "
                         "at 8 then elastically finishes at 4")
    ap.add_argument("--scenario", default="frontier_250k")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--engine", default=None,
                    choices=["dense", "bucketed"],
                    help="forwarded to every rank (run_multihost.py "
                         "--engine): bucketed drives the powerlaw family "
                         "on the row-sharded degree-bucketed step")
    ap.add_argument("--bucketed-rng", default=None,
                    choices=["bucket", "dense"],
                    help="forwarded to every rank (run_multihost.py)")
    ap.add_argument("--topology", default=None,
                    choices=[None, "replicated", "sharded"])
    ap.add_argument("--ticks", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk-ticks", type=int, default=None)
    ap.add_argument("--run-dir", required=True,
                    help="SHARED directory this supervisor owns: "
                         "checkpoints (ckpt/), heartbeats, chaos "
                         "markers, mh_journal.jsonl, per-rank logs")
    ap.add_argument("--base-port", type=int, default=0,
                    help="coordinator port; 0 = a fresh free port per "
                         "attempt (a TIME_WAIT corpse from the killed "
                         "group must not wedge the relaunch)")
    ap.add_argument("--max-relaunches", type=int, default=4)
    ap.add_argument("--backoff-base-s", type=float, default=1.0)
    ap.add_argument("--backoff-factor", type=float, default=2.0)
    ap.add_argument("--backoff-cap-s", type=float, default=60.0)
    ap.add_argument("--stall-timeout-s", type=float, default=600.0,
                    help="no heartbeat TICK progress for this long → the "
                         "group is wedged and torn down (covers "
                         "all-ranks-alive-but-blocked, which the "
                         "rank-side dead-peer detector can't see)")
    ap.add_argument("--fresh", action="store_true",
                    help="wipe the run-dir's checkpoints/markers/journal "
                         "first (a NEW run; default resumes)")
    ap.add_argument("--dump-state", default=None)
    ap.add_argument("--journal", default=None)
    ap.add_argument("--health", default=None)
    ap.add_argument("--source", default=None,
                    help="live command plane directive stream, forwarded "
                         "to every rank (run_multihost.py --source); the "
                         "checkpoint's stamped stream_offset makes "
                         "directive ingestion exactly-once across "
                         "relaunches")
    ap.add_argument("--directive-slots", type=int, default=64)
    ap.add_argument("--ingest-stall-timeout", type=float, default=10.0)
    ap.add_argument("--ingest-coast-poll", type=float, default=0.05)
    ap.add_argument("--contracts", default=None,
                    help="live contract specs (JSON list), forwarded to "
                         "every rank (run_multihost.py --contracts); the "
                         "checkpoint sidecar's monitor state makes "
                         "verdict journaling exactly-once across "
                         "relaunches")
    ap.add_argument("--verdict-policy", default=None,
                    choices=["journal", "snapshot", "abort"],
                    help="forwarded FAIL response; under 'abort' a "
                         "breach exits every rank with code 44, which "
                         "this driver treats as TERMINAL (mh_verdict_"
                         "abort journal line, no relaunch — the "
                         "trajectory would replay into the same breach)")
    args = ap.parse_args()

    try:
        schedule = parse_procs(args.procs)
    except ValueError as e:
        raise SystemExit(str(e))

    run_dir = os.path.abspath(args.run_dir)
    if args.fresh and os.path.isdir(run_dir):
        shutil.rmtree(run_dir)
    os.makedirs(run_dir, exist_ok=True)
    journal = _Journal(os.path.join(run_dir, "mh_journal.jsonl"))
    # the resume command of record: the dashboard's DEAD-RANK banner
    # surfaces this line verbatim
    resume_cmd = (f"python scripts/mh_supervisor.py --procs {args.procs} "
                  f"--scenario {args.scenario} --ticks {args.ticks} "
                  f"--seed {args.seed} --run-dir {run_dir}")
    journal.record(kind="mh_run", argv=sys.argv[1:], resume_cmd=resume_cmd,
                   schedule=schedule)

    # this process OWNS the group: if it is itself preempted (SIGTERM from
    # a scheduler, ctrl-C) the default handler would kill it without the
    # per-attempt finally below ever running, orphaning ranks that keep
    # beating — and possibly wedged in collectives — forever. Convert the
    # signals to SystemExit so teardown always runs; the journal records
    # the interruption and the resume command above picks the run back up.
    def _on_signal(signum, frame):
        try:
            journal.record(kind="mh_signal", signum=signum)
        except OSError:
            pass
        raise SystemExit(128 + signum)
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    ckpt_dir = os.path.join(run_dir, "ckpt")
    rung = 0
    for attempt in range(args.max_relaunches + 1):
        procs_n = schedule[min(attempt, len(schedule) - 1)]
        port = args.base_port or _free_port()
        coordinator = f"127.0.0.1:{port}"
        # stale heartbeat files from the previous (larger or killed)
        # group would read as instantly-dead peers
        for name in os.listdir(run_dir):
            if name.startswith("hb_rank"):
                try:
                    os.remove(os.path.join(run_dir, name))
                except OSError:
                    pass
        tick_before = _newest_ckpt_tick(ckpt_dir)
        # the rung lands fsync'd BEFORE any rank launches: every rank of
        # this attempt reads the SAME agreed rung (GRAFT_MH_RUNG) — the
        # rank-symmetric degrade ladder by construction
        journal.record(kind="mh_attempt", attempt=attempt, procs=procs_n,
                       rung=rung, coordinator=coordinator,
                       ckpt_tick=tick_before)
        print(json.dumps({"mh": "launch", "attempt": attempt,
                          "procs": procs_n, "rung": rung,
                          "ckpt_tick": tick_before}), flush=True)

        group = [_launch_rank(args, r, procs_n, coordinator, attempt,
                              rung, run_dir) for r in range(procs_n)]
        failure = None
        try:
            first_exit0: float | None = None
            last_progress = time.time()
            last_ticks = _heartbeat_ticks(run_dir, procs_n)
            verdict_abort = False
            while failure is None:
                time.sleep(0.25)
                codes = [p.poll() for p, _ in group]
                if any(c == resilience.EXIT_VERDICT_ABORT for c in codes):
                    # TERMINAL, not a crash: a live behavior contract
                    # failed under verdict_policy=abort and the group
                    # tore itself down cleanly at a chunk boundary.
                    # Relaunching would replay the same checkpointed
                    # trajectory into the same breach — don't.
                    verdict_abort = True
                    failure = "verdict_abort"
                    break
                if any(c is not None and c != 0 for c in codes):
                    failure = "rank_exit " + " ".join(
                        f"r{r}={c}" for r, c in enumerate(codes)
                        if c is not None and c != 0)
                    break
                if all(c == 0 for c in codes):
                    break                               # clean finish
                if any(c == 0 for c in codes):
                    # some ranks done, others running: normal teardown
                    # skew for a few seconds; forever = wedged collective
                    first_exit0 = first_exit0 or time.time()
                    if time.time() - first_exit0 > _EXIT_LINGER_S:
                        failure = "exit_skew"
                        break
                ticks = _heartbeat_ticks(run_dir, procs_n)
                if ticks != last_ticks and any(
                        ticks.get(r, -1) > last_ticks.get(r, -1)
                        for r in ticks):
                    last_ticks, last_progress = ticks, time.time()
                elif time.time() - last_progress > args.stall_timeout_s:
                    failure = "stall"
                    break
        finally:
            # runs on clean finishes, failures, AND SystemExit from the
            # signal handler — the group never outlives its owner
            _teardown(group)
        if failure is None:
            journal.record(kind="mh_done", attempt=attempt,
                           relaunches=attempt)
            print(json.dumps({"mh": "done", "attempts": attempt + 1,
                              "relaunches": attempt, "rung": rung}),
                  flush=True)
            return 0
        if verdict_abort:
            journal.record(kind="mh_verdict_abort", attempt=attempt,
                           exit_code=resilience.EXIT_VERDICT_ABORT)
            print(json.dumps({"mh": "verdict_abort", "attempt": attempt,
                              "exit_code":
                                  resilience.EXIT_VERDICT_ABORT}),
                  flush=True)
            return resilience.EXIT_VERDICT_ABORT

        tick_after = _newest_ckpt_tick(ckpt_dir)
        made_progress = (tick_after or -1) > (tick_before or -1)
        # rung policy: an attempt that advanced the checkpoint frontier
        # failed ENVIRONMENTALLY (preemption, chaos, a dead host) — the
        # program is fine, keep the rung. Only a zero-progress attempt
        # escalates: the program itself may not run at this rung
        if not made_progress:
            rung += 1
        journal.record(kind="mh_failure", attempt=attempt, why=failure,
                       ckpt_tick=tick_after, made_progress=made_progress,
                       next_rung=rung)
        print(json.dumps({"mh": "failure", "attempt": attempt,
                          "why": failure, "ckpt_tick": tick_after,
                          "next_rung": rung}), flush=True)
        if attempt < args.max_relaunches:
            delay = min(args.backoff_cap_s,
                        args.backoff_base_s
                        * args.backoff_factor ** attempt)
            journal.record(kind="mh_backoff", delay_s=round(delay, 3))
            time.sleep(delay)

    journal.record(kind="mh_giveup", attempts=args.max_relaunches + 1)
    print(json.dumps({"mh": "giveup",
                      "attempts": args.max_relaunches + 1}), flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
