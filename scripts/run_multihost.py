#!/usr/bin/env python
"""Multi-process launcher: one supervised sharded run per host.

Each participating host runs ONE copy of this script with the same
coordinator address and its own rank; together they form the
``make_mesh_2d`` (dcn × peers) mesh, each host building ONLY its
contiguous ``[N/P, ...]`` block of the SimState
(``parallel.multihost.init_state_local`` — a 1M-peer state never
materializes on one host), assembled into the global sharded state via
``host_local_array_to_global_array`` and advanced in supervised chunks of
the SHARDED scan (``parallel.sharding.make_sharded_run_keys``, halo
routes intact). Rank 0 alone writes checkpoints, the journal, and metric
lines; checkpoint *gathers* are collective, so every rank participates in
the boundary (sim/supervisor.py ``state_to_host``/``write_files``).

Trajectory contract: bit-identical to the single-process
``engine.run(state, cfg, tp, PRNGKey(seed), ticks)`` at any process
count (tests/test_multihost.py pins the 2-process CPU run).

Typical 2-host invocation (same for both, differing only in rank):

    GRAFT_COORDINATOR=host0:9911 GRAFT_NUM_PROCESSES=2 \
    GRAFT_PROCESS_ID=<0|1> python scripts/run_multihost.py \
        --scenario frontier_1m --ticks 600 \
        --checkpoint-dir /shared/ckpt --journal /shared/journal.jsonl

CPU smoke (localhost, two terminals or a driver spawning both):

    JAX_PLATFORMS=cpu python scripts/run_multihost.py \
        --coordinator localhost:9911 --num-processes 2 --process-id <r> \
        --scenario frontier_250k --n 128 --ticks 4 --dump-state /tmp/out.npz

Heavy-tailed (degree-bucketed) engine — the powerlaw family rides the
row-sharded bucketed step (parallel/sharding.make_sharded_bucketed_run):
every bucket's rows split across the (dcn x peers) mesh, each rank builds
only its own bucket blocks (parallel/multihost.init_bucketed_local), and
GRAFT_HBM_BUDGET prices the closed-form partition per (bucket x shard)
before any underlay row is constructed:

    GRAFT_HBM_BUDGET=16GiB python scripts/run_multihost.py \
        --engine bucketed --scenario powerlaw_10m --topology sharded \
        --ticks 600 --checkpoint-dir /shared/ckpt
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--coordinator", default=None,
                    help="host:port of rank 0 (or $GRAFT_COORDINATOR)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--scenario", default="frontier_250k",
                    help="frontier family member "
                         "(frontier_250k/500k/1m/4m/10m), or with "
                         "--engine bucketed a powerlaw family member "
                         "(powerlaw_100k/1m/10m)")
    ap.add_argument("--engine", default="dense",
                    choices=["dense", "bucketed"],
                    help="dense: the uniform-degree [N, K] sharded step "
                         "(frontier family). bucketed: the degree-"
                         "bucketed row-sharded step (powerlaw family) — "
                         "every bucket's rows split across the mesh, "
                         "per-tick cost and HBM scale with "
                         "sum-of-degrees instead of N * D_max")
    ap.add_argument("--n", type=int, default=None,
                    help="peer-count override (smoke runs)")
    ap.add_argument("--topology", default="replicated",
                    choices=["replicated", "sharded"],
                    help="replicated: every process builds the full "
                         "host-side [N, K] underlay table and slices its "
                         "rows (topology.sparse_fast — the 1M-scale "
                         "path). sharded: each process materializes ONLY "
                         "its own [N/P, K] rows of the seeded circulant "
                         "underlay (topology.sparse_hash — mandatory at "
                         "10M, where the global table alone is ~2.7 GiB "
                         "of host RAM per process)")
    ap.add_argument("--bucketed-rng", default=None,
                    choices=["bucket", "dense"],
                    help="--engine bucketed only: per-edge RNG layout. "
                         "'dense' reproduces the dense engine bit for "
                         "bit (the parity contract); 'bucket' (scenario "
                         "default) draws at bucket width for "
                         "sum-of-degrees cost")
    ap.add_argument("--ticks", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk-ticks", type=int, default=None)
    ap.add_argument("--max-chunks", type=int, default=None,
                    help="window-bounded execution: stop cleanly after N "
                         "chunks; a later invocation with the SAME "
                         "--ticks/--seed resumes from the checkpoint")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="SHARED filesystem path (all ranks read, rank 0 "
                         "writes)")
    ap.add_argument("--journal", default=None,
                    help="rank-0 JSONL journal of run/chunk outcomes")
    ap.add_argument("--health", default=None,
                    help="rank-0 streaming health journal "
                         "(sim/telemetry.py; or $GRAFT_HEALTH_STREAM): "
                         "the sharded scan computes per-tick aggregates "
                         "on device, rank 0 streams them for "
                         "scripts/dashboard.py to tail")
    ap.add_argument("--dump-state", default=None,
                    help="rank-0 .npz of the final host-complete state "
                         "(parity smoke)")
    ap.add_argument("--run-dir", default=None,
                    help="SHARED resilience-plane directory (or "
                         "$GRAFT_MH_RUN_DIR): every rank beats a "
                         "heartbeat file here and watches its peers' "
                         "(parallel/resilience.py) — a dead peer aborts "
                         "this rank at a chunk boundary instead of "
                         "hanging a collective. scripts/mh_supervisor.py "
                         "owns the directory when it drives the group")
    ap.add_argument("--source", default=None,
                    help="live command plane (sim/commands.py): NDJSON "
                         "directive stream (publish/join/leave/attack) or "
                         "recorded reference trace, drained per chunk "
                         "boundary. Rank 0 tails the file; frames "
                         "broadcast to every rank as traced chunk inputs "
                         "— the flag changes the COMPILED program, so "
                         "EVERY rank must get it")
    ap.add_argument("--directive-slots", type=int, default=64,
                    help="fixed directive slots per chunk (the jit-static "
                         "frame shape); offered load beyond the budget "
                         "is journaled load-shedding, never a retrace")
    ap.add_argument("--ingest-stall-timeout", type=float, default=10.0,
                    help="seconds of producer silence before the run "
                         "enters coast mode (empty frames + "
                         "ingest_stalled journal marker)")
    ap.add_argument("--ingest-coast-poll", type=float, default=0.05,
                    help="per-boundary pacing sleep while coasting, so a "
                         "stalled run cannot sprint arbitrarily far from "
                         "its stream before the producer restarts")
    ap.add_argument("--contracts", default=None,
                    help="live contract verdict plane (sim/adversary.py): "
                         "inline JSON list of contract specs evaluated "
                         "over the streamed telemetry at every chunk "
                         "boundary; status transitions journal "
                         "contract_verdict notes exactly-once across "
                         "relaunches. Requires --health. Example: "
                         "'[{\"kind\": \"delivery_floor\", \"floor\": "
                         "0.9, \"start\": 0}]'")
    ap.add_argument("--verdict-policy", default=None,
                    choices=["journal", "snapshot", "abort"],
                    help="FAIL response (or $GRAFT_VERDICT_POLICY): "
                         "journal an alarm (default), snapshot an "
                         "off-cadence breach checkpoint, or abort — "
                         "clean named teardown at the breach boundary "
                         "(exit code 44, terminal for mh_supervisor.py)")
    args = ap.parse_args()

    from go_libp2p_pubsub_tpu.parallel import multihost, resilience

    run_dir = args.run_dir or os.environ.get("GRAFT_MH_RUN_DIR") or None
    liveness = None
    if run_dir:
        # liveness starts BEFORE jax.distributed: rank/nproc come from the
        # args/env the launcher already requires, and the first beat lands
        # even if this rank later wedges in the coordinator handshake (the
        # relaunch supervisor's stall detector needs exactly that signal)
        rank_hint = args.process_id if args.process_id is not None \
            else int(os.environ.get(multihost.ENV_PROCESS_ID, "0"))
        nproc_hint = args.num_processes if args.num_processes is not None \
            else int(os.environ.get(multihost.ENV_NUM_PROCESSES, "1"))
        liveness = resilience.RankLiveness.from_env(
            run_dir, rank_hint, nproc_hint).start()
    chaos = resilience.ChaosPlan.from_env(
        args.process_id if args.process_id is not None
        else int(os.environ.get(multihost.ENV_PROCESS_ID, "0")), run_dir)

    # MUST precede any backend touch (device discovery happens at init)
    multihost.initialize(args.coordinator, args.num_processes,
                         args.process_id)

    import jax
    import numpy as np

    from go_libp2p_pubsub_tpu.parallel.sharding import (
        make_mesh_2d, make_sharded_bucketed_run, make_sharded_run_keys)
    from go_libp2p_pubsub_tpu.sim import scenarios
    from go_libp2p_pubsub_tpu.sim.state import check_hbm_budget
    from go_libp2p_pubsub_tpu.sim.supervisor import (
        SupervisorConfig, supervised_run)

    n_proc = jax.process_count()
    rank = jax.process_index()
    coord = multihost.is_coordinator()

    bucketed = args.engine == "bucketed"
    sharded_topo = args.topology == "sharded"
    if bucketed:
        if args.scenario not in scenarios.POWERLAW_NS:
            raise SystemExit(
                f"--engine bucketed --scenario {args.scenario!r}: the "
                "bucketed engine drives the powerlaw family "
                "(powerlaw_100k/1m/10m) — the frontier family is "
                "uniform-degree and takes the dense engine")
        n = args.n or scenarios.POWERLAW_NS[args.scenario]
        # topo_rows is a pure function of row id: the sharded topology
        # builds ONLY each rank's bucket blocks (init_bucketed_local);
        # replicated materializes the full underlay on every host first
        spec_kw = ({"bucketed_rng": args.bucketed_rng}
                   if args.bucketed_rng else {})
        cfg, tp, topo_rows, subscribed = scenarios.powerlaw_mh_spec(
            n, **spec_kw)
        # defer the (possibly full-graph) build until the HBM gate below
        # has priced the closed-form partition — a 10M launch over budget
        # refuses before a single underlay row is constructed
        topo = topo_rows
    else:
        if args.bucketed_rng:
            raise SystemExit("--bucketed-rng requires --engine bucketed")
        if not args.scenario.startswith("frontier"):
            raise SystemExit(
                f"--scenario {args.scenario!r}: the multihost launcher "
                "drives the frontier family (frontier_250k/500k/1m/4m/"
                "10m) on the dense engine and the powerlaw family "
                "(powerlaw_100k/1m/10m) under --engine bucketed; other "
                "scenarios construct full device states")
        n = args.n or scenarios.FRONTIER_NS[args.scenario]
        # XL scenarios run compact by construction
        # (scenarios.frontier_4m/_10m); the spec path takes the
        # precision explicitly
        precision = "compact" if args.scenario in (
            "frontier_4m", "frontier_10m") else "f32"
        trows = multihost.local_peer_rows(n, n_proc, rank) if sharded_topo \
            else None
        cfg, tp, topo, subscribed = scenarios.frontier_spec(
            n, state_precision=precision, rows=trows)

    # hosts-major device order so each host's contiguous peer block lands
    # on its own chips (make_mesh_2d layout contract)
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    mesh = make_mesh_2d(n_proc, devs)
    # price the state BEFORE any device allocation: with GRAFT_HBM_BUDGET
    # set, an over-budget launch refuses here by name (the error cites the
    # worst per-shard fields and the knobs that shrink them) instead of
    # OOMing minutes into topology construction
    budget = check_hbm_budget(cfg, len(devs),
                              what=f"{args.scenario} state")
    if coord:
        header = {
            "info": "multihost run", "scenario": args.scenario, "n_peers": n,
            "processes": n_proc, "devices": len(devs),
            "engine": args.engine, "topology": args.topology,
            "state_precision": cfg.state_precision,
            "state_nbytes_total": budget["total"],
            "state_nbytes_per_shard": budget["per_shard"]}
        if "bucket_shards" in budget:
            # per-(bucket x shard) pricing for dashboards
            # (scripts/dashboard.py renders these instead of re-deriving
            # a dense estimate it can't get right for bucketed layouts)
            header["bucket_shards"] = budget["bucket_shards"]
        print(json.dumps(header), flush=True)
        if args.journal:
            # the journal leads with the header, so dashboard.py can
            # render the run's shape and per-(bucket x shard) pricing
            # without parsing launcher stdout
            with open(args.journal, "a") as f:
                f.write(json.dumps(header) + "\n")
                f.flush()
                os.fsync(f.fileno())

    if bucketed:
        if not sharded_topo:
            # replicated: the full underlay once per host, sliced per
            # bucket block by init_bucketed_local
            topo = topo(0, n)
        local = multihost.init_bucketed_local(cfg, topo, rank, n_proc,
                                              subscribed=subscribed)
        state = multihost.global_bucketed_state(local, mesh, cfg)
    else:
        local = multihost.init_state_local(cfg, topo, rank, n_proc,
                                           subscribed=subscribed,
                                           topo_local=sharded_topo)
        state = multihost.global_state(local, mesh, cfg)

    # sharded chunk runner: one compiled scan per (exec_cfg, chunk shape),
    # cached so retries and steady-state chunks re-dispatch the same
    # executable (the degrade ladder swaps exec_cfg, landing a new entry).
    # With a health stream the runner returns (state, HealthRecord) —
    # EVERY rank runs the telemetry program (the reduction's collectives
    # are part of it), only rank 0 journals (write_files below)
    health = args.health or os.environ.get("GRAFT_HEALTH_STREAM") or None
    if bucketed and health:
        raise SystemExit(
            "--engine bucketed: the health stream reads the dense [N, K] "
            "planes (sim/telemetry.health_record) — drop --health/"
            "GRAFT_HEALTH_STREAM or run the dense engine")
    _runs: dict = {}

    def run_fn(st, exec_cfg, tp_arg, keys):
        # the cache keys on exec_cfg (what the degrade ladder swaps); the
        # TopicParams the supervisor hands us ride as a per-call traced
        # argument, so a cached runner can never serve a stale tp
        fn = _runs.get(exec_cfg)
        if fn is None:
            fn = _runs[exec_cfg] = (
                make_sharded_bucketed_run(mesh, exec_cfg, tp_arg)
                if bucketed else
                make_sharded_run_keys(mesh, exec_cfg, tp_arg,
                                      telemetry=health is not None))
        return fn(st, keys, tp_arg)

    def state_from_host(host_state):
        # the checkpoint restores host-complete; each rank re-slices its
        # rows at the CURRENT process count (elastic P -> P' resume)
        if bucketed:
            loc = multihost.local_bucketed_rows_state(host_state, cfg,
                                                      rank, n_proc)
            return multihost.global_bucketed_state(loc, mesh, cfg)
        loc = multihost.local_rows_state(host_state, cfg, rank, n_proc)
        return multihost.global_state(loc, mesh, cfg)

    # relaunch provenance from the group supervisor (mh_supervisor.py):
    # the agreed degrade rung (GRAFT_MH_RUNG → SupervisorConfig
    # initial_degrade via from_env) and how many relaunches this attempt
    # rides on — stamped into the health header so dashboards and
    # post-hoc analysis see what a banked number cost
    relaunches = int(os.environ.get("GRAFT_MH_RELAUNCHES", "0"))
    health_meta = {"processes": n_proc}
    if run_dir:
        health_meta.update(
            mh_run_dir=os.path.abspath(run_dir),
            mh_rung=int(os.environ.get("GRAFT_MH_RUNG", "0")),
            mh_relaunches=relaunches,
            mh_peer_timeout_s=(liveness.peer_timeout_s
                               if liveness is not None else None))

    # live command plane: rank 0 owns the real queue (and the chaos
    # ingest drills); under >1 process every rank wraps in
    # BroadcastCommands so the per-boundary frame broadcast — a
    # collective — runs rank-symmetrically
    commands = None
    if args.source:
        from go_libp2p_pubsub_tpu.sim import commands as cmdmod
        queue = None
        if coord:
            queue = cmdmod.CommandQueue(
                args.source, n_peers=cfg.n_peers, n_topics=cfg.n_topics,
                msg_window=cfg.msg_window, slots=args.directive_slots,
                stall_timeout_s=args.ingest_stall_timeout,
                coast_poll_s=args.ingest_coast_poll, chaos=chaos)
        commands = cmdmod.BroadcastCommands(
            queue, slots=args.directive_slots) if n_proc > 1 else queue
        health_meta.update(ingest_source=os.path.abspath(args.source),
                           directive_slots=args.directive_slots)

    # live contract verdict plane: every rank folds the same replicated
    # telemetry rows (the abort policy must be rank-symmetric); only
    # rank 0 journals the verdict notes. The declared contracts also
    # stamp into the health header so the dashboard evaluates the RUN's
    # contracts, not schedule defaults
    contracts = ()
    if args.contracts:
        from go_libp2p_pubsub_tpu.sim import adversary
        try:
            specs = json.loads(args.contracts)
        except json.JSONDecodeError as e:
            raise SystemExit(f"--contracts: not valid JSON ({e})")
        if not isinstance(specs, list):
            raise SystemExit("--contracts: expected a JSON LIST of "
                             "contract objects")
        try:
            contracts = adversary.contracts_from_json(specs)
        except ValueError as e:
            raise SystemExit(f"--contracts: {e}")
        health_meta["contracts"] = adversary.contracts_to_json(contracts)

    sup = SupervisorConfig.from_env(
        scenario=args.scenario,
        run_fn=run_fn,
        commands=commands,
        contracts=contracts,
        chaos=chaos,
        **({"verdict_policy": args.verdict_policy}
           if args.verdict_policy else {}),
        state_to_host=multihost.gather_state,
        state_from_host=state_from_host,
        write_files=coord,
        liveness=liveness,
        health_meta=health_meta,
        **({"health_path": health} if health else {}),
        **({"chunk_ticks": args.chunk_ticks} if args.chunk_ticks else {}),
        **({"max_chunks": args.max_chunks} if args.max_chunks else {}),
        **({"checkpoint_dir": args.checkpoint_dir}
           if args.checkpoint_dir else {}),
    )

    from go_libp2p_pubsub_tpu.sim.supervisor import VerdictAbort
    try:
        t0 = time.perf_counter()
        try:
            state, report = supervised_run(state, cfg, tp,
                                           jax.random.PRNGKey(args.seed),
                                           args.ticks, sup,
                                           _chunk_hook=chaos.fire
                                           if chaos is not None else None)
        except VerdictAbort as e:
            # clean named teardown: every verdict note already drained
            # to the journal before the raise. All ranks raise together
            # (the fold is rank-symmetric), so no collective is left
            # half-entered; the distinct exit code tells the relaunch
            # supervisor this is TERMINAL, not a crash to relaunch past
            if coord:
                line = {"info": "verdict_abort", **(e.event or {}),
                        "exit_code": resilience.EXIT_VERDICT_ABORT}
                print(json.dumps(line), flush=True)
                if args.journal:
                    with open(args.journal, "a") as f:
                        f.write(json.dumps(line) + "\n")
                        f.flush()
                        os.fsync(f.fileno())
            if liveness is not None:
                liveness.finish()
            sys.exit(resilience.EXIT_VERDICT_ABORT)
        wall = time.perf_counter() - t0

        # final host-complete copy: collective gather on every rank,
        # writes on rank 0 only (the checkpoint-boundary discipline)
        host = multihost.gather_state(state)
        if liveness is not None:
            # mark this rank's heartbeat done BEFORE the skewed teardown
            # window: a peer must never read a finished rank as dead
            liveness.finish()
    finally:
        if liveness is not None:
            liveness.stop()
        if commands is not None:
            commands.close()
    if coord:
        from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction
        from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
        flags = int(np.asarray(host.fault_flags))
        # delivery census reads only row/message planes — for the
        # bucketed engine those all live in the g half
        census = host.g if bucketed else host
        line = {
            "metric": f"multihost_run@{args.scenario}"
                      f"[{jax.devices()[0].platform}x{n_proc}p]",
            "engine": args.engine,
            "n_peers": n, "ticks": args.ticks, "wall_s": round(wall, 2),
            "hbps": round(args.ticks / max(wall, 1e-9), 3),
            "chunks": report.chunks_run, "retries": report.retries,
            "resumed_from": report.resumed_from,
            "delivery_fraction": round(
                float(delivery_fraction(census, cfg)), 4),
            "fault_flags": flags, "fault_flag_names": decode_flags(flags),
            "state_nbytes_per_shard": budget["per_shard"],
        }
        if run_dir:
            line["mh_rung"] = int(os.environ.get("GRAFT_MH_RUNG", "0"))
            line["mh_relaunches"] = relaunches
        if commands is not None:
            line["commands_applied"] = int(
                getattr(commands, "applied_total", 0))
            line["commands_shed"] = int(getattr(commands, "shed_total", 0))
            line["commands_refused"] = int(
                getattr(commands, "refused_total", 0))
            line["ingest_offset"] = int(
                getattr(commands, "consumed_offset", 0))
            line["commands_per_sec"] = round(
                line["commands_applied"] / max(wall, 1e-9), 3)
        print(json.dumps(line), flush=True)
        if args.journal:
            with open(args.journal, "a") as f:
                f.write(json.dumps(line) + "\n")
                f.flush()
                os.fsync(f.fileno())
        if args.dump_state:
            from go_libp2p_pubsub_tpu.sim.checkpoint import _named_leaves
            np.savez(args.dump_state,
                     **{f: np.asarray(v) for f, v in _named_leaves(host)})
    # all ranks exit together (the gather above already synchronized)


if __name__ == "__main__":
    main()
