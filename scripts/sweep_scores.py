#!/usr/bin/env python
"""Peer-score / gater parameter sweep: delivery vs attack resistance.

The evaluation the gossipsub v1.1 hardening literature actually runs
(PAPER.md L4: peer scoring P1-P7, gater, PX) as one fleet product
(sim/fleet.py): a grid of score-weight VARIANTS x small-N ATTACK
scenarios (sybil_small / partition_small / outage_small, sim/scenarios.py)
x seeds, where every cell is a fleet member and the whole missing grid
runs as a handful of vmap-batched scans — P1-P4 variants share a
jit-static config and batch into ONE scan per scenario; P5-P7/gater
variants (static SimConfig floats) land in their own fleet groups
automatically.

Each (scenario, variant) cell reports:

- ``delivery``: settled delivery fraction over the whole run (attack
  window included — the damage the attack did),
- ``resistance``: the scenario's attack-resistance metric — for sybil,
  1 - (share of honest peers' mesh slots held by sybils) (scoring must
  evict attackers from meshes); for partition/outage, the settled
  delivery of messages published AFTER the heal tick (the network must
  actually recover),
- the per-member ``fault_flags`` union (a poisoned cell self-identifies).

The sweep is JOURNAL-RESUMABLE under the BENCH_JOURNAL discipline
(supervisor plane, ISSUE 5): the grid runs one fleet per scenario, each
completed scenario's cells are fsync-appended to ``--journal`` with their
env + variant-spec fingerprint, and a re-invocation replays recorded
cells instead of re-running them — a killed TPU-window sweep completes
incrementally at scenario granularity (set GRAFT_CHECKPOINT_DIR to also
checkpoint/resume WITHIN the in-flight scenario's fleet). ``--write-perf-model`` re-renders the
frontier table between the sweep_scores markers in PERF_MODEL.md.

Env fallbacks: SWEEP_N, SWEEP_TICKS, SWEEP_SEEDS, SWEEP_SCENARIOS,
SWEEP_VARIANTS, SWEEP_JOURNAL. Tiny-grid smoke: tests/test_sweep_scores.py
(tier-1).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# variant spec: keys in sim.config.SCORE_WEIGHT_KEYS ride
# with_score_weights (p1..p4 = traced TopicParams rows -> batch together;
# p5..p7 = jit-static SimConfig floats -> own fleet group); everything
# else is a plain SimConfig override (gater knobs)
VARIANTS = {
    "baseline": {},
    "p1_off": {"p1": 0.0},
    "p2_heavy": {"p2": 4.0},
    "p3_off": {"p3": 0.0, "p3b": 0.0},
    "p4_harsh": {"p4": -40.0},
    "p6_harsh": {"p6": -200.0},
    "p7_harsh": {"p7": -40.0},
    "gater_on": {"gater_enabled": True, "validation_queue_cap": 64},
}

SCENARIO_NAMES = ("sybil_small", "partition_small", "outage_small")
# the adversary/workload library families (sim/adversary.py, ISSUE 10):
# sweepable like the classic trio (--scenarios eclipse_small,... or
# SWEEP_SCENARIOS); their cells additionally evaluate the scenario's
# declared behavior contracts per member (contracts_failed column) from
# the fleet's collected telemetry rows
ATTACK_SCENARIOS = ("eclipse_small", "censor_small", "flashcrowd_small",
                    "slowlink_small", "diurnal_small")
SEED_KEY_BASE = 271828

PERF_BEGIN = "<!-- sweep_scores:frontier:begin -->"
PERF_END = "<!-- sweep_scores:frontier:end -->"


def apply_variant(cfg, tp, spec: dict):
    """Split a variant spec into score-weight overrides (P1-P7 via
    with_score_weights) and plain SimConfig overrides; apply both."""
    from go_libp2p_pubsub_tpu.sim.config import (SCORE_WEIGHT_KEYS,
                                                 with_score_weights)
    weights = {k: v for k, v in spec.items() if k in SCORE_WEIGHT_KEYS}
    extra = {k: v for k, v in spec.items() if k not in SCORE_WEIGHT_KEYS}
    if weights:
        tp, cfg = with_score_weights(tp, cfg=cfg, **weights)
    if extra:
        cfg = dataclasses.replace(cfg, **extra)
    return cfg, tp


def _sybil_mesh_share(state) -> float:
    """Share of honest peers' mesh slots held by malicious neighbors —
    the eviction metric: scoring that works drives this to ~0."""
    import jax.numpy as jnp
    n = state.neighbors.shape[0]
    nbr_mal = state.malicious[jnp.clip(state.neighbors, 0, n - 1)] \
        & (state.neighbors >= 0)                          # [N, K]
    honest_mesh = state.mesh & (~state.malicious)[:, None, None]
    bad = honest_mesh & nbr_mal[:, None, :]
    return float(jnp.sum(bad) / jnp.maximum(jnp.sum(honest_mesh), 1))


def _recovery_fraction(state, cfg, heal_tick: int) -> float | None:
    """Settled delivery over messages published AFTER the heal tick —
    delivery_fraction's census restricted to the recovered regime.
    ``None`` when the census is empty (the run ended before heal +
    settle; a silent 0.0 would read as catastrophic non-recovery)."""
    import jax.numpy as jnp
    age = state.tick - state.msg_publish_tick
    alive = (age < cfg.history_length) & (age >= 2) \
        & (state.msg_publish_tick >= heal_tick)
    t_m = jnp.clip(state.msg_topic, 0, cfg.n_topics - 1)
    should = state.subscribed[:, t_m] \
        & (alive & (state.msg_topic >= 0))[None, :]
    denom = int(jnp.sum(should))
    if denom == 0:
        return None
    from go_libp2p_pubsub_tpu.sim.state import unpack_have
    have = unpack_have(state, cfg.msg_window)
    return float(jnp.sum(have & should) / denom)


def _heal_tick(cfg) -> int:
    """The tick the member's own FaultPlan fully heals/ends its LAST
    scheduled window — derived from the config so a re-tuned scenario
    window can never silently desynchronize the recovery census (the
    hardcoded-20 bug class fixed in PR 7). ``faults.attack_end_tick``
    covers every windowed family (partition/outage/eclipse/censor/storm/
    wave); window-free plans (slow-link classes) return 0, making the
    recovery census the whole settled run."""
    from go_libp2p_pubsub_tpu.sim.faults import attack_end_tick
    return attack_end_tick(cfg.fault_plan)


def cell_metrics(scenario: str, res, cfg) -> dict:
    from go_libp2p_pubsub_tpu.sim.engine import delivery_fraction
    delivery = float(delivery_fraction(res.state, cfg, min_age_ticks=2))
    if scenario == "sybil_small":
        resistance = 1.0 - _sybil_mesh_share(res.state)
    else:
        resistance = _recovery_fraction(res.state, cfg, _heal_tick(cfg))
    return {"delivery": round(delivery, 4),
            "resistance": None if resistance is None
            else round(resistance, 4)}


def _env_fingerprint(n: int, ticks: int, seeds: int) -> dict:
    import jax
    return {"n": n, "ticks": ticks, "seeds": seeds,
            "platform": jax.devices()[0].platform}


def _journal_load(path: str | None, env: dict) -> dict:
    """{(scenario, variant): row} for records whose env + variant spec
    match the CURRENT run (torn tail lines skipped — their cells re-run)."""
    recs: dict = {}
    if path and os.path.exists(path):
        with open(path) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if r.get("env") == env and "row" in r \
                        and r.get("spec") == VARIANTS.get(r.get("variant")):
                    recs[(r["scenario"], r["variant"])] = r["row"]
    return recs


def _journal_append(path: str | None, scenario: str, variant: str,
                    env: dict, row: dict) -> None:
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps({"scenario": scenario, "variant": variant,
                            "spec": VARIANTS.get(variant), "env": env,
                            "row": row}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def run_sweep(scenario_names=None, variant_names=None, *, n: int = 512,
              ticks: int = 40, seeds: int = 2, journal: str | None = None,
              emit=print, sup=None) -> list:
    """Run the grid's missing cells — ONE fleet call per scenario (its
    variant × seed cells batch into that fleet's groups), cells journaled
    as soon as their scenario's fleet completes — and return the frontier
    rows in (scenario, variant) order. A kill mid-sweep loses at most the
    in-flight scenario (whose own windows GRAFT_CHECKPOINT_DIR can
    checkpoint); completed scenarios replay from the journal."""
    import jax
    import numpy as np
    from go_libp2p_pubsub_tpu.sim import scenarios as scen_mod
    from go_libp2p_pubsub_tpu.sim.fleet import (FleetMember,
                                                supervised_fleet_run)
    from go_libp2p_pubsub_tpu.sim.invariants import decode_flags
    from go_libp2p_pubsub_tpu.sim.supervisor import SupervisorConfig

    scenario_names = list(scenario_names or SCENARIO_NAMES)
    variant_names = list(variant_names or VARIANTS)
    env = _env_fingerprint(n, ticks, seeds)
    recorded = _journal_load(journal, env)

    rows = []
    for scen in scenario_names:
        # adversary-family scenarios (sim/adversary.py) carry behavior
        # contracts: run their fleets on the telemetry lane and judge
        # every member's row stream against the scenario's contracts.
        # Members run at least the scenario's recommended n_ticks — the
        # contracts' decision ticks (e.g. diurnal's last-wave recovery
        # window) can sit past the grid's default, and a run that ends
        # before them would fail every cell's contracts structurally
        from go_libp2p_pubsub_tpu.sim import adversary
        scen_ticks = ticks
        contracts = ()
        if scen in adversary.ATTACKS:
            attack = adversary.ATTACKS[scen](n_peers=n)
            contracts = attack.contracts
            scen_ticks = max(ticks, attack.n_ticks)

        members, cells, cfgs = [], [], {}
        for var in variant_names:
            if (scen, var) in recorded:
                emit(json.dumps({"info": "journal skip", "scenario": scen,
                                 "variant": var}))
                continue
            cfg, tp, st = scen_mod.SCENARIOS[scen](n_peers=n)
            cfg, tp = apply_variant(cfg, tp, VARIANTS[var])
            cfgs[var] = cfg
            for s in range(seeds):
                members.append(FleetMember(
                    cfg, tp, st, jax.random.PRNGKey(SEED_KEY_BASE + s),
                    scen_ticks, name=f"{scen}/{var}/s{s}"))
                cells.append(var)

        by_cell: dict = {}
        if members:
            results, report = supervised_fleet_run(
                members, sup or SupervisorConfig.from_env(),
                collect_health=bool(contracts))
            groups = next((len(e["sizes"]) for e in report.events
                           if e["event"] == "fleet_plan"), 0)
            emit(json.dumps({"info": "fleet done", "scenario": scen,
                             "members": len(members), "groups": groups,
                             "member_ticks": report.ticks_run}))
            for var, res in zip(cells, results):
                by_cell.setdefault(var, []).append(res)

        for var in variant_names:
            if (scen, var) in recorded:
                rows.append(recorded[(scen, var)])
                emit(json.dumps(recorded[(scen, var)]))
                continue
            cell_res = by_cell[var]
            mets = [cell_metrics(scen, r, cfgs[var]) for r in cell_res]
            flags = int(np.bitwise_or.reduce(np.asarray(
                [r.fault_flags for r in cell_res], np.uint32)))
            resist = [m["resistance"] for m in mets]
            row = {
                "scenario": scen, "variant": var,
                "delivery": round(float(np.mean(
                    [m["delivery"] for m in mets])), 4),
                "resistance": None if any(r is None for r in resist)
                else round(float(np.mean(resist)), 4),
                "fault_flags": flags,
                "fault_flag_names": decode_flags(flags),
                "tripped": any(r.tripped for r in cell_res),
                "seeds": seeds, "n": n, "ticks": scen_ticks,
            }
            if contracts:
                # every member's stream judged against the scenario's
                # declared contracts; the row carries how many member-
                # contract pairs failed and which kinds (a weight
                # variant that breaks a contract shows it here)
                failed = []
                for r in cell_res:
                    for c in adversary.evaluate_contracts(
                            contracts, r.health_rows or [], final=True):
                        if not c.passed:
                            failed.append(c.kind)
                row["contracts"] = len(contracts) * len(cell_res)
                row["contracts_failed"] = len(failed)
                row["contracts_failed_kinds"] = sorted(set(failed))
            rows.append(row)
            emit(json.dumps(row))
            _journal_append(journal, scen, var, env, row)
    return rows


def _pareto(rows: list) -> set:
    """Indices of non-dominated (delivery, resistance) points — the
    frontier a score-weight choice should be picked from. Rows with an
    empty resistance census (None) are out of the running."""
    out = set()
    comp = [r for r in rows if r["resistance"] is not None]
    for i, a in enumerate(rows):
        if a["resistance"] is None:
            continue
        dominated = any(
            (b["delivery"] >= a["delivery"]
             and b["resistance"] >= a["resistance"]
             and (b["delivery"] > a["delivery"]
                  or b["resistance"] > a["resistance"]))
            for b in comp)
        if not dominated:
            out.add(i)
    return out


def render_table(rows: list) -> str:
    import jax
    platform = jax.devices()[0].platform
    if not rows:
        return "(no sweep rows)"
    meta = rows[0]
    lines = [
        f"Grid: {meta['seeds']} seed(s) x {meta['ticks']} ticks at "
        f"N={meta['n']} per member, platform={platform} "
        "(`python scripts/sweep_scores.py`). `frontier` marks the "
        "Pareto-optimal (delivery, resistance) points per scenario.",
        "",
        "| scenario | variant | delivery | resistance | frontier | flags |",
        "|---|---|---|---|---|---|",
    ]
    for scen in dict.fromkeys(r["scenario"] for r in rows):
        sub = [r for r in rows if r["scenario"] == scen]
        front = _pareto(sub)
        for i, r in enumerate(sub):
            flg = ",".join(r.get("fault_flag_names", [])) or "-"
            res = "n/a" if r["resistance"] is None \
                else f"{r['resistance']:.4f}"
            lines.append(
                f"| {scen} | {r['variant']} | {r['delivery']:.4f} | "
                f"{res} | {'*' if i in front else ''} | {flg} |")
    return "\n".join(lines)


def write_perf_model(rows: list, path: str) -> None:
    """Replace the frontier table between the sweep_scores markers in
    PERF_MODEL.md (append the whole section when the markers are new)."""
    table = render_table(rows)
    block = f"{PERF_BEGIN}\n{table}\n{PERF_END}"
    with open(path) as f:
        text = f.read()
    if PERF_BEGIN in text and PERF_END in text:
        head, rest = text.split(PERF_BEGIN, 1)
        _, tail = rest.split(PERF_END, 1)
        text = head + block + tail
    else:
        text = text.rstrip("\n") + (
            "\n\n## Peer-score / gater sweep frontier "
            "(scripts/sweep_scores.py)\n\n" + block + "\n")
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n", type=int,
                    default=int(os.environ.get("SWEEP_N", 512)))
    ap.add_argument("--ticks", type=int,
                    default=int(os.environ.get("SWEEP_TICKS", 40)))
    ap.add_argument("--seeds", type=int,
                    default=int(os.environ.get("SWEEP_SEEDS", 2)))
    ap.add_argument("--scenarios",
                    default=os.environ.get("SWEEP_SCENARIOS", ""))
    ap.add_argument("--variants",
                    default=os.environ.get("SWEEP_VARIANTS", ""))
    ap.add_argument("--journal",
                    default=os.environ.get("SWEEP_JOURNAL", ""))
    ap.add_argument("--write-perf-model", action="store_true",
                    help="re-render the frontier table in PERF_MODEL.md")
    args = ap.parse_args()
    rows = run_sweep(
        [s for s in args.scenarios.split(",") if s] or None,
        [v for v in args.variants.split(",") if v] or None,
        n=args.n, ticks=args.ticks, seeds=args.seeds,
        journal=args.journal or None)
    if args.write_perf_model:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PERF_MODEL.md")
        write_perf_model(rows, path)
        print(json.dumps({"info": "perf model updated", "path": path,
                          "rows": len(rows)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
