from .events import RawTracer, RawTracerBase  # noqa: F401
