from .events import RawTracer, RawTracerBase  # noqa: F401
from .replay import (  # noqa: F401
    ReplayFeed,
    replay,
    replay_feed,
    replay_topic_params,
    tensorize_trace,
)
from .sinks import JSONTracer, MemoryTracer, PBTracer, RemoteTracer  # noqa: F401
