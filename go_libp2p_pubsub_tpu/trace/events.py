"""Tracer contracts: rejection reasons + the RawTracer hook protocol.

Mirrors trace.go:15-60 and tracer.go:27-39. The RawTracer bus is the
reference's internal event backbone (SURVEY.md L5): scoring, promise
tracking, connmgr tags, and the peer gater all implement this protocol.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Protocol

if TYPE_CHECKING:
    from ..core.types import Message, RPC

# rejection reasons (tracer.go:27-39)
REJECT_BLACKLISTED_PEER = "blacklisted peer"
REJECT_BLACKLISTED_SOURCE = "blacklisted source"
REJECT_MISSING_SIGNATURE = "missing signature"
REJECT_UNEXPECTED_SIGNATURE = "unexpected signature"
REJECT_UNEXPECTED_AUTH_INFO = "unexpected auth info"
REJECT_INVALID_SIGNATURE = "invalid signature"
REJECT_VALIDATION_QUEUE_FULL = "validation queue full"
REJECT_VALIDATION_THROTTLED = "validation throttled"
REJECT_VALIDATION_FAILED = "validation failed"
REJECT_VALIDATION_IGNORED = "validation ignored"
REJECT_SELF_ORIGIN = "self originated message"


class RawTracer(Protocol):
    """Synchronous hook bus, 15 methods (trace.go:27-60).

    Implementations may subclass ``RawTracerBase`` for default no-ops.
    """

    def add_peer(self, peer: str, proto: str) -> None: ...
    def remove_peer(self, peer: str) -> None: ...
    def join(self, topic: str) -> None: ...
    def leave(self, topic: str) -> None: ...
    def graft(self, peer: str, topic: str) -> None: ...
    def prune(self, peer: str, topic: str) -> None: ...
    def validate_message(self, msg: "Message") -> None: ...
    def deliver_message(self, msg: "Message") -> None: ...
    def reject_message(self, msg: "Message", reason: str) -> None: ...
    def duplicate_message(self, msg: "Message") -> None: ...
    def throttle_peer(self, peer: str) -> None: ...
    def recv_rpc(self, rpc: "RPC") -> None: ...
    def send_rpc(self, rpc: "RPC", peer: str) -> None: ...
    def drop_rpc(self, rpc: "RPC", peer: str) -> None: ...
    def undeliverable_message(self, msg: "Message") -> None: ...


class RawTracerBase:
    """No-op defaults for all 15 RawTracer hooks."""

    def add_peer(self, peer: str, proto: str) -> None: pass
    def remove_peer(self, peer: str) -> None: pass
    def join(self, topic: str) -> None: pass
    def leave(self, topic: str) -> None: pass
    def graft(self, peer: str, topic: str) -> None: pass
    def prune(self, peer: str, topic: str) -> None: pass
    def validate_message(self, msg: "Message") -> None: pass
    def deliver_message(self, msg: "Message") -> None: pass
    def reject_message(self, msg: "Message", reason: str) -> None: pass
    def duplicate_message(self, msg: "Message") -> None: pass
    def throttle_peer(self, peer: str) -> None: pass
    def recv_rpc(self, rpc: "RPC") -> None: pass
    def send_rpc(self, rpc: "RPC", peer: str) -> None: pass
    def drop_rpc(self, rpc: "RPC", peer: str) -> None: pass
    def undeliverable_message(self, msg: "Message") -> None: pass
