"""Tracer bus: fans every router/runtime event to one EventTracer (structured
events for offline analysis) and N RawTracers (synchronous hooks).

Mirrors trace.go:63-531. Events are dicts shaped after pb/trace.proto's
TraceEvent (type, peerID, timestamp, per-type payload); the pb layer
serializes them for interop. The RawTracer bus is also the internal wiring
mechanism: scoring, promise tracking, connmgr tags, and the gater subscribe
to it (SURVEY.md §1 L5).
"""

from __future__ import annotations

from typing import Callable, Protocol

from ..core.types import RPC, Message, PeerID
from .events import RawTracer


class EventTracer(Protocol):
    """Structured trace sink (trace.go:15-17)."""

    def trace(self, evt: dict) -> None: ...


def _rpc_meta(rpc: RPC) -> dict:
    meta: dict = {}
    if rpc.subscriptions:
        meta["subscription"] = [
            {"subscribe": s.subscribe, "topic": s.topicid} for s in rpc.subscriptions]
    if rpc.publish:
        meta["messages"] = [{"messageID": m._id, "topic": m.topic} for m in rpc.publish]
    if rpc.control is not None and not rpc.control.is_empty():
        c = rpc.control
        meta["control"] = {
            "ihave": [{"topic": ih.topic, "messageIDs": list(ih.message_ids)}
                      for ih in c.ihave],
            "iwant": [{"messageIDs": list(iw.message_ids)} for iw in c.iwant],
            "graft": [{"topic": g.topic} for g in c.graft],
            "prune": [{"topic": p.topic, "peers": [pi.peer_id for pi in p.peers]}
                      for p in c.prune],
        }
    return meta


class PubsubTracer:
    """The per-node fan-out bus (trace.go:63-76)."""

    def __init__(self, now: Callable[[], float], pid: PeerID,
                 msg_id: Callable[[Message], str],
                 tracer: EventTracer | None = None,
                 raw: list[RawTracer] | None = None):
        self._now = now
        self._pid = pid
        self._msg_id = msg_id
        self.tracer = tracer
        self.raw: list[RawTracer] = list(raw or [])

    def add_raw(self, rt: RawTracer) -> None:
        self.raw.append(rt)

    def _emit(self, typ: str, **payload) -> None:
        if self.tracer is not None:
            self.tracer.trace({"type": typ, "peerID": self._pid,
                               "timestamp": self._now(), **payload})

    # --- event methods (trace.go:78-531) ---

    def publish_message(self, msg: Message) -> None:
        self._emit("PUBLISH_MESSAGE", publishMessage={
            "messageID": self._msg_id(msg), "topic": msg.topic})

    def validate_message(self, msg: Message) -> None:
        if msg.received_from != self._pid:
            for rt in self.raw:
                rt.validate_message(msg)

    def reject_message(self, msg: Message, reason: str) -> None:
        if msg.received_from != self._pid:
            for rt in self.raw:
                rt.reject_message(msg, reason)
        self._emit("REJECT_MESSAGE", rejectMessage={
            "messageID": self._msg_id(msg), "receivedFrom": msg.received_from,
            "reason": reason, "topic": msg.topic})

    def duplicate_message(self, msg: Message) -> None:
        if msg.received_from != self._pid:
            for rt in self.raw:
                rt.duplicate_message(msg)
        self._emit("DUPLICATE_MESSAGE", duplicateMessage={
            "messageID": self._msg_id(msg), "receivedFrom": msg.received_from,
            "topic": msg.topic})

    def deliver_message(self, msg: Message) -> None:
        if msg.received_from != self._pid:
            for rt in self.raw:
                rt.deliver_message(msg)
        self._emit("DELIVER_MESSAGE", deliverMessage={
            "messageID": self._msg_id(msg), "topic": msg.topic,
            "receivedFrom": msg.received_from})

    def add_peer(self, peer: PeerID, proto: str) -> None:
        for rt in self.raw:
            rt.add_peer(peer, proto)
        self._emit("ADD_PEER", addPeer={"peerID": peer, "proto": proto})

    def remove_peer(self, peer: PeerID) -> None:
        for rt in self.raw:
            rt.remove_peer(peer)
        self._emit("REMOVE_PEER", removePeer={"peerID": peer})

    def recv_rpc(self, rpc: RPC) -> None:
        for rt in self.raw:
            rt.recv_rpc(rpc)
        self._emit("RECV_RPC", receivedFrom=rpc.from_peer, meta=_rpc_meta(rpc))

    def send_rpc(self, rpc: RPC, peer: PeerID) -> None:
        for rt in self.raw:
            rt.send_rpc(rpc, peer)
        self._emit("SEND_RPC", sendTo=peer, meta=_rpc_meta(rpc))

    def drop_rpc(self, rpc: RPC, peer: PeerID) -> None:
        for rt in self.raw:
            rt.drop_rpc(rpc, peer)
        self._emit("DROP_RPC", sendTo=peer, meta=_rpc_meta(rpc))

    def undeliverable_message(self, msg: Message) -> None:
        for rt in self.raw:
            rt.undeliverable_message(msg)

    def throttle_peer(self, peer: PeerID) -> None:
        for rt in self.raw:
            rt.throttle_peer(peer)

    def join(self, topic: str) -> None:
        for rt in self.raw:
            rt.join(topic)
        self._emit("JOIN", join={"topic": topic})

    def leave(self, topic: str) -> None:
        for rt in self.raw:
            rt.leave(topic)
        self._emit("LEAVE", leave={"topic": topic})

    def graft(self, peer: PeerID, topic: str) -> None:
        for rt in self.raw:
            rt.graft(peer, topic)
        self._emit("GRAFT", graft={"peerID": peer, "topic": topic})

    def prune(self, peer: PeerID, topic: str) -> None:
        for rt in self.raw:
            rt.prune(peer, topic)
        self._emit("PRUNE", prune={"peerID": peer, "topic": topic})
