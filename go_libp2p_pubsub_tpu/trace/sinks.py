"""Trace sinks (tracer.go:79-303): NDJSON file, length-delimited binary file,
and a batching "remote" sink.

All sinks share the buffered, lossy writer discipline of the reference's
``basicTracer`` (64k buffer, drop-when-full for the lossy remote sink,
tracer.go:23,42-60); flushing happens on a scheduler timer instead of a
writer goroutine.
"""

from __future__ import annotations

import gzip
import json
import os
import zlib
from typing import Callable

TRACE_BUFFER_SIZE = 1 << 16  # tracer.go:23
MIN_TRACE_BATCH_SIZE = 16    # tracer.go:24


class _BufferedTracer:
    def __init__(self, lossy: bool):
        self.buf: list[dict] = []
        self.lossy = lossy
        self.dropped = 0
        self.closed = False

    def trace(self, evt: dict) -> None:
        if self.closed:
            return
        if self.lossy and len(self.buf) >= TRACE_BUFFER_SIZE:
            self.dropped += 1
            return
        self.buf.append(evt)

    def hard_flush(self) -> None:
        """Flush buffered events AND fsync the backing file (when there is
        one): the supervisor's failure path (sim/supervisor.py) calls this
        so a crashed run leaves a readable partial trace on disk rather
        than a page-cache-resident truncation. Batch-size gates do not
        apply — everything buffered goes out."""
        if self.closed:
            return
        self.flush()
        fh = getattr(self, "_fh", None)
        if fh is not None and not fh.closed:
            fh.flush()
            os.fsync(fh.fileno())


class MemoryTracer:
    """In-memory event collector. Shared across all nodes of an in-process
    network it yields the true global emission order — the canonical event
    order for trace replay (trace/replay.py)."""

    def __init__(self):
        self.events: list[dict] = []

    def trace(self, evt: dict) -> None:
        self.events.append(evt)


class JSONTracer(_BufferedTracer):
    """NDJSON file sink (tracer.go:79-129)."""

    def __init__(self, path: str):
        super().__init__(lossy=False)
        self.path = path
        self._fh = open(path, "w")

    def flush(self) -> None:
        for evt in self.buf:
            self._fh.write(json.dumps(evt) + "\n")
        self.buf.clear()
        self._fh.flush()

    def close(self) -> None:
        self.flush()
        self.closed = True
        self._fh.close()


class PBTracer(_BufferedTracer):
    """Length-delimited binary file sink (tracer.go:132-181). Uses the pb
    layer's TraceEvent encoding (uvarint length prefix + protobuf bytes)."""

    def __init__(self, path: str):
        super().__init__(lossy=False)
        self.path = path
        self._fh = open(path, "wb")

    def flush(self) -> None:
        from ..pb import codec
        for evt in self.buf:
            payload = codec.encode_trace_event(evt)
            self._fh.write(codec.write_uvarint(len(payload)) + payload)
        self.buf.clear()
        self._fh.flush()

    def close(self) -> None:
        self.flush()
        self.closed = True
        self._fh.close()


class RemoteTracer(_BufferedTracer):
    """Remote collector sink (tracer.go:186-303): lossy buffering,
    MIN_TRACE_BATCH_SIZE-gated flushing, and gzip'd delimited
    ``TraceEventBatch`` frames — the reference's exact wire unit
    (tracer.go:211-239) — written to a persistent stream.

    ``open_stream`` is the substrate's NewStream analogue: a zero-arg
    callable returning a write callable, raising on dial failure. A write
    failure resets the stream and reopens it once per flush
    (tracer.go:268-276 ``s.Reset()`` + ``openStream``); if the reopen or the
    retry also fails the batch is dropped (the sink is lossy by contract).
    Passing a plain write callable models a stream that never fails.
    Divergence from the reference, declared in MIGRATION.md: gzip is
    per-batch rather than one stream-long gzip writer, so each batch is
    independently decompressible (no gzip state rides the stream)."""

    def __init__(self, send: Callable[[bytes], None] | None = None, *,
                 open_stream: Callable[[], Callable[[bytes], None]] | None
                 = None):
        super().__init__(lossy=True)
        if (send is None) == (open_stream is None):
            raise ValueError("pass exactly one of send / open_stream")
        self._open = open_stream if open_stream is not None \
            else (lambda: send)
        self._stream: Callable[[bytes], None] | None = None

    def flush(self) -> None:
        if len(self.buf) < MIN_TRACE_BATCH_SIZE:
            return
        self._write_batch()

    def hard_flush(self) -> None:
        # failure path: the min-batch gate yields to getting the events out
        if not self.closed and self.buf:
            self._write_batch()

    def _write_batch(self) -> None:
        from ..pb import codec

        batch, self.buf = self.buf, []
        body = codec.encode_trace_event_batch(batch)
        payload = gzip.compress(codec.write_uvarint(len(body)) + body)
        for _attempt in range(2):
            if self._stream is None:
                try:
                    self._stream = self._open()
                except Exception:
                    break               # collector unreachable: drop batch
            try:
                self._stream(payload)
                return
            except Exception:
                self._stream = None     # reset + reopen once, then give up
        self.dropped += len(batch)

    def close(self) -> None:
        if self.buf:
            self._write_batch()
        self.closed = True

    @staticmethod
    def decode_batch(payload: bytes) -> list[dict]:
        from ..pb import codec

        data = zlib.decompress(payload, wbits=31)
        events: list[dict] = []
        pos = 0
        while pos < len(data):
            ln, pos = codec.read_uvarint(data, pos)
            events.extend(codec.decode_trace_event_batch(data[pos:pos + ln]))
            pos += ln
        return events
