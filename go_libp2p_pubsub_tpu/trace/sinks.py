"""Trace sinks (tracer.go:79-303): NDJSON file, length-delimited binary file,
and a batching "remote" sink.

All sinks share the buffered, lossy writer discipline of the reference's
``basicTracer`` (64k buffer, drop-when-full for the lossy remote sink,
tracer.go:23,42-60); flushing happens on a scheduler timer instead of a
writer goroutine.
"""

from __future__ import annotations

import gzip
import json
import zlib
from typing import Callable

TRACE_BUFFER_SIZE = 1 << 16  # tracer.go:23
MIN_TRACE_BATCH_SIZE = 16    # tracer.go:24


class _BufferedTracer:
    def __init__(self, lossy: bool):
        self.buf: list[dict] = []
        self.lossy = lossy
        self.dropped = 0
        self.closed = False

    def trace(self, evt: dict) -> None:
        if self.closed:
            return
        if self.lossy and len(self.buf) >= TRACE_BUFFER_SIZE:
            self.dropped += 1
            return
        self.buf.append(evt)


class MemoryTracer:
    """In-memory event collector. Shared across all nodes of an in-process
    network it yields the true global emission order — the canonical event
    order for trace replay (trace/replay.py)."""

    def __init__(self):
        self.events: list[dict] = []

    def trace(self, evt: dict) -> None:
        self.events.append(evt)


class JSONTracer(_BufferedTracer):
    """NDJSON file sink (tracer.go:79-129)."""

    def __init__(self, path: str):
        super().__init__(lossy=False)
        self.path = path
        self._fh = open(path, "w")

    def flush(self) -> None:
        for evt in self.buf:
            self._fh.write(json.dumps(evt) + "\n")
        self.buf.clear()
        self._fh.flush()

    def close(self) -> None:
        self.flush()
        self.closed = True
        self._fh.close()


class PBTracer(_BufferedTracer):
    """Length-delimited binary file sink (tracer.go:132-181). Uses the pb
    layer's TraceEvent encoding (uvarint length prefix + protobuf bytes)."""

    def __init__(self, path: str):
        super().__init__(lossy=False)
        self.path = path
        self._fh = open(path, "wb")

    def flush(self) -> None:
        from ..pb import codec
        for evt in self.buf:
            payload = codec.encode_trace_event(evt)
            self._fh.write(codec.write_uvarint(len(payload)) + payload)
        self.buf.clear()
        self._fh.flush()

    def close(self) -> None:
        self.flush()
        self.closed = True
        self._fh.close()


class RemoteTracer(_BufferedTracer):
    """Batched gzip sink (tracer.go:186-303): lossy, batches of at least
    MIN_TRACE_BATCH_SIZE events compressed and handed to a collector callable
    (the substrate stand-in for the remote libp2p stream)."""

    def __init__(self, send: Callable[[bytes], None]):
        super().__init__(lossy=True)
        self._send = send

    def flush(self) -> None:
        if len(self.buf) < MIN_TRACE_BATCH_SIZE:
            return
        batch, self.buf = self.buf, []
        payload = gzip.compress(json.dumps({"batch": batch}).encode())
        self._send(payload)

    def close(self) -> None:
        if self.buf:
            batch, self.buf = self.buf, []
            self._send(gzip.compress(json.dumps({"batch": batch}).encode()))
        self.closed = True

    @staticmethod
    def decode_batch(payload: bytes) -> list[dict]:
        return json.loads(zlib.decompress(payload, wbits=31))["batch"]
