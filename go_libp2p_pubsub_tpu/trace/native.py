"""ctypes binding for the native trace codec (native/trace_codec.cpp).

The shared library is compiled on demand with g++ (one translation unit,
O2) into the package's ``native/`` directory and cached; when no compiler
is available the pure-Python tensorizer (trace/replay.py) is the fallback.
``tensorize_file`` is the fast path for SURVEY.md §7's "chunked,
pre-tensorized event feeds": it parses a uvarint-delimited TraceEvent file
and returns the ReplayFeed without instantiating per-event Python objects.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from .replay import ReplayFeed

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "trace_codec.cpp")
_SO = os.path.join(_NATIVE_DIR, "libtracecodec.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.trace_codec_tensorize.restype = ctypes.c_int
        lib.trace_codec_tensorize.argtypes = [
            ctypes.c_char_p, ctypes.c_long,          # buf, len
            ctypes.c_char_p, ctypes.c_long,          # peers blob, n
            ctypes.c_char_p, ctypes.c_long,          # topics blob, n
            ctypes.POINTER(ctypes.c_double),         # dup_window
            ctypes.c_double, ctypes.c_double,        # decay_interval, t_end
            ctypes.c_int, ctypes.c_long,             # has_t_end, msg_window
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.trace_codec_free.argtypes = [ctypes.c_void_p]
        # the health-row NDJSON encoder (sim/telemetry.py hot sink path);
        # a stale .so built before the symbol existed degrades to the
        # Python encoder instead of failing the load
        try:
            lib.trace_codec_health_json.restype = ctypes.c_int
            lib.trace_codec_health_json.argtypes = [
                ctypes.POINTER(ctypes.c_double),      # vals [rows*cols]
                ctypes.c_long, ctypes.c_long,         # n_rows, n_cols
                ctypes.c_char_p, ctypes.c_long,       # names blob, len
                ctypes.c_char_p,                      # is_int per col
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_long),
            ]
        except AttributeError:
            pass
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def tensorize_bytes(data: bytes, peer_index: dict, topic_index: dict, *,
                    msg_window: int, decay_interval: float = 1.0,
                    dup_window=None, t_end: float | None = None) -> ReplayFeed:
    """Native twin of replay.tensorize_trace over encoded TraceEvent bytes.

    peer_index / topic_index must map contiguous indices 0..n-1 (the same
    contract replay.tensorize_trace relies on for array addressing).
    """
    lib = load()
    if lib is None:
        from ..pb.codec import decode_trace_bytes
        from .replay import tensorize_trace
        return tensorize_trace(decode_trace_bytes(data), peer_index,
                               topic_index, msg_window=msg_window,
                               decay_interval=decay_interval,
                               dup_window=dup_window, t_end=t_end)

    t_count = len(topic_index)
    if dup_window is None:
        dw = [0.0] * t_count
    elif np.isscalar(dup_window):
        dw = [float(dup_window)] * t_count
    else:
        dw = [float(x) for x in dup_window]

    def blob(index: dict) -> bytes:
        # length-prefixed, binary-safe (peer ids are raw multihash bytes
        # round-tripped through surrogateescape by pb/codec.py)
        ordered = sorted(index, key=index.get)
        out = bytearray()
        for s in ordered:
            raw = s.encode("utf-8", "surrogateescape")
            out += len(raw).to_bytes(4, "little") + raw
        return bytes(out)

    out = ctypes.POINTER(ctypes.c_int32)()
    out_events = ctypes.c_long()
    mids_p = ctypes.POINTER(ctypes.c_char)()
    n_mids = ctypes.c_long()
    dw_arr = (ctypes.c_double * t_count)(*dw)
    rc = lib.trace_codec_tensorize(
        data, len(data), blob(peer_index), len(peer_index),
        blob(topic_index), t_count, dw_arr,
        decay_interval, t_end if t_end is not None else 0.0,
        1 if t_end is not None else 0, msg_window,
        ctypes.byref(out), ctypes.byref(out_events),
        ctypes.byref(mids_p), ctypes.byref(n_mids))
    if rc != 0:
        lib.trace_codec_free(out)
        lib.trace_codec_free(mids_p)
        raise ValueError(f"native tensorize failed (rc={rc}); "
                         "msg_window too small or malformed stream")
    n = out_events.value
    arr = np.ctypeslib.as_array(out, shape=(n, 4)).copy()
    mid_slot: dict = {}
    off = 0
    for i in range(n_mids.value):
        ln = int.from_bytes(ctypes.string_at(
            ctypes.addressof(mids_p.contents) + off, 4), "little")
        off += 4
        mid = ctypes.string_at(
            ctypes.addressof(mids_p.contents) + off, ln).decode("latin-1")
        off += ln
        mid_slot[mid] = i
    lib.trace_codec_free(out)
    lib.trace_codec_free(mids_p)
    return ReplayFeed(op=np.ascontiguousarray(arr[:, 0]),
                      a=np.ascontiguousarray(arr[:, 1]),
                      b=np.ascontiguousarray(arr[:, 2]),
                      c=np.ascontiguousarray(arr[:, 3]),
                      mid_slot=mid_slot)


def tensorize_file(path: str, peer_index: dict, topic_index: dict,
                   **kw) -> ReplayFeed:
    with open(path, "rb") as f:
        return tensorize_bytes(f.read(), peer_index, topic_index, **kw)


def encode_health_json(matrix, columns) -> bytes | None:
    """Format a telemetry row matrix as NDJSON in ONE native call — the
    hot sink path of the streaming health journal (sim/telemetry.py).
    ``matrix`` is ``[n_rows, n_cols]`` float64, ``columns`` the ordered
    ``(name, is_int)`` schema. Returns None when the native library (or
    the symbol, in a stale pre-telemetry .so) is unavailable — the caller
    falls back to the pure-Python encoder, which parses to identical
    values."""
    lib = load()
    if lib is None or not hasattr(lib, "trace_codec_health_json"):
        return None
    mat = np.ascontiguousarray(matrix, np.float64)
    if mat.ndim != 2 or mat.shape[1] != len(columns):
        raise ValueError(
            f"encode_health_json: matrix {mat.shape} does not match "
            f"{len(columns)} columns")
    if mat.shape[0] == 0:
        return b""
    blob = bytearray()
    for name, _is_int in columns:
        raw = name.encode()
        blob += len(raw).to_bytes(4, "little") + raw
    is_int = bytes(1 if i else 0 for _n, i in columns)
    out = ctypes.POINTER(ctypes.c_char)()
    out_len = ctypes.c_long()
    rc = lib.trace_codec_health_json(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        mat.shape[0], mat.shape[1], bytes(blob), len(blob), is_int,
        ctypes.byref(out), ctypes.byref(out_len))
    if rc != 0:
        lib.trace_codec_free(out)
        return None
    payload = ctypes.string_at(out, out_len.value)
    lib.trace_codec_free(out)
    return payload
