"""Trace replay: drive the batched TPU engine from recorded trace events.

This is the differential-testing contract from SURVEY.md §7 step 7 and
BASELINE.json ("replaying pb/trace.pb events into the JAX state"): a stream
of TraceEvents — from the host-side functional runtime's tracer bus
(trace/bus.py, mirroring trace.go:63-531) or decoded from a PBTracer file
(pb/codec.py `read_trace_file`) — is *tensorized* host-side into a flat
op-stream (`ReplayFeed`), then *injected* on device into a `SimState` by a
single jitted scan. After replay, the sim's mesh membership and P1–P7 score
counters can be diffed against the live router that produced the trace.

Two halves:

- ``tensorize_trace``: mirrors the reference's delivery-record state machine
  (score.go:840-877; routers/score.py:317-372) while walking the event
  stream, expanding DELIVER/DUPLICATE/REJECT into primitive counter ops
  (first-delivery, in-window mesh duplicate, invalid delivery) exactly as
  the score RawTracer hooks would fire. Decay boundaries (refreshScores,
  score.go:504-565) are synthesized from timestamps: every node's decay
  ticker fires before same-instant traffic (scheduler seq ordering), so a
  single global DECAY op per boundary is exact.
- ``replay``: applies the ops in trace order with per-event dynamic-index
  updates under ``lax.scan`` + ``lax.switch`` — the canonical event order
  demanded by SURVEY.md §7 "Order-sensitivity vs batching".

Time quantization: replay grafts happen strictly inside a tick interval but
the sim clock is integral, so grafts record ``graft_tick = tick + 1``
("credit starts at the next boundary"). With that convention P1's floor
(score.go:285-291) matches the wall-clock router exactly; the P3 activation
latch (strict ``>``, score.go:539) then needs its threshold lowered by one
tick — ``replay_topic_params`` applies that shift. Counters themselves
(P2/P3/P3b/P4/P7) replay exactly (same decay chain, f32 vs f64 rounding
aside).

Known scope limits (documented, not silent): behaviour-penalty events
(P7 add_penalty calls, score.go:439) are not traced by the reference's
schema, so free-running penalty accrual cannot be replayed — suites that
exercise P7 must diff against synthetic PENALTY ops; delivery marking
during a disconnected peer's score-retention window is gated on
``connected`` rather than the reference's stats-retention lifetime.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from . import events as ev

# primitive op codes (device-side lax.switch branch index)
OP_NOP = 0
OP_DECAY = 1        # tick += 1, then refreshScores decay pass
OP_GRAFT = 2        # a=observer, b=peer, c=topic
OP_PRUNE = 3        # a=observer, b=peer, c=topic
OP_FIRST = 4        # first message delivery from b (score.go:920-947)
OP_DUP = 5          # in-window mesh duplicate from b (score.go:949-981)
OP_INVALID = 6      # invalid delivery from b (score.go:899-918)
OP_PENALTY = 7      # a=observer, b=peer, c=count (score.go:439 AddPenalty)
OP_JOIN = 8         # a=observer, c=topic
OP_LEAVE = 9
OP_PUBLISH = 10     # a=publisher, b=msg slot, c=topic
OP_DELIVER = 11     # a=observer, b=msg slot (local delivery bookkeeping)
OP_CONNECT = 12     # a=observer, b=peer (ADD_PEER)
OP_DISCONNECT = 13  # a=observer, b=peer (REMOVE_PEER, score.go:611-644)
N_OPS = 14

_SIG_REJECTS = frozenset({
    ev.REJECT_MISSING_SIGNATURE, ev.REJECT_INVALID_SIGNATURE,
    ev.REJECT_UNEXPECTED_SIGNATURE, ev.REJECT_UNEXPECTED_AUTH_INFO,
    ev.REJECT_SELF_ORIGIN,
})
_SILENT_REJECTS = frozenset({
    ev.REJECT_BLACKLISTED_PEER, ev.REJECT_BLACKLISTED_SOURCE,
    ev.REJECT_VALIDATION_QUEUE_FULL,
})

# delivery-record states (score.go:90-120)
_UNKNOWN, _VALID, _INVALID_ST, _THROTTLED, _IGNORED = range(5)


class ReplayFeed(NamedTuple):
    """Flat tensorized op stream + the mid -> slot assignment used."""

    op: np.ndarray      # [E] int32
    a: np.ndarray       # [E] int32
    b: np.ndarray      # [E] int32
    c: np.ndarray       # [E] int32
    mid_slot: dict      # message id -> slot index


def replay_topic_params(topics, heartbeat_interval: float = 1.0) -> TopicParams:
    """TopicParams for replay: activation threshold shifted by -1 tick to
    compensate the graft-at-next-boundary convention (module docstring)."""
    tp = TopicParams.from_topic_params(topics, heartbeat_interval)
    return tp._replace(
        mesh_message_deliveries_activation_ticks=(
            tp.mesh_message_deliveries_activation_ticks - 1.0))


class _Record:
    __slots__ = ("status", "peers", "validated")

    def __init__(self):
        self.status = _UNKNOWN
        self.peers: list[str] = []      # insertion-ordered, deterministic
        self.validated = 0.0


def tensorize_trace(events: list[dict], peer_index: dict, topic_index: dict,
                    *, msg_window: int, decay_interval: float = 1.0,
                    dup_window=None, t_end: float | None = None) -> ReplayFeed:
    """Expand a trace-ordered event stream into primitive replay ops.

    events: tracer-bus dicts (trace/bus.py shape / decode_trace_event output),
    globally ordered as emitted (a shared EventTracer preserves the true
    scheduler order; timestamp order is equivalent for distinct instants).
    dup_window: per-topic-index mesh_message_deliveries_window seconds
    (score_params.go:117-170); scalar or list; default 0 (same-instant only).
    t_end: run end time — trailing decay boundaries up to here are emitted.
    """
    t_count = len(topic_index)
    if dup_window is None:
        dup_window = [0.0] * t_count
    elif np.isscalar(dup_window):
        dup_window = [float(dup_window)] * t_count

    ops: list[tuple[int, int, int, int]] = []
    records: dict[tuple[str, str], _Record] = {}
    mid_slot: dict[str, int] = {}
    next_decay = decay_interval
    eps = 1e-9

    def slot_of(mid: str) -> int:
        s = mid_slot.get(mid)
        if s is None:
            s = len(mid_slot)
            if s >= msg_window:
                raise ValueError(
                    f"trace has more than msg_window={msg_window} message ids")
            mid_slot[mid] = s
        return s

    def rec_of(observer: str, mid: str) -> _Record:
        r = records.get((observer, mid))
        if r is None:
            r = _Record()
            records[(observer, mid)] = r
        return r

    for e in events:
        ts = e.get("timestamp", 0.0)
        while ts >= next_decay - eps:
            ops.append((OP_DECAY, 0, 0, 0))
            next_decay += decay_interval
        typ = e["type"]
        obs = e.get("peerID")
        ai = peer_index.get(obs, -1)
        if ai < 0:
            continue

        if typ == "GRAFT" or typ == "PRUNE":
            pl = e["graft" if typ == "GRAFT" else "prune"]
            bi = peer_index.get(pl["peerID"], -1)
            ci = topic_index.get(pl["topic"], -1)
            if bi >= 0 and ci >= 0:
                ops.append((OP_GRAFT if typ == "GRAFT" else OP_PRUNE,
                            ai, bi, ci))
        elif typ == "JOIN":
            ci = topic_index.get(e["join"]["topic"], -1)
            if ci >= 0:
                ops.append((OP_JOIN, ai, -1, ci))
        elif typ == "LEAVE":
            ci = topic_index.get(e["leave"]["topic"], -1)
            if ci >= 0:
                ops.append((OP_LEAVE, ai, -1, ci))
        elif typ == "ADD_PEER":
            bi = peer_index.get(e["addPeer"]["peerID"], -1)
            if bi >= 0:
                ops.append((OP_CONNECT, ai, bi, -1))
        elif typ == "REMOVE_PEER":
            bi = peer_index.get(e["removePeer"]["peerID"], -1)
            if bi >= 0:
                ops.append((OP_DISCONNECT, ai, bi, -1))
        elif typ == "PUBLISH_MESSAGE":
            pl = e["publishMessage"]
            ci = topic_index.get(pl.get("topic"), -1)
            if ci >= 0:
                ops.append((OP_PUBLISH, ai, slot_of(pl["messageID"]), ci))
        elif typ == "DELIVER_MESSAGE":
            pl = e["deliverMessage"]
            mid = pl["messageID"]
            ci = topic_index.get(pl.get("topic"), -1)
            rf = pl.get("receivedFrom")
            if ci < 0:
                continue
            sl = slot_of(mid)
            # the raw score hook is gated on received_from != observer
            # (trace/bus.py deliver_message; pubsub self-publish path)
            if rf is not None and rf != obs:
                bi = peer_index.get(rf, -1)
                if bi >= 0:
                    ops.append((OP_FIRST, ai, bi, ci))
                r = rec_of(obs, mid)
                if r.status == _UNKNOWN:
                    r.status = _VALID
                    r.validated = ts
                    # retro-credit duplicates that arrived during validation
                    # (score.go deliver: always in-window)
                    for p in r.peers:
                        if p != rf:
                            pi = peer_index.get(p, -1)
                            if pi >= 0:
                                ops.append((OP_DUP, ai, pi, ci))
            ops.append((OP_DELIVER, ai, sl, ci))
        elif typ == "DUPLICATE_MESSAGE":
            pl = e["duplicateMessage"]
            mid = pl["messageID"]
            ci = topic_index.get(pl.get("topic"), -1)
            rf = pl.get("receivedFrom")
            if ci < 0 or rf is None or rf == obs:
                continue
            r = rec_of(obs, mid)
            if rf in r.peers:
                continue
            if r.status == _UNKNOWN:
                r.peers.append(rf)
            elif r.status == _VALID:
                r.peers.append(rf)
                if ts - r.validated <= dup_window[ci]:
                    pi = peer_index.get(rf, -1)
                    if pi >= 0:
                        ops.append((OP_DUP, ai, pi, ci))
            elif r.status == _INVALID_ST:
                pi = peer_index.get(rf, -1)
                if pi >= 0:
                    ops.append((OP_INVALID, ai, pi, ci))
            # throttled/ignored: nothing
        elif typ == "REJECT_MESSAGE":
            pl = e["rejectMessage"]
            mid = pl["messageID"]
            ci = topic_index.get(pl.get("topic"), -1)
            rf = pl.get("receivedFrom")
            reason = pl.get("reason", "")
            if ci < 0 or rf is None or rf == obs:
                continue
            pi = peer_index.get(rf, -1)
            if reason in _SIG_REJECTS:
                if pi >= 0:
                    ops.append((OP_INVALID, ai, pi, ci))
                continue
            if reason in _SILENT_REJECTS:
                continue
            r = rec_of(obs, mid)
            if r.status != _UNKNOWN:
                continue
            if reason == ev.REJECT_VALIDATION_THROTTLED:
                r.status = _THROTTLED
                r.peers = []
            elif reason == ev.REJECT_VALIDATION_IGNORED:
                r.status = _IGNORED
                r.peers = []
            else:
                r.status = _INVALID_ST
                if pi >= 0:
                    ops.append((OP_INVALID, ai, pi, ci))
                for p in r.peers:
                    qi = peer_index.get(p, -1)
                    if qi >= 0:
                        ops.append((OP_INVALID, ai, qi, ci))
                r.peers = []

    if t_end is not None:
        while next_decay <= t_end + eps:
            ops.append((OP_DECAY, 0, 0, 0))
            next_decay += decay_interval

    if not ops:
        ops.append((OP_NOP, 0, 0, 0))
    arr = np.asarray(ops, dtype=np.int32)
    return ReplayFeed(op=arr[:, 0], a=arr[:, 1], b=arr[:, 2], c=arr[:, 3],
                      mid_slot=mid_slot)


# --- device-side injection ---


def _slot_lookup(st: SimState, a, b):
    """Slot of peer b in observer a's neighbor table; (k, found)."""
    row = st.neighbors[a]
    hit = row == b
    return jnp.argmax(hit), jnp.any(hit) & (b >= 0)


def _slot_score(st: SimState, cfg: SimConfig, tp: TopicParams, a, k) -> jnp.ndarray:
    """Score of the peer in observer a's slot k (score.go:265-342), scalar.

    Used by OP_DISCONNECT to pick the retention branch (score.go:614-618:
    positive scores are not retained)."""
    in_mesh = st.mesh[a, :, k]
    mesh_time = jnp.where(in_mesh, (st.tick - st.graft_tick[a, :, k])
                          .astype(jnp.float32), 0.0)
    p1 = jnp.minimum(jnp.floor(mesh_time / tp.time_in_mesh_quantum_ticks + 1e-9),
                     tp.time_in_mesh_cap)
    t_score = jnp.where(in_mesh, p1 * tp.time_in_mesh_weight, 0.0)
    t_score += st.first_message_deliveries[a, :, k] * \
        tp.first_message_deliveries_weight
    deficit = tp.mesh_message_deliveries_threshold - \
        st.mesh_message_deliveries[a, :, k]
    p3 = jnp.where(st.mesh_active[a, :, k] & (deficit > 0), deficit * deficit, 0.0)
    t_score += p3 * tp.mesh_message_deliveries_weight
    t_score += st.mesh_failure_penalty[a, :, k] * tp.mesh_failure_penalty_weight
    t_score += (st.invalid_message_deliveries[a, :, k] ** 2) * \
        tp.invalid_message_deliveries_weight
    score = jnp.sum(t_score * tp.topic_weight)
    if cfg.topic_score_cap > 0:
        score = jnp.minimum(score, cfg.topic_score_cap)
    if cfg.app_specific_weight != 0.0:
        nbr = jnp.clip(st.neighbors[a, k], 0, cfg.n_peers - 1)
        score += cfg.app_specific_weight * st.app_score[nbr]
    if cfg.behaviour_penalty_weight != 0.0:
        excess = st.behaviour_penalty[a, k] - cfg.behaviour_penalty_threshold
        score += jnp.where(excess > 0, excess * excess, 0.0) * \
            cfg.behaviour_penalty_weight
    return score


def _make_branches(cfg: SimConfig, tp: TopicParams):
    from ..ops.score_ops import decay_counters

    def nop(st, a, b, c):
        return st

    def decay(st, a, b, c):
        st = st._replace(tick=st.tick + 1)
        return decay_counters(st, cfg, tp)

    def graft(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        # score.go:649-667 Graft: in_mesh, graft time = now, latch reset;
        # graft_tick = tick+1 (module docstring: next-boundary convention)
        return st._replace(
            mesh=st.mesh.at[a, c, k].set(ok | st.mesh[a, c, k]),
            graft_tick=st.graft_tick.at[a, c, k].set(
                jnp.where(ok, st.tick + 1, st.graft_tick[a, c, k])),
            mesh_active=st.mesh_active.at[a, c, k].set(
                jnp.where(ok, False, st.mesh_active[a, c, k])))

    def prune(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        # score.go:669-694 Prune: sticky penalty while the P3 latch is
        # active and under threshold; latch itself is NOT cleared
        deficit = tp.mesh_message_deliveries_threshold[c] - \
            st.mesh_message_deliveries[a, c, k]
        add = jnp.where(ok & st.mesh_active[a, c, k] & (deficit > 0),
                        deficit * deficit, 0.0)
        return st._replace(
            mesh_failure_penalty=st.mesh_failure_penalty.at[a, c, k].add(add),
            mesh=st.mesh.at[a, c, k].set(jnp.where(ok, False, st.mesh[a, c, k])),
            backoff=st.backoff.at[a, c, k].set(jnp.where(
                ok, st.tick + cfg.prune_backoff_ticks, st.backoff[a, c, k])))

    def first(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        ok = ok & st.connected[a, k]
        fmd = jnp.where(ok, jnp.minimum(
            st.first_message_deliveries[a, c, k] + 1.0,
            tp.first_message_deliveries_cap[c]),
            st.first_message_deliveries[a, c, k])
        in_mesh = ok & st.mesh[a, c, k]
        mmd = jnp.where(in_mesh, jnp.minimum(
            st.mesh_message_deliveries[a, c, k] + 1.0,
            tp.mesh_message_deliveries_cap[c]),
            st.mesh_message_deliveries[a, c, k])
        return st._replace(
            first_message_deliveries=st.first_message_deliveries.at[a, c, k].set(fmd),
            mesh_message_deliveries=st.mesh_message_deliveries.at[a, c, k].set(mmd))

    def dup(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        ok = ok & st.connected[a, k] & st.mesh[a, c, k]
        mmd = jnp.where(ok, jnp.minimum(
            st.mesh_message_deliveries[a, c, k] + 1.0,
            tp.mesh_message_deliveries_cap[c]),
            st.mesh_message_deliveries[a, c, k])
        return st._replace(
            mesh_message_deliveries=st.mesh_message_deliveries.at[a, c, k].set(mmd))

    def invalid(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        ok = ok & st.connected[a, k]
        return st._replace(
            invalid_message_deliveries=st.invalid_message_deliveries
            .at[a, c, k].add(jnp.where(ok, 1.0, 0.0)))

    def penalty(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        return st._replace(behaviour_penalty=st.behaviour_penalty.at[a, k].add(
            jnp.where(ok, c.astype(jnp.float32), 0.0)))

    def join(st, a, b, c):
        from ..sim.state import refresh_nbr_subscribed
        return refresh_nbr_subscribed(
            st._replace(subscribed=st.subscribed.at[a, c].set(True)))

    def leave(st, a, b, c):
        from ..sim.state import refresh_nbr_subscribed
        return refresh_nbr_subscribed(
            st._replace(subscribed=st.subscribed.at[a, c].set(False)))

    def publish_op(st, a, b, c):
        from ..sim.state import have_set_bit
        return st._replace(
            msg_topic=st.msg_topic.at[b].set(c),
            msg_publish_tick=st.msg_publish_tick.at[b].set(st.tick),
            have=have_set_bit(st.have, a, b),
            deliver_tick=st.deliver_tick.at[a, b].set(st.tick))

    def deliver(st, a, b, c):
        from ..sim.state import have_set_bit
        return st._replace(
            have=have_set_bit(st.have, a, b),
            deliver_tick=st.deliver_tick.at[a, b].set(
                jnp.minimum(st.deliver_tick[a, b], st.tick)))

    def connect(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        expired = ok & (st.tick - st.disconnect_tick[a, k] > cfg.retain_score_ticks)
        zt = jnp.zeros((st.mesh.shape[1],), jnp.float32)

        def clr(x):
            return x.at[a, :, k].set(jnp.where(expired, zt, x[a, :, k]))

        return st._replace(
            first_message_deliveries=clr(st.first_message_deliveries),
            mesh_message_deliveries=clr(st.mesh_message_deliveries),
            mesh_failure_penalty=clr(st.mesh_failure_penalty),
            invalid_message_deliveries=clr(st.invalid_message_deliveries),
            behaviour_penalty=st.behaviour_penalty.at[a, k].set(
                jnp.where(expired, 0.0, st.behaviour_penalty[a, k])),
            connected=st.connected.at[a, k].set(ok | st.connected[a, k]),
            disconnect_tick=st.disconnect_tick.at[a, k].set(
                jnp.where(ok, NEVER, st.disconnect_tick[a, k])))

    def disconnect(st, a, b, c):
        k, ok = _slot_lookup(st, a, b)
        # score.go:611-644 RemovePeer: positive score -> stats dropped
        # outright; otherwise retention (FMD cleared, sticky P3b, frozen)
        drop = ok & (_slot_score(st, cfg, tp, a, k) > 0)
        retain = ok & ~drop
        t_ = st.mesh.shape[1]
        zt = jnp.zeros((t_,), jnp.float32)
        deficit = tp.mesh_message_deliveries_threshold - \
            st.mesh_message_deliveries[a, :, k]
        sticky = jnp.where(
            retain & st.mesh[a, :, k] & st.mesh_active[a, :, k] & (deficit > 0),
            deficit * deficit, 0.0)
        fmd = jnp.where(drop | retain, zt, st.first_message_deliveries[a, :, k])
        mmd = jnp.where(drop, zt, st.mesh_message_deliveries[a, :, k])
        mfp = jnp.where(drop, zt,
                        st.mesh_failure_penalty[a, :, k] + sticky)
        imd = jnp.where(drop, zt, st.invalid_message_deliveries[a, :, k])
        return st._replace(
            first_message_deliveries=st.first_message_deliveries.at[a, :, k].set(fmd),
            mesh_message_deliveries=st.mesh_message_deliveries.at[a, :, k].set(mmd),
            mesh_failure_penalty=st.mesh_failure_penalty.at[a, :, k].set(mfp),
            invalid_message_deliveries=st.invalid_message_deliveries
            .at[a, :, k].set(imd),
            behaviour_penalty=st.behaviour_penalty.at[a, k].set(
                jnp.where(drop, 0.0, st.behaviour_penalty[a, k])),
            mesh=st.mesh.at[a, :, k].set(
                jnp.where(ok, False, st.mesh[a, :, k])),
            fanout=st.fanout.at[a, :, k].set(
                jnp.where(ok, False, st.fanout[a, :, k])),
            connected=st.connected.at[a, k].set(
                jnp.where(ok, False, st.connected[a, k])),
            disconnect_tick=st.disconnect_tick.at[a, k].set(
                jnp.where(ok, st.tick, st.disconnect_tick[a, k])))

    return [nop, decay, graft, prune, first, dup, invalid, penalty,
            join, leave, publish_op, deliver, connect, disconnect]


@partial(jax.jit, static_argnames=("cfg",))
def replay(state: SimState, cfg: SimConfig, tp: TopicParams,
           op: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray,
           c: jnp.ndarray) -> SimState:
    """Inject a tensorized op stream into the state, in trace order."""
    branches = _make_branches(cfg, tp)

    def step(st, e):
        o, aa, bb, cc = e
        return jax.lax.switch(o, branches, st, aa, bb, cc), None

    state, _ = jax.lax.scan(step, state, (op, a, b, c))
    return state


def replay_feed(state: SimState, cfg: SimConfig, tp: TopicParams,
                feed: ReplayFeed) -> SimState:
    return replay(state, cfg, tp, jnp.asarray(feed.op), jnp.asarray(feed.a),
                  jnp.asarray(feed.b), jnp.asarray(feed.c))
