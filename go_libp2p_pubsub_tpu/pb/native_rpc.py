"""ctypes binding for the native RPC wire scanner (native/rpc_codec.cpp).

Bulk host-side RPC streams — interop captures, adversarial load fixtures,
differential-test corpora — are framed exactly like the reference's wire
(uvarint length prefix per RPC, comm.go:157-171). Scanning them frame by
frame through pb/codec.py builds a Python object per message; this path
walks the stream natively and returns three arrays:

  stats  [F, 8] int64 — per frame: subscriptions, publish count, publish
         data bytes, IHAVE ids, IWANT ids, GRAFTs, PRUNEs, PX records
  msgs   [M, 4] int64 — per publish message: frame idx, topic id,
         data length, big-endian seqno
  topics list[str] — topic_id -> topic name (first-seen order)

``scan_bytes`` uses the native library when buildable and falls back to
the pure-Python scan (same contract; tests/test_native_codec.py asserts
array equality between the two).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "rpc_codec.cpp")
_SO = os.path.join(_NATIVE_DIR, "librpccodec.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.rpc_codec_scan.restype = ctypes.c_int
        lib.rpc_codec_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.rpc_codec_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def scan_bytes_python(data: bytes, max_frame: int = 0):
    """Pure-Python twin of the native scan (the fallback + parity oracle)."""
    from .codec import read_uvarint, decode_rpc

    stats, msgs, topics, topic_ids = [], [], [], {}
    pos, frame = 0, 0
    while pos < len(data):
        flen, pos = read_uvarint(data, pos)
        if flen > len(data) - pos:
            raise ValueError("malformed frame")
        if max_frame and flen > max_frame:
            raise ValueError("oversize frame")
        rpc = decode_rpc(data[pos:pos + flen])
        pos += flen
        st = [0] * 8
        st[0] = len(rpc.subscriptions)
        st[1] = len(rpc.publish)
        for m in rpc.publish:
            tid = topic_ids.get(m.topic)
            if tid is None and m.topic:
                tid = len(topics)
                topics.append(m.topic)
                topic_ids[m.topic] = tid
            data_len = len(m.data or b"")
            st[2] += data_len
            seqno = int.from_bytes((m.seqno or b"")[:8], "big")
            msgs.append([frame, tid if tid is not None else -1,
                         data_len, seqno])
        c = rpc.control
        if c is not None:
            st[3] = sum(len(ih.message_ids) for ih in c.ihave)
            st[4] = sum(len(iw.message_ids) for iw in c.iwant)
            st[5] = len(c.graft)
            st[6] = len(c.prune)
            st[7] = sum(len(pr.peers) for pr in c.prune)
        stats.append(st)
        frame += 1
    return (np.asarray(stats, np.int64).reshape(-1, 8),
            np.asarray(msgs, np.int64).reshape(-1, 4), topics)


def scan_bytes(data: bytes, max_frame: int = 0):
    """Scan an RPC frame stream -> (stats [F,8], msgs [M,4], topics)."""
    lib = load()
    if lib is None:
        return scan_bytes_python(data, max_frame)
    stats_p = ctypes.POINTER(ctypes.c_int64)()
    msgs_p = ctypes.POINTER(ctypes.c_int64)()
    topics_p = ctypes.POINTER(ctypes.c_char)()
    n_frames = ctypes.c_long()
    n_msgs = ctypes.c_long()
    topics_bytes = ctypes.c_long()
    rc = lib.rpc_codec_scan(
        data, len(data), max_frame,
        ctypes.byref(stats_p), ctypes.byref(n_frames),
        ctypes.byref(msgs_p), ctypes.byref(n_msgs),
        ctypes.byref(topics_p), ctypes.byref(topics_bytes))
    if rc != 0:
        raise ValueError(f"native rpc scan failed (rc={rc}): "
                         + ("oversize frame" if rc == 3 else "malformed"))
    try:
        stats = np.ctypeslib.as_array(
            stats_p, shape=(n_frames.value, 8)).copy() \
            if n_frames.value else np.zeros((0, 8), np.int64)
        msgs = np.ctypeslib.as_array(
            msgs_p, shape=(n_msgs.value, 4)).copy() \
            if n_msgs.value else np.zeros((0, 4), np.int64)
        raw = ctypes.string_at(topics_p, topics_bytes.value) \
            if topics_bytes.value else b""
    finally:
        lib.rpc_codec_free(stats_p)
        lib.rpc_codec_free(msgs_p)
        lib.rpc_codec_free(topics_p)
    topics, off = [], 0
    while off < len(raw):
        ln = int.from_bytes(raw[off:off + 4], "little")
        off += 4
        topics.append(raw[off:off + ln].decode("utf-8"))
        off += ln
    return stats.astype(np.int64), msgs.astype(np.int64), topics
