"""Hand-rolled proto2 wire codec for the pubsub RPC/trace/compat schemas.

Implements exactly the reference's wire contract so frames interoperate with
go-libp2p-pubsub:

- RPC{subscriptions=1, publish=2, control=3} with SubOpts{subscribe=1,
  topicid=2}, ControlMessage{ihave=1, iwant=2, graft=3, prune=4},
  ControlIHave{topicID=1, messageIDs=2}, ControlIWant{messageIDs=1},
  ControlGraft{topicID=1}, ControlPrune{topicID=1, peers=2, backoff=3},
  PeerInfo{peerID=1, signedPeerRecord=2} (pb/rpc.proto:5-57)
- Message{from=1, data=2, seqno=3, topic=4, signature=5, key=6}
- legacy compat Message with repeated topicIDs=4 (compat/compat.proto:5-12)
- TraceEvent{type=1, peerID=2, timestamp=3, <payload>=4..16}
  (pb/trace.proto:5-150)

Wire framing between hosts is uvarint-length-delimited (comm.go:64,157-171).
Message-id strings are latin-1 round-tripped so arbitrary id bytes survive
(the reference warns its "string" ids are not valid utf8, pb/rpc.proto:35).
"""

from __future__ import annotations

from ..core.types import (
    RPC,
    ControlGraft,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    Message,
    PeerInfo,
    SubOpts,
)

# --- varint + field primitives ---


def write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def _tag(field: int, wire: int) -> bytes:
    return write_uvarint((field << 3) | wire)


def _bytes_field(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + write_uvarint(len(data)) + data


def _str_field(field: int, s: str) -> bytes:
    return _bytes_field(field, s.encode("utf-8"))


def _mid_field(field: int, s: str) -> bytes:
    # message ids carry raw bytes in a "string" field
    return _bytes_field(field, s.encode("latin-1"))


def _varint_field(field: int, n: int) -> bytes:
    return _tag(field, 0) + write_uvarint(n)


def _iter_fields(buf: bytes):
    """Yield (field, wire, value, next_pos) tuples; value is bytes for wire 2,
    int for wire 0, skipped otherwise."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_uvarint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = read_uvarint(buf, pos)
        elif wire == 2:
            ln, pos = read_uvarint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


# --- Message ---


def encode_message(m: Message) -> bytes:
    out = bytearray()
    if m.from_peer is not None:
        out += _bytes_field(1, m.from_peer.encode("utf-8"))
    if m.data:
        out += _bytes_field(2, m.data)
    if m.seqno is not None:
        out += _bytes_field(3, m.seqno)
    if m.topic:
        out += _str_field(4, m.topic)
    if m.signature is not None:
        out += _bytes_field(5, m.signature)
    if m.key is not None:
        out += _bytes_field(6, m.key)
    return bytes(out)


def decode_message(buf: bytes) -> Message:
    m = Message()
    for field, _, val in _iter_fields(buf):
        if field == 1:
            m.from_peer = val.decode("utf-8", "surrogateescape")
        elif field == 2:
            m.data = val
        elif field == 3:
            m.seqno = val
        elif field == 4:
            m.topic = val.decode("utf-8")
        elif field == 5:
            m.signature = val
        elif field == 6:
            m.key = val
    return m


# --- legacy compat Message (repeated topicIDs=4, compat/compat.proto) ---


def encode_compat_message(m: Message, topics: list[str] | None = None) -> bytes:
    out = bytearray()
    if m.from_peer is not None:
        out += _bytes_field(1, m.from_peer.encode("utf-8"))
    if m.data:
        out += _bytes_field(2, m.data)
    if m.seqno is not None:
        out += _bytes_field(3, m.seqno)
    for t in (topics if topics is not None else ([m.topic] if m.topic else [])):
        out += _str_field(4, t)
    if m.signature is not None:
        out += _bytes_field(5, m.signature)
    if m.key is not None:
        out += _bytes_field(6, m.key)
    return bytes(out)


def decode_compat_message(buf: bytes) -> tuple[Message, list[str]]:
    m = Message()
    topics: list[str] = []
    for field, _, val in _iter_fields(buf):
        if field == 1:
            m.from_peer = val.decode("utf-8", "surrogateescape")
        elif field == 2:
            m.data = val
        elif field == 3:
            m.seqno = val
        elif field == 4:
            topics.append(val.decode("utf-8"))
        elif field == 5:
            m.signature = val
        elif field == 6:
            m.key = val
    if topics:
        m.topic = topics[0]
    return m, topics


# --- control messages ---


def _encode_control(c: ControlMessage) -> bytes:
    out = bytearray()
    for ih in c.ihave:
        body = bytearray()
        if ih.topic:
            body += _str_field(1, ih.topic)
        for mid in ih.message_ids:
            body += _mid_field(2, mid)
        out += _bytes_field(1, bytes(body))
    for iw in c.iwant:
        body = bytearray()
        for mid in iw.message_ids:
            body += _mid_field(1, mid)
        out += _bytes_field(2, bytes(body))
    for g in c.graft:
        body = _str_field(1, g.topic) if g.topic else b""
        out += _bytes_field(3, bytes(body))
    for pr in c.prune:
        body = bytearray()
        if pr.topic:
            body += _str_field(1, pr.topic)
        for pi in pr.peers:
            pibody = _bytes_field(1, pi.peer_id.encode("utf-8"))
            if pi.signed_peer_record is not None:
                pibody += _bytes_field(2, pi.signed_peer_record)
            body += _bytes_field(2, pibody)
        if pr.backoff:
            body += _varint_field(3, int(pr.backoff))
        out += _bytes_field(4, bytes(body))
    return bytes(out)


def _decode_control(buf: bytes) -> ControlMessage:
    c = ControlMessage()
    for field, _, val in _iter_fields(buf):
        if field == 1:
            ih = ControlIHave()
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    ih.topic = v2.decode("utf-8")
                elif f2 == 2:
                    ih.message_ids.append(v2.decode("latin-1"))
            c.ihave.append(ih)
        elif field == 2:
            iw = ControlIWant()
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    iw.message_ids.append(v2.decode("latin-1"))
            c.iwant.append(iw)
        elif field == 3:
            g = ControlGraft()
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    g.topic = v2.decode("utf-8")
            c.graft.append(g)
        elif field == 4:
            pr = ControlPrune()
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    pr.topic = v2.decode("utf-8")
                elif f2 == 2:
                    pi = PeerInfo()
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            pi.peer_id = v3.decode("utf-8", "surrogateescape")
                        elif f3 == 2:
                            pi.signed_peer_record = v3
                    pr.peers.append(pi)
                elif f2 == 3:
                    pr.backoff = float(v2)
            c.prune.append(pr)
    return c


# --- RPC ---


def encode_rpc(rpc: RPC) -> bytes:
    out = bytearray()
    for sub in rpc.subscriptions:
        body = _varint_field(1, 1 if sub.subscribe else 0) + _str_field(2, sub.topicid)
        out += _bytes_field(1, body)
    for msg in rpc.publish:
        out += _bytes_field(2, encode_message(msg))
    if rpc.control is not None and not rpc.control.is_empty():
        out += _bytes_field(3, _encode_control(rpc.control))
    return bytes(out)


def decode_rpc(buf: bytes) -> RPC:
    rpc = RPC()
    for field, wire, val in _iter_fields(buf):
        if field == 1:
            sub = SubOpts()
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    sub.subscribe = bool(v2)
                elif f2 == 2:
                    sub.topicid = v2.decode("utf-8")
            rpc.subscriptions.append(sub)
        elif field == 2:
            rpc.publish.append(decode_message(val))
        elif field == 3:
            rpc.control = _decode_control(val)
    return rpc


def frame_rpc(rpc: RPC) -> bytes:
    """uvarint-length-delimited frame (comm.go:157-171)."""
    payload = encode_rpc(rpc)
    return write_uvarint(len(payload)) + payload


def read_frames(buf: bytes) -> list[RPC]:
    out = []
    pos = 0
    while pos < len(buf):
        ln, pos = read_uvarint(buf, pos)
        out.append(decode_rpc(buf[pos:pos + ln]))
        pos += ln
    return out


# --- TraceEvent (pb/trace.proto) ---

TRACE_TYPES = {
    "PUBLISH_MESSAGE": 0, "REJECT_MESSAGE": 1, "DUPLICATE_MESSAGE": 2,
    "DELIVER_MESSAGE": 3, "ADD_PEER": 4, "REMOVE_PEER": 5, "RECV_RPC": 6,
    "SEND_RPC": 7, "DROP_RPC": 8, "JOIN": 9, "LEAVE": 10, "GRAFT": 11,
    "PRUNE": 12,
}
TRACE_TYPE_NAMES = {v: k for k, v in TRACE_TYPES.items()}

# payload field number per event type (pb/trace.proto:9-22)
_PAYLOAD_FIELDS = {
    "PUBLISH_MESSAGE": 4, "REJECT_MESSAGE": 5, "DUPLICATE_MESSAGE": 6,
    "DELIVER_MESSAGE": 7, "ADD_PEER": 8, "REMOVE_PEER": 9, "RECV_RPC": 10,
    "SEND_RPC": 11, "DROP_RPC": 12, "JOIN": 13, "LEAVE": 14, "GRAFT": 15,
    "PRUNE": 16,
}

# sub-message schemas: payload key -> list of (field_no, kind, dict key)
# NOTE Leave.topic is field 2, not 1 (pb/trace.proto:94 — the only payload
# whose first field number is not 1; verified against trace.pb.go's
# TraceEvent_Leave.MarshalToSizedBuffer tag byte 0x12).
_PAYLOAD_SCHEMAS: dict[str, list[tuple[int, str, str]]] = {
    "publishMessage": [(1, "mid", "messageID"), (2, "str", "topic")],
    "rejectMessage": [(1, "mid", "messageID"), (2, "peer", "receivedFrom"),
                      (3, "str", "reason"), (4, "str", "topic")],
    "duplicateMessage": [(1, "mid", "messageID"), (2, "peer", "receivedFrom"),
                         (3, "str", "topic")],
    "deliverMessage": [(1, "mid", "messageID"), (2, "str", "topic"),
                       (3, "peer", "receivedFrom")],
    "addPeer": [(1, "peer", "peerID"), (2, "str", "proto")],
    "removePeer": [(1, "peer", "peerID")],
    "recvRPC": [(1, "peer", "receivedFrom"), (2, "meta", "meta")],
    "sendRPC": [(1, "peer", "sendTo"), (2, "meta", "meta")],
    "dropRPC": [(1, "peer", "sendTo"), (2, "meta", "meta")],
    "join": [(1, "str", "topic")],
    "leave": [(2, "str", "topic")],
    "graft": [(1, "peer", "peerID"), (2, "str", "topic")],
    "prune": [(1, "peer", "peerID"), (2, "str", "topic")],
}

_TYPE_TO_PAYLOAD_KEY = {
    "PUBLISH_MESSAGE": "publishMessage", "REJECT_MESSAGE": "rejectMessage",
    "DUPLICATE_MESSAGE": "duplicateMessage", "DELIVER_MESSAGE": "deliverMessage",
    "ADD_PEER": "addPeer", "REMOVE_PEER": "removePeer", "RECV_RPC": "recvRPC",
    "SEND_RPC": "sendRPC", "DROP_RPC": "dropRPC", "JOIN": "join",
    "LEAVE": "leave", "GRAFT": "graft", "PRUNE": "prune",
}


def _peer_field(field: int, s: str) -> bytes:
    # peer ids are raw multihash bytes surviving in str via surrogateescape
    return _bytes_field(field, s.encode("utf-8", "surrogateescape"))


def _encode_rpc_meta(meta: dict) -> bytes:
    """TraceEvent.RPCMeta (pb/trace.proto:106-110), dict shape as produced by
    trace/bus.py's _rpc_meta: messages / subscription / control."""
    out = bytearray()
    for mm in meta.get("messages", ()):
        body = bytearray()
        if mm.get("messageID") is not None:
            body += _mid_field(1, mm["messageID"])
        if mm.get("topic") is not None:
            body += _str_field(2, mm["topic"])
        out += _bytes_field(1, bytes(body))
    for sm in meta.get("subscription", ()):
        body = bytearray()
        if sm.get("subscribe") is not None:
            body += _varint_field(1, 1 if sm["subscribe"] else 0)
        if sm.get("topic") is not None:
            body += _str_field(2, sm["topic"])
        out += _bytes_field(2, bytes(body))
    ctl = meta.get("control")
    if ctl is not None:
        body = bytearray()
        for ih in ctl.get("ihave", ()):
            b2 = bytearray()
            if ih.get("topic") is not None:
                b2 += _str_field(1, ih["topic"])
            for mid in ih.get("messageIDs", ()):
                b2 += _mid_field(2, mid)
            body += _bytes_field(1, bytes(b2))
        for iw in ctl.get("iwant", ()):
            b2 = bytearray()
            for mid in iw.get("messageIDs", ()):
                b2 += _mid_field(1, mid)
            body += _bytes_field(2, bytes(b2))
        for g in ctl.get("graft", ()):
            b2 = _str_field(1, g["topic"]) if g.get("topic") is not None else b""
            body += _bytes_field(3, bytes(b2))
        for p in ctl.get("prune", ()):
            b2 = bytearray()
            if p.get("topic") is not None:
                b2 += _str_field(1, p["topic"])
            for pid in p.get("peers", ()):
                b2 += _peer_field(2, pid)
            body += _bytes_field(4, bytes(b2))
        out += _bytes_field(3, bytes(body))
    return bytes(out)


def _decode_rpc_meta(buf: bytes) -> dict:
    meta: dict = {}
    for field, _, val in _iter_fields(buf):
        if field == 1:
            mm: dict = {}
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    mm["messageID"] = v2.decode("latin-1")
                elif f2 == 2:
                    mm["topic"] = v2.decode("utf-8")
            meta.setdefault("messages", []).append(mm)
        elif field == 2:
            sm: dict = {}
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    sm["subscribe"] = bool(v2)
                elif f2 == 2:
                    sm["topic"] = v2.decode("utf-8")
            meta.setdefault("subscription", []).append(sm)
        elif field == 3:
            ctl: dict = {}
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    ih: dict = {}
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            ih["topic"] = v3.decode("utf-8")
                        elif f3 == 2:
                            ih.setdefault("messageIDs", []).append(
                                v3.decode("latin-1"))
                    ctl.setdefault("ihave", []).append(ih)
                elif f2 == 2:
                    iw: dict = {}
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            iw.setdefault("messageIDs", []).append(
                                v3.decode("latin-1"))
                    ctl.setdefault("iwant", []).append(iw)
                elif f2 == 3:
                    g: dict = {}
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            g["topic"] = v3.decode("utf-8")
                    ctl.setdefault("graft", []).append(g)
                elif f2 == 4:
                    p: dict = {}
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:
                            p["topic"] = v3.decode("utf-8")
                        elif f3 == 2:
                            p.setdefault("peers", []).append(
                                v3.decode("utf-8", "surrogateescape"))
                    ctl.setdefault("prune", []).append(p)
            meta["control"] = ctl
    return meta


def _encode_payload(key: str, payload: dict) -> bytes:
    out = bytearray()
    for field, kind, name in _PAYLOAD_SCHEMAS[key]:
        v = payload.get(name)
        if v is None:
            continue
        if kind == "mid":
            out += _mid_field(field, v)
        elif kind == "peer":
            out += _peer_field(field, v)
        elif kind == "meta":
            out += _bytes_field(field, _encode_rpc_meta(v))
        else:
            out += _str_field(field, v)
    return bytes(out)


def _decode_payload(key: str, buf: bytes) -> dict:
    schema = {f: (kind, name) for f, kind, name in _PAYLOAD_SCHEMAS[key]}
    out: dict = {}
    for field, _, val in _iter_fields(buf):
        if field not in schema:
            continue
        kind, name = schema[field]
        if kind == "mid":
            out[name] = val.decode("latin-1")
        elif kind == "peer":
            out[name] = val.decode("utf-8", "surrogateescape")
        elif kind == "meta":
            out[name] = _decode_rpc_meta(val)
        else:
            out[name] = val.decode("utf-8")
    return out


def encode_trace_event(evt: dict) -> bytes:
    """Encode a tracer-bus event dict (trace/bus.py shape) to TraceEvent bytes.

    Timestamps are virtual-clock seconds scaled to int64 nanoseconds, matching
    the reference's UnixNano timestamps (trace.go:90); an integer
    ``timestamp_ns`` takes precedence so real UnixNano values (> 2**53, not
    exactly representable as float seconds) round-trip bit-exactly."""
    typ = evt["type"]
    out = bytearray()
    out += _varint_field(1, TRACE_TYPES[typ])
    if "peerID" in evt:
        out += _peer_field(2, evt["peerID"])
    if "timestamp_ns" in evt:
        out += _varint_field(3, int(evt["timestamp_ns"]))
    elif "timestamp" in evt:
        out += _varint_field(3, int(evt["timestamp"] * 1e9))
    key = _TYPE_TO_PAYLOAD_KEY[typ]
    payload = evt.get(key)
    if payload is None:
        # RPC events carry their peer + meta at the top level of the bus dict
        payload = {k: v for k, v in evt.items()
                   if k in ("receivedFrom", "sendTo", "meta")}
    if payload:
        out += _bytes_field(_PAYLOAD_FIELDS[typ], _encode_payload(key, payload))
    return bytes(out)


def decode_trace_event(buf: bytes) -> dict:
    evt: dict = {}
    payload_field_to_type = {v: k for k, v in _PAYLOAD_FIELDS.items()}
    for field, wire, val in _iter_fields(buf):
        if field == 1:
            evt["type"] = TRACE_TYPE_NAMES[val]
        elif field == 2:
            evt["peerID"] = val.decode("utf-8", "surrogateescape")
        elif field == 3:
            evt["timestamp"] = val / 1e9
            evt["timestamp_ns"] = val
        elif field in payload_field_to_type:
            typ = payload_field_to_type[field]
            evt[_TYPE_TO_PAYLOAD_KEY[typ]] = _decode_payload(
                _TYPE_TO_PAYLOAD_KEY[typ], val)
    return evt


def decode_trace_bytes(data: bytes) -> list[dict]:
    """Decode a uvarint-delimited TraceEvent stream."""
    out = []
    pos = 0
    while pos < len(data):
        ln, pos = read_uvarint(data, pos)
        out.append(decode_trace_event(data[pos:pos + ln]))
        pos += ln
    return out


def read_trace_file(path: str) -> list[dict]:
    """Read a PBTracer output file (uvarint-delimited TraceEvents)."""
    with open(path, "rb") as f:
        return decode_trace_bytes(f.read())


def encode_trace_event_batch(events: list[dict]) -> bytes:
    """TraceEventBatch{batch=1 repeated TraceEvent} (pb/trace.proto:148-150),
    the RemoteTracer wire unit (tracer.go:239)."""
    out = bytearray()
    for e in events:
        out += _bytes_field(1, encode_trace_event(e))
    return bytes(out)


def decode_trace_event_batch(buf: bytes) -> list[dict]:
    return [decode_trace_event(val)
            for field, _, val in _iter_fields(buf) if field == 1]
