"""Fused Pallas forwarding-hop kernel (PERF_MODEL.md S4).

One hop of frontier propagation currently costs ~1.1 GB of HBM traffic at
100k peers under the XLA lowering: the neighbor gather materializes
[W,K,N], the lowest-slot winner attribution runs a 5-pass associative-scan
prefix-OR over K, and the event accumulators are read+written as separate
passes. This kernel fuses the whole hop per receiver block with the packed
frontier table pinned in VMEM:

    gather (in-VMEM table lookups) -> allowed/mesh expansion from bool
    planes -> K-unrolled prefix-OR in registers -> uint8 per-(topic, slot)
    event counts accumulated into aliased outputs

HBM per hop drops to: nbr indices + two bool planes + the uint8 count
accumulators + a handful of [W, N] tables — ~55 MB at the headline shape
(PERF_MODEL.md "planned" hop row).

Eligibility (resolve_hop_mode; ``auto`` ranks through ops/dispatch.py):
no per-edge/validation budgets, no gater, no provenance, no
flood-publish — those configs keep the XLA formulation.
Bit-identical to the XLA hop: tests/test_hopkernel.py checks op-level
(forward_tick, T=1 and T=3) and full-8-tick-run state equality in
interpret mode, plus the resolution policy.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..parallel.kernel_context import (
    PEER,
    current_kernel_mesh,
    local_rows,
    shard_kernel,
)
from .bits import U32, pack_words, prefix_count_words, unpack_words
from .permgather import _PALLAS_VMEM_PAYLOAD_BYTES, _block_rows


def _take_rows(tab, nbrb, w, k, gather="take"):
    """In-kernel neighbor gather of a VMEM-pinned [W, N] table -> [W, BN, K].

    ``gather="take"`` is the jnp.take lowering (Mosaic refuses it above 128
    lanes — the gather wall); ``"mxu"`` is the gather-free two-level
    one-hot select (ops/mxutake.take_words_onehot), the formulation the
    ``pallas-mxu`` hop mode exists to A/B on a live window."""
    if gather == "mxu":
        from .mxutake import take_words_onehot
        g = take_words_onehot(tab, nbrb.reshape(-1))
    else:
        g = jnp.take(tab, nbrb.reshape(-1), axis=1)
    return g.reshape(w, nbrb.shape[0], k)


def _expand_topic(planes_u8, tb, like):
    """In-kernel per-topic expansion: [BN, T, K] uint8 bool planes + [T, W]
    topic message sets -> [W, BN, K] packed words (topic sets are disjoint,
    so OR == sum)."""
    out = jnp.zeros_like(like)
    for ti in range(tb.shape[0]):
        out = out | jnp.where((planes_u8[:, ti, :] != 0)[None, :, :],
                              tb[ti][:, None, None], U32(0))
    return out


class HopOut(NamedTuple):
    new_valid: jnp.ndarray    # [W, N] next frontier (validated new arrivals)
    have: jnp.ndarray         # [W, N] updated seen set
    dlv: jnp.ndarray          # [W, N] updated delivered set
    dlv_new: jnp.ndarray      # [W, N] deliveries accumulated this tick
    nv: jnp.ndarray           # [T, K, N] uint8 first-delivery counts
    ni: jnp.ndarray           # [T, K, N] uint8 invalid (P4) counts
    dup: jnp.ndarray          # [T, K, N] uint8 mesh-duplicate counts


def _hop_config_ok(cfg) -> bool:
    """Config eligibility shared by both Pallas hop variants."""
    return not (cfg.gater_enabled or cfg.record_provenance
                or cfg.edge_queue_cap > 0 or cfg.validation_queue_cap > 0
                or (cfg.flood_publish and cfg.router == "gossipsub")
                or cfg.count_dtype != "uint8"
                # link duplication ORs an extra hop-0 offer table the
                # fused kernel has no input for; link drop needs the
                # split broken-promise accounting (a link-eaten answer IS
                # broken, a graylist/gater drop is not — propagate.py
                # resolve step), which the fused resolve kernel's single
                # data_ok plane cannot express
                or (cfg.fault_plan is not None
                    and (cfg.fault_plan.link_dup_prob > 0
                         or cfg.fault_plan.link_drop_prob > 0
                         # slow-link classes fold into link_ok (same
                         # split-accounting need as link drop); the
                         # censorship per-sender frontier mask has no
                         # fused-kernel input at all (propagate.py)
                         or getattr(cfg.fault_plan, "slowlinks", ())
                         or getattr(cfg.fault_plan, "censorships", ()))))


def _hop_shape_ok(w: int, n: int, k: int) -> bool:
    # table feasibility is GLOBAL n; block feasibility is the per-shard
    # row count under a kernel mesh. pallas-mxu no longer needs a
    # lane-aligned peer count: the table pads OUT of kernel (mxutake
    # .pad_lanes seam in hop_pallas/iwant_resolve_pallas/emit_pallas)
    return (w * n * 4 <= _PALLAS_VMEM_PAYLOAD_BYTES
            and _block_rows(local_rows(n), 4 * w * k * 4) is not None)


def resolve_hop_mode(mode: str, cfg, w: int, n: int, k: int) -> str:
    """Resolve the forwarding-hop formulation. ``auto`` ranks candidates
    through the measured cost-model dispatch (ops/dispatch.py); under the
    shipped conservative table that is 'xla' everywhere: the fused
    kernels are bit-exact and shard_map-ready, but the first live-tunnel
    window proved current Mosaic CANNOT lower any >128-wide table lookup
    ("Multiple source vregs along gather dimension" — tpu.dynamic_gather
    shuffles within one vector register only), so the VMEM-table design
    is not compilable on real v5e today ('pallas' is quarantined in the
    table). ``pallas-mxu`` — the same fused design with every in-kernel
    gather rewritten as the gather-free two-level one-hot select
    (mxutake.py) — is priced pessimistically (streamed one-hot operand)
    until a calibrated GRAFT_DISPATCH_TABLE measures the resident
    lowering and promotes it. Config eligibility applies to both Pallas
    variants; the old lane-aligned-N constraint on ``pallas-mxu`` is
    gone (out-of-kernel pad seam)."""
    if mode not in ("auto", "xla", "pallas", "pallas-mxu"):
        raise ValueError(f"unknown hop_mode {mode!r}")
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("hop", w=w, n=n, k=k):
            if cand == "xla" or (_hop_config_ok(cfg)
                                 and _hop_shape_ok(w, n, k)):
                return cand
        return "xla"
    if mode in ("pallas", "pallas-mxu") and \
            not (_hop_config_ok(cfg) and _hop_shape_ok(w, n, k)):
        return "xla"
    return mode


def resolve_emit_mode(mode: str, w: int, n: int, k: int) -> str:
    """Gossip-emit formulation: the fused kernel has no config
    restrictions (the emit step has no cap/gater/provenance interaction) —
    only VMEM-feasibility gates (lane alignment is handled by the
    out-of-kernel pad seam, as in resolve_hop_mode). ``auto`` ranks
    through ops/dispatch.py like the hop.

    NATIVE-LOWERING RISK (ADVICE r5): ``emit_pallas`` mixes
    ``prefix_count_words`` and ``pack_words`` inside the kernel body —
    1-D iota, a ``masked.T`` transpose, per-word shifts — an op class
    Mosaic has historically refused to lower even where interpret mode
    (the CI tier) is exact. The conservative table therefore keeps
    ``auto`` at ``xla``; before promoting an explicit
    ``pallas``/``pallas-mxu`` emit on real TPU, confirm the dedicated
    native probes in scripts/tpu_kernel_smoke.py ("emit_pallas*" and
    "emit resolve path (engine-shaped)") pass on a live window."""
    if mode not in ("auto", "xla", "pallas", "pallas-mxu"):
        raise ValueError(f"unknown hop_mode {mode!r}")
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("emit", w=w, n=n, k=k):
            if cand == "xla" or _hop_shape_ok(w, n, k):
                return cand
        return "xla"
    if mode in ("pallas", "pallas-mxu") and not _hop_shape_ok(w, n, k):
        return "xla"
    return mode


@functools.partial(jax.jit,
                   static_argnames=("m", "budget", "gather", "interpret"))
def emit_pallas(window, have, gossip_u8, topic_bits, nbr, m, budget,
                gather="take", interpret=False) -> jnp.ndarray:
    """Fused IHAVE->IWANT chooser (PERF_MODEL.md S7): gossipsub.go:654-676.

    window: [W, N] u32 sender gossip-window table (VMEM-pinned);
    have: [W, N] u32 receiver seen sets; gossip_u8: [N, T, K] uint8
    receiver-view gossip-edge planes (valid-slot and gossip-threshold
    masking already applied); topic_bits: [T, W]; nbr pre-clipped [N, K];
    budget: the per-sender iasked cap (MaxIHaveLength) — a budget >= M
    reduces exactly to the lowest-offering-slot choice. Returns
    iwant_pending [N, M] int32 (chosen slot per message, -1 none).

    Replaces: the [W,K,N] offer materialization, the 5-pass prefix-OR,
    the bit-plane slot decode, and the K-step budget scan of the XLA
    formulation — everything happens per receiver block in VMEM.
    """
    from jax.experimental import pallas as pl

    if gather == "mxu":
        # out-of-kernel pad seam: the in-kernel one-hot select needs a
        # lane-aligned table width (mxutake.take_words_onehot); nbr < N
        # never selects a pad column
        from .mxutake import pad_lanes
        window = pad_lanes(window)
    w, n = window.shape
    nr, k = nbr.shape                  # receiver rows (local shard under
    t = topic_bits.shape[0]            # a kernel mesh; == n unsharded)
    bn = _block_rows(nr, 4 * w * k * 4)
    assert bn is not None, "resolve_emit_mode admitted an infeasible shape"

    def kernel(win_ref, have_ref, gos_ref, tb_ref, nbr_ref, out_ref):
        tab = win_ref[:]                                  # [W, N] in VMEM
        nbrb = nbr_ref[:]                                 # [BN, K]
        g = _take_rows(tab, nbrb, w, k, gather)           # [W, BN, K]
        tb = tb_ref[:]
        off = g & _expand_topic(gos_ref[:], tb, g)

        def unpack(words):                                # [W, BN] -> [BN, M]
            return unpack_words(words, m)                 # ops/bits layout

        assigned_w = have_ref[:]                          # packed; seen = never asked
        pend = jnp.full((nbrb.shape[0], m), -1, jnp.int32)
        # slot-order serial assignment with per-slot budget (the iasked
        # counter): an id a budget-exhausted slot passes over is still
        # pulled from a later slot with headroom (gossipsub.go:654-676).
        # Same masked-popcount rank as _budgeted_iwant (ops/bits
        # prefix_count_words — the cumsum lowering it replaces measured
        # ~16x slower on CPU, where this kernel's interpret path runs)
        for ki in range(k):
            masked = off[:, :, ki] & ~assigned_w          # [W, BN]
            off_u = unpack(masked)                        # [BN, M]
            take = off_u & (prefix_count_words(masked.T, m) <= budget)
            pend = jnp.where(take, ki, pend)
            assigned_w = assigned_w | pack_words(take)
        out_ref[:] = pend

    return pl.pallas_call(
        kernel,
        grid=(nr // bn,),
        in_specs=[
            pl.BlockSpec((w, n), lambda i: (0, 0)),       # window table
            pl.BlockSpec((w, bn), lambda i: (0, i)),      # have
            pl.BlockSpec((bn, t, k), lambda i: (i, 0, 0)),  # gossip planes
            pl.BlockSpec((t, w), lambda i: (0, 0)),
            pl.BlockSpec((bn, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, m), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nr, m), jnp.int32),
        interpret=interpret,
    )(window, have, gossip_u8, topic_bits, nbr)


class ResolveOut(NamedTuple):
    got_any: jnp.ndarray      # [W, N] pulled (seen) this tick
    got_valid_any: jnp.ndarray  # [W, N] pulled AND delivered
    nv: jnp.ndarray           # [T, K, N] uint8 first-delivery seed counts
    ni: jnp.ndarray           # [T, K, N] uint8 invalid seed counts
    broken: jnp.ndarray       # [K, N] uint8 broken-promise counts (P7)


@functools.partial(jax.jit, static_argnames=("m", "gather", "interpret"))
def iwant_resolve_pallas(pend, answers, have, vm, inv_n, alive, data_ok_u8,
                         topic_bits, nbr, m, gather="take",
                         interpret=False) -> ResolveOut:
    """Fused IWANT resolution (PERF_MODEL.md S6): gossipsub.go:698-739 +
    the broken-promise accounting of gossip_tracer.go:79-115.

    pend: [N, M] int32 pending-pull slot per message (-1 none); answers:
    [W, N] u32 sender mcache table (malicious columns zeroed, VMEM-pinned);
    have/vm/inv_n: [W, N] receiver tables; alive: [W, 1]; data_ok_u8:
    [N, K] uint8 graylist admission; topic_bits: [T, W]; nbr pre-clipped.
    Same eligibility as the hop kernel (no caps/gater/provenance), so the
    budget/throttle plumbing of the XLA path is dead here.
    """
    from jax.experimental import pallas as pl

    if gather == "mxu":
        # out-of-kernel pad seam (see emit_pallas)
        from .mxutake import pad_lanes
        answers = pad_lanes(answers)
    w, n = answers.shape
    nr, k = nbr.shape                  # receiver rows (local shard under
    t = topic_bits.shape[0]            # a kernel mesh; == n unsharded)
    bn = _block_rows(nr, 4 * w * k * 4)
    assert bn is not None, "resolve_hop_mode admitted an infeasible shape"

    def kernel(pend_ref, ans_ref, have_ref, vm_ref, inv_ref, alive_ref,
               ok_ref, tb_ref, nbr_ref,
               out_ga, out_gva, out_nv, out_ni, out_bk):
        tab = ans_ref[:]                                  # [W, N] in VMEM
        pend_b = pend_ref[:]                              # [BN, M]
        nbrb = nbr_ref[:]
        have_b = have_ref[:]
        vm_b = vm_ref[:]
        inv_b = inv_ref[:]
        alive_b = alive_ref[:]                            # [W, 1]
        ok_b = ok_ref[:]                                  # [BN, K] u8
        tb = tb_ref[:]

        def pack(bits):                                   # [BN, M] -> [W, BN]
            return pack_words(bits)                       # ops/bits layout

        nv = jnp.zeros((t, k, pend_b.shape[0]), jnp.uint8)
        ni = jnp.zeros((t, k, pend_b.shape[0]), jnp.uint8)
        bk = jnp.zeros((k, pend_b.shape[0]), jnp.uint8)
        got_any = jnp.zeros_like(have_b)
        got_valid_any = jnp.zeros_like(have_b)
        for ki in range(k):
            asked = pack(pend_b == ki) & alive_b          # [W, BN]
            if gather == "mxu":
                from .mxutake import take_words_onehot
                ans_k = take_words_onehot(tab, nbrb[:, ki])   # [W, BN]
            else:
                ans_k = jnp.take(tab, nbrb[:, ki], axis=1)    # [W, BN]
            adm = jnp.where((ok_b[:, ki] != 0)[None, :],
                            U32(0xFFFFFFFF), U32(0))
            got = asked & ans_k & ~have_b & adm
            broken = asked & ~ans_k
            gv = got & vm_b
            got_any = got_any | got
            got_valid_any = got_valid_any | gv
            bk = bk.at[ki, :].add(jnp.sum(
                jax.lax.population_count(broken), axis=0).astype(jnp.uint8))
            for ti in range(t):
                tw = tb[ti][:, None]
                nv = nv.at[ti, ki, :].add(jnp.sum(jax.lax.population_count(
                    gv & tw), axis=0).astype(jnp.uint8))
                ni = ni.at[ti, ki, :].add(jnp.sum(jax.lax.population_count(
                    got & inv_b & tw), axis=0).astype(jnp.uint8))
        out_ga[:] = got_any
        out_gva[:] = got_valid_any
        out_nv[:] = nv
        out_ni[:] = ni
        out_bk[:] = bk

    wn = lambda i: (0, i)                                 # noqa: E731
    tkn = lambda i: (0, 0, i)                             # noqa: E731
    outs = pl.pallas_call(
        kernel,
        grid=(nr // bn,),
        in_specs=[
            pl.BlockSpec((bn, m), lambda i: (i, 0)),      # pend
            pl.BlockSpec((w, n), lambda i: (0, 0)),       # answers table
            pl.BlockSpec((w, bn), wn),                    # have
            pl.BlockSpec((w, bn), wn),                    # vm
            pl.BlockSpec((w, bn), wn),                    # inv
            pl.BlockSpec((w, 1), lambda i: (0, 0)),       # alive
            pl.BlockSpec((bn, k), lambda i: (i, 0)),      # data_ok
            pl.BlockSpec((t, w), lambda i: (0, 0)),
            pl.BlockSpec((bn, k), lambda i: (i, 0)),      # nbr
        ],
        out_specs=[
            pl.BlockSpec((w, bn), wn), pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((t, k, bn), tkn), pl.BlockSpec((t, k, bn), tkn),
            pl.BlockSpec((k, bn), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((t, k, nr), jnp.uint8),
            jax.ShapeDtypeStruct((t, k, nr), jnp.uint8),
            jax.ShapeDtypeStruct((k, nr), jnp.uint8),
        ],
        interpret=interpret,
    )(pend, answers, have, vm, inv_n, alive, data_ok_u8, topic_bits, nbr)
    return ResolveOut(*outs)


@functools.partial(jax.jit, static_argnames=("gather", "interpret"))
def hop_pallas(frontier, have, dlv, dlv_new, vm, inv_n, window_old,
               valid_msg, nbr, fwd_mask_u8, mesh_u8, topic_bits,
               nv, ni, dup, gather="take", interpret=False) -> HopOut:
    """One fused forwarding hop.

    frontier/have/dlv/dlv_new/vm/inv_n/window_old: [W, N] u32 packed tables
    (receiver-indexed except frontier, which is sender-indexed and pinned
    whole in VMEM). valid_msg: [W, 1] u32. nbr: [N, K] pre-clipped.
    fwd_mask_u8/mesh_u8: [N, T, K] uint8 bool planes. topic_bits: [T, W]
    u32 per-topic live-message sets. nv/ni/dup: [T, K, N] uint8 event-count
    accumulators, updated in place via aliasing.
    """
    from jax.experimental import pallas as pl

    if gather == "mxu":
        # out-of-kernel pad seam (see emit_pallas)
        from .mxutake import pad_lanes
        frontier = pad_lanes(frontier)
    w, n = frontier.shape
    nr, k = nbr.shape                  # receiver rows (local shard under
    t = topic_bits.shape[0]            # a kernel mesh; == n unsharded)
    bn = _block_rows(nr, 4 * w * k * 4)
    assert bn is not None, "resolve_hop_mode admitted an infeasible shape"

    def kernel(fro_ref, have_ref, dlv_ref, dlvnew_ref, vm_ref, inv_ref,
               wold_ref, vmsg_ref, nbr_ref, fwd_ref, mesh_ref, tb_ref,
               nv_ref, ni_ref, dup_ref,
               out_newv, out_have, out_dlv, out_dlvnew,
               out_nv, out_ni, out_dup):
        tab = fro_ref[:]                                  # [W, N] in VMEM
        nbrb = nbr_ref[:]                                 # [BN, K]
        g = _take_rows(tab, nbrb, w, k, gather)           # [W, BN, K] offered
        tb = tb_ref[:]                                    # [T, W]
        allowed = _expand_topic(fwd_ref[:], tb, g)
        mesh_eb = _expand_topic(mesh_ref[:], tb, g)
        off = g & allowed                                 # [W, BN, K]

        have_b = have_ref[:]                              # [W, BN]
        vm_b = vm_ref[:]
        inv_b = inv_ref[:]
        nv_acc = nv_ref[:]                                # [T, K, BN] u8
        ni_acc = ni_ref[:]
        # K-unrolled lowest-slot prefix: excl carries OR of lower slots
        excl = jnp.zeros_like(have_b)
        for ki in range(k):
            off_k = off[:, :, ki]
            nf_k = off_k & ~excl & ~have_b                # winner bits
            excl = excl | off_k
            for ti in range(t):
                tw = tb[ti][:, None]
                ev_nv = nf_k & vm_b & tw
                ev_ni = nf_k & inv_b & tw
                cnt_nv = jnp.sum(jax.lax.population_count(ev_nv),
                                 axis=0).astype(jnp.uint8)
                cnt_ni = jnp.sum(jax.lax.population_count(ev_ni),
                                 axis=0).astype(jnp.uint8)
                nv_acc = nv_acc.at[ti, ki, :].add(cnt_nv)
                ni_acc = ni_acc.at[ti, ki, :].add(cnt_ni)

        new_any = excl & ~have_b
        new_valid = new_any & vm_b
        # mesh-duplicate eligibility uses the WHOLE hop's new deliveries
        # (order-independent within the hop, as the XLA formulation)
        elig = (wold_ref[:] | dlvnew_ref[:] | new_valid) & vmsg_ref[:]
        dup_acc = dup_ref[:]
        for ki in range(k):
            dup_k = off[:, :, ki] & mesh_eb[:, :, ki] & elig
            for ti in range(t):
                ev = dup_k & tb[ti][:, None]
                cnt = jnp.sum(jax.lax.population_count(ev),
                              axis=0).astype(jnp.uint8)
                dup_acc = dup_acc.at[ti, ki, :].add(cnt)

        out_newv[:] = new_valid
        out_have[:] = have_b | new_any
        out_dlv[:] = dlv_ref[:] | new_valid
        out_dlvnew[:] = dlvnew_ref[:] | new_valid
        out_nv[:] = nv_acc
        out_ni[:] = ni_acc
        out_dup[:] = dup_acc

    wn = lambda i: (0, i)       # [W, BN] blocks          # noqa: E731
    tkn = lambda i: (0, 0, i)   # [T, K, BN] blocks       # noqa: E731
    grid = nr // bn
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((w, n), lambda i: (0, 0)),       # frontier table
            pl.BlockSpec((w, bn), wn),                    # have
            pl.BlockSpec((w, bn), wn),                    # dlv
            pl.BlockSpec((w, bn), wn),                    # dlv_new
            pl.BlockSpec((w, bn), wn),                    # vm
            pl.BlockSpec((w, bn), wn),                    # inv_n
            pl.BlockSpec((w, bn), wn),                    # window_old
            pl.BlockSpec((w, 1), lambda i: (0, 0)),       # valid_msg
            pl.BlockSpec((bn, k), lambda i: (i, 0)),      # nbr
            pl.BlockSpec((bn, t, k), lambda i: (i, 0, 0)),  # fwd planes
            pl.BlockSpec((bn, t, k), lambda i: (i, 0, 0)),  # mesh planes
            pl.BlockSpec((t, w), lambda i: (0, 0)),       # topic bits
            pl.BlockSpec((t, k, bn), tkn),                # nv acc
            pl.BlockSpec((t, k, bn), tkn),                # ni acc
            pl.BlockSpec((t, k, bn), tkn),                # dup acc
        ],
        out_specs=[
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((t, k, bn), tkn),
            pl.BlockSpec((t, k, bn), tkn),
            pl.BlockSpec((t, k, bn), tkn),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((w, nr), U32),
            jax.ShapeDtypeStruct((t, k, nr), jnp.uint8),
            jax.ShapeDtypeStruct((t, k, nr), jnp.uint8),
            jax.ShapeDtypeStruct((t, k, nr), jnp.uint8),
        ],
        input_output_aliases={1: 1, 2: 2, 3: 3, 12: 4, 13: 5, 14: 6},
        interpret=interpret,
    )(frontier, have, dlv, dlv_new, vm, inv_n, window_old, valid_msg,
      nbr, fwd_mask_u8, mesh_u8, topic_bits, nv, ni, dup)
    return HopOut(*outs)


# --- kernel-mesh dispatch (parallel/kernel_context.py) ---
#
# Under a sharded step the SPMD partitioner cannot split a pallas_call, so
# each kernel dispatches through shard_map: the packed lookup table (the
# sender-indexed [W, N] window — the ONLY operand read through global
# neighbor ids) replicates via one small all-gather, every receiver-indexed
# operand stays sharded, and each device runs its own peer rows. Unsharded
# callers fall through to the plain kernels.

_WN = (None, PEER)          # [W, N] receiver-indexed packed words
_ROWS = (PEER, None)        # [N, K]-style receiver-major arrays
_TKN = (None, None, PEER)   # [T, K, N] count accumulators
_REPL2 = (None, None)       # replicated 2-D (tables, topic bits)


def emit_dispatch(window, have, gossip_u8, topic_bits, nbr, m, budget,
                  gather="take", interpret=False):
    """emit_pallas, shard_map-wrapped when a kernel mesh is active."""
    fn = functools.partial(emit_pallas, m=m, budget=budget, gather=gather,
                           interpret=interpret)
    if current_kernel_mesh() is None:
        return fn(window, have, gossip_u8, topic_bits, nbr)
    return shard_kernel(
        fn,
        in_specs=[_REPL2, _WN, (PEER, None, None), _REPL2, _ROWS],
        out_specs=[_ROWS],
    )(window, have, gossip_u8, topic_bits, nbr)


def iwant_resolve_dispatch(pend, answers, have, vm, inv_n, alive,
                           data_ok_u8, topic_bits, nbr, m,
                           gather="take", interpret=False) -> ResolveOut:
    """iwant_resolve_pallas, shard_map-wrapped when a kernel mesh is active."""
    fn = functools.partial(iwant_resolve_pallas, m=m, gather=gather,
                           interpret=interpret)
    if current_kernel_mesh() is None:
        return fn(pend, answers, have, vm, inv_n, alive, data_ok_u8,
                  topic_bits, nbr)
    outs = shard_kernel(
        lambda *a: tuple(fn(*a)),
        in_specs=[_ROWS, _REPL2, _WN, _WN, _WN, _REPL2, _ROWS, _REPL2,
                  _ROWS],
        out_specs=[_WN, _WN, _TKN, _TKN, _WN],
    )(pend, answers, have, vm, inv_n, alive, data_ok_u8, topic_bits, nbr)
    return ResolveOut(*outs)


def hop_dispatch(frontier, have, dlv, dlv_new, vm, inv_n, window_old,
                 valid_msg, nbr, fwd_mask_u8, mesh_u8, topic_bits,
                 nv, ni, dup, gather="take", interpret=False) -> HopOut:
    """hop_pallas, shard_map-wrapped when a kernel mesh is active. The
    frontier is the one sender-indexed table; its replication is the whole
    per-hop cross-device exchange (0.8 MB at the 100k headline shape)."""
    fn = functools.partial(hop_pallas, gather=gather, interpret=interpret)
    if current_kernel_mesh() is None:
        return fn(frontier, have, dlv, dlv_new, vm, inv_n, window_old,
                  valid_msg, nbr, fwd_mask_u8, mesh_u8, topic_bits,
                  nv, ni, dup)
    outs = shard_kernel(
        lambda *a: tuple(fn(*a)),
        in_specs=[_REPL2, _WN, _WN, _WN, _WN, _WN, _WN, _REPL2, _ROWS,
                  (PEER, None, None), (PEER, None, None), _REPL2,
                  _TKN, _TKN, _TKN],
        out_specs=[_WN, _WN, _WN, _WN, _TKN, _TKN, _TKN],
    )(frontier, have, dlv, dlv_new, vm, inv_n, window_old, valid_msg,
      nbr, fwd_mask_u8, mesh_u8, topic_bits, nv, ni, dup)
    return HopOut(*outs)
