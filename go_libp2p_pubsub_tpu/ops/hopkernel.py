"""Fused Pallas forwarding-hop kernel (PERF_MODEL.md S4).

One hop of frontier propagation currently costs ~1.1 GB of HBM traffic at
100k peers under the XLA lowering: the neighbor gather materializes
[W,K,N], the lowest-slot winner attribution runs a 5-pass associative-scan
prefix-OR over K, and the event accumulators are read+written as separate
passes. This kernel fuses the whole hop per receiver block with the packed
frontier table pinned in VMEM:

    gather (in-VMEM table lookups) -> allowed/mesh expansion from bool
    planes -> K-unrolled prefix-OR in registers -> uint8 per-(topic, slot)
    event counts accumulated into aliased outputs

HBM per hop drops to: nbr indices + two bool planes + the uint8 count
accumulators + a handful of [W, N] tables — ~55 MB at the headline shape
(PERF_MODEL.md "planned" hop row).

Eligibility (resolve_hop_mode): TPU backend (CPU auto keeps the XLA path;
interpret mode is for tests), no per-edge/validation budgets, no gater, no
provenance, no flood-publish — those configs keep the XLA formulation.
Bit-identical to the XLA hop: tests/test_hopkernel.py checks op-level
(forward_tick, T=1 and T=3) and full-8-tick-run state equality in
interpret mode, plus the resolution policy.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .bits import U32
from .permgather import _PALLAS_VMEM_PAYLOAD_BYTES, _block_rows


class HopOut(NamedTuple):
    new_valid: jnp.ndarray    # [W, N] next frontier (validated new arrivals)
    have: jnp.ndarray         # [W, N] updated seen set
    dlv: jnp.ndarray          # [W, N] updated delivered set
    dlv_new: jnp.ndarray      # [W, N] deliveries accumulated this tick
    nv: jnp.ndarray           # [T, K, N] uint8 first-delivery counts
    ni: jnp.ndarray           # [T, K, N] uint8 invalid (P4) counts
    dup: jnp.ndarray          # [T, K, N] uint8 mesh-duplicate counts


def resolve_hop_mode(mode: str, cfg, w: int, n: int, k: int) -> str:
    """'pallas' on TPU for cap-free/gater-free/provenance-free gossipsub
    configs with a VMEM-resident frontier table; 'xla' otherwise."""
    if mode not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown hop_mode {mode!r}")
    backend = jax.default_backend()
    if mode == "auto":
        mode = "pallas" if backend == "tpu" else "xla"
    if mode == "pallas":
        if (cfg.gater_enabled or cfg.record_provenance
                or cfg.edge_queue_cap > 0 or cfg.validation_queue_cap > 0
                or (cfg.flood_publish and cfg.router == "gossipsub")):
            return "xla"
        if (w * n * 4 > _PALLAS_VMEM_PAYLOAD_BYTES
                or _block_rows(n, 4 * w * k * 4) is None):
            return "xla"
    return mode


@functools.partial(jax.jit, static_argnames=("interpret",))
def hop_pallas(frontier, have, dlv, dlv_new, vm, inv_n, window_old,
               valid_msg, nbr, fwd_mask_u8, mesh_u8, topic_bits,
               nv, ni, dup, interpret=False) -> HopOut:
    """One fused forwarding hop.

    frontier/have/dlv/dlv_new/vm/inv_n/window_old: [W, N] u32 packed tables
    (receiver-indexed except frontier, which is sender-indexed and pinned
    whole in VMEM). valid_msg: [W, 1] u32. nbr: [N, K] pre-clipped.
    fwd_mask_u8/mesh_u8: [N, T, K] uint8 bool planes. topic_bits: [T, W]
    u32 per-topic live-message sets. nv/ni/dup: [T, K, N] uint8 event-count
    accumulators, updated in place via aliasing.
    """
    from jax.experimental import pallas as pl

    w, n = frontier.shape
    k = nbr.shape[1]
    t = topic_bits.shape[0]
    bn = _block_rows(n, 4 * w * k * 4)
    assert bn is not None, "resolve_hop_mode admitted an infeasible shape"

    def kernel(fro_ref, have_ref, dlv_ref, dlvnew_ref, vm_ref, inv_ref,
               wold_ref, vmsg_ref, nbr_ref, fwd_ref, mesh_ref, tb_ref,
               nv_ref, ni_ref, dup_ref,
               out_newv, out_have, out_dlv, out_dlvnew,
               out_nv, out_ni, out_dup):
        tab = fro_ref[:]                                  # [W, N] in VMEM
        nbrb = nbr_ref[:]                                 # [BN, K]
        g = jnp.take(tab, nbrb.reshape(-1), axis=1)
        g = g.reshape(w, nbrb.shape[0], k)                # [W, BN, K] offered
        tb = tb_ref[:]                                    # [T, W]
        fwd = fwd_ref[:]                                  # [BN, T, K] u8
        msh = mesh_ref[:]
        # allowed[w, bn, k] = OR_t (fwd[bn,t,k] & topic_bits[t,w]);
        # topic message sets are disjoint so OR == sum
        allowed = jnp.zeros_like(g)
        mesh_eb = jnp.zeros_like(g)
        for ti in range(t):
            tw = tb[ti][:, None, None]                    # [W, 1, 1]
            allowed = allowed | jnp.where(
                (fwd[:, ti, :] != 0)[None, :, :], tw, U32(0))
            mesh_eb = mesh_eb | jnp.where(
                (msh[:, ti, :] != 0)[None, :, :], tw, U32(0))
        off = g & allowed                                 # [W, BN, K]

        have_b = have_ref[:]                              # [W, BN]
        vm_b = vm_ref[:]
        inv_b = inv_ref[:]
        nv_acc = nv_ref[:]                                # [T, K, BN] u8
        ni_acc = ni_ref[:]
        # K-unrolled lowest-slot prefix: excl carries OR of lower slots
        excl = jnp.zeros_like(have_b)
        for ki in range(k):
            off_k = off[:, :, ki]
            nf_k = off_k & ~excl & ~have_b                # winner bits
            excl = excl | off_k
            for ti in range(t):
                tw = tb[ti][:, None]
                ev_nv = nf_k & vm_b & tw
                ev_ni = nf_k & inv_b & tw
                cnt_nv = jnp.sum(jax.lax.population_count(ev_nv),
                                 axis=0).astype(jnp.uint8)
                cnt_ni = jnp.sum(jax.lax.population_count(ev_ni),
                                 axis=0).astype(jnp.uint8)
                nv_acc = nv_acc.at[ti, ki, :].add(cnt_nv)
                ni_acc = ni_acc.at[ti, ki, :].add(cnt_ni)

        new_any = excl & ~have_b
        new_valid = new_any & vm_b
        # mesh-duplicate eligibility uses the WHOLE hop's new deliveries
        # (order-independent within the hop, as the XLA formulation)
        elig = (wold_ref[:] | dlvnew_ref[:] | new_valid) & vmsg_ref[:]
        dup_acc = dup_ref[:]
        for ki in range(k):
            dup_k = off[:, :, ki] & mesh_eb[:, :, ki] & elig
            for ti in range(t):
                ev = dup_k & tb[ti][:, None]
                cnt = jnp.sum(jax.lax.population_count(ev),
                              axis=0).astype(jnp.uint8)
                dup_acc = dup_acc.at[ti, ki, :].add(cnt)

        out_newv[:] = new_valid
        out_have[:] = have_b | new_any
        out_dlv[:] = dlv_ref[:] | new_valid
        out_dlvnew[:] = dlvnew_ref[:] | new_valid
        out_nv[:] = nv_acc
        out_ni[:] = ni_acc
        out_dup[:] = dup_acc

    wn = lambda i: (0, i)       # [W, BN] blocks          # noqa: E731
    tkn = lambda i: (0, 0, i)   # [T, K, BN] blocks       # noqa: E731
    grid = n // bn
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((w, n), lambda i: (0, 0)),       # frontier table
            pl.BlockSpec((w, bn), wn),                    # have
            pl.BlockSpec((w, bn), wn),                    # dlv
            pl.BlockSpec((w, bn), wn),                    # dlv_new
            pl.BlockSpec((w, bn), wn),                    # vm
            pl.BlockSpec((w, bn), wn),                    # inv_n
            pl.BlockSpec((w, bn), wn),                    # window_old
            pl.BlockSpec((w, 1), lambda i: (0, 0)),       # valid_msg
            pl.BlockSpec((bn, k), lambda i: (i, 0)),      # nbr
            pl.BlockSpec((bn, t, k), lambda i: (i, 0, 0)),  # fwd planes
            pl.BlockSpec((bn, t, k), lambda i: (i, 0, 0)),  # mesh planes
            pl.BlockSpec((t, w), lambda i: (0, 0)),       # topic bits
            pl.BlockSpec((t, k, bn), tkn),                # nv acc
            pl.BlockSpec((t, k, bn), tkn),                # ni acc
            pl.BlockSpec((t, k, bn), tkn),                # dup acc
        ],
        out_specs=[
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((w, bn), wn),
            pl.BlockSpec((t, k, bn), tkn),
            pl.BlockSpec((t, k, bn), tkn),
            pl.BlockSpec((t, k, bn), tkn),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((w, n), U32),
            jax.ShapeDtypeStruct((w, n), U32),
            jax.ShapeDtypeStruct((w, n), U32),
            jax.ShapeDtypeStruct((w, n), U32),
            jax.ShapeDtypeStruct((t, k, n), jnp.uint8),
            jax.ShapeDtypeStruct((t, k, n), jnp.uint8),
            jax.ShapeDtypeStruct((t, k, n), jnp.uint8),
        ],
        input_output_aliases={1: 1, 2: 2, 3: 3, 12: 4, 13: 5, 14: 6},
        interpret=interpret,
    )(frontier, have, dlv, dlv_new, vm, inv_n, window_old, valid_msg,
      nbr, fwd_mask_u8, mesh_u8, topic_bits, nv, ni, dup)
    return HopOut(*outs)
