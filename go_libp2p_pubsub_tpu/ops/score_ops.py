"""Batched peer scoring: P1-P7 over [N, T, K] counters.

Vectorized twin of routers/score.py (itself mirroring score.go:265-342
``score()`` and score.go:504-565 ``refreshScores``). The observer axis is N,
the observed neighbor lives in slot k; topic axis T carries the [T]-shaped
TopicParams. One fused elementwise pass; XLA fuses the reductions.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState


def compute_scores(state: SimState, cfg: SimConfig, tp: TopicParams,
                   mask_disconnected: bool = True) -> jnp.ndarray:
    """Score of the peer in slot k as seen by observer n -> [N, K] f32.

    Mirrors score.go:265-342; disconnected/empty slots score 0 unless
    ``mask_disconnected=False``, which exposes the retained counters of down
    edges (score.go:611-644 RetainScore — used by the PX reconnect gate).
    """
    if not cfg.scoring_enabled:
        return jnp.zeros(state.behaviour_penalty.shape, jnp.float32)

    # per-(n,t,k) topic components; tp broadcast as [1,T,1]
    def t_(x):
        return x[None, :, None]

    in_mesh = state.mesh
    mesh_time = jnp.where(in_mesh, (state.tick - state.graft_tick).astype(jnp.float32), 0.0)
    # P1: floor(mesh_time/quantum), capped (score.go:285-291)
    p1 = jnp.minimum(jnp.floor(mesh_time / t_(tp.time_in_mesh_quantum_ticks) + 1e-9),
                     t_(tp.time_in_mesh_cap))
    topic_score = jnp.where(in_mesh, p1 * t_(tp.time_in_mesh_weight), 0.0)
    # P2
    topic_score += state.first_message_deliveries * t_(tp.first_message_deliveries_weight)
    # P3: squared deficit once activated (score.go:297-303)
    deficit = t_(tp.mesh_message_deliveries_threshold) - state.mesh_message_deliveries
    p3 = jnp.where(state.mesh_active & (deficit > 0), deficit * deficit, 0.0)
    topic_score += p3 * t_(tp.mesh_message_deliveries_weight)
    # P3b
    topic_score += state.mesh_failure_penalty * t_(tp.mesh_failure_penalty_weight)
    # P4: squared counter
    topic_score += (state.invalid_message_deliveries ** 2) * \
        t_(tp.invalid_message_deliveries_weight)

    score = jnp.sum(topic_score * t_(tp.topic_weight), axis=1)  # [N, K]
    if cfg.topic_score_cap > 0:
        score = jnp.minimum(score, cfg.topic_score_cap)

    nbr = jnp.clip(state.neighbors, 0, None)
    # P5: app-specific (score.go:326-327)
    if cfg.app_specific_weight != 0.0:
        score += cfg.app_specific_weight * state.app_score[nbr]
    # P6: IP colocation surplus^2 (score.go:329-331, 344-385); group census is
    # global — the batched analogue of every observer seeing the same conns
    if cfg.ip_colocation_factor_weight != 0.0:
        counts = jnp.bincount(state.ip_group, length=cfg.n_ip_groups)
        surplus = (counts[state.ip_group] - cfg.ip_colocation_factor_threshold
                   ).astype(jnp.float32)
        p6 = jnp.where(surplus > 0, surplus * surplus, 0.0)
        score += cfg.ip_colocation_factor_weight * p6[nbr]
    # P7: behaviour penalty excess^2 (score.go:334-339)
    if cfg.behaviour_penalty_weight != 0.0:
        excess = state.behaviour_penalty - cfg.behaviour_penalty_threshold
        score += jnp.where(excess > 0, excess * excess, 0.0) * cfg.behaviour_penalty_weight

    if mask_disconnected:
        return jnp.where(state.connected, score, 0.0)
    return jnp.where(state.neighbors >= 0, score, 0.0)


def decay_counters(state: SimState, cfg: SimConfig, tp: TopicParams) -> SimState:
    """refreshScores' decay pass (score.go:504-565), one tick == DecayInterval.

    Also advances the P3 activation latch (mesh_time > activation).
    """
    def t_(x):
        return x[None, :, None]

    def dec(v, factor):
        v = v * factor
        return jnp.where(v < cfg.decay_to_zero, 0.0, v)

    fmd = dec(state.first_message_deliveries, t_(tp.first_message_deliveries_decay))
    mmd = dec(state.mesh_message_deliveries, t_(tp.mesh_message_deliveries_decay))
    mfp = dec(state.mesh_failure_penalty, t_(tp.mesh_failure_penalty_decay))
    imd = dec(state.invalid_message_deliveries, t_(tp.invalid_message_deliveries_decay))
    bp = state.behaviour_penalty * cfg.behaviour_penalty_decay
    bp = jnp.where(bp < cfg.decay_to_zero, 0.0, bp)
    mesh_time = (state.tick - state.graft_tick).astype(jnp.float32)
    active = state.mesh_active | (
        state.mesh & (mesh_time > t_(tp.mesh_message_deliveries_activation_ticks)))
    return state._replace(
        first_message_deliveries=fmd, mesh_message_deliveries=mmd,
        mesh_failure_penalty=mfp, invalid_message_deliveries=imd,
        behaviour_penalty=bp, mesh_active=active)


def apply_prune_penalty(state: SimState, pruned: jnp.ndarray,
                        tp: TopicParams) -> SimState:
    """P3b sticky failure penalty on prune (score.go:672-694): where an edge
    is pruned while the P3 penalty is active and under threshold, add the
    squared deficit; then clear the activation latch for the slot."""
    def t_(x):
        return x[None, :, None]

    deficit = t_(tp.mesh_message_deliveries_threshold) - state.mesh_message_deliveries
    add = jnp.where(pruned & state.mesh_active & (deficit > 0), deficit * deficit, 0.0)
    return state._replace(
        mesh_failure_penalty=state.mesh_failure_penalty + add,
        mesh_active=jnp.where(pruned, False, state.mesh_active),
        graft_tick=jnp.where(pruned, NEVER, state.graft_tick))
