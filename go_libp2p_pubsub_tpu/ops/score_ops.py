"""Batched peer scoring: P1-P7 over [N, T, K] counters.

Vectorized twin of routers/score.py (itself mirroring score.go:265-342
``score()`` and score.go:504-565 ``refreshScores``). The observer axis is N,
the observed neighbor lives in slot k; topic axis T carries the [T]-shaped
TopicParams. One fused elementwise pass; XLA fuses the reductions.

Decay placement (PERF_MODEL.md S5): the engine runs NO standalone decay
pass. The stored counters are "pre-decay" values; every reader applies
``zclamp(counter * decay)`` inline (compute_scores, the prune-penalty
deficit) and every per-tick writer folds the same decay into its write
(forward_tick attribution for fmd/mmd/imd, the heartbeat for
behaviour_penalty and mesh_failure_penalty, advance_active_latch for the
P3 activation). Stored values at tick boundaries are bit-identical to the
old decay-pass ordering — decay-then-add with cap-at-add, exactly
score.go:504-565 + 899-981 — while the dedicated 150 MB/tick pass
disappears. ``decay_counters`` remains as the reference formulation for
ablations and tests.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState


def decayed(v: jnp.ndarray, factor, z: float) -> jnp.ndarray:
    """One refreshScores decay step applied inline at a read/write site:
    multiply by the decay factor, zero below decay_to_zero (score.go:504-565)."""
    v = v * factor
    return jnp.where(v < z, 0.0, v)


def compute_scores(state: SimState, cfg: SimConfig, tp: TopicParams,
                   mask_disconnected: bool = True,
                   apply_decay: bool = False) -> jnp.ndarray:
    """Score of the peer in slot k as seen by observer n -> [N, K] f32.

    Mirrors score.go:265-342; disconnected/empty slots score 0 unless
    ``mask_disconnected=False``, which exposes the retained counters of down
    edges (score.go:611-644 RetainScore — used by the PX reconnect gate).

    The DEFAULT contract scores the stored counter values verbatim — what
    golden tests, trace replay, and any decay_counters composition expect.
    The engine's heartbeat passes ``apply_decay=True``: its counters are
    stored pre-decay (module docstring) and this tick's decay applies
    inline at the read, reproducing the old decay-pass-then-score ordering
    exactly.
    """
    if not cfg.scoring_enabled:
        return jnp.zeros(state.behaviour_penalty.shape, jnp.float32)

    # per-(n,t,k) topic components; tp broadcast as [1,T,1]
    def t_(x):
        return x[None, :, None]

    z = cfg.decay_to_zero
    # identity "decay" when scoring stored values verbatim (unit tests)
    dec = decayed if apply_decay else (lambda v, factor, z: v)
    in_mesh = state.mesh
    mesh_time = jnp.where(in_mesh, (state.tick - state.graft_tick).astype(jnp.float32), 0.0)
    # P1: floor(mesh_time/quantum), capped (score.go:285-291)
    p1 = jnp.minimum(jnp.floor(mesh_time / t_(tp.time_in_mesh_quantum_ticks) + 1e-9),
                     t_(tp.time_in_mesh_cap))
    topic_score = jnp.where(in_mesh, p1 * t_(tp.time_in_mesh_weight), 0.0)
    # P2
    topic_score += dec(state.first_message_deliveries,
                           t_(tp.first_message_deliveries_decay), z) \
        * t_(tp.first_message_deliveries_weight)
    # P3: squared deficit once activated (score.go:297-303)
    deficit = t_(tp.mesh_message_deliveries_threshold) - dec(
        state.mesh_message_deliveries, t_(tp.mesh_message_deliveries_decay), z)
    p3 = jnp.where(state.mesh_active & (deficit > 0), deficit * deficit, 0.0)
    topic_score += p3 * t_(tp.mesh_message_deliveries_weight)
    # P3b
    topic_score += dec(state.mesh_failure_penalty,
                           t_(tp.mesh_failure_penalty_decay), z) \
        * t_(tp.mesh_failure_penalty_weight)
    # P4: squared counter
    topic_score += (dec(state.invalid_message_deliveries,
                            t_(tp.invalid_message_deliveries_decay), z) ** 2) * \
        t_(tp.invalid_message_deliveries_weight)

    score = jnp.sum(topic_score * t_(tp.topic_weight), axis=1)  # [N, K]
    if cfg.topic_score_cap > 0:
        score = jnp.minimum(score, cfg.topic_score_cap)

    nbr = jnp.clip(state.neighbors, 0, None)
    # P5: app-specific (score.go:326-327)
    if cfg.app_specific_weight != 0.0:
        score += cfg.app_specific_weight * state.app_score[nbr]
    # P6: IP colocation surplus^2 (score.go:329-331, 344-385); group census is
    # global — the batched analogue of every observer seeing the same conns
    if cfg.ip_colocation_factor_weight != 0.0:
        counts = jnp.bincount(state.ip_group, length=cfg.n_ip_groups)
        surplus = (counts[state.ip_group] - cfg.ip_colocation_factor_threshold
                   ).astype(jnp.float32)
        p6 = jnp.where(surplus > 0, surplus * surplus, 0.0)
        score += cfg.ip_colocation_factor_weight * p6[nbr]
    # P7: behaviour penalty excess^2 (score.go:334-339)
    if cfg.behaviour_penalty_weight != 0.0:
        bp = dec(state.behaviour_penalty, cfg.behaviour_penalty_decay, z)
        excess = bp - cfg.behaviour_penalty_threshold
        score += jnp.where(excess > 0, excess * excess, 0.0) * cfg.behaviour_penalty_weight

    if mask_disconnected:
        return jnp.where(state.connected, score, 0.0)
    return jnp.where(state.neighbors >= 0, score, 0.0)


def advance_active_latch(state: SimState, tp: TopicParams) -> SimState:
    """Advance the P3 activation latch (score.go:550-556: refreshScores sets
    mesh_message_deliveries_active once mesh_time exceeds the activation
    window). Under the no-decay-pass layout this runs at the top of the
    heartbeat, before compute_scores — the same point in the tick the decay
    pass used to run."""
    def t_(x):
        return x[None, :, None]

    mesh_time = (state.tick - state.graft_tick).astype(jnp.float32)
    active = state.mesh_active | (
        state.mesh & (mesh_time > t_(tp.mesh_message_deliveries_activation_ticks)))
    return state._replace(mesh_active=active)


def decay_counters(state: SimState, cfg: SimConfig, tp: TopicParams) -> SimState:
    """refreshScores' decay pass (score.go:504-565), one tick == DecayInterval.

    Also advances the P3 activation latch (mesh_time > activation).

    NOT called by the engine anymore (module docstring): kept as the
    reference formulation for ablations and equivalence tests against the
    inline-decay layout.
    """
    def t_(x):
        return x[None, :, None]

    def dec(v, factor):
        v = v * factor
        return jnp.where(v < cfg.decay_to_zero, 0.0, v)

    fmd = dec(state.first_message_deliveries, t_(tp.first_message_deliveries_decay))
    mmd = dec(state.mesh_message_deliveries, t_(tp.mesh_message_deliveries_decay))
    mfp = dec(state.mesh_failure_penalty, t_(tp.mesh_failure_penalty_decay))
    imd = dec(state.invalid_message_deliveries, t_(tp.invalid_message_deliveries_decay))
    bp = state.behaviour_penalty * cfg.behaviour_penalty_decay
    bp = jnp.where(bp < cfg.decay_to_zero, 0.0, bp)
    mesh_time = (state.tick - state.graft_tick).astype(jnp.float32)
    active = state.mesh_active | (
        state.mesh & (mesh_time > t_(tp.mesh_message_deliveries_activation_ticks)))
    return state._replace(
        first_message_deliveries=fmd, mesh_message_deliveries=mmd,
        mesh_failure_penalty=mfp, invalid_message_deliveries=imd,
        behaviour_penalty=bp, mesh_active=active)


def apply_prune_penalty(state: SimState, pruned: jnp.ndarray, tp: TopicParams,
                        decay_to_zero: float = 0.0,
                        apply_decay: bool = False) -> SimState:
    """P3b sticky failure penalty on prune (score.go:672-694): where an edge
    is pruned while the P3 penalty is active and under threshold, add the
    squared deficit; then clear the activation latch for the slot.

    The DEFAULT adds to the stored values verbatim (churn's RemovePeer-time
    calls and standalone tests — their counters already carry this tick's
    decay). The heartbeat passes ``apply_decay=True``: its call is
    mesh_failure_penalty's once-per-tick decay site (module docstring), so
    the deficit reads this tick's decayed mmd view and the stored mfp
    becomes zclamp(mfp * decay) + add — the old decay-then-add ordering.
    Decay must fold in EXACTLY ONE call per tick."""
    def t_(x):
        return x[None, :, None]

    if apply_decay:
        mmd = decayed(state.mesh_message_deliveries,
                      t_(tp.mesh_message_deliveries_decay), decay_to_zero)
        mfp = decayed(state.mesh_failure_penalty,
                      t_(tp.mesh_failure_penalty_decay), decay_to_zero)
    else:
        mmd = state.mesh_message_deliveries
        mfp = state.mesh_failure_penalty
    deficit = t_(tp.mesh_message_deliveries_threshold) - mmd
    add = jnp.where(pruned & state.mesh_active & (deficit > 0), deficit * deficit, 0.0)
    return state._replace(
        mesh_failure_penalty=mfp + add,
        mesh_active=jnp.where(pruned, False, state.mesh_active),
        graft_tick=jnp.where(pruned, NEVER, state.graft_tick))
