"""Batched gossipsub heartbeat: mesh maintenance for all N peers at once.

Vectorized re-design of GossipSubRouter.heartbeat (gossipsub.go:1345-1606):
every per-node map walk becomes a masked reduction over the K slot axis, the
shuffles become gumbel selections, and GRAFT/PRUNE exchange resolves in the
same round via edge gathers (the (n,k)->(j,reverse_slot) mapping is a
permutation of directed edge slots, so receiver-side views are gathers, not
scatters).

Round semantics: decisions read the pre-round state (SURVEY.md §7
"Order-sensitivity vs batching" — canonical order with stable tie-breaks),
with ONE deliberate exception: receiver-side GRAFT vetting serializes
acceptance WITHIN the round (lowest-slot-first against the growing mesh,
including the receiver's own round grafts) to mirror the reference's
serial handleGraft Dhi check — see the capacity-budget block in
heartbeat() and ROUND4_NOTES.md "Parity offset".
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .bits import U32, prefix_count
from .permgather import permutation_gather
from .score_ops import (
    advance_active_latch,
    apply_prune_penalty,
    compute_scores,
    decayed,
)
from .selection import masked_median, select_random, select_top


def edge_gather(x: jnp.ndarray, state: SimState, fill=False,
                mode: str = "auto") -> jnp.ndarray:
    """incoming[j, t, s] = x[neighbors[j,s], t, reverse_slot[j,s]].

    The receiver-side view of per-edge state: what the peer in my slot s has
    recorded about me. Invalid slots read ``fill``. Boolean masks with
    fill=False ride the packed permutation gather (one u32 gather for up to
    32 topic planes); other dtypes use the generic advanced-index form.
    """
    if x.dtype == jnp.bool_ and fill is False:
        return edge_gather_packed([x], state, mode)[0]
    n, t, k = x.shape
    j = jnp.clip(state.neighbors, 0, n - 1)[:, None, :]
    rk = jnp.clip(state.reverse_slot, 0, k - 1)[:, None, :]
    tt = jnp.arange(t)[None, :, None]
    y = x[j, tt, rk]
    valid = ((state.neighbors >= 0) & (state.reverse_slot >= 0))[:, None, :]
    return jnp.where(valid, y, fill)


def edge_gather_packed(masks: list, state: SimState,
                       mode: str = "auto",
                       extra_words: list | None = None):
    """Gather several [N, T, K] boolean edge masks through the reverse-edge
    permutation in ceil(B/32) uint32 gathers (B = total bit-planes), instead
    of one [N,T,K] advanced-index gather per mask. The permutation gather is
    the expensive op on TPU; packing divides its index count by T-per-mask
    and amortizes it across masks, while the pack/unpack shifts are cheap
    VPU passes. ``mode`` picks the formulation: ``sort`` (TPU auto) routes
    every 32-plane payload group through ONE variadic sort-permute over
    the edge involution (permgather.edge_sort_key — fastest measured on
    real TPU); ``pallas`` packs all B planes x K slots into a
    [N, ceil(B*K/32)] u32 bit-table pinned in VMEM (PERF_MODEL.md S2 —
    blocked from auto by the Mosaic gather wall); ``mxu`` routes the same
    bit-table through the gather-free two-level MXU take
    (permgather._edge_table_mxu — the one formulation with no gather op at
    all, so the Mosaic wall cannot block it); the others build
    per-32-plane [N, K] u32 payloads routed through
    ops/permgather.permutation_gather.

    ``extra_words``: optional [W_i, N] u32 word-tables to route through the
    SAME involution as extra lanes of the SAME variadic sort (returned as
    [W_i, K, N] receiver views, out[w, k, n] = table[w, neighbors[n, k]]).
    Every serially-dependent sort is ~7% of the sort-era tick (VERDICT r4
    item 1), so data-independent exchanges must share one comparator pass —
    forward_tick's IWANT answer-table gather rides the heartbeat's final
    exchange this way. Legal when the resolved mode is ``sort`` (extra
    lanes of the variadic sort) or ``mxu`` (extra word rows concatenated
    onto the bit-table, fetched by the same two-level take — the MXU
    formulation of the ride-along); callers gate on
    resolve_edge_packed_mode. Invalid slots carry garbage the consumers
    mask, exactly like gather_words' sort path."""
    from ..parallel.kernel_context import current_kernel_mesh
    from .permgather import (
        _edge_table_mxu,
        _edge_table_pallas,
        edge_sort_key,
        resolve_edge_packed_mode,
    )

    n, t, k = masks[0].shape
    planes = jnp.concatenate(masks, axis=1)                    # [N, B, K]
    b = planes.shape[1]
    jn = jnp.clip(state.neighbors, 0, n - 1)
    rk = jnp.clip(state.reverse_slot, 0, k - 1)
    valid = ((state.neighbors >= 0) & (state.reverse_slot >= 0))[:, None, :]
    has_extras = extra_words is not None      # [] still returns the 2-tuple
    extra_words = extra_words or []
    extra_w = sum(tab.shape[0] for tab in extra_words)
    mode = resolve_edge_packed_mode(mode, n, k, b, extra_w=extra_w)
    if extra_words and mode not in ("sort", "mxu"):
        raise ValueError(
            f"extra_words requires the sort or mxu formulation (resolved "
            f"{mode!r}); callers gate on resolve_edge_packed_mode")
    sk = edge_sort_key(state.neighbors, state.reverse_slot, k_major=False) \
        if mode == "sort" else None
    # broadcast each extra word-table row along the slot axis: source slot
    # (j, r) carries table[w, j], landing at its involution partner (n, k)
    # with neighbors[n, k] == j — the receiver view, [N, K] per row
    extra_lanes = [jnp.broadcast_to(tab[i][:, None], (n, k))
                   for tab in extra_words for i in range(tab.shape[0])]
    extras_views = []                          # [W_i, K, N] per extra table
    if mode == "mxu":
        from .bits import pack_bool
        table = pack_bool(planes.reshape(n, b * k))        # [N, ceil(BK/32)]
        # the extras ride the SAME two-level take as concatenated word
        # rows (permgather._edge_table_mxu) — the mxu analogue of the
        # shared variadic sort below
        groups, extras_views = _edge_table_mxu(
            table, jn, rk, b, extra_words=tuple(extra_words),
            interpret=jax.default_backend() != "tpu")
    elif mode == "pallas":
        from functools import partial

        from ..parallel.kernel_context import (
            PEER, current_kernel_mesh, shard_kernel)
        from .bits import pack_bool
        table = pack_bool(planes.reshape(n, b * k))        # [N, ceil(BK/32)]
        fn = partial(_edge_table_pallas, b_planes=b,
                     interpret=jax.default_backend() != "tpu")
        if current_kernel_mesh() is not None:
            n_groups = (b + 31) // 32
            groups = shard_kernel(
                lambda tab, j, r: tuple(fn(tab, j, r)),
                in_specs=[(None, None), (PEER, None), (PEER, None)],
                out_specs=[(PEER, None)] * n_groups)(table, jn, rk)
        else:
            groups = fn(table, jn, rk)
    else:
        payloads = []
        for w0 in range(0, b, 32):
            bits = planes[:, w0:w0 + 32, :]
            nb = bits.shape[1]
            sh = (U32(1) << jnp.arange(nb, dtype=U32))[None, :, None]
            payloads.append(jnp.sum(bits.astype(U32) * sh, axis=1, dtype=U32))
        ctx = current_kernel_mesh() if mode == "sort" else None
        if mode == "sort" and ctx is not None and ctx.route == "halo":
            # sharded: every group (and extra lane) rides one per-shard
            # halo route
            from ..parallel.halo import route_payloads_halo
            routed = route_payloads_halo(payloads + extra_lanes,
                                         state.neighbors,
                                         state.reverse_slot)
            groups, extra_out = routed[:len(payloads)], routed[len(payloads):]
        elif mode == "sort":
            # ONE variadic sort routes every 32-plane group AND every
            # extra word lane: the keys are identical, so sorting once
            # moves all payloads for a single O(NK log NK) comparator pass
            outs = jax.lax.sort(
                (sk, *[p.reshape(-1) for p in payloads + extra_lanes]),
                num_keys=1)
            flat_outs = [o.reshape(n, k) for o in outs[1:]]
            groups = flat_outs[:len(payloads)]
            extra_out = flat_outs[len(payloads):]
        else:
            groups = [permutation_gather(p, jn, rk, mode) for p in payloads]
    parts = []
    for w0, g in zip(range(0, b, 32), groups):
        nb = min(32, b - w0)
        parts.append(((g[:, None, :] >> jnp.arange(nb, dtype=U32)[None, :, None])
                      & U32(1)).astype(bool))
    flat = jnp.concatenate(parts, axis=1) & valid
    results = [flat[:, i * t:(i + 1) * t, :] for i in range(len(masks))]
    if not has_extras:
        return results
    # invalid slots carry sort garbage on the extra lanes exactly like the
    # mask groups did before their '& valid' above — zero them with a
    # word-AND so no consumer can ever read a down edge's garbage words
    # (ADVICE r5: the old contract leaned on churn clearing iwant_pending
    # for downed edges, an implicit cross-module invariant)
    if mode == "sort":
        ofs = 0
        for tab in extra_words:
            wt = tab.shape[0]
            extras_views.append(jnp.stack(
                [extra_out[ofs + i].T for i in range(wt)]))
            ofs += wt                                 # [W_i, K, N] each
    vmask = jnp.where(valid[:, 0, :].T, U32(0xFFFFFFFF), U32(0))   # [K, N]
    extras = [view & vmask[None] for view in extras_views]
    return results, extras


class HeartbeatOut(NamedTuple):
    state: SimState
    scores: jnp.ndarray      # [N, K] pre-maintenance scores (score cache,
                             # gossipsub.go:1375-1381); disconnected slots 0
    scores_all: jnp.ndarray  # [N, K] same cache WITHOUT the connected mask —
                             # retained scores of down edges (RetainScore),
                             # consumed by the PX reconnect gate (ops/churn.py)
    inc_gossip: jnp.ndarray  # [N, T, K] receiver view of emitGossip edges:
                             # slot s's peer gossips topic t to me (already
                             # gathered through the edge permutation)
    fwd_send: jnp.ndarray    # [N, T, K] receiver view of the eager-forward
                             # edges (sender's mesh | non-subscribed fanout),
                             # consumed by forward_tick's gossipsub path
    extra_routed: tuple = () # receiver views ([W_i, K, N]) of the caller's
                             # extra_words tables, routed on the final
                             # exchange's variadic sort (engine.step merges
                             # forward_tick's IWANT answer gather here — one
                             # fewer serially-dependent sort per tick).
                             # Invalid slots are word-ANDed to 0 by
                             # edge_gather_packed, so consumers read zeros —
                             # never routing garbage — on down edges


def heartbeat(state: SimState, cfg: SimConfig, tp: TopicParams,
              key: jax.Array,
              extra_words: list | None = None) -> HeartbeatOut:
    n, t, k = state.mesh.shape
    tick = state.tick
    ks = jax.random.split(key, 8)

    # P3 activation latch advances where the decay pass used to run —
    # before scores are computed (PERF_MODEL.md S5 inline-decay layout)
    state = advance_active_latch(state, tp)
    # apply_decay: engine counters are stored pre-decay; this read applies
    # the tick's decay inline (score_ops docstring, PERF_MODEL.md S5)
    scores_all = compute_scores(state, cfg, tp, mask_disconnected=False,
                                apply_decay=True)
    scores = jnp.where(state.connected, scores_all, 0.0)         # [N, K]
    s = scores[:, None, :]                           # broadcast over T
    sb = jnp.broadcast_to(s, (n, t, k))
    joined = state.subscribed[:, :, None]
    conn = state.connected[:, None, :]
    out3 = state.outbound[:, None, :]
    direct3 = state.direct[:, None, :]
    nbr_sub = state.nbr_subscribed & conn          # cached receiver view
    backoff_ok = tick >= state.backoff
    backoff_active = ~backoff_ok

    mesh = state.mesh & joined
    # graft candidates (gossipsub.go:1413-1427): connected topic peers outside
    # the mesh with non-negative score, no backoff, not direct
    candidate = conn & nbr_sub & ~mesh & backoff_ok & (s >= 0) & ~direct3 & joined

    # 1. prune all negative-score mesh members (gossipsub.go:1404-1410)
    prune_neg = mesh & (s < 0)
    mesh1 = mesh & ~prune_neg
    candidate = candidate & ~prune_neg

    # The regime blocks below are lax.cond-gated on "any row needs this":
    # after mesh convergence most ticks have no under/over-subscribed rows
    # and the opportunistic pass fires 1/60 ticks, so gating skips their
    # selection kernels at runtime. Results are bit-identical to the
    # ungated form — a skipped block equals selecting with count 0, and the
    # RNG keys are pre-split so skipping consumes no randomness.

    # 2. undersubscribed: graft random candidates up to D (gossipsub.go:1413-1427).
    # The gate requires need AND at least one candidate: sparse corners sit
    # permanently under Dlo with nothing to graft, and would otherwise keep
    # the selection kernel live every tick (a no-op row selects nothing
    # either way, so the gate never changes results).
    n_mesh = jnp.sum(mesh1, axis=-1)
    need = jnp.where(n_mesh < cfg.dlo, cfg.d - n_mesh, 0)
    graft1 = jax.lax.cond(
        jnp.any((need > 0) & jnp.any(candidate, -1)),
        lambda: select_random(candidate, need, ks[0],
                              max_count=cfg.d, mode=cfg.selection_mode),
        lambda: jnp.zeros_like(candidate))
    mesh2 = mesh1 | graft1

    # 3. oversubscribed: keep top-Dscore by score + random rest to D, then
    # bubble up to Dout outbound among the kept (gossipsub.go:1430-1490)
    n2 = jnp.sum(mesh2, axis=-1)
    over = (n2 > cfg.dhi)[..., None]

    def _over_block():
        protected = select_top(sb, mesh2, jnp.full((n, t), cfg.dscore),
                               max_count=cfg.dscore, mode=cfg.selection_mode)
        rest = mesh2 & ~protected
        keep_rand = select_random(rest, jnp.full((n, t), cfg.d - cfg.dscore),
                                  ks[1], max_count=cfg.d - cfg.dscore,
                                  mode=cfg.selection_mode)
        kept = protected | keep_rand
        n_out_kept = jnp.sum(kept & out3, axis=-1)
        deficit_out = jnp.clip(cfg.dout - n_out_kept, 0)
        add_out = select_random(mesh2 & ~kept & out3, deficit_out, ks[2],
                                max_count=cfg.dout, mode=cfg.selection_mode)
        remove_nonout = select_random(keep_rand & ~out3,
                                      jnp.sum(add_out, axis=-1), ks[3],
                                      max_count=cfg.dout,
                                      mode=cfg.selection_mode)
        return (kept | add_out) & ~remove_nonout

    kept = jax.lax.cond(jnp.any(over), _over_block, lambda: mesh2)
    mesh3 = jnp.where(over, kept, mesh2)
    prune_over = mesh2 & ~mesh3

    # 4. outbound quota top-up in the [Dlo, Dhi] regime (gossipsub.go:1493-1518)
    n3 = jnp.sum(mesh3, axis=-1)
    n_out = jnp.sum(mesh3 & out3, axis=-1)
    need_out = jnp.where((n3 >= cfg.dlo) & ~over[..., 0] & (n_out < cfg.dout),
                         cfg.dout - n_out, 0)
    out_cand = candidate & out3 & ~mesh3
    graft_out = jax.lax.cond(
        jnp.any((need_out > 0) & jnp.any(out_cand, -1)),
        lambda: select_random(out_cand, need_out, ks[4],
                              max_count=cfg.dout, mode=cfg.selection_mode),
        lambda: jnp.zeros_like(mesh3))
    mesh4 = mesh3 | graft_out

    # 5. opportunistic grafting every OpportunisticGraftTicks when the median
    # mesh score sags below the threshold (gossipsub.go:1521-1552)
    og_tick = (tick % cfg.opportunistic_graft_ticks) == 0

    def _og_block():
        med = masked_median(sb, mesh4)                # [N, T]
        og_cond = (jnp.sum(mesh4, -1) > 1) & \
            (med < cfg.opportunistic_graft_threshold)
        og_need = jnp.where(og_cond, cfg.opportunistic_graft_peers, 0)
        return select_random(candidate & (sb > med[..., None]) & ~mesh4,
                             og_need, ks[5],
                             max_count=cfg.opportunistic_graft_peers,
                             mode=cfg.selection_mode)

    og_sel = jax.lax.cond(og_tick, _og_block, lambda: jnp.zeros_like(mesh4))
    mesh5 = mesh4 | og_sel

    grafts = graft1 | graft_out | og_sel
    prunes = prune_neg | prune_over

    # --- cross-peer exchange, all against pre-round state ---
    inc_graft, inc_prune = edge_gather_packed([grafts, prunes], state,
                                             cfg.edge_gather_mode)

    # receiver-side GRAFT vetting (gossipsub.go:741-837). A GRAFT from a
    # peer already in my (post-own-grafts) mesh is a no-op accept
    # (gossipsub.go:758-767) — without this, a capacity refusal of one
    # side of a MUTUAL same-round graft would leave a half-edge and break
    # mesh symmetry. Hard refusals for not-joined, backoff, negative
    # sender score, or direct peers...
    already = inc_graft & mesh5
    hard_refuse = inc_graft & ~already & \
        (~joined | backoff_active | (s < 0) | direct3)
    cand_graft = inc_graft & ~already & ~hard_refuse
    # ...and a CAPACITY-BUDGETED Dhi check: the serial reference vets each
    # GRAFT against its mesh as it GROWS within the heartbeat
    # (gossipsub.go:804-812), so a receiver never overshoots Dhi from a
    # burst of same-round grafts. A pre-round-mesh check accepted them all,
    # overshot, and the next tick's over-subscription pass slashed to D
    # with 60-tick backoffs — depressing the equilibrium degree a full
    # point below the functional runtime (ROUND4_NOTES.md "Parity
    # offset"). Non-outbound grafts are accepted lowest-slot-first up to
    # the headroom left by the receiver's own round grafts; outbound
    # grafts bypass the check, as in the reference.
    n_mine = jnp.sum(mesh5, axis=-1, keepdims=True)
    acc_out = cand_graft & out3                  # outbound: always accepted
    nonout = cand_graft & ~out3
    # serial arrival in slot order: a non-outbound graft is accepted iff
    # the mesh at its arrival (own grafts + everything accepted in lower
    # slots, outbound included — accepted outbound grafts grow the mesh
    # and consume Dhi headroom for later arrivals) is still below Dhi
    c_out_excl = prefix_count(acc_out, exclusive=True)
    rank = prefix_count(nonout)                             # 1-based
    accept = already | acc_out | \
        (nonout & (n_mine + c_out_excl + rank <= cfg.dhi))
    refuse = inc_graft & ~accept
    # graft-during-backoff behaviour penalty (gossipsub.go:781-795): one
    # point always, a second point when the GRAFT lands within the flood
    # window right after the PRUNE that set the backoff (the reference
    # checks elapsed < GraftFloodThreshold of the prune time; the backoff
    # expiry tick minus its span recovers that prune tick)
    prune_tick = state.backoff - cfg.prune_backoff_ticks
    flood = backoff_active & (tick < prune_tick + cfg.graft_flood_ticks)
    bp_add = jnp.sum(inc_graft & backoff_active, axis=1).astype(jnp.float32) \
        + jnp.sum(inc_graft & flood, axis=1).astype(jnp.float32)
    # behaviour_penalty's per-tick decay folds into this write site
    # (forward_tick's broken-promise points add to the already-decayed
    # value afterward, as the old decay-at-tick-start ordering did)
    behaviour_penalty = decayed(state.behaviour_penalty,
                                cfg.behaviour_penalty_decay,
                                cfg.decay_to_zero) + bp_add

    refused_back, = edge_gather_packed([refuse], state,
                                       cfg.edge_gather_mode)

    new_mesh = ((mesh5 | accept) & ~inc_prune & ~refused_back) & joined
    # the REFUSING receiver also backs the edge off (handleGraft calls
    # addBackoff before queueing the refusal PRUNE, gossipsub.go:795-818 —
    # for every refusal reason except an unjoined topic), so it cannot
    # re-graft the refused peer next tick and charge it graft-during-
    # backoff penalties for a sequence the reference makes impossible
    pruned_any = prunes | inc_prune | refused_back | (refuse & joined)
    new_backoff = jnp.where(pruned_any,
                            tick + cfg.prune_backoff_ticks, state.backoff)

    # score hooks: Graft (score.go:649-667) on newly added edges, Prune
    # (score.go:669-694) on removed ones
    newly = new_mesh & ~state.mesh
    removed = state.mesh & ~new_mesh

    # fanout maintenance (gossipsub.go:1560-1596): expire topics past
    # FanoutTTL since last publish; drop disconnected/low-score members; top
    # up to D from topic peers with score >= publish threshold. Fanout only
    # exists for non-joined topics (Join promotes it, gossipsub.go:1047-1102).
    fanout_alive = (state.fanout_lastpub < NEVER) & \
        (tick <= state.fanout_lastpub + cfg.fanout_ttl_ticks) & ~state.subscribed
    fa3 = fanout_alive[..., None]

    def _fanout_block():
        keep_f = state.fanout & conn & nbr_sub & \
            (s >= cfg.publish_threshold) & fa3
        need_f = jnp.where(fanout_alive,
                           jnp.maximum(cfg.d - jnp.sum(keep_f, -1), 0), 0)
        add_f = select_random(
            conn & nbr_sub & ~keep_f & ~direct3
            & (s >= cfg.publish_threshold) & fa3,
            need_f, ks[7], max_count=cfg.d, mode=cfg.selection_mode)
        return keep_f | add_f

    new_fanout = jax.lax.cond(jnp.any(fanout_alive), _fanout_block,
                              lambda: jnp.zeros_like(state.fanout))
    fanout_lastpub = jnp.where(fanout_alive, state.fanout_lastpub, NEVER)

    st = state._replace(mesh=new_mesh, backoff=new_backoff,
                        behaviour_penalty=behaviour_penalty,
                        fanout=new_fanout, fanout_lastpub=fanout_lastpub)
    # the heartbeat call is mfp's once-per-tick decay site; churn's later
    # RemovePeer-time calls add verbatim (apply_decay stays False there)
    st = apply_prune_penalty(st, removed, tp,
                             decay_to_zero=cfg.decay_to_zero,
                             apply_decay=True)
    st = st._replace(
        graft_tick=jnp.where(newly, tick, st.graft_tick),
        mesh_active=jnp.where(newly, False, st.mesh_active))

    # emitGossip peer selection (gossipsub.go:1711-1775): non-mesh/non-fanout
    # topic peers with score >= gossip threshold, for joined AND active-fanout
    # topics (the heartbeat gossips both loops, gossipsub.go:1556, 1596);
    # target max(Dlazy, factor * candidates)
    gossip_cand = conn & nbr_sub & ~new_mesh & ~new_fanout & ~direct3 & \
        (s >= cfg.gossip_threshold) & (joined | fa3)
    n_cand = jnp.sum(gossip_cand, axis=-1)
    # the product is PINNED to f32 (explicit casts) so the traced dtype
    # cannot drift to f64 under jax_enable_x64 — the static bound below is
    # derived in the same f32 arithmetic and floor(f64) could otherwise
    # exceed it by one, silently under-selecting gossip peers
    target = jnp.maximum(cfg.dlazy, jnp.floor(
        jnp.float32(cfg.gossip_factor) * n_cand.astype(jnp.float32)
    ).astype(jnp.int32))
    # static bound: target = max(Dlazy, floor(factor * n_cand)), n_cand <= K.
    # Derived in the SAME f32 arithmetic as the traced target so the bound
    # can never round below it (f64 int(factor*k) can be one less than
    # f32 floor(f32(factor)*k) when factor sits just under a binary tick)
    gossip_bound = max(cfg.dlazy, int(np.floor(
        np.float32(cfg.gossip_factor) * np.float32(k))))
    gossip_sel = select_random(gossip_cand, target, ks[6],
                               max_count=gossip_bound,
                               mode=cfg.selection_mode)

    # one shared permutation gather hands forward_tick its receiver views:
    # who gossips to me, and whose eager forwarding reaches me
    # (gossipsub.go:1020-1035 mesh forward, :1007 fanout publish)
    send = new_mesh | (new_fanout & ~state.subscribed[:, :, None])
    (inc_gossip, fwd_send), extras = edge_gather_packed(
        [gossip_sel, send], st, cfg.edge_gather_mode,
        extra_words=extra_words if extra_words is not None else [])

    return HeartbeatOut(state=st, scores=scores, scores_all=scores_all,
                        inc_gossip=inc_gossip, fwd_send=fwd_send,
                        extra_routed=tuple(extras))
