"""The reverse-edge permutation gather — the engine's hottest index op.

``out[n, k] = payload[jn[n, k], rk[n, k]]`` routes per-edge state through
the (sender, slot) -> (receiver, reverse_slot) permutation of directed edge
slots (ops/heartbeat.py edge_gather_packed, ops/churn.py symmetric
exchanges, ops/propagate.py sender-score views). Round-2 TPU profiling
showed XLA lowers the advanced-index form to serialized scalar HBM loads
(~1GB/s effective on 480k indices) — the dominant cost of the heartbeat.

Three formulations, selectable per SimConfig (``edge_gather_mode``) so the
TPU recheck can measure them head-to-head (scripts/microbench_kernels.py):

- ``scalar``: the direct advanced-index gather. Fastest on CPU backends
  (single-threaded pointer chase beats extra passes).
- ``rows``: gather whole neighbor ROWS (``payload[jn]`` -> [N, K, K]) — the
  vector-DMA path XLA does tile — then pick the reverse slot per edge with
  ``take_along_axis`` along the minor axis. Trades an [N, K, K] HBM
  temporary for vectorized loads; the same trade that made the hop gather
  2.5x+ faster on the chip (ops/bits.py gather_words_rows).
- ``pallas``: a Pallas kernel that pins the whole payload in VMEM and
  performs the row-take + lane-pick per receiver block ON-CHIP, so the
  permutation never round-trips HBM at all. Only eligible while the payload
  fits VMEM (N*K*4B <= ~8MB, i.e. <= ~60k peers at K=32); falls back to
  ``rows`` above that.
- ``mxu``: the gather-free two-level MXU take (ops/mxutake.py) — one-hot
  bf16 matmul block select + lane select, no gather op of any width, so
  it sidesteps the Mosaic 128-lane wall that blocks every ``pallas``
  table kernel on current chips. Word-table call sites (gather_words, the
  packed edge exchange via its bit-table, which also carries the IWANT
  answer ride-along as extra concatenated word rows) route through the
  two-level take, and the generic [N, K] payload permute rides the
  blocked/tiled variant (mxutake.take_payload_onehot) for 4-byte dtypes —
  ``edge_gather_mode="mxu"`` lowers with zero serialized scalar HBM
  gathers.

``auto`` ranks every formulation through the measured cost-model dispatch
(ops/dispatch.py; the shipped conservative table reproduces the
measured-safe legacy picks — scalar on CPU, sort on TPU — until a
calibrated GRAFT_DISPATCH_TABLE promotes a winner).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..parallel.kernel_context import (
    PEER,
    current_kernel_mesh,
    local_rows,
    shard_kernel,
)

# VMEM budgets (v5e ~16MB/core): the kernel holds the whole [N,K] payload
# plus one [BN,K,K] row-take scratch per block; both must fit with headroom
# for the index/output blocks
_PALLAS_VMEM_PAYLOAD_BYTES = 8 * 1024 * 1024
_PALLAS_VMEM_SCRATCH_BYTES = 4 * 1024 * 1024


def _mosaic_take(tab, idx):
    """``out[r, l] = tab[r, idx[l]]`` — the one gather Mosaic lowers.

    Pallas-TPU supports exactly one gather form: a same-shape 2-D
    ``take_along_axis`` (lowered to ``tpu.dynamic_gather``); arbitrary-length
    ``jnp.take`` raises "Shape mismatch in input, indices and output"
    (discovered on the first live tunnel window — interpret mode accepts
    anything). So the flat index vector [L] is processed in full-table-width
    chunks: pad the (last) chunk to width C, broadcast across rows, take,
    concatenate, slice back to L."""
    r, c = tab.shape
    length = idx.shape[0]
    outs = []
    for s in range(0, length, c):
        part = jax.lax.slice_in_dim(idx, s, min(s + c, length))
        if part.shape[0] < c:
            part = jnp.concatenate(
                [part, jnp.zeros((c - part.shape[0],), part.dtype)])
        outs.append(jnp.take_along_axis(
            tab, jnp.broadcast_to(part[None, :], (r, c)), axis=1))
    g = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    return g[:, :length] if g.shape[1] != length else g


def edge_sort_key(neighbors: jnp.ndarray, reverse_slot: jnp.ndarray,
                  k_major: bool) -> jnp.ndarray:
    """Destination key per SOURCE edge slot for the sort-permute gather.

    The (sender, slot) -> (receiver, reverse_slot) map is an involution of
    the N*K directed edge slots (reverse of reverse = self), so routing
    per-slot values to their receivers is applying a PERMUTATION — and on
    this chip `lax.sort` moves payload bytes ~4x faster than any gather
    formulation (live-window measurement: 9.0 ms vs 24.7 ms for the hop
    words-gather at 100k; XLA gathers pay ~7 ns per index regardless of
    form). Sorting by the destination slot index IS the permutation apply.

    Invalid slots (no neighbor) keep their own index — identity-mapped, so
    the keys stay a bijection (valid slots map valid<->valid under the
    involution; the two sets are disjoint) and the sort never sees
    duplicate keys, which would misalign everything after them. Values
    landing at invalid destinations are garbage the callers already mask.

    ``k_major``: True -> destination flat order k*N+n (for [W, K, N]
    packed-word outputs); False -> n*K+k (for [N, K] payload outputs).
    """
    n, k = neighbors.shape
    valid = (neighbors >= 0) & (reverse_slot >= 0)
    jn = jnp.clip(neighbors, 0, n - 1)
    rk = jnp.clip(reverse_slot, 0, k - 1)
    if k_major:
        dest = rk * n + jn
        own = jnp.arange(k)[None, :] * n + jnp.arange(n)[:, None]
    else:
        dest = jn * k + rk
        own = jnp.arange(n)[:, None] * k + jnp.arange(k)[None, :]
    return jnp.where(valid, dest, own).reshape(-1)


def _gather_sort(payload, sort_key):
    """out_flat[dest] = payload_flat[src] via one variadic sort: n-major
    destination keys -> [N, K] output."""
    n, k = payload.shape
    _, out = jax.lax.sort((sort_key, payload.reshape(-1)), num_keys=1)
    return out.reshape(n, k)


def _gather_scalar(payload, jn, rk):
    return payload[jn, rk]


def _gather_rows(payload, jn, rk):
    rows = payload[jn]                                     # [N, K, K] rows
    return jnp.take_along_axis(rows, rk[:, :, None], axis=-1)[..., 0]


def _block_rows(n: int, row_bytes: int) -> int | None:
    """Receiver-block size for the Pallas kernels: the largest 128-multiple
    divisor of n whose per-block scratch (``row_bytes`` per receiver row)
    fits the VMEM budget, else the whole array as one block. None when
    neither exists (caller falls back to the XLA formulation).

    The 128-multiple constraint is Mosaic's, learned on the real chip: a
    block's minor dimension must be lane-aligned (divisible by 128) or
    cover the full array dimension — and the peer axis is the minor axis of
    every packed table and accumulator these kernels block. Shapes whose
    peer count has no 128-multiple divisor (e.g. exactly 100000) only get
    the single-block form; the benchmark scenarios size their networks
    128-friendly (102400, 51200, 10240, 1024) for this reason."""
    bn_max = _PALLAS_VMEM_SCRATCH_BYTES // max(1, row_bytes)
    for bn in (1024, 512, 256, 128):
        if bn <= bn_max and n % bn == 0:
            return bn
    if n <= bn_max:
        return n                      # single block, scratch still fits
    return None


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_pallas(payload, jn, rk, interpret=False):
    """``payload`` is the full [N, K] table (global under sharding); ``jn``/
    ``rk`` may cover a subset of receiver rows (the local shard). The
    payload flattens to a [1, N*K] VMEM row and the (row, slot) pair to a
    linear index, so the in-kernel lookup is the one gather Mosaic supports
    (_mosaic_take)."""
    from jax.experimental import pallas as pl

    n, k = payload.shape
    nr = jn.shape[0]                                       # local rows
    bn = _block_rows(nr, 2 * k * payload.dtype.itemsize)
    assert bn is not None, "resolve_mode admitted an infeasible shape"
    flat = payload.reshape(1, n * k)
    jn_t, rk_t = jn.T, rk.T                                # [K, N] k-major

    def kernel(pay_ref, jnt_ref, rkt_ref, out_ref):
        li = (jnt_ref[:] * k + rkt_ref[:]).reshape(-1)     # [K*BN] linear
        g = _mosaic_take(pay_ref[:], li)                   # [1, K*BN]
        out_ref[:] = g.reshape(k, bn).T                    # [BN, K] block

    return pl.pallas_call(
        kernel,
        grid=(nr // bn,),
        in_specs=[
            pl.BlockSpec((1, n * k), lambda i: (0, 0)),    # full payload
            pl.BlockSpec((k, bn), lambda i: (0, i)),
            pl.BlockSpec((k, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((bn, k), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nr, k), payload.dtype),
        interpret=interpret,
    )(flat, jn_t, rk_t)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gather_words_pallas(x_w, nbr, interpret=False):
    """out[w, k, n] = x_w[w, nbr[n, k]] with the whole packed message table
    pinned in VMEM (at 100k peers and W=2 the table is only 0.8MB, vs the
    ~200MB [N, K, M] bool temporary of the unpack/row-gather/repack path)."""
    from jax.experimental import pallas as pl

    w, n = x_w.shape
    nr, k = nbr.shape                                      # local rows
    # x2: the [W,K,BN] output block matches the gather temporary in size
    # (unlike the edge kernel whose output is K-times smaller than scratch)
    bn = _block_rows(nr, 2 * w * k * x_w.dtype.itemsize)
    assert bn is not None, "resolve_words_mode admitted an infeasible shape"
    nbr_t = nbr.T                                          # [K, N] k-major

    def kernel(pay_ref, nbrt_ref, out_ref):
        pay = pay_ref[:]                                   # [W, N] in VMEM
        idx = nbrt_ref[:].reshape(-1)                      # [K*BN] k-major
        g = _mosaic_take(pay, idx)                         # [W, K*BN]
        out_ref[:] = g.reshape(w, k, bn)

    return pl.pallas_call(
        kernel,
        grid=(nr // bn,),
        in_specs=[
            pl.BlockSpec((w, n), lambda i: (0, 0)),        # full table
            pl.BlockSpec((k, bn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((w, k, bn), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((w, k, nr), x_w.dtype),
        interpret=interpret,
    )(x_w, nbr_t)


@functools.partial(jax.jit, static_argnames=("b_planes", "interpret"))
def _edge_table_pallas(table, jn, rk, b_planes, interpret=False):
    """Bit-table edge exchange: the B sender-side bool planes over K slots
    pack into one [N, ceil(B*K/32)] u32 table (b-major, slot-minor bit
    order); bit (b % 32) of output group b//32 at [n, k] is table bit
    (b*K + rk[n,k]) of row jn[n,k].

    The table is 16x smaller than the [N, K] u32 payload the per-group
    formulation gathers (B bits vs 32 per slot at T=1), so it pins in VMEM
    at 100k+ peers where the payload kernel had to fall back to the
    [N,K,K]-temporary `rows` form (PERF_MODEL.md S2). Returns one [N, K]
    u32 payload per 32-plane group, bit-compatible with the per-group path.
    """
    from jax.experimental import pallas as pl

    n, wb = table.shape
    nr, k = jn.shape                                       # local rows
    n_groups = (b_planes + 31) // 32
    # scratch per receiver row: [WB, K] gathered row words + work vectors
    bn = _block_rows(nr, 2 * k * wb * 4)
    assert bn is not None, "resolve admitted an infeasible shape"
    u32 = jnp.uint32
    tab_t = table.T                                        # [WB, N]
    jn_t, rk_t = jn.T, rk.T                                # [K, N] k-major

    def kernel(tabt_ref, jnt_ref, rkt_ref, *out_refs):
        tab = tabt_ref[:]                                  # [WB, N] in VMEM
        idx = jnt_ref[:].reshape(-1)                       # [K*BN] k-major
        rows = _mosaic_take(tab, idx)                      # [WB, K*BN]
        pos0 = rkt_ref[:].reshape(-1)[None, :]             # [1, K*BN]
        accs = [jnp.zeros_like(pos0, dtype=u32) for _ in range(n_groups)]
        for b in range(b_planes):
            pos = pos0 + b * k                             # bit positions
            wsel = pos // 32
            word = jnp.zeros_like(accs[0])
            for wi in range(wb):                           # wb is tiny and
                word = jnp.where(wsel == wi,               # static: select
                                 rows[wi:wi + 1], word)    # replaces gather
            bit = (word >> (pos % 32).astype(u32)) & u32(1)
            accs[b // 32] = accs[b // 32] | (bit << u32(b % 32))
        for ref, acc in zip(out_refs, accs):
            ref[:] = acc.reshape(k, bn).T                  # [BN, K] block

    return pl.pallas_call(
        kernel,
        grid=(nr // bn,),
        in_specs=[
            pl.BlockSpec((wb, n), lambda i: (0, 0)),       # full table
            pl.BlockSpec((k, bn), lambda i: (0, i)),
            pl.BlockSpec((k, bn), lambda i: (0, i)),
        ],
        out_specs=[pl.BlockSpec((bn, k), lambda i: (i, 0))
                   for _ in range(n_groups)],
        out_shape=[jax.ShapeDtypeStruct((nr, k), jnp.uint32)
                   for _ in range(n_groups)],
        interpret=interpret,
    )(tab_t, jn_t, rk_t)


def _mxu_take_feasible(w: int, n: int) -> bool:
    """VMEM feasibility of one two-level take over a [w, n] u32 word table:
    the bf16 chunk planes (8·w·n_pad bytes) + the one-hot tile + the f32
    rows scratch must fit the payload budget. Layout constants come from
    ops/mxutake.py so the gate prices exactly what the kernel allocates.
    Unsharded only — the take is a whole-table kernel, and the sharded
    step's halo/replicated routes already cover the kernel-mesh case."""
    from .mxutake import DEFAULT_BLOCK_G, LANES
    nb = -(-n // LANES)
    vmem = (w * 4 * nb * LANES * 2          # chunk planes, bf16
            + DEFAULT_BLOCK_G * nb * 2      # one-hot tile
            + DEFAULT_BLOCK_G * LANES * 4)  # MXU rows, f32
    return vmem <= _PALLAS_VMEM_PAYLOAD_BYTES and current_kernel_mesh() is None


def _edge_table_mxu(table, jn, rk, b_planes, extra_words=(),
                    interpret=False):
    """Bit-table edge exchange routed through the gather-free two-level MXU
    take: same [N, ceil(B*K/32)] u32 b-major/slot-minor bit-table contract
    as ``_edge_table_pallas``, but the per-edge row fetch is
    ``take_words_twolevel`` (one-hot matmul block select — no gather op of
    any width, mxutake.py) and the bit extraction runs as plain XLA
    word-selects. Returns ``(groups, extras)``: one [N, K] u32 payload per
    32-plane group, bit-compatible with every other formulation, plus the
    receiver views of ``extra_words``.

    ``extra_words`` ([W_i, N] u32 tables) is the MXU formulation of the
    sort mode's ride-along (heartbeat.edge_gather_packed): the extra word
    rows CONCATENATE onto the bit-table, so the one one-hot matmul — the
    expensive operand — fetches the exchange AND the extras in a single
    take, exactly as the variadic sort carries extra payload lanes. This
    is what lets engine._iwant_answer_extras merge the IWANT answer
    gather under ``edge_gather_mode="mxu"`` instead of paying its own
    serially-dependent take (the last mxu scalar tail, ROADMAP item 2)."""
    from .mxutake import take_words_twolevel

    n, wb = table.shape
    nr, k = jn.shape
    n_groups = (b_planes + 31) // 32
    u32 = jnp.uint32
    idx = jn.reshape(-1).astype(jnp.int32)                 # n-major [NR*K]
    tabs = table.T                                         # [WB, N]
    if extra_words:
        tabs = jnp.concatenate([tabs, *extra_words], axis=0)
    rows_all = take_words_twolevel(tabs, idx, interpret=interpret)
    rows = rows_all[:wb].reshape(wb, nr, k)                # [WB, N, K]
    pos0 = rk.astype(u32)                                  # bit positions
    accs = [jnp.zeros((nr, k), u32) for _ in range(n_groups)]
    for b in range(b_planes):
        pos = pos0 + u32(b * k)
        wsel = pos // u32(32)
        word = jnp.zeros((nr, k), u32)
        for wi in range(wb):                               # wb is tiny and
            word = jnp.where(wsel == wi, rows[wi], word)   # static: select
        bit = (word >> (pos % u32(32))) & u32(1)
        accs[b // 32] = accs[b // 32] | (bit << u32(b % 32))
    extras, ofs = [], wb
    for tab in extra_words:
        wt = tab.shape[0]
        extras.append(jnp.transpose(
            rows_all[ofs:ofs + wt].reshape(wt, nr, k), (0, 2, 1)))
        ofs += wt                                          # [W_i, K, N]
    return accs, extras


def _edge_packed_eligible(mode: str, n: int, k: int, b_planes: int,
                          extra_w: int = 0) -> str:
    """Concrete mode if ``mode`` is executable at this shape, else its
    degrade target (the dispatch walk skips candidates that degrade)."""
    wb = (b_planes * k + 31) // 32
    if mode == "mxu" and not _mxu_take_feasible(wb + extra_w, n):
        return "rows"
    if mode == "pallas":
        # table feasibility is GLOBAL n (the whole bit-table pins in VMEM);
        # block feasibility is the per-shard row count under a kernel mesh
        # (table + _mosaic_take's table-width index/result temporaries)
        if (n * wb * 12 > _PALLAS_VMEM_PAYLOAD_BYTES
                or _block_rows(local_rows(n), 2 * k * wb * 4) is None):
            return "rows"
    return mode


def resolve_edge_packed_mode(mode: str, n: int, k: int, b_planes: int,
                             extra_w: int = 0) -> str:
    """Resolve the packed-edge-exchange formulation (heartbeat
    edge_gather_packed). ``pallas`` is the bit-table kernel above; ``mxu``
    is the same bit-table routed through the two-level MXU take
    (_edge_table_mxu). ``auto`` ranks candidates through the measured
    cost-model dispatch (ops/dispatch.py — sort on TPU, scalar on CPU
    under the shipped conservative table) and takes the first executable
    one. ``extra_w`` is the ride-along word count (sort and mxu carry
    extras; the mxu VMEM gate prices them). Ineligible shapes degrade
    pallas/mxu -> rows."""
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("edge_packed", n=n, k=k, b=b_planes):
            got = _edge_packed_eligible(cand, n, k, b_planes, extra_w)
            if got == cand:
                return got
        return "scalar"
    return _edge_packed_eligible(mode, n, k, b_planes, extra_w)


def _words_eligible(mode: str, w: int, n: int, k: int, itemsize: int,
                    have_sort_key: bool) -> str:
    if mode == "sort" and not have_sort_key:
        return "rows"
    if mode == "mxu":
        # the two-level take recombines exactly 4 u8 chunk planes per word
        if itemsize != 4 or not _mxu_take_feasible(w, n):
            return "rows"
    if mode == "pallas":
        # table + _mosaic_take's table-width index/result temporaries
        if (w * n * (2 * itemsize + 4) > _PALLAS_VMEM_PAYLOAD_BYTES
                or _block_rows(local_rows(n), 2 * w * k * itemsize) is None):
            return "rows"
    return mode


def resolve_words_mode(mode: str, w: int, n: int, k: int,
                       itemsize: int = 4,
                       have_sort_key: bool = False) -> str:
    """Resolve the message-table gather mode (bits.gather_words_rows).

    ``auto`` ranks candidates through the measured cost-model dispatch
    (ops/dispatch.py): under the shipped conservative table TPU picks
    ``sort`` when the caller passes the edge keys (9.0 vs 24.7 ms for the
    100k hop gather on the live window), else ``rows``; CPU picks
    ``scalar``. A calibrated GRAFT_DISPATCH_TABLE can promote ``mxu``.
    ``pallas`` (the VMEM table kernel PERF_MODEL.md S1 designed) is
    quarantined from TPU auto by the Mosaic >128-wide gather wall and
    stays explicit-only; scripts/ablate.py sweeps all formulations
    head-to-head."""
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("words", w=w, n=n, k=k, itemsize=itemsize,
                           have_sort_key=have_sort_key):
            if _words_eligible(cand, w, n, k, itemsize,
                               have_sort_key) == cand:
                return cand
        return "scalar"
    return _words_eligible(mode, w, n, k, itemsize, have_sort_key)


def gather_words(x_w: jnp.ndarray, nbr: jnp.ndarray, m: int,
                 mode: str = "auto",
                 sort_key: jnp.ndarray | None = None) -> jnp.ndarray:
    """out[w, k, n] = x_w[w, nbr[n, k]] — the per-hop neighbor gather of the
    packed message window. ``nbr`` must be pre-clipped to [0, N).

    scalar: per-word advanced-index gather (CPU fast path). rows: unpack to
    [N, M] bool, row-gather, repack. sort: broadcast each sender's words
    along its K slots and sort-permute them to the receivers (k-major
    ``edge_sort_key``) — the fastest formulation measured on real TPU
    (edge_sort_key docstring). pallas: VMEM-resident table gather, blocked
    by the Mosaic gather wall on current chips.
    """
    from .bits import pack_bool, unpack_words

    w, n = x_w.shape
    k = nbr.shape[1]
    mode = resolve_words_mode(mode, w, n, k, x_w.dtype.itemsize,
                              have_sort_key=sort_key is not None)
    if mode == "sort":
        vals = jnp.broadcast_to(x_w[:, :, None], (w, n, k)).reshape(w, n * k)
        outs = jax.lax.sort((sort_key, *[vals[i] for i in range(w)]),
                            num_keys=1)
        return jnp.stack([o.reshape(k, n) for o in outs[1:]])
    if mode == "scalar":
        return jnp.stack([x_w[i][nbr.T] for i in range(w)])
    if mode == "rows":
        planes = unpack_words(x_w, m)                     # [N, M] bool
        rows = planes[nbr]                                # [N, K, M]
        return jnp.transpose(pack_bool(rows), (2, 1, 0))  # [W, K, N]
    if mode == "mxu":
        # gather-free two-level MXU take (ops/mxutake.py): k-major flat
        # indices so the [W, R] take reshapes straight to the [W, K, N]
        # receiver view
        from .mxutake import take_words_twolevel
        idx = nbr.T.reshape(-1).astype(jnp.int32)
        out = take_words_twolevel(x_w, idx,
                                  interpret=jax.default_backend() != "tpu")
        return out.reshape(w, k, nbr.shape[0])
    if mode == "pallas":
        fn = functools.partial(_gather_words_pallas,
                               interpret=jax.default_backend() != "tpu")
        if current_kernel_mesh() is not None:
            # table replicated (one small all-gather), rows per-shard
            return shard_kernel(fn,
                                in_specs=[(None, None), (PEER, None)],
                                out_specs=[(None, None, PEER)])(x_w, nbr)
        return fn(x_w, nbr)
    raise ValueError(f"unknown gather_words mode {mode!r}")


def _payload_eligible(mode: str, itemsize: int, n: int, k: int,
                      have_sort_key: bool) -> str:
    if mode == "mxu":
        # the blocked/tiled one-hot payload take
        # (mxutake.take_payload_onehot) views the K slot columns as word
        # planes and tiles them through the two-level take, so VMEM stays
        # bounded at any shape — the gates left are the exact-4-u8-chunk
        # dtype contract and the whole-table (unsharded) requirement
        if itemsize != 4 or current_kernel_mesh() is not None:
            return "scalar"
    if mode == "sort" and not have_sort_key:
        return "scalar"
    if mode == "pallas":
        # footprint = payload table + _mosaic_take's full-table-width
        # broadcast index (i32) and take result per chunk — ~3x the
        # payload for u32, which the old payload-only gate understated
        # (round-4 advisor finding)
        flat_bytes = n * k * (2 * itemsize + 4)
        if (itemsize < 4 or flat_bytes > _PALLAS_VMEM_PAYLOAD_BYTES
                or _block_rows(local_rows(n), 2 * k * itemsize) is None):
            return "rows"    # sub-word dtype, payload > VMEM budget, or no
                             # block size whose row scratch fits
    return mode


def resolve_mode(mode: str, payload_dtype, n: int, k: int,
                 have_sort_key: bool = False) -> str:
    """Resolve ``auto``/ineligible requests to a concrete formulation.

    ``auto`` ranks candidates through the measured cost-model dispatch
    (ops/dispatch.py): under the shipped conservative table TPU picks
    ``sort`` (the sort-permute apply, edge_sort_key docstring) when the
    caller supplies the destination keys, else ``scalar`` — the
    honest-methodology live-window numbers: sort ~5-7 ms vs scalar
    advanced-index ~23-34 ms vs rows ~55 ms for a [N,K] u32 exchange at
    100k (XLA gathers pay ~7 ns/index; sort moves the same bytes 4x
    faster); CPU picks ``scalar``. Explicit ``mxu`` now rides the
    blocked one-hot payload take (mxutake.take_payload_onehot) for
    4-byte dtypes — the generic payload permute no longer degrades the
    mxu mode to serialized scalar HBM gathers."""
    itemsize = jnp.dtype(payload_dtype).itemsize
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("edge_permute", n=n, k=k, itemsize=itemsize,
                           have_sort_key=have_sort_key):
            if _payload_eligible(cand, itemsize, n, k,
                                 have_sort_key) == cand:
                return cand
        return "scalar"
    return _payload_eligible(mode, itemsize, n, k, have_sort_key)


def permutation_gather(payload: jnp.ndarray, jn: jnp.ndarray,
                       rk: jnp.ndarray, mode: str = "auto",
                       sort_key: jnp.ndarray | None = None) -> jnp.ndarray:
    """out[n, k] = payload[jn[n, k], rk[n, k]].

    ``payload`` is [N, K] of any dtype; ``jn``/``rk`` must be pre-clipped to
    valid range (callers mask invalid slots on the result). ``sort_key``
    (n-major ``edge_sort_key``) enables the sort-permute formulation — the
    fastest measured on real TPU.
    """
    n, k = payload.shape
    mode = resolve_mode(mode, payload.dtype, n, k,
                        have_sort_key=sort_key is not None)
    if mode == "sort":
        return _gather_sort(payload, sort_key)
    if mode == "scalar":
        return _gather_scalar(payload, jn, rk)
    if mode == "rows":
        return _gather_rows(payload, jn, rk)
    if mode == "mxu":
        # blocked/tiled one-hot payload take (ops/mxutake.py): no gather
        # op of any width — the mxu mode's last scalar degradation closed
        from .mxutake import take_payload_onehot
        return take_payload_onehot(payload, jn, rk,
                                   interpret=jax.default_backend() != "tpu")
    if mode == "pallas":
        fn = functools.partial(_gather_pallas,
                               interpret=jax.default_backend() != "tpu")
        if current_kernel_mesh() is not None:
            return shard_kernel(fn,
                                in_specs=[(None, None), (PEER, None),
                                          (PEER, None)],
                                out_specs=[(PEER, None)])(payload, jn, rk)
        return fn(payload, jn, rk)
    raise ValueError(f"unknown edge_gather_mode {mode!r}")
