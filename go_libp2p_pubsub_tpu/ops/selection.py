"""Masked selection primitives for the batched router.

The reference's peer-selection idioms — random shuffles + "pick first D"
(gossipsub.go:1954-1973), score-ordered keeps (gossipsub.go:1430-1490) —
become masked (gumbel-)top-k over the K neighbor-slot axis. ``count`` may be
a traced per-row scalar; selection is rank-based so the whole thing is one
sort per call, MXU/VPU friendly, no data-dependent shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalar, not jnp: a module-level jax Array closed over by the
# fleet plane's vmapped traces leaks a stale constant tracer across
# fleet-group retraces (see sim/state.py NEVER)
NEG_INF = np.float32(-1e30)

# Test-time guard for the count <= max_count precondition of the iterative
# formulation (see _select_iter): flip on in tests/debug runs to turn a
# silent truncation into a loud failure. Off by default — the check inserts
# a host callback into the jitted program. The flag is read at TRACE time:
# callables jitted before flipping it keep their cached guard-free traces,
# so set it before any engine call (or call jax.clear_caches() after).
CHECK_COUNT_BOUND = False


def _check_count_bound(count: jnp.ndarray, max_count: int) -> None:
    if not CHECK_COUNT_BOUND:
        return

    def _raise(over):
        if over:
            raise AssertionError(
                f"selection count exceeds the static max_count={max_count} "
                "bound; the iterative formulation would silently truncate")
    jax.debug.callback(_raise, jnp.any(count > max_count))


def ranks_desc(keys: jnp.ndarray) -> jnp.ndarray:
    """Rank (0 = largest) of each element along the last axis; ties break
    toward the lower index (the stable-argsort order).

    For the slot axis (K <= 64 everywhere in this engine) a comparison-count
    rank is one fused O(K^2) reduction — far cheaper on TPU than the
    two-bitonic-argsort formulation it replaces, and exact."""
    k = keys.shape[-1]
    ki = keys[..., :, None]                     # element being ranked
    kj = keys[..., None, :]                     # elements compared against
    i = jnp.arange(k)[:, None]
    j = jnp.arange(k)[None, :]
    beats = (kj > ki) | ((kj == ki) & (j < i))
    return jnp.sum(beats, axis=-1)


def resolve_selection_mode(mode: str, k: int,
                           max_count: int | None = None) -> str:
    """Resolve ``auto``/ineligible selection-mode requests through the
    measured cost-model dispatch (ops/dispatch.py). The shipped
    conservative table reproduces the legacy static rule — CPU picks
    ``iter`` while ``2 * max_count <= k`` else ``sort``; TPU picks
    ``ranks`` — until a calibrated GRAFT_DISPATCH_TABLE re-ranks.

    ``iter`` needs a static ``max_count`` bound and only pays off while the
    bound is well under K (its cost is max_count sequential argmax passes).
    """
    backend = jax.default_backend()
    if mode == "auto":
        from .dispatch import choose
        for cand in choose("selection", k=k, max_count=max_count):
            if cand == "iter" and (max_count is None or max_count >= k):
                continue
            return cand
        mode = "sort"
    if mode == "iter" and (max_count is None or max_count >= k):
        return "ranks" if backend != "cpu" else "sort"
    return mode


def _select_iter(keys: jnp.ndarray, mask: jnp.ndarray, count: jnp.ndarray,
                 max_count: int) -> jnp.ndarray:
    """O(max_count * K): sequential first-occurrence maxima. Bit-identical
    to the rank form for keys where every unmasked entry is > NEG_INF
    (true for both producers: uniform noise in [0, 1) and bounded scores)."""
    k = keys.shape[-1]

    def body(i, carry):
        sel, rem = carry
        idx = jnp.argmax(rem, axis=-1)
        take = (i < count) & jnp.take_along_axis(
            mask, idx[..., None], axis=-1)[..., 0]
        onehot = (jnp.arange(k) == idx[..., None]) & take[..., None]
        return sel | onehot, jnp.where(onehot, NEG_INF, rem)

    sel, _ = jax.lax.fori_loop(0, max_count, body,
                               (jnp.zeros_like(mask), keys))
    return sel


def _select_by_keys(keys: jnp.ndarray, mask: jnp.ndarray,
                    count: jnp.ndarray, *, max_count: int | None = None,
                    mode: str = "auto") -> jnp.ndarray:
    """Top-``count`` by key per row, masked. Three formulations with
    identical results (ties break toward the lower slot in all of them):
    the fused O(K^2) comparison rank wins on TPU (no [..., K, K]
    materialization survives fusion); a sort + per-row threshold and an
    O(c*K) iterative argmax (for statically count-bounded callers — every
    heartbeat selection is bounded by a degree param <= Dhi) compete on
    CPU, where iter measured 1.7x over sort at beacon shapes
    (scripts/microbench_kernels.py)."""
    k = keys.shape[-1]
    mode = resolve_selection_mode(mode, k, max_count)
    if mode == "iter":
        _check_count_bound(count, max_count)
        return _select_iter(keys, mask, count, max_count)
    if mode == "sort":
        # exact tie handling (float32 keys DO collide at 4M draws/call)
        # without x64: lexicographic two-key sort on (inverted sortable
        # bits, slot), so equal keys break toward the lower slot — the
        # same order ranks_desc defines — then select by per-row
        # count-th-smallest threshold pair
        u = jax.lax.bitcast_convert_type(keys, jnp.uint32)
        u = jnp.where(keys < 0, ~u, u | jnp.uint32(0x80000000))
        p = ~u                                     # ascending = best first
        slot = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32), keys.shape)
        sp, ss = jax.lax.sort((p, slot), dimension=-1, num_keys=2)
        idx = jnp.clip(count[..., None] - 1, 0, k - 1)
        p_thr = jnp.take_along_axis(sp, idx, axis=-1)
        s_thr = jnp.take_along_axis(ss, idx, axis=-1)
        sel = (p < p_thr) | ((p == p_thr) & (slot <= s_thr))
        return mask & sel & (count[..., None] > 0)
    if mode == "ranks":
        r = ranks_desc(keys)
        return (r < count[..., None]) & mask
    raise ValueError(f"unknown selection mode {mode!r}")


def select_random(mask: jnp.ndarray, count: jnp.ndarray, key: jax.Array, *,
                  max_count: int | None = None,
                  mode: str = "auto",
                  noise: jnp.ndarray | None = None) -> jnp.ndarray:
    """Uniformly choose up to ``count`` True positions per row of ``mask``.

    count broadcasts against mask.shape[:-1]. Ties impossible w.p. 1.
    ``max_count`` is a static upper bound on count enabling the iterative
    formulation; ``mode`` picks it explicitly (SimConfig.selection_mode).
    ``noise`` substitutes pre-drawn uniform [0, 1) noise of ``mask.shape``
    for the internal draw (``key`` is then unused) — the bucketed step
    (sim/bucketed.py, bucketed_rng="dense") draws once at the dense
    [N, k_slots] shape and feeds each bucket its slice, so the selection
    consumes the exact dense stream and stays bit-exact per bucket.

    PRECONDITION: every element of ``count`` must be <= ``max_count`` when
    one is given — the iterative formulation runs exactly max_count argmax
    passes and SILENTLY truncates larger requests. All engine callers derive
    count by clipping against the same degree parameter they pass as the
    bound; enable selection.CHECK_COUNT_BOUND in tests to enforce it.
    """
    if noise is None:
        noise = jax.random.uniform(key, mask.shape)
    keys = jnp.where(mask, noise, NEG_INF)
    return _select_by_keys(keys, mask, count, max_count=max_count, mode=mode)


def select_top(score: jnp.ndarray, mask: jnp.ndarray, count: jnp.ndarray, *,
               max_count: int | None = None,
               mode: str = "auto") -> jnp.ndarray:
    """Choose up to ``count`` highest-score True positions per row.

    Deterministic tie-break by slot index (lower slot wins), mirroring the
    sorted-iteration determinism the batched engine guarantees.

    PRECONDITION: count <= max_count elementwise when a bound is given —
    see select_random.
    """
    k = mask.shape[-1]
    tiebreak = -jnp.arange(k, dtype=jnp.float32) * 1e-9
    keys = jnp.where(mask, score + tiebreak, NEG_INF)
    return _select_by_keys(keys, mask, count, max_count=max_count, mode=mode)


def masked_median(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Median of masked values along the last axis (gossipsub.go:1527-1542
    computes the median mesh score for opportunistic grafting).

    Matches Go's integer midpoint: element at index n//2 of the ascending
    sorted masked values. Rows with an empty mask return +inf (no graft).
    """
    big = jnp.float32(1e30)
    padded = jnp.where(mask, values, big)
    srt = jnp.sort(padded, axis=-1)
    n = jnp.sum(mask, axis=-1)
    idx = jnp.clip(n // 2, 0, values.shape[-1] - 1)
    return jnp.take_along_axis(srt, idx[..., None], axis=-1)[..., 0]
