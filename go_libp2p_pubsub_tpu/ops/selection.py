"""Masked selection primitives for the batched router.

The reference's peer-selection idioms — random shuffles + "pick first D"
(gossipsub.go:1954-1973), score-ordered keeps (gossipsub.go:1430-1490) —
become masked (gumbel-)top-k over the K neighbor-slot axis. ``count`` may be
a traced per-row scalar; selection is rank-based so the whole thing is one
sort per call, MXU/VPU friendly, no data-dependent shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-1e30)


def ranks_desc(keys: jnp.ndarray) -> jnp.ndarray:
    """Rank (0 = largest) of each element along the last axis; ties break
    toward the lower index (the stable-argsort order).

    For the slot axis (K <= 64 everywhere in this engine) a comparison-count
    rank is one fused O(K^2) reduction — far cheaper on TPU than the
    two-bitonic-argsort formulation it replaces, and exact."""
    k = keys.shape[-1]
    ki = keys[..., :, None]                     # element being ranked
    kj = keys[..., None, :]                     # elements compared against
    i = jnp.arange(k)[:, None]
    j = jnp.arange(k)[None, :]
    beats = (kj > ki) | ((kj == ki) & (j < i))
    return jnp.sum(beats, axis=-1)


def _select_by_keys(keys: jnp.ndarray, mask: jnp.ndarray,
                    count: jnp.ndarray) -> jnp.ndarray:
    """Top-``count`` by key per row, masked. Two formulations with
    identical results on distinct keys (ties occur only between masked
    NEG_INF entries, which are excluded): the fused O(K^2) comparison rank
    wins on TPU (no [..., K, K] materialization survives fusion), a sort +
    per-row threshold wins on CPU where the comparison matrix is ~30%
    slower at beacon shapes (scripts/microbench_kernels.py)."""
    k = keys.shape[-1]
    if jax.default_backend() == "cpu":
        # exact tie handling (float32 keys DO collide at 4M draws/call)
        # without x64: lexicographic two-key sort on (inverted sortable
        # bits, slot), so equal keys break toward the lower slot — the
        # same order ranks_desc defines — then select by per-row
        # count-th-smallest threshold pair
        u = jax.lax.bitcast_convert_type(keys, jnp.uint32)
        u = jnp.where(keys < 0, ~u, u | jnp.uint32(0x80000000))
        p = ~u                                     # ascending = best first
        slot = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32), keys.shape)
        sp, ss = jax.lax.sort((p, slot), dimension=-1, num_keys=2)
        idx = jnp.clip(count[..., None] - 1, 0, k - 1)
        p_thr = jnp.take_along_axis(sp, idx, axis=-1)
        s_thr = jnp.take_along_axis(ss, idx, axis=-1)
        sel = (p < p_thr) | ((p == p_thr) & (slot <= s_thr))
        return mask & sel & (count[..., None] > 0)
    r = ranks_desc(keys)
    return (r < count[..., None]) & mask


def select_random(mask: jnp.ndarray, count: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Uniformly choose up to ``count`` True positions per row of ``mask``.

    count broadcasts against mask.shape[:-1]. Ties impossible w.p. 1.
    """
    noise = jax.random.uniform(key, mask.shape)
    keys = jnp.where(mask, noise, NEG_INF)
    return _select_by_keys(keys, mask, count)


def select_top(score: jnp.ndarray, mask: jnp.ndarray, count: jnp.ndarray) -> jnp.ndarray:
    """Choose up to ``count`` highest-score True positions per row.

    Deterministic tie-break by slot index (lower slot wins), mirroring the
    sorted-iteration determinism the batched engine guarantees.
    """
    k = mask.shape[-1]
    tiebreak = -jnp.arange(k, dtype=jnp.float32) * 1e-9
    keys = jnp.where(mask, score + tiebreak, NEG_INF)
    return _select_by_keys(keys, mask, count)


def masked_median(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Median of masked values along the last axis (gossipsub.go:1527-1542
    computes the median mesh score for opportunistic grafting).

    Matches Go's integer midpoint: element at index n//2 of the ascending
    sorted masked values. Rows with an empty mask return +inf (no graft).
    """
    big = jnp.float32(1e30)
    padded = jnp.where(mask, values, big)
    srt = jnp.sort(padded, axis=-1)
    n = jnp.sum(mask, axis=-1)
    idx = jnp.clip(n // 2, 0, values.shape[-1] - 1)
    return jnp.take_along_axis(srt, idx[..., None], axis=-1)[..., 0]
