"""Connection churn: batched dead-peer / reconnect dynamics.

Models the reference's connection lifecycle as per-edge state toggles over the
fixed neighbor table:

- **Edge down** = the stream-reader sentinel firing (comm.go:144-154
  ``handlePeerDead``) followed by ``handleDeadPeers`` (pubsub.go:711-757):
  the peer leaves every mesh and fanout it was in — router ``RemovePeer``
  (gossipsub.go:575-596) — and its score enters the retention window
  (score.go:611-644 ``RemovePeer`` with RetainScore): the P3 deficit is
  converted to a sticky mesh-failure penalty exactly as on PRUNE, and the
  counters are kept frozen until retention expires.
- **Edge up** = a (re)connect notification (notify.go:11-75): the slot becomes
  usable again. If the edge was down longer than ``retain_score_ticks``, the
  per-slot score counters reset (the reference deletes ``peerStats`` after
  retention, score.go:631-643); a faster reconnect sees its old score — this
  is the reference's defence against whitewashing by reconnect.

Symmetry: both directions of an edge go down/up together (a TCP stream dies
for both ends), decided by the lower-id endpoint's random draw and mirrored
through ``reverse_slot``.

Churn is OFF unless ``SimConfig.churn_disconnect_prob > 0`` (a jit-static
flag, so non-churn configs compile identical programs as before).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .score_ops import apply_prune_penalty, compute_scores


def _symmetric_value(state: SimState, x: jnp.ndarray) -> jnp.ndarray:
    """[N, K] per-edge values made equal on both directions of each edge: the
    lower-id endpoint's value wins, gathered through reverse_slot."""
    n, k = state.neighbors.shape
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    rk = jnp.clip(state.reverse_slot, 0, k - 1)
    x_rev = x[nbr, rk]
    mine_wins = jnp.arange(n)[:, None] < nbr
    return jnp.where(mine_wins, x, x_rev)


def _symmetric_uniform(state: SimState, key: jax.Array) -> jnp.ndarray:
    """[N, K] uniform draws equal on both directions of each edge: the draw of
    the lower-id endpoint wins, gathered through reverse_slot."""
    n, k = state.neighbors.shape
    return _symmetric_value(state, jax.random.uniform(key, (n, k)))


def churn_edges(state: SimState, cfg: SimConfig, tp: TopicParams,
                key: jax.Array) -> SimState:
    """One churn round: take down a random fraction of live edges, bring back
    a random fraction of down edges, with RemovePeer/retention semantics."""
    n, t, k = state.mesh.shape
    kd, ku = jax.random.split(key)

    known = state.neighbors >= 0
    down = known & ~state.connected
    live = known & state.connected

    go_down = live & (_symmetric_uniform(state, kd) < cfg.churn_disconnect_prob)
    if cfg.px_enabled:
        # PX-seeded reconnects (gossipsub.go:893-973): the dialing side only
        # gets a PX referral for well-scored peers (handlePrune's
        # AcceptPXThreshold gate, gossipsub.go:860-866); edges to peers it
        # scores below the threshold come back at a fraction of the rate.
        # The dialing endpoint is the same lower-id side that decides the
        # symmetric draw, so edges stay symmetric.
        scores = compute_scores(state, cfg, tp, mask_disconnected=False)
        p_up = jnp.where(scores >= cfg.accept_px_threshold,
                         cfg.churn_reconnect_prob,
                         cfg.churn_reconnect_prob * cfg.px_low_score_factor)
        p_up = _symmetric_value(state, p_up)
    else:
        p_up = cfg.churn_reconnect_prob
    come_up = down & (_symmetric_uniform(state, ku) < p_up)
    # direct peers are force-redialed on a fixed cadence regardless of churn
    # (gossipsub.go:1648-1670 directConnect, every 300 ticks). The lower-id
    # endpoint's direct flag decides, keeping `connected` edge-symmetric
    # even if a scenario marks direct on one side only.
    redial = (state.tick % cfg.direct_connect_ticks) == 0
    come_up = come_up | (down & _symmetric_value(state, state.direct) & redial)

    # --- RemovePeer on edges going down (gossipsub.go:575-596) ---
    down3 = go_down[:, None, :]
    removed_mesh = state.mesh & down3
    state = apply_prune_penalty(state, removed_mesh, tp)
    state = state._replace(
        mesh=state.mesh & ~down3,
        fanout=state.fanout & ~down3,
        # a dead peer's pending gossip pulls never resolve; drop them rather
        # than charging a broken promise (the reference cancels promises on
        # peer removal, gossip_tracer.go:154-162)
        iwant_pending=jnp.where(
            go_down[jnp.arange(n)[:, None],
                    jnp.clip(state.iwant_pending, 0, k - 1)]
            & (state.iwant_pending >= 0),
            -1, state.iwant_pending),
        disconnect_tick=jnp.where(go_down, state.tick, state.disconnect_tick))

    # --- reconnect: expire retention, then flip the edge up ---
    down_age = state.tick - state.disconnect_tick
    expired = come_up & (down_age > cfg.retain_score_ticks)
    exp3 = expired[:, None, :]
    z3 = jnp.zeros((n, t, k), jnp.float32)
    state = state._replace(
        first_message_deliveries=jnp.where(exp3, z3, state.first_message_deliveries),
        mesh_message_deliveries=jnp.where(exp3, z3, state.mesh_message_deliveries),
        mesh_failure_penalty=jnp.where(exp3, z3, state.mesh_failure_penalty),
        invalid_message_deliveries=jnp.where(exp3, z3, state.invalid_message_deliveries),
        behaviour_penalty=jnp.where(expired, 0.0, state.behaviour_penalty),
        graft_tick=jnp.where(exp3, NEVER, state.graft_tick),
        mesh_active=state.mesh_active & ~exp3,
        connected=(state.connected & ~go_down) | come_up,
        disconnect_tick=jnp.where(come_up, NEVER, state.disconnect_tick))
    return state
