"""Connection churn: batched dead-peer / reconnect dynamics.

Models the reference's connection lifecycle as per-edge state toggles over the
fixed neighbor table:

- **Edge down** = the stream-reader sentinel firing (comm.go:144-154
  ``handlePeerDead``) followed by ``handleDeadPeers`` (pubsub.go:711-757):
  the peer leaves every mesh and fanout it was in — router ``RemovePeer``
  (gossipsub.go:575-596) — and its score enters the retention window
  (score.go:611-644 ``RemovePeer`` with RetainScore): the P3 deficit is
  converted to a sticky mesh-failure penalty exactly as on PRUNE, and the
  counters are kept frozen until retention expires.
- **Edge up** = a (re)connect notification (notify.go:11-75): the slot becomes
  usable again. If the edge was down longer than ``retain_score_ticks``, the
  per-slot score counters reset (the reference deletes ``peerStats`` after
  retention, score.go:631-643); a faster reconnect sees its old score — this
  is the reference's defence against whitewashing by reconnect.

Symmetry: both directions of an edge go down/up together (a TCP stream dies
for both ends), decided by the lower-id endpoint's random draw and mirrored
through ``reverse_slot``.

Churn is OFF unless ``SimConfig.churn_disconnect_prob > 0`` (a jit-static
flag, so non-churn configs compile identical programs as before).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .bits import U32, pack_bool
from .permgather import edge_sort_key, permutation_gather, resolve_mode
from .score_ops import apply_prune_penalty, compute_scores


def _edge_exchange(state: SimState, x: jnp.ndarray,
                   mode: str = "auto") -> jnp.ndarray:
    """One [N, K] payload routed through the reverse-edge involution:
    ``out[n, k] = x[jn, rk]``. Under a sharded step with
    ``sharded_route="halo"`` and a sort-resolved mode, the payload rides
    the per-shard all_to_all halo route instead of the global sort the
    SPMD partitioner would replicate via a dense [N, K] all-gather —
    churn's score/PX reconnect exchange was the last engine plane still
    riding partitioner-inserted collectives (tests/test_hlo_sharded_budget
    enforces the packed budget over the whole step)."""
    from ..parallel.kernel_context import current_kernel_mesh

    n, k = state.neighbors.shape
    ctx = current_kernel_mesh()
    if ctx is not None and ctx.route == "halo" and \
            resolve_mode(mode, x.dtype, n, k, have_sort_key=True) == "sort":
        from ..parallel.halo import route_payloads_halo
        return route_payloads_halo([x], state.neighbors,
                                   state.reverse_slot)[0]
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    rk = jnp.clip(state.reverse_slot, 0, k - 1)
    sk = edge_sort_key(state.neighbors, state.reverse_slot, k_major=False)
    return permutation_gather(x, nbr, rk, mode, sort_key=sk)


def _symmetric_value(state: SimState, x: jnp.ndarray,
                     mode: str = "auto") -> jnp.ndarray:
    """[N, K] per-edge values made equal on both directions of each edge: the
    lower-id endpoint's value wins, gathered through reverse_slot."""
    n = state.neighbors.shape[0]
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    x_rev = _edge_exchange(state, x, mode)
    mine_wins = jnp.arange(n)[:, None] < nbr
    return jnp.where(mine_wins, x, x_rev)


def _symmetric_bools(state: SimState, bits: list,
                     mode: str = "auto") -> list:
    """Symmetrize boolean per-edge decisions: both directions of an edge use
    the lower-id endpoint's bit. All planes (up to 32) share ONE packed u32
    permutation gather — each f32 `_symmetric_value` costs its own N*K
    serialized scalar loads on TPU, so decisions that can be taken locally
    first (draw < prob) and exchanged as bits should be."""
    n = state.neighbors.shape[0]
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    payload = jnp.zeros(state.neighbors.shape, U32)
    for i, b in enumerate(bits):
        payload = payload | jnp.where(b, U32(1) << U32(i), U32(0))
    g = _edge_exchange(state, payload, mode)
    mine_wins = jnp.arange(n)[:, None] < nbr
    return [jnp.where(mine_wins, b, ((g >> U32(i)) & U32(1)).astype(bool))
            for i, b in enumerate(bits)]


def churn_subscriptions(state: SimState, cfg: SimConfig, tp: TopicParams,
                        key: jax.Array) -> SimState:
    """Batched topic Join/Leave round (§3.5 topic lifecycle).

    Leave (gossipsub.go:1104-1124): the leaver PRUNEs every mesh member of
    the topic; both sides drop the edge, take the P3b prune penalty
    (score.go:669-694 fires on Prune for either direction), and enter the
    *unsubscribe* backoff (gossipsub.go:313-320 add_backoff is_unsubscribe).

    Join (gossipsub.go:1047-1102): live fanout edges promote straight into
    the mesh (mirrored on the remote side — the reference sends GRAFTs that
    the fanout peers accept barring backoff, which promotion respects);
    everything else fills in at the next heartbeat's undersubscribed graft.
    """
    n, t, k = state.mesh.shape
    kj, kl = jax.random.split(key)
    leave = state.subscribed & \
        (jax.random.uniform(kl, (n, t)) < cfg.sub_leave_prob)
    join = ~state.subscribed & \
        (jax.random.uniform(kj, (n, t)) < cfg.sub_join_prob)

    from .heartbeat import edge_gather  # local import: avoid cycle
    removed = state.mesh & leave[:, :, None]
    inc_removed = edge_gather(removed, state,
                              mode=cfg.edge_gather_mode) & state.mesh
    mesh_removed = removed | inc_removed
    state = apply_prune_penalty(state, mesh_removed, tp)
    backoff = jnp.where(mesh_removed,
                        state.tick + cfg.unsubscribe_backoff_ticks,
                        state.backoff)

    # Join: promote fanout edges not under backoff ON EITHER SIDE (the
    # reference's GRAFT would be refused by a remote in backoff and the
    # joiner would drop the edge — a one-sided promote would otherwise
    # persist as an asymmetric mesh edge until the remote's backoff expires)
    backoff_ok = state.tick >= backoff
    remote_ok = edge_gather(backoff_ok, state, mode=cfg.edge_gather_mode)
    promote = join[:, :, None] & state.fanout & \
        state.connected[:, None, :] & backoff_ok & remote_ok
    promote_in = edge_gather(promote, state, mode=cfg.edge_gather_mode)
    promoted = promote | promote_in
    new_mesh = (state.mesh & ~mesh_removed) | promoted
    subscribed = (state.subscribed | join) & ~leave
    from ..sim.state import refresh_nbr_subscribed
    state = refresh_nbr_subscribed(state._replace(subscribed=subscribed))
    return state._replace(
        mesh=new_mesh, backoff=backoff,
        fanout=state.fanout & ~join[:, :, None],
        fanout_lastpub=jnp.where(join, NEVER, state.fanout_lastpub),
        graft_tick=jnp.where(promoted & ~state.mesh, state.tick,
                             state.graft_tick),
        mesh_active=state.mesh_active & ~(promoted & ~state.mesh))


def take_edges_down(state: SimState, cfg: SimConfig, tp: TopicParams,
                    go_down: jnp.ndarray) -> SimState:
    """RemovePeer semantics for an arbitrary [N, K] edge-down mask
    (gossipsub.go:575-596): prune penalty, mesh/fanout eviction, pending
    gossip-pull cancellation, disconnect-tick stamp. ``go_down`` must be
    edge-symmetric (both directions down together, like a dying TCP
    stream) — churn_edges symmetrizes its draws, sim/faults.py cut masks
    are symmetric by construction."""
    n, t, k = state.mesh.shape
    down3 = go_down[:, None, :]
    removed_mesh = state.mesh & down3
    state = apply_prune_penalty(state, removed_mesh, tp)
    # a dead peer's pending gossip pulls never resolve; drop them rather
    # than charging a broken promise (the reference cancels promises on
    # peer removal, gossip_tracer.go:154-162). The slot-id lookup is a
    # per-lane word shift against go_down packed along K — not a [N, M]
    # scalar gather.
    gd_words = pack_bool(go_down)                   # [N, ceil(K/32)] u32
    pend = state.iwant_pending
    pc = jnp.clip(pend, 0, k - 1)
    sel = jnp.broadcast_to(gd_words[:, 0][:, None], pend.shape)
    for wi in range(1, gd_words.shape[1]):
        sel = jnp.where(pc // 32 == wi, gd_words[:, wi][:, None], sel)
    pend_down = (((sel >> (pc % 32).astype(U32)) & U32(1)) != 0) & (pend >= 0)
    return state._replace(
        mesh=state.mesh & ~down3,
        fanout=state.fanout & ~down3,
        iwant_pending=jnp.where(pend_down, -1, pend),
        connected=state.connected & ~go_down,
        disconnect_tick=jnp.where(go_down, state.tick, state.disconnect_tick))


def bring_edges_up(state: SimState, cfg: SimConfig,
                   come_up: jnp.ndarray) -> SimState:
    """Reconnect an arbitrary [N, K] down-edge mask with score-retention
    semantics (notify.go:11-75 connect + score.go:611-644 RetainScore):
    an edge down longer than ``cfg.retain_score_ticks`` resets its
    per-slot counters (the reference deletes peerStats after retention);
    a faster reconnect sees its old score."""
    n, t, k = state.mesh.shape
    down_age = state.tick - state.disconnect_tick
    expired = come_up & (down_age > cfg.retain_score_ticks)
    exp3 = expired[:, None, :]
    z3 = jnp.zeros((n, t, k), jnp.float32)
    return state._replace(
        first_message_deliveries=jnp.where(exp3, z3, state.first_message_deliveries),
        mesh_message_deliveries=jnp.where(exp3, z3, state.mesh_message_deliveries),
        mesh_failure_penalty=jnp.where(exp3, z3, state.mesh_failure_penalty),
        invalid_message_deliveries=jnp.where(exp3, z3, state.invalid_message_deliveries),
        behaviour_penalty=jnp.where(expired, 0.0, state.behaviour_penalty),
        graft_tick=jnp.where(exp3, NEVER, state.graft_tick),
        mesh_active=state.mesh_active & ~exp3,
        connected=state.connected | come_up,
        disconnect_tick=jnp.where(come_up, NEVER, state.disconnect_tick))


def churn_edges(state: SimState, cfg: SimConfig, tp: TopicParams,
                key: jax.Array,
                scores_all: jnp.ndarray | None = None,
                forbid_up: jnp.ndarray | None = None) -> SimState:
    """One churn round: take down a random fraction of live edges, bring back
    a random fraction of down edges, with RemovePeer/retention semantics.

    ``scores_all`` is the heartbeat's unmasked score cache (HeartbeatOut
    .scores_all) when the engine drives churn; direct callers may omit it
    and pay for a fresh compute. ``forbid_up`` masks edges a FaultPlan is
    holding down (sim/faults.py partitions/outages) out of the reconnect
    draw — without it, churn's random redials would flap cut edges back
    up for a tick until the next fault pass re-cut them.
    """
    n, t, k = state.mesh.shape
    kd, ku = jax.random.split(key)

    known = state.neighbors >= 0
    down = known & ~state.connected
    live = known & state.connected

    n_, k_ = state.neighbors.shape
    d_down = jax.random.uniform(kd, (n_, k_)) < cfg.churn_disconnect_prob
    if cfg.px_enabled:
        # PX-seeded reconnects (gossipsub.go:893-973): the dialing side only
        # gets a PX referral for well-scored peers (handlePrune's
        # AcceptPXThreshold gate, gossipsub.go:860-866); edges to peers it
        # scores below the threshold come back at a fraction of the rate.
        # The dialing endpoint is the same lower-id side that decides the
        # symmetric draw, so edges stay symmetric.
        if scores_all is None:
            scores_all = compute_scores(state, cfg, tp,
                                        mask_disconnected=False)
        # retained counters expire after RetainScore (score.go:611-644):
        # an edge down longer than the retention window scores 0 again, so
        # a once-bad long-gone peer is not shunned forever
        down_age = state.tick - state.disconnect_tick
        px_score = jnp.where(down_age > cfg.retain_score_ticks,
                             0.0, scores_all)
        p_up = jnp.where(px_score >= cfg.accept_px_threshold,
                         cfg.churn_reconnect_prob,
                         cfg.churn_reconnect_prob * cfg.px_low_score_factor)
    else:
        p_up = cfg.churn_reconnect_prob
    # decisions are taken locally (draw < prob) and the lower-id endpoint's
    # BITS are exchanged in one packed gather — identical trajectories to
    # symmetrizing the f32 draws/probabilities first, at a third of the
    # permutation-gather cost
    d_up = jax.random.uniform(ku, (n_, k_)) < p_up
    d_down, d_up, direct_low = _symmetric_bools(
        state, [d_down, d_up, state.direct], cfg.edge_gather_mode)
    go_down = live & d_down
    come_up = down & d_up
    # direct peers are force-redialed on a fixed cadence regardless of churn
    # (gossipsub.go:1648-1670 directConnect, every 300 ticks). The lower-id
    # endpoint's direct flag decides, keeping `connected` edge-symmetric
    # even if a scenario marks direct on one side only.
    redial = (state.tick % cfg.direct_connect_ticks) == 0
    come_up = come_up | (down & direct_low & redial)
    if forbid_up is not None:
        # plan-cut edges stay down (symmetric mask, so symmetry holds)
        come_up = come_up & ~forbid_up

    # --- RemovePeer on edges going down (gossipsub.go:575-596) ---
    state = take_edges_down(state, cfg, tp, go_down)
    # --- reconnect: expire retention, then flip the edge up ---
    return bring_edges_up(state, cfg, come_up)
