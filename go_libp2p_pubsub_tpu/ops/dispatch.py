"""Measured cost-model dispatch: one shape+platform → formulation layer.

Every engine seam with competing formulations — the generic [N, K]
payload permute, the [W, N] word-table gathers, the packed edge exchange,
the forwarding-hop / gossip-emit kernels, and masked selection — used to
resolve ``"auto"`` through its own scattered static rule
(``permgather.resolve_*``, ``hopkernel.resolve_*``,
``selection.resolve_selection_mode``).  This module replaces those rules
with ONE table-driven chooser:

    choose(op, backend, **shape) -> ranked candidate formulations

The ranking is driven by the analytic cost models (``ops/mxutake
.cost_model`` is the template; the other formulations are priced from the
same bytes/FLOP inventories PERF_MODEL.md derives its projections from),
parameterized by per-platform coefficients, and optionally overridden by
MEASURED timings from a microbench sweep (``scripts/calibrate_dispatch
.py``).  The table is a versioned, platform-fingerprinted JSON artifact:

    - the shipped default (``ops/dispatch_table.json``) is analytic and
      CONSERVATIVE — its TPU coefficients price the mxu one-hot operand
      as streamed (the pessimistic lowering), so TPU ``auto`` keeps the
      measured sort-era winners until a live window calibrates;
    - ``GRAFT_DISPATCH_TABLE=path`` loads a calibrated table — the one
      env flip that promotes a measured winner into every ``auto``;
    - ``quarantined`` markers exclude losing formulations from auto
      ranking (explicit requests still honored; deletion deferred until
      a real TPU window confirms, ROADMAP item 2).

The resolvers keep their FEASIBILITY gates (VMEM budgets, dtype/block
constraints, config eligibility): dispatch ranks, the resolver walks the
ranking and takes the first formulation that is actually executable.
Dispatch is deterministic for a fixed table + shape
(tests/test_dispatch.py pins it, and pins CPU parity with the legacy
static rules at the bench shapes).
"""

from __future__ import annotations

import json
import math
import os

# canonical formulation order per op; doubles as the deterministic
# tie-break (earlier wins on exact cost ties — "iter" leads selection so
# the legacy CPU 2·max_count == k boundary keeps resolving to iter)
OPS: dict = {
    "edge_permute": ("scalar", "rows", "sort", "pallas", "mxu"),
    "words": ("scalar", "rows", "sort", "pallas", "mxu"),
    "edge_packed": ("scalar", "rows", "sort", "pallas", "mxu"),
    "hop": ("xla", "pallas", "pallas-mxu"),
    "emit": ("xla", "pallas", "pallas-mxu"),
    "selection": ("iter", "sort", "ranks"),
}

DEFAULT_TABLE_PATH = os.path.join(os.path.dirname(__file__),
                                  "dispatch_table.json")

_COEFF_KEYS = (
    "mem_gbps",             # effective HBM/stream bandwidth
    "gather_ns_per_index",  # XLA gather cost (measured ~7 ns on v5e)
    "sort_ns_per_elem",     # variadic-sort comparator cost per element
    "mxu_gflops",           # usable matmul rate for the one-hot selects
    "onehot_streamed",      # bool: price the one-hot operand as streamed
                            # (worst-case lowering) instead of resident
    "pallas_overhead",      # multiplier on Pallas-kernel estimates (the
                            # interpret emulation on CPU is ~1000x)
    "sel_elem_ns",          # selection elementwise cost per element
    "sel_sort_factor",      # sort-threshold work multiplier
    "sel_ranks_factor",     # O(K^2) comparison-rank work multiplier
    "sel_serial_us",        # per-sequential-pass latency (iter argmax)
)

_TABLE_CACHE: dict = {}


class DispatchTableError(ValueError):
    """The dispatch table failed to parse or misses required keys."""


def clear_table_cache() -> None:
    """Drop cached tables (tests that flip GRAFT_DISPATCH_TABLE)."""
    _TABLE_CACHE.clear()


def _validate(table: dict, path: str) -> dict:
    if not isinstance(table, dict) or "platforms" not in table:
        raise DispatchTableError(f"{path}: no 'platforms' mapping")
    if int(table.get("version", 0)) < 1:
        raise DispatchTableError(f"{path}: missing/zero 'version'")
    for plat, entry in table["platforms"].items():
        coeff = entry.get("coefficients", {})
        missing = [k for k in _COEFF_KEYS if k not in coeff]
        if missing:
            raise DispatchTableError(
                f"{path}: platform {plat!r} misses coefficients {missing}")
        for op in entry.get("quarantined", {}):
            if op not in OPS:
                raise DispatchTableError(
                    f"{path}: platform {plat!r} quarantines unknown op "
                    f"{op!r}")
    return table


def load_table(path: str | None = None) -> dict:
    """The active dispatch table: ``path`` arg, else the
    ``GRAFT_DISPATCH_TABLE`` env override, else the shipped default.
    Cached per path — the table is jit-static configuration, not state."""
    path = path or os.environ.get("GRAFT_DISPATCH_TABLE") \
        or DEFAULT_TABLE_PATH
    cached = _TABLE_CACHE.get(path)
    if cached is not None:
        return cached
    with open(path) as f:
        table = _validate(json.load(f), path)
    _TABLE_CACHE[path] = table
    return table


def platform_fingerprint() -> dict:
    """What a calibrated table is stamped with — enough to refuse to
    stand in for a different chip/runtime (scripts/calibrate_dispatch.py
    writes it; bench journals carry the same discipline)."""
    import jax
    dev = jax.devices()[0]
    return {"platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", ""),
            "jax": jax.__version__}


def _entry(table: dict, backend: str) -> dict:
    plats = table["platforms"]
    return plats.get(backend) or plats.get("default") or plats["cpu"]


# --- analytic per-formulation cost models (milliseconds per call) ---------
#
# Shapes are jit-static ints; costs are host floats. The models reuse the
# honest inventories of ops/mxutake.cost_model and PERF_MODEL.md's phase
# accounting — bytes at mem_gbps, indices at gather_ns, sort elements at
# sort_ns, one-hot FLOPs at mxu_gflops (plus the streamed-operand bytes
# when the platform prices the pessimistic lowering).

def _t_mem(nbytes: float, c: dict) -> float:
    return nbytes / (c["mem_gbps"] * 1e9) * 1e3


def _t_gather(indices: float, c: dict) -> float:
    return indices * c["gather_ns_per_index"] * 1e-6


def _t_sort(elems: float, lanes: int, c: dict) -> float:
    # a variadic sort carries extra payload lanes almost free (measured
    # on the live window); 15%/lane covers the extra payload moves
    return elems * c["sort_ns_per_elem"] * 1e-6 * (1 + 0.15 * max(0, lanes - 1))


def _t_mxu(model: dict, c: dict) -> float:
    t = model["flops"] / (c["mxu_gflops"] * 1e9) * 1e3
    t += _t_mem(model["table_bytes"] + model["out_bytes"]
                + model.get("select_bytes", 0), c)
    if c.get("onehot_streamed"):
        t += _t_mem(model["onehot_bytes"] + model["lane_bytes"], c)
    return t


def _cost_edge_permute(form: str, c: dict, n: int, k: int,
                       itemsize: int = 4, have_sort_key: bool = True,
                       **_: object) -> float:
    from .mxutake import cost_model_payload
    r = n * k
    if form == "scalar":
        return _t_gather(r, c) + _t_mem(r * (2 * itemsize + 8), c)
    if form == "rows":
        # the row fetch is STILL an r-index gather (just of whole rows) —
        # exactly why the live window measured rows at ~24.7 ms vs the
        # model's bytes-only 2 ms — plus the [N, K, K] temporary
        return _t_gather(r, c) \
            + _t_mem(n * k * k * itemsize * 2 + r * (itemsize + 8), c)
    if form == "sort":
        if not have_sort_key:
            return math.inf
        return _t_sort(r, 1, c) + _t_mem(r * (itemsize + 4) * 2, c)
    if form == "pallas":
        return (_t_mem(n * k * itemsize * 3, c)
                + _t_gather(r, c) * 0.2) * c["pallas_overhead"]
    if form == "mxu":
        if itemsize != 4:
            return math.inf
        return _t_mxu(cost_model_payload(n, k), c)
    return math.inf


def _cost_words(form: str, c: dict, w: int, n: int, k: int,
                itemsize: int = 4, have_sort_key: bool = True,
                **_: object) -> float:
    from .mxutake import cost_model
    r = n * k
    m = 32 * w
    if form == "scalar":
        return _t_gather(w * r, c) + _t_mem(w * r * itemsize * 2, c)
    if form == "rows":
        # row gather of r neighbor rows + [N, M] bool planes + the
        # [N, K, M] row temporary (write + read)
        return _t_gather(r, c) \
            + _t_mem(n * m + n * k * m * 2 + w * r * itemsize, c)
    if form == "sort":
        if not have_sort_key:
            return math.inf
        return _t_sort(r, w, c) + _t_mem(w * r * itemsize * 2, c)
    if form == "pallas":
        return _t_mem(w * n * itemsize + w * r * itemsize, c) \
            * c["pallas_overhead"]
    if form == "mxu":
        if itemsize != 4:
            return math.inf
        return _t_mxu(cost_model(n, r, w), c)
    return math.inf


def _cost_edge_packed(form: str, c: dict, n: int, k: int, b: int,
                      **_: object) -> float:
    from .mxutake import cost_model
    r = n * k
    n_groups = (b + 31) // 32
    wb = (b * k + 31) // 32
    if form in ("scalar", "rows"):
        return n_groups * _cost_edge_permute(form, c, n, k, itemsize=4)
    if form == "sort":
        # the packed exchange always computes its own destination keys
        return _t_sort(r, n_groups, c) + _t_mem(n_groups * r * 8, c)
    if form == "pallas":
        return _t_mem(n * wb * 4 * 3, c) * c["pallas_overhead"]
    if form == "mxu":
        # one wb-word take + the plain-XLA bit-extract passes (b selects
        # over the fetched [WB, N, K] rows)
        return _t_mxu(cost_model(n, r, wb), c) + _t_mem(b * r / 2, c)
    return math.inf


def _cost_hop(form: str, c: dict, w: int, n: int, k: int,
              **_: object) -> float:
    from .mxutake import cost_model
    r = n * k
    if form == "xla":
        # the best available words gather + the 5-pass K-prefix scan and
        # the bit-set accumulators (PERF_MODEL.md pre-surgery inventory)
        gather = min(_cost_words(f, c, w, n, k) for f in
                     ("scalar", "rows", "sort"))
        return gather + _t_mem(9 * w * k * n * 4, c)
    if form == "pallas":
        return (_t_mem(w * n * 4 + w * r, c) + _t_gather(r, c) * 0.2) \
            * c["pallas_overhead"]
    if form == "pallas-mxu":
        return (_t_mxu(cost_model(n, r, w), c) + _t_mem(w * n * 4, c)) \
            * c["pallas_overhead"]
    return math.inf


def _cost_emit(form: str, c: dict, w: int, n: int, k: int,
               **_: object) -> float:
    from .mxutake import cost_model
    r = n * k
    if form == "xla":
        gather = min(_cost_words(f, c, w, n, k) for f in
                     ("scalar", "rows", "sort"))
        return gather + _t_mem(3 * k * w * n * 4, c)
    if form == "pallas":
        return (_t_mem(w * n * 4 + w * r, c) + _t_gather(r, c) * 0.2) \
            * c["pallas_overhead"]
    if form == "pallas-mxu":
        return (_t_mxu(cost_model(n, r, w), c) + _t_mem(w * n * 4, c)) \
            * c["pallas_overhead"]
    return math.inf


# nominal row count for selection ranking: the resolver does not know its
# caller's row count (it never did), so ranking uses a fixed nominal —
# keeping dispatch a pure function of (table, k, max_count)
_SEL_ROWS = 4096


def _cost_selection(form: str, c: dict, k: int,
                    max_count: int | None = None, **_: object) -> float:
    e = c["sel_elem_ns"] * 1e-6
    if form == "iter":
        if max_count is None or max_count >= k:
            return math.inf
        return max_count * k * _SEL_ROWS * e \
            + max_count * c["sel_serial_us"] * 1e-3
    if form == "sort":
        return (k * k / 2) * _SEL_ROWS * e * c["sel_sort_factor"]
    if form == "ranks":
        return k * k * _SEL_ROWS * e * c["sel_ranks_factor"]
    return math.inf


_COST_FNS = {
    "edge_permute": _cost_edge_permute,
    "words": _cost_words,
    "edge_packed": _cost_edge_packed,
    "hop": _cost_hop,
    "emit": _cost_emit,
    "selection": _cost_selection,
}


def _measured_ms(entry: dict, op: str, shape: dict) -> dict:
    """Measured per-formulation timings for the closest recorded shape
    bucket, or {}. A record only matches when every shared numeric dim is
    within 2x; the closest (min sum of |log ratio|) wins — deterministic
    for a fixed table."""
    best, best_d = {}, math.inf
    for rec in entry.get("measured", ()):
        if rec.get("op") != op:
            continue
        rshape = rec.get("shape", {})
        d = 0.0
        ok = True
        for dim, val in rshape.items():
            have = shape.get(dim)
            if not isinstance(val, (int, float)) or have in (None, 0) \
                    or val <= 0:
                continue
            ratio = have / val
            if ratio > 2.0 or ratio < 0.5:
                ok = False
                break
            d += abs(math.log(ratio))
        if ok and d < best_d:
            best, best_d = rec.get("ms", {}), d
    return best


def cost_ms(op: str, form: str, coeff: dict, **shape) -> float:
    """Analytic cost estimate (ms) of one ``form`` call of ``op`` at
    ``shape`` under the platform ``coeff`` — the number the ranking
    sorts by when no measured bucket matches."""
    return _COST_FNS[op](form, coeff, **shape)


def explain(op: str, backend: str | None = None,
            table: dict | None = None, **shape) -> dict:
    """{formulation: estimated/measured ms} for every non-quarantined
    candidate — the debugging/calibration view of one choose() call."""
    import jax
    backend = backend or jax.default_backend()
    table = table or load_table()
    entry = _entry(table, backend)
    quarantined = set(entry.get("quarantined", {}).get(op, ()))
    measured = _measured_ms(entry, op, shape)
    out = {}
    for form in OPS[op]:
        if form in quarantined:
            continue
        ms = measured.get(form)
        out[form] = float(ms) if ms is not None \
            else cost_ms(op, form, entry["coefficients"], **shape)
    return out


def choose(op: str, backend: str | None = None,
           table: dict | None = None, **shape) -> list:
    """Ranked formulation candidates for ``op`` at ``shape`` on
    ``backend`` (default: the active JAX backend), cheapest first.
    Quarantined formulations are excluded; exact ties break toward the
    canonical OPS order. The caller (the resolver) walks the list and
    takes the first formulation that passes its feasibility gates."""
    costs = explain(op, backend, table, **shape)
    order = {f: i for i, f in enumerate(OPS[op])}
    ranked = sorted(costs, key=lambda f: (costs[f], order[f]))
    return ranked or list(OPS[op])


def explain_bucketed(op: str, buckets, backend: str | None = None,
                     table: dict | None = None, **shape) -> dict:
    """{formulation: ms} for a degree-bucketed edge pass: the bucketed
    step (sim/bucketed.py) runs ``op`` once per bucket at that bucket's
    ``(n_rows, k_ceil)``, so a formulation's cost is the SUM of its
    per-bucket costs — a form that wins at the narrow hub bucket but
    loses at the wide tail ranks by its aggregate. Shape keys other than
    ``n``/``k`` (w, itemsize, ...) apply to every bucket."""
    totals: dict = {}
    for n_b, k_b in buckets:
        per = explain(op, backend, table,
                      **{**shape, "n": n_b, "k": k_b})
        for form, ms in per.items():
            totals[form] = totals.get(form, 0.0) + ms
    return totals


def choose_bucketed(op: str, buckets, backend: str | None = None,
                    table: dict | None = None, **shape) -> list:
    """Ranked candidates for a bucketed edge pass — choose() with the
    per-bucket-summed costs of :func:`explain_bucketed`."""
    costs = explain_bucketed(op, buckets, backend, table, **shape)
    order = {f: i for i, f in enumerate(OPS[op])}
    ranked = sorted(costs, key=lambda f: (costs[f], order[f]))
    return ranked or list(OPS[op])


def resolved_formulations(cfg) -> dict:
    """The concrete formulation every engine seam executes under ``cfg``
    — requested ``"auto"`` resolved through the dispatch table. bench.py
    stamps this into every record so sort-vs-mxu trajectory lines are
    attributable post-hoc without re-deriving the resolution logic."""
    import jax.numpy as jnp

    from .hopkernel import resolve_emit_mode, resolve_hop_mode
    from .permgather import (
        resolve_edge_packed_mode,
        resolve_mode,
        resolve_words_mode,
    )
    from .selection import resolve_selection_mode

    n, k, t = cfg.n_peers, cfg.k_slots, cfg.n_topics
    w = (cfg.msg_window + 31) // 32
    out = {
        "edge_permute": resolve_mode(cfg.edge_gather_mode, jnp.uint32, n, k,
                                     have_sort_key=True),
        "words": resolve_words_mode(cfg.edge_gather_mode, w, n, k,
                                    have_sort_key=True),
        "edge_packed": resolve_edge_packed_mode(cfg.edge_gather_mode, n, k,
                                                2 * t, extra_w=w),
        "hop": resolve_hop_mode(cfg.hop_mode, cfg, w, n, k),
        "emit": resolve_emit_mode(cfg.hop_mode, w, n, k),
        "selection": resolve_selection_mode(cfg.selection_mode, k,
                                            max_count=cfg.dhi),
    }
    if getattr(cfg, "degree_buckets", None):
        # the bucketed step resolves each per-edge seam PER BUCKET at
        # that bucket's (n_rows, k_ceil) — stamp every bucket's winners
        # so banked heavy-tail lines are attributable per degree class
        out["bucketed"] = {
            f"b{i}:{n_b}x{k_b}": {
                "edge_permute": resolve_mode(
                    cfg.edge_gather_mode, jnp.uint32, n_b, k_b,
                    have_sort_key=True),
                "words": resolve_words_mode(
                    cfg.edge_gather_mode, w, n_b, k_b, have_sort_key=True),
                "edge_packed": resolve_edge_packed_mode(
                    cfg.edge_gather_mode, n_b, k_b, 2 * t, extra_w=w),
                "selection": resolve_selection_mode(
                    cfg.selection_mode, k_b, max_count=min(cfg.dhi, k_b)),
            } for i, (n_b, k_b) in enumerate(cfg.degree_buckets)}
    return out
