"""Bit-packed message-window primitives.

The data plane packs the M-slot message window into ceil(M/32) uint32 lanes
per peer, so frontier propagation and delivery attribution are bitwise
OR/AND/popcount passes over [N, W] / [N, K, W] words instead of [N, K, M]
float temporaries. This is what makes 100k-peer ticks HBM-feasible: a full
forwarding hop touches ~N*K*W words (megabytes) rather than N*K*M floats
(gigabytes). See SURVEY.md §7 "Kernels" — the frontier scatter over mesh
edges — and BASELINE.md's heartbeats/sec target.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

U32 = jnp.uint32
popcount = jax.lax.population_count


def n_words(m: int) -> int:
    return (m + 31) // 32


def pack_bool(x: jnp.ndarray) -> jnp.ndarray:
    """bool [..., M] -> uint32 [..., ceil(M/32)] (little-endian bit order)."""
    *lead, m = x.shape
    w = n_words(m)
    pad = w * 32 - m
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((*lead, pad), x.dtype)], axis=-1)
    xr = x.reshape(*lead, w, 32).astype(U32)
    shifts = U32(1) << jnp.arange(32, dtype=U32)
    return jnp.sum(xr * shifts, axis=-1, dtype=U32)


def unpack_bool(p: jnp.ndarray, m: int) -> jnp.ndarray:
    """uint32 [..., ceil(m/32)] -> bool [..., m] (inverse of pack_bool).

    Last-axis counterpart of :func:`unpack_words` for planes stored
    peer-major-packed (the compact SimState bool planes, sim/state.py):
    bit ``j%32`` of word ``j//32`` is element ``j``."""
    *lead, w = p.shape
    if w != n_words(m):
        raise ValueError(
            f"unpack_bool: packed shape {p.shape} does not carry "
            f"ceil({m}/32)={n_words(m)} words on the last axis")
    bits = (p[..., :, None] >> jnp.arange(32, dtype=U32)) & U32(1)
    return bits.reshape(*lead, w * 32)[..., :m].astype(bool)


def pack_words(x: jnp.ndarray) -> jnp.ndarray:
    """bool [N, M] -> uint32 [W, N] (word-major, peer-minor).

    The peer axis stays minor so packed arrays tile the TPU's (8, 128)
    vector-lane layout with no padding waste — a [N, K, W] array with W=2
    minor would be padded 64x on the lane dimension.
    """
    n, m = x.shape
    w = n_words(m)
    pad = w * 32 - m
    xt = x.T                                        # [M, N]
    if pad:
        xt = jnp.concatenate([xt, jnp.zeros((pad, n), x.dtype)], axis=0)
    xr = xt.reshape(w, 32, n).astype(U32)
    shifts = (U32(1) << jnp.arange(32, dtype=U32))[None, :, None]
    return jnp.sum(xr * shifts, axis=1, dtype=U32)


def unpack_words(p: jnp.ndarray, m: int) -> jnp.ndarray:
    """uint32 [W, ...] -> bool [..., m] (inverse of pack_words)."""
    w, *rest = p.shape
    bits = (p[:, None] >> jnp.arange(32, dtype=U32)[None, :].reshape(
        (1, 32) + (1,) * len(rest))) & U32(1)
    flat = bits.reshape((w * 32,) + tuple(rest))[:m]
    return jnp.moveaxis(flat, 0, -1).astype(bool)


def gather_words_rows(x_w: jnp.ndarray, nbr: jnp.ndarray, m: int,
                      mode: str = "auto",
                      sort_key: jnp.ndarray | None = None) -> jnp.ndarray:
    """out[w, k, n] = x_w[w, nbr[n, k]] — neighbor gather of packed words.

    Formulation per ``mode`` (ops/permgather.py gather_words): on TPU the
    direct per-word scalar-index gather lowers to serialized scalar loads
    (~5ms per 480k indices measured on v5e), so ``auto`` picks the
    unpack/row-gather/repack form there (vector DMA path, ~2.5x faster at
    10k peers) and the scalar form on CPU; ``pallas`` pins the packed table
    in VMEM and skips the unpacked temporary entirely.
    """
    from .permgather import gather_words
    return gather_words(x_w, nbr, m, mode, sort_key=sort_key)


def reduce_or(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Bitwise-OR reduction along ``axis``."""
    return jax.lax.reduce(x, U32(0), jnp.bitwise_or, (axis,))


def exclusive_prefix_or(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Exclusive running OR along ``axis`` (first element -> 0).

    Used for lowest-slot first-sender attribution: slot k is the first
    sender of a message bit iff it offers the bit and no slot < k does.
    """
    incl = jax.lax.associative_scan(jnp.bitwise_or, x, axis=axis)
    zero = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, 1, axis=axis))
    return jnp.concatenate(
        [zero, jax.lax.slice_in_dim(incl, 0, x.shape[axis] - 1, axis=axis)],
        axis=axis)


def popcount_sum(x: jnp.ndarray, axis: int = -1, dtype=jnp.float32) -> jnp.ndarray:
    """Total set bits summed over the word axis."""
    return jnp.sum(popcount(x).astype(dtype), axis=axis)


def prefix_count(x: jnp.ndarray, exclusive: bool = False) -> jnp.ndarray:
    """Running count of set bools along the LAST axis (inclusive by
    default), as bit-pack + masked popcount instead of ``jnp.cumsum``.

    XLA lowers a cumsum to a reduce-window / multi-pass associative scan —
    measured ~16x slower than this formulation at the [N,T,K] heartbeat
    shapes on CPU (246 vs 15 us at 1k peers; the round-4 GRAFT
    capacity-vetting cumsums alone cost ~30% of the 1k-peer tick,
    BENCH_r03->r04). Here every output element is one masked popcount of
    its own 32-bit word plus a static per-word correction — pure
    elementwise VPU work on TPU, vectorizable on CPU, O(ceil(K/32)) words
    per element."""
    return prefix_count_words(pack_bool(x), x.shape[-1], exclusive)


def prefix_count_words(packed: jnp.ndarray, k: int,
                       exclusive: bool = False) -> jnp.ndarray:
    """:func:`prefix_count` on an ALREADY-PACKED ``[..., ceil(k/32)]`` u32
    input -> ``[..., k]`` int32 — for callers that hold the packed words
    anyway (the budgeted-IWANT scan masks packed offer words per step;
    re-packing its unpacked view would pay an O(N*M) pack per scan step)."""
    w = n_words(k)
    if packed.shape[-1] != w:
        # not assert: -O must not strip the packed-width contract guard —
        # a wrong-width caller would get silently wrong prefix counts
        raise ValueError(
            f"prefix_count_words: packed shape {packed.shape} does not "
            f"carry ceil({k}/32)={w} words on the last axis")
    kidx = jnp.arange(k)
    word_of = kidx // 32
    nbits = (kidx % 32).astype(U32) + (U32(0) if exclusive else U32(1))
    # bits of the element's own word at or below it ("below" when
    # exclusive); nbits=32 -> whole word (shift guarded: 1<<32 is UB)
    own_mask = jnp.where(nbits >= 32, U32(0xFFFFFFFF),
                         (U32(1) << jnp.minimum(nbits, U32(31))) - U32(1))
    own_word = jnp.zeros_like(packed[..., :1])         # [..., 1] -> bcast [..., K]
    total = jnp.zeros(packed.shape[:-1] + (k,), jnp.int32)
    for wi in range(w):                                # static, w = ceil(K/32)
        wrd = packed[..., wi:wi + 1]
        own_word = jnp.where(word_of == wi, wrd, own_word)
        if wi < w - 1:                                 # full words strictly below
            total = total + jnp.where(word_of > wi,
                                      popcount(wrd).astype(jnp.int32), 0)
    return total + popcount(own_word & own_mask).astype(jnp.int32)
