"""Batched peer gater: Random-Early-Drop admission over neighbor slots.

Vectorized twin of routers/peer_gater.py (mirroring peer_gater.go:119-363):

- Global per-receiver ``validate``/``throttle`` counters decay with
  ``gater_global_decay``; per-source deliver/duplicate/ignore/reject stats
  decay with ``gater_source_decay`` (peer_gater.go:219-259 ``decayStats``).
- ``accept_data`` reproduces ``AcceptFrom`` (peer_gater.go:320-363): gate off
  when quiet for ``gater_quiet_ticks``, throttle is zero, or
  throttled/validated sits under ``gater_threshold``; otherwise admit data
  with probability (1 + deliver) / (1 + weighted total) per source, else
  strip to control-only (AcceptControl, gossipsub.go:604-608: the router
  keeps processing IHAVE/GRAFT but drops the payloads).
- The reference keys source stats by IP so colocated sybils share one stats
  record; the sim keeps stats per neighbor slot (each sybil connection builds
  its own record) and leaves colocation punishment to P6.

Throttle events come from the validation admission cap
(``validation_queue_cap``, modeling validation.go:246-260 drop-on-full),
charged in ops/propagate.py where arrivals are counted.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig
from ..sim.state import SimState


def gater_decay(state: SimState, cfg: SimConfig) -> SimState:
    """Per-tick stat decay (peer_gater.go:219-259); DecayInterval == 1 tick.

    The reference skips decay for disconnected sources and expires their
    stats after ``RetainStats``; the sim decays every slot uniformly — a
    down slot's stats keep decaying toward zero, which is the same limit the
    reference reaches by deletion.
    """
    z = cfg.decay_to_zero

    def dec(v, factor):
        v = v * factor
        return jnp.where(v < z, 0.0, v)

    return state._replace(
        gater_validate=dec(state.gater_validate, cfg.gater_global_decay),
        gater_throttle=dec(state.gater_throttle, cfg.gater_global_decay),
        gater_deliver=dec(state.gater_deliver, cfg.gater_source_decay),
        gater_duplicate=dec(state.gater_duplicate, cfg.gater_source_decay),
        gater_ignore=dec(state.gater_ignore, cfg.gater_source_decay),
        gater_reject=dec(state.gater_reject, cfg.gater_source_decay))


def accept_data(state: SimState, cfg: SimConfig, key: jax.Array,
                noise: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N, K] bool: receiver n admits DATA from the peer in slot k this tick
    (AcceptFrom, peer_gater.go:320-363). Control always flows.

    ``noise`` substitutes pre-drawn uniform [0, 1) noise of [N, K] shape
    for the internal draw (``key`` then unused) — see
    ops/selection.select_random; same bucketed dense-RNG discipline."""
    n, k = state.gater_deliver.shape
    quiet = (state.tick - state.gater_last_throttle) > cfg.gater_quiet_ticks
    ratio_low = (state.gater_validate != 0.0) & \
        (state.gater_throttle / jnp.maximum(state.gater_validate, 1e-9)
         < cfg.gater_threshold)
    gate_off = quiet | (state.gater_throttle == 0.0) | ratio_low      # [N]

    total = (state.gater_deliver
             + cfg.gater_duplicate_weight * state.gater_duplicate
             + cfg.gater_ignore_weight * state.gater_ignore
             + cfg.gater_reject_weight * state.gater_reject)          # [N, K]
    p = (1.0 + state.gater_deliver) / (1.0 + total)
    if noise is None:
        noise = jax.random.uniform(key, (n, k))
    draw = noise < p
    return gate_off[:, None] | (total == 0.0) | draw
