"""Batched message propagation: publish, eager mesh forwarding, lazy gossip.

Models the reference's data path — Publish fan-out (gossipsub.go:975-1045),
per-hop forwarding through mesh members, IHAVE emission over the mcache
gossip window + IWANT pull (gossipsub.go:630-739, 1711-1775) — as frontier
expansion over the padded adjacency:

- Message "wire transfer" between heartbeats is ``prop_substeps`` frontier
  hops per tick (a message crosses the mesh in milliseconds between 1s
  heartbeats; the hop bound plays the role of network latency).
- The mcache ring (mcache.go) is derived state: a message is in a peer's
  gossip window iff it was delivered within ``history_gossip`` ticks.
- IWANT pulls resolve with a one-tick delay through ``iwant_pending``
  (slot of the chosen IHAVE sender, lowest-slot deterministic choice vs the
  reference's random pick, gossip_tracer.go:53). Unanswered pulls are broken
  gossip promises: one P7 behaviour-penalty point per broken message id
  (gossip_tracer.go:79-115 GetBrokenPromises → gossipsub.go:1620-1625
  applyIwantPenalties).
- Delivery bookkeeping feeds the score counters exactly where the reference's
  RawTracer hooks fire: first deliveries (score.go:920-947), same-window
  duplicates from mesh members (score.go:949-981), invalid deliveries
  (score.go:899-918 RejectMessage → P4).
- Receive gating: data from peers scored below ``graylist_threshold`` is
  ignored (AcceptFrom, gossipsub.go:598-609), and IHAVE from peers below
  ``gossip_threshold`` is ignored (gossipsub.go:634-645) — both use the
  RECEIVER's score of the sender. The per-tick IWANT budget enforces
  MaxIHaveLength flood protection (gossipsub.go:654-676).
- Adversaries (``state.malicious``): publish invalid messages, advertise the
  entire live window, never answer IWANTs, and accept/forward anything —
  the gossipsub_spam_test.go actor behaviors as peer attributes.

Memory/layout: the message window lives in uint32 bitmask words in
**word-major, peer-minor** layout ([W, N] and [W, K, N]; ops/bits.py), so a
forwarding hop is W per-word neighbor gathers plus a handful of bitwise
passes that tile the TPU vector lanes with zero padding waste. Per-slot
score attribution happens once per tick on OR-accumulated event sets, which
is exact because each (receiver, message) first-delivery and each
(receiver, sender, message) duplicate occurs at most once per tick
(frontier semantics: a peer forwards a message the hop after it first
receives it).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .bits import (
    U32,
    exclusive_prefix_or,
    gather_words_rows,
    n_words,
    pack_bool,
    pack_words,
    popcount_sum,
    prefix_count_words,
    reduce_or,
    unpack_words,
)
from . import gater
from .heartbeat import edge_gather
from .score_ops import decayed
from .selection import select_random


def publish(state: SimState, cfg: SimConfig, publishers: jnp.ndarray,
            topics: jnp.ndarray, key: jax.Array | None = None,
            corrupt: jnp.ndarray | None = None) -> SimState:
    """Start ``P`` new messages this tick, rotating through message slots.

    publishers: [P] int32 peer ids; topics: [P] int32 topic ids. Slot reuse
    resets the per-peer seen state (the timecache TTL analogue: a slot lives
    msg_window // publishers_per_tick ticks). Publishers not subscribed to
    their topic stamp ``fanout_lastpub`` (gossipsub.go:1007-1018: publish to
    fanout, record lastpub). Malicious publishers emit invalid messages;
    a ``cfg.ignore_fraction`` of honest messages draw validation verdict
    IGNORE (validation.go:344-370 ValidationIgnore). ``corrupt`` ([P] bool,
    sim/faults.py) marks honest publishes corrupted in flight: honest
    receivers REJECT them and charge P4 (score.go:899-918), exactly like a
    sybil's invalid publish — but originating from an honest peer.
    """
    p = publishers.shape[0]
    m = cfg.msg_window
    if p > m:
        # more publishes than window slots would alias slots WITHIN one
        # batch: the message-table .set writes become last-writer races
        # and the packed seen-set scatter-add below carries into adjacent
        # bits (its exactness rests on distinct slots per batch)
        raise ValueError(
            f"publish: {p} publishers per tick exceed msg_window={m}; "
            "message slots must be distinct within one batch")
    slots = (state.tick * p + jnp.arange(p)) % m

    invalid_pub = state.malicious[publishers]
    if corrupt is not None:
        # OR is exact: a malicious publish is invalid already, so whether
        # the caller pre-masked corrupt draws against malicious publishers
        # (engine.step does, for honest FAULT_CORRUPT flag accounting)
        # cannot change message validity
        invalid_pub = invalid_pub | corrupt
    msg_topic = state.msg_topic.at[slots].set(topics)
    msg_publish_tick = state.msg_publish_tick.at[slots].set(state.tick)
    msg_invalid = state.msg_invalid.at[slots].set(invalid_pub)
    if cfg.ignore_fraction > 0.0 and key is not None:
        ign = (jax.random.uniform(key, (p,)) < cfg.ignore_fraction) \
            & ~state.malicious[publishers]
    else:
        ign = jnp.zeros((p,), bool)
    msg_ignored = state.msg_ignored.at[slots].set(ign)
    msg_publisher = state.msg_publisher.at[slots].set(publishers)
    if cfg.record_provenance:
        deliver_from = state.deliver_from.at[:, slots].set(-1)
    else:
        deliver_from = state.deliver_from      # dormant buffer, no hot-path op
    # reset recycled slots, then mark the publisher as having it. The
    # seen-set is stored packed ([N, W] u32, sim/state.py): clearing is a
    # word-AND against the recycled slots' bit mask (elementwise — shard-
    # friendly under the peer-sharded step), setting is a scatter-add of
    # the publisher's slot bit (exact: the bits were just cleared and the
    # slots of one publish batch are distinct, so added bits never carry)
    clear_w = pack_bool(jnp.zeros((1, m), bool).at[0, slots].set(True))[0]
    have = state.have & ~clear_w[None, :]
    have = have.at[publishers, slots // 32].add(
        U32(1) << (slots % 32).astype(U32))
    deliver_tick = state.deliver_tick.at[:, slots].set(NEVER)
    deliver_tick = deliver_tick.at[publishers, slots].set(state.tick)
    iwant_pending = state.iwant_pending.at[:, slots].set(-1)
    # fanout lastpub for non-subscribed publishers
    sub_pub = state.subscribed[publishers, topics]
    cur_lp = state.fanout_lastpub[publishers, topics]
    fanout_lastpub = state.fanout_lastpub.at[publishers, topics].set(
        jnp.where(sub_pub, cur_lp, state.tick))
    return state._replace(msg_topic=msg_topic, msg_publish_tick=msg_publish_tick,
                          msg_invalid=msg_invalid, msg_ignored=msg_ignored,
                          msg_publisher=msg_publisher,
                          have=have, deliver_tick=deliver_tick,
                          deliver_from=deliver_from,
                          iwant_pending=iwant_pending,
                          fanout_lastpub=fanout_lastpub)


def _edge_forward_mask(state: SimState, cfg: SimConfig, key: jax.Array,
                       fwd_send: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N, T, K] receiver-view forwarding mask: slot s's peer would forward a
    topic-t message to me. Router-variant dispatch (static)."""
    n, t, k = state.mesh.shape
    conn = state.connected[:, None, :]
    my_sub = state.subscribed[:, :, None]
    if cfg.router == "gossipsub":
        # sender forwards along ITS mesh edges (gossipsub.go:1020-1035); a
        # non-subscribed publisher sends along its fanout (gossipsub.go:1007);
        # the engine passes the receiver view pre-gathered by the heartbeat's
        # shared permutation gather, direct callers pay for their own
        if fwd_send is not None:
            return fwd_send
        send = state.mesh | (state.fanout & ~state.subscribed[:, :, None])
        return edge_gather(send, state, mode=cfg.edge_gather_mode)
    if cfg.router == "floodsub":
        # sender forwards to every subscribed neighbor (floodsub.go:76-100)
        return conn & my_sub
    if cfg.router == "randomsub":
        # sender forwards to EXACTLY max(D, ceil(sqrt N)) random topic peers
        # (randomsub.go:124-143): a uniform sample without replacement from
        # its connected subscribed neighbors, taken sender-side, then viewed
        # from the receiver through the edge permutation
        target = max(cfg.d, math.ceil(math.sqrt(cfg.n_peers)))
        cand = state.connected[:, None, :] & state.nbr_subscribed   # sender view
        sel = select_random(cand, jnp.full((n, t), target), key,
                            max_count=min(target, cfg.k_slots),
                            mode=cfg.selection_mode)
        return edge_gather(sel, state,
                           mode=cfg.edge_gather_mode) & conn & my_sub
    raise ValueError(f"unknown router {cfg.router!r}")


def _edge_topic_bits(mask_ntk: jnp.ndarray, topic_bits: jnp.ndarray,
                     w: int) -> jnp.ndarray:
    """Expand a per-(peer, topic, slot) edge mask into packed per-edge message
    words: out[w,k,n] = OR over topics t with mask[n,t,k] of topic_bits[t,w].

    Topic message sets are disjoint, so OR == sum; T is small and static.
    """
    n, t, k = mask_ntk.shape
    acc = jnp.zeros((w, k, n), U32)
    for ti in range(t):
        acc = acc | jnp.where(mask_ntk[:, ti, :].T[None, :, :],
                              topic_bits[ti][:, None, None], U32(0))
    return acc


def _slot_bitplanes(pend: jnp.ndarray, k: int) -> jnp.ndarray:
    """iwant_pending [N, M] (slot id or -1) -> packed per-slot ask sets
    [W, K, N]: bit m of out[:, s, n] iff pend[n, m] == s.

    Encoded via ceil(log2 K) packed bit-planes of the slot index, so no
    [N, K, M] temporary is materialized.
    """
    n, m = pend.shape
    nbits = max(1, (k - 1).bit_length())
    valid = pack_words(pend >= 0)                              # [W, N]
    planes = [pack_words((pend > -1) & (((pend >> b) & 1) == 1))
              for b in range(nbits)]                           # each [W, N]
    out = jnp.broadcast_to(valid[:, None, :], (valid.shape[0], k, n))
    for b in range(nbits):
        kbit = ((jnp.arange(k) >> b) & 1).astype(bool)[None, :, None]
        match = jnp.where(kbit, planes[b][:, None, :], ~planes[b][:, None, :])
        out = out & match
    return out


def _bits_to_slot(chosen: jnp.ndarray, m: int) -> jnp.ndarray:
    """Packed disjoint per-slot sets [W, K, N] -> [N, M] slot id or -1
    (inverse of _slot_bitplanes), again via bit-planes."""
    w, k, n = chosen.shape
    nbits = max(1, (k - 1).bit_length())
    any_bits = reduce_or(chosen, axis=1)                       # [W, N]
    slot = jnp.zeros((n, m), jnp.int32)
    for b in range(nbits):
        kbit = ((jnp.arange(k) >> b) & 1).astype(U32)[None, :, None]
        plane = reduce_or(chosen * kbit, axis=1)               # [W, N]
        slot = slot + (unpack_words(plane, m).astype(jnp.int32) << b)
    return jnp.where(unpack_words(any_bits, m), slot, -1)


def forward_tick(state: SimState, cfg: SimConfig, tp: TopicParams,
                 inc_gossip: jnp.ndarray, scores: jnp.ndarray,
                 key: jax.Array, *,
                 fwd_send: jnp.ndarray | None = None,
                 answers_k: jnp.ndarray | None = None,
                 link_ok: jnp.ndarray | None = None,
                 dup_edges: jnp.ndarray | None = None,
                 censor_bits: jnp.ndarray | None = None) -> SimState:
    """One tick of data-plane traffic: resolve last tick's IWANTs, run
    ``prop_substeps`` forwarding hops, then emit this tick's IHAVE/IWANT.

    ``scores`` is the heartbeat's [N, K] score cache (receiver's score of the
    peer in slot k), used for accept/gossip gating. ``inc_gossip`` and
    ``fwd_send`` are receiver views pre-gathered by the heartbeat's shared
    edge-permutation gather (HeartbeatOut); ``fwd_send=None`` makes the
    gossipsub path gather its own. Admission control layers,
    outermost first (matching handleIncomingRPC, pubsub.go:1029-1105):

    1. graylist: score < graylist_threshold drops everything (AcceptFrom,
       gossipsub.go:598-609);
    2. peer gater RED drop (``cfg.gater_enabled``): data stripped to
       control-only per ops/gater.py (peer_gater.go:320-363);
    3. per-edge queue capacity (``cfg.edge_queue_cap``): a hop whose RPC
       would exceed the edge's per-tick message budget is dropped whole
       (comm.go:156-191 drop-on-full, traced gossipsub.go:1195-1202);
    4. validation admission (``cfg.validation_queue_cap``): new arrivals
       beyond the per-receiver budget are throttled — dropped unseen and
       charged to the gater throttle stat (validation.go:246-260).

    Validation verdicts: ACCEPT delivers + forwards; REJECT marks seen +
    counts P4 + gater reject; IGNORE marks seen only + gater ignore
    (validation.go:344-370).

    Fault injection (sim/faults.py): ``link_ok`` ([N, K] bool) is the
    tick's lossy-link draw, ANDed into the data admission like a gater RED
    drop — eager forwards, flood publishes, and pull answers on a dropped
    edge vanish in flight, control still flows, and no P7 broken promise
    is charged (the answer existed; the link ate it). ``dup_edges``
    ([N, K] bool) makes mesh edges re-offer their recent deliveries on hop
    0, landing as seen-cache hits in the mesh-duplicate (P3 credit) and
    gater-duplicate stats — a re-transmitted RPC, not new traffic.

    ``censor_bits`` ([W, N] packed words, sim/faults.py censor_word_mask)
    marks the message slots each SENDER suppresses this tick (the
    censorship attack): a censor neither advertises (IHAVE window), nor
    answers pulls for, nor forwards a censored message — but still
    receives it. An unanswered pull for a censored message IS a broken
    promise: the asker charges P7 exactly as for a malicious non-answer
    (the score-gamed censor pays in behaviour penalty), and withheld mesh
    forwarding starves the censor's P3 credit — the scoring response the
    adversary contracts assert on. Requires the non-fused hop
    (ops/hopkernel.py gates Pallas out under a censor plan: the per-sender
    frontier mask cannot enter the fused kernel).
    """
    n, t, k = state.mesh.shape
    m = cfg.msg_window
    w = n_words(m)
    k_fwd, k_gate = jax.random.split(key)
    nbr = jnp.clip(state.neighbors, 0, n - 1)                  # [N, K]
    mal = state.malicious
    # destination keys for the sort-permute gathers (edge_sort_key
    # docstring): computed once, shared by every gather this tick (XLA
    # CSEs the duplicates; unused on backends that resolve away from sort)
    from ..parallel.kernel_context import current_kernel_mesh
    from .permgather import edge_sort_key, resolve_words_mode
    sk_w = edge_sort_key(state.neighbors, state.reverse_slot, k_major=True)
    _ctx = current_kernel_mesh()
    _halo = (_ctx is not None and _ctx.route == "halo"
             and resolve_words_mode(cfg.edge_gather_mode, w, n, k,
                                    have_sort_key=True) == "sort")

    def gw(table):
        """The per-tick words gather: halo-routed under a sharded step
        when configured, else the mode-dispatched gather."""
        if _halo:
            from ..parallel.halo import route_words_halo
            return route_words_halo(table, state.neighbors,
                                    state.reverse_slot)
        return gather_words_rows(table, nbr, m, cfg.edge_gather_mode,
                                 sort_key=sk_w)

    # --- per-tick packed masks ---
    age_pub = state.tick - state.msg_publish_tick
    alive = (age_pub >= 0) & (age_pub < cfg.history_length)             # [M]
    t_m = jnp.clip(state.msg_topic, 0, t - 1)
    live_topic = (state.msg_topic >= 0) & alive
    # [T, W]: per-topic live message sets (disjoint across topics)
    topic_bits = pack_bool((t_m[None, :] == jnp.arange(t)[:, None])
                           & live_topic[None, :])
    alive_bits = pack_bool(alive[None, :])[0]                           # [W]
    invalid_bits = pack_bool((state.msg_invalid & alive)[None, :])[0]
    ignored_bits = pack_bool((state.msg_ignored & alive)[None, :])[0]
    valid_msg_bits = alive_bits & ~invalid_bits & ~ignored_bits
    # per-receiver deliverability: honest peers deliver only ACCEPT-verdict
    # messages (validation.go:293-370); malicious receivers accept + forward
    # anything. P4 charges REJECT only; IGNORE is seen-not-delivered.
    vm = jnp.where(mal[None, :], alive_bits[:, None],
                   valid_msg_bits[:, None])                             # [W,N]
    inv_n = jnp.where(mal[None, :], U32(0), invalid_bits[:, None])      # [W,N]
    ign_n = jnp.where(mal[None, :], U32(0), ignored_bits[:, None])      # [W,N]

    have_bits = state.have.T                    # [W,N] (stored packed)
    dlv_bits = pack_words(state.deliver_tick < NEVER)                   # [W,N]
    dlv_start = dlv_bits
    n_have_start = popcount_sum(have_bits, axis=(0, 1))

    if cfg.scoring_enabled:
        accept_ok = scores >= cfg.graylist_threshold      # [N,K] AcceptFrom
        gossip_ok = scores >= cfg.gossip_threshold        # [N,K] handleIHave
    else:
        accept_ok = jnp.ones((n, k), bool)
        gossip_ok = jnp.ones((n, k), bool)
    # gater RED admission for DATA (control still flows); malicious
    # receivers run no gater of their own
    if cfg.gater_enabled:
        data_ok = accept_ok & (gater.accept_data(state, cfg, k_gate)
                               | mal[:, None])
    else:
        data_ok = accept_ok
    if link_ok is not None:
        # lossy links drop the edge's DATA plane for the tick (faults
        # docstring above); receiver-side like every admission layer
        data_ok = data_ok & link_ok

    # Delivery-event accumulators are per-topic COUNTS, not [W,K,N] bit
    # sets (PERF_MODEL.md S3): frontier semantics make each
    # (receiver, sender-slot, message) event occur in at most one hop, so
    # per-hop popcounts summed across hops equal the popcount of the OR'd
    # sets. ``cfg.count_dtype`` picks the width: uint8 minimizes HBM
    # bytes (safe: events per (topic, slot, receiver) per tick are
    # bounded by the message window); int32 trades bytes for native
    # vector lanes (config.py note).
    if cfg.count_dtype not in ("uint8", "int32"):
        raise ValueError(
            f"count_dtype={cfg.count_dtype!r}: only 'uint8' and 'int32' "
            "are supported (numpy shorthands like 'u8' parse as OTHER "
            "widths and would silently defeat the knob)")
    cdt = jnp.dtype(cfg.count_dtype)
    if m > jnp.iinfo(cdt).max:
        # not assert: -O must not strip the overflow guard
        raise ValueError(
            f"msg_window={m} > {jnp.iinfo(cdt).max} would wrap the "
            f"{cfg.count_dtype} hop-count accumulators; shrink the window "
            "or widen count_dtype")

    def topic_counts(events_wkn):
        """[W,K,N] packed event bits -> [T,K,N] per-topic counts.
        (jnp.sum promotes sub-word accumulation to uint32, so cast back.)"""
        return jnp.stack([
            popcount_sum(events_wkn & topic_bits[ti][:, None, None],
                         axis=0, dtype=cdt)
            for ti in range(t)]).astype(cdt)

    # -- step 1: resolve pending IWANTs from last tick (gossipsub.go:698-739:
    # the sender answers from its mcache; delivery counts as a first delivery
    # from a non-mesh peer) --
    from .hopkernel import (
        emit_dispatch,
        hop_dispatch,
        iwant_resolve_dispatch,
        resolve_emit_mode,
        resolve_hop_mode,
    )
    hop_mode = resolve_hop_mode(cfg.hop_mode, cfg, w, n, k)
    fused_hop = hop_mode in ("pallas", "pallas-mxu")
    # pallas-mxu: the fused kernels with in-kernel gathers rewritten as the
    # gather-free two-level one-hot select (hopkernel._take_rows)
    hop_gather = "mxu" if hop_mode == "pallas-mxu" else "take"
    # malicious sources never answer IWANTs (the iwantEverything-style actor
    # holds its promises open, gossipsub_spam_test.go:23-133); honest sources
    # answer from their mcache, which rejected/ignored messages never enter
    # (deliver_tick stays NEVER on rejection — validation.go:293-370).
    # Censors additionally withhold the victim's slots (docstring above).
    answer_bits = jnp.where(mal[None, :], U32(0), dlv_bits)             # [W,N]
    if censor_bits is not None:
        answer_bits = answer_bits & ~censor_bits
    if fused_hop:
        # fused resolve (PERF_MODEL.md S6): eligibility (resolve_hop_mode)
        # guarantees the cap/throttle plumbing below is dead here
        r = iwant_resolve_dispatch(
            state.iwant_pending, answer_bits, have_bits, vm, inv_n,
            alive_bits[:, None],
            data_ok.astype(jnp.uint8), topic_bits, nbr, m=m,
            gather=hop_gather,
            interpret=jax.default_backend() != "tpu")
        got_any, got_valid_any = r.got_any, r.got_valid_any
        behaviour_penalty = state.behaviour_penalty \
            + r.broken.astype(jnp.float32).T
        have_bits = have_bits | got_any
        dlv_bits = dlv_bits | got_valid_any
        throttled = jnp.zeros((n,), jnp.int32)
        edge_used = jnp.zeros((k, n), jnp.int32)
        arrivals = jnp.zeros((n,), jnp.int32)
        validated = jnp.zeros((n,), jnp.float32)
        seed_nv, seed_ni = r.nv, r.ni
        got_k = got_valid = None
    else:
        seed_nv = seed_ni = None
        asked_k = _slot_bitplanes(state.iwant_pending, k) \
            & alive_bits[:, None, None]
        if answers_k is None:
            answers_k = gw(answer_bits)                                 # [W,K,N]
        # else: engine.step pre-routed the answer table on the heartbeat's
        # final exchange (_iwant_answer_extras) — same receiver view, one
        # fewer serially-dependent sort
        # pulled data is still data: graylist + gater admission apply, and pulls
        # are charged against the same per-edge and validation budgets as eager
        # traffic (an IHAVE-flooding adversary must not route unlimited data
        # through the pull path)
        adm_kn = jnp.where(data_ok.T[None, :, :], U32(0xFFFFFFFF), U32(0))
        got_k = asked_k & answers_k & ~have_bits[:, None, :] & adm_kn
        broken_k = asked_k & ~answers_k
        if link_ok is not None:
            # a link-eaten answer is STILL a broken promise: the reference
            # tracer charges on non-delivery at expiry whatever the cause
            # (gossip_tracer.go:79-115; the repo's host tracer mirrors
            # that), so the batched half charges P7 when the lossy link
            # ate an answer that existed — cross-half scoring parity
            # under a drop plan. Receiver-side admission drops (graylist/
            # gater/queue) keep their pre-existing not-broken treatment.
            link_kn = jnp.where(link_ok.T[None, :, :],
                                U32(0xFFFFFFFF), U32(0))
            broken_k = asked_k & ~(answers_k & link_kn)
        throttled = jnp.zeros((n,), jnp.int32)
        if cfg.edge_queue_cap > 0:
            pull_sz = popcount_sum(got_k, axis=0, dtype=jnp.int32)          # [K,N]
            got_k = jnp.where((pull_sz <= cfg.edge_queue_cap)[None, :, :],
                              got_k, U32(0))
        if cfg.validation_queue_cap > 0:
            cnt0 = popcount_sum(reduce_or(got_k, axis=1), axis=0,
                                dtype=jnp.int32)                            # [N]
            fits0 = cnt0 <= cfg.validation_queue_cap
            got_k = got_k & jnp.where(fits0, U32(0xFFFFFFFF), U32(0))[None, None, :]
            # over-budget pulls are dropped unseen and charged as throttle
            # events; the unanswered promise is NOT charged to the sender (it
            # did answer — the local queue dropped it)
            throttled = throttled + jnp.where(fits0, 0, cnt0)
        got_any = reduce_or(got_k, axis=1)                                  # [W,N]
        # pulled messages still go through the receiver's validation: deliver on
        # ACCEPT, seen-only on IGNORE (an honest publisher answers pulls for its
        # own ignore-class message), P4 on REJECT (unreachable in practice:
        # rejecting answerers are malicious and never answer)
        got_valid = got_k & vm[:, None, :]
        got_valid_any = reduce_or(got_valid, axis=1)
        # broken promises: one penalty point per unfulfilled message id
        # (gossip_tracer.go:79-115, applied gossipsub.go:1620-1625)
        behaviour_penalty = state.behaviour_penalty + \
            popcount_sum(broken_k, axis=0).T
        have_bits = have_bits | got_any
        dlv_bits = dlv_bits | got_valid_any

        # per-tick admission budgets, seeded with the (cap-masked) IWANT pulls
        edge_used = popcount_sum(got_k, axis=0, dtype=jnp.int32)            # [K,N]
        arrivals = popcount_sum(got_any, axis=0, dtype=jnp.int32)           # [N]
        validated = arrivals.astype(jnp.float32)

    # -- step 2: eager forwarding, prop_substeps hops, fully bit-packed --
    fwd_mask = _edge_forward_mask(state, cfg, k_fwd, fwd_send)
    fwd_mask = fwd_mask & data_ok[:, None, :]
    if fused_hop:
        # the fused kernel expands allowed/mesh planes in VMEM from the
        # uint8 bool planes — no [W,K,N] materialization at all
        fwd_u8 = fwd_mask.astype(jnp.uint8)
        mesh_u8 = state.mesh.astype(jnp.uint8)
        allowed = mesh_eb = None
    else:
        allowed = _edge_topic_bits(fwd_mask, topic_bits, w)             # [W,K,N]
        mesh_eb = _edge_topic_bits(state.mesh, topic_bits, w)           # [W,K,N]

    if cfg.flood_publish and cfg.router == "gossipsub":
        # WithFloodPublish (gossipsub.go:989-1004): the ORIGIN sends its own
        # publishes to every subscribed topic peer it scores >=
        # publish_threshold — direct peers bypass the score gate, and the
        # publisher itself need not be subscribed (flood replaces the fanout
        # path too). Only hop 0 carries origin messages. Sender-side values
        # (its score of me, its direct flag for me) arrive through the edge
        # permutation.
        from .permgather import permutation_gather, resolve_mode
        rk = jnp.clip(state.reverse_slot, 0, k - 1)
        sk_e = edge_sort_key(state.neighbors, state.reverse_slot,
                             k_major=False)
        _sort_e = resolve_mode(cfg.edge_gather_mode, jnp.float32, n, k,
                               have_sort_key=True) == "sort"
        if _sort_e and _ctx is not None and _ctx.route == "halo":
            from ..parallel.halo import route_payloads_halo
            ss, sd = route_payloads_halo(
                [scores, state.direct.astype(U32)],
                state.neighbors, state.reverse_slot)
            sender_scores_me = ss                                       # [N,K]
            sender_direct_me = sd.astype(bool)                          # [N,K]
        elif _sort_e:
            # both sender-side planes share one variadic sort
            _, ss, sd = jax.lax.sort(
                (sk_e, scores.reshape(-1),
                 state.direct.astype(U32).reshape(-1)), num_keys=1)
            sender_scores_me = ss.reshape(n, k)                         # [N,K]
            sender_direct_me = sd.reshape(n, k).astype(bool)            # [N,K]
        else:
            sender_scores_me = permutation_gather(
                scores, nbr, rk, cfg.edge_gather_mode)                  # [N,K]
            sender_direct_me = permutation_gather(
                state.direct.astype(U32), nbr, rk,
                cfg.edge_gather_mode).astype(bool)                      # [N,K]
        if cfg.scoring_enabled:
            score_gate = sender_direct_me | \
                (sender_scores_me >= cfg.publish_threshold)
        else:
            score_gate = jnp.ones_like(sender_direct_me)
        flood_mask = state.connected[:, None, :] & \
            state.subscribed[:, :, None] & score_gate[:, None, :] & \
            data_ok[:, None, :]
        flood_allowed = _edge_topic_bits(flood_mask, topic_bits, w)
        # origin set: slots this peer itself published this tick
        origin_bits = pack_words(
            (state.deliver_tick == state.tick)
            & (state.msg_publish_tick == state.tick)[None, :])
        flood_offer = gw(origin_bits) & flood_allowed
    else:
        flood_offer = None

    if dup_edges is not None:
        # link duplication (sim/faults.py): a duplicating mesh edge
        # re-offers the sender's recent deliveries (its mcache gossip
        # slice) on hop 0 — mostly seen-cache hits that land in the
        # mesh-duplicate/gater-duplicate stats; a receiver that missed the
        # original genuinely gets it from the retransmission. Admission
        # (graylist/gater/lossy-link) applies like any other data.
        age_d = state.tick - state.deliver_tick
        dup_window = pack_words((age_d >= 0) & (age_d < cfg.history_gossip)) \
            & alive_bits[:, None]
        if censor_bits is not None:
            dup_window = dup_window & ~censor_bits
        dup_kn = jnp.where((dup_edges & data_ok).T[None, :, :],
                           U32(0xFFFFFFFF), U32(0))
        dup_offer = gw(dup_window) & mesh_eb & dup_kn
    else:
        dup_offer = None

    # P3 duplicate-credit window (score.go:949-981): past deliveries stay
    # creditable for mesh_message_deliveries_window_ticks (default 0 = this
    # tick only; the reference default window is 10ms << 1 heartbeat)
    age_dlv = state.tick - state.deliver_tick
    window_old = pack_words((age_dlv >= 0)
                            & (age_dlv <= cfg.mesh_message_deliveries_window_ticks))

    # frontier: messages that entered this peer THIS tick (fresh publishes and
    # IWANT pulls above); peers forward a message exactly one hop after they
    # first receive it, so the per-tick event sets below are disjoint across
    # hops and per-hop counting counts each event exactly once. Accumulators
    # are seeded with the pull events so pulls share the attribution path.
    frontier = pack_words(state.deliver_tick == state.tick) | got_valid_any
    # halo-route overflow accounting across the while_loop boundary: notes
    # created OUTSIDE the loop (heartbeat exchanges, the resolve/flood
    # gathers above) drain into the initial carry; notes created INSIDE
    # the hop body drain within the body's own trace (a tracer must not
    # escape the loop); the post-loop total is re-noted for engine.step
    from ..parallel.kernel_context import (
        drain_halo_overflow, note_halo_overflow)
    halo_ovf0 = sum(drain_halo_overflow(), jnp.int32(0))
    carry0 = {
        "halo_ovf": halo_ovf0,
        "i": jnp.int32(0),
        "frontier": frontier,
        "have": have_bits,
        "dlv": dlv_bits,
        "dlv_new": got_valid_any,          # deliveries accumulated this tick
        # first-delivery / reject (P4) seed counts [T,K,N]: from the fused
        # resolve kernel, or from the XLA pull sets
        "nv": seed_nv if seed_nv is not None else topic_counts(got_valid),
        "ni": seed_ni if seed_ni is not None
        else topic_counts(got_k & inv_n[:, None, :]),
        "dup": jnp.zeros((t, k, n), cdt),        # mesh-duplicate counts
        "edge_used": edge_used,
        "arrivals": arrivals,
        "throttled": throttled,
        "validated": validated,
    }
    if cfg.gater_enabled:
        # gater-only stats compile only when the gater can consume them
        carry0["ig"] = popcount_sum(got_k & ign_n[:, None, :], axis=0,
                                    dtype=cdt).astype(cdt)  # ignore [K,N]
        carry0["gdup"] = jnp.zeros((k, n), cdt)          # any-duplicate [K,N]
    if cfg.record_provenance:
        # trace export needs the winning sender slot per first delivery —
        # the one consumer that still wants per-slot bit sets
        carry0["nv_acc"] = got_valid

    def hop(c):
        if fused_hop:
            # fused kernel (PERF_MODEL.md S4): gather + allowed/mesh
            # expansion + K-prefix winner attribution + uint8 event counts
            # in one VMEM pass; eligibility (resolve_hop_mode) guarantees
            # the cap/gater/provenance/flood paths below are dead here
            h = hop_dispatch(c["frontier"], c["have"], c["dlv"], c["dlv_new"],
                             vm, inv_n, window_old, valid_msg_bits[:, None],
                             nbr, fwd_u8, mesh_u8, topic_bits,
                             c["nv"], c["ni"], c["dup"],
                             gather=hop_gather,
                             interpret=jax.default_backend() != "tpu")
            out = dict(c)
            out.update(i=c["i"] + 1, frontier=h.new_valid, have=h.have,
                       dlv=h.dlv, dlv_new=h.dlv_new, nv=h.nv, ni=h.ni,
                       dup=h.dup,
                       halo_ovf=c["halo_ovf"]
                       + sum(drain_halo_overflow(), jnp.int32(0)))
            return out
        i, frontier, have_bits, dlv_bits, dlv_new = \
            c["i"], c["frontier"], c["have"], c["dlv"], c["dlv_new"]
        edge_used, arrivals, throttled, validated = \
            c["edge_used"], c["arrivals"], c["throttled"], c["validated"]
        is_first = i == 0
        # censors hold censored messages out of their outgoing offers;
        # the message stays in their have/frontier accounting (they DID
        # receive it) — only the sender-side visibility is masked
        src = frontier if censor_bits is None else frontier & ~censor_bits
        offered = gw(src) & allowed                                     # [W,K,N]
        if flood_offer is not None:
            offered = offered | jnp.where(is_first, flood_offer, U32(0))
        if dup_offer is not None:
            offered = offered | jnp.where(is_first, dup_offer, U32(0))
        if cfg.edge_queue_cap > 0:
            # drop-on-full, whole-RPC granularity (comm.go:156-191): the
            # hop's RPC on an edge either fits the remaining budget or drops
            rpc_size = popcount_sum(offered, axis=0, dtype=jnp.int32)   # [K,N]
            edge_fits = (edge_used + rpc_size) <= cfg.edge_queue_cap
            offered = jnp.where(edge_fits[None, :, :], offered, U32(0))
            edge_used = edge_used + jnp.where(edge_fits, rpc_size, 0)
        excl = exclusive_prefix_or(offered, axis=1)
        new_from_k = offered & ~excl & ~have_bits[:, None, :]
        new_any = (excl[:, -1] | offered[:, -1]) & ~have_bits           # [W,N]
        if cfg.validation_queue_cap > 0:
            # validation admission (validation.go:246-260): a receiver whose
            # budget this hop's arrivals would blow drops them unseen
            cnt = popcount_sum(new_any, axis=0, dtype=jnp.int32)        # [N]
            fits = (arrivals + cnt) <= cfg.validation_queue_cap
            fit_m = jnp.where(fits, U32(0xFFFFFFFF), U32(0))[None, :]
            new_any = new_any & fit_m
            new_from_k = new_from_k & fit_m[:, None, :]
            arrivals = arrivals + jnp.where(fits, cnt, 0)
            throttled = throttled + jnp.where(fits, 0, cnt)
            validated = validated + jnp.where(fits, cnt, 0).astype(jnp.float32)
        elif cfg.gater_enabled:
            # unbounded queue: everything admitted still counts as validated
            # (peer_gater.go:404-407 ValidateMessage fires per admitted msg)
            validated = validated + popcount_sum(new_any, axis=0)
        new_valid = new_any & vm
        nv_ev = new_from_k & vm[:, None, :]
        out = dict(c)
        out["nv"] = c["nv"] + topic_counts(nv_ev)
        out["ni"] = c["ni"] + topic_counts(new_from_k & inv_n[:, None, :])
        # mesh-delivery credit: any mesh sender of a message I hold valid
        # within the credit window — covers first-in-mesh (score.go:938-947)
        # and windowed duplicates (score.go:949-981). Invalid messages never
        # earn MMD, including for malicious receivers who "deliver" them: an
        # adversary's own counters about its neighbors are never consulted
        # by honest-peer defenses, and the reference's spam actors run no
        # scoring at all (gossipsub_spam_test.go drives raw streams)
        elig = (window_old | dlv_new | new_valid) & valid_msg_bits[:, None]
        out["dup"] = c["dup"] + topic_counts(offered & mesh_eb
                                             & elig[:, None, :])
        if cfg.gater_enabled:
            out["ig"] = c["ig"] + popcount_sum(
                new_from_k & ign_n[:, None, :], axis=0,
                dtype=cdt).astype(cdt)
            # gater duplicate stat: any offer of a message already seen OR
            # won by another slot this same hop (pubsub.go:1145-1148
            # seen-cache hit -> DuplicateMessage; same-hop losers hit the
            # cache the moment the winner marks it). Throttle-dropped
            # arrivals were never marked seen, so their re-offers are not
            # duplicates — new_any is post-throttle.
            out["gdup"] = c["gdup"] + popcount_sum(
                offered & ~new_from_k & (have_bits | new_any)[:, None, :],
                axis=0, dtype=cdt).astype(cdt)
        if cfg.record_provenance:
            out["nv_acc"] = c["nv_acc"] | nv_ev
        out["i"] = i + 1
        out["frontier"] = new_valid
        out["have"] = have_bits | new_any
        out["dlv"] = dlv_bits | new_valid
        out["dlv_new"] = dlv_new | new_valid
        out["edge_used"] = edge_used
        out["arrivals"] = arrivals
        out["throttled"] = throttled
        out["validated"] = validated
        out["halo_ovf"] = c["halo_ovf"] \
            + sum(drain_halo_overflow(), jnp.int32(0))
        return out

    # the hop loop is a lax.while_loop (not unrolled): one hop's code
    # compiles once, temporaries are reused across hops, the executable
    # stays small at 100k peers (the unrolled form compiled to >100MB of
    # code) — and the loop exits as soon as the frontier empties (message
    # transit takes ~graph-diameter hops, typically < prop_substeps), a
    # hop with an empty frontier being a no-op
    carry = jax.lax.while_loop(
        lambda c: (c["i"] < cfg.prop_substeps) & jnp.any(c["frontier"] != 0),
        hop, carry0)
    note_halo_overflow(carry["halo_ovf"])
    have_bits, dlv_bits = carry["have"], carry["dlv"]
    arrivals, throttled, validated = \
        carry["arrivals"], carry["throttled"], carry["validated"]

    # [T,K,N] counts -> [N,T,K] f32 counter increments
    fmd_add = jnp.transpose(carry["nv"], (2, 0, 1)).astype(jnp.float32)
    imd_add = jnp.transpose(carry["ni"], (2, 0, 1)).astype(jnp.float32)
    mmd_add = jnp.transpose(carry["dup"], (2, 0, 1)).astype(jnp.float32)

    # the delivery counters' once-per-tick write site: fold this tick's
    # decay into the update (score_ops module docstring) — stored value is
    # min(zclamp(counter * decay) + arrivals, cap), the old
    # decay-pass-then-add ordering exactly
    def t2(x):
        return x[None, :, None]
    z = cfg.decay_to_zero
    caps = tp.first_message_deliveries_cap[None, :, None], \
        tp.mesh_message_deliveries_cap[None, :, None]
    fmd = jnp.minimum(
        decayed(state.first_message_deliveries,
                t2(tp.first_message_deliveries_decay), z) + fmd_add, caps[0])
    mmd = jnp.minimum(
        decayed(state.mesh_message_deliveries,
                t2(tp.mesh_message_deliveries_decay), z) + mmd_add, caps[1])
    imd = decayed(state.invalid_message_deliveries,
                  t2(tp.invalid_message_deliveries_decay), z) + imd_add

    newly_dlv = dlv_bits & ~dlv_start
    have = have_bits.T                          # store packed ([N, W])
    new_dlv_mask = unpack_words(newly_dlv, m)
    deliver_tick = jnp.where(new_dlv_mask, state.tick, state.deliver_tick)
    delivered = popcount_sum(have_bits, axis=(0, 1)) - n_have_start

    if cfg.record_provenance:
        # winning sender slot per first delivery this tick (nv_acc holds the
        # per-slot first-delivery bit sets, pulls included) — trace export
        state = state._replace(deliver_from=jnp.where(
            new_dlv_mask, _bits_to_slot(carry["nv_acc"], m),
            state.deliver_from))

    state = state._replace(
        have=have, deliver_tick=deliver_tick,
        first_message_deliveries=fmd,
        mesh_message_deliveries=mmd,
        invalid_message_deliveries=imd,
        behaviour_penalty=behaviour_penalty,
        delivered_total=state.delivered_total + delivered)

    if cfg.gater_enabled:
        # stat attribution where the reference's RawTracer hooks fire
        # (peer_gater.go:366-453): deliver on first delivery (pulls included
        # via the seeded accumulators), duplicate on seen-cache hits,
        # ignore/reject on validation outcomes, throttle from the admission
        # budget above. Per-topic counts sum over T: the gater stats are
        # topic-blind (peer_gater.go keys them by source only).
        sum_t = lambda c: jnp.sum(c.astype(jnp.float32), axis=0).T  # noqa: E731
        state = state._replace(
            gater_deliver=state.gater_deliver + sum_t(carry["nv"]),
            gater_duplicate=state.gater_duplicate
            + carry["gdup"].astype(jnp.float32).T,
            gater_ignore=state.gater_ignore
            + carry["ig"].astype(jnp.float32).T,
            gater_reject=state.gater_reject + sum_t(carry["ni"]),
            gater_validate=state.gater_validate + validated,
            gater_throttle=state.gater_throttle + throttled.astype(jnp.float32),
            gater_last_throttle=jnp.where(throttled > 0, state.tick,
                                          state.gater_last_throttle))

    # -- step 3: IHAVE/IWANT for next tick (gossipsub.go:1711-1775) --
    # receiver view of gossip edges (pre-gathered by the heartbeat): slot
    # s's peer gossips topic t to me; ignore IHAVE from senders I score
    # below the gossip threshold; invalid slots masked for direct callers
    # that pass raw sender-view masks
    valid_slots = ((state.neighbors >= 0)
                   & (state.reverse_slot >= 0))[:, None, :]
    inc_gossip = inc_gossip & valid_slots & gossip_ok[:, None, :]
    # sender gossip window = the mcache gossip slice: DELIVERED within the
    # last history_gossip ticks (rejected messages never enter the mcache, so
    # have-but-not-delivered is excluded)
    age = state.tick - state.deliver_tick
    window_bits = pack_words((age >= 0) & (age < cfg.history_gossip)) \
        & alive_bits[:, None]
    # malicious peers advertise everything alive (IHAVE flood). Censors
    # deliberately DO advertise the victim's messages (censor_bits does
    # not mask the window): the score-gamed starvation is advertise-but-
    # never-answer — the IHAVE looks normal, the pull goes out, the
    # answer never comes, and the asker charges a P7 broken promise
    # (gossip_tracer.go:79-115) while gossip_ok eventually routes its
    # pulls to honest advertisers once the censor sinks below the gossip
    # threshold. Masking the advertisement would delete the very scoring
    # response the contract asserts on.
    window_bits = jnp.where(mal[None, :], alive_bits[:, None], window_bits)
    emit_mode = resolve_emit_mode(cfg.hop_mode, w, n, k)
    if emit_mode in ("pallas", "pallas-mxu"):
        # fused chooser (PERF_MODEL.md S7): window table in VMEM, budget
        # scan per receiver block; covers budgeted and unbudgeted paths
        # (budget >= M reduces to the lowest-offering-slot choice)
        iwant_pending = emit_dispatch(
            window_bits, have_bits, inc_gossip.astype(jnp.uint8),
            topic_bits, nbr, m=m,
            budget=min(cfg.max_iwant_per_tick, m),
            gather="mxu" if emit_mode == "pallas-mxu" else "take",
            interpret=jax.default_backend() != "tpu")
        return state._replace(iwant_pending=iwant_pending)
    gossip_allowed = _edge_topic_bits(inc_gossip, topic_bits, w)        # [W,K,N]
    offer = gw(window_bits) & gossip_allowed
    if cfg.max_iwant_per_tick >= m:
        # a sender can offer at most M ids per tick, so the iasked budget
        # cannot bind: pick the lowest offering slot per message
        # (deterministic stand-in for the reference's random IWANT pick,
        # gossip_tracer.go:53)
        excl = exclusive_prefix_or(offer, axis=1)
        chosen_k = offer & ~excl & ~have_bits[:, None, :]
        iwant_pending = _bits_to_slot(chosen_k, m)
    else:
        # MaxIHaveLength flood protection, PER SENDING PEER: the iasked[p]
        # budget caps ids asked from each advertiser within a heartbeat, and
        # an id advertised by a second peer with headroom is still pulled
        # from that peer, so one flooder cannot starve honest pulls
        # (gossipsub.go:654-676). Vectorized over messages: a K-step scan
        # assigns each wanted id to its lowest offering slot with budget
        # headroom (slot-order tie-break as everywhere in the engine).
        iwant_pending = _budgeted_iwant(offer, have_bits, m,
                                        cfg.max_iwant_per_tick)
    # the per-tick peerhave cap (MaxIHaveMessages=10, gossipsub.go:630-652)
    # is structurally satisfied: an edge carries at most one IHAVE per tick
    return state._replace(iwant_pending=iwant_pending)


def _budgeted_iwant(offer: jnp.ndarray, have_bits: jnp.ndarray, m: int,
                    budget: int) -> jnp.ndarray:
    """[W,K,N] packed offers -> [N,M] chosen slot per message (or -1), asking
    at most ``budget`` ids from any single slot (the iasked counter,
    gossipsub.go:654-676). Scans the K slot axis (K is small and static);
    each step ranks the slot's still-unassigned offers and takes the first
    ``budget`` by message index."""
    w, k, n = offer.shape

    def pick(carry, off_k):                       # off_k: [W, N]
        assigned, pend, slot_idx = carry
        masked = off_k & ~assigned                                # [W, N]
        off_u = unpack_words(masked, m)                           # [N, M]
        rank = prefix_count_words(masked.T, m)
        take = off_u & (rank <= budget)
        pend = jnp.where(take, slot_idx, pend)
        assigned = assigned | pack_words(take)
        return (assigned, pend, slot_idx + 1), None

    pend0 = jnp.full((n, m), -1, jnp.int32)
    (_, pend, _), _ = jax.lax.scan(
        pick, (have_bits, pend0, jnp.int32(0)), jnp.moveaxis(offer, 1, 0))
    return pend
