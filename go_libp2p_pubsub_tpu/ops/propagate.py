"""Batched message propagation: publish, eager mesh forwarding, lazy gossip.

Models the reference's data path — Publish fan-out (gossipsub.go:975-1045),
per-hop forwarding through mesh members, IHAVE emission over the mcache
gossip window + IWANT pull (gossipsub.go:630-739, 1711-1775) — as frontier
expansion over the padded adjacency:

- Message "wire transfer" between heartbeats is ``prop_substeps`` frontier
  hops per tick (a message crosses the mesh in milliseconds between 1s
  heartbeats; the hop bound plays the role of network latency).
- The mcache ring (mcache.go) is derived state: a message is in a peer's
  gossip window iff it was delivered within ``history_gossip`` ticks.
- IWANT pulls resolve with a one-tick delay through ``iwant_pending``
  (slot of the chosen IHAVE sender, lowest-slot deterministic choice vs the
  reference's random pick, gossip_tracer.go:53). Unanswered pulls are broken
  gossip promises: one P7 behaviour-penalty point per broken message id
  (gossip_tracer.go:79-115 GetBrokenPromises → gossipsub.go:1620-1625
  applyIwantPenalties).
- Delivery bookkeeping feeds the score counters exactly where the reference's
  RawTracer hooks fire: first deliveries (score.go:920-947), same-window
  duplicates from mesh members (score.go:949-981), invalid deliveries
  (score.go:899-918 RejectMessage → P4).
- Receive gating: data from peers scored below ``graylist_threshold`` is
  ignored (AcceptFrom, gossipsub.go:598-609), and IHAVE from peers below
  ``gossip_threshold`` is ignored (gossipsub.go:634-645) — both use the
  RECEIVER's score of the sender. The per-tick IWANT budget enforces
  MaxIHaveLength flood protection (gossipsub.go:654-676).
- Adversaries (``state.malicious``): publish invalid messages, advertise the
  entire live window, never answer IWANTs, and accept/forward anything —
  the gossipsub_spam_test.go actor behaviors as peer attributes.

Memory/layout: the message window lives in uint32 bitmask words in
**word-major, peer-minor** layout ([W, N] and [W, K, N]; ops/bits.py), so a
forwarding hop is W per-word neighbor gathers plus a handful of bitwise
passes that tile the TPU vector lanes with zero padding waste. Per-slot
score attribution happens once per tick on OR-accumulated event sets, which
is exact because each (receiver, message) first-delivery and each
(receiver, sender, message) duplicate occurs at most once per tick
(frontier semantics: a peer forwards a message the hop after it first
receives it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .bits import (
    U32,
    exclusive_prefix_or,
    n_words,
    pack_bool,
    pack_words,
    popcount_sum,
    reduce_or,
    unpack_words,
)
from .heartbeat import edge_gather


def publish(state: SimState, cfg: SimConfig, publishers: jnp.ndarray,
            topics: jnp.ndarray) -> SimState:
    """Start ``P`` new messages this tick, rotating through message slots.

    publishers: [P] int32 peer ids; topics: [P] int32 topic ids. Slot reuse
    resets the per-peer seen state (the timecache TTL analogue: a slot lives
    msg_window // publishers_per_tick ticks). Publishers not subscribed to
    their topic stamp ``fanout_lastpub`` (gossipsub.go:1007-1018: publish to
    fanout, record lastpub). Malicious publishers emit invalid messages.
    """
    p = publishers.shape[0]
    m = cfg.msg_window
    slots = (state.tick * p + jnp.arange(p)) % m

    msg_topic = state.msg_topic.at[slots].set(topics)
    msg_publish_tick = state.msg_publish_tick.at[slots].set(state.tick)
    msg_invalid = state.msg_invalid.at[slots].set(state.malicious[publishers])
    # reset recycled slots, then mark the publisher as having it
    have = state.have.at[:, slots].set(False)
    have = have.at[publishers, slots].set(True)
    deliver_tick = state.deliver_tick.at[:, slots].set(NEVER)
    deliver_tick = deliver_tick.at[publishers, slots].set(state.tick)
    iwant_pending = state.iwant_pending.at[:, slots].set(-1)
    # fanout lastpub for non-subscribed publishers
    sub_pub = state.subscribed[publishers, topics]
    cur_lp = state.fanout_lastpub[publishers, topics]
    fanout_lastpub = state.fanout_lastpub.at[publishers, topics].set(
        jnp.where(sub_pub, cur_lp, state.tick))
    return state._replace(msg_topic=msg_topic, msg_publish_tick=msg_publish_tick,
                          msg_invalid=msg_invalid, have=have,
                          deliver_tick=deliver_tick, iwant_pending=iwant_pending,
                          fanout_lastpub=fanout_lastpub)


def _edge_forward_mask(state: SimState, cfg: SimConfig, key: jax.Array) -> jnp.ndarray:
    """[N, T, K] receiver-view forwarding mask: slot s's peer would forward a
    topic-t message to me. Router-variant dispatch (static)."""
    n, t, k = state.mesh.shape
    conn = state.connected[:, None, :]
    my_sub = state.subscribed[:, :, None]
    if cfg.router == "gossipsub":
        # sender forwards along ITS mesh edges (gossipsub.go:1020-1035); a
        # non-subscribed publisher sends along its fanout (gossipsub.go:1007)
        send = state.mesh | (state.fanout & ~state.subscribed[:, :, None])
        return edge_gather(send, state)
    if cfg.router == "floodsub":
        # sender forwards to every subscribed neighbor (floodsub.go:76-100)
        return conn & my_sub
    if cfg.router == "randomsub":
        # sender forwards to max(D, ceil(sqrt N)) random topic peers
        # (randomsub.go:124-143): statistical model via per-edge Bernoulli
        # with matching expected degree
        target = jnp.maximum(cfg.d, jnp.ceil(jnp.sqrt(float(cfg.n_peers))))
        # probability is per SENDER: it picks target of ITS peers; view from
        # the receiver via the neighbor table
        nbr = jnp.clip(state.neighbors, 0, cfg.n_peers - 1)
        sender_deg = jnp.maximum(jnp.sum(state.connected, -1), 1)[nbr]  # [N,K]
        prob = jnp.minimum(target / sender_deg, 1.0)[:, None, :]
        draw = jax.random.uniform(key, (n, t, k)) < prob
        return conn & my_sub & draw
    raise ValueError(f"unknown router {cfg.router!r}")


def _gather_words(x_w: jnp.ndarray, nbr_t: jnp.ndarray) -> jnp.ndarray:
    """out[w, k, n] = x_w[w, nbr_t[k, n]] — per-word 1D neighbor gather.

    The per-word form keeps both the table ([N] u32) and the result
    peer-minor; a [N, K, W] row gather would materialize a 64x lane-padded
    intermediate on TPU.
    """
    return jnp.stack([x_w[i][nbr_t] for i in range(x_w.shape[0])])


def _edge_topic_bits(mask_ntk: jnp.ndarray, topic_bits: jnp.ndarray,
                     w: int) -> jnp.ndarray:
    """Expand a per-(peer, topic, slot) edge mask into packed per-edge message
    words: out[w,k,n] = OR over topics t with mask[n,t,k] of topic_bits[t,w].

    Topic message sets are disjoint, so OR == sum; T is small and static.
    """
    n, t, k = mask_ntk.shape
    acc = jnp.zeros((w, k, n), U32)
    for ti in range(t):
        acc = acc | jnp.where(mask_ntk[:, ti, :].T[None, :, :],
                              topic_bits[ti][:, None, None], U32(0))
    return acc


def _slot_bitplanes(pend: jnp.ndarray, k: int) -> jnp.ndarray:
    """iwant_pending [N, M] (slot id or -1) -> packed per-slot ask sets
    [W, K, N]: bit m of out[:, s, n] iff pend[n, m] == s.

    Encoded via ceil(log2 K) packed bit-planes of the slot index, so no
    [N, K, M] temporary is materialized.
    """
    n, m = pend.shape
    nbits = max(1, (k - 1).bit_length())
    valid = pack_words(pend >= 0)                              # [W, N]
    planes = [pack_words((pend > -1) & (((pend >> b) & 1) == 1))
              for b in range(nbits)]                           # each [W, N]
    out = jnp.broadcast_to(valid[:, None, :], (valid.shape[0], k, n))
    for b in range(nbits):
        kbit = ((jnp.arange(k) >> b) & 1).astype(bool)[None, :, None]
        match = jnp.where(kbit, planes[b][:, None, :], ~planes[b][:, None, :])
        out = out & match
    return out


def _bits_to_slot(chosen: jnp.ndarray, m: int) -> jnp.ndarray:
    """Packed disjoint per-slot sets [W, K, N] -> [N, M] slot id or -1
    (inverse of _slot_bitplanes), again via bit-planes."""
    w, k, n = chosen.shape
    nbits = max(1, (k - 1).bit_length())
    any_bits = reduce_or(chosen, axis=1)                       # [W, N]
    slot = jnp.zeros((n, m), jnp.int32)
    for b in range(nbits):
        kbit = ((jnp.arange(k) >> b) & 1).astype(U32)[None, :, None]
        plane = reduce_or(chosen * kbit, axis=1)               # [W, N]
        slot = slot + (unpack_words(plane, m).astype(jnp.int32) << b)
    return jnp.where(unpack_words(any_bits, m), slot, -1)


def forward_tick(state: SimState, cfg: SimConfig, tp: TopicParams,
                 gossip_sel: jnp.ndarray, scores: jnp.ndarray,
                 key: jax.Array) -> SimState:
    """One tick of data-plane traffic: resolve last tick's IWANTs, run
    ``prop_substeps`` forwarding hops, then emit this tick's IHAVE/IWANT.

    ``scores`` is the heartbeat's [N, K] score cache (receiver's score of the
    peer in slot k), used for accept/gossip gating.
    """
    n, t, k = state.mesh.shape
    m = cfg.msg_window
    w = n_words(m)
    nbr_t = jnp.clip(state.neighbors, 0, n - 1).T              # [K, N]
    mal = state.malicious

    # --- per-tick packed masks ---
    age_pub = state.tick - state.msg_publish_tick
    alive = (age_pub >= 0) & (age_pub < cfg.history_length)             # [M]
    t_m = jnp.clip(state.msg_topic, 0, t - 1)
    live_topic = (state.msg_topic >= 0) & alive
    # [T, W]: per-topic live message sets (disjoint across topics)
    topic_bits = pack_bool((t_m[None, :] == jnp.arange(t)[:, None])
                           & live_topic[None, :])
    alive_bits = pack_bool(alive[None, :])[0]                           # [W]
    invalid_bits = pack_bool((state.msg_invalid & alive)[None, :])[0]
    valid_msg_bits = alive_bits & ~invalid_bits
    # per-receiver acceptance: honest peers reject invalid messages
    # (validation.go:293-370); malicious receivers accept + forward anything
    vm = jnp.where(mal[None, :], alive_bits[:, None],
                   valid_msg_bits[:, None])                             # [W,N]

    have_bits = pack_words(state.have)                                  # [W,N]
    dlv_bits = pack_words(state.deliver_tick < NEVER)                   # [W,N]
    dlv_start = dlv_bits
    n_have_start = popcount_sum(have_bits, axis=(0, 1))

    if cfg.scoring_enabled:
        accept_ok = scores >= cfg.graylist_threshold      # [N,K] AcceptFrom
        gossip_ok = scores >= cfg.gossip_threshold        # [N,K] handleIHave
    else:
        accept_ok = jnp.ones((n, k), bool)
        gossip_ok = jnp.ones((n, k), bool)

    fmd_add = jnp.zeros((n, t, k), jnp.float32)
    mmd_add = jnp.zeros((n, t, k), jnp.float32)
    imd_add = jnp.zeros((n, t, k), jnp.float32)

    # -- step 1: resolve pending IWANTs from last tick (gossipsub.go:698-739:
    # the sender answers from its mcache; delivery counts as a first delivery
    # from a non-mesh peer) --
    asked_k = _slot_bitplanes(state.iwant_pending, k) & alive_bits[:, None, None]
    # malicious sources never answer IWANTs (the iwantEverything-style actor
    # holds its promises open, gossipsub_spam_test.go:23-133); honest sources
    # answer from their mcache, which rejected messages never enter
    # (deliver_tick stays NEVER on rejection — validation.go:293-370)
    answer_bits = jnp.where(mal[None, :], U32(0), dlv_bits)             # [W,N]
    answers_k = _gather_words(answer_bits, nbr_t)                       # [W,K,N]
    got_k = asked_k & answers_k & ~have_bits[:, None, :]
    broken_k = asked_k & ~answers_k
    got_any = reduce_or(got_k, axis=1)                                  # [W,N]
    # pulls cannot yield invalid messages (see above), so they are deliveries
    for ti in range(t):
        fmd_add = fmd_add.at[:, ti, :].add(
            popcount_sum(got_k & topic_bits[ti][:, None, None], axis=0).T)
    # broken promises: one penalty point per unfulfilled message id
    # (gossip_tracer.go:79-115, applied gossipsub.go:1620-1625)
    behaviour_penalty = state.behaviour_penalty + \
        popcount_sum(broken_k, axis=0).T
    have_bits = have_bits | got_any
    dlv_bits = dlv_bits | got_any

    # -- step 2: eager forwarding, prop_substeps hops, fully bit-packed --
    fwd_mask = _edge_forward_mask(state, cfg, key)
    fwd_mask = fwd_mask & accept_ok[:, None, :]
    allowed = _edge_topic_bits(fwd_mask, topic_bits, w)                 # [W,K,N]
    mesh_eb = _edge_topic_bits(state.mesh, topic_bits, w)               # [W,K,N]

    # frontier: messages that entered this peer THIS tick (fresh publishes and
    # IWANT pulls above); peers forward a message exactly one hop after they
    # first receive it, so the per-tick event sets below are disjoint across
    # hops and OR-accumulation counts each event exactly once
    frontier = pack_words(state.deliver_tick == state.tick) | got_any   # [W,N]
    nv_acc = jnp.zeros((w, k, n), U32)     # first-delivery events, per slot
    ni_acc = jnp.zeros((w, k, n), U32)     # invalid-delivery events, per slot
    dup_acc = jnp.zeros((w, k, n), U32)    # mesh-duplicate events, per slot

    for _hop in range(cfg.prop_substeps):
        offered = _gather_words(frontier, nbr_t) & allowed              # [W,K,N]
        excl = exclusive_prefix_or(offered, axis=1)
        new_from_k = offered & ~excl & ~have_bits[:, None, :]
        new_any = (excl[:, -1] | offered[:, -1]) & ~have_bits           # [W,N]
        new_valid = new_any & vm
        nv_acc = nv_acc | (new_from_k & vm[:, None, :])
        ni_acc = ni_acc | (new_from_k & ~vm[:, None, :])
        # mesh-delivery credit: any mesh sender of a message I (now) hold
        # valid — covers first-in-mesh (score.go:938-947) and same-window
        # duplicates (score.go:949-981; window < 1 tick -> same tick).
        # Invalid messages never earn MMD, including for malicious
        # receivers who "deliver" them: an adversary's own counters about
        # its neighbors are never consulted by honest-peer defenses, and
        # the reference's spam actors run no scoring at all
        # (gossipsub_spam_test.go drives raw streams)
        elig = (dlv_bits | new_valid) & valid_msg_bits[:, None]
        dup_acc = dup_acc | (offered & mesh_eb & elig[:, None, :])
        have_bits = have_bits | new_any
        dlv_bits = dlv_bits | new_valid
        frontier = new_valid

    for ti in range(t):
        tb = topic_bits[ti][:, None, None]
        fmd_add = fmd_add.at[:, ti, :].add(popcount_sum(nv_acc & tb, axis=0).T)
        imd_add = imd_add.at[:, ti, :].add(popcount_sum(ni_acc & tb, axis=0).T)
        mmd_add = mmd_add.at[:, ti, :].add(popcount_sum(dup_acc & tb, axis=0).T)

    caps = tp.first_message_deliveries_cap[None, :, None], \
        tp.mesh_message_deliveries_cap[None, :, None]
    fmd = jnp.minimum(state.first_message_deliveries + fmd_add, caps[0])
    mmd = jnp.minimum(state.mesh_message_deliveries + mmd_add, caps[1])
    imd = state.invalid_message_deliveries + imd_add

    newly_dlv = dlv_bits & ~dlv_start
    have = unpack_words(have_bits, m)
    deliver_tick = jnp.where(unpack_words(newly_dlv, m), state.tick,
                             state.deliver_tick)
    delivered = popcount_sum(have_bits, axis=(0, 1)) - n_have_start

    state = state._replace(
        have=have, deliver_tick=deliver_tick,
        first_message_deliveries=fmd,
        mesh_message_deliveries=mmd,
        invalid_message_deliveries=imd,
        behaviour_penalty=behaviour_penalty,
        delivered_total=state.delivered_total + delivered)

    # -- step 3: IHAVE/IWANT for next tick (gossipsub.go:1711-1775) --
    # receiver view of gossip edges: slot s's peer gossips topic t to me;
    # ignore IHAVE from senders I score below the gossip threshold
    inc_gossip = edge_gather(gossip_sel, state) & gossip_ok[:, None, :]
    # sender gossip window = the mcache gossip slice: DELIVERED within the
    # last history_gossip ticks (rejected messages never enter the mcache, so
    # have-but-not-delivered is excluded)
    age = state.tick - state.deliver_tick
    window_bits = pack_words((age >= 0) & (age < cfg.history_gossip)) \
        & alive_bits[:, None]
    # malicious peers advertise everything alive (IHAVE flood)
    window_bits = jnp.where(mal[None, :], alive_bits[:, None], window_bits)
    gossip_allowed = _edge_topic_bits(inc_gossip, topic_bits, w)        # [W,K,N]
    offer = _gather_words(window_bits, nbr_t) & gossip_allowed
    if cfg.max_iwant_per_tick >= m:
        # a sender can offer at most M ids, so the budget cannot bind: pick
        # the lowest offering slot per message (deterministic stand-in for
        # the reference's random IWANT pick, gossip_tracer.go:53)
        excl = exclusive_prefix_or(offer, axis=1)
        chosen_k = offer & ~excl & ~have_bits[:, None, :]
        iwant_pending = _bits_to_slot(chosen_k, m)
    else:
        # MaxIHaveLength flood protection, PER SENDING PEER: the iasked[p]
        # budget caps ids asked from each advertiser within a heartbeat, and
        # an id advertised by a second peer with headroom is still pulled
        # from that peer, so one flooder cannot starve honest pulls
        # (gossipsub.go:654-676). Exact sequential selection, only on this
        # adversarial-config path.
        offer_u = jnp.moveaxis(unpack_words(offer.reshape(w, k * n), m)
                               .reshape(k, n, m), 0, 1)                 # [N,K,M]
        offer_u = offer_u & ~state.have[:, None, :]

        def pick(asked_ct, off_m):                                      # [N,K]
            avail = off_m & (asked_ct < cfg.max_iwant_per_tick)
            slot = jnp.argmax(avail, axis=1).astype(jnp.int32)          # [N]
            take = jnp.any(avail, axis=1)
            oh = jax.nn.one_hot(slot, k, dtype=jnp.int32) * take[:, None]
            return asked_ct + oh, jnp.where(take, slot, -1)

        _, pend_t = jax.lax.scan(pick, jnp.zeros((n, k), jnp.int32),
                                 jnp.moveaxis(offer_u, -1, 0))
        iwant_pending = jnp.moveaxis(pend_t, 0, -1)                     # [N,M]
    return state._replace(iwant_pending=iwant_pending)
