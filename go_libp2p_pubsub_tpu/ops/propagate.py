"""Batched message propagation: publish, eager mesh forwarding, lazy gossip.

Models the reference's data path — Publish fan-out (gossipsub.go:975-1045),
per-hop forwarding through mesh members, IHAVE emission over the mcache
gossip window + IWANT pull (gossipsub.go:630-739, 1711-1775) — as frontier
expansion over the padded adjacency:

- Message "wire transfer" between heartbeats is ``prop_substeps`` frontier
  hops per tick (a message crosses the mesh in milliseconds between 1s
  heartbeats; the hop bound plays the role of network latency).
- The mcache ring (mcache.go) is derived state: a message is in a peer's
  gossip window iff it was delivered within ``history_gossip`` ticks.
- IWANT pulls resolve with a one-tick delay through ``iwant_pending``
  (slot of the chosen IHAVE sender, lowest-slot deterministic choice vs the
  reference's random pick, gossip_tracer.go:53). Unanswered pulls are broken
  gossip promises: one P7 behaviour-penalty point per broken message id
  (gossip_tracer.go:79-115 GetBrokenPromises → gossipsub.go:1620-1625
  applyIwantPenalties).
- Delivery bookkeeping feeds the score counters exactly where the reference's
  RawTracer hooks fire: first deliveries (score.go:920-947), same-window
  duplicates from mesh members (score.go:949-981), invalid deliveries
  (score.go:899-918 RejectMessage → P4).
- Receive gating: data from peers scored below ``graylist_threshold`` is
  ignored (AcceptFrom, gossipsub.go:598-609), and IHAVE from peers below
  ``gossip_threshold`` is ignored (gossipsub.go:634-645) — both use the
  RECEIVER's score of the sender. The per-tick IWANT budget enforces
  MaxIHaveLength flood protection (gossipsub.go:654-676).
- Adversaries (``state.malicious``): publish invalid messages, advertise the
  entire live window, never answer IWANTs, and accept/forward anything —
  the gossipsub_spam_test.go actor behaviors as peer attributes.

Memory: all [N, K, M] temporaries are chunked over M (``msg_chunk``), and
per-(topic)-scatters are one-hot matmuls over the small T axis (MXU-friendly,
no scatter in the hot loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.config import SimConfig, TopicParams
from ..sim.state import NEVER, SimState
from .heartbeat import edge_gather


def publish(state: SimState, cfg: SimConfig, publishers: jnp.ndarray,
            topics: jnp.ndarray) -> SimState:
    """Start ``P`` new messages this tick, rotating through message slots.

    publishers: [P] int32 peer ids; topics: [P] int32 topic ids. Slot reuse
    resets the per-peer seen state (the timecache TTL analogue: a slot lives
    msg_window // publishers_per_tick ticks). Publishers not subscribed to
    their topic stamp ``fanout_lastpub`` (gossipsub.go:1007-1018: publish to
    fanout, record lastpub). Malicious publishers emit invalid messages.
    """
    p = publishers.shape[0]
    m = cfg.msg_window
    slots = (state.tick * p + jnp.arange(p)) % m

    msg_topic = state.msg_topic.at[slots].set(topics)
    msg_publish_tick = state.msg_publish_tick.at[slots].set(state.tick)
    msg_invalid = state.msg_invalid.at[slots].set(state.malicious[publishers])
    # reset recycled slots, then mark the publisher as having it
    have = state.have.at[:, slots].set(False)
    have = have.at[publishers, slots].set(True)
    deliver_tick = state.deliver_tick.at[:, slots].set(NEVER)
    deliver_tick = deliver_tick.at[publishers, slots].set(state.tick)
    iwant_pending = state.iwant_pending.at[:, slots].set(-1)
    # fanout lastpub for non-subscribed publishers
    sub_pub = state.subscribed[publishers, topics]
    cur_lp = state.fanout_lastpub[publishers, topics]
    fanout_lastpub = state.fanout_lastpub.at[publishers, topics].set(
        jnp.where(sub_pub, cur_lp, state.tick))
    return state._replace(msg_topic=msg_topic, msg_publish_tick=msg_publish_tick,
                          msg_invalid=msg_invalid, have=have,
                          deliver_tick=deliver_tick, iwant_pending=iwant_pending,
                          fanout_lastpub=fanout_lastpub)


def _edge_forward_mask(state: SimState, cfg: SimConfig, key: jax.Array) -> jnp.ndarray:
    """[N, T, K] receiver-view forwarding mask: slot s's peer would forward a
    topic-t message to me. Router-variant dispatch (static)."""
    n, t, k = state.mesh.shape
    conn = state.connected[:, None, :]
    my_sub = state.subscribed[:, :, None]
    if cfg.router == "gossipsub":
        # sender forwards along ITS mesh edges (gossipsub.go:1020-1035); a
        # non-subscribed publisher sends along its fanout (gossipsub.go:1007)
        send = state.mesh | (state.fanout & ~state.subscribed[:, :, None])
        return edge_gather(send, state)
    if cfg.router == "floodsub":
        # sender forwards to every subscribed neighbor (floodsub.go:76-100)
        return conn & my_sub
    if cfg.router == "randomsub":
        # sender forwards to max(D, ceil(sqrt N)) random topic peers
        # (randomsub.go:124-143): statistical model via per-edge Bernoulli
        # with matching expected degree
        target = jnp.maximum(cfg.d, jnp.ceil(jnp.sqrt(float(cfg.n_peers))))
        # probability is per SENDER: it picks target of ITS peers; view from
        # the receiver via the neighbor table
        nbr = jnp.clip(state.neighbors, 0, cfg.n_peers - 1)
        sender_deg = jnp.maximum(jnp.sum(state.connected, -1), 1)[nbr]  # [N,K]
        prob = jnp.minimum(target / sender_deg, 1.0)[:, None, :]
        draw = jax.random.uniform(key, (n, t, k)) < prob
        return conn & my_sub & draw
    raise ValueError(f"unknown router {cfg.router!r}")


def forward_tick(state: SimState, cfg: SimConfig, tp: TopicParams,
                 gossip_sel: jnp.ndarray, scores: jnp.ndarray,
                 key: jax.Array) -> SimState:
    """One tick of data-plane traffic: resolve last tick's IWANTs, run
    ``prop_substeps`` forwarding hops, then emit this tick's IHAVE/IWANT.

    ``scores`` is the heartbeat's [N, K] score cache (receiver's score of the
    peer in slot k), used for accept/gossip gating.
    """
    n, t, k = state.mesh.shape
    m = cfg.msg_window
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    # [M] slot holds a live message: published (tick < NEVER, so the age is
    # non-negative) within the mcache history window
    age_pub = state.tick - state.msg_publish_tick
    alive = (age_pub >= 0) & (age_pub < cfg.history_length)
    t_m = jnp.clip(state.msg_topic, 0, t - 1)                           # [M]
    onehot_t = jax.nn.one_hot(t_m, t, dtype=jnp.float32) * \
        (state.msg_topic >= 0)[:, None]                                  # [M,T]
    mal_recv = state.malicious[:, None]                                  # [N,1]

    if cfg.scoring_enabled:
        accept_ok = scores >= cfg.graylist_threshold      # [N,K] AcceptFrom
        gossip_ok = scores >= cfg.gossip_threshold        # [N,K] handleIHave
    else:
        accept_ok = jnp.ones((n, k), bool)
        gossip_ok = jnp.ones((n, k), bool)

    fwd_mask = _edge_forward_mask(state, cfg, key)   # [N,T,K] receiver view
    fwd_mask = fwd_mask & accept_ok[:, None, :]
    my_mesh = state.mesh                             # [N,T,K] my own mesh view
    caps = tp.first_message_deliveries_cap[None, :, None], \
        tp.mesh_message_deliveries_cap[None, :, None]

    # -- step 1: resolve pending IWANTs from last tick (gossipsub.go:698-739:
    # the sender answers from its mcache; delivery counts as a first delivery
    # from a non-mesh peer) --
    pend = state.iwant_pending                       # [N,M] slot or -1
    # pend indexes slots per (peer, message); gather sender peer ids:
    src = nbr[jnp.arange(n)[:, None], jnp.clip(pend, 0, k - 1)]       # [N,M]
    # malicious sources never answer IWANTs (the iwantEverything-style actor
    # holds its promises open, gossipsub_spam_test.go:23-133); honest sources
    # answer from their mcache, which rejected messages never enter
    # (deliver_tick stays NEVER on rejection — validation.go:293-370)
    src_answers = (state.deliver_tick[src, jnp.arange(m)[None, :]] < NEVER) \
        & ~state.malicious[src]
    asked = (pend >= 0) & alive[None, :]
    # pulls cannot yield invalid messages: honest mcaches never contain them
    # (rejected messages are not delivered) and malicious sources never answer
    got = asked & src_answers & ~state.have
    broken = asked & ~src_answers
    have = state.have | got
    deliver_tick = jnp.where(got, state.tick, state.deliver_tick)
    # per-slot attribution via one-hot matmuls
    slot_onehot = jax.nn.one_hot(jnp.clip(pend, 0, k - 1), k, dtype=jnp.float32)
    fmd_add = jnp.einsum("nm,mt,nmk->ntk", got.astype(jnp.float32), onehot_t, slot_onehot)
    fmd = jnp.minimum(state.first_message_deliveries + fmd_add, caps[0])
    # broken promises: one penalty point per unfulfilled message id
    # (gossip_tracer.go:79-115, applied gossipsub.go:1620-1625)
    broken_per_slot = jnp.einsum("nm,nmk->nk", broken.astype(jnp.float32), slot_onehot)
    state = state._replace(
        have=have, deliver_tick=deliver_tick,
        first_message_deliveries=fmd,
        behaviour_penalty=state.behaviour_penalty + broken_per_slot,
        iwant_pending=jnp.full_like(pend, -1),
        delivered_total=state.delivered_total + jnp.sum(got))

    # -- step 2: eager forwarding, prop_substeps hops, chunked over messages --
    invalid_m = state.msg_invalid                    # [M]

    def hop(carry, _):
        have, deliver_tick, frontier, fmd, mmd, imd = carry

        def chunk_body(c0, sl):
            have_c, dt_c, fr_c, fmd_i, mmd_i, imd_i = c0
            msl = sl  # [Mc] message indices
            fr_nbr = frontier[:, msl][nbr]            # [N,K,Mc] sender frontier
            # edge forward mask for each chunk message's topic:
            em = jnp.transpose(fwd_mask[:, t_m[msl], :], (0, 2, 1))  # [N,K,Mc]
            senders = fr_nbr & em & alive[msl][None, None, :]
            recv = jnp.any(senders, axis=1)           # [N,Mc]
            had = have_c[:, msl]
            new = recv & ~had
            # honest receivers reject invalid messages: seen but not
            # delivered/forwarded; P4 charged to the delivering slot
            new_invalid = new & invalid_m[msl][None, :] & ~mal_recv
            new_valid = new & ~new_invalid
            # first-sender attribution: lowest active slot
            first_slot = jnp.argmax(senders, axis=1)  # [N,Mc]
            slot_oh = jax.nn.one_hot(first_slot, k, dtype=jnp.float32)
            new_f = new_valid.astype(jnp.float32)
            fmd_add = jnp.einsum("nm,mt,nmk->ntk", new_f, onehot_t[msl], slot_oh)
            imd_add = jnp.einsum("nm,mt,nmk->ntk",
                                 new_invalid.astype(jnp.float32),
                                 onehot_t[msl], slot_oh)
            # mesh-delivery credit: first delivery from a peer in MY mesh
            # (score.go:938-947), plus same-window duplicates from mesh
            # members (score.go:949-981; window < 1 tick -> same tick)
            in_my_mesh = jnp.transpose(my_mesh[:, t_m[msl], :], (0, 2, 1))  # [N,K,Mc]
            dup = senders & (had | new_valid)[:, None, :] & in_my_mesh & \
                ~invalid_m[msl][None, None, :]
            # exclude the first-delivery slot from dup, count it via new_f
            dup = dup & ~(slot_oh.transpose(0, 2, 1).astype(bool) & new_valid[:, None, :])
            mmd_add = jnp.einsum("nkm,mt->ntk", dup.astype(jnp.float32), onehot_t[msl])
            first_in_mesh = jnp.einsum(
                "nm,mt,nmk->ntk", new_f, onehot_t[msl],
                slot_oh * jnp.transpose(in_my_mesh, (0, 2, 1)))
            have_c = have_c.at[:, msl].set(had | recv)
            dt_c = dt_c.at[:, msl].set(jnp.where(new_valid, state.tick, dt_c[:, msl]))
            fr_c = fr_c.at[:, msl].set(new_valid)
            return (have_c, dt_c, fr_c, fmd_i + fmd_add,
                    mmd_i + mmd_add + first_in_mesh, imd_i + imd_add), 0

        slices = jnp.arange(m).reshape(-1, cfg.msg_chunk)
        new_frontier = jnp.zeros_like(frontier)
        (have, deliver_tick, new_frontier, fmd_d, mmd_d, imd_d), _ = jax.lax.scan(
            chunk_body, (have, deliver_tick, new_frontier,
                         jnp.zeros((n, t, k), jnp.float32),
                         jnp.zeros((n, t, k), jnp.float32),
                         jnp.zeros((n, t, k), jnp.float32)), slices)
        return (have, deliver_tick, new_frontier, fmd + fmd_d, mmd + mmd_d,
                imd + imd_d), 0

    frontier0 = state.deliver_tick == state.tick     # published/just received
    z = jnp.zeros((n, t, k), jnp.float32)
    carry0 = (state.have, state.deliver_tick, frontier0, z, z, z)
    (have, deliver_tick, _, fmd_add, mmd_add, imd_add), _ = jax.lax.scan(
        hop, carry0, None, length=cfg.prop_substeps)

    delivered = jnp.sum(have) - jnp.sum(state.have)
    fmd = jnp.minimum(state.first_message_deliveries + fmd_add, caps[0])
    mmd = jnp.minimum(state.mesh_message_deliveries + mmd_add, caps[1])
    imd = state.invalid_message_deliveries + imd_add
    state = state._replace(have=have, deliver_tick=deliver_tick,
                           first_message_deliveries=fmd,
                           mesh_message_deliveries=mmd,
                           invalid_message_deliveries=imd,
                           delivered_total=state.delivered_total + delivered)

    # -- step 3: IHAVE/IWANT for next tick (gossipsub.go:1711-1775) --
    # receiver view of gossip edges: slot s's peer gossips topic t to me;
    # ignore IHAVE from senders I score below the gossip threshold
    inc_gossip = edge_gather(gossip_sel, state) & gossip_ok[:, None, :]
    # sender gossip window = the mcache gossip slice: DELIVERED within the
    # last history_gossip ticks (rejected messages never enter the mcache, so
    # have-but-not-delivered is excluded)
    age = state.tick - state.deliver_tick
    window = (age >= 0) & (age < cfg.history_gossip) & alive[None, :]
    # malicious peers advertise everything alive (IHAVE flood)
    window = window | (state.malicious[:, None] & alive[None, :])

    def iwant_chunk(c, sl):
        pend, asked_ct = c                           # asked_ct: [N,K] iasked
        w_nbr = window[:, sl][nbr]                   # [N,K,Mc]
        eg = jnp.transpose(inc_gossip[:, t_m[sl], :], (0, 2, 1))  # [N,K,Mc]
        # MaxIHaveLength flood protection, PER SENDING PEER: the iasked[p]
        # budget caps ids asked from each advertiser within a heartbeat
        # (gossipsub.go:654-676); an id advertised by a second peer with
        # headroom is still pulled from that peer, so one flooder cannot
        # starve honest pulls (headroom checked at chunk granularity)
        headroom = (asked_ct < cfg.max_iwant_per_tick)[:, :, None]
        offer = w_nbr & eg & headroom
        wanted = jnp.any(offer, axis=1) & ~state.have[:, sl]
        best_slot = jnp.argmax(offer, axis=1).astype(jnp.int32)   # lowest slot
        oh = jax.nn.one_hot(best_slot, k, dtype=jnp.int32) * \
            wanted[..., None].astype(jnp.int32)      # [N,Mc,K]
        before = asked_ct[:, None, :] + jnp.cumsum(oh, axis=1) - oh
        within = jnp.sum(before * oh, axis=-1) < cfg.max_iwant_per_tick
        take = wanted & within
        pend = pend.at[:, sl].set(jnp.where(take, best_slot, -1))
        asked_ct = asked_ct + jnp.sum(oh * take[..., None].astype(jnp.int32),
                                      axis=1)
        return (pend, asked_ct), 0

    slices = jnp.arange(m).reshape(-1, cfg.msg_chunk)
    (iwant_pending, _), _ = jax.lax.scan(
        iwant_chunk,
        (jnp.full((n, m), -1, jnp.int32), jnp.zeros((n, k), jnp.int32)),
        slices)
    return state._replace(iwant_pending=iwant_pending)
