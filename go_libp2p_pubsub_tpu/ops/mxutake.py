"""Two-level gather-free VMEM table lookup: one-hot MXU matmul + lane select.

The round-4 live window proved current Mosaic cannot lower ANY table
lookup wider than one vector register — ``tpu.dynamic_gather`` is a
128-lane in-vreg shuffle, and every wider formulation fails with
``Not implemented: Multiple source vregs along gather dimension``
(PERF_MODEL.md "reality check"; `scripts/tpu_kernel_smoke.py` keeps the
distilled repro). That wall killed the S1–S7 fused-kernel design
(1.36 ms/tick → ~734 hb/s single-chip at the 100k headline).

This module is VERDICT r4 item 3's attack on the wall: express
``table[idx]`` with NO gather op of any width. Factor idx = 128·b + l:

    1. block select (MXU): rows = onehot(b) @ table_blocks — the [NB, 128]
       re-blocked table hit with a [G, NB] one-hot bf16 matmul. Each
       output row has exactly ONE nonzero term, and the table is split
       into u8 chunks (0..255 — exact in bf16's 8-bit mantissa, and the
       MXU accumulates in f32), so the select is EXACT integer routing.
    2. lane select (VPU): out = sum_l rows[g, l] · onehot(l) — an
       elementwise multiply + 128-lane reduction, again one nonzero term.

    u32 words travel as 4 u8 chunk planes recombined by shifts.

Ops used: iota, compare, convert, dot_general, multiply, reduce — all
core Mosaic. FLOP cost per index: 2·NB (MXU) + 2·128 (VPU) per chunk; at
the 100k headline's hop gather (L = N·K = 3.2M indices, NB = 800) that is
~20 Gflop on a 197 TFLOP/s MXU ≈ 0.1 ms — against 9 ms for the measured
sort-permute routing and ~25 ms for XLA's 7 ns/index gathers. If this
lowers on a live window (scripts/tpu_kernel_smoke.py checks it), the
ready-and-tested Pallas kernel suite comes back from the dead with its
gathers rewritten this way.

Reference seam being accelerated: the per-edge neighbor lookups behind
every router exchange (gossipsub.go:1345-1606 heartbeat fan-out,
comm.go:44-191 per-connection streams), batched here as table routing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANES = 128
# default grid-step index-block size: the VMEM tenant is the [block_g, NB]
# bf16 one-hot tile (~1.6 MB at the 100k headline's NB=800). permgather's
# mxu feasibility gate prices exactly this block size — keep them in sync
# by importing from here.
DEFAULT_BLOCK_G = 1024
# word-tile budget for the blocked payload take: the chunk-plane table of
# one take_words_twolevel call is 8·n_pad bytes per word row; tiles are
# sized so the resident planes stay under this, leaving headroom for the
# one-hot tile + MXU rows inside permgather's 8 MB payload budget
_PAYLOAD_PLANES_BYTES = 4 * 1024 * 1024


def pad_lanes(x_w: jnp.ndarray) -> jnp.ndarray:
    """Zero-pad the table axis (last) of a [..., N] word table up to a
    LANES multiple — the out-of-kernel pad seam that generalizes
    ``take_words_onehot`` past the 128-lane-multiple constraint: callers
    pad the table BEFORE the pallas_call (indices < N never select pad
    columns), so the in-kernel chunk reshape always sees an aligned N."""
    n = x_w.shape[-1]
    pad = -n % LANES
    if not pad:
        return x_w
    widths = [(0, 0)] * (x_w.ndim - 1) + [(0, pad)]
    return jnp.pad(x_w, widths)


def payload_w_tile(n: int, k: int) -> int:
    """Word-tile size for the blocked payload take: how many of the K
    word planes one take_words_twolevel call may carry before its resident
    chunk planes (8·n_pad bytes/word) outgrow the tile budget."""
    n_pad = -(-n // LANES) * LANES
    return max(1, min(k, _PAYLOAD_PLANES_BYTES // (8 * n_pad)))


def _prep_table(x_w: jnp.ndarray) -> jnp.ndarray:
    """[W, N] u32 -> [W, 4, NB, 128] bf16 u8-chunk planes (N zero-padded up
    to a 128 multiple; idx < N so pad rows are never selected)."""
    w, n = x_w.shape
    nb = -(-n // LANES)
    pad = nb * LANES - n
    if pad:
        x_w = jnp.pad(x_w, ((0, 0), (0, pad)))
    chunks = jnp.stack([(x_w >> (8 * c)) & jnp.uint32(0xFF)
                        for c in range(4)], axis=1)          # [W, 4, NB*128]
    return chunks.reshape(w, 4, nb, LANES).astype(jnp.bfloat16)


def _select_block(tab_c: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """tab_c [NB, 128] bf16, idx [G] -> [G] f32 exact values (one chunk)."""
    nb = tab_c.shape[0]
    blk = idx // LANES
    lane = idx % LANES
    oh_b = (blk[:, None] == jnp.arange(nb)[None, :]).astype(jnp.bfloat16)
    rows = jax.lax.dot_general(
        oh_b, tab_c, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [G, 128]
    oh_l = (lane[:, None] == jnp.arange(LANES)[None, :]).astype(jnp.float32)
    return jnp.sum(rows * oh_l, axis=1)                      # [G] f32


def _kernel(tab_ref, idx_ref, out_ref, *, w: int):
    idx = idx_ref[:].reshape(-1)
    tab = tab_ref[:]                                         # [W, 4, NB, 128]
    words = []
    for wi in range(w):
        acc = jnp.zeros(idx.shape, jnp.uint32)
        for c in range(4):
            v = _select_block(tab[wi, c], idx).astype(jnp.uint32)
            acc = acc | (v << (8 * c))
        words.append(acc)
    out_ref[:] = jnp.stack(words).reshape(out_ref.shape)


def take_words_twolevel(x_w: jnp.ndarray, idx: jnp.ndarray,
                        block_g: int = DEFAULT_BLOCK_G,
                        interpret: bool = False) -> jnp.ndarray:
    """out[w, r] = x_w[w, idx[r]] — the gather-free two-level take.

    ``idx`` must be pre-clipped to [0, N). ``block_g`` indices are
    processed per grid step (VMEM: the one-hot tile is block_g x NB bf16;
    ~1.6 MB at the 100k headline's NB=800). Any index count is accepted:
    a count that is not a block_g multiple is zero-padded up to one (idx 0
    is always valid) and the pad columns sliced off — engine shapes like
    N*K = 100000*32 need not divide the block size."""
    from jax.experimental import pallas as pl

    w, n = x_w.shape
    (r,) = idx.shape
    if r == 0:
        return jnp.zeros((w, 0), jnp.uint32)
    bg = min(r, block_g)
    r_pad = -(-r // bg) * bg
    if r_pad != r:
        idx = jnp.concatenate(
            [idx, jnp.zeros((r_pad - r,), idx.dtype)])
    tab = _prep_table(x_w)
    nb = tab.shape[2]
    out = pl.pallas_call(
        functools.partial(_kernel, w=w),
        grid=(r_pad // bg,),
        in_specs=[
            pl.BlockSpec((w, 4, nb, LANES), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((bg,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((w, bg), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((w, r_pad), jnp.uint32),
        interpret=interpret,
    )(tab, idx)
    return out[:, :r] if r_pad != r else out


def take_words_onehot(tab: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """out[w, r] = tab[w, idx[r]] as the two-level one-hot select, pure jnp
    — for use INSIDE another Pallas kernel body whose [W, N] u32 table is
    already VMEM-resident (ops/hopkernel.py ``pallas-mxu`` dispatch). The
    chunk planes are built in-kernel from the words, so N must be a LANES
    multiple — callers pad the table BEFORE the pallas_call with
    :func:`pad_lanes` (no pad seam inside a traced body; the hop/resolve/
    emit kernels all do, which is what freed ``pallas-mxu`` from the
    lane-aligned peer-count constraint)."""
    w, n = tab.shape
    if n % LANES:
        # not assert: -O must not strip the reshape-contract guard
        raise ValueError(
            f"take_words_onehot needs a lane-aligned table width, got {n}")
    nb = n // LANES
    words = []
    for wi in range(w):
        acc = jnp.zeros(idx.shape, jnp.uint32)
        for c in range(4):
            chunk = (((tab[wi] >> jnp.uint32(8 * c)) & jnp.uint32(0xFF))
                     .reshape(nb, LANES).astype(jnp.bfloat16))
            v = _select_block(chunk, idx).astype(jnp.uint32)
            acc = acc | (v << jnp.uint32(8 * c))
        words.append(acc)
    return jnp.stack(words)


def take_payload_onehot(payload: jnp.ndarray, jn: jnp.ndarray,
                        rk: jnp.ndarray, block_g: int = DEFAULT_BLOCK_G,
                        interpret: bool = False) -> jnp.ndarray:
    """out[i, s] = payload[jn[i, s], rk[i, s]] with NO gather op — the
    blocked/tiled one-hot variant of the generic [N, K] payload permute
    (the last scalar degradation the mxu mode carried, ROADMAP item 2).

    The payload's K slot columns are viewed as K word planes ([K, N] u32
    via bitcast for any 4-byte dtype), routed through the two-level take
    in word TILES (``payload_w_tile``) so the resident chunk planes stay
    VMEM-bounded at any K — the all-at-once formulation would need a
    block_g × ceil(NK/128) one-hot tile (~50 MB at the 100k headline).
    The slot pick is then a K-wide one-hot select over the fetched rows
    (exactly one nonzero term per edge), all plain XLA.

    ``jn``/``rk`` must be pre-clipped to valid range, like every
    permutation_gather formulation. Exact for every 4-byte dtype
    (u32 round-trips bitcast; the chunk select is integer routing)."""
    dt = payload.dtype
    if dt.itemsize != 4:
        # not assert: -O must not strip the 4-u8-chunk contract guard
        raise ValueError(
            f"take_payload_onehot needs a 4-byte payload dtype, got {dt}")
    n, k = payload.shape
    words = payload if dt == jnp.uint32 else \
        jax.lax.bitcast_convert_type(payload, jnp.uint32)
    planes = words.T                                       # [K, N] tables
    idx = jn.reshape(-1).astype(jnp.int32)                 # n-major [R]
    wt = payload_w_tile(n, k)
    rows = jnp.concatenate(
        [take_words_twolevel(planes[w0:w0 + wt], idx, block_g, interpret)
         for w0 in range(0, k, wt)], axis=0)               # [K, R]
    sel = rk.reshape(-1)[None, :] == jnp.arange(k)[:, None]
    out = jnp.sum(jnp.where(sel, rows, jnp.uint32(0)), axis=0,
                  dtype=jnp.uint32).reshape(jn.shape)
    return out if dt == jnp.uint32 else \
        jax.lax.bitcast_convert_type(out, dt)


def cost_model(n: int, r: int, w: int, block_g: int = DEFAULT_BLOCK_G) -> dict:
    """Bytes-touched + FLOP inventory of one two-level take (the honest
    accounting VERDICT r5 weak #3 asked for — the one-hot operand is the
    real cost driver, not the 2·NB FLOPs/index).

    Two regimes per call:

    - resident (what a real fused Mosaic lowering would do): table planes
      + the per-block one-hot tile + lane scratch live in VMEM
      (``vmem_bytes``, ~1.6 MB/block at the 100k headline's NB=800) and
      only ``table_bytes`` + ``out_bytes`` touch HBM;
    - streamed worst case (what the XLA interpret lowering measurably
      does — tests/test_mxutake.py pins it): the [G, NB] one-hot operand
      is re-read per chunk plane and word (``onehot_bytes``: 4·w
      dot_generals over the tile) and every [G, 128] MXU-row / lane-mask
      intermediate materializes (``lane_bytes``).

    PERF_MODEL.md "Two-level MXU take" derives the expected native timing
    range from exactly these numbers."""
    nb = -(-n // LANES)
    bg = min(max(r, 1), block_g)
    n_blocks = -(-r // bg)
    table_bytes = w * 4 * nb * LANES * 2          # bf16 chunk planes, HBM
    onehot_tile = bg * nb * 2                     # bf16, per block
    # one full pass over the one-hot operand, re-read per chunk and word
    onehot_bytes = n_blocks * onehot_tile * 4 * w
    # [G, 128] f32 MXU rows + lane one-hot, per chunk per word
    lane_bytes = 2 * r * LANES * 4 * 4 * w
    out_bytes = w * r * 4
    flops = r * (2 * nb + 2 * LANES) * 4 * w      # per-index, 4 chunks
    return {
        "table_bytes": table_bytes,
        "vmem_bytes": table_bytes + onehot_tile + bg * LANES * 4,
        "onehot_bytes": onehot_bytes,
        "lane_bytes": lane_bytes,
        "out_bytes": out_bytes,
        "flops": flops,
    }


def cost_model_payload(n: int, k: int,
                       block_g: int = DEFAULT_BLOCK_G) -> dict:
    """Bytes/FLOP inventory of one blocked payload take
    (``take_payload_onehot``): a W=K-word two-level take over all N*K
    edge indices, plus the K-wide one-hot slot select that re-reads the
    fetched [K, R] rows once (``select_bytes``). Same honest-accounting
    contract as :func:`cost_model` — PERF_MODEL.md "Dispatch table"
    prices the mxu payload-permute formulation from exactly this."""
    m = cost_model(n, n * k, k, block_g)
    m["select_bytes"] = k * (n * k) * 4 + n * k * 4
    # VMEM residency is per word TILE, not per the full K planes
    wt = payload_w_tile(n, k)
    nb = -(-n // LANES)
    m["vmem_bytes"] = (wt * 4 * nb * LANES * 2
                       + min(n * k, block_g) * nb * 2
                       + min(n * k, block_g) * LANES * 4)
    return m


def take_words_twolevel_ref(x_w: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """The XLA reference the kernel must match bit-for-bit."""
    return x_w[:, idx]
