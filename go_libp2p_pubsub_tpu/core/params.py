"""Parameter dataclasses + validation.

Mirrors the reference's three config mechanisms (SURVEY.md §5.6):
- ``GossipSubParams`` defaults (gossipsub.go:32-60, 63-205)
- ``PeerScoreParams`` / ``TopicScoreParams`` / ``PeerScoreThresholds`` with
  the atomic-or-selective validation matrix (score_params.go:12-398)
- ``score_parameter_decay`` helper (score_params.go:407-417)

Durations are virtual-clock float seconds (core/clock.py). All dataclasses are
plain (not frozen) to allow the reference's selective-mutation idiom, but the
batched engine snapshots them into jit-static tuples.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable

from .clock import MILLISECOND, MINUTE, SECOND

# --- gossipsub global defaults (gossipsub.go:32-60) ---
GOSSIPSUB_D = 6
GOSSIPSUB_DLO = 5
GOSSIPSUB_DHI = 12
GOSSIPSUB_DSCORE = 4
GOSSIPSUB_DOUT = 2
GOSSIPSUB_HISTORY_LENGTH = 5
GOSSIPSUB_HISTORY_GOSSIP = 3
GOSSIPSUB_DLAZY = 6
GOSSIPSUB_GOSSIP_FACTOR = 0.25
GOSSIPSUB_GOSSIP_RETRANSMISSION = 3
GOSSIPSUB_HEARTBEAT_INITIAL_DELAY = 100 * MILLISECOND
GOSSIPSUB_HEARTBEAT_INTERVAL = 1 * SECOND
GOSSIPSUB_FANOUT_TTL = 60 * SECOND
GOSSIPSUB_PRUNE_PEERS = 16
GOSSIPSUB_PRUNE_BACKOFF = MINUTE
GOSSIPSUB_UNSUBSCRIBE_BACKOFF = 10 * SECOND
GOSSIPSUB_CONNECTORS = 8
GOSSIPSUB_MAX_PENDING_CONNECTIONS = 128
GOSSIPSUB_CONNECTION_TIMEOUT = 30 * SECOND
GOSSIPSUB_DIRECT_CONNECT_TICKS = 300
GOSSIPSUB_DIRECT_CONNECT_INITIAL_DELAY = 1 * SECOND
GOSSIPSUB_OPPORTUNISTIC_GRAFT_TICKS = 60
GOSSIPSUB_OPPORTUNISTIC_GRAFT_PEERS = 2
GOSSIPSUB_GRAFT_FLOOD_THRESHOLD = 10 * SECOND
GOSSIPSUB_MAX_IHAVE_LENGTH = 5000
GOSSIPSUB_MAX_IHAVE_MESSAGES = 10
GOSSIPSUB_IWANT_FOLLOWUP_TIME = 3 * SECOND

# pubsub-level defaults (pubsub.go:27-36)
DEFAULT_MAX_MESSAGE_SIZE = 1 << 20
TIME_CACHE_DURATION = 120 * SECOND
DEFAULT_PEER_OUTBOUND_QUEUE_SIZE = 32
DEFAULT_VALIDATE_QUEUE_SIZE = 32
DEFAULT_VALIDATE_THROTTLE = 8192
DEFAULT_VALIDATE_CONCURRENCY = 1024


def _invalid(x: float) -> bool:
    """NaN/Inf check (score_params.go:419-423)."""
    return math.isnan(x) or math.isinf(x)


@dataclass
class GossipSubParams:
    """All gossipsub-specific knobs (gossipsub.go:63-205)."""

    d: int = GOSSIPSUB_D
    dlo: int = GOSSIPSUB_DLO
    dhi: int = GOSSIPSUB_DHI
    dscore: int = GOSSIPSUB_DSCORE
    dout: int = GOSSIPSUB_DOUT
    history_length: int = GOSSIPSUB_HISTORY_LENGTH
    history_gossip: int = GOSSIPSUB_HISTORY_GOSSIP
    dlazy: int = GOSSIPSUB_DLAZY
    gossip_factor: float = GOSSIPSUB_GOSSIP_FACTOR
    gossip_retransmission: int = GOSSIPSUB_GOSSIP_RETRANSMISSION
    heartbeat_initial_delay: float = GOSSIPSUB_HEARTBEAT_INITIAL_DELAY
    heartbeat_interval: float = GOSSIPSUB_HEARTBEAT_INTERVAL
    slow_heartbeat_warning: float = 0.1
    fanout_ttl: float = GOSSIPSUB_FANOUT_TTL
    prune_peers: int = GOSSIPSUB_PRUNE_PEERS
    prune_backoff: float = GOSSIPSUB_PRUNE_BACKOFF
    unsubscribe_backoff: float = GOSSIPSUB_UNSUBSCRIBE_BACKOFF
    connectors: int = GOSSIPSUB_CONNECTORS
    max_pending_connections: int = GOSSIPSUB_MAX_PENDING_CONNECTIONS
    connection_timeout: float = GOSSIPSUB_CONNECTION_TIMEOUT
    direct_connect_ticks: int = GOSSIPSUB_DIRECT_CONNECT_TICKS
    direct_connect_initial_delay: float = GOSSIPSUB_DIRECT_CONNECT_INITIAL_DELAY
    opportunistic_graft_ticks: int = GOSSIPSUB_OPPORTUNISTIC_GRAFT_TICKS
    opportunistic_graft_peers: int = GOSSIPSUB_OPPORTUNISTIC_GRAFT_PEERS
    graft_flood_threshold: float = GOSSIPSUB_GRAFT_FLOOD_THRESHOLD
    max_ihave_length: int = GOSSIPSUB_MAX_IHAVE_LENGTH
    max_ihave_messages: int = GOSSIPSUB_MAX_IHAVE_MESSAGES
    iwant_followup_time: float = GOSSIPSUB_IWANT_FOLLOWUP_TIME


@dataclass
class PeerScoreThresholds:
    """Score thresholds gating router behavior (score_params.go:12-35)."""

    skip_atomic_validation: bool = False
    gossip_threshold: float = 0.0
    publish_threshold: float = 0.0
    graylist_threshold: float = 0.0
    accept_px_threshold: float = 0.0
    opportunistic_graft_threshold: float = 0.0

    def validate(self) -> None:
        """Validation per score_params.go:37-64."""
        if (not self.skip_atomic_validation or self.publish_threshold != 0
                or self.gossip_threshold != 0 or self.graylist_threshold != 0):
            if self.gossip_threshold > 0 or _invalid(self.gossip_threshold):
                raise ValueError("invalid gossip threshold; it must be <= 0 and a valid number")
            if (self.publish_threshold > 0 or self.publish_threshold > self.gossip_threshold
                    or _invalid(self.publish_threshold)):
                raise ValueError(
                    "invalid publish threshold; it must be <= 0 and <= gossip threshold and a valid number")
            if (self.graylist_threshold > 0 or self.graylist_threshold > self.publish_threshold
                    or _invalid(self.graylist_threshold)):
                raise ValueError(
                    "invalid graylist threshold; it must be <= 0 and <= publish threshold and a valid number")
        if not self.skip_atomic_validation or self.accept_px_threshold != 0:
            if self.accept_px_threshold < 0 or _invalid(self.accept_px_threshold):
                raise ValueError("invalid accept PX threshold; it must be >= 0 and a valid number")
        if not self.skip_atomic_validation or self.opportunistic_graft_threshold != 0:
            if self.opportunistic_graft_threshold < 0 or _invalid(self.opportunistic_graft_threshold):
                raise ValueError(
                    "invalid opportunistic grafting threshold; it must be >= 0 and a valid number")


@dataclass
class TopicScoreParams:
    """Per-topic score function parameters P1-P4 (score_params.go:117-170)."""

    skip_atomic_validation: bool = False
    topic_weight: float = 0.0
    # P1: time in mesh
    time_in_mesh_weight: float = 0.0
    time_in_mesh_quantum: float = 0.0
    time_in_mesh_cap: float = 0.0
    # P2: first message deliveries
    first_message_deliveries_weight: float = 0.0
    first_message_deliveries_decay: float = 0.0
    first_message_deliveries_cap: float = 0.0
    # P3: mesh message delivery rate
    mesh_message_deliveries_weight: float = 0.0
    mesh_message_deliveries_decay: float = 0.0
    mesh_message_deliveries_cap: float = 0.0
    mesh_message_deliveries_threshold: float = 0.0
    mesh_message_deliveries_window: float = 0.0
    mesh_message_deliveries_activation: float = 0.0
    # P3b: sticky mesh failure penalty
    mesh_failure_penalty_weight: float = 0.0
    mesh_failure_penalty_decay: float = 0.0
    # P4: invalid messages
    invalid_message_deliveries_weight: float = 0.0
    invalid_message_deliveries_decay: float = 0.0

    def validate(self) -> None:
        """Validation per score_params.go:236-398 (atomic or selective)."""
        if self.topic_weight < 0 or _invalid(self.topic_weight):
            raise ValueError("invalid topic weight; must be >= 0 and a valid number")
        self._validate_time_in_mesh()
        self._validate_first_message_deliveries()
        self._validate_mesh_message_deliveries()
        self._validate_mesh_failure_penalty()
        self._validate_invalid_message_deliveries()

    def _validate_time_in_mesh(self) -> None:
        if self.skip_atomic_validation and (
                self.time_in_mesh_weight == 0 and self.time_in_mesh_quantum == 0
                and self.time_in_mesh_cap == 0):
            return
        if self.time_in_mesh_quantum == 0:
            raise ValueError("invalid TimeInMeshQuantum; must be non zero")
        if self.time_in_mesh_weight < 0 or _invalid(self.time_in_mesh_weight):
            raise ValueError("invalid TimeInMeshWeight; must be positive (or 0 to disable)")
        if self.time_in_mesh_weight != 0 and self.time_in_mesh_quantum <= 0:
            raise ValueError("invalid TimeInMeshQuantum; must be positive")
        if self.time_in_mesh_weight != 0 and (
                self.time_in_mesh_cap <= 0 or _invalid(self.time_in_mesh_cap)):
            raise ValueError("invalid TimeInMeshCap; must be positive")

    def _validate_first_message_deliveries(self) -> None:
        if self.skip_atomic_validation and (
                self.first_message_deliveries_weight == 0
                and self.first_message_deliveries_cap == 0
                and self.first_message_deliveries_decay == 0):
            return
        w = self.first_message_deliveries_weight
        if w < 0 or _invalid(w):
            raise ValueError("invalid FirstMessageDeliveriesWeight; must be positive (or 0 to disable)")
        if w != 0 and (self.first_message_deliveries_decay <= 0
                       or self.first_message_deliveries_decay >= 1
                       or _invalid(self.first_message_deliveries_decay)):
            raise ValueError("invalid FirstMessageDeliveriesDecay; must be between 0 and 1")
        if w != 0 and (self.first_message_deliveries_cap <= 0
                       or _invalid(self.first_message_deliveries_cap)):
            raise ValueError("invalid FirstMessageDeliveriesCap; must be positive")

    def _validate_mesh_message_deliveries(self) -> None:
        if self.skip_atomic_validation and (
                self.mesh_message_deliveries_weight == 0
                and self.mesh_message_deliveries_cap == 0
                and self.mesh_message_deliveries_decay == 0
                and self.mesh_message_deliveries_threshold == 0
                and self.mesh_message_deliveries_window == 0
                and self.mesh_message_deliveries_activation == 0):
            return
        w = self.mesh_message_deliveries_weight
        if w > 0 or _invalid(w):
            raise ValueError("invalid MeshMessageDeliveriesWeight; must be negative (or 0 to disable)")
        if w != 0 and (self.mesh_message_deliveries_decay <= 0
                       or self.mesh_message_deliveries_decay >= 1
                       or _invalid(self.mesh_message_deliveries_decay)):
            raise ValueError("invalid MeshMessageDeliveriesDecay; must be between 0 and 1")
        if w != 0 and (self.mesh_message_deliveries_cap <= 0
                       or _invalid(self.mesh_message_deliveries_cap)):
            raise ValueError("invalid MeshMessageDeliveriesCap; must be positive")
        if w != 0 and (self.mesh_message_deliveries_threshold <= 0
                       or _invalid(self.mesh_message_deliveries_threshold)):
            raise ValueError("invalid MeshMessageDeliveriesThreshold; must be positive")
        if self.mesh_message_deliveries_window < 0:
            raise ValueError("invalid MeshMessageDeliveriesWindow; must be non-negative")
        if w != 0 and self.mesh_message_deliveries_activation < 1 * SECOND:
            raise ValueError("invalid MeshMessageDeliveriesActivation; must be at least 1s")

    def _validate_mesh_failure_penalty(self) -> None:
        if self.skip_atomic_validation and (
                self.mesh_failure_penalty_decay == 0 and self.mesh_failure_penalty_weight == 0):
            return
        if self.mesh_failure_penalty_weight > 0 or _invalid(self.mesh_failure_penalty_weight):
            raise ValueError("invalid MeshFailurePenaltyWeight; must be negative (or 0 to disable)")
        if self.mesh_failure_penalty_weight != 0 and (
                _invalid(self.mesh_failure_penalty_decay)
                or self.mesh_failure_penalty_decay <= 0
                or self.mesh_failure_penalty_decay >= 1):
            raise ValueError("invalid MeshFailurePenaltyDecay; must be between 0 and 1")

    def _validate_invalid_message_deliveries(self) -> None:
        if self.skip_atomic_validation and (
                self.invalid_message_deliveries_decay == 0
                and self.invalid_message_deliveries_weight == 0):
            return
        if self.invalid_message_deliveries_weight > 0 or _invalid(self.invalid_message_deliveries_weight):
            raise ValueError("invalid InvalidMessageDeliveriesWeight; must be negative (or 0 to disable)")
        if (self.invalid_message_deliveries_decay <= 0
                or self.invalid_message_deliveries_decay >= 1
                or _invalid(self.invalid_message_deliveries_decay)):
            raise ValueError("invalid InvalidMessageDeliveriesDecay; must be between 0 and 1")


@dataclass
class PeerScoreParams:
    """Global score function parameters P5-P7 + per-topic table (score_params.go:66-115)."""

    skip_atomic_validation: bool = False
    topics: dict[str, TopicScoreParams] = field(default_factory=dict)
    topic_score_cap: float = 0.0
    app_specific_score: Callable[[str], float] | None = None
    app_specific_weight: float = 0.0
    ip_colocation_factor_weight: float = 0.0
    ip_colocation_factor_threshold: int = 0
    ip_colocation_factor_whitelist: list[str] = field(default_factory=list)  # CIDR strings
    behaviour_penalty_weight: float = 0.0
    behaviour_penalty_threshold: float = 0.0
    behaviour_penalty_decay: float = 0.0
    decay_interval: float = 0.0
    decay_to_zero: float = 0.0
    retain_score: float = 0.0
    seen_msg_ttl: float = 0.0

    def validate(self) -> None:
        """Validation per score_params.go:173-234."""
        for topic, tp in self.topics.items():
            try:
                tp.validate()
            except ValueError as e:
                raise ValueError(f"invalid score parameters for topic {topic}: {e}") from e

        if not self.skip_atomic_validation or self.topic_score_cap != 0:
            if self.topic_score_cap < 0 or _invalid(self.topic_score_cap):
                raise ValueError("invalid topic score cap; must be positive (or 0 for no cap)")

        if self.app_specific_score is None:
            if self.skip_atomic_validation:
                self.app_specific_score = lambda p: 0.0
            else:
                raise ValueError("missing application specific score function")

        if not self.skip_atomic_validation or self.ip_colocation_factor_weight != 0:
            if self.ip_colocation_factor_weight > 0 or _invalid(self.ip_colocation_factor_weight):
                raise ValueError(
                    "invalid IPColocationFactorWeight; must be negative (or 0 to disable)")
            if self.ip_colocation_factor_weight != 0 and self.ip_colocation_factor_threshold < 1:
                raise ValueError("invalid IPColocationFactorThreshold; must be at least 1")

        if (not self.skip_atomic_validation or self.behaviour_penalty_weight != 0
                or self.behaviour_penalty_threshold != 0):
            if self.behaviour_penalty_weight > 0 or _invalid(self.behaviour_penalty_weight):
                raise ValueError("invalid BehaviourPenaltyWeight; must be negative (or 0 to disable)")
            if self.behaviour_penalty_weight != 0 and (
                    self.behaviour_penalty_decay <= 0 or self.behaviour_penalty_decay >= 1
                    or _invalid(self.behaviour_penalty_decay)):
                raise ValueError("invalid BehaviourPenaltyDecay; must be between 0 and 1")
            if self.behaviour_penalty_threshold < 0 or _invalid(self.behaviour_penalty_threshold):
                raise ValueError("invalid BehaviourPenaltyThreshold; must be >= 0")

        if not self.skip_atomic_validation or self.decay_interval != 0 or self.decay_to_zero != 0:
            if self.decay_interval < 1 * SECOND:
                raise ValueError("invalid DecayInterval; must be at least 1s")
            if self.decay_to_zero <= 0 or self.decay_to_zero >= 1 or _invalid(self.decay_to_zero):
                raise ValueError("invalid DecayToZero; must be between 0 and 1")


DEFAULT_DECAY_INTERVAL = 1 * SECOND
DEFAULT_DECAY_TO_ZERO = 0.01


def score_parameter_decay_with_base(decay: float, base: float, decay_to_zero: float) -> float:
    """factor^n = decay_to_zero for n = decay/base ticks (score_params.go:412-417).

    Matches Go's integer duration division truncation; for decay < base the
    tick count truncates to 0 and the factor is decay_to_zero^Inf == 0."""
    ticks = float(int(decay / base))
    if ticks == 0.0:
        return 0.0
    return decay_to_zero ** (1.0 / ticks)


def score_parameter_decay(decay: float) -> float:
    """Decay factor assuming 1s DecayInterval, 0.01 floor (score_params.go:407-410)."""
    return score_parameter_decay_with_base(decay, DEFAULT_DECAY_INTERVAL, DEFAULT_DECAY_TO_ZERO)
