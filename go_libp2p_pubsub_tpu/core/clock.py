"""Virtual time for the deterministic runtime.

The reference mixes wall-clock durations (backoff expiry, TTLs, score
activation windows) with tick-based logic (heartbeats). Here everything lives
in ONE virtual-clock domain measured in float seconds; the batched engine
further quantizes to heartbeat ticks (SURVEY.md §7 "Time").

Durations are plain floats in seconds. Constants below mirror Go's
time.Millisecond / time.Second / time.Minute units so parameter defaults read
the same as the reference's (e.g. gossipsub.go:41-58).
"""

from __future__ import annotations

MILLISECOND = 1e-3
SECOND = 1.0
MINUTE = 60.0
HOUR = 3600.0


class VirtualClock:
    """A monotonically advancing virtual clock owned by the scheduler."""

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> None:
        if t < self._now:
            raise ValueError(f"clock going backwards: {t} < {self._now}")
        self._now = t
