from .clock import VirtualClock, MILLISECOND, SECOND, MINUTE, HOUR  # noqa: F401
from .params import (  # noqa: F401
    GossipSubParams,
    PeerScoreParams,
    PeerScoreThresholds,
    TopicScoreParams,
    score_parameter_decay,
)
from .types import AcceptStatus, Message, RPC, ControlMessage  # noqa: F401
