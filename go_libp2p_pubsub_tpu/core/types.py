"""Core wire-level and router-level types.

Dataclass mirrors of the reference wire schema (pb/rpc.proto:5-57) used by the
in-process runtime; the protobuf serialization lives in
``go_libp2p_pubsub_tpu.pb``. Peer identity is an opaque string (the reference
uses libp2p peer.ID); the batched engine maps peers to dense int32 indices.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


# Peer identifiers are opaque strings in the functional core.
PeerID = str


class AcceptStatus(enum.Enum):
    """Router vetting verdict for an incoming RPC (pubsub.go:217-227)."""

    ACCEPT_NONE = 0      # drop the RPC entirely (graylisted peer)
    ACCEPT_CONTROL = 1   # process control messages only, strip payloads
    ACCEPT_ALL = 2       # process everything


@dataclass
class Message:
    """A pubsub message (pb/rpc.proto Message{from,data,seqno,topic,signature,key}).

    ``from_peer`` is the author (may differ from the forwarding peer);
    ``received_from`` is runtime metadata, not serialized.
    """

    from_peer: PeerID | None = None
    data: bytes = b""
    seqno: bytes | None = None
    topic: str = ""
    signature: bytes | None = None
    key: bytes | None = None
    # runtime-only metadata (Message wrapper, pubsub.go:986-1007)
    received_from: PeerID | None = None
    validator_data: object = None
    local: bool = False
    # cached canonical id (midgen.go:39-52); cache state, not identity
    _id: str | None = field(default=None, compare=False, repr=False)

    def get_from(self) -> PeerID | None:
        return self.from_peer


@dataclass
class SubOpts:
    """A subscription announcement (pb/rpc.proto SubOpts)."""

    subscribe: bool = True
    topicid: str = ""


@dataclass
class ControlIHave:
    topic: str = ""
    message_ids: list[str] = field(default_factory=list)


@dataclass
class ControlIWant:
    message_ids: list[str] = field(default_factory=list)


@dataclass
class ControlGraft:
    topic: str = ""


@dataclass
class PeerInfo:
    """Peer-exchange record carried in PRUNE (pb/rpc.proto PeerInfo)."""

    peer_id: PeerID = ""
    signed_peer_record: bytes | None = None


@dataclass
class ControlPrune:
    topic: str = ""
    peers: list[PeerInfo] = field(default_factory=list)
    backoff: float = 0.0  # seconds; wire uses uint64 seconds


@dataclass
class ControlMessage:
    ihave: list[ControlIHave] = field(default_factory=list)
    iwant: list[ControlIWant] = field(default_factory=list)
    graft: list[ControlGraft] = field(default_factory=list)
    prune: list[ControlPrune] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (self.ihave or self.iwant or self.graft or self.prune)


@dataclass
class RPC:
    """One wire frame (pb/rpc.proto RPC{subscriptions, publish, control})."""

    subscriptions: list[SubOpts] = field(default_factory=list)
    publish: list[Message] = field(default_factory=list)
    control: ControlMessage | None = None
    # runtime-only: which peer this RPC came from (comm.go:84)
    from_peer: PeerID | None = None

    def size(self) -> int:
        """Approximate serialized size, used for fragmentation decisions
        (gossipsub.go:1204-1293). Computed from the dataclass contents with
        protobuf-style overhead estimates; exactness is not required, only a
        consistent, monotone measure."""
        n = 0
        for s in self.subscriptions:
            n += len(s.topicid.encode()) + 4
        for m in self.publish:
            n += len(m.data) + len(m.topic.encode())
            n += len(m.seqno or b"") + len(m.signature or b"") + len(m.key or b"")
            n += len((m.from_peer or "").encode()) + 12
        if self.control is not None:
            c = self.control
            for ih in c.ihave:
                n += len(ih.topic.encode()) + sum(len(mid.encode()) + 2 for mid in ih.message_ids) + 4
            for iw in c.iwant:
                n += sum(len(mid.encode()) + 2 for mid in iw.message_ids) + 4
            for g in c.graft:
                n += len(g.topic.encode()) + 4
            for pr in c.prune:
                n += len(pr.topic.encode()) + 14
                for pi in pr.peers:
                    n += len(pi.peer_id.encode()) + len(pi.signed_peer_record or b"") + 4
        return n


def trim_rpc(rpc: RPC) -> RPC | None:
    """Return None if the RPC carries nothing."""
    if rpc.subscriptions or rpc.publish or (rpc.control and not rpc.control.is_empty()):
        return rpc
    return None
