"""Validation pipeline (validation.go).

Queue caps and throttles are preserved as *counters* on the deterministic
scheduler instead of goroutines/channels:

- front-end queue: ``validate_queue_size`` pending requests; overflow drops
  with RejectValidationQueueFull (validation.go:246-260)
- sync workers: requests drain from the queue after ``worker_delay`` virtual
  seconds (the off-loop hop the reference gets from its NumCPU workers)
- async validators: bounded by the global throttle (8192) and per-validator
  throttle (1024); overflow -> RejectValidationThrottled / peer throttled
  (validation.go:344-370, 459-500)
- the signature check -> mark-seen -> inline validators -> async validators
  ordering matches validation.go:293-370
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable

from ..core.params import (
    DEFAULT_VALIDATE_CONCURRENCY,
    DEFAULT_VALIDATE_QUEUE_SIZE,
    DEFAULT_VALIDATE_THROTTLE,
)
from ..core.types import Message, PeerID
from ..trace import events as ev
from .sign import SignError, verify_message_signature

if TYPE_CHECKING:
    from .pubsub import PubSub

# ValidationResult (validation.go:36-52)
VALIDATION_ACCEPT = 0
VALIDATION_REJECT = 1
VALIDATION_IGNORE = 2

ValidatorEx = Callable[[PeerID, Message], int]


class ValidationError(ValueError):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class ValidatorImpl:
    def __init__(self, topic: str, validate: ValidatorEx, throttle: int,
                 inline: bool):
        self.topic = topic
        self.validate = validate
        self.throttle = throttle
        self.inflight = 0
        self.inline = inline


def as_validator_ex(fn) -> ValidatorEx:
    """Accept bool-returning Validator or enum ValidatorEx (validation.go:163-192)."""
    def wrapped(src: PeerID, msg: Message) -> int:
        r = fn(src, msg)
        if isinstance(r, bool):
            return VALIDATION_ACCEPT if r else VALIDATION_REJECT
        return int(r)
    return wrapped


class Validation:
    def __init__(self, queue_size: int = DEFAULT_VALIDATE_QUEUE_SIZE,
                 throttle: int = DEFAULT_VALIDATE_THROTTLE,
                 worker_delay: float = 0.0):
        self.p: "PubSub | None" = None
        self.topic_vals: dict[str, ValidatorImpl] = {}
        self.default_vals: list[ValidatorImpl] = []
        self.queue_size = queue_size
        self.queued = 0
        self.throttle_cap = throttle
        self.throttled = 0
        self.worker_delay = worker_delay

    def start(self, p: "PubSub") -> None:
        self.p = p

    # -- registration (validation.go:140-226) --

    def add_validator(self, topic: str, validate, throttle: int = 0,
                      inline: bool = False) -> None:
        if topic in self.topic_vals:
            raise ValueError(f"duplicate validator for topic {topic}")
        self.topic_vals[topic] = ValidatorImpl(
            topic, as_validator_ex(validate),
            throttle or DEFAULT_VALIDATE_CONCURRENCY, inline)

    def add_default_validator(self, validate, inline: bool = False) -> None:
        self.default_vals.append(ValidatorImpl(
            "", as_validator_ex(validate), DEFAULT_VALIDATE_CONCURRENCY, inline))

    def remove_validator(self, topic: str) -> None:
        if topic not in self.topic_vals:
            raise ValueError(f"no validator for topic {topic}")
        del self.topic_vals[topic]

    def get_validators(self, msg: Message) -> list[ValidatorImpl]:
        vals = list(self.default_vals)
        v = self.topic_vals.get(msg.topic)
        return vals + [v] if v is not None else vals

    # -- entry points --

    def push_local(self, msg: Message) -> None:
        """Synchronous local-publish path (validation.go:232-242).
        Raises ValidationError on rejection."""
        p = self.p
        assert p is not None
        p.tracer.publish_message(msg)
        p.check_signing_policy(msg)  # raises on policy violation
        self._validate(self.get_validators(msg), msg.received_from, msg,
                       synchronous=True)

    def push(self, src: PeerID, msg: Message) -> bool:
        """Inbound path; True means forward immediately, no validation needed
        (validation.go:246-260)."""
        p = self.p
        assert p is not None
        vals = self.get_validators(msg)
        if vals or msg.signature is not None:
            if self.queued >= self.queue_size:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_QUEUE_FULL)
                return False
            self.queued += 1

            def worker():
                self.queued -= 1
                try:
                    self._validate(vals, src, msg, synchronous=False)
                except ValidationError:
                    pass

            if self.worker_delay > 0:
                p.scheduler.call_later(self.worker_delay, worker)
            else:
                worker()
            return False
        return True

    # -- the pipeline (validation.go:293-370) --

    def _validate(self, vals: list[ValidatorImpl], src: PeerID | None,
                  msg: Message, synchronous: bool) -> None:
        p = self.p
        assert p is not None
        if msg.signature is not None:
            try:
                verify_message_signature(msg)
            except SignError:
                p.tracer.reject_message(msg, ev.REJECT_INVALID_SIGNATURE)
                raise ValidationError(ev.REJECT_INVALID_SIGNATURE) from None

        # mark seen after signature verification, before user validators
        mid = p.id_gen.id(msg)
        if not p.mark_seen(mid):
            p.tracer.duplicate_message(msg)
            return
        p.tracer.validate_message(msg)

        inline = [v for v in vals if v.inline or synchronous]
        async_vals = [v for v in vals if not (v.inline or synchronous)]

        result = VALIDATION_ACCEPT
        for v in inline:
            r = v.validate(src, msg)
            if r == VALIDATION_REJECT:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_FAILED)
                raise ValidationError(ev.REJECT_VALIDATION_FAILED)
            if r == VALIDATION_IGNORE:
                result = VALIDATION_IGNORE

        if async_vals:
            if self.throttled >= self.throttle_cap:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_THROTTLED)
                return
            self.throttled += 1
            self._do_validate_topic(async_vals, src, msg, result)
            self.throttled -= 1
            return

        if result == VALIDATION_IGNORE:
            p.tracer.reject_message(msg, ev.REJECT_VALIDATION_IGNORED)
            raise ValidationError(ev.REJECT_VALIDATION_IGNORED)

        p.deliver_validated(msg)

    def _do_validate_topic(self, vals: list[ValidatorImpl], src: PeerID | None,
                           msg: Message, prior: int) -> None:
        """Async leg (validation.go:410-500) with per-validator throttles."""
        p = self.p
        assert p is not None
        result = prior
        for v in vals:
            if v.inflight >= v.throttle:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_THROTTLED)
                p.tracer.throttle_peer(src)
                return
            v.inflight += 1
            try:
                r = v.validate(src, msg)
            finally:
                v.inflight -= 1
            if r == VALIDATION_REJECT:
                result = VALIDATION_REJECT
                break
            if r == VALIDATION_IGNORE:
                result = VALIDATION_IGNORE
        if result == VALIDATION_REJECT:
            p.tracer.reject_message(msg, ev.REJECT_VALIDATION_FAILED)
            return
        if result == VALIDATION_IGNORE:
            p.tracer.reject_message(msg, ev.REJECT_VALIDATION_IGNORED)
            return
        p.deliver_validated(msg)
