"""Validation pipeline (validation.go).

Queue caps and throttles are preserved as *counters* on the deterministic
scheduler instead of goroutines/channels:

- front-end queue: ``validate_queue_size`` pending requests; overflow drops
  with RejectValidationQueueFull (validation.go:246-260)
- sync workers: requests drain from the queue after ``worker_delay`` virtual
  seconds (the off-loop hop the reference gets from its NumCPU workers)
- async validators: bounded by the global throttle (8192) and per-validator
  throttle (1024); overflow -> RejectValidationThrottled / peer throttled
  (validation.go:344-370, 459-500)
- the signature check -> mark-seen -> inline validators -> async validators
  ordering matches validation.go:293-370
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable

from ..core.params import (
    DEFAULT_VALIDATE_CONCURRENCY,
    DEFAULT_VALIDATE_QUEUE_SIZE,
    DEFAULT_VALIDATE_THROTTLE,
)
from ..core.types import Message, PeerID
from ..trace import events as ev
from .sign import SignError, verify_message_signature

if TYPE_CHECKING:
    from .pubsub import PubSub

# ValidationResult (validation.go:36-52)
VALIDATION_ACCEPT = 0
VALIDATION_REJECT = 1
VALIDATION_IGNORE = 2

ValidatorEx = Callable[[PeerID, Message], int]


class ValidationError(ValueError):
    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class ValidatorImpl:
    def __init__(self, topic: str, validate: ValidatorEx, throttle: int,
                 inline: bool, timeout: float = 0.0):
        self.topic = topic
        self.validate = validate
        self.throttle = throttle
        self.inflight = 0
        self.inline = inline
        # WithValidatorTimeout (validation.go:564-570): deadline for the
        # async leg, in virtual seconds; 0 = none
        self.timeout = timeout


def as_validator_ex(fn) -> ValidatorEx:
    """Accept bool-returning Validator or enum ValidatorEx (validation.go:163-192)."""
    def wrapped(src: PeerID, msg: Message) -> int:
        r = fn(src, msg)
        if isinstance(r, bool):
            return VALIDATION_ACCEPT if r else VALIDATION_REJECT
        return int(r)
    # a validator may model its execution time on the virtual clock; the
    # async leg uses it for deadline (timeout) semantics
    wrapped.virtual_duration = getattr(fn, "virtual_duration", 0.0)
    return wrapped


class Validation:
    def __init__(self, queue_size: int = DEFAULT_VALIDATE_QUEUE_SIZE,
                 throttle: int = DEFAULT_VALIDATE_THROTTLE,
                 worker_delay: float = 0.0):
        self.p: "PubSub | None" = None
        self.topic_vals: dict[str, ValidatorImpl] = {}
        self.default_vals: list[ValidatorImpl] = []
        self.queue_size = queue_size
        self.queued = 0
        self.throttle_cap = throttle
        self.throttled = 0
        self.worker_delay = worker_delay

    def start(self, p: "PubSub") -> None:
        self.p = p

    # -- registration (validation.go:140-226) --

    def add_validator(self, topic: str, validate, throttle: int = 0,
                      inline: bool = False, timeout: float = 0.0) -> None:
        if topic in self.topic_vals:
            raise ValueError(f"duplicate validator for topic {topic}")
        self.topic_vals[topic] = ValidatorImpl(
            topic, as_validator_ex(validate),
            throttle or DEFAULT_VALIDATE_CONCURRENCY, inline, timeout)

    def add_default_validator(self, validate, inline: bool = False,
                              timeout: float = 0.0) -> None:
        self.default_vals.append(ValidatorImpl(
            "", as_validator_ex(validate), DEFAULT_VALIDATE_CONCURRENCY,
            inline, timeout))

    @staticmethod
    def _run_validator(v: ValidatorImpl, src: PeerID | None,
                       msg: Message) -> tuple[int, float]:
        """validateMsg (validation.go:473-497): run one validator under its
        deadline. A validator models its execution time on the virtual clock
        via a ``virtual_duration`` attribute; exceeding ``timeout`` means
        the context expires and the verdict is IGNORE (the reference's
        ctx-respecting validators return ignore on deadline). Returns
        (result, virtual seconds consumed)."""
        dur = getattr(v.validate, "virtual_duration", 0.0)
        if v.timeout > 0 and dur > v.timeout:
            return VALIDATION_IGNORE, v.timeout
        return v.validate(src, msg), dur

    def remove_validator(self, topic: str) -> None:
        if topic not in self.topic_vals:
            raise ValueError(f"no validator for topic {topic}")
        del self.topic_vals[topic]

    def get_validators(self, msg: Message) -> list[ValidatorImpl]:
        vals = list(self.default_vals)
        v = self.topic_vals.get(msg.topic)
        return vals + [v] if v is not None else vals

    # -- entry points --

    def push_local(self, msg: Message) -> None:
        """Synchronous local-publish path (validation.go:232-242).
        Raises ValidationError on rejection."""
        p = self.p
        assert p is not None
        p.tracer.publish_message(msg)
        p.check_signing_policy(msg)  # raises on policy violation
        self._validate(self.get_validators(msg), msg.received_from, msg,
                       synchronous=True)

    def push(self, src: PeerID, msg: Message) -> bool:
        """Inbound path; True means forward immediately, no validation needed
        (validation.go:246-260)."""
        p = self.p
        assert p is not None
        vals = self.get_validators(msg)
        if vals or msg.signature is not None:
            if self.queued >= self.queue_size:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_QUEUE_FULL)
                return False
            self.queued += 1

            def worker():
                self.queued -= 1
                try:
                    self._validate(vals, src, msg, synchronous=False)
                except ValidationError:
                    pass

            if self.worker_delay > 0:
                p.scheduler.call_later(self.worker_delay, worker)
            else:
                worker()
            return False
        return True

    # -- the pipeline (validation.go:293-370) --

    def _validate(self, vals: list[ValidatorImpl], src: PeerID | None,
                  msg: Message, synchronous: bool) -> None:
        p = self.p
        assert p is not None
        if msg.signature is not None:
            try:
                verify_message_signature(msg)
            except SignError:
                p.tracer.reject_message(msg, ev.REJECT_INVALID_SIGNATURE)
                raise ValidationError(ev.REJECT_INVALID_SIGNATURE) from None

        # mark seen after signature verification, before user validators
        mid = p.id_gen.id(msg)
        if not p.mark_seen(mid):
            p.tracer.duplicate_message(msg)
            return
        p.tracer.validate_message(msg)

        inline = [v for v in vals if v.inline or synchronous]
        async_vals = [v for v in vals if not (v.inline or synchronous)]

        result = VALIDATION_ACCEPT
        for v in inline:
            # deadline applies to the inline leg too: the reference's
            # inline loop also calls validateMsg (validation.go:326-327);
            # the caller stays synchronous, only the verdict reflects it
            r, _ = self._run_validator(v, src, msg)
            if r == VALIDATION_REJECT:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_FAILED)
                raise ValidationError(ev.REJECT_VALIDATION_FAILED)
            if r == VALIDATION_IGNORE:
                result = VALIDATION_IGNORE

        if async_vals:
            if self.throttled >= self.throttle_cap:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_THROTTLED)
                return
            # the global throttle slot is held until the async leg's verdict
            # lands (the reference's validation goroutine lifetime); with
            # slow validators that is `elapsed` virtual seconds later
            self.throttled += 1
            self._do_validate_topic(async_vals, src, msg, result)
            return

        if result == VALIDATION_IGNORE:
            p.tracer.reject_message(msg, ev.REJECT_VALIDATION_IGNORED)
            raise ValidationError(ev.REJECT_VALIDATION_IGNORED)

        p.deliver_validated(msg)

    def _do_validate_topic(self, vals: list[ValidatorImpl], src: PeerID | None,
                           msg: Message, prior: int) -> None:
        """Async leg (validation.go:410-500) with per-validator throttles
        and deadlines. Validators with a nonzero virtual duration hold their
        throttle slot and defer the verdict until that much virtual time
        elapses (the reference's validator goroutine blocking on a slow
        validate call); a validator over its timeout contributes only the
        timeout and yields IGNORE (validateMsg ctx deadline,
        validation.go:479-483)."""
        p = self.p
        assert p is not None
        result = prior
        elapsed = 0.0
        acquired: list[ValidatorImpl] = []
        try:
            for v in vals:
                if v.inflight >= v.throttle:
                    for a in acquired:
                        a.inflight -= 1
                    self.throttled -= 1
                    p.tracer.reject_message(msg, ev.REJECT_VALIDATION_THROTTLED)
                    p.tracer.throttle_peer(src)
                    return
                v.inflight += 1
                acquired.append(v)
                r, dur = self._run_validator(v, src, msg)
                # validators run CONCURRENTLY in the reference (one
                # goroutine each, validation.go:428-456): latency is the
                # max of their durations, not the sum
                elapsed = max(elapsed, dur)
                if r == VALIDATION_REJECT:
                    result = VALIDATION_REJECT
                    break
                if r == VALIDATION_IGNORE:
                    result = VALIDATION_IGNORE
        except BaseException:
            # a raising user validator must not leak throttle slots
            for a in acquired:
                a.inflight -= 1
            self.throttled -= 1
            raise

        def finish():
            for a in acquired:
                a.inflight -= 1
            self.throttled -= 1
            if result == VALIDATION_REJECT:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_FAILED)
                return
            if result == VALIDATION_IGNORE:
                p.tracer.reject_message(msg, ev.REJECT_VALIDATION_IGNORED)
                return
            p.deliver_validated(msg)

        if elapsed > 0:
            p.scheduler.call_later(elapsed, finish)
        else:
            finish()
