from .pubsub import PubSub  # noqa: F401
from .sign import (  # noqa: F401
    LAX_NO_SIGN,
    LAX_SIGN,
    STRICT_NO_SIGN,
    STRICT_SIGN,
    SignError,
    SignPolicy,
    generate_keypair,
    sign_message,
    verify_message_signature,
)
from .subscription import Subscription  # noqa: F401
from .topic import PeerEvent, Topic, TopicEventHandler  # noqa: F401
from .validation import (  # noqa: F401
    VALIDATION_ACCEPT,
    VALIDATION_IGNORE,
    VALIDATION_REJECT,
    Validation,
    ValidationError,
)
