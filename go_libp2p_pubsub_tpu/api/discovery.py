"""Discovery bridge (discovery.go).

A poll loop asks the router ``enough_peers`` per joined topic and fans out
``find_peers`` to a pluggable discovery service; joined topics are advertised
with periodic re-advertisement; ``bootstrap`` blocks publishing readiness
until the router reports enough peers (discovery.go:51-297).

The default service is ``NetworkDiscovery``: a rendezvous registry over the
simulated substrate (the stand-in for the DHT), namespaced ``floodsub:<topic>``
like the reference (discovery.go:324-328).
"""

from __future__ import annotations

import random
from typing import TYPE_CHECKING, Callable, Protocol

from ..core.clock import SECOND
from ..core.types import PeerID

if TYPE_CHECKING:
    from .pubsub import PubSub

DISCOVERY_POLL_INITIAL_DELAY = 0 * SECOND
DISCOVERY_POLL_INTERVAL = 1 * SECOND


def namespace(topic: str) -> str:
    return f"floodsub:{topic}"


class DiscoveryService(Protocol):
    """discovery.Discovery analogue: advertise + find_peers."""

    def advertise(self, ns: str, peer: PeerID) -> float:
        """Register; returns the TTL until re-advertisement is needed."""
        ...

    def find_peers(self, ns: str, limit: int) -> list[PeerID]: ...


class NetworkDiscovery:
    """Rendezvous registry over the substrate (the mock DHT the reference's
    tests build by hand, discovery_test.go:27-113)."""

    def __init__(self, ttl: float = 120.0, rng: random.Random | None = None):
        self.ttl = ttl
        self._reg: dict[str, dict[PeerID, float]] = {}
        self.rng = rng or random.Random(0)
        self._now: Callable[[], float] = lambda: 0.0

    def bind(self, now: Callable[[], float]) -> None:
        self._now = now

    def advertise(self, ns: str, peer: PeerID) -> float:
        self._reg.setdefault(ns, {})[peer] = self._now() + self.ttl
        return self.ttl

    def find_peers(self, ns: str, limit: int) -> list[PeerID]:
        now = self._now()
        entries = self._reg.get(ns, {})
        live = sorted(p for p, exp in entries.items() if exp > now)
        self.rng.shuffle(live)
        return live[:limit] if limit else live


class Discover:
    """The per-node discovery pipeline (discovery.go:50-84)."""

    def __init__(self, service: DiscoveryService | None,
                 min_peers: int = 0):
        self.service = service
        self.p: "PubSub | None" = None
        self.advertising: dict[str, int] = {}  # topic -> chain generation
        self.min_peers = min_peers

    def start(self, p: "PubSub") -> None:
        if self.service is None:
            return
        self.p = p
        if isinstance(self.service, NetworkDiscovery):
            self.service.bind(p.scheduler.now)
        p.scheduler.call_every(DISCOVERY_POLL_INTERVAL, self._poll)

    def _poll(self) -> None:
        """requestDiscovery (discovery.go:139-145)."""
        assert self.p is not None
        for topic in list(self.p.my_topics):
            if not self.p.rt.enough_peers(topic, 0):
                self._handle_discovery(topic)

    def _handle_discovery(self, topic: str) -> None:
        assert self.p is not None and self.service is not None
        found = self.service.find_peers(namespace(topic), limit=0)
        for pid in found:
            if pid == self.p.pid or pid in self.p.host.conns:
                continue
            other = self.p.host.network.hosts.get(pid)
            if other is not None:
                self.p.host.connect(other)

    def advertise(self, topic: str) -> None:
        """discovery.go:177-218, with TTL-driven re-advertisement."""
        if self.service is None or self.p is None:
            return
        if topic in self.advertising:
            return
        # generation guard: a cancel+re-advertise cycle must not leave the old
        # timer chain alive alongside the new one
        gen = self.advertising[topic] = self._gen = getattr(self, "_gen", 0) + 1

        def readvertise():
            if self.advertising.get(topic) != gen:
                return  # chain superseded or stopped
            assert self.p is not None
            ttl = self.service.advertise(namespace(topic), self.p.pid)
            self.p.scheduler.call_later(max(ttl * 0.8, 1.0), readvertise)

        readvertise()

    def stop_advertise(self, topic: str) -> None:
        self.advertising.pop(topic, None)

    def discover(self, topic: str) -> None:
        if self.service is not None and self.p is not None:
            self._handle_discovery(topic)

    def bootstrap(self, topic: str, ready: Callable[[], bool] | None = None,
                  timeout: float = 60.0) -> bool:
        """Drive discovery until the router is ready (discovery.go:242-297).
        Runs the scheduler in 1s slices up to ``timeout`` virtual seconds."""
        assert self.p is not None
        sched = self.p.scheduler
        deadline = sched.now() + timeout
        is_ready = ready or (lambda: self.p.rt.enough_peers(topic, self.min_peers))
        while sched.now() < deadline:
            if is_ready():
                return True
            self._handle_discovery(topic)
            sched.run_for(1.0)
        return is_ready()
