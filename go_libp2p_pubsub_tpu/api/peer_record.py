"""Signed peer records + envelopes for PX validation.

Mirrors the reference's record validation on the PX dial path
(gossipsub.go:893-926): a ``PeerInfo`` carrying a ``signedPeerRecord`` must
unmarshal as a signed envelope over the peer-record domain, its payload must
be a peer record, and the record's peer id must match the announced id —
otherwise the peer is skipped without dialing. Records are produced on the
prune side from the host's certified-record store (gossipsub.go:1885-1901
``cab.GetPeerRecord``) and consumed into it after a successful PX dial
(gossipsub.go:954-958 ``ConsumePeerRecord``).

Wire layout follows libp2p's envelope.proto / peer_record.proto field
numbers (Envelope: publicKey=1, payloadType=2, payload=3, signature=5;
PeerRecord: peerId=1, seq=2, addresses=3{multiaddr=1}; signed payload =
len-prefixed domain + payloadType + payload), with the raw Ed25519 public
key standing in for libp2p's PublicKey submessage on this simulated
substrate — the framework's ids are self-certifying ``ed25519:<hex>``
(api/sign.py), so the key IS the identity and the envelope is
self-validating.
"""

from __future__ import annotations

from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # minimal images: record encode/decode stays available,
    # only seal/consume (the ed25519 envelope paths) are gated below
    _HAVE_CRYPTOGRAPHY = False
    InvalidSignature = None
    Ed25519PrivateKey = Ed25519PublicKey = None

from ..core.types import PeerID
from ..pb.codec import (
    _bytes_field,
    _iter_fields,
    _str_field,
    _varint_field,
    write_uvarint,
)
from .sign import peer_id_from_key

PEER_RECORD_ENVELOPE_DOMAIN = "libp2p-peer-record"
PEER_RECORD_PAYLOAD_TYPE = b"\x03\x01"  # multicodec libp2p-peer-record


class RecordError(ValueError):
    """Envelope/record that fails to parse or validate."""


@dataclass
class PeerRecord:
    """peer_record.proto: the routable self-description PX hands around."""

    peer_id: PeerID = ""
    seq: int = 0
    addrs: tuple[str, ...] = ()


def encode_peer_record(rec: PeerRecord) -> bytes:
    out = bytearray()
    out += _bytes_field(1, rec.peer_id.encode("utf-8", "surrogateescape"))
    out += _varint_field(2, rec.seq)
    for a in rec.addrs:
        out += _bytes_field(3, _str_field(1, a))
    return bytes(out)


def decode_peer_record(buf: bytes) -> PeerRecord:
    pid, seq, addrs = "", 0, []
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == 2:
            pid = val.decode("utf-8", "surrogateescape")
        elif field == 2 and wire == 0:
            seq = int(val)
        elif field == 3 and wire == 2:
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    addrs.append(v2.decode("utf-8", "surrogateescape"))
    return PeerRecord(peer_id=pid, seq=seq, addrs=tuple(addrs))


def _unsigned_bytes(domain: str, payload_type: bytes, payload: bytes) -> bytes:
    """The byte string the envelope signature covers (record/envelope.go):
    each component length-prefixed so the triple is unambiguous."""
    out = bytearray()
    for part in (domain.encode(), payload_type, payload):
        out += write_uvarint(len(part)) + part
    return bytes(out)


def seal_record(rec: PeerRecord, key: Ed25519PrivateKey) -> bytes:
    """Sign ``rec`` into an envelope over the peer-record domain."""
    if not _HAVE_CRYPTOGRAPHY:
        raise RecordError("the 'cryptography' package is not installed: "
                          "cannot seal peer-record envelopes")
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)

    payload = encode_peer_record(rec)
    sig = key.sign(_unsigned_bytes(
        PEER_RECORD_ENVELOPE_DOMAIN, PEER_RECORD_PAYLOAD_TYPE, payload))
    pub = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    out = bytearray()
    out += _bytes_field(1, pub)
    out += _bytes_field(2, PEER_RECORD_PAYLOAD_TYPE)
    out += _bytes_field(3, payload)
    out += _bytes_field(5, sig)
    return bytes(out)


def consume_peer_record(envelope: bytes) -> PeerRecord:
    """Validate an envelope and return its peer record.

    Raises RecordError when the envelope doesn't parse, the payload type is
    not a peer record, the signature doesn't verify, or the record's peer id
    doesn't match the signing key (self-certifying ids) — the failure modes
    the reference skips PX peers for (gossipsub.go:909-926)."""
    pub_raw = payload_type = payload = sig = None
    try:
        for field, wire, val in _iter_fields(envelope):
            if wire != 2:
                continue    # all envelope fields are length-delimited; a
                            # varint here is an attack shape, not our data
            if field == 1:
                pub_raw = val
            elif field == 2:
                payload_type = val
            elif field == 3:
                payload = val
            elif field == 5:
                sig = val
    except (ValueError, IndexError) as e:
        raise RecordError(f"malformed envelope: {e}") from e
    if pub_raw is None or payload is None or sig is None:
        raise RecordError("envelope missing key, payload, or signature")
    if payload_type != PEER_RECORD_PAYLOAD_TYPE:
        raise RecordError("envelope payload is not a peer record")
    if not _HAVE_CRYPTOGRAPHY:
        raise RecordError("the 'cryptography' package is not installed: "
                          "cannot verify peer-record envelopes")
    try:
        pub = Ed25519PublicKey.from_public_bytes(bytes(pub_raw))
    except ValueError as e:
        raise RecordError(f"bad envelope key: {e}") from e
    try:
        pub.verify(bytes(sig), _unsigned_bytes(
            PEER_RECORD_ENVELOPE_DOMAIN, PEER_RECORD_PAYLOAD_TYPE,
            bytes(payload)))
    except InvalidSignature as e:
        raise RecordError("invalid envelope signature") from e
    try:
        rec = decode_peer_record(bytes(payload))
    except (ValueError, IndexError, UnicodeDecodeError) as e:
        # validly signed garbage is still garbage (attacker signs anything
        # with their own key) — reject, don't crash the PRUNE handler
        raise RecordError(f"malformed peer record payload: {e}") from e
    if rec.peer_id != peer_id_from_key(pub):
        raise RecordError(
            f"record peer id {rec.peer_id!r} doesn't match signing key")
    return rec
