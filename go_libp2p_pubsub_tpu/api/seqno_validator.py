"""Built-in seqno replay validator (validation_builtin.go).

Suppresses replayed/out-of-order messages via a per-author max-seqno table in
a pluggable metadata store (validation_builtin.go:12-101). The reference's
double-checked locking collapses to a single check on the deterministic
scheduler.
"""

from __future__ import annotations

from typing import Protocol

from ..core.types import Message, PeerID
from .validation import VALIDATION_ACCEPT, VALIDATION_IGNORE


class PeerMetadataStore(Protocol):
    """validation_builtin.go:12-18."""

    def get(self, peer: PeerID) -> bytes | None: ...
    def put(self, peer: PeerID, val: bytes) -> None: ...


class InMemoryPeerMetadataStore:
    def __init__(self):
        self._m: dict[PeerID, bytes] = {}

    def get(self, peer: PeerID) -> bytes | None:
        return self._m.get(peer)

    def put(self, peer: PeerID, val: bytes) -> None:
        self._m[peer] = val


class BasicSeqnoValidator:
    """validation_builtin.go:32-101; use as a default (all-topic) validator."""

    def __init__(self, meta: PeerMetadataStore | None = None):
        self.meta = meta or InMemoryPeerMetadataStore()

    def __call__(self, src: PeerID, msg: Message) -> int:
        author = msg.from_peer or ""
        seqno = int.from_bytes(msg.seqno or b"", "big")
        prev_raw = self.meta.get(author)
        prev = int.from_bytes(prev_raw, "big") if prev_raw else 0
        if seqno <= prev:
            return VALIDATION_IGNORE
        self.meta.put(author, seqno.to_bytes(8, "big"))
        return VALIDATION_ACCEPT
