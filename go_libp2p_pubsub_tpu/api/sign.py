"""Message signing: policies + Ed25519 sign/verify (sign.go).

Policies mirror sign.go:13-45 (StrictSign / StrictNoSign / LaxSign /
LaxNoSign as a bitfield of sign|verify). The signed payload is the message's
deterministic serialization prefixed with ``libp2p-pubsub:`` (sign.go:47,
109-134). Key resolution mirrors sign.go:77-107: a peer id of the form
``ed25519:<hex pubkey>`` is self-certifying (the analogue of identity-hashed
libp2p IDs, whose pubkey is extractable); otherwise the message must carry
the author's public key and it must match the id.
"""

from __future__ import annotations

import enum
import hashlib

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # minimal images: the package must stay importable
    # (LAX_NO_SIGN swarms, the batched engine, and trace tooling need no
    # signing at all); only the ed25519 entry points below are gated
    HAVE_CRYPTOGRAPHY = False
    InvalidSignature = None
    Ed25519PrivateKey = Ed25519PublicKey = None

from ..core.types import Message, PeerID


def _require_crypto() -> None:
    if not HAVE_CRYPTOGRAPHY:
        raise SignError(
            "the 'cryptography' package is not installed: Ed25519 "
            "signing/verification is unavailable (use LAX_NO_SIGN, or "
            "install cryptography for strict policies)")

SIGN_PREFIX = b"libp2p-pubsub:"


class SignPolicy(enum.IntFlag):
    """MessageSignaturePolicy (sign.go:13-34)."""

    MSG_SIGNING = 1
    MSG_VERIFICATION = 2

    @property
    def must_sign(self) -> bool:
        return bool(self & SignPolicy.MSG_SIGNING)

    @property
    def must_verify(self) -> bool:
        return bool(self & SignPolicy.MSG_VERIFICATION)


STRICT_SIGN = SignPolicy.MSG_SIGNING | SignPolicy.MSG_VERIFICATION
STRICT_NO_SIGN = SignPolicy.MSG_VERIFICATION
LAX_SIGN = SignPolicy.MSG_SIGNING
LAX_NO_SIGN = SignPolicy(0)


class SignError(ValueError):
    pass


def generate_keypair(seed: bytes | None = None) -> tuple[Ed25519PrivateKey, PeerID]:
    """New Ed25519 key + its self-certifying peer id."""
    _require_crypto()
    if seed is not None:
        priv = Ed25519PrivateKey.from_private_bytes(hashlib.sha256(seed).digest())
    else:
        priv = Ed25519PrivateKey.generate()
    return priv, peer_id_from_key(priv.public_key())


def peer_id_from_key(pub: Ed25519PublicKey) -> PeerID:
    _require_crypto()
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)
    raw = pub.public_bytes(Encoding.Raw, PublicFormat.Raw)
    return "ed25519:" + raw.hex()


def _pubkey_from_peer_id(pid: PeerID) -> Ed25519PublicKey | None:
    if pid.startswith("ed25519:"):
        _require_crypto()
        try:
            return Ed25519PublicKey.from_public_bytes(bytes.fromhex(pid[8:]))
        except ValueError:
            return None
    return None


def signable_bytes(m: Message) -> bytes:
    """Deterministic serialization of the message sans signature/key.

    Stands in for the proto marshal in sign.go:56-62; length-prefixed fields
    keep it unambiguous.
    """
    parts = []
    for b in ((m.from_peer or "").encode(), m.data, m.seqno or b"",
              m.topic.encode()):
        parts.append(len(b).to_bytes(4, "big"))
        parts.append(b)
    return SIGN_PREFIX + b"".join(parts)


def sign_message(pid: PeerID, key: Ed25519PrivateKey, m: Message) -> None:
    """Sign in place; attaches the pubkey when the id is not self-certifying
    (sign.go:109-134)."""
    _require_crypto()
    m.signature = key.sign(signable_bytes(m))
    if _pubkey_from_peer_id(pid) is None:
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat)
        m.key = key.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)


def verify_message_signature(m: Message) -> None:
    """Raises SignError when the signature doesn't verify (sign.go:49-75)."""
    _require_crypto()
    pid = m.from_peer or ""
    pub = _pubkey_from_peer_id(pid)
    if pub is None:
        if m.key is None:
            raise SignError("cannot extract signing key")
        try:
            pub = Ed25519PublicKey.from_public_bytes(m.key)
        except ValueError as e:
            raise SignError(f"cannot unmarshal signing key: {e}") from e
        # a self-certifying id must match the attached key
        if pid.startswith("ed25519:") and peer_id_from_key(pub) != pid:
            raise SignError(f"bad signing key; source ID {pid} doesn't match key")
    if m.signature is None:
        raise SignError("missing signature")
    try:
        pub.verify(m.signature, signable_bytes(m))
    except InvalidSignature as e:
        raise SignError("invalid signature") from e
