"""Subscription: buffered delivery handle (subscription.go:10-51)."""

from __future__ import annotations

from collections import deque
from typing import TYPE_CHECKING, Callable

from ..core.types import Message

if TYPE_CHECKING:
    from .topic import Topic


class Subscription:
    """Bounded message buffer (default 32, topic.go:162-165); messages beyond
    capacity are dropped and traced as undeliverable (pubsub.go:973-984)."""

    def __init__(self, topic: "Topic", buffer_size: int = 32):
        self.topic_handle = topic
        self.topic = topic.name
        self._buf: deque[Message] = deque()
        self._buffer_size = buffer_size
        self._cancelled = False
        # optional push callback for event-driven consumers
        self.on_message: Callable[[Message], None] | None = None

    def _deliver(self, msg: Message) -> None:
        if self._cancelled:
            return
        if self.on_message is not None:
            self.on_message(msg)
            return
        if len(self._buf) >= self._buffer_size:
            self.topic_handle.p.tracer.undeliverable_message(msg)
            return
        self._buf.append(msg)

    def next(self) -> Message | None:
        """Non-blocking Next (subscription.go:25-41): the deterministic
        runtime has no blocking reads; None means no message buffered."""
        if self._buf:
            return self._buf.popleft()
        return None

    def pending(self) -> int:
        return len(self._buf)

    def cancel(self) -> None:
        """subscription.go:44-48."""
        if not self._cancelled:
            self._cancelled = True
            self.topic_handle._remove_subscription(self)
