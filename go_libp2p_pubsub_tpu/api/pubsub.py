"""PubSub core: the L3 runtime (pubsub.go).

Owns all topic/peer/subscription state for one node. The reference serializes
everything through one processLoop goroutine (pubsub.go:561-675); here the
deterministic scheduler provides that serialization globally, so handlers
mutate state directly.

State fields mirror pubsub.go:48-183: ``topics`` (topic -> peers who
announced it), ``my_topics`` (joined Topic handles), ``peers`` (connected +
hello'd peers), seen-cache, blacklist, validation, tracer, router.
"""

from __future__ import annotations

import random
from typing import Callable

from ..core.params import TIME_CACHE_DURATION
from ..core.types import RPC, AcceptStatus, Message, PeerID, SubOpts, trim_rpc
from ..net.network import Host, Scheduler
from ..routers.base import Router
from ..trace import events as ev
from ..trace.bus import EventTracer, PubsubTracer
from ..utils.blacklist import Blacklist, MapBlacklist
from ..utils.midgen import MsgIdGenerator
from ..utils.subscription_filter import SubscriptionFilter
from ..utils.timecache import SWEEP_INTERVAL, Strategy, TimeCache
from .sign import STRICT_SIGN, SignError, SignPolicy, sign_message
from .validation import Validation, ValidationError


class PubSub:
    """One pubsub node (NewPubSub, pubsub.go:251-339)."""

    def __init__(self, host: Host, router: Router, *,
                 sign_policy: SignPolicy = STRICT_SIGN,
                 sign_key=None,
                 validation: Validation | None = None,
                 event_tracer: EventTracer | None = None,
                 raw_tracers: list[ev.RawTracer] | None = None,
                 blacklist: Blacklist | None = None,
                 subscription_filter: SubscriptionFilter | None = None,
                 seen_ttl: float = TIME_CACHE_DURATION,
                 seen_strategy: Strategy = Strategy.FIRST_SEEN,
                 msg_id_fn: Callable[[Message], str] | None = None,
                 rpc_inspector: Callable[[PeerID, RPC], bool] | None = None,
                 peer_filter: Callable[[PeerID, str], bool] | None = None,
                 protocol_match_fn: Callable[
                     [str], Callable[[str], bool]] | None = None,
                 max_message_size: int = 1 << 20,
                 author: PeerID | None = None,
                 no_author: bool = False,
                 discovery=None,
                 rng: random.Random | None = None):
        self.host = host
        self.rt = router
        self.scheduler: Scheduler = host.network.scheduler
        self.pid = host.peer_id
        self.rng = rng or random.Random(hash(self.pid) & 0xFFFFFFFF)

        self.sign_policy = sign_policy
        self.sign_key = sign_key
        # author id for outbound messages; defaults to the host id and is only
        # cleared by WithNoAuthor (pubsub.go:261, 413-427)
        self.sign_id: PeerID | None = None if no_author else (author or self.pid)
        if no_author:
            self.sign_policy &= ~SignPolicy.MSG_SIGNING
        if self.sign_policy.must_sign and sign_key is None:
            raise ValueError(f"can't sign for peer {self.pid}: no private key")
        if sign_key is not None and host.local_record is None:
            # publish a sealed self-record so peers can vouch for us over PX
            # (the identify/peerstore flow feeding cab.GetPeerRecord,
            # gossipsub.go:1885-1893); only a self-certifying id can seal a
            # record that validates, so skip when signing as someone else
            from .peer_record import PeerRecord, seal_record
            from .sign import peer_id_from_key
            if peer_id_from_key(sign_key.public_key()) == self.pid:
                host.local_record = seal_record(
                    PeerRecord(peer_id=self.pid, seq=1, addrs=(host.addr,)),
                    sign_key)

        self.id_gen = MsgIdGenerator()
        if msg_id_fn is not None:
            self.id_gen.default = msg_id_fn

        self.seen = TimeCache(seen_ttl, self.scheduler.now, seen_strategy)
        self.blacklist = blacklist or MapBlacklist()
        self.sub_filter = subscription_filter
        self.rpc_inspector = rpc_inspector
        self.peer_filter = peer_filter or (lambda pid, topic: True)
        self.max_message_size = max_message_size

        self.val = validation or Validation()
        self.tracer = PubsubTracer(self.scheduler.now, self.pid,
                                   self.id_gen.id, event_tracer, raw_tracers)

        # state registries (pubsub.go:123-150)
        self.topics: dict[str, set[PeerID]] = {}       # topic -> announced peers
        self.my_topics: dict[str, "Topic"] = {}        # joined Topic handles
        self.my_relays: dict[str, int] = {}            # relay refcounts
        self.peers: set[PeerID] = set()                # hello'd peers
        self.counter = 0                               # seqno (pubsub.go:1341)

        # discovery bridge (pubsub.go:317, discovery.go:86)
        from .discovery import Discover
        self.disc = discovery if isinstance(discovery, Discover) \
            else Discover(discovery)
        self.disc.start(self)

        # wire up the substrate (pubsub.go:321-336); protocol_match_fn is
        # WithProtocolMatchFn (pubsub.go:520-531): custom multistream
        # acceptance, combined with the router's feature test / protocol list
        host.set_protocols(router.protocols(), self._handle_new_stream,
                           self._handle_incoming_rpc_wire,
                           match_fn=protocol_match_fn)
        host.notify(_Notifiee(self))
        router.attach(self)
        self.val.start(self)
        self.scheduler.call_every(SWEEP_INTERVAL, self.seen.sweep)
        # sweep pre-existing connections (pubsub.go:336)
        for peer in list(host.conns):
            self._peer_connected(peer)

    # ---- wire events ----

    def _handle_new_stream(self, peer: PeerID, proto: str) -> None:
        pass  # inbound streams are implicit in the substrate

    def _peer_connected(self, peer: PeerID) -> None:
        """New peer: hello packet + router add (handleNewPeer, comm.go:114-133,
        handlePendingPeers pubsub.go:683-709)."""
        if peer in self.peers or self.blacklist.contains(peer):
            return
        proto = self.host.protocols.get(peer)
        if proto is None:
            return  # no mutually supported pubsub protocol
        self.peers.add(peer)
        hello = self._get_hello_packet()
        if hello is not None:
            self.host.send(peer, hello)
        self.tracer.add_peer(peer, proto)
        self.rt.add_peer(peer, proto)

    def _peer_disconnected(self, peer: PeerID) -> None:
        """handleDeadPeers (pubsub.go:711-757)."""
        if peer not in self.peers:
            return
        self.peers.discard(peer)
        for topic, tmap in self.topics.items():
            if peer in tmap:
                tmap.discard(peer)
                self._notify_leave(topic, peer)
        self.rt.remove_peer(peer)
        self.tracer.remove_peer(peer)

    def _get_hello_packet(self) -> RPC | None:
        """Announce all current subscriptions (getHelloPacket, pubsub.go:759-775)."""
        topics = set(self.my_topics) | set(self.my_relays)
        if not topics:
            return None
        return RPC(subscriptions=[SubOpts(True, t) for t in sorted(topics)])

    # ---- inbound RPC (pubsub.go:1029-1105) ----

    def _handle_incoming_rpc_wire(self, src: PeerID, rpc: RPC) -> None:
        if src not in self.peers:
            return  # not hello'd / dead
        if rpc.size() > self.max_message_size:
            return
        self.handle_incoming_rpc(src, rpc)

    def handle_incoming_rpc(self, src: PeerID, rpc: RPC) -> None:
        rpc.from_peer = src
        if self.rpc_inspector is not None and not self.rpc_inspector(src, rpc):
            return
        self.tracer.recv_rpc(rpc)

        subs = rpc.subscriptions
        if subs and self.sub_filter is not None:
            try:
                subs = self.sub_filter.filter_incoming_subscriptions(src, subs)
            except ValueError:
                return
        for sub in subs:
            t = sub.topicid
            if sub.subscribe:
                tmap = self.topics.setdefault(t, set())
                if src not in tmap:
                    tmap.add(src)
                    topic = self.my_topics.get(t)
                    if topic is not None:
                        topic._notify_peer_event("join", src)
            else:
                tmap = self.topics.get(t)
                if tmap is not None and src in tmap:
                    tmap.discard(src)
                    self._notify_leave(t, src)

        accept = self.rt.accept_from(src)
        if accept == AcceptStatus.ACCEPT_NONE:
            return
        if accept == AcceptStatus.ACCEPT_CONTROL:
            if rpc.publish:
                self.tracer.throttle_peer(src)
        else:
            for pmsg in rpc.publish:
                if not (self._subscribed_to_msg(pmsg) or self._can_relay_msg(pmsg)):
                    continue
                msg = Message(from_peer=pmsg.from_peer, data=pmsg.data,
                              seqno=pmsg.seqno, topic=pmsg.topic,
                              signature=pmsg.signature, key=pmsg.key,
                              received_from=src)
                self.push_msg(msg)
        self.rt.handle_rpc(rpc)

    def _subscribed_to_msg(self, msg: Message) -> bool:
        return msg.topic in self.my_topics

    def _can_relay_msg(self, msg: Message) -> bool:
        return self.my_relays.get(msg.topic, 0) > 0

    def _notify_leave(self, topic: str, peer: PeerID) -> None:
        t = self.my_topics.get(topic)
        if t is not None:
            t._notify_peer_event("leave", peer)

    # ---- message push (pubsub.go:1118-1162) ----

    def push_msg(self, msg: Message) -> None:
        src = msg.received_from
        if src is not None and self.blacklist.contains(src):
            self.tracer.reject_message(msg, ev.REJECT_BLACKLISTED_PEER)
            return
        if msg.from_peer is not None and self.blacklist.contains(msg.from_peer):
            self.tracer.reject_message(msg, ev.REJECT_BLACKLISTED_SOURCE)
            return
        try:
            self.check_signing_policy(msg)
        except ValidationError:
            return
        # reject messages claiming to be from ourselves but not locally published
        if msg.from_peer == self.pid and src != self.pid:
            self.tracer.reject_message(msg, ev.REJECT_SELF_ORIGIN)
            return
        mid = self.id_gen.id(msg)
        if self.seen.has(mid):
            self.tracer.duplicate_message(msg)
            return
        if not self.val.push(src, msg):
            return
        # no validators apply: mark seen and publish directly
        if self.mark_seen(mid):
            self.publish_message(msg)

    def check_signing_policy(self, msg: Message) -> None:
        """pubsub.go:1164-1194; raises ValidationError and traces on violation."""
        if self.sign_policy.must_verify:
            if self.sign_policy.must_sign:
                if msg.signature is None:
                    self.tracer.reject_message(msg, ev.REJECT_MISSING_SIGNATURE)
                    raise ValidationError(ev.REJECT_MISSING_SIGNATURE)
            else:
                if msg.signature is not None:
                    self.tracer.reject_message(msg, ev.REJECT_UNEXPECTED_SIGNATURE)
                    raise ValidationError(ev.REJECT_UNEXPECTED_SIGNATURE)
                if self.sign_id is None and (
                        msg.seqno is not None or msg.from_peer is not None
                        or msg.key is not None):
                    self.tracer.reject_message(msg, ev.REJECT_UNEXPECTED_AUTH_INFO)
                    raise ValidationError(ev.REJECT_UNEXPECTED_AUTH_INFO)

    def mark_seen(self, mid: str) -> bool:
        return self.seen.add(mid)

    def deliver_validated(self, msg: Message) -> None:
        """Validation pipeline completion -> deliver (processLoop sendMsg case,
        pubsub.go:641-642)."""
        self.publish_message(msg)

    def publish_message(self, msg: Message) -> None:
        """pubsub.go:1196-1202."""
        self.tracer.deliver_message(msg)
        self._notify_subs(msg)
        if not msg.local:
            self.rt.publish(msg)

    def _notify_subs(self, msg: Message) -> None:
        """Deliver to local subscriptions, drop-if-slow (pubsub.go:973-984)."""
        topic = self.my_topics.get(msg.topic)
        if topic is not None:
            for sub in topic._subs:
                sub._deliver(msg)

    # ---- public API (L6) ----

    def join(self, topic_name: str, *, msg_id_fn=None) -> "Topic":
        """pubsub.go:1228-1279 (tryJoin). ``msg_id_fn`` is the
        WithTopicMessageIdFn TopicOpt (pubsub.go:1219-1224): a per-topic
        message-id override consulted by dedup, mcache, and tracing."""
        if self.sub_filter is not None and not self.sub_filter.can_subscribe(topic_name):
            raise ValueError(f"topic is not allowed by the subscription filter: {topic_name}")
        t = self.my_topics.get(topic_name)
        if t is not None:
            if msg_id_fn is not None:
                # the reference refuses Join on an existing topic outright
                # (pubsub.go:1229-1232); we allow handle reuse but never
                # silently drop a requested option
                raise ValueError(
                    f"topic already joined: {topic_name}; per-topic "
                    "msg_id_fn must be set on the first join")
            return t
        if msg_id_fn is not None:
            self.id_gen.set(topic_name, msg_id_fn)
        from .topic import Topic
        t = Topic(self, topic_name)
        self.my_topics[topic_name] = t
        return t

    def get_topics(self) -> list[str]:
        """Joined+subscribed topics (pubsub.go:1290)."""
        return sorted(t for t, topic in self.my_topics.items() if topic._subs)

    def list_peers(self, topic: str) -> list[PeerID]:
        return sorted(self.topics.get(topic, ()))

    def blacklist_peer(self, peer: PeerID) -> None:
        """pubsub.go:1311-1339: blacklist + hard-disconnect state."""
        self.blacklist.add(peer)
        if peer in self.peers:
            self._peer_disconnected(peer)

    def register_topic_validator(self, topic: str, validate, *, throttle: int = 0,
                                 inline: bool = False,
                                 timeout: float = 0.0) -> None:
        """RegisterTopicValidator (pubsub.go:1379) with the ValidatorOpt
        knobs: WithValidatorConcurrency, WithValidatorInline, and
        WithValidatorTimeout (validation.go:540-570)."""
        self.val.add_validator(topic, validate, throttle=throttle,
                               inline=inline, timeout=timeout)

    def unregister_topic_validator(self, topic: str) -> None:
        self.val.remove_validator(topic)

    def next_seqno(self) -> bytes:
        self.counter += 1
        return self.counter.to_bytes(8, "big")

    # ---- outbound ----

    def send_rpc(self, peer: PeerID, rpc: RPC) -> bool:
        """Send with drop-trace on queue overflow (pubsub.go:917-925 announce
        path and gossipsub.go:1195-1202 both land here). Returns whether the
        RPC entered the peer's queue (empty-after-trim counts as sent)."""
        out = trim_rpc(rpc)
        if out is None:
            return True
        if self.host.send(peer, out):
            self.tracer.send_rpc(out, peer)
            return True
        self.tracer.drop_rpc(out, peer)
        return False

    def announce(self, topic: str, subscribe: bool) -> None:
        """Announce (un)subscription to every peer (pubsub.go:910-927)."""
        for peer in sorted(self.peers):
            self._announce_to_peer(peer, topic, subscribe)

    def _announce_to_peer(self, peer: PeerID, topic: str,
                          subscribe: bool) -> None:
        """One peer's announcement; a queue-overflow drop schedules a
        jittered retry (1..1000ms) that re-checks the (un)subscription
        still holds before resending (pubsub.go:917-925 + announceRetry
        pubsub.go:929-969)."""
        if self.send_rpc(peer, RPC(subscriptions=[SubOpts(subscribe, topic)])):
            return
        delay = 0.001 * (1 + self.rng.randrange(1000))

        def retry():
            if peer not in self.peers:
                return
            t = self.my_topics.get(topic)
            wanted = t is not None and (bool(t._subs) or t._relay_count > 0)
            if wanted == subscribe:
                self._announce_to_peer(peer, topic, subscribe)

        self.scheduler.call_later(delay, retry)

    def sign_and_finalize(self, msg: Message) -> None:
        """Attach author/seqno/signature per policy (topic.go:252-264)."""
        if self.sign_id is not None:
            msg.from_peer = self.sign_id
            msg.seqno = self.next_seqno()
        if self.sign_policy.must_sign:
            assert self.sign_key is not None
            try:
                sign_message(self.pid, self.sign_key, msg)
            except Exception as e:  # pragma: no cover
                raise SignError(str(e)) from e


class _Notifiee:
    """Bridges substrate connect events into the runtime (notify.go:11-75)."""

    def __init__(self, p: PubSub):
        self.p = p

    def connected(self, peer: PeerID) -> None:
        self.p._peer_connected(peer)

    def disconnected(self, peer: PeerID) -> None:
        self.p._peer_disconnected(peer)
