"""Topic handle: subscribe/publish/relay/events (topic.go).

Join/subscribe lifecycle per SURVEY.md §3.5: the first subscription (or
relay) announces to all peers and calls router.join; the last cancel
announces unsubscription and calls router.leave (pubsub.go:800-848).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable

from ..core.types import Message, PeerID
from .subscription import Subscription
from .validation import ValidationError

if TYPE_CHECKING:
    from .pubsub import PubSub


class PeerEvent:
    __slots__ = ("type", "peer")

    def __init__(self, type_: str, peer: PeerID):
        self.type = type_    # "join" | "leave"
        self.peer = peer


class TopicEventHandler:
    """Coalescing join/leave event log (topic.go:392-477): rapid join+leave
    pairs for the same peer cancel out, mirroring the reference's
    peer-event coalescing."""

    def __init__(self, topic: "Topic | None" = None):
        self._topic = topic
        self._pending: dict[PeerID, str] = {}
        self._order: list[PeerID] = []

    def cancel(self) -> None:
        """Stop receiving events (topic.go:432-436 TopicEventHandler.Cancel);
        drops anything buffered; idempotent."""
        if self._topic is not None:
            try:
                self._topic._event_handlers.remove(self)
            except ValueError:
                pass
            self._topic = None
        self._pending.clear()
        self._order.clear()

    def _push(self, ev: PeerEvent) -> None:
        cur = self._pending.get(ev.peer)
        if cur is None:
            self._pending[ev.peer] = ev.type
            self._order.append(ev.peer)
        elif cur != ev.type:
            del self._pending[ev.peer]
            self._order.remove(ev.peer)

    def next_peer_event(self) -> PeerEvent | None:
        while self._order:
            peer = self._order.pop(0)
            typ = self._pending.pop(peer, None)
            if typ is not None:
                return PeerEvent(typ, peer)
        return None


class Topic:
    """topic.go:26-35."""

    def __init__(self, p: "PubSub", name: str):
        self.p = p
        self.name = name
        self._subs: list[Subscription] = []
        self._event_handlers: list[TopicEventHandler] = []
        self._relay_count = 0
        self._closed = False
        self._pending_pubs: list = []      # (Message, gate|None), FIFO
        self._drain_scheduled = False      # one poll chain at a time

    # -- lifecycle --

    def _check_closed(self) -> None:
        if self._closed:
            raise RuntimeError("this Topic handle was closed")

    def subscribe(self, buffer_size: int = 32,
                  on_message: Callable[[Message], None] | None = None) -> Subscription:
        """topic.go:143-182."""
        self._check_closed()
        sub = Subscription(self, buffer_size)
        sub.on_message = on_message
        first = not self._subs and self._relay_count == 0
        self._subs.append(sub)
        if first:
            self._announce_and_join()
        return sub

    def relay(self) -> Callable[[], None]:
        """Relay refcounting (topic.go:186-207): pump messages without a
        subscription; returns a cancel function."""
        self._check_closed()
        first = not self._subs and self._relay_count == 0
        self._relay_count += 1
        self.p.my_relays[self.name] = self._relay_count
        if first:
            self._announce_and_join()
        cancelled = False

        def cancel():
            nonlocal cancelled
            if cancelled:
                return
            cancelled = True
            self._relay_count -= 1
            self.p.my_relays[self.name] = self._relay_count
            if self._relay_count == 0:
                del self.p.my_relays[self.name]
            self._maybe_leave()
        return cancel

    def _announce_and_join(self) -> None:
        """First sub/relay (handleAddSubscription, pubsub.go:827-848)."""
        self.p.disc.advertise(self.name)
        self.p.disc.discover(self.name)
        self.p.announce(self.name, True)
        self.p.rt.join(self.name)  # routers trace Join themselves

    def _remove_subscription(self, sub: Subscription) -> None:
        """handleRemoveSubscription (pubsub.go:800-821)."""
        self._subs.remove(sub)
        self._maybe_leave()

    def _maybe_leave(self) -> None:
        if not self._subs and self._relay_count == 0:
            self.p.disc.stop_advertise(self.name)
            self.p.announce(self.name, False)
            self.p.rt.leave(self.name)

    def close(self) -> None:
        """topic.go:480-494: only an idle handle can be closed."""
        if self._subs or self._relay_count:
            raise RuntimeError("cannot close topic with active subscriptions or relays")
        if self._pending_pubs:
            raise RuntimeError("cannot close topic with pending gated publishes")
        self._closed = True
        self.p.my_topics.pop(self.name, None)
        # drop any per-topic msg-id fn so a later join(topic) starts from
        # the default instead of silently inheriting the closed handle's
        # custom fn (the reference never deletes, midgen.go — an explicit
        # divergence: join() insists the fn be set on first join, so
        # surviving close would contradict that contract)
        self.p.id_gen._topic_gens.pop(self.name, None)

    # -- events --

    def event_handler(self) -> TopicEventHandler:
        """topic.go:392-430; pre-seeds with currently known topic peers."""
        self._check_closed()
        h = TopicEventHandler(self)
        for peer in sorted(self.p.topics.get(self.name, ())):
            h._push(PeerEvent("join", peer))
        self._event_handlers.append(h)
        return h

    def _notify_peer_event(self, typ: str, peer: PeerID) -> None:
        for h in self._event_handlers:
            h._push(PeerEvent(typ, peer))

    def list_peers(self) -> list[PeerID]:
        return self.p.list_peers(self.name)

    # -- publish (topic.go:224-312) --

    def publish(self, data: bytes, *, custom_key=None, local_only: bool = False,
                ready=None, ready_poll: float = 0.2) -> None:
        """Build, sign, validate and route a message. Raises ValidationError
        if local validation rejects it. ``local_only`` notifies in-process
        subscribers without routing (WithLocalPublication, topic.go:323-331).

        ``ready`` is the WithReadiness gate (topic.go:270-309): a callable
        polled on the scheduler; routing is deferred until it returns True
        (the deterministic analogue of the reference blocking the caller
        until RouterReady). Later routed publishes on the topic queue
        behind a pending gated one so seqno order is preserved on the
        wire; ``local_only`` messages never touch the wire and therefore
        bypass the queue and deliver immediately. A deferred message a
        validator later rejects is dropped (the rejection is traced by
        the validation pipeline — with no caller left to raise into, the
        trace is the error surface). While a drain chain is pending, the
        chain polls at the ``ready_poll`` of the publish that started it;
        a later publish's ``ready_poll`` takes effect only once the queue
        empties. A gate that never opens can be abandoned with
        :meth:`cancel_pending_publishes`. See :meth:`ready_min_peers`."""
        self._check_closed()
        if ready is not None and ready_poll <= 0:
            raise ValueError("ready_poll must be positive")
        msg = Message(data=data, topic=self.name, received_from=self.p.pid,
                      local=local_only)
        if custom_key is not None:
            pid, key = custom_key
            msg.from_peer = pid
            msg.seqno = self.p.next_seqno()
            from .sign import sign_message
            if self.p.sign_policy.must_sign:
                sign_message(pid, key, msg)
        else:
            self.p.sign_and_finalize(msg)
        if not local_only and \
                (self._pending_pubs or (ready is not None and not ready())):
            self._pending_pubs.append((msg, ready))
            if not self._drain_scheduled:
                self._drain_scheduled = True
                self.p.scheduler.call_later(ready_poll,
                                            lambda: self._drain_pubs(ready_poll))
            return
        self.p.val.push_local(msg)

    def _drain_pubs(self, poll: float) -> None:
        from .validation import ValidationError
        # _drain_scheduled stays True for the whole drain so a reentrant
        # publish (from a subscriber's handler) can't start a second chain.
        try:
            while self._pending_pubs:
                msg, gate = self._pending_pubs[0]
                if gate is not None and not gate():
                    self.p.scheduler.call_later(poll,
                                                lambda: self._drain_pubs(poll))
                    return
                self._pending_pubs.pop(0)
                try:
                    self.p.val.push_local(msg)
                except ValidationError:
                    pass    # traced by the pipeline; nothing left to raise into
        except BaseException:
            # A raising gate callable or subscriber handler must not wedge
            # the chain: keep draining what remains, or release the flag.
            if self._pending_pubs:
                self.p.scheduler.call_later(poll,
                                            lambda: self._drain_pubs(poll))
            else:
                self._drain_scheduled = False
            raise
        self._drain_scheduled = False

    def cancel_pending_publishes(self) -> int:
        """Drop deferred gated publishes without routing them — the
        deterministic analogue of cancelling the ctx that blocks the
        reference's Topic.Publish readiness wait (topic.go:270-309).
        Returns the number of messages dropped; after this, :meth:`close`
        is no longer blocked by a gate that never opens."""
        n = len(self._pending_pubs)
        self._pending_pubs.clear()
        return n

    def ready_min_peers(self, count: int = 1):
        """Readiness predicate: the router reports enough topic peers
        (MinTopicSize, discovery.go:79-83 + RouterReady, topic.go:316-321)."""
        return lambda: self.p.rt.enough_peers(self.name, count)

    def set_score_params(self, params) -> None:
        """Per-topic score reconfiguration (topic.go:44-82)."""
        rt = self.p.rt
        score = getattr(rt, "score", None)
        if score is None:
            raise RuntimeError("peer scoring is not enabled")
        params.validate()
        score.set_topic_score_params(self.name, params)
