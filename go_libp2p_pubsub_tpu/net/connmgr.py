"""Connection manager with protection tags and decaying tags.

The substrate analogue of go-libp2p's connmgr consumed by the tag tracer
(tag_tracer.go): ``protect``/``unprotect`` pin connections; decaying tags
accumulate bounded per-peer values that decay on a timer. Eviction itself is
out of scope for the simulation — the value of the tags is observability and
test parity (gossipsub_connmgr_test.go asserts protection/tag state).
"""

from __future__ import annotations


from ..core.types import PeerID


class DecayingTag:
    def __init__(self, name: str, interval: float, decay_amount: int,
                 bump_cap: int, scheduler) -> None:
        self.name = name
        self.values: dict[PeerID, int] = {}
        self._decay_amount = decay_amount
        self._cap = bump_cap
        self._closed = False
        self._cancel = scheduler.call_every(interval, self._decay)

    def bump(self, peer: PeerID, amount: int) -> None:
        if self._closed:
            raise RuntimeError(f"decaying tag {self.name} is closed")
        self.values[peer] = min(self.values.get(peer, 0) + amount, self._cap)

    def _decay(self) -> None:
        for peer in list(self.values):
            v = self.values[peer] - self._decay_amount
            if v <= 0:
                del self.values[peer]
            else:
                self.values[peer] = v

    def close(self) -> None:
        self._closed = True
        self._cancel()


class ConnManager:
    def __init__(self, scheduler):
        self._scheduler = scheduler
        self.protections: dict[PeerID, set[str]] = {}
        self.tags: dict[str, DecayingTag] = {}

    def protect(self, peer: PeerID, tag: str) -> None:
        self.protections.setdefault(peer, set()).add(tag)

    def unprotect(self, peer: PeerID, tag: str) -> bool:
        tags = self.protections.get(peer)
        if tags is None:
            return False
        tags.discard(tag)
        if not tags:
            del self.protections[peer]
        return bool(tags)

    def is_protected(self, peer: PeerID, tag: str = "") -> bool:
        tags = self.protections.get(peer, set())
        return bool(tags) if not tag else tag in tags

    def register_decaying_tag(self, name: str, interval: float,
                              decay_amount: int, bump_cap: int) -> DecayingTag:
        tag = DecayingTag(name, interval, decay_amount, bump_cap, self._scheduler)
        self.tags[name] = tag
        return tag
