from .network import Host, Network, Notifiee, Scheduler  # noqa: F401
