"""Simulated network substrate: the L0/L1 replacement (SURVEY.md §1).

The reference sits on libp2p hosts with real TCP/QUIC streams and one
goroutine per stream (comm.go). Here the substrate is a deterministic
discrete-event simulation:

- ``Scheduler``: a (time, seq)-ordered event heap driving ONE virtual clock;
  every callback runs to completion before the next (the single-threaded
  ``processLoop`` invariant, pubsub.go:561, holds globally by construction).
- ``Host``: peer identity + addresses + connection table + notifiee fan-out
  (notify.go) + per-protocol stream handlers.
- RPC transfer: ``Host.send`` schedules delivery at now + latency with a
  bounded in-flight cap per (src, dst) modeling the reference's per-peer
  32-slot writer queue with silent-but-traced drops (comm.go:156-191,
  gossipsub.go:1195-1202).

Determinism: event order is (time, seq); all randomness comes from seeded
RNGs owned by nodes.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Protocol

from ..core.clock import VirtualClock
from ..core.params import DEFAULT_PEER_OUTBOUND_QUEUE_SIZE
from ..core.types import RPC, PeerID


class Scheduler:
    def __init__(self):
        self.clock = VirtualClock()
        self._heap: list[tuple[float, int, Callable[[], None]]] = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self.clock.now()

    def call_at(self, t: float, fn: Callable[[], None]) -> None:
        if t < self.now():
            raise ValueError("scheduling into the past")
        heapq.heappush(self._heap, (t, next(self._seq), fn))

    def call_later(self, dt: float, fn: Callable[[], None]) -> None:
        self.call_at(self.now() + dt, fn)

    def call_every(self, interval: float, fn: Callable[[], None],
                   initial_delay: float | None = None) -> Callable[[], None]:
        """Periodic timer; returns a cancel function."""
        cancelled = False

        def tick():
            if cancelled:
                return
            fn()
            self.call_later(interval, tick)

        self.call_later(interval if initial_delay is None else initial_delay, tick)

        def cancel():
            nonlocal cancelled
            cancelled = True
        return cancel

    def run_until(self, t: float) -> None:
        while self._heap and self._heap[0][0] <= t:
            when, _, fn = heapq.heappop(self._heap)
            self.clock.advance_to(when)
            fn()
        self.clock.advance_to(max(t, self.now()))

    def run_for(self, dt: float) -> None:
        self.run_until(self.now() + dt)


class Notifiee(Protocol):
    """Network event listener (notify.go:11-75)."""

    def connected(self, peer: PeerID) -> None: ...
    def disconnected(self, peer: PeerID) -> None: ...


class Host:
    """A simulated libp2p host: identity, addresses, connections, handlers."""

    def __init__(self, network: "Network", peer_id: PeerID, addr: str):
        self.network = network
        self.peer_id = peer_id
        self.addr = addr                     # source IP for P6 colocation
        self.conns: dict[PeerID, str] = {}   # peer -> "outbound"/"inbound"
        self.protocols: dict[PeerID, str] = {}  # negotiated protocol per peer
        self._notifiees: list[Notifiee] = []
        # protocol registration: ordered preference list + handler
        self.supported: list[str] = []
        self.stream_handler: Callable[[PeerID, str], None] | None = None
        self.rpc_handler: Callable[[PeerID, RPC], None] | None = None
        self.match_fn: Callable[[str], Callable[[str], bool]] | None = None
        self._inflight: dict[PeerID, int] = {}
        self.outbound_queue_size = DEFAULT_PEER_OUTBOUND_QUEUE_SIZE
        self.dropped_rpcs = 0
        self.faulted_rpcs = 0        # RPCs lost to an injected link fault
        # certified-addr-book analogue (peerstore.GetCertifiedAddrBook):
        # this host's own sealed record + validated records learned from
        # peers (identify exchange on connect, ConsumePeerRecord after PX)
        self.local_record: bytes | None = None
        self.certified_records: dict[PeerID, bytes] = {}
        from .connmgr import ConnManager
        self.conn_manager = ConnManager(network.scheduler)

    # -- wiring --

    def set_protocols(self, protos: list[str],
                      stream_handler: Callable[[PeerID, str], None],
                      rpc_handler: Callable[[PeerID, RPC], None],
                      match_fn: Callable[[str], Callable[[str], bool]] | None
                      = None) -> None:
        """Register pubsub's protocol list + handlers (pubsub.go:323-329).

        ``match_fn`` is the WithProtocolMatchFn hook (pubsub.go:520-531):
        maps each locally supported base protocol to a predicate over a
        peer's proposed protocol id, replacing exact multistream matching
        (e.g. semver-range acceptance, gossipsub_matchfn_test.go:79-90)."""
        self.supported = list(protos)
        self.stream_handler = stream_handler
        self.rpc_handler = rpc_handler
        self.match_fn = match_fn
        # late registration: negotiate streams over connections that existed
        # before this protocol handler did (the reference opens streams
        # lazily per peer, so a pubsub attached after dialing still works —
        # exercised by its preconnected-nodes scenario). Re-fires connected
        # notifications so both sides' pubsubs re-evaluate the peer.
        for peer in list(self.conns):
            other = self.network.hosts.get(peer)
            if other is None or peer in self.protocols:
                continue
            proto_out = next((p for p in self.supported
                              if other.accepts(p)), None)
            proto_in = next((q for q in other.supported
                             if self.accepts(q)), None)
            if proto_out is None or proto_in is None:
                continue
            self.protocols[peer] = proto_out
            other.protocols[self.peer_id] = proto_in
            for n in self._notifiees:
                n.connected(peer)
            for n in other._notifiees:
                n.connected(self.peer_id)

    def accepts(self, proposal: str) -> bool:
        """Would this host's mux accept a peer's proposed protocol id?"""
        if self.match_fn is None:
            return proposal in self.supported
        return any(self.match_fn(base)(proposal) for base in self.supported)

    def notify(self, n: Notifiee) -> None:
        self._notifiees.append(n)

    # -- connectivity --

    def connect(self, other: "Host") -> bool:
        """Dial ``other``; negotiates each direction's stream protocol: the
        dialer proposes its list in order, the listener's mux accepts via
        exact match or its match_fn (the multistream-select analogue; the
        per-direction proposal mirrors the reference opening one outbound
        stream per side, comm.go:114-133). Returns False when either
        direction has no acceptable proposal — a simplification of the
        reference, where the transport connection survives but no pubsub
        streams open (observable pubsub behavior is identical)."""
        if other.peer_id in self.conns:
            return True
        proto_out = next((p for p in self.supported if other.accepts(p)), None)
        proto_in = next((q for q in other.supported if self.accepts(q)), None)
        if self.supported and other.supported and \
                (proto_out is None or proto_in is None):
            return False
        self.conns[other.peer_id] = "outbound"
        other.conns[self.peer_id] = "inbound"
        # identify exchange: each side learns the other's signed record
        if other.local_record is not None:
            self.certified_records[other.peer_id] = other.local_record
        if self.local_record is not None:
            other.certified_records[self.peer_id] = self.local_record
        if proto_out is not None:
            self.protocols[other.peer_id] = proto_out
            other.protocols[self.peer_id] = proto_in
        for n in self._notifiees:
            n.connected(other.peer_id)
        for n in other._notifiees:
            n.connected(self.peer_id)
        return True

    def disconnect(self, peer: PeerID) -> None:
        other = self.network.hosts.get(peer)
        self.conns.pop(peer, None)
        self.protocols.pop(peer, None)
        if other is not None:
            other.conns.pop(self.peer_id, None)
            other.protocols.pop(self.peer_id, None)
            for n in other._notifiees:
                n.disconnected(self.peer_id)
        for n in self._notifiees:
            n.disconnected(peer)

    def conns_to_peer(self, peer: PeerID) -> list[str]:
        """Remote addresses for a connected peer (score.go getIPs source)."""
        other = self.network.hosts.get(peer)
        if peer in self.conns and other is not None:
            return [other.addr]
        return []

    # -- wire transfer (comm.go equivalent) --

    def send(self, peer: PeerID, rpc: RPC) -> bool:
        """Queue an RPC to ``peer``. Models the bounded per-peer writer: at
        most ``outbound_queue_size`` RPCs in flight; overflow is dropped and
        reported to the caller (who traces it, gossipsub.go:1195-1202).

        The network's ``link_fault`` hook (sim/faults.py HostFaultInjector)
        is consulted per send: ``"drop"`` loses the RPC in flight — the
        sender believes it sent (True), nothing arrives, ``faulted_rpcs``
        counts it; ``"drop_data"`` strips the publish payload and lets the
        control/subscription planes through (the batched half's link drop
        masks only the DATA admission — ops/propagate.forward_tick — so a
        lossy-link plan must not eat GRAFT/PRUNE/IHAVE here either; same
        shape as the gater's RED drop, peer_gater.go:320-363); ``"dup"``
        delivers the RPC twice (a retransmitting link)."""
        if peer not in self.conns:
            return False
        copies = 1
        if self.network.link_fault is not None:
            action = self.network.link_fault(self.peer_id, peer,
                                             bool(rpc.publish))
            if action == "drop":
                self.faulted_rpcs += 1
                return True           # lost in flight, not queue overflow
            if action == "drop_data":
                self.faulted_rpcs += 1
                if rpc.control is None and not rpc.subscriptions:
                    return True       # data-only frame: fully eaten
                from ..core.types import RPC as _RPC
                rpc = _RPC(subscriptions=list(rpc.subscriptions),
                           publish=[], control=rpc.control)
            if action == "dup":
                copies = 2
        inflight = self._inflight.get(peer, 0)
        if inflight >= self.outbound_queue_size:
            self.dropped_rpcs += 1
            return False
        # a duplicating link still honors the bounded writer: the second
        # copy is shed when only one slot remains (the cap is the
        # invariant, comm.go's 32-slot queue; duplication is best-effort)
        copies = min(copies, self.outbound_queue_size - inflight)
        self._inflight[peer] = inflight + copies
        rpc.from_peer = self.peer_id
        sched = self.network.scheduler
        delay = self.network.latency(self.peer_id, peer)

        def deliver():
            self._inflight[peer] = self._inflight.get(peer, 1) - 1
            other = self.network.hosts.get(peer)
            # connection may have died in flight
            if other is not None and self.peer_id in other.conns \
                    and other.rpc_handler is not None:
                other.rpc_handler(self.peer_id, rpc)

        for _ in range(copies):
            sched.call_later(delay, deliver)
        return True


class Network:
    """The swarm: host registry + shared scheduler + latency model
    (the getNetHosts/connect test substrate, floodsub_test.go:45-100)."""

    def __init__(self, latency: float | Callable[[PeerID, PeerID], float] = 0.001):
        self.scheduler = Scheduler()
        self.hosts: dict[PeerID, Host] = {}
        self._latency = latency
        # per-send fault hook (sim/faults.py HostFaultInjector installs
        # it): (src, dst, has_data) -> "ok" | "drop" | "drop_data" |
        # "dup", consulted by Host.send. "drop" loses the whole frame
        # (cut/dark links); "drop_data" models a lossy link that sheds
        # the data plane but passes control (batched-half parity)
        self.link_fault: Callable[[PeerID, PeerID, bool], str] | None = None

    def latency(self, a: PeerID, b: PeerID) -> float:
        if callable(self._latency):
            return self._latency(a, b)
        return self._latency

    def add_host(self, peer_id: PeerID | None = None, addr: str | None = None) -> Host:
        pid = peer_id if peer_id is not None else f"peer-{len(self.hosts)}"
        if pid in self.hosts:
            raise ValueError(f"duplicate peer id {pid}")
        h = Host(self, pid, addr or f"10.0.{len(self.hosts) // 256}.{len(self.hosts) % 256}")
        self.hosts[pid] = h
        return h

    # topology builders mirroring floodsub_test.go:58-100
    def connect(self, a: Host, b: Host) -> None:
        a.connect(b)

    def connect_all(self, hosts: list[Host]) -> None:
        for i, a in enumerate(hosts):
            for b in hosts[i + 1:]:
                a.connect(b)

    def sparse_connect(self, hosts: list[Host], degree: int = 3, seed: int = 314159) -> None:
        self.connect_some(hosts, degree, seed)

    def dense_connect(self, hosts: list[Host], degree: int = 10, seed: int = 314159) -> None:
        self.connect_some(hosts, degree, seed)

    def connect_some(self, hosts: list[Host], d: int, seed: int = 314159) -> None:
        import random
        rng = random.Random(seed)
        n = len(hosts)
        for i, a in enumerate(hosts):
            for _ in range(d):
                j = rng.randrange(n)
                if j != i:
                    a.connect(hosts[j])
