"""RandomSubRouter: probabilistic flooding (randomsub.go).

Forward to max(RandomSubD, ceil(sqrt(network size))) randomly selected topic
peers (randomsub.go:124-143).
"""

from __future__ import annotations

import math
from typing import TYPE_CHECKING

from ..core.types import RPC, AcceptStatus, Message, PeerID

if TYPE_CHECKING:
    from ..api.pubsub import PubSub

RANDOMSUB_ID = "/randomsub/1.0.0"
RANDOMSUB_D = 6  # randomsub.go:16


class RandomSubRouter:
    def __init__(self, size: int):
        """``size`` estimates the network size (NewRandomSub, randomsub.go:21-35)."""
        self.p: "PubSub | None" = None
        self.size = size
        self.peers: dict[PeerID, str] = {}

    def protocols(self) -> list[str]:
        from .floodsub import FLOODSUB_ID
        return [RANDOMSUB_ID, FLOODSUB_ID]

    def attach(self, p: "PubSub") -> None:
        self.p = p

    def add_peer(self, peer: PeerID, proto: str) -> None:
        self.peers[peer] = proto

    def remove_peer(self, peer: PeerID) -> None:
        self.peers.pop(peer, None)

    def enough_peers(self, topic: str, suggested: int) -> bool:
        """randomsub.go:60-74."""
        assert self.p is not None
        tmap = self.p.topics.get(topic, ())
        if suggested == 0:
            suggested = RANDOMSUB_D
        return len(tmap) >= suggested

    def accept_from(self, peer: PeerID) -> AcceptStatus:
        return AcceptStatus.ACCEPT_ALL

    def handle_rpc(self, rpc: RPC) -> None:
        pass

    def publish(self, msg: Message) -> None:
        """randomsub.go:99-160: floodsub peers always get it; randomsub peers
        get it with probability target/candidates."""
        p = self.p
        assert p is not None
        from .floodsub import FLOODSUB_ID
        src = msg.received_from
        author = msg.from_peer
        tmap = p.topics.get(msg.topic, set())
        flood_targets: list[PeerID] = []
        rs_candidates: list[PeerID] = []
        for peer in sorted(tmap):
            if peer == src or peer == author or peer not in p.peers:
                continue
            if self.peers.get(peer) == FLOODSUB_ID:
                flood_targets.append(peer)
            else:
                rs_candidates.append(peer)

        target = max(RANDOMSUB_D, math.isqrt(self.size)
                     + (0 if math.isqrt(self.size) ** 2 == self.size else 1))
        if len(rs_candidates) > target:
            p.rng.shuffle(rs_candidates)
            rs_candidates = rs_candidates[:target]
        for peer in flood_targets + rs_candidates:
            p.send_rpc(peer, RPC(publish=[msg]))

    def join(self, topic: str) -> None:
        assert self.p is not None
        self.p.tracer.join(topic)

    def leave(self, topic: str) -> None:
        assert self.p is not None
        self.p.tracer.leave(topic)
