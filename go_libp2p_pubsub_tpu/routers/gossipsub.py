"""GossipSubRouter (host-side functional core): gossipsub v1.1 (gossipsub.go).

Mesh overlay (GRAFT/PRUNE) + lazy gossip (IHAVE/IWANT), fanout, heartbeat
maintenance, PX, direct peers, flood publish, opportunistic grafting, RPC
fragmentation, scoring + gater + promise-tracker integration. Runs on the
deterministic scheduler (heartbeat timer -> scheduler event, PX connector ->
scheduled connect) with node-seeded RNG instead of Go's global shuffles.
"""

from __future__ import annotations

import logging
import random
import time
from typing import TYPE_CHECKING

from ..core.params import GossipSubParams, PeerScoreParams, PeerScoreThresholds
from ..core.types import (
    RPC,
    AcceptStatus,
    ControlGraft,
    ControlIHave,
    ControlIWant,
    ControlMessage,
    ControlPrune,
    Message,
    PeerID,
    PeerInfo,
)
from ..utils.mcache import MessageCache
from .feat import (
    GOSSIPSUB_ID_V10,
    GOSSIPSUB_ID_V11,
    GossipSubFeature,
    GossipSubFeatureTest,
    default_features,
)
from .floodsub import FLOODSUB_ID
from .gossip_tracer import GossipPromiseTracker
from .score import PeerScore

if TYPE_CHECKING:
    from ..api.pubsub import PubSub


class GossipSubRouter:
    """gossipsub.go:420-477."""

    def __init__(self, params: GossipSubParams | None = None, *,
                 score_params: PeerScoreParams | None = None,
                 thresholds: PeerScoreThresholds | None = None,
                 direct_peers: list[PeerID] | None = None,
                 do_px: bool = False,
                 flood_publish: bool = False,
                 gater=None,
                 feature_test: GossipSubFeatureTest = default_features,
                 protocols: list[str] | None = None):
        self.p: "PubSub | None" = None
        self.params = params or GossipSubParams()
        self.peers: dict[PeerID, str] = {}
        self.direct: set[PeerID] = set(direct_peers or ())
        self.mesh: dict[str, set[PeerID]] = {}
        self.fanout: dict[str, set[PeerID]] = {}
        self.lastpub: dict[str, float] = {}
        self.gossip: dict[PeerID, list[ControlIHave]] = {}
        self.control: dict[PeerID, ControlMessage] = {}
        self.peerhave: dict[PeerID, int] = {}
        self.iasked: dict[PeerID, int] = {}
        self.outbound: dict[PeerID, bool] = {}
        self.backoff: dict[str, dict[PeerID, float]] = {}
        self.protos = list(protocols or [GOSSIPSUB_ID_V11, GOSSIPSUB_ID_V10,
                                         FLOODSUB_ID])
        self.feature = feature_test

        self.do_px = do_px
        self.flood_publish = flood_publish
        self.heartbeat_ticks = 0
        th = thresholds or PeerScoreThresholds()
        self.accept_px_threshold = th.accept_px_threshold
        self.gossip_threshold = th.gossip_threshold
        self.publish_threshold = th.publish_threshold
        self.graylist_threshold = th.graylist_threshold
        self.opportunistic_graft_threshold = th.opportunistic_graft_threshold

        self._score_params = score_params
        self._inspect_fn = None
        self._inspect_ex_fn = None
        self._inspect_period = 0.0
        self.score: PeerScore | None = None
        self.gossip_tracer: GossipPromiseTracker | None = None
        self.gate = gater
        self.tag_tracer = None  # wired in attach (connmgr decaying tags)
        self.mcache = MessageCache(self.params.history_gossip,
                                   self.params.history_length)
        self.rng = random.Random(0)
        self._pending_connects: list[PeerInfo] = []

    # -- scoring accessor: 0 when scoring disabled (score.go nil receiver) --

    def _score_of(self, peer: PeerID) -> float:
        return self.score.score(peer) if self.score is not None else 0.0

    def with_peer_score_inspect(self, inspect, period: float, *,
                                extended: bool = False) -> None:
        """WithPeerScoreInspect (score.go:143-180): register a periodic
        score-debugging callback — ``{peer: score}`` by default, or
        ``{peer: PeerScoreSnapshot}`` with ``extended=True`` (the
        ExtendedPeerScoreInspectFn variant). Must be configured with
        scoring enabled and at most once, as the reference enforces."""
        if self._score_params is None:
            raise ValueError("peer scoring is not enabled")
        if self._inspect_fn is not None or self._inspect_ex_fn is not None:
            raise ValueError("duplicate peer score inspector")
        if period <= 0:
            # a zero-period ticker would wedge the virtual clock (Go's
            # time.NewTicker panics on non-positive periods)
            raise ValueError("inspect period must be positive")
        if extended:
            self._inspect_ex_fn = inspect
        else:
            self._inspect_fn = inspect
        self._inspect_period = period
        if self.score is not None:          # post-attach registration
            self._wire_inspect(self.p.scheduler)

    def _wire_inspect(self, sched) -> None:
        self.score.inspect = self._inspect_fn
        self.score.inspect_ex = self._inspect_ex_fn
        self.score.inspect_period = self._inspect_period
        sched.call_every(self._inspect_period, self.score.inspect_scores)

    # -- Router interface --

    def protocols(self) -> list[str]:
        return list(self.protos)

    def attach(self, p: "PubSub") -> None:
        """gossipsub.go:488-523."""
        self.p = p
        self.rng = p.rng
        sched = p.scheduler
        if self._score_params is not None:
            self.score = PeerScore(self._score_params, sched.now,
                                   get_ips=p.host.conns_to_peer, id_gen=p.id_gen)
            p.tracer.add_raw(self.score)
            self.gossip_tracer = GossipPromiseTracker(
                sched.now, self.params.iwant_followup_time, rng=self.rng,
                id_gen=p.id_gen)
            p.tracer.add_raw(self.gossip_tracer)
            # score background tickers (score.go:408-445)
            decay = self._score_params.decay_interval or 1.0
            sched.call_every(decay, self.score.refresh_scores)
            sched.call_every(60.0, self.score.refresh_ips)
            sched.call_every(60.0, self.score.gc_delivery_records)
            if self._inspect_fn is not None or self._inspect_ex_fn is not None:
                self._wire_inspect(sched)
        if self.gate is not None:
            self.gate.attach(p)
            p.tracer.add_raw(self.gate)
        # connmgr tag tracer (NewGossipSub wires rt.tagTracer, gossipsub.go:208-212)
        from .tag_tracer import TagTracer
        self.tag_tracer = TagTracer(p.host.conn_manager, id_gen=p.id_gen,
                                    direct=self.direct)
        p.tracer.add_raw(self.tag_tracer)
        self.mcache.set_msg_id_fn(p.id_gen.id)
        sched.call_every(self.params.heartbeat_interval, self.heartbeat,
                         initial_delay=self.params.heartbeat_initial_delay)
        if self.direct:
            sched.call_later(self.params.direct_connect_initial_delay,
                             self._connect_direct)

    def add_peer(self, peer: PeerID, proto: str) -> None:
        """gossipsub.go:525-556; connection direction from the substrate."""
        self.peers[peer] = proto
        assert self.p is not None
        self.outbound[peer] = self.p.host.conns.get(peer) == "outbound"

    def remove_peer(self, peer: PeerID) -> None:
        """gossipsub.go:558-567."""
        self.peers.pop(peer, None)
        for peers in self.mesh.values():
            peers.discard(peer)
        for peers in self.fanout.values():
            peers.discard(peer)
        self.gossip.pop(peer, None)
        self.control.pop(peer, None)
        self.outbound.pop(peer, None)

    def enough_peers(self, topic: str, suggested: int) -> bool:
        """gossipsub.go:569-595."""
        assert self.p is not None
        tmap = self.p.topics.get(topic)
        if tmap is None:
            return False
        fs_peers = sum(1 for p in tmap
                       if not self.feature(GossipSubFeature.MESH, self.peers.get(p, "")))
        gs_peers = len(self.mesh.get(topic, ()))
        if suggested == 0:
            suggested = self.params.dlo
        return fs_peers + gs_peers >= suggested or gs_peers >= self.params.dhi

    def accept_from(self, peer: PeerID) -> AcceptStatus:
        """gossipsub.go:597-609."""
        if peer in self.direct:
            return AcceptStatus.ACCEPT_ALL
        if self._score_of(peer) < self.graylist_threshold:
            return AcceptStatus.ACCEPT_NONE
        if self.gate is not None:
            return self.gate.accept_from(peer)
        return AcceptStatus.ACCEPT_ALL

    def handle_rpc(self, rpc: RPC) -> None:
        """gossipsub.go:611-628."""
        ctl = rpc.control
        if ctl is None or ctl.is_empty():
            return
        src = rpc.from_peer
        assert src is not None
        iwant = self.handle_ihave(src, ctl)
        ihave = self.handle_iwant(src, ctl)
        prune = self.handle_graft(src, ctl)
        self.handle_prune(src, ctl)
        if not iwant and not ihave and not prune:
            return
        out = RPC(publish=ihave,
                  control=ControlMessage(iwant=iwant, prune=prune))
        self.send_rpc(src, out)

    # -- control handlers --

    def handle_ihave(self, peer: PeerID, ctl: ControlMessage) -> list[ControlIWant]:
        """gossipsub.go:630-696."""
        assert self.p is not None
        if self._score_of(peer) < self.gossip_threshold:
            return []
        self.peerhave[peer] = self.peerhave.get(peer, 0) + 1
        if self.peerhave[peer] > self.params.max_ihave_messages:
            return []
        if self.iasked.get(peer, 0) >= self.params.max_ihave_length:
            return []
        iwant: dict[str, None] = {}
        for ihave in ctl.ihave:
            topic = ihave.topic
            if topic not in self.mesh:
                continue
            if not self.p.peer_filter(peer, topic):
                continue
            for mid in ihave.message_ids:
                if self.p.seen.has(mid):
                    continue
                iwant[mid] = None
        if not iwant:
            return []
        iask = min(len(iwant), self.params.max_ihave_length - self.iasked.get(peer, 0))
        lst = list(iwant)
        self.rng.shuffle(lst)
        lst = lst[:iask]
        self.iasked[peer] = self.iasked.get(peer, 0) + iask
        if self.gossip_tracer is not None:
            self.gossip_tracer.add_promise(peer, lst)
        return [ControlIWant(message_ids=lst)]

    def handle_iwant(self, peer: PeerID, ctl: ControlMessage) -> list[Message]:
        """gossipsub.go:698-739."""
        assert self.p is not None
        if self._score_of(peer) < self.gossip_threshold:
            return []
        ihave: dict[str, Message] = {}
        for iwant in ctl.iwant:
            for mid in iwant.message_ids:
                msg, count = self.mcache.get_for_peer(mid, peer)
                if msg is None:
                    continue
                if not self.p.peer_filter(peer, msg.topic):
                    continue
                if count > self.params.gossip_retransmission:
                    continue
                ihave[mid] = msg
        return list(ihave.values())

    def handle_graft(self, peer: PeerID, ctl: ControlMessage) -> list[ControlPrune]:
        """gossipsub.go:741-837."""
        assert self.p is not None
        prune: list[str] = []
        do_px = self.do_px
        score = self._score_of(peer)
        now = self.p.scheduler.now()
        for graft in ctl.graft:
            topic = graft.topic
            if not self.p.peer_filter(peer, topic):
                continue
            peers = self.mesh.get(topic)
            if peers is None:
                # unknown topic: no PX (don't leak peers), spam hardening
                do_px = False
                continue
            if peer in peers:
                continue
            if peer in self.direct:
                prune.append(topic)
                do_px = False
                continue
            expire = self.backoff.get(topic, {}).get(peer)
            if expire is not None and now < expire:
                # graft during backoff: behaviour penalty (+flood extra)
                if self.score is not None:
                    self.score.add_penalty(peer, 1)
                do_px = False
                flood_cutoff = expire + self.params.graft_flood_threshold \
                    - self.params.prune_backoff
                if now < flood_cutoff and self.score is not None:
                    self.score.add_penalty(peer, 1)
                self.add_backoff(peer, topic, is_unsubscribe=False)
                prune.append(topic)
                continue
            if score < 0:
                prune.append(topic)
                do_px = False
                self.add_backoff(peer, topic, is_unsubscribe=False)
                continue
            if len(peers) >= self.params.dhi and not self.outbound.get(peer, False):
                prune.append(topic)
                self.add_backoff(peer, topic, is_unsubscribe=False)
                continue
            self.p.tracer.graft(peer, topic)
            peers.add(peer)
        return [self.make_prune(peer, t, do_px, False) for t in prune]

    def handle_prune(self, peer: PeerID, ctl: ControlMessage) -> None:
        """gossipsub.go:839-871."""
        assert self.p is not None
        score = self._score_of(peer)
        for pr in ctl.prune:
            topic = pr.topic
            peers = self.mesh.get(topic)
            if peers is None:
                continue
            self.p.tracer.prune(peer, topic)
            peers.discard(peer)
            if pr.backoff > 0:
                self.do_add_backoff(peer, topic, pr.backoff)
            else:
                self.add_backoff(peer, topic, is_unsubscribe=False)
            if pr.peers:
                if score < self.accept_px_threshold:
                    continue
                self.px_connect(pr.peers)

    def add_backoff(self, peer: PeerID, topic: str, is_unsubscribe: bool) -> None:
        interval = self.params.unsubscribe_backoff if is_unsubscribe \
            else self.params.prune_backoff
        self.do_add_backoff(peer, topic, interval)

    def do_add_backoff(self, peer: PeerID, topic: str, interval: float) -> None:
        """gossipsub.go:880-891 (keeps the later expiry)."""
        assert self.p is not None
        backoff = self.backoff.setdefault(topic, {})
        expire = self.p.scheduler.now() + interval
        if backoff.get(peer, 0.0) < expire:
            backoff[peer] = expire

    def px_connect(self, peers: list[PeerInfo]) -> None:
        """gossipsub.go:893-943: dial up to PrunePeers learned peers, bounded
        pending queue, via the scheduler (the connector goroutines). A
        PeerInfo carrying a signed record must validate — envelope signature
        over the peer-record domain AND record id matching the announced id
        — or the peer is skipped entirely (gossipsub.go:909-926)."""
        assert self.p is not None
        from ..api.peer_record import RecordError, consume_peer_record

        if len(peers) > self.params.prune_peers:
            peers = list(peers)
            self.rng.shuffle(peers)
            peers = peers[:self.params.prune_peers]
        for pi in peers:
            if pi.peer_id in self.peers:
                continue
            if pi.signed_peer_record is not None:
                try:
                    rec = consume_peer_record(pi.signed_peer_record)
                except RecordError:
                    continue    # bogus envelope obtained through px
                if rec.peer_id != pi.peer_id:
                    continue    # record doesn't certify the announced peer
            if len(self._pending_connects) >= self.params.max_pending_connections:
                break
            self._pending_connects.append(pi)
        if self._pending_connects:
            self.p.scheduler.call_later(0.0, self._drain_connects)

    def _drain_connects(self) -> None:
        assert self.p is not None
        pending, self._pending_connects = self._pending_connects, []
        for pi in pending:
            other = self.p.host.network.hosts.get(pi.peer_id)
            if other is not None and pi.peer_id not in self.p.host.conns:
                if self.p.host.connect(other) \
                        and pi.signed_peer_record is not None:
                    # validated in px_connect; persist like ConsumePeerRecord
                    # only after the dial succeeds (gossipsub.go:954-958)
                    self.p.host.certified_records[pi.peer_id] = \
                        pi.signed_peer_record

    def _connect_direct(self) -> None:
        assert self.p is not None
        for peer in sorted(self.direct):
            if peer not in self.peers:
                other = self.p.host.network.hosts.get(peer)
                if other is not None:
                    self.p.host.connect(other)

    # -- publish (gossipsub.go:975-1045) --

    def publish(self, msg: Message) -> None:
        assert self.p is not None
        self.mcache.put(msg)
        src = msg.received_from
        topic = msg.topic
        tmap = self.p.topics.get(topic)
        if not tmap:
            return
        tosend: set[PeerID] = set()
        if self.flood_publish and src == self.p.pid:
            for pr in tmap:
                if pr in self.direct or self._score_of(pr) >= self.publish_threshold:
                    tosend.add(pr)
        else:
            for pr in self.direct:
                if pr in tmap:
                    tosend.add(pr)
            for pr in tmap:
                if not self.feature(GossipSubFeature.MESH, self.peers.get(pr, "")) \
                        and self._score_of(pr) >= self.publish_threshold:
                    tosend.add(pr)
            gmap = self.mesh.get(topic)
            if gmap is None:
                gmap = self.fanout.get(topic)
                if not gmap:
                    plst = self.get_peers(topic, self.params.d, lambda p: (
                        p not in self.direct
                        and self._score_of(p) >= self.publish_threshold))
                    if plst:
                        gmap = set(plst)
                        self.fanout[topic] = gmap
                    else:
                        gmap = set()
                self.lastpub[topic] = self.p.scheduler.now()
            tosend |= gmap
        for pid in sorted(tosend):
            if pid == src or pid == msg.from_peer:
                continue
            self.send_rpc(pid, RPC(publish=[msg]))

    # -- join/leave (gossipsub.go:1047-1124) --

    def join(self, topic: str) -> None:
        assert self.p is not None
        if topic in self.mesh:
            return
        self.p.tracer.join(topic)
        gmap = self.fanout.get(topic)
        if gmap is not None:
            backoff = self.backoff.get(topic, {})
            gmap = {p for p in gmap
                    if self._score_of(p) >= 0 and p not in backoff}
            if len(gmap) < self.params.d:
                more = self.get_peers(topic, self.params.d - len(gmap), lambda p: (
                    p not in gmap and p not in self.direct and p not in backoff
                    and self._score_of(p) >= 0))
                gmap |= set(more)
            self.mesh[topic] = gmap
            self.fanout.pop(topic, None)
            self.lastpub.pop(topic, None)
        else:
            backoff = self.backoff.get(topic, {})
            gmap = set(self.get_peers(topic, self.params.d, lambda p: (
                p not in self.direct and p not in backoff
                and self._score_of(p) >= 0)))
            self.mesh[topic] = gmap
        for p in sorted(gmap):
            self.p.tracer.graft(p, topic)
            self.send_rpc(p, RPC(control=ControlMessage(
                graft=[ControlGraft(topic=topic)])))

    def leave(self, topic: str) -> None:
        assert self.p is not None
        gmap = self.mesh.pop(topic, None)
        if gmap is None:
            return
        self.p.tracer.leave(topic)
        for p in sorted(gmap):
            self.p.tracer.prune(p, topic)
            self.send_rpc(p, RPC(control=ControlMessage(
                prune=[self.make_prune(p, topic, self.do_px, True)])))
            self.add_backoff(p, topic, is_unsubscribe=True)

    # -- RPC send path with piggybacking + fragmentation --

    def send_rpc(self, peer: PeerID, out: RPC) -> None:
        """gossipsub.go:1138-1202."""
        assert self.p is not None
        ctl = self.control.pop(peer, None)
        if ctl is not None:
            self.piggyback_control(peer, out, ctl)
        ihave = self.gossip.pop(peer, None)
        if ihave is not None:
            if out.control is None:
                out.control = ControlMessage()
            out.control.ihave.extend(ihave)
        if peer not in self.p.peers:
            return
        if out.size() < self.p.max_message_size:
            self._do_send(peer, out)
            return
        for frag in fragment_rpc(out, self.p.max_message_size):
            self._do_send(peer, frag)

    def _do_send(self, peer: PeerID, rpc: RPC) -> None:
        assert self.p is not None
        if self.p.host.send(peer, rpc):
            self.p.tracer.send_rpc(rpc, peer)
        else:
            self.p.tracer.drop_rpc(rpc, peer)
            # re-queue GRAFT/PRUNE for retry; gossip is not retried
            # (gossipsub.go:1285-1300 doDropRPC/pushControl)
            if rpc.control is not None and (rpc.control.graft or rpc.control.prune):
                self.push_control(peer, ControlMessage(
                    graft=rpc.control.graft, prune=rpc.control.prune))

    def push_control(self, peer: PeerID, ctl: ControlMessage) -> None:
        if ctl.graft or ctl.prune:
            existing = self.control.get(peer)
            if existing is None:
                self.control[peer] = ControlMessage(graft=list(ctl.graft),
                                                    prune=list(ctl.prune))
            else:
                existing.graft.extend(ctl.graft)
                existing.prune.extend(ctl.prune)

    def piggyback_control(self, peer: PeerID, out: RPC, ctl: ControlMessage) -> None:
        """Drop stale retries (gossipsub.go:1822-1864)."""
        tograft = [g for g in ctl.graft if peer in self.mesh.get(g.topic, set())]
        toprune = [pr for pr in ctl.prune if peer not in self.mesh.get(pr.topic, set())]
        if not tograft and not toprune:
            return
        if out.control is None:
            out.control = ControlMessage()
        out.control.graft.extend(tograft)
        out.control.prune.extend(toprune)

    def make_prune(self, peer: PeerID, topic: str, do_px: bool,
                   is_unsubscribe: bool) -> ControlPrune:
        """gossipsub.go:1866-1906."""
        assert self.p is not None
        if not self.feature(GossipSubFeature.PX, self.peers.get(peer, "")):
            return ControlPrune(topic=topic)
        backoff = self.params.unsubscribe_backoff if is_unsubscribe \
            else self.params.prune_backoff
        px: list[PeerInfo] = []
        if do_px:
            plst = self.get_peers(topic, self.params.prune_peers, lambda xp: (
                xp != peer and self._score_of(xp) >= 0))
            # attach the signed record when the certified store has one;
            # otherwise just the id — unsigned PX addresses can't be
            # trusted anyway (gossipsub.go:1885-1901)
            px = [PeerInfo(peer_id=p,
                           signed_peer_record=(
                               self.p.host.certified_records.get(p)))
                  for p in plst]
        return ControlPrune(topic=topic, peers=px, backoff=backoff)

    def get_peers(self, topic: str, count: int, flt) -> list[PeerID]:
        """Random topic peers passing the filter (gossipsub.go:1908-1928)."""
        assert self.p is not None
        tmap = self.p.topics.get(topic)
        if not tmap:
            return []
        peers = [p for p in sorted(tmap)
                 if self.feature(GossipSubFeature.MESH, self.peers.get(p, ""))
                 and flt(p) and self.p.peer_filter(p, topic)]
        self.rng.shuffle(peers)
        if 0 < count < len(peers):
            peers = peers[:count]
        return peers

    # -- heartbeat (gossipsub.go:1345-1606) --

    def heartbeat(self) -> None:
        """Timed wrapper: warn when one heartbeat burns more wall-clock than
        slow_heartbeat_warning x the (virtual) interval — the reference's
        slow-heartbeat observability (gossipsub.go:1346-1354)."""
        start = time.perf_counter()
        try:
            self._heartbeat()
        finally:
            if self.params.slow_heartbeat_warning > 0:
                dt = time.perf_counter() - start
                slow = (self.params.slow_heartbeat_warning *
                        self.params.heartbeat_interval)
                if dt > slow:
                    logging.getLogger(__name__).warning(
                        "slow heartbeat: took %.3fs (interval %.1fs)",
                        dt, self.params.heartbeat_interval)

    def _heartbeat(self) -> None:
        assert self.p is not None
        self.heartbeat_ticks += 1
        tograft: dict[PeerID, list[str]] = {}
        toprune: dict[PeerID, list[str]] = {}
        no_px: dict[PeerID, bool] = {}

        self.clear_backoff()
        self.peerhave.clear()
        self.iasked.clear()
        self.apply_iwant_penalties()
        if self.heartbeat_ticks % self.params.direct_connect_ticks == 0 \
                and self.direct:
            self._connect_direct()

        scores: dict[PeerID, float] = {}

        def score(p: PeerID) -> float:
            if p not in scores:
                scores[p] = self._score_of(p)
            return scores[p]

        for topic, peers in self.mesh.items():
            def prune_peer(p: PeerID, topic=topic, peers=peers):
                self.p.tracer.prune(p, topic)
                peers.discard(p)
                self.add_backoff(p, topic, is_unsubscribe=False)
                toprune.setdefault(p, []).append(topic)

            def graft_peer(p: PeerID, topic=topic, peers=peers):
                self.p.tracer.graft(p, topic)
                peers.add(p)
                tograft.setdefault(p, []).append(topic)

            # drop negative-score peers, no PX
            for p in sorted(peers):
                if score(p) < 0:
                    prune_peer(p)
                    no_px[p] = True

            backoff = self.backoff.get(topic, {})
            # undersubscription
            if len(peers) < self.params.dlo:
                ineed = self.params.d - len(peers)
                for p in self.get_peers(topic, ineed, lambda p: (
                        p not in peers and p not in backoff
                        and p not in self.direct and score(p) >= 0)):
                    graft_peer(p)

            # oversubscription (gossipsub.go:1430-1490)
            if len(peers) > self.params.dhi:
                plst = sorted(peers)
                self.rng.shuffle(plst)
                plst.sort(key=lambda p: -score(p))
                tail = plst[self.params.dscore:]
                self.rng.shuffle(tail)
                plst[self.params.dscore:] = tail
                outbound = sum(1 for p in plst[:self.params.d]
                               if self.outbound.get(p, False))
                if outbound < self.params.dout:
                    def rotate(i):
                        p = plst.pop(i)
                        plst.insert(0, p)
                    if outbound > 0:
                        ihave_ct = outbound
                        i = 1
                        while i < self.params.d and ihave_ct > 0:
                            if self.outbound.get(plst[i], False):
                                rotate(i)
                                ihave_ct -= 1
                            i += 1
                    ineed = self.params.dout - outbound
                    i = self.params.d
                    while i < len(plst) and ineed > 0:
                        if self.outbound.get(plst[i], False):
                            rotate(i)
                            ineed -= 1
                        i += 1
                for p in plst[self.params.d:]:
                    prune_peer(p)

            # outbound quota (gossipsub.go:1493-1518)
            if len(peers) >= self.params.dlo:
                outbound = sum(1 for p in peers if self.outbound.get(p, False))
                if outbound < self.params.dout:
                    ineed = self.params.dout - outbound
                    for p in self.get_peers(topic, ineed, lambda p: (
                            p not in peers and p not in backoff
                            and p not in self.direct
                            and self.outbound.get(p, False) and score(p) >= 0)):
                        graft_peer(p)

            # opportunistic grafting (gossipsub.go:1521-1552)
            if self.heartbeat_ticks % self.params.opportunistic_graft_ticks == 0 \
                    and len(peers) > 1:
                plst = sorted(peers, key=score)
                median_score = score(plst[len(plst) // 2])
                if median_score < self.opportunistic_graft_threshold:
                    for p in self.get_peers(
                            topic, self.params.opportunistic_graft_peers,
                            lambda p: (p not in peers and p not in backoff
                                       and p not in self.direct
                                       and score(p) > median_score)):
                        graft_peer(p)

            self.emit_gossip(topic, peers)

        # fanout expiry + maintenance (gossipsub.go:1560-1596)
        now = self.p.scheduler.now()
        for topic in list(self.lastpub):
            if self.lastpub[topic] + self.params.fanout_ttl < now:
                self.fanout.pop(topic, None)
                del self.lastpub[topic]
        for topic, peers in self.fanout.items():
            tmap = self.p.topics.get(topic, set())
            for p in sorted(peers):
                if p not in tmap or score(p) < self.publish_threshold:
                    peers.discard(p)
            if len(peers) < self.params.d:
                for p in self.get_peers(topic, self.params.d - len(peers),
                                        lambda p: (p not in peers
                                                   and p not in self.direct
                                                   and score(p) >= self.publish_threshold)):
                    peers.add(p)
            self.emit_gossip(topic, peers)

        self.send_graft_prune(tograft, toprune, no_px)
        self.flush()
        self.mcache.shift()

    def apply_iwant_penalties(self) -> None:
        if self.gossip_tracer is not None and self.score is not None:
            for p, count in self.gossip_tracer.get_broken_promises().items():
                self.score.add_penalty(p, count)

    def clear_backoff(self) -> None:
        """Every 15 ticks, expire with 2-heartbeat slack (gossipsub.go:1627-1646)."""
        if self.heartbeat_ticks % 15 != 0:
            return
        assert self.p is not None
        now = self.p.scheduler.now()
        for topic in list(self.backoff):
            bk = self.backoff[topic]
            for p in list(bk):
                if bk[p] + 2 * self.params.heartbeat_interval < now:
                    del bk[p]
            if not bk:
                del self.backoff[topic]

    def send_graft_prune(self, tograft, toprune, no_px) -> None:
        """Coalesced per-peer GRAFT/PRUNE (gossipsub.go:1672-1707)."""
        for p, topics in tograft.items():
            graft = [ControlGraft(topic=t) for t in topics]
            prune = []
            pruning = toprune.pop(p, None)
            if pruning:
                prune = [self.make_prune(p, t, self.do_px and not no_px.get(p, False), False)
                         for t in pruning]
            self.send_rpc(p, RPC(control=ControlMessage(graft=graft, prune=prune)))
        for p, topics in toprune.items():
            prune = [self.make_prune(p, t, self.do_px and not no_px.get(p, False), False)
                     for t in topics]
            self.send_rpc(p, RPC(control=ControlMessage(prune=prune)))

    def emit_gossip(self, topic: str, exclude: set[PeerID]) -> None:
        """gossipsub.go:1711-1775."""
        assert self.p is not None
        mids = self.mcache.get_gossip_ids(topic)
        if not mids:
            return
        self.rng.shuffle(mids)
        tmap = self.p.topics.get(topic, set())
        peers = [p for p in sorted(tmap)
                 if p not in exclude and p not in self.direct
                 and self.feature(GossipSubFeature.MESH, self.peers.get(p, ""))
                 and self._score_of(p) >= self.gossip_threshold]
        target = max(self.params.dlazy,
                     int(self.params.gossip_factor * len(peers)))
        if target < len(peers):
            self.rng.shuffle(peers)
            peers = peers[:target]
        for p in peers:
            peer_mids = mids
            if len(mids) > self.params.max_ihave_length:
                self.rng.shuffle(mids)
                peer_mids = mids[:self.params.max_ihave_length]
            self.gossip.setdefault(p, []).append(
                ControlIHave(topic=topic, message_ids=list(peer_mids)))

    def flush(self) -> None:
        """gossipsub.go:1777-1791."""
        for p in list(self.gossip):
            ihave = self.gossip.pop(p)
            self.send_rpc(p, RPC(control=ControlMessage(ihave=ihave)))
        for p in list(self.control):
            ctl = self.control.pop(p)
            self.send_rpc(p, RPC(control=ControlMessage(graft=ctl.graft,
                                                        prune=ctl.prune)))


def fragment_rpc(rpc: RPC, limit: int) -> list[RPC]:
    """Split an oversized RPC (gossipsub.go:1204-1293). Raises ValueError for
    a single message exceeding the limit."""
    if rpc.size() < limit:
        return [rpc]
    out: list[RPC] = [RPC()]

    def out_rpc(size_to_add: int, with_ctl: bool) -> RPC:
        cur = out[-1]
        if cur.size() + size_to_add + 1 < limit:
            if with_ctl and cur.control is None:
                cur.control = ControlMessage()
            return cur
        nxt = RPC(control=ControlMessage() if with_ctl else None)
        out.append(nxt)
        return nxt

    for msg in rpc.publish:
        s = RPC(publish=[msg]).size()
        if s > limit:
            raise ValueError(f"message with len={s} exceeds limit {limit}")
        out_rpc(s, False).publish.append(msg)
    for sub in rpc.subscriptions:
        out_rpc(len(sub.topicid) + 4, False).subscriptions.append(sub)
    ctl = rpc.control
    if ctl is None or ctl.is_empty():
        return out
    whole = RPC(control=ctl)
    if whole.size() < limit:
        out.append(whole)
        return out
    for graft in ctl.graft:
        out_rpc(len(graft.topic) + 4, True).control.graft.append(graft)
    for prune in ctl.prune:
        sz = RPC(control=ControlMessage(prune=[prune])).size()
        out_rpc(sz, True).control.prune.append(prune)
    overhead = 6
    for iwant in ctl.iwant:
        for ids in fragment_message_ids(iwant.message_ids, limit - overhead):
            piece = ControlIWant(message_ids=ids)
            sz = RPC(control=ControlMessage(iwant=[piece])).size()
            out_rpc(sz, True).control.iwant.append(piece)
    for ihave in ctl.ihave:
        for ids in fragment_message_ids(ihave.message_ids, limit - overhead):
            piece = ControlIHave(topic=ihave.topic, message_ids=ids)
            sz = RPC(control=ControlMessage(ihave=[piece])).size()
            out_rpc(sz, True).control.ihave.append(piece)
    return out


def fragment_message_ids(mids: list[str], limit: int) -> list[list[str]]:
    """gossipsub.go:1295-1316."""
    overhead = 2
    out: list[list[str]] = [[]]
    blen = 0
    for mid in mids:
        size = len(mid) + overhead
        if size > limit:
            continue  # pathological single id; dropped like the reference
        blen += size
        if blen > limit:
            out.append([])
            blen = size
        out[-1].append(mid)
    return out
