"""Peer gater: Random-Early-Drop before the validation queue (peer_gater.go).

Turns on when throttled/validated exceeds ``threshold``; while on, a peer's
RPCs are admitted with probability (1 + deliveries) / (1 + weighted total) of
its source-IP stats, else stripped to control-only (AcceptControl). Auto-off
after a quiet period without throttle events (peer_gater.go:320-363).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

from ..core.clock import HOUR, MINUTE, SECOND
from ..core.params import (
    DEFAULT_DECAY_INTERVAL,
    DEFAULT_DECAY_TO_ZERO,
    score_parameter_decay,
)
from ..core.types import AcceptStatus, Message, PeerID
from ..trace import events as ev
from ..trace.events import RawTracerBase

if TYPE_CHECKING:
    from ..api.pubsub import PubSub

DEFAULT_PEER_GATER_RETAIN_STATS = 6 * HOUR
DEFAULT_PEER_GATER_QUIET = MINUTE
DEFAULT_PEER_GATER_DUPLICATE_WEIGHT = 0.125
DEFAULT_PEER_GATER_IGNORE_WEIGHT = 1.0
DEFAULT_PEER_GATER_REJECT_WEIGHT = 16.0
DEFAULT_PEER_GATER_THRESHOLD = 0.33
DEFAULT_PEER_GATER_GLOBAL_DECAY = score_parameter_decay(2 * MINUTE)
DEFAULT_PEER_GATER_SOURCE_DECAY = score_parameter_decay(HOUR)


@dataclass
class PeerGaterParams:
    """peer_gater.go:31-116."""

    threshold: float = DEFAULT_PEER_GATER_THRESHOLD
    global_decay: float = DEFAULT_PEER_GATER_GLOBAL_DECAY
    source_decay: float = DEFAULT_PEER_GATER_SOURCE_DECAY
    decay_interval: float = DEFAULT_DECAY_INTERVAL
    decay_to_zero: float = DEFAULT_DECAY_TO_ZERO
    retain_stats: float = DEFAULT_PEER_GATER_RETAIN_STATS
    quiet: float = DEFAULT_PEER_GATER_QUIET
    duplicate_weight: float = DEFAULT_PEER_GATER_DUPLICATE_WEIGHT
    ignore_weight: float = DEFAULT_PEER_GATER_IGNORE_WEIGHT
    reject_weight: float = DEFAULT_PEER_GATER_REJECT_WEIGHT
    topic_delivery_weights: dict[str, float] = field(default_factory=dict)

    def validate(self) -> None:
        """peer_gater.go:57-88."""
        if self.threshold <= 0:
            raise ValueError("invalid Threshold; must be > 0")
        if not 0 < self.global_decay < 1:
            raise ValueError("invalid GlobalDecay; must be between 0 and 1")
        if not 0 < self.source_decay < 1:
            raise ValueError("invalid SourceDecay; must be between 0 and 1")
        if self.decay_interval < 1 * SECOND:
            raise ValueError("invalid DecayInterval; must be at least 1s")
        if not 0 < self.decay_to_zero < 1:
            raise ValueError("invalid DecayToZero; must be between 0 and 1")
        if self.quiet < 1 * SECOND:
            raise ValueError("invalid Quiet interval; must be at least 1s")
        if self.duplicate_weight <= 0:
            raise ValueError("invalid DuplicateWeight; must be > 0")
        if self.ignore_weight < 1:
            raise ValueError("invalid IgnoreWeight; must be >= 1")
        if self.reject_weight < 1:
            raise ValueError("invalid RejectWeight; must be >= 1")


class _Stats:
    __slots__ = ("connected", "expire", "deliver", "duplicate", "ignore", "reject")

    def __init__(self):
        self.connected = 0
        self.expire = 0.0
        self.deliver = 0.0
        self.duplicate = 0.0
        self.ignore = 0.0
        self.reject = 0.0


class PeerGater(RawTracerBase):
    """peer_gater.go:119-151; peers sharing an IP share one stats object."""

    def __init__(self, params: PeerGaterParams | None = None,
                 get_ip: Callable[[PeerID], str] | None = None,
                 rng: random.Random | None = None):
        self.params = params or PeerGaterParams()
        self.params.validate()
        self.peer_stats: dict[PeerID, _Stats] = {}
        self.ip_stats: dict[str, _Stats] = {}
        self.validate = 0.0
        self.throttle = 0.0
        self.last_throttle = -float("inf")
        self._get_ip = get_ip
        self.rng = rng or random.Random(0)
        self._now: Callable[[], float] = lambda: 0.0

    def attach(self, p: "PubSub") -> None:
        self._now = p.scheduler.now
        self.rng = p.rng
        if self._get_ip is None:
            def host_ip(peer: PeerID) -> str:
                addrs = p.host.conns_to_peer(peer)
                return addrs[0] if addrs else "<unknown>"
            self._get_ip = host_ip
        p.scheduler.call_every(self.params.decay_interval, self.decay_stats)

    def _stats_for(self, peer: PeerID) -> _Stats:
        st = self.peer_stats.get(peer)
        if st is None:
            ip = self._get_ip(peer) if self._get_ip else "<unknown>"
            st = self.ip_stats.get(ip)
            if st is None:
                st = _Stats()
                self.ip_stats[ip] = st
            self.peer_stats[peer] = st
        return st

    def decay_stats(self) -> None:
        """peer_gater.go:219-259."""
        z = self.params.decay_to_zero

        def dec(v, factor):
            v *= factor
            return 0.0 if v < z else v

        self.validate = dec(self.validate, self.params.global_decay)
        self.throttle = dec(self.throttle, self.params.global_decay)
        now = self._now()
        for ip in list(self.ip_stats):
            st = self.ip_stats[ip]
            if st.connected > 0:
                st.deliver = dec(st.deliver, self.params.source_decay)
                st.duplicate = dec(st.duplicate, self.params.source_decay)
                st.ignore = dec(st.ignore, self.params.source_decay)
                st.reject = dec(st.reject, self.params.source_decay)
            elif st.expire < now:
                del self.ip_stats[ip]

    def accept_from(self, peer: PeerID) -> AcceptStatus:
        """peer_gater.go:320-363."""
        if self._now() - self.last_throttle > self.params.quiet:
            return AcceptStatus.ACCEPT_ALL
        if self.throttle == 0:
            return AcceptStatus.ACCEPT_ALL
        if self.validate != 0 and self.throttle / self.validate < self.params.threshold:
            return AcceptStatus.ACCEPT_ALL
        st = self._stats_for(peer)
        total = (st.deliver + self.params.duplicate_weight * st.duplicate
                 + self.params.ignore_weight * st.ignore
                 + self.params.reject_weight * st.reject)
        if total == 0:
            return AcceptStatus.ACCEPT_ALL
        threshold = (1 + st.deliver) / (1 + total)
        if self.rng.random() < threshold:
            return AcceptStatus.ACCEPT_ALL
        return AcceptStatus.ACCEPT_CONTROL

    # -- RawTracer hooks (peer_gater.go:366-453) --

    def add_peer(self, peer: PeerID, proto: str) -> None:
        self._stats_for(peer).connected += 1

    def remove_peer(self, peer: PeerID) -> None:
        st = self._stats_for(peer)
        st.connected -= 1
        st.expire = self._now() + self.params.retain_stats
        self.peer_stats.pop(peer, None)

    def validate_message(self, msg: Message) -> None:
        self.validate += 1

    def deliver_message(self, msg: Message) -> None:
        st = self._stats_for(msg.received_from)  # type: ignore[arg-type]
        weight = self.params.topic_delivery_weights.get(msg.topic, 1.0)
        st.deliver += weight

    def reject_message(self, msg: Message, reason: str) -> None:
        if reason in (ev.REJECT_VALIDATION_QUEUE_FULL, ev.REJECT_VALIDATION_THROTTLED):
            self.last_throttle = self._now()
            self.throttle += 1
        elif reason == ev.REJECT_VALIDATION_IGNORED:
            self._stats_for(msg.received_from).ignore += 1  # type: ignore[arg-type]
        else:
            self._stats_for(msg.received_from).reject += 1  # type: ignore[arg-type]

    def duplicate_message(self, msg: Message) -> None:
        self._stats_for(msg.received_from).duplicate += 1  # type: ignore[arg-type]
