from .base import Router  # noqa: F401
from .floodsub import FLOODSUB_ID, FloodSubRouter  # noqa: F401
from .randomsub import RANDOMSUB_ID, RandomSubRouter  # noqa: F401
from .score import PeerScore  # noqa: F401
