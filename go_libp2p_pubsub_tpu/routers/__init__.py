from .base import Router  # noqa: F401
from .feat import (  # noqa: F401
    GOSSIPSUB_ID_V10,
    GOSSIPSUB_ID_V11,
    GossipSubFeature,
    default_features,
)
from .floodsub import FLOODSUB_ID, FloodSubRouter  # noqa: F401
from .gossip_tracer import GossipPromiseTracker  # noqa: F401
from .gossipsub import GossipSubRouter  # noqa: F401
from .peer_gater import PeerGater, PeerGaterParams  # noqa: F401
from .randomsub import RANDOMSUB_ID, RandomSubRouter  # noqa: F401
from .score import PeerScore  # noqa: F401
from .tag_tracer import TagTracer  # noqa: F401
