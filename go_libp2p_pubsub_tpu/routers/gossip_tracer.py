"""Gossip promise tracker (gossip_tracer.go).

Tracks IWANT promises probabilistically: ONE random message id per IWANT is
tracked (gossip_tracer.go:48-66); if the message hasn't arrived (in any form)
within ``followup_time`` the promise is broken and the router applies a P7
penalty per broken promise (gossipsub.go:1620-1625).
"""

from __future__ import annotations

import random
from typing import Callable

from ..core.types import Message, PeerID
from ..trace.events import RawTracerBase
from ..utils.midgen import MsgIdGenerator
from .. import trace


class GossipPromiseTracker(RawTracerBase):
    def __init__(self, now: Callable[[], float], followup_time: float,
                 rng: random.Random | None = None,
                 id_gen: MsgIdGenerator | None = None):
        self._now = now
        self.followup_time = followup_time
        self.rng = rng or random.Random(0)
        self.id_gen = id_gen or MsgIdGenerator()
        # mid -> peer -> expiry (gossip_tracer.go:21)
        self.promises: dict[str, dict[PeerID, float]] = {}
        # peers with broken promises already counted this round
        self.peer_promises: dict[PeerID, set[str]] = {}

    def add_promise(self, peer: PeerID, mids: list[str]) -> None:
        """Track one random id from the IWANT (gossip_tracer.go:48-66)."""
        if not mids:
            return
        mid = mids[self.rng.randrange(len(mids))]
        peers = self.promises.setdefault(mid, {})
        if peer not in peers:
            peers[peer] = self._now() + self.followup_time
            self.peer_promises.setdefault(peer, set()).add(mid)

    def get_broken_promises(self) -> dict[PeerID, int]:
        """Expired, unfulfilled promises per peer; expired entries are dropped
        (gossip_tracer.go:79-105)."""
        now = self._now()
        result: dict[PeerID, int] = {}
        to_del = []
        for mid, peers in self.promises.items():
            broken = [p for p, exp in peers.items() if exp < now]
            for p in broken:
                result[p] = result.get(p, 0) + 1
                del peers[p]
                pp = self.peer_promises.get(p)
                if pp is not None:
                    pp.discard(mid)
                    if not pp:
                        del self.peer_promises[p]
            if not peers:
                to_del.append(mid)
        for mid in to_del:
            del self.promises[mid]
        return result

    def _fulfill(self, msg: Message) -> None:
        """Message arrived in ANY form -> promises for its id are satisfied
        (gossip_tracer.go:109-133)."""
        mid = self.id_gen.id(msg)
        peers = self.promises.pop(mid, None)
        if peers:
            for p in peers:
                pp = self.peer_promises.get(p)
                if pp is not None:
                    pp.discard(mid)
                    if not pp:
                        del self.peer_promises[p]

    # RawTracer hooks (gossip_tracer.go:141-200)
    def deliver_message(self, msg: Message) -> None:
        self._fulfill(msg)

    def reject_message(self, msg: Message, reason: str) -> None:
        # obviously-invalid deliveries (bad/missing signature) keep the
        # promise penalty on top of the invalid-delivery one
        # (gossip_tracer.go:146-159)
        if reason in (trace.events.REJECT_MISSING_SIGNATURE,
                      trace.events.REJECT_INVALID_SIGNATURE):
            return
        self._fulfill(msg)

    def validate_message(self, msg: Message) -> None:
        # fulfilled as soon as validation begins (gossip_tracer.go:161-166)
        self._fulfill(msg)

    def throttle_peer(self, peer: PeerID) -> None:
        """Validation throttled the peer: stop tracking all its promises
        (gossip_tracer.go:182-200)."""
        pp = self.peer_promises.pop(peer, None)
        if not pp:
            return
        for mid in pp:
            peers = self.promises.get(mid)
            if peers is not None:
                peers.pop(peer, None)
                if not peers:
                    del self.promises[mid]
