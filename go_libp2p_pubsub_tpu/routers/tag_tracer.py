"""Connmgr tag tracer (tag_tracer.go).

Protects direct and mesh peers in the connection manager and bumps decaying
per-topic delivery tags for first and near-first deliverers (peers who
delivered while the message was still validating).
"""

from __future__ import annotations

from ..core.clock import MINUTE
from ..core.types import Message, PeerID
from ..net.connmgr import ConnManager
from ..trace import events as ev
from ..trace.events import RawTracerBase
from ..utils.midgen import MsgIdGenerator

# tag_tracer.go:13-31
CONN_TAG_BUMP_MESSAGE_DELIVERY = 1
CONN_TAG_DECAY_INTERVAL = 10 * MINUTE
CONN_TAG_DECAY_AMOUNT = 1
CONN_TAG_MESSAGE_DELIVERY_CAP = 15


def topic_tag(topic: str) -> str:
    return f"pubsub:{topic}"


class TagTracer(RawTracerBase):
    def __init__(self, cmgr: ConnManager, id_gen: MsgIdGenerator | None = None,
                 direct: set[PeerID] | None = None):
        self.cmgr = cmgr
        self.id_gen = id_gen or MsgIdGenerator()
        self.direct = direct or set()
        self.decaying: dict[str, object] = {}
        # message id -> peers who delivered during validation (tag_tracer.go:55)
        self.near_first: dict[str, set[PeerID]] = {}

    def start(self, gs) -> None:
        """Wire to the router's idGen and direct set (tag_tracer.go:73-81)."""
        self.id_gen = gs.p.id_gen
        self.direct = gs.direct

    # -- RawTracer hooks (tag_tracer.go:177-259) --

    def add_peer(self, peer: PeerID, proto: str) -> None:
        if peer in self.direct:
            self.cmgr.protect(peer, "pubsub:<direct>")

    def join(self, topic: str) -> None:
        self.decaying[topic] = self.cmgr.register_decaying_tag(
            f"pubsub-deliveries:{topic}", CONN_TAG_DECAY_INTERVAL,
            CONN_TAG_DECAY_AMOUNT, CONN_TAG_MESSAGE_DELIVERY_CAP)

    def leave(self, topic: str) -> None:
        tag = self.decaying.pop(topic, None)
        if tag is not None:
            tag.close()

    def graft(self, peer: PeerID, topic: str) -> None:
        self.cmgr.protect(peer, topic_tag(topic))

    def prune(self, peer: PeerID, topic: str) -> None:
        self.cmgr.unprotect(peer, topic_tag(topic))

    def validate_message(self, msg: Message) -> None:
        self.near_first.setdefault(self.id_gen.id(msg), set())

    def duplicate_message(self, msg: Message) -> None:
        peers = self.near_first.get(self.id_gen.id(msg))
        if peers is not None and msg.received_from is not None:
            peers.add(msg.received_from)

    def deliver_message(self, msg: Message) -> None:
        mid = self.id_gen.id(msg)
        near = self.near_first.pop(mid, set())
        self._bump(msg.received_from, msg.topic)
        for p in near:
            self._bump(p, msg.topic)

    def reject_message(self, msg: Message, reason: str) -> None:
        # only drop tracking for messages that passed through validation
        # (tag_tracer.go:240-254)
        if reason in (ev.REJECT_VALIDATION_THROTTLED, ev.REJECT_VALIDATION_IGNORED,
                      ev.REJECT_VALIDATION_FAILED):
            self.near_first.pop(self.id_gen.id(msg), None)

    def _bump(self, peer: PeerID | None, topic: str) -> None:
        tag = self.decaying.get(topic)
        if tag is not None and peer is not None:
            tag.bump(peer, CONN_TAG_BUMP_MESSAGE_DELIVERY)
