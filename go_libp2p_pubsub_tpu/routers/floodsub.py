"""FloodSubRouter: baseline flooding (floodsub.go).

Forward every validated message to every connected topic peer except the
source and the author (floodsub.go:76-100).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from ..core.types import RPC, AcceptStatus, Message, PeerID

if TYPE_CHECKING:
    from ..api.pubsub import PubSub

FLOODSUB_ID = "/floodsub/1.0.0"
FLOODSUB_TOPIC_SEARCH_SIZE = 5  # floodsub.go:13


class FloodSubRouter:
    def __init__(self, protocols: list[str] | None = None):
        """``protocols`` is NewFloodsubWithProtocols (floodsub.go:29-38):
        a custom protocol list replacing the default floodsub id."""
        self.p: "PubSub | None" = None
        self._protocols = list(protocols) if protocols is not None \
            else [FLOODSUB_ID]

    def protocols(self) -> list[str]:
        return list(self._protocols)

    def attach(self, p: "PubSub") -> None:
        self.p = p

    def add_peer(self, peer: PeerID, proto: str) -> None:
        pass

    def remove_peer(self, peer: PeerID) -> None:
        pass

    def enough_peers(self, topic: str, suggested: int) -> bool:
        """floodsub.go:52-66."""
        assert self.p is not None
        tmap = self.p.topics.get(topic, ())
        if suggested == 0:
            suggested = FLOODSUB_TOPIC_SEARCH_SIZE
        return len(tmap) >= suggested

    def accept_from(self, peer: PeerID) -> AcceptStatus:
        return AcceptStatus.ACCEPT_ALL

    def handle_rpc(self, rpc: RPC) -> None:
        pass  # floodsub has no control plane

    def publish(self, msg: Message) -> None:
        """floodsub.go:76-100."""
        p = self.p
        assert p is not None
        src = msg.received_from
        author = msg.from_peer
        tmap = p.topics.get(msg.topic, set())
        for peer in sorted(tmap):
            if peer == src or peer == author or peer not in p.peers:
                continue
            p.send_rpc(peer, RPC(publish=[msg]))

    def join(self, topic: str) -> None:
        assert self.p is not None
        self.p.tracer.join(topic)

    def leave(self, topic: str) -> None:
        assert self.p is not None
        self.p.tracer.leave(topic)
