"""Peer-score engine (host-side, per-node): gossipsub v1.1 P1-P7.

Faithful functional re-implementation of score.go on the virtual clock:
- score() P1-P7 composition (score.go:265-342), including the duration
  integer-division truncation in P1 (score.go:286)
- refreshScores decay + retention purge (score.go:504-565)
- delivery-record state machine (score.go:90-120, 840-877) driving
  first/duplicate/invalid delivery marking (score.go:899-981)
- IP colocation tracking (score.go:984-1081) against the simulated
  substrate's peer addresses
- RemovePeer score retention for non-positive scores (score.go:611-644)

The batched TPU twin of this engine lives in ops/score_ops.py; both are
validated against the same golden scenarios (tests/test_score.py).
"""

from __future__ import annotations

import ipaddress
from typing import Callable

from ..core.params import TIME_CACHE_DURATION, PeerScoreParams, TopicScoreParams
from ..core.types import Message
from ..trace import events as ev
from ..utils.midgen import MsgIdGenerator

# delivery record status (score.go:110-117)
DELIVERY_UNKNOWN = 0
DELIVERY_VALID = 1
DELIVERY_INVALID = 2
DELIVERY_IGNORED = 3
DELIVERY_THROTTLED = 4


class TopicScoreSnapshot:
    """Per-topic counter dump for extended score inspection
    (score.go:136-141 TopicScoreSnapshot)."""
    __slots__ = ("time_in_mesh", "first_message_deliveries",
                 "mesh_message_deliveries", "invalid_message_deliveries")

    def __init__(self, time_in_mesh=0.0, first_message_deliveries=0.0,
                 mesh_message_deliveries=0.0, invalid_message_deliveries=0.0):
        self.time_in_mesh = time_in_mesh
        self.first_message_deliveries = first_message_deliveries
        self.mesh_message_deliveries = mesh_message_deliveries
        self.invalid_message_deliveries = invalid_message_deliveries


class PeerScoreSnapshot:
    """Full per-peer score decomposition for extended inspection
    (score.go:127-134 PeerScoreSnapshot)."""
    __slots__ = ("score", "topics", "app_specific_score",
                 "ip_colocation_factor", "behaviour_penalty")

    def __init__(self, score=0.0, topics=None, app_specific_score=0.0,
                 ip_colocation_factor=0.0, behaviour_penalty=0.0):
        self.score = score
        self.topics: dict[str, TopicScoreSnapshot] = topics or {}
        self.app_specific_score = app_specific_score
        self.ip_colocation_factor = ip_colocation_factor
        self.behaviour_penalty = behaviour_penalty


class _TopicStats:
    __slots__ = ("in_mesh", "graft_time", "mesh_time", "first_message_deliveries",
                 "mesh_message_deliveries", "mesh_message_deliveries_active",
                 "mesh_failure_penalty", "invalid_message_deliveries")

    def __init__(self):
        self.in_mesh = False
        self.graft_time = 0.0
        self.mesh_time = 0.0
        self.first_message_deliveries = 0.0
        self.mesh_message_deliveries = 0.0
        self.mesh_message_deliveries_active = False
        self.mesh_failure_penalty = 0.0
        self.invalid_message_deliveries = 0.0


class _PeerStats:
    __slots__ = ("connected", "expire", "topics", "ips", "ip_whitelist", "behaviour_penalty")

    def __init__(self):
        self.connected = False
        self.expire = 0.0
        self.topics: dict[str, _TopicStats] = {}
        self.ips: list[str] = []
        self.ip_whitelist: dict[str, bool] = {}
        self.behaviour_penalty = 0.0

    def get_topic_stats(self, topic: str, params: PeerScoreParams) -> _TopicStats | None:
        """Lazily create stats iff the topic is scored (score.go:879-897)."""
        ts = self.topics.get(topic)
        if ts is not None:
            return ts
        if topic not in params.topics:
            return None
        ts = _TopicStats()
        self.topics[topic] = ts
        return ts


class _DeliveryRecord:
    __slots__ = ("status", "first_seen", "validated", "peers")

    def __init__(self, first_seen: float):
        self.status = DELIVERY_UNKNOWN
        self.first_seen = first_seen
        self.validated = 0.0
        self.peers: set[str] | None = set()


class _MessageDeliveries:
    """Record table + FIFO expiry queue (score.go:90-108, 840-877)."""

    def __init__(self, seen_msg_ttl: float, now: Callable[[], float]):
        self._ttl = seen_msg_ttl
        self._now = now
        self.records: dict[str, _DeliveryRecord] = {}
        self._queue: list[tuple[str, float]] = []
        self._head = 0

    def get_record(self, mid: str) -> _DeliveryRecord:
        rec = self.records.get(mid)
        if rec is None:
            now = self._now()
            rec = _DeliveryRecord(now)
            self.records[mid] = rec
            self._queue.append((mid, now + self._ttl))
        return rec

    def gc(self) -> None:
        now = self._now()
        q, h = self._queue, self._head
        while h < len(q) and now > q[h][1]:
            self.records.pop(q[h][0], None)
            h += 1
        if h > 64 and h * 2 > len(q):
            q[:h] = []
            h = 0
        self._head = h


class PeerScore(ev.RawTracerBase):
    """Per-node peer scorer; wired into the router as a RawTracer (score.go:88)."""

    def __init__(self, params: PeerScoreParams, now: Callable[[], float],
                 get_ips: Callable[[str], list[str]] | None = None,
                 id_gen: MsgIdGenerator | None = None):
        self.params = params
        self._now = now
        self._get_ips = get_ips or (lambda p: [])
        self.id_gen = id_gen or MsgIdGenerator()
        self.peer_stats: dict[str, _PeerStats] = {}
        self.peer_ips: dict[str, set[str]] = {}
        seen_ttl = params.seen_msg_ttl or TIME_CACHE_DURATION
        self.deliveries = _MessageDeliveries(seen_ttl, now)
        self._whitelist_nets = [ipaddress.ip_network(c, strict=False)
                                for c in params.ip_colocation_factor_whitelist]
        # debugging inspection (score.go:127-180); called by the node's
        # scheduler. `inspect` receives {peer: score}; `inspect_ex` receives
        # {peer: PeerScoreSnapshot} (ExtendedPeerScoreInspectFn)
        self.inspect: Callable[[dict[str, float]], None] | None = None
        self.inspect_ex: Callable[[dict[str, PeerScoreSnapshot]], None] | None = None
        self.inspect_period: float = 0.0

    # -- scoring (score.go:265-342) --

    def score(self, peer: str) -> float:
        pstats = self.peer_stats.get(peer)
        if pstats is None:
            return 0.0
        score = 0.0
        for topic, ts in pstats.topics.items():
            tp = self.params.topics.get(topic)
            if tp is None:
                continue
            topic_score = 0.0
            # P1: time in mesh, quantized by integer division (score.go:285-291)
            if ts.in_mesh:
                # epsilon guards decimal float quanta (0.3/0.1 -> 2.999...)
                # so truncation matches Go's integer-nanosecond division
                p1 = float(int(ts.mesh_time / tp.time_in_mesh_quantum + 1e-9))
                p1 = min(p1, tp.time_in_mesh_cap)
                topic_score += p1 * tp.time_in_mesh_weight
            # P2: first message deliveries
            topic_score += ts.first_message_deliveries * tp.first_message_deliveries_weight
            # P3: mesh message delivery deficit (squared), only once activated
            if ts.mesh_message_deliveries_active and \
                    ts.mesh_message_deliveries < tp.mesh_message_deliveries_threshold:
                deficit = tp.mesh_message_deliveries_threshold - ts.mesh_message_deliveries
                topic_score += deficit * deficit * tp.mesh_message_deliveries_weight
            # P3b: sticky mesh failure penalty
            topic_score += ts.mesh_failure_penalty * tp.mesh_failure_penalty_weight
            # P4: invalid messages (squared)
            topic_score += (ts.invalid_message_deliveries ** 2) * tp.invalid_message_deliveries_weight
            score += topic_score * tp.topic_weight

        if self.params.topic_score_cap > 0 and score > self.params.topic_score_cap:
            score = self.params.topic_score_cap

        # P5: application-specific
        score += self.params.app_specific_score(peer) * self.params.app_specific_weight
        # P6: IP colocation (squared surplus above threshold)
        score += self.ip_colocation_factor(peer) * self.params.ip_colocation_factor_weight
        # P7: behavioural penalty excess (squared)
        if pstats.behaviour_penalty > self.params.behaviour_penalty_threshold:
            excess = pstats.behaviour_penalty - self.params.behaviour_penalty_threshold
            score += excess * excess * self.params.behaviour_penalty_weight
        return score

    def ip_colocation_factor(self, peer: str) -> float:
        pstats = self.peer_stats.get(peer)
        if pstats is None:
            return 0.0
        result = 0.0
        for ip in pstats.ips:
            if self._whitelist_nets:
                whitelisted = pstats.ip_whitelist.get(ip)
                if whitelisted is None:
                    try:
                        addr = ipaddress.ip_address(ip)
                        whitelisted = any(addr in net for net in self._whitelist_nets)
                    except ValueError:
                        whitelisted = False
                    pstats.ip_whitelist[ip] = whitelisted
                if whitelisted:
                    continue
            peers_in_ip = len(self.peer_ips.get(ip, ()))
            if peers_in_ip > self.params.ip_colocation_factor_threshold:
                surplus = float(peers_in_ip - self.params.ip_colocation_factor_threshold)
                result += surplus * surplus
        return result

    def add_penalty(self, peer: str, count: int) -> None:
        """P7 behavioural penalty, applied by the router (score.go:389-403)."""
        pstats = self.peer_stats.get(peer)
        if pstats is not None:
            pstats.behaviour_penalty += float(count)

    # -- periodic maintenance (score.go:408-445); the node scheduler calls
    # refresh_scores every DecayInterval and refresh_ips/gc every minute --

    def refresh_scores(self) -> None:
        """Decay + retention purge (score.go:504-565)."""
        now = self._now()
        to_delete = []
        for peer, pstats in self.peer_stats.items():
            if not pstats.connected:
                if now > pstats.expire:
                    to_delete.append(peer)
                continue  # retained scores don't decay
            for topic, ts in pstats.topics.items():
                tp = self.params.topics.get(topic)
                if tp is None:
                    continue
                ts.first_message_deliveries *= tp.first_message_deliveries_decay
                if ts.first_message_deliveries < self.params.decay_to_zero:
                    ts.first_message_deliveries = 0.0
                ts.mesh_message_deliveries *= tp.mesh_message_deliveries_decay
                if ts.mesh_message_deliveries < self.params.decay_to_zero:
                    ts.mesh_message_deliveries = 0.0
                ts.mesh_failure_penalty *= tp.mesh_failure_penalty_decay
                if ts.mesh_failure_penalty < self.params.decay_to_zero:
                    ts.mesh_failure_penalty = 0.0
                ts.invalid_message_deliveries *= tp.invalid_message_deliveries_decay
                if ts.invalid_message_deliveries < self.params.decay_to_zero:
                    ts.invalid_message_deliveries = 0.0
                if ts.in_mesh:
                    ts.mesh_time = now - ts.graft_time
                    if ts.mesh_time > tp.mesh_message_deliveries_activation:
                        ts.mesh_message_deliveries_active = True
            pstats.behaviour_penalty *= self.params.behaviour_penalty_decay
            if pstats.behaviour_penalty < self.params.decay_to_zero:
                pstats.behaviour_penalty = 0.0
        for peer in to_delete:
            pstats = self.peer_stats.pop(peer)
            self._remove_ips(peer, pstats.ips)

    def refresh_ips(self) -> None:
        """Re-resolve IPs of connected peers (score.go:567-585)."""
        for peer, pstats in self.peer_stats.items():
            if pstats.connected:
                ips = list(self._get_ips(peer))
                self._set_ips(peer, ips, pstats.ips)
                pstats.ips = ips

    def gc_delivery_records(self) -> None:
        self.deliveries.gc()

    def inspect_scores(self) -> None:
        """Dump tracked scores into the inspector(s) (score.go:446-460)."""
        if self.inspect is not None:
            self.inspect({p: self.score(p) for p in self.peer_stats})
        if self.inspect_ex is not None:
            self.inspect_ex(self.dump_snapshots())

    def dump_snapshots(self) -> dict[str, PeerScoreSnapshot]:
        """Extended per-peer decomposition (score.go:462-500
        inspectScoresExtended): raw per-topic counters, raw app-specific
        score and IP-colocation factor (unweighted, as the reference dumps
        them), and the behaviour-penalty counter. TimeInMesh reports the
        stored mesh_time, refreshed each decay pass, and only for peers
        currently in the mesh — exactly the reference's `if ts.inMesh`."""
        out: dict[str, PeerScoreSnapshot] = {}
        for p, pstats in self.peer_stats.items():
            topics: dict[str, TopicScoreSnapshot] = {}
            for topic, ts in pstats.topics.items():
                tss = TopicScoreSnapshot(
                    first_message_deliveries=ts.first_message_deliveries,
                    mesh_message_deliveries=ts.mesh_message_deliveries,
                    invalid_message_deliveries=ts.invalid_message_deliveries)
                if ts.in_mesh:
                    tss.time_in_mesh = ts.mesh_time
                topics[topic] = tss
            out[p] = PeerScoreSnapshot(
                score=self.score(p),
                topics=topics,
                app_specific_score=self.params.app_specific_score(p),
                ip_colocation_factor=self.ip_colocation_factor(p),
                behaviour_penalty=pstats.behaviour_penalty)
        return out

    # -- RawTracer hooks (score.go:594-838) --

    def add_peer(self, peer: str, proto: str) -> None:
        pstats = self.peer_stats.setdefault(peer, _PeerStats())
        pstats.connected = True
        ips = list(self._get_ips(peer))
        self._set_ips(peer, ips, pstats.ips)
        pstats.ips = ips

    def remove_peer(self, peer: str) -> None:
        pstats = self.peer_stats.get(peer)
        if pstats is None:
            return
        # only retain non-positive scores, to dissuade score-reset attacks
        if self.score(peer) > 0:
            self._remove_ips(peer, pstats.ips)
            del self.peer_stats[peer]
            return
        for topic, ts in pstats.topics.items():
            ts.first_message_deliveries = 0.0
            threshold = self.params.topics[topic].mesh_message_deliveries_threshold
            if ts.in_mesh and ts.mesh_message_deliveries_active \
                    and ts.mesh_message_deliveries < threshold:
                deficit = threshold - ts.mesh_message_deliveries
                ts.mesh_failure_penalty += deficit * deficit
            ts.in_mesh = False
        pstats.connected = False
        pstats.expire = self._now() + self.params.retain_score

    def graft(self, peer: str, topic: str) -> None:
        pstats = self.peer_stats.get(peer)
        if pstats is None:
            return
        ts = pstats.get_topic_stats(topic, self.params)
        if ts is None:
            return
        ts.in_mesh = True
        ts.graft_time = self._now()
        ts.mesh_time = 0.0
        ts.mesh_message_deliveries_active = False

    def prune(self, peer: str, topic: str) -> None:
        pstats = self.peer_stats.get(peer)
        if pstats is None:
            return
        ts = pstats.get_topic_stats(topic, self.params)
        if ts is None:
            return
        threshold = self.params.topics[topic].mesh_message_deliveries_threshold
        if ts.mesh_message_deliveries_active and ts.mesh_message_deliveries < threshold:
            deficit = threshold - ts.mesh_message_deliveries
            ts.mesh_failure_penalty += deficit * deficit
        ts.in_mesh = False

    def validate_message(self, msg: Message) -> None:
        # create the record early for an accurate first-seen time (score.go:693-700)
        self.deliveries.get_record(self.id_gen.id(msg))

    def deliver_message(self, msg: Message) -> None:
        self._mark_first_message_delivery(msg.received_from, msg)
        drec = self.deliveries.get_record(self.id_gen.id(msg))
        if drec.status != DELIVERY_UNKNOWN:
            return
        drec.status = DELIVERY_VALID
        drec.validated = self._now()
        for p in drec.peers or ():
            if p != msg.received_from:
                self._mark_duplicate_message_delivery(p, msg, None)

    def reject_message(self, msg: Message, reason: str) -> None:
        if reason in (ev.REJECT_MISSING_SIGNATURE, ev.REJECT_INVALID_SIGNATURE,
                      ev.REJECT_UNEXPECTED_SIGNATURE, ev.REJECT_UNEXPECTED_AUTH_INFO,
                      ev.REJECT_SELF_ORIGIN):
            # no delivery tracking, but the forwarder is clearly misbehaving
            self._mark_invalid_message_delivery(msg.received_from, msg)
            return
        if reason in (ev.REJECT_BLACKLISTED_PEER, ev.REJECT_BLACKLISTED_SOURCE,
                      ev.REJECT_VALIDATION_QUEUE_FULL):
            return
        drec = self.deliveries.get_record(self.id_gen.id(msg))
        if drec.status != DELIVERY_UNKNOWN:
            return
        if reason == ev.REJECT_VALIDATION_THROTTLED:
            drec.status = DELIVERY_THROTTLED
            drec.peers = None
            return
        if reason == ev.REJECT_VALIDATION_IGNORED:
            drec.status = DELIVERY_IGNORED
            drec.peers = None
            return
        drec.status = DELIVERY_INVALID
        self._mark_invalid_message_delivery(msg.received_from, msg)
        for p in drec.peers or ():
            self._mark_invalid_message_delivery(p, msg)
        drec.peers = None

    def duplicate_message(self, msg: Message) -> None:
        drec = self.deliveries.get_record(self.id_gen.id(msg))
        if drec.peers is not None and msg.received_from in drec.peers:
            return  # already seen this duplicate
        if drec.status == DELIVERY_UNKNOWN:
            assert drec.peers is not None
            drec.peers.add(msg.received_from)
        elif drec.status == DELIVERY_VALID:
            assert drec.peers is not None
            drec.peers.add(msg.received_from)
            self._mark_duplicate_message_delivery(msg.received_from, msg, drec.validated)
        elif drec.status == DELIVERY_INVALID:
            self._mark_invalid_message_delivery(msg.received_from, msg)
        # throttled/ignored: do nothing

    # -- delivery marking (score.go:899-981) --

    def _mark_invalid_message_delivery(self, peer: str | None, msg: Message) -> None:
        pstats = self.peer_stats.get(peer)  # type: ignore[arg-type]
        if pstats is None:
            return
        ts = pstats.get_topic_stats(msg.topic, self.params)
        if ts is None:
            return
        ts.invalid_message_deliveries += 1.0

    def _mark_first_message_delivery(self, peer: str | None, msg: Message) -> None:
        pstats = self.peer_stats.get(peer)  # type: ignore[arg-type]
        if pstats is None:
            return
        ts = pstats.get_topic_stats(msg.topic, self.params)
        if ts is None:
            return
        tp = self.params.topics[msg.topic]
        ts.first_message_deliveries = min(
            ts.first_message_deliveries + 1.0, tp.first_message_deliveries_cap)
        if ts.in_mesh:
            ts.mesh_message_deliveries = min(
                ts.mesh_message_deliveries + 1.0, tp.mesh_message_deliveries_cap)

    def _mark_duplicate_message_delivery(self, peer: str | None, msg: Message,
                                         validated: float | None) -> None:
        pstats = self.peer_stats.get(peer)  # type: ignore[arg-type]
        if pstats is None:
            return
        ts = pstats.get_topic_stats(msg.topic, self.params)
        if ts is None or not ts.in_mesh:
            return
        tp = self.params.topics[msg.topic]
        # validated=None means delivery during validation: always in-window
        if validated is not None and \
                self._now() - validated > tp.mesh_message_deliveries_window:
            return
        ts.mesh_message_deliveries = min(
            ts.mesh_message_deliveries + 1.0, tp.mesh_message_deliveries_cap)

    # -- topic param swap with counter recapping (score.go:196-241) --

    def set_topic_score_params(self, topic: str, p: TopicScoreParams) -> None:
        old = self.params.topics.get(topic)
        self.params.topics[topic] = p
        if old is None:
            return
        recap = (p.first_message_deliveries_cap < old.first_message_deliveries_cap
                 or p.mesh_message_deliveries_cap < old.mesh_message_deliveries_cap)
        if not recap:
            return
        for pstats in self.peer_stats.values():
            ts = pstats.topics.get(topic)
            if ts is None:
                continue
            ts.first_message_deliveries = min(
                ts.first_message_deliveries, p.first_message_deliveries_cap)
            ts.mesh_message_deliveries = min(
                ts.mesh_message_deliveries, p.mesh_message_deliveries_cap)

    # -- IP tracking (score.go:1031-1081) --

    def _set_ips(self, peer: str, newips: list[str], oldips: list[str]) -> None:
        for ip in newips:
            if ip not in oldips:
                self.peer_ips.setdefault(ip, set()).add(peer)
        for ip in oldips:
            if ip not in newips:
                peers = self.peer_ips.get(ip)
                if peers is not None:
                    peers.discard(peer)
                    if not peers:
                        del self.peer_ips[ip]

    def _remove_ips(self, peer: str, ips: list[str]) -> None:
        for ip in ips:
            peers = self.peer_ips.get(ip)
            if peers is not None:
                peers.discard(peer)
                if not peers:
                    del self.peer_ips[ip]
