"""Protocol feature negotiation (gossipsub_feat.go).

Feature tests keyed by protocol ID: Mesh (v1.0 + v1.1), PX (v1.1 only).
"""

from __future__ import annotations

import enum
from typing import Callable

GOSSIPSUB_ID_V10 = "/meshsub/1.0.0"
GOSSIPSUB_ID_V11 = "/meshsub/1.1.0"


class GossipSubFeature(enum.Enum):
    MESH = 1  # GRAFT/PRUNE control (gossipsub_feat.go:14-20)
    PX = 2    # peer exchange on prune (v1.1 only)


def default_features(feat: GossipSubFeature, proto: str) -> bool:
    """gossipsub_feat.go:24-36."""
    if feat == GossipSubFeature.MESH:
        return proto in (GOSSIPSUB_ID_V10, GOSSIPSUB_ID_V11)
    if feat == GossipSubFeature.PX:
        return proto == GOSSIPSUB_ID_V11
    return False


GossipSubFeatureTest = Callable[[GossipSubFeature, str], bool]
