"""Wedge-proof default-platform probing, shared by bench.py and
__graft_entry__.py.

The remote-TPU ("axon") plugin in this environment can wedge backend
initialization so hard that any in-process ``jax.devices()`` or jit call
blocks forever — and jax initializes every registered backend together, so
probe ordering cannot dodge it. The only safe probe is a bounded-timeout
subprocess; the only safe fallback is a child process whose environment
disables the plugin and forces the virtual CPU mesh.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys


def probe_default_platform(timeout: int | None = None) -> tuple[bool, int]:
    """(alive, n_devices) of the DEFAULT jax backend, measured in a
    bounded-timeout subprocess so a wedged platform plugin costs a timeout,
    not a hang."""
    alive, n, _ = probe_default_platform_info(timeout)
    return alive, n


def probe_default_platform_info(
        timeout: int | None = None) -> tuple[bool, int, str]:
    """Like :func:`probe_default_platform`, but also reports the platform
    kind of device 0 ("tpu"/"cpu"/...), so a watcher can distinguish a live
    tunnel from a healthy-but-CPU default backend. Returns
    ``(alive, n_devices, platform)`` with platform "" when dead."""
    # default 120s: a healthy tunnel answers in ~10-20s (tiny compile +
    # device list); a wedged one burns the whole budget before the CPU
    # fallback, so the margin is wall-clock the driver pays on every entry
    timeout = timeout if timeout is not None else int(
        os.environ.get("GRAFT_PROBE_TIMEOUT",
                       os.environ.get("BENCH_PROBE_TIMEOUT", 120)))
    try:
        res = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "assert float(jnp.ones((8, 8)).sum()) == 64.0; "
             "d = jax.devices(); "
             "print('NDEV', len(d), d[0].platform)"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, 0, ""
    if res.returncode != 0:
        return False, 0, ""
    for line in res.stdout.splitlines():
        if line.startswith("NDEV "):
            parts = line.split()
            return True, int(parts[1]), parts[2]
    return False, 0, ""


def cpu_mesh_env(env: dict, n_devices: int | None = None) -> dict:
    """A child env forcing the CPU platform with the axon TPU plugin
    disabled (it can wedge backend init even under JAX_PLATFORMS=cpu unless
    its pool address list is cleared). With ``n_devices``, also force an
    n-device virtual CPU mesh."""
    env = dict(env)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    if n_devices is not None:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={n_devices}")
    return env


def forced_cpu_device_count(env: dict | None = None) -> int:
    """The virtual CPU device count a JAX_PLATFORMS=cpu process will see,
    parsed from XLA_FLAGS (last flag wins, matching XLA), default 1."""
    env = env if env is not None else os.environ
    hits = re.findall(r"--xla_force_host_platform_device_count=(\d+)",
                      env.get("XLA_FLAGS", ""))
    return int(hits[-1]) if hits else 1
