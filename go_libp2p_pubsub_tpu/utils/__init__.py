from .backoff import Backoff, MaxBackoffAttemptsError  # noqa: F401
from .blacklist import Blacklist, MapBlacklist, TimeCachedBlacklist  # noqa: F401
from .mcache import MessageCache  # noqa: F401
from .midgen import MsgIdGenerator, default_msg_id_fn  # noqa: F401
from .subscription_filter import (  # noqa: F401
    AllowlistSubscriptionFilter,
    LimitSubscriptionFilter,
    RegexpSubscriptionFilter,
    SubscriptionFilter,
    TooManySubscriptionsError,
    filter_subscriptions,
)
from .timecache import SWEEP_INTERVAL, Strategy, TimeCache  # noqa: F401
