"""Seen-message TTL caches on the virtual clock.

Mirrors timecache/ (time_cache.go:22-53, first_seen_cache.go,
last_seen_cache.go, util.go). Two fidelity-relevant details kept:

- ``has`` does NOT itself expire entries; expiry happens in ``sweep`` which
  the runtime calls every ``SWEEP_INTERVAL`` (util.go:9,26-35). An entry can
  thus remain visible slightly past its TTL, exactly like the reference.
- LastSeen ``has``/``add`` refresh the expiry; FirstSeen never refreshes.
"""

from __future__ import annotations

import enum
from typing import Callable

from ..core.clock import MINUTE

SWEEP_INTERVAL = 1 * MINUTE


class Strategy(enum.Enum):
    FIRST_SEEN = 0
    LAST_SEEN = 1


class TimeCache:
    """TTL dedup cache. ``now`` is a callable returning virtual time."""

    def __init__(self, ttl: float, now: Callable[[], float], strategy: Strategy = Strategy.FIRST_SEEN):
        self._m: dict[str, float] = {}
        self._ttl = ttl
        self._now = now
        self._strategy = strategy

    def add(self, key: str) -> bool:
        """Insert; returns True if newly added (first_seen_cache.go:46-56)."""
        present = key in self._m
        if self._strategy is Strategy.FIRST_SEEN:
            if present:
                return False
            self._m[key] = self._now() + self._ttl
            return True
        # last-seen: always refresh (last_seen_cache.go:40-47)
        self._m[key] = self._now() + self._ttl
        return not present

    def has(self, key: str) -> bool:
        present = key in self._m
        if present and self._strategy is Strategy.LAST_SEEN:
            self._m[key] = self._now() + self._ttl
        return present

    def sweep(self) -> None:
        """Drop expired entries (util.go:26-35); call every SWEEP_INTERVAL."""
        now = self._now()
        expired = [k for k, exp in self._m.items() if exp < now]
        for k in expired:
            del self._m[k]

    def done(self) -> None:
        self._m.clear()

    def __len__(self) -> int:
        return len(self._m)
