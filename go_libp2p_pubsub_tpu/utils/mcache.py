"""Sliding-window message cache for gossip (mcache.go).

Window semantics: ``put`` appends to slot 0; ``shift`` (called once per
heartbeat, gossipsub.go:1605) evicts the oldest slot and rotates.
``get_gossip_ids`` only reads the first ``gossip`` slots (mcache.go:82-92).
Per-peer IWANT retransmission counters live here (mcache.go:66-80) and feed
the GossipRetransmission cutoff (gossipsub.go:719-731).
"""

from __future__ import annotations

from typing import Callable

from ..core.types import Message
from .midgen import default_msg_id_fn


class MessageCache:
    def __init__(self, gossip: int, history: int, msg_id: Callable[[Message], str] | None = None):
        if gossip > history:
            raise ValueError(
                f"invalid parameters for message cache; gossip slots ({gossip}) "
                f"cannot be larger than history slots ({history})")
        self._msgs: dict[str, Message] = {}
        self._peertx: dict[str, dict[str, int]] = {}
        self._history: list[list[tuple[str, str]]] = [[] for _ in range(history)]
        self._gossip = gossip
        self._msg_id = msg_id or default_msg_id_fn

    def set_msg_id_fn(self, fn: Callable[[Message], str]) -> None:
        self._msg_id = fn

    def put(self, msg: Message) -> None:
        mid = self._msg_id(msg)
        self._msgs[mid] = msg
        self._history[0].append((mid, msg.topic))

    def get(self, mid: str) -> Message | None:
        return self._msgs.get(mid)

    def get_for_peer(self, mid: str, peer: str) -> tuple[Message | None, int]:
        """Return (message, transmission count incl. this request)."""
        m = self._msgs.get(mid)
        if m is None:
            return None, 0
        tx = self._peertx.setdefault(mid, {})
        tx[peer] = tx.get(peer, 0) + 1
        return m, tx[peer]

    def get_gossip_ids(self, topic: str) -> list[str]:
        return [mid for entries in self._history[: self._gossip]
                for (mid, t) in entries if t == topic]

    def shift(self) -> None:
        for mid, _ in self._history[-1]:
            self._msgs.pop(mid, None)
            self._peertx.pop(mid, None)
        self._history[1:] = self._history[:-1]
        self._history[0] = []
