"""Message-ID generation with per-topic overrides (midgen.go:11-52).

The default ID is author + seqno (pubsub.go:1107-1110). IDs are Python
strings in the functional core; the batched engine hashes them to fixed-width
uint64 (ops/hashing) — SURVEY.md §7 "String message-IDs".
"""

from __future__ import annotations

from typing import Callable

from ..core.types import Message

MsgIdFunction = Callable[[Message], str]


def default_msg_id_fn(msg: Message) -> str:
    """Concatenate author and sequence number (pubsub.go:1107-1110)."""
    return (msg.from_peer or "") + (msg.seqno or b"").decode("latin-1")


class MsgIdGenerator:
    def __init__(self):
        self.default: MsgIdFunction = default_msg_id_fn
        self._topic_gens: dict[str, MsgIdFunction] = {}

    def set(self, topic: str, gen: MsgIdFunction) -> None:
        self._topic_gens[topic] = gen

    def id(self, msg: Message) -> str:
        """Compute and cache the id on the message (midgen.go:33-40)."""
        if msg._id is None:
            msg._id = self.raw_id(msg)
        return msg._id

    def raw_id(self, msg: Message) -> str:
        gen = self._topic_gens.get(msg.topic, self.default)
        return gen(msg)
