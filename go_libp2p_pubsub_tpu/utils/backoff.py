"""Dead-peer reconnect exponential backoff (backoff.go:13-107).

Schedule: attempt 1 fires immediately; then 100ms; then doubling plus
0-99ms jitter, capped at 10s; after ``max_attempts`` updates the peer is
ejected with an error. Entries expire after ``TIME_TO_LIVE`` since last try
(both lazily in ``update_and_get`` and via ``cleanup``).

Jitter draws from an injected ``random.Random`` so runs are reproducible —
the deterministic-simulation replacement for backoff.go:47's global seed.
"""

from __future__ import annotations

import random
from typing import Callable

from ..core.clock import MILLISECOND, MINUTE, SECOND

MIN_BACKOFF_DELAY = 100 * MILLISECOND
MAX_BACKOFF_DELAY = 10 * SECOND
TIME_TO_LIVE = 10 * MINUTE
BACKOFF_CLEANUP_INTERVAL = 1 * MINUTE
BACKOFF_MULTIPLIER = 2
MAX_BACKOFF_JITTER_COFF = 100
MAX_BACKOFF_ATTEMPTS = 4


class MaxBackoffAttemptsError(RuntimeError):
    pass


class _History:
    __slots__ = ("duration", "last_tried", "attempts")

    def __init__(self):
        self.duration = 0.0
        self.last_tried = 0.0
        self.attempts = 0


class Backoff:
    def __init__(self, now: Callable[[], float], rng: random.Random,
                 max_attempts: int = MAX_BACKOFF_ATTEMPTS):
        self._now = now
        self._info: dict[str, _History] = {}
        self._max_attempts = max_attempts
        self._rng = rng

    def update_and_get(self, peer: str) -> float:
        """Next delay for ``peer`` (backoff.go:52-82). Raises after max attempts."""
        now = self._now()
        h = self._info.get(peer)
        if h is None or now - h.last_tried > TIME_TO_LIVE:
            h = _History()  # first request goes immediately
        elif h.attempts >= self._max_attempts:
            raise MaxBackoffAttemptsError(
                f"peer {peer} has reached its maximum backoff attempts")
        elif h.duration < MIN_BACKOFF_DELAY:
            h.duration = MIN_BACKOFF_DELAY
        elif h.duration < MAX_BACKOFF_DELAY:
            jitter = self._rng.randrange(MAX_BACKOFF_JITTER_COFF)
            h.duration = BACKOFF_MULTIPLIER * h.duration + jitter * MILLISECOND
            if h.duration > MAX_BACKOFF_DELAY or h.duration < 0:
                h.duration = MAX_BACKOFF_DELAY

        h.attempts += 1
        h.last_tried = now
        self._info[peer] = h
        return h.duration

    def cleanup(self) -> None:
        """Expire stale entries (backoff.go:84-93); call every BACKOFF_CLEANUP_INTERVAL."""
        now = self._now()
        stale = [p for p, h in self._info.items() if now - h.last_tried > TIME_TO_LIVE]
        for p in stale:
            del self._info[p]

    def __len__(self) -> int:
        return len(self._info)
