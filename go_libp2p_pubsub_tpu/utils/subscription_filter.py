"""Subscription filters (subscription_filter.go:24-149).

Gate local joins and incoming subscription announcements. Unlike the Go
version's map-iteration order, ``filter_subscriptions`` returns results in
first-seen order — deterministic by construction.
"""

from __future__ import annotations

import re
from typing import Callable, Protocol

from ..core.types import SubOpts


class TooManySubscriptionsError(ValueError):
    pass


class SubscriptionFilter(Protocol):
    def can_subscribe(self, topic: str) -> bool: ...
    def filter_incoming_subscriptions(
        self, from_peer: str, subs: list[SubOpts]) -> list[SubOpts]: ...


def filter_subscriptions(subs: list[SubOpts], allow: Callable[[str], bool]) -> list[SubOpts]:
    """Filter + dedup; contradictory sub/unsub pairs for one topic cancel out
    (subscription_filter.go:101-131)."""
    accept: dict[str, SubOpts] = {}
    for sub in subs:
        topic = sub.topicid
        if not allow(topic):
            continue
        other = accept.get(topic)
        if other is not None:
            if sub.subscribe != other.subscribe:
                # contradictory pair cancels out; a later announcement for the
                # same topic may re-enter
                del accept[topic]
        else:
            accept[topic] = sub
    return list(accept.values())


class AllowlistSubscriptionFilter:
    def __init__(self, *topics: str):
        self._allow = set(topics)

    def can_subscribe(self, topic: str) -> bool:
        return topic in self._allow

    def filter_incoming_subscriptions(self, from_peer: str, subs: list[SubOpts]) -> list[SubOpts]:
        return filter_subscriptions(subs, self.can_subscribe)


class RegexpSubscriptionFilter:
    def __init__(self, pattern: str | re.Pattern):
        self._rx = re.compile(pattern) if isinstance(pattern, str) else pattern

    def can_subscribe(self, topic: str) -> bool:
        return self._rx.search(topic) is not None

    def filter_incoming_subscriptions(self, from_peer: str, subs: list[SubOpts]) -> list[SubOpts]:
        return filter_subscriptions(subs, self.can_subscribe)


class LimitSubscriptionFilter:
    """Hard cap on subscriptions per RPC (subscription_filter.go:133-149)."""

    def __init__(self, inner: SubscriptionFilter, limit: int):
        self._inner = inner
        self._limit = limit

    def can_subscribe(self, topic: str) -> bool:
        return self._inner.can_subscribe(topic)

    def filter_incoming_subscriptions(self, from_peer: str, subs: list[SubOpts]) -> list[SubOpts]:
        if len(subs) > self._limit:
            raise TooManySubscriptionsError("too many subscriptions")
        return self._inner.filter_incoming_subscriptions(from_peer, subs)
