"""Peer blacklists (blacklist.go:12-58): set-backed and TTL-backed."""

from __future__ import annotations

from typing import Callable, Protocol

from .timecache import TimeCache


class Blacklist(Protocol):
    def add(self, peer: str) -> bool: ...
    def contains(self, peer: str) -> bool: ...


class MapBlacklist:
    def __init__(self):
        self._s: set[str] = set()

    def add(self, peer: str) -> bool:
        self._s.add(peer)
        return True

    def contains(self, peer: str) -> bool:
        return peer in self._s


class TimeCachedBlacklist:
    """Blacklist whose entries expire after ``expiry`` (blacklist.go:36-58)."""

    def __init__(self, expiry: float, now: Callable[[], float]):
        self._tc = TimeCache(expiry, now)

    def add(self, peer: str) -> bool:
        if self._tc.has(peer):
            return False
        self._tc.add(peer)
        return True

    def contains(self, peer: str) -> bool:
        return self._tc.has(peer)

    def sweep(self) -> None:
        self._tc.sweep()
