"""Distributed resilience plane: rank liveness, coordinated abort, chaos.

PR 8 documented the multihost failure contract as "relaunch-all-ranks +
checkpoint resume" and deferred building it: a rank-LOCAL retry/degrade
ladder cannot be rank-symmetric (one rank re-dispatching a degraded or
re-sized program while its peers sit in the original chunk's collectives
would deadlock or pair wrong collectives — sim/supervisor.py
``handle_failure``), so until now any rank error killed the whole window
and a DEAD rank could leave its peers blocked forever inside a gloo/ICI
collective. This module is the rank-side half of the recovery plane
(``scripts/mh_supervisor.py`` is the group-owning driver):

- :class:`RankLiveness` — each rank writes an atomic heartbeat file
  ``hb_rank<r>.json`` (rank, chunk, tick, wall, pid) into a SHARED run
  directory: a background beater thread refreshes the wall stamp every
  ``beat_interval_s`` (process alive), and the supervisor's chunk loop
  stamps progress (``beat``) as chunks confirm. ``check()`` — called by
  ``supervised_run`` at the pre-dispatch safe point, BEFORE the next
  chunk's collectives — raises :class:`PeerDeadError` naming any peer
  whose heartbeat went stale, so the rank aborts its window cleanly at a
  chunk boundary (through the supervisor's multi-process fail-fast crash
  path, which writes the crash dump and journal marker). For the rank
  that is already BLOCKED inside a collective when its peer dies, the
  beater thread doubles as a watchdog: ``abort_grace_s`` after first
  sighting a dead peer it hard-exits the process with
  :data:`EXIT_PEER_DEAD` — no rank ever blocks forever on a dead peer;
  the relaunch supervisor observes the exit and restarts the group from
  the last drained checkpoint.
- :class:`ChaosPlan` — the ``GRAFT_CHAOS`` fault-injection knob:
  deterministic ``kill@RANK:TICK`` (the rank SIGKILLs itself at the
  first chunk whose start tick reaches TICK) and ``stall@RANK:TICK:SECS``
  (the rank sleeps SECS inside one chunk attempt, tripping the chunk
  deadline) specs, comma-separated. Each spec fires ONCE per run
  directory — a marker file lands (fsync'd) BEFORE the fault, so a
  supervised relaunch resumes past the chaos instead of dying to it
  again. Wired as ``supervised_run``'s ``_chunk_hook`` by
  ``scripts/run_multihost.py`` and exercised in every banked TPU window
  (``tpu_recheck.sh mh_resilience`` step, ``supervisor_smoke.py``).

Deliberately jax-free: liveness must work BEFORE ``jax.distributed``
initializes (a rank wedged in the coordinator handshake still beats) and
keep working after a peer's backend died.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

# watchdog hard-exit code: "my peer died while I was blocked in a
# collective" — distinct from a crash (1) and from SIGKILL (-9) so the
# relaunch supervisor's journal names the abort cause
EXIT_PEER_DEAD = 43

# verdict-abort exit code: a live behavior contract FAILED under
# verdict_policy="abort" and the run tore down cleanly at a chunk
# boundary (sim/supervisor.VerdictAbort). TERMINAL for the relaunch
# supervisor: the simulated network broke its contract — relaunching
# would replay the same trajectory into the same breach
EXIT_VERDICT_ABORT = 44


class PeerDeadError(RuntimeError):
    """A peer rank's heartbeat went stale/missing: this rank must abort
    its window at the next chunk boundary (the multi-process fail-fast
    crash path) instead of entering collectives the dead peer will never
    join."""


def heartbeat_path(run_dir: str, rank: int) -> str:
    return os.path.join(run_dir, f"hb_rank{rank}.json")


class RankLiveness:
    """Per-rank heartbeat writer + dead-peer detector (module docstring).

    ``start()`` launches the beater/watchdog daemon thread; ``beat()``
    stamps progress from the supervisor loop; ``check()`` raises
    :class:`PeerDeadError` on a stale peer; ``finish()`` marks this
    rank's heartbeat done (a finished rank is never read as dead);
    ``stop()`` ends the thread. ``hard_exit`` is injectable for tests —
    the real one is ``os._exit`` (atexit/finally must NOT run: the
    process is abandoning in-flight collectives, and the relaunch
    supervisor owns cleanup)."""

    def __init__(self, run_dir: str, rank: int, num_processes: int, *,
                 peer_timeout_s: float = 30.0,
                 beat_interval_s: float = 1.0,
                 startup_grace_s: float = 120.0,
                 abort_grace_s: float = 15.0,
                 hard_exit=os._exit):
        self.run_dir = run_dir
        self.rank = int(rank)
        self.num_processes = int(num_processes)
        self.peer_timeout_s = float(peer_timeout_s)
        self.beat_interval_s = float(beat_interval_s)
        self.startup_grace_s = float(startup_grace_s)
        self.abort_grace_s = float(abort_grace_s)
        self._hard_exit = hard_exit
        self._progress = {"chunk": -1, "tick": -1}
        self._done = False
        self._born = time.monotonic()
        self._dead_since: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        os.makedirs(run_dir, exist_ok=True)

    @classmethod
    def from_env(cls, run_dir: str, rank: int,
                 num_processes: int) -> "RankLiveness":
        """Knobs from the ``GRAFT_MH_*`` env family the relaunch
        supervisor hands every rank (tests shrink the timeouts)."""
        def _f(name, default):
            v = os.environ.get(name)
            return float(v) if v else default
        return cls(run_dir, rank, num_processes,
                   peer_timeout_s=_f("GRAFT_MH_PEER_TIMEOUT_S", 30.0),
                   beat_interval_s=_f("GRAFT_MH_BEAT_INTERVAL_S", 1.0),
                   startup_grace_s=_f("GRAFT_MH_STARTUP_GRACE_S", 120.0),
                   abort_grace_s=_f("GRAFT_MH_ABORT_GRACE_S", 15.0))

    # ---- heartbeat writes -------------------------------------------------

    def _write(self) -> None:
        with self._lock:
            rec = {"rank": self.rank, "pid": os.getpid(),
                   "wall": time.time(), "done": self._done,
                   **self._progress}
        path = heartbeat_path(self.run_dir, self.rank)
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, path)       # atomic: peers never read a torn beat
        except OSError:
            pass    # a full/slow shared fs must not kill the rank itself

    def beat(self, tick: int | None = None, chunk: int | None = None) -> None:
        """Stamp progress (supervisor chunk loop) and refresh the wall."""
        with self._lock:
            if tick is not None:
                self._progress["tick"] = int(tick)
            if chunk is not None:
                self._progress["chunk"] = int(chunk)
        self._write()

    def finish(self) -> None:
        """Mark this rank's heartbeat done: ranks exit together after the
        final gather, but a peer reading the file during teardown skew
        must never take a finished rank for a dead one."""
        with self._lock:
            self._done = True
        self._write()

    # ---- dead-peer detection ----------------------------------------------

    def dead_peers(self) -> list:
        """``[(rank, reason)]`` for every peer whose heartbeat is missing
        (past the startup grace) or stale (older than ``peer_timeout_s``
        and not marked done)."""
        now = time.time()
        up_for = time.monotonic() - self._born
        out = []
        for r in range(self.num_processes):
            if r == self.rank:
                continue
            path = heartbeat_path(self.run_dir, r)
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                if up_for > self.startup_grace_s:
                    out.append((r, f"no heartbeat file after "
                                   f"{up_for:.0f}s"))
                continue
            if d.get("done"):
                continue
            age = now - float(d.get("wall", 0.0))
            if age > self.peer_timeout_s:
                out.append((r, f"heartbeat {age:.1f}s stale "
                               f"(> {self.peer_timeout_s:g}s)"))
        return out

    def check(self) -> None:
        """Raise :class:`PeerDeadError` naming dead peers — the
        supervisor's pre-dispatch safe point calls this so the abort
        happens at a chunk boundary, never inside a collective."""
        dead = self.dead_peers()
        if dead:
            names = "; ".join(f"rank {r}: {why}" for r, why in dead)
            raise PeerDeadError(
                f"peer rank(s) dead ({names}) — aborting this window at a "
                "chunk boundary so no collective blocks on a dead peer; "
                "relaunch the group from the last checkpoint "
                "(scripts/mh_supervisor.py)")

    # ---- beater / watchdog thread -----------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.beat_interval_s):
            self._write()
            dead = self.dead_peers()
            if not dead:
                self._dead_since = None
                continue
            if self._dead_since is None:
                self._dead_since = time.monotonic()
                continue
            if time.monotonic() - self._dead_since > self.abort_grace_s \
                    and not self._stop.is_set():
                # the main thread had abort_grace_s to reach the clean
                # chunk-boundary abort; it is blocked in a collective the
                # dead peer will never join — hard-exit so the relaunch
                # supervisor can recover the group
                try:
                    names = ", ".join(str(r) for r, _why in dead)
                    print(f"[resilience] rank {self.rank}: peer rank(s) "
                          f"{names} dead and this rank is blocked; "
                          f"hard-exiting {EXIT_PEER_DEAD}", flush=True)
                except Exception:
                    pass
                self._hard_exit(EXIT_PEER_DEAD)
                return      # injectable hard_exit (tests) returns

    def start(self) -> "RankLiveness":
        self._write()       # first beat lands before any jax/backend touch
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=f"graft-hb-r{self.rank}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# GRAFT_CHAOS: deterministic kill/stall fault injection


class ChaosPlan:
    """Parsed ``GRAFT_CHAOS`` spec, bound to one rank and one run dir.

    Spec grammar (comma-separated)::

        kill@RANK:TICK          rank RANK SIGKILLs itself at the first
                                chunk attempt whose start tick >= TICK
        stall@RANK:TICK:SECS    rank RANK sleeps SECS inside that chunk
                                attempt (trips the chunk deadline)
        ingest_stall@TICK:SECS  the rank-0 command-plane reader pauses
                                SECS at the first boundary drain whose
                                chunk start >= TICK (the stalled-producer
                                watchdog trips → coast mode)
        ingest_kill@TICK        the reader stops for good (a SIGKILLed
                                producer that never comes back)
        verdict_kill@TICK       rank 0 SIGKILLs itself at the first chunk
                                boundary >= TICK that detected NEW
                                contract-verdict transitions — between
                                the breach and its journaled verdict
                                (the ISSUE 20 exactly-once drill: the
                                relaunch re-derives the verdict off the
                                checkpoint sidecar's monitor state and
                                journals it exactly once)

    Each spec fires ONCE per run directory: the marker file
    ``chaos_<action>_r<rank>_t<tick>.fired`` is written (fsync'd) BEFORE
    the fault, so the relaunched group resumes past the injected fault
    instead of dying to it forever. With ``run_dir=None`` the marker is
    in-memory (once per process). ``fire(info)`` is shaped as
    ``supervised_run``'s ``_chunk_hook``; the ``ingest_*`` family fires
    queue-side instead (``fire_ingest``, called by
    ``sim/commands.CommandQueue.frame_for`` — ingestion is rank 0's, so
    the specs pin to rank 0)."""

    def __init__(self, specs: list, rank: int, run_dir: str | None = None,
                 kill=None, sleep=time.sleep):
        mine = [s for s in specs if s["rank"] == int(rank)]
        self.ingest_specs = [s for s in mine
                             if s["action"].startswith("ingest_")]
        self.verdict_specs = [s for s in mine
                              if s["action"] == "verdict_kill"]
        self.specs = [s for s in mine
                      if not s["action"].startswith("ingest_")
                      and s["action"] != "verdict_kill"]
        self.rank = int(rank)
        self.run_dir = run_dir
        self._fired: set = set()
        self._kill = kill or (
            lambda: os.kill(os.getpid(), signal.SIGKILL))
        self._sleep = sleep

    @staticmethod
    def parse(text: str) -> list:
        """Parse a spec string; raises ``ValueError`` naming GRAFT_CHAOS
        on any malformed entry."""
        out = []
        for part in filter(None, (p.strip() for p in text.split(","))):
            try:
                action, rest = part.split("@", 1)
                fields = rest.split(":")
                if action == "kill" and len(fields) == 2:
                    out.append({"action": "kill", "rank": int(fields[0]),
                                "tick": int(fields[1]), "seconds": 0.0})
                    continue
                if action == "stall" and len(fields) == 3:
                    out.append({"action": "stall", "rank": int(fields[0]),
                                "tick": int(fields[1]),
                                "seconds": float(fields[2])})
                    continue
                # ingest chaos has no RANK field: the command-plane
                # reader lives on rank 0 by construction
                if action == "ingest_stall" and len(fields) == 2:
                    out.append({"action": "ingest_stall", "rank": 0,
                                "tick": int(fields[0]),
                                "seconds": float(fields[1])})
                    continue
                if action == "ingest_kill" and len(fields) == 1:
                    out.append({"action": "ingest_kill", "rank": 0,
                                "tick": int(fields[0]), "seconds": 0.0})
                    continue
                # verdict chaos pins to rank 0 like the ingest family:
                # the journaled verdict stream is rank 0's
                if action == "verdict_kill" and len(fields) == 1:
                    out.append({"action": "verdict_kill", "rank": 0,
                                "tick": int(fields[0]), "seconds": 0.0})
                    continue
            except ValueError as e:
                raise ValueError(
                    f"GRAFT_CHAOS entry {part!r}: {e} — expected "
                    "kill@RANK:TICK, stall@RANK:TICK:SECS, "
                    "ingest_stall@TICK:SECS, ingest_kill@TICK or "
                    "verdict_kill@TICK") from e
            raise ValueError(
                f"GRAFT_CHAOS entry {part!r}: expected kill@RANK:TICK, "
                "stall@RANK:TICK:SECS, ingest_stall@TICK:SECS, "
                "ingest_kill@TICK or verdict_kill@TICK")
        return out

    @classmethod
    def from_env(cls, rank: int,
                 run_dir: str | None = None) -> "ChaosPlan | None":
        text = os.environ.get("GRAFT_CHAOS", "").strip()
        if not text:
            return None
        return cls(cls.parse(text), rank, run_dir)

    def _marker(self, spec: dict) -> str:
        return (f"chaos_{spec['action']}_r{spec['rank']}"
                f"_t{spec['tick']}.fired")

    def _claim(self, spec: dict, info: dict) -> bool:
        """True iff this spec has not fired yet; the marker lands durably
        BEFORE the caller injects the fault (kill included)."""
        name = self._marker(spec)
        if name in self._fired:
            return False
        self._fired.add(name)
        if self.run_dir is None:
            return True
        path = os.path.join(self.run_dir, name)
        if os.path.exists(path):
            return False
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"rank": self.rank, "wall": time.time(),
                       "chunk_start": info.get("chunk_start")}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return True

    def fire(self, info: dict) -> None:
        """The ``_chunk_hook``: inject any armed fault whose tick this
        chunk attempt reached."""
        start = info.get("chunk_start")
        if start is None:
            return
        for spec in self.specs:
            if start < spec["tick"] or not self._claim(spec, info):
                continue
            if spec["action"] == "kill":
                self._kill()
            else:
                self._sleep(spec["seconds"])

    def fire_verdict(self, tick: int) -> None:
        """The verdict-plane fire point (``sim/supervisor.py``): called
        at a chunk boundary that detected NEW contract-verdict
        transitions, AFTER the fold and BEFORE their journal notes are
        submitted — the exact window the exactly-once scheme must
        survive. Same once-per-run-dir fsync'd-marker discipline."""
        for spec in self.verdict_specs:
            if tick < spec["tick"] \
                    or not self._claim(spec, {"chunk_start": tick}):
                continue
            self._kill()

    def fire_ingest(self, chunk_start: int, queue) -> None:
        """The command-plane fire point (``CommandQueue.frame_for``):
        same once-per-run-dir fsync'd-marker discipline as ``fire``, but
        the fault lands on the ingest reader thread — pause (the
        watchdog trips and the run coasts) or permanent stop."""
        for spec in self.ingest_specs:
            if chunk_start < spec["tick"] \
                    or not self._claim(spec, {"chunk_start": chunk_start}):
                continue
            if spec["action"] == "ingest_kill":
                queue.kill_reader()
            else:
                queue.pause_reader(spec["seconds"])
