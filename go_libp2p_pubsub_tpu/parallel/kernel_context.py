"""Trace-time mesh context for shard_map-wrapped Pallas kernels.

The engine's Pallas kernels (ops/permgather, ops/hopkernel) are opaque to
the SPMD partitioner: under a plain ``pjit`` over a device mesh it can only
satisfy them by all-gathering EVERY operand and running the full-size kernel
replicated on every device — full work × n_devices, the opposite of
scaling. The fix (ROUND4_NOTES.md sharded-path item) is to dispatch them
under ``jax.shard_map`` with explicit specs: the small packed lookup tables
(the [W, N] message windows / [N, WB] edge bit-tables — ≤ ~1 MB at the
100k-peer headline shape) replicate, which the partitioner realizes as one
cheap all-gather per call, and every receiver-indexed operand stays
sharded, so each device runs the kernel over its own peer rows only. This
is the TPU-native analogue of the reference's per-connection stream fan-out
(comm.go:44-191): the only cross-device traffic is the table everyone
reads.

``parallel.sharding.make_sharded_step`` enters :func:`kernel_mesh` while
tracing the sharded step; the kernel dispatch sites consult
:func:`current_kernel_mesh` at trace time and wrap themselves with
:func:`shard_kernel` when a mesh is active. Unsharded runs (context absent)
dispatch the kernels directly, exactly as before.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from typing import NamedTuple

import jax
from jax.sharding import Mesh, PartitionSpec as P

PEER = "__peer_axes__"          # spec placeholder for the sharded peer axis


class KernelMesh(NamedTuple):
    mesh: Mesh
    peer_axes: tuple            # mesh axis name(s) the peer dim shards over
    route: str = "replicated"   # sort-mode routing: "replicated" global
                                # sort | "halo" per-shard all_to_all
                                # (parallel/halo.py)
    capacity_factor: int = 4    # halo bucket capacity over the uniform mean
                                # (parallel/halo.py capacity rule)
    bucket_capacity: int = 0    # EXACT per-(src,dst) bucket capacity; 0 =
                                # derive from capacity_factor's uniform-
                                # degree rule. Set from halo.
                                # required_bucket_capacity for heavy-
                                # tailed underlays (degree-aware pricing:
                                # neither overflow nor over-allocation)
    overflow_notes: list = None # trace-time accumulator: halo overflow
                                # counts (outer-trace scalars) noted by
                                # route_*_halo, drained once per step by
                                # engine.step into SimState.halo_overflow


# a ContextVar, not a module global: the context is consulted at TRACE
# time, and a process tracing a sharded and an unsharded step from
# different threads (or an async retrace escaping the manager) must each
# see their own mesh decision (round-4 advisor finding)
_current: contextvars.ContextVar[KernelMesh | None] = \
    contextvars.ContextVar("kernel_mesh", default=None)


@contextmanager
def kernel_mesh(mesh: Mesh, peer_axes, route: str = "replicated",
                capacity_factor: int = 4, bucket_capacity: int = 0):
    """Activate shard_map kernel dispatch for code traced inside."""
    tok = _current.set(KernelMesh(mesh, tuple(peer_axes), route,
                                  capacity_factor, bucket_capacity, []))
    try:
        yield
    finally:
        _current.reset(tok)


def current_kernel_mesh() -> KernelMesh | None:
    return _current.get()


def note_halo_overflow(count) -> None:
    """Record a halo-route bucket-overflow count (an outer-trace scalar —
    shard_map has already psum'd it) for the current step to absorb."""
    ctx = _current.get()
    if ctx is not None and ctx.overflow_notes is not None:
        ctx.overflow_notes.append(count)


def drain_halo_overflow() -> list:
    """Take (and clear) the overflow counts noted since the last drain."""
    ctx = _current.get()
    if ctx is None or not ctx.overflow_notes:
        return []
    notes, ctx.overflow_notes[:] = list(ctx.overflow_notes), []
    return notes


def peer_shards() -> int:
    """Number of shards the peer axis splits over (1 when unsharded)."""
    ctx = _current.get()
    if ctx is None:
        return 1
    size = 1
    for ax in ctx.peer_axes:
        size *= ctx.mesh.shape[ax]
    return size


def local_rows(n: int) -> int:
    """Per-device peer-row count under the active context (n when absent)."""
    shards = peer_shards()
    if n % shards:
        raise ValueError(
            f"n_peers {n} does not divide the {shards}-shard peer axis")
    return n // shards


def _spec(dims) -> P:
    ctx = _current.get()
    return P(*[ctx.peer_axes if d is PEER else None for d in dims])


def shard_kernel(fn, in_specs, out_specs):
    """shard_map ``fn`` over the active mesh. ``in_specs``/``out_specs`` are
    per-array dim tuples using ``PEER`` for the sharded peer dimension and
    None for replicated dims (an all-``None`` tuple replicates the whole
    array — the table inputs). Must only be called with a context active."""
    ctx = _current.get()
    assert ctx is not None, "shard_kernel outside a kernel_mesh context"
    ins = tuple(_spec(s) for s in in_specs)
    outs = tuple(_spec(s) for s in out_specs)
    if len(outs) == 1:
        outs = outs[0]
    # check_vma off: pallas_call carries no varying-manual-axes rule, and
    # the specs above are exactly the partitioning the kernels are written
    # for (tables whole, rows local)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=ctx.mesh, in_specs=ins,
                             out_specs=outs, check_vma=False)
    # jax < 0.5: the API lives in jax.experimental and the replication
    # check is named check_rep — same semantics, off for the same reason
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=ctx.mesh, in_specs=ins, out_specs=outs,
                      check_rep=False)
