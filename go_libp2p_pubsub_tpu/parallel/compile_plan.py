"""Centralized compile plan: ONE module owns shardings, donation, and
AOT caching for every execution plane's chunk executable (ISSUE 12,
tentpole d — the pattern of Titanax's ``compile_step_with_plan``: a
single place that binds step function + sharding plan + donation so no
plane hand-rolls its own jit site).

The four compile sites this replaces:

- ``sim/supervisor.py`` held its own ``_AOT_CACHE`` of
  ``run_keys.lower().compile()`` chunk executables → :func:`engine_chunk`
  / :func:`engine_window` (the ``key_schedule="fold_in"`` flavor, whose
  chunk length is static because no key window ships in);
- ``sim/fleet.py`` tracked first-use compiles of the batched fleet scan
  in its own set → :func:`fleet_chunk`;
- ``parallel/sharding.py`` built the sharded step/chunk jits inline →
  :func:`sharded_step_plan` / :func:`sharded_chunk_plan` (sharding.py
  keeps thin delegating wrappers for its public factory names);
- ``scripts/run_multihost.py`` cached sharded runners per exec-config →
  now a dict of :func:`sharded_chunk_plan` results.

Donation policy (the async pipeline's contract, sim/supervisor.py):
every plane's chunk executable EXISTS in a donated flavor — the carried
state aliases in place, halving peak state memory — but the caller
decides per dispatch, because three inputs must outlive their chunk:
the caller's own initial state, any state serving as the host-side
retry anchor, and a checkpoint-boundary input whose output the writer
thread still has to fetch. :func:`donated_param_count` introspects what
a lowered/compiled executable actually promises (the donation audit,
tests/test_compile_plan.py).

The fleet plane is the exception that proves the cache: AOT-compiling
the batched fleet scan hoists module-level jnp constants into executable
parameters (the round-9 "compiled for 61 inputs but called with 59"
failure), so :func:`fleet_chunk` deliberately returns the plain-jit
entry point and only CENTRALIZES the first-use bookkeeping its compile
deadline needs; its donation audit compiles a throwaway lowering purely
for introspection.
"""

from __future__ import annotations

import re
from collections import deque
from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..sim.config import SimConfig, TopicParams
from ..sim.state import SimState

# ---------------------------------------------------------------------------
# plain engine plane: AOT chunk executables

# keyed by (schedule, exec_cfg, chunk shape, key dtype, telemetry,
# donate): compiling through .lower().compile() ahead of the watchdog
# keeps compile time out of the run deadline, and re-dispatching the SAME
# executable across chunks/retries skips the jit cache lookup entirely.
# SimConfig is frozen/hashable, so the dict stays small (one entry per
# ladder rung per tail-chunk shape per donation flavor).
_ENGINE_AOT: dict = {}


def engine_chunk(exec_cfg: SimConfig, state: SimState, tp: TopicParams,
                 keys_chunk, *, telemetry: bool = False,
                 donate: bool = False):
    """AOT executable for one supervised chunk of the plain engine scan
    (``key_schedule="host"``: explicit per-tick key rows). Call as
    ``exe(state, tp, keys_chunk)``; ``donate=True`` consumes ``state``."""
    from ..sim.engine import run_keys, run_keys_donated
    cache_key = ("engine", exec_cfg, int(keys_chunk.shape[0]),
                 str(keys_chunk.dtype), telemetry, donate)
    exe = _ENGINE_AOT.get(cache_key)
    if exe is None:
        fn = run_keys_donated if donate else run_keys
        exe = fn.lower(state, exec_cfg, tp, keys_chunk,
                       telemetry=telemetry).compile()
        _ENGINE_AOT[cache_key] = exe
    return exe


def engine_window(exec_cfg: SimConfig, state: SimState, tp: TopicParams,
                  key, n_ticks: int, *, telemetry: bool = False,
                  donate: bool = False):
    """AOT executable for one supervised chunk under
    ``key_schedule="fold_in"``: per-tick keys derive on device from the
    master key and the carried absolute tick, so the call ships two
    scalars' worth of key material instead of a ``[C, 2]`` window. Call
    as ``exe(state, tp, key)``."""
    from ..sim.engine import run_window, run_window_donated
    cache_key = ("window", exec_cfg, int(n_ticks), str(key.dtype),
                 telemetry, donate)
    exe = _ENGINE_AOT.get(cache_key)
    if exe is None:
        fn = run_window_donated if donate else run_window
        exe = fn.lower(state, exec_cfg, tp, key, n_ticks,
                       telemetry=telemetry).compile()
        _ENGINE_AOT[cache_key] = exe
    return exe


# ---------------------------------------------------------------------------
# fleet plane: plain-jit dispatch with centralized first-use bookkeeping

_FLEET_SEEN: set = set()


def fleet_chunk(exec_cfg: SimConfig, keys_shape=None, key_dtype=None, *,
                telemetry: bool = False, mark: bool = True):
    """The batched fleet window entry point + whether this (config,
    [C, B] window shape, key dtype, lane) is a first use (the fleet
    driver runs first uses under its compile deadline instead of the run
    deadline — compile time is not execution time). ``mark=False`` only
    queries: the async fleet driver marks a shape compiled on CONFIRM,
    not dispatch, so a window that dies mid-compile retries under the
    compile deadline again. Plain jit on purpose — see the module
    docstring's const-hoisting rationale."""
    from ..sim.fleet import fleet_run_keys
    seen_key = ("fleet", exec_cfg, tuple(keys_shape or ()), str(key_dtype),
                telemetry)
    first_use = seen_key not in _FLEET_SEEN
    if mark:
        _FLEET_SEEN.add(seen_key)
    return fleet_run_keys, first_use


# ---------------------------------------------------------------------------
# sharded plane: the jit factories (moved here from parallel/sharding.py,
# which keeps its public make_sharded_* names as delegating wrappers)

# stale-id protection, both directions: the dispatch cache keys on
# function identity, and a garbage-collected closure's id() can be REUSED
# by the next factory call, hitting a stale executable.
# (a) each factory pins its jit to the returned wrapper — a
#     STILL-REFERENCED step can never be evicted out from under its
#     caller (the old deque's 65th-call hazard);
# (b) the bounded deque ALSO retains the last 64 steps so a
#     drop-and-recreate config sweep (wrapper rebound each iteration)
#     cannot recycle a dead closure's id into a live cache entry.
_LIVE_STEPS: deque = deque(maxlen=64)


def _sharded_prelude(mesh, cfg: SimConfig, tp: TopicParams):
    from .sharding import DCN_AXIS, PEER_AXIS, state_shardings
    if cfg.sharded_route not in ("replicated", "halo"):
        raise ValueError(f"unknown sharded_route {cfg.sharded_route!r}; "
                         "expected 'replicated' or 'halo'")
    if cfg.degree_buckets is not None:
        raise ValueError(
            "sharded dense plan: cfg.degree_buckets is set — heavy-tailed "
            "configs take the row-sharded bucketed plane (parallel/"
            "sharding.make_sharded_bucketed_run / compile_plan."
            "bucketed_chunk_plan), not the dense-padded step")
    shardings = state_shardings(mesh, cfg)
    repl = NamedSharding(mesh, P())
    tp_sh = jax.tree.map(lambda _: repl, tp)
    peer_axes = tuple(ax for ax in (DCN_AXIS, PEER_AXIS)
                      if ax in mesh.axis_names)
    return shardings, repl, tp_sh, peer_axes


def sharded_step_plan(mesh, cfg: SimConfig, tp: TopicParams):
    """jit the full network step with explicit peer-sharded in/out state.

    Entering :func:`kernel_context.kernel_mesh` while the step traces
    makes the Pallas kernel dispatch sites (ops/permgather, ops/hopkernel)
    wrap themselves in shard_map — without it the SPMD partitioner could
    only replicate the pallas_calls (full-size kernel on every device).
    The XLA-formulation paths ignore the context and auto-partition."""
    from ..sim.engine import step
    from .kernel_context import kernel_mesh

    shardings, repl, tp_sh, peer_axes = _sharded_prelude(mesh, cfg, tp)

    # tp is passed as a traced ARGUMENT, not closed over: closure arrays
    # become hoisted constants, and round 4 hit a jit AOT/dispatch
    # disagreement about them ("compiled for 60 inputs but called with
    # 41" whenever a .lower().compile() of the program preceded a regular
    # dispatch anywhere in the process). With no captured arrays the
    # lowered parameter list equals the explicit arguments and both
    # execution paths agree.
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, repl), out_shardings=shardings)
    def _step(state: SimState, tp_arg: TopicParams,
              key: jax.Array) -> SimState:
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor,
                         bucket_capacity=cfg.halo_bucket_capacity):
            return step(state, cfg, tp_arg, key)

    def sharded_step(state: SimState, key: jax.Array) -> SimState:
        # commit the key before dispatch: the jit fast path was observed
        # re-sharding an uncommitted PRNG key with a STATE leaf's spec
        return _step(state, tp, jax.device_put(key, repl))

    sharded_step._step = _step
    _LIVE_STEPS.append(_step)
    sharded_step.lower = lambda st, k: _step.lower(
        st, tp, jax.device_put(k, repl))
    return sharded_step


def sharded_chunk_plan(mesh, cfg: SimConfig, tp: TopicParams,
                       telemetry: bool = False, donate: bool = False):
    """jit a whole chunk — ``lax.scan`` of the sharded step over explicit
    per-tick keys — with the peer-sharded in/out state, the multi-host
    execution unit (parallel/multihost.py drives supervised chunks
    through this instead of ``engine.run_keys``, whose unsharded trace
    would lower the halo routes away). Same key discipline as
    ``engine.run_keys``: the caller pre-splits one master key and scans
    contiguous windows, so the chunked sharded trajectory is
    bit-identical to the single-scan unsharded one.

    ``telemetry=True`` stacks per-tick ``HealthRecord`` aggregates whose
    reductions the SPMD partitioner lowers over the same peer sharding
    as the step, emitted REPLICATED — every rank holds the full ``[C]``
    record buffer, so rank 0 can journal without any extra gather; the
    runner then returns ``(state, HealthRecord)``. ``donate=True``
    aliases the carried state in place (the multihost driver keeps the
    default False: boundary gathers and rank-local retries need the
    input alive)."""
    from ..sim.engine import step
    from ..sim.telemetry import health_record
    from .kernel_context import kernel_mesh

    shardings, repl, tp_sh, peer_axes = _sharded_prelude(mesh, cfg, tp)
    # health aggregates replicate (repl is a pytree PREFIX spec for the
    # whole HealthRecord subtree)
    out_sh = (shardings, repl) if telemetry else shardings

    # tp rides as a traced argument, not a closure, for the same AOT/
    # dispatch-agreement reason documented on sharded_step_plan
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, repl), out_shardings=out_sh,
             donate_argnums=(0,) if donate else ())
    def _run(state: SimState, tp_arg: TopicParams, keys: jax.Array):
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor,
                         bucket_capacity=cfg.halo_bucket_capacity):
            def body(carry, k):
                nxt = step(carry, cfg, tp_arg, k)
                return nxt, health_record(nxt, cfg, tp_arg) \
                    if telemetry else None
            out, health = jax.lax.scan(body, state, keys)
        return (out, health) if telemetry else out

    def sharded_run_keys(state: SimState, keys: jax.Array,
                         tp_arg: TopicParams | None = None):
        # tp is a traced argument of the compiled scan, so a caller may
        # swap it per call (the supervisor run_fn hook hands one) without
        # invalidating the executable; default is the build-time tp
        return _run(state, tp if tp_arg is None else tp_arg,
                    jax.device_put(keys, repl))

    sharded_run_keys._run = _run
    _LIVE_STEPS.append(_run)
    sharded_run_keys.lower = lambda st, keys: _run.lower(
        st, tp, jax.device_put(keys, repl))
    return sharded_run_keys


def bucketed_chunk_plan(mesh, cfg: SimConfig, tp: TopicParams,
                        telemetry: bool = False, donate: bool = False):
    """jit a whole chunk of the DEGREE-BUCKETED step — ``lax.scan`` of
    ``sim.bucketed.bucketed_step`` with every bucket's edge planes
    row-sharded over the mesh (parallel/sharding.bucketed_state_shardings)
    and the flat reverse-edge exchange riding
    ``parallel.halo.route_bucketed_flat`` under ``sharded_route="halo"``.
    Same key discipline as ``sharded_chunk_plan``: the caller pre-splits
    one master key and scans contiguous windows, so the chunked sharded
    bucketed trajectory is bit-identical to the single-scan one (and,
    under ``bucketed_rng="dense"``, to the dense engine's)."""
    from ..sim.bucketed import bucketed_step, check_bucketable
    from .kernel_context import kernel_mesh
    from .sharding import DCN_AXIS, PEER_AXIS, bucketed_state_shardings

    check_bucketable(cfg)
    if telemetry:
        raise ValueError(
            "bucketed_chunk_plan: telemetry is not bucketed — "
            "sim/telemetry.health_record reads the dense [N, K] planes")
    if cfg.sharded_route not in ("replicated", "halo"):
        raise ValueError(f"unknown sharded_route {cfg.sharded_route!r}; "
                         "expected 'replicated' or 'halo'")
    shardings = bucketed_state_shardings(mesh, cfg)
    repl = NamedSharding(mesh, P())
    tp_sh = jax.tree.map(lambda _: repl, tp)
    peer_axes = tuple(ax for ax in (DCN_AXIS, PEER_AXIS)
                      if ax in mesh.axis_names)

    # tp rides as a traced argument, not a closure, for the same AOT/
    # dispatch-agreement reason documented on sharded_step_plan
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, repl), out_shardings=shardings,
             donate_argnums=(0,) if donate else ())
    def _run(state, tp_arg: TopicParams, keys: jax.Array):
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor,
                         bucket_capacity=cfg.halo_bucket_capacity):
            def body(carry, k):
                return bucketed_step(carry, cfg, tp_arg, k), None
            out, _ = jax.lax.scan(body, state, keys)
        return out

    def bucketed_run_keys(state, keys: jax.Array,
                          tp_arg: TopicParams | None = None):
        return _run(state, tp if tp_arg is None else tp_arg,
                    jax.device_put(keys, repl))

    bucketed_run_keys._run = _run
    _LIVE_STEPS.append(_run)
    bucketed_run_keys.lower = lambda st, keys: _run.lower(
        st, tp, jax.device_put(keys, repl))
    return bucketed_run_keys


# ---------------------------------------------------------------------------
# donation audit: introspect what an executable actually promises

# compiled HLO: `input_output_alias={ {0}: (0, {}, may-alias), ... }` —
# the first tuple element is the donated PARAMETER number
_ALIAS_RE = re.compile(
    r"\(\s*(\d+)\s*,\s*\{[^}]*\}\s*,\s*(?:may|must)-alias\s*\)")


def donated_param_count(obj) -> int:
    """How many input buffers a lowered/compiled executable donates,
    parsed from its text form. Accepts either a ``jax.stages.Lowered``
    (StableHLO: one ``tf.aliasing_output`` arg attribute per donated
    input) or a ``jax.stages.Compiled`` (HLO: the ``input_output_alias``
    table). 0 means the executable donates nothing — the audit's
    negative control."""
    txt = obj.as_text()
    n = len(re.findall(r"tf\.aliasing_output", txt))
    if n:
        return n
    return len(set(_ALIAS_RE.findall(txt)))


def clear_caches() -> None:
    """Drop the AOT cache and fleet first-use marks (tests that need a
    cold plan)."""
    _ENGINE_AOT.clear()
    _FLEET_SEEN.clear()
