"""Multi-process execution: the path past the single-chip HBM wall.

Everything before this module assumed ONE process: the peer-axis sharded
step is bit-exact at 8 devices (tests/test_sharding.py) and the 2-D
``make_mesh_2d`` dcn×peers layout dry-runs, but a 1M-peer ``SimState``
(~3.7 GB of peer-major planes, ``sim.state.state_nbytes``) cannot
materialize on one host before being scattered. This module stands up the
real thing (SNIPPETS [1]/[2] pattern):

- :func:`initialize` — the ``jax.distributed.initialize`` bootstrap
  (coordinator address + process rank from args or the ``GRAFT_*`` env
  family; CPU backends get gloo cross-process collectives so the 2-process
  localhost smoke test runs in CI with no TPU).
- :func:`init_state_local` — builds ONLY this process's contiguous
  ``[N/P, ...]`` block of every peer-major SimState plane (hosts-major,
  matching the ``make_mesh_2d`` layout where the peer axis shards over
  (dcn, peers) with a contiguous block per host); the replicated message
  tables and scalars are built in full on every process. The full state
  never exists on any single host — only the host-side numpy topology
  ([N, K] int32, ~128 MB at 1M) does, which every process needs anyway to
  slice its rows.
- :func:`global_state` — assembles the per-process shards into one global
  sharded SimState via ``multihost_utils.host_local_array_to_global_array``
  with the canonical ``state_partition_specs``.
- :func:`gather_state` / :func:`local_rows_state` — the rank-0 write
  discipline: ``gather_state`` (collective — EVERY process must call it)
  materializes a host-complete numpy state so only the coordinator writes
  checkpoints/journals (sim/supervisor.py ``state_to_host``/
  ``write_files`` hooks); ``local_rows_state`` slices a host-complete
  state back to this process's rows for re-assembly on resume.

``scripts/run_multihost.py`` is the launcher gluing these into a
supervised run per process; tests/test_multihost.py pins the 2-process
CPU trajectory bit-exact against the single-process scan.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ..sim.config import SimConfig
from ..sim.state import SimState, state_spec
from ..sim.topology import Topology

# env family the launcher and initialize() share (one process per host in
# the reference deployment; localhost smoke runs set all three explicitly)
ENV_COORDINATOR = "GRAFT_COORDINATOR"          # host:port of process 0
ENV_NUM_PROCESSES = "GRAFT_NUM_PROCESSES"
ENV_PROCESS_ID = "GRAFT_PROCESS_ID"


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """``jax.distributed.initialize`` from explicit args or the ``GRAFT_*``
    env family. A single-process invocation (no coordinator anywhere) is a
    no-op, so code paths shared with tests run unchanged; calling twice is
    a no-op too (the backend tolerates one initialize per process).

    Must run BEFORE any jax backend touch (first ``jax.devices()`` /
    dispatch): distributed device discovery happens at backend init."""
    coordinator = coordinator or os.environ.get(ENV_COORDINATOR)
    if coordinator is None:
        return
    if num_processes is None:
        num_processes = int(os.environ[ENV_NUM_PROCESSES])
    if process_id is None:
        process_id = int(os.environ[ENV_PROCESS_ID])
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # cross-process CPU collectives need an explicit implementation
        # (the TPU backend brings its own ICI/DCN transport)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass        # older jaxlibs pick gloo by default
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the ONE process allowed to write checkpoints, journals,
    crash dumps, and metric lines (rank 0)."""
    return jax.process_index() == 0


def local_peer_rows(n_peers: int, num_processes: int,
                    process_id: int) -> tuple[int, int]:
    """(first row, row count) of this process's contiguous peer block —
    hosts-major, matching ``make_mesh_2d``'s (dcn, peers) layout where
    each host owns one contiguous slab of the peer axis."""
    if num_processes <= 0 or n_peers % num_processes:
        raise ValueError(
            f"local_peer_rows: n_peers={n_peers} must divide evenly over "
            f"{num_processes} processes (the peer sharding raises the same)")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"local_peer_rows: process_id={process_id} outside "
            f"[0, {num_processes})")
    nl = n_peers // num_processes
    return process_id * nl, nl


def init_state_local(cfg: SimConfig, topo: Topology,
                     process_id: int | None = None,
                     num_processes: int | None = None,
                     subscribed: np.ndarray | None = None,
                     ip_group: np.ndarray | None = None,
                     app_score: np.ndarray | None = None,
                     malicious: np.ndarray | None = None,
                     topo_local: bool = False) -> SimState:
    """This process's host-local SimState shard: peer-major planes cover
    rows ``[n0, n0+nl)`` only, replicated planes (message tables, scalars)
    are full. The per-peer inputs (``subscribed`` etc.) are the GLOBAL
    host-side numpy arrays — slicing happens here, and the cached
    ``nbr_subscribed`` receiver view is computed host-side from the full
    ``subscribed`` (a local row's neighbors can live on any process).

    ``topo_local=True`` declares that ``topo`` already carries ONLY this
    process's ``[N/P, K]`` rows (a sharded build —
    ``sim.topology.sparse_hash(..., rows=...)``), so no global topology
    table ever exists on any host: the 10M-peer construction path. The
    flag is explicit (not shape-sniffed) because at P=1 the two cases
    are indistinguishable by shape but mean different things; a
    wrong-shape ``topo`` for the declared mode raises by name.

    With ``process_id``/``num_processes`` omitted, the live distributed
    runtime's rank/size apply (a plain single process builds the full
    state — bit-identical to ``init_state``)."""
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    n, k, t = cfg.n_peers, cfg.k_slots, cfg.n_topics
    n0, nl = local_peer_rows(n, num_processes, process_id)
    want_rows = nl if topo_local else n
    if topo.neighbors.shape[0] != want_rows:
        raise ValueError(
            f"init_state_local: topo carries {topo.neighbors.shape[0]} "
            f"rows but topo_local={topo_local} expects {want_rows} "
            f"(n_peers={n}, {num_processes} processes)")
    # topo arrays index locally when they ARE the rows slice already; the
    # global per-peer inputs (subscribed etc.) always slice globally
    trows = slice(0, nl) if topo_local else slice(n0, n0 + nl)
    rows = slice(n0, n0 + nl)

    if subscribed is None:
        subscribed = np.ones((n, t), dtype=bool)
    if ip_group is None:
        ip_group = np.zeros(n, np.int32)
    if app_score is None:
        app_score = np.zeros(n, np.float32)
    if malicious is None:
        malicious = np.zeros(n, bool)

    nbr_l = np.asarray(topo.neighbors[trows])
    # receiver view of neighbor subscriptions, host-side: index the FULL
    # subscribed table with this block's (global-id) neighbor rows
    nbr_sub_l = np.transpose(
        subscribed[np.clip(nbr_l, 0, n - 1)], (0, 2, 1)) \
        & (nbr_l >= 0)[:, None, :]

    import jax.numpy as jnp

    from ..sim.state import _device_init
    # the shared builder with n_rows=nl: one SimState construction for the
    # full and local-shard cases (the receiver view rides precomputed —
    # it indexes the full subscription table, which only exists host-side)
    return _device_init(
        cfg,
        jnp.asarray(nbr_l), jnp.asarray(topo.outbound[trows]),
        jnp.asarray(topo.reverse_slot[trows]), jnp.asarray(subscribed[rows]),
        jnp.asarray(ip_group[rows]), jnp.asarray(app_score[rows]),
        jnp.asarray(malicious[rows]),
        nbr_subscribed=jnp.asarray(nbr_sub_l), n_rows=nl)


def global_state(local: SimState, mesh, cfg: SimConfig) -> SimState:
    """Assemble per-process host-local shards into ONE global sharded
    SimState on ``mesh`` (peer-major leaves concatenate hosts-major along
    the peer axis; replicated leaves must be identical on every process).
    Single-process meshes pass through the same call — it degrades to a
    device_put with the canonical shardings."""
    from jax.experimental import multihost_utils

    from .sharding import state_partition_specs
    specs = state_partition_specs(mesh, cfg)
    return SimState(*multihost_utils.host_local_array_to_global_array(
        tuple(local), mesh, tuple(specs)))


def gather_state(state: SimState) -> SimState:
    """Host-complete numpy copy of a (possibly multi-process sharded)
    SimState. COLLECTIVE: every process must call it (it all-gathers the
    non-addressable shards), but only rank 0 should write the result —
    the supervisor's ``state_to_host`` hook."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return SimState(*[np.asarray(x) for x in state])
    # non-fully-addressable inputs come back fully replicated (tiled is
    # ignored for them — every leaf of a multi-process state is one)
    return SimState(*multihost_utils.process_allgather(tuple(state)))


def local_rows_state(full: SimState, cfg: SimConfig,
                     process_id: int | None = None,
                     num_processes: int | None = None) -> SimState:
    """Slice a host-complete state back to this process's peer rows
    (resume path: rank 0's checkpoint restores host-complete on every
    process — shared filesystem — then each process re-slices and
    re-assembles via :func:`global_state`)."""
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    n0, nl = local_peer_rows(cfg.n_peers, num_processes, process_id)
    spec = state_spec(cfg)
    return SimState(**{
        f: (np.asarray(getattr(full, f))[n0:n0 + nl]
            if spec[f][2] else np.asarray(getattr(full, f)))
        for f in SimState._fields})
