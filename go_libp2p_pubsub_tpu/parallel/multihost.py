"""Multi-process execution: the path past the single-chip HBM wall.

Everything before this module assumed ONE process: the peer-axis sharded
step is bit-exact at 8 devices (tests/test_sharding.py) and the 2-D
``make_mesh_2d`` dcn×peers layout dry-runs, but a 1M-peer ``SimState``
(~3.7 GB of peer-major planes, ``sim.state.state_nbytes``) cannot
materialize on one host before being scattered. This module stands up the
real thing (SNIPPETS [1]/[2] pattern):

- :func:`initialize` — the ``jax.distributed.initialize`` bootstrap
  (coordinator address + process rank from args or the ``GRAFT_*`` env
  family; CPU backends get gloo cross-process collectives so the 2-process
  localhost smoke test runs in CI with no TPU).
- :func:`init_state_local` — builds ONLY this process's contiguous
  ``[N/P, ...]`` block of every peer-major SimState plane (hosts-major,
  matching the ``make_mesh_2d`` layout where the peer axis shards over
  (dcn, peers) with a contiguous block per host); the replicated message
  tables and scalars are built in full on every process. The full state
  never exists on any single host — only the host-side numpy topology
  ([N, K] int32, ~128 MB at 1M) does, which every process needs anyway to
  slice its rows.
- :func:`global_state` — assembles the per-process shards into one global
  sharded SimState via ``multihost_utils.host_local_array_to_global_array``
  with the canonical ``state_partition_specs``.
- :func:`gather_state` / :func:`local_rows_state` — the rank-0 write
  discipline: ``gather_state`` (collective — EVERY process must call it)
  materializes a host-complete numpy state so only the coordinator writes
  checkpoints/journals (sim/supervisor.py ``state_to_host``/
  ``write_files`` hooks); ``local_rows_state`` slices a host-complete
  state back to this process's rows for re-assembly on resume.

``scripts/run_multihost.py`` is the launcher gluing these into a
supervised run per process; tests/test_multihost.py pins the 2-process
CPU trajectory bit-exact against the single-process scan.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from ..sim.config import SimConfig
from ..sim.state import SimState, state_spec
from ..sim.topology import Topology

# env family the launcher and initialize() share (one process per host in
# the reference deployment; localhost smoke runs set all three explicitly)
ENV_COORDINATOR = "GRAFT_COORDINATOR"          # host:port of process 0
ENV_NUM_PROCESSES = "GRAFT_NUM_PROCESSES"
ENV_PROCESS_ID = "GRAFT_PROCESS_ID"


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """``jax.distributed.initialize`` from explicit args or the ``GRAFT_*``
    env family. A single-process invocation (no coordinator anywhere) is a
    no-op, so code paths shared with tests run unchanged; calling twice is
    a no-op too (the backend tolerates one initialize per process).

    Must run BEFORE any jax backend touch (first ``jax.devices()`` /
    dispatch): distributed device discovery happens at backend init."""
    coordinator = coordinator or os.environ.get(ENV_COORDINATOR)
    if coordinator is None:
        return
    if num_processes is None:
        num_processes = int(os.environ[ENV_NUM_PROCESSES])
    if process_id is None:
        process_id = int(os.environ[ENV_PROCESS_ID])
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # cross-process CPU collectives need an explicit implementation
        # (the TPU backend brings its own ICI/DCN transport)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass        # older jaxlibs pick gloo by default
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the ONE process allowed to write checkpoints, journals,
    crash dumps, and metric lines (rank 0)."""
    return jax.process_index() == 0


def local_peer_rows(n_peers: int, num_processes: int,
                    process_id: int) -> tuple[int, int]:
    """(first row, row count) of this process's contiguous peer block —
    hosts-major, matching ``make_mesh_2d``'s (dcn, peers) layout where
    each host owns one contiguous slab of the peer axis."""
    if num_processes <= 0 or n_peers % num_processes:
        raise ValueError(
            f"local_peer_rows: n_peers={n_peers} must divide evenly over "
            f"{num_processes} processes (the peer sharding raises the same)")
    if not (0 <= process_id < num_processes):
        raise ValueError(
            f"local_peer_rows: process_id={process_id} outside "
            f"[0, {num_processes})")
    nl = n_peers // num_processes
    return process_id * nl, nl


def init_state_local(cfg: SimConfig, topo: Topology,
                     process_id: int | None = None,
                     num_processes: int | None = None,
                     subscribed: np.ndarray | None = None,
                     ip_group: np.ndarray | None = None,
                     app_score: np.ndarray | None = None,
                     malicious: np.ndarray | None = None,
                     topo_local: bool = False) -> SimState:
    """This process's host-local SimState shard: peer-major planes cover
    rows ``[n0, n0+nl)`` only, replicated planes (message tables, scalars)
    are full. The per-peer inputs (``subscribed`` etc.) are the GLOBAL
    host-side numpy arrays — slicing happens here, and the cached
    ``nbr_subscribed`` receiver view is computed host-side from the full
    ``subscribed`` (a local row's neighbors can live on any process).

    ``topo_local=True`` declares that ``topo`` already carries ONLY this
    process's ``[N/P, K]`` rows (a sharded build —
    ``sim.topology.sparse_hash(..., rows=...)``), so no global topology
    table ever exists on any host: the 10M-peer construction path. The
    flag is explicit (not shape-sniffed) because at P=1 the two cases
    are indistinguishable by shape but mean different things; a
    wrong-shape ``topo`` for the declared mode raises by name.

    With ``process_id``/``num_processes`` omitted, the live distributed
    runtime's rank/size apply (a plain single process builds the full
    state — bit-identical to ``init_state``)."""
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    n, k, t = cfg.n_peers, cfg.k_slots, cfg.n_topics
    n0, nl = local_peer_rows(n, num_processes, process_id)
    want_rows = nl if topo_local else n
    if topo.neighbors.shape[0] != want_rows:
        raise ValueError(
            f"init_state_local: topo carries {topo.neighbors.shape[0]} "
            f"rows but topo_local={topo_local} expects {want_rows} "
            f"(n_peers={n}, {num_processes} processes)")
    # topo arrays index locally when they ARE the rows slice already; the
    # global per-peer inputs (subscribed etc.) always slice globally
    trows = slice(0, nl) if topo_local else slice(n0, n0 + nl)
    rows = slice(n0, n0 + nl)

    if subscribed is None:
        subscribed = np.ones((n, t), dtype=bool)
    if ip_group is None:
        ip_group = np.zeros(n, np.int32)
    if app_score is None:
        app_score = np.zeros(n, np.float32)
    if malicious is None:
        malicious = np.zeros(n, bool)

    nbr_l = np.asarray(topo.neighbors[trows])
    # receiver view of neighbor subscriptions, host-side: index the FULL
    # subscribed table with this block's (global-id) neighbor rows
    nbr_sub_l = np.transpose(
        subscribed[np.clip(nbr_l, 0, n - 1)], (0, 2, 1)) \
        & (nbr_l >= 0)[:, None, :]

    import jax.numpy as jnp

    from ..sim.state import _device_init
    # the shared builder with n_rows=nl: one SimState construction for the
    # full and local-shard cases (the receiver view rides precomputed —
    # it indexes the full subscription table, which only exists host-side)
    return _device_init(
        cfg,
        jnp.asarray(nbr_l), jnp.asarray(topo.outbound[trows]),
        jnp.asarray(topo.reverse_slot[trows]), jnp.asarray(subscribed[rows]),
        jnp.asarray(ip_group[rows]), jnp.asarray(app_score[rows]),
        jnp.asarray(malicious[rows]),
        nbr_subscribed=jnp.asarray(nbr_sub_l), n_rows=nl)


def init_bucketed_local(cfg: SimConfig, topo,
                        process_id: int | None = None,
                        num_processes: int | None = None,
                        subscribed: np.ndarray | None = None,
                        ip_group: np.ndarray | None = None,
                        app_score: np.ndarray | None = None,
                        malicious: np.ndarray | None = None):
    """This process's host-local shard of a DEGREE-BUCKETED state, built
    WITHOUT the global dense state ever materializing anywhere — the
    heavy-tailed 10M construction path.

    Two different row sets per process, matching
    ``parallel.sharding.bucketed_partition_specs``:

    - the global half ``g`` covers the contiguous peer block
      ``[n0, n0+nl)`` (hosts-major, like :func:`init_state_local`) — built
      directly at ZERO edge width (``k_slots=0`` through the shared
      ``_device_init``, whose topology-derived plane widths come from the
      passed arrays), so no dense [nl, K] slab backs it;
    - each bucket's edge planes cover that BUCKET's local row window
      ``[s_b + p*c_b/P, s_b + (p+1)*c_b/P)`` — built one bucket at a time
      from ``topo(start, count)`` row-window topology (e.g.
      ``lambda s, c: topology.powerlaw(..., rows=(s, c))``) through
      ``bucketize_state(rows=...)``, so the transient peak is one
      bucket's local slab, not the graph.

    ``topo`` is either that callable or a full host-side Topology (sliced
    per window — the small-N test path). Per-peer inputs are the GLOBAL
    host-side arrays, exactly as :func:`init_state_local` takes them.
    Concatenating every process's shards reproduces
    ``init_bucketed_state`` bit for bit (tests/test_multihost.py)."""
    import dataclasses

    import jax.numpy as jnp

    from ..sim.bucketed import BucketedState, bucketize_state, \
        check_bucketable, encode_bucketed
    from ..sim.state import _device_init, decode_state
    from ..sim.topology import Topology

    check_bucketable(cfg)
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    n, t = cfg.n_peers, cfg.n_topics
    n0, nl = local_peer_rows(n, num_processes, process_id)

    if subscribed is None:
        subscribed = np.ones((n, t), dtype=bool)
    if ip_group is None:
        ip_group = np.zeros(n, np.int32)
    if app_score is None:
        app_score = np.zeros(n, np.float32)
    if malicious is None:
        malicious = np.zeros(n, bool)

    if isinstance(topo, Topology):
        full_topo = topo

        def topo_rows(start, count):
            sl = slice(start, start + count)
            return Topology(neighbors=full_topo.neighbors[sl],
                            outbound=full_topo.outbound[sl],
                            reverse_slot=full_topo.reverse_slot[sl],
                            degree=full_topo.degree[sl])
    else:
        topo_rows = topo

    # the global half at zero edge width: _device_init sizes the
    # topology-derived planes from the passed arrays and the k_slots
    # zeros planes at width 0, and "f32" makes its encode_state a no-op,
    # so the result IS the compute-layout g with correctly-typed
    # zero-width edge placeholders (encode_bucketed below applies the
    # real codec to the non-edge planes)
    gcfg = dataclasses.replace(cfg, k_slots=0, degree_buckets=None,
                               state_precision="f32")
    rows = slice(n0, n0 + nl)
    g = _device_init(
        gcfg,
        jnp.zeros((nl, 0), jnp.int32), jnp.zeros((nl, 0), bool),
        jnp.zeros((nl, 0), jnp.int32), jnp.asarray(subscribed[rows]),
        jnp.asarray(ip_group[rows]), jnp.asarray(app_score[rows]),
        jnp.asarray(malicious[rows]),
        nbr_subscribed=jnp.zeros((nl, t, 0), bool), n_rows=nl)

    e, rev = [], []
    start = 0
    for b, (c, kb) in enumerate(cfg.degree_buckets):
        c, kb = int(c), int(kb)
        if c % num_processes:
            raise ValueError(
                f"init_bucketed_local: bucket {b} ({c} rows x k_ceil {kb}) "
                f"does not split over {num_processes} processes — realign "
                "the partition with topology.align_degree_buckets")
        cb = c // num_processes
        gs = start + process_id * cb
        tb = topo_rows(gs, cb)
        if tb.neighbors.shape[0] != cb:
            raise ValueError(
                f"init_bucketed_local: topo({gs}, {cb}) returned "
                f"{tb.neighbors.shape[0]} rows")
        nbr_l = np.asarray(tb.neighbors)
        nbr_sub_l = np.transpose(
            subscribed[np.clip(nbr_l, 0, n - 1)], (0, 2, 1)) \
            & (nbr_l >= 0)[:, None, :]
        wrows = slice(gs, gs + cb)
        slab = _device_init(
            cfg,
            jnp.asarray(nbr_l), jnp.asarray(tb.outbound),
            jnp.asarray(tb.reverse_slot), jnp.asarray(subscribed[wrows]),
            jnp.asarray(ip_group[wrows]), jnp.asarray(app_score[wrows]),
            jnp.asarray(malicious[wrows]),
            nbr_subscribed=jnp.asarray(nbr_sub_l), n_rows=cb)
        part = bucketize_state(decode_state(slab, cfg), cfg, rows=(gs, cb))
        e.append(part.e[b])
        rev.append(part.rev[b])
        start += c
    return encode_bucketed(
        BucketedState(g=g, e=tuple(e), rev=tuple(rev)), cfg)


def global_state(local: SimState, mesh, cfg: SimConfig) -> SimState:
    """Assemble per-process host-local shards into ONE global sharded
    SimState on ``mesh`` (peer-major leaves concatenate hosts-major along
    the peer axis; replicated leaves must be identical on every process).
    Single-process meshes pass through the same call — it degrades to a
    device_put with the canonical shardings."""
    from jax.experimental import multihost_utils

    from .sharding import state_partition_specs
    specs = state_partition_specs(mesh, cfg)
    return SimState(*multihost_utils.host_local_array_to_global_array(
        tuple(local), mesh, tuple(specs)))


def gather_state(state):
    """Host-complete numpy copy of a (possibly multi-process sharded)
    state pytree — SimState and BucketedState alike. COLLECTIVE: every
    process must call it (it all-gathers the non-addressable shards), but
    only rank 0 should write the result — the supervisor's
    ``state_to_host`` hook."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, state)
    # non-fully-addressable inputs come back fully replicated (tiled is
    # ignored for them — every leaf of a multi-process state is one)
    leaves, tdef = jax.tree.flatten(state)
    return jax.tree.unflatten(
        tdef, list(multihost_utils.process_allgather(tuple(leaves))))


def local_rows_state(full: SimState, cfg: SimConfig,
                     process_id: int | None = None,
                     num_processes: int | None = None) -> SimState:
    """Slice a host-complete state back to this process's peer rows
    (resume path: rank 0's checkpoint restores host-complete on every
    process — shared filesystem — then each process re-slices and
    re-assembles via :func:`global_state`)."""
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    n0, nl = local_peer_rows(cfg.n_peers, num_processes, process_id)
    spec = state_spec(cfg)
    return SimState(**{
        f: (np.asarray(getattr(full, f))[n0:n0 + nl]
            if spec[f][2] else np.asarray(getattr(full, f)))
        for f in SimState._fields})


def global_bucketed_state(local, mesh, cfg: SimConfig):
    """Assemble per-process host-local BUCKETED shards
    (:func:`init_bucketed_local` / :func:`local_bucketed_rows_state`) into
    one global sharded BucketedState on ``mesh`` with the canonical
    ``parallel.sharding.bucketed_partition_specs``."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from .sharding import bucketed_partition_specs
    specs = bucketed_partition_specs(mesh, cfg)
    leaves, tdef = jax.tree.flatten(local)
    spec_leaves = jax.tree.leaves(specs,
                                  is_leaf=lambda x: isinstance(x, P))
    out = multihost_utils.host_local_array_to_global_array(
        tuple(leaves), mesh, tuple(spec_leaves))
    return jax.tree.unflatten(tdef, list(out))


def local_bucketed_rows_state(full, cfg: SimConfig,
                              process_id: int | None = None,
                              num_processes: int | None = None):
    """Slice a host-complete BucketedState back to this process's rows —
    the bucketed resume path, elastic in P: the global half re-slices to
    the contiguous peer block and every bucket's planes to THAT bucket's
    local window, so a checkpoint gathered at P restores at any P' that
    divides the (P-independent) bucket alignment."""
    from ..sim.bucketed import BucketedState, EdgePlanes

    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    g = local_rows_state(full.g, cfg, process_id=process_id,
                         num_processes=num_processes)
    e, rev = [], []
    for b, (c, kb) in enumerate(cfg.degree_buckets):
        c, kb = int(c), int(kb)
        if c % num_processes:
            raise ValueError(
                f"local_bucketed_rows_state: bucket {b} ({c} rows x "
                f"k_ceil {kb}) does not split over {num_processes} "
                "processes — realign the partition with "
                "topology.align_degree_buckets")
        cb = c // num_processes
        sl = slice(process_id * cb, (process_id + 1) * cb)
        e.append(EdgePlanes(**{
            f: np.asarray(getattr(full.e[b], f))[sl]
            for f in EdgePlanes._fields}))
        rev.append(np.asarray(full.rev[b])[sl])
    return BucketedState(g=g, e=tuple(e), rev=tuple(rev))
