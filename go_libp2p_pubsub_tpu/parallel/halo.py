"""Distributed sort-permute: per-shard routing with an all-to-all halo.

The engine's edge routing (ops/permgather ``sort`` mode) applies the
edge-slot involution as one global ``lax.sort``. Under the peer-sharded
step that global sort lowers to all-gathers plus a REPLICATED sort on
every device — correct (tests pin it bit-exact) but the sort itself does
not scale with devices. This module is the scaling formulation, the
TPU-native analogue of the reference's per-connection stream fan-out
scaled across hosts (comm.go:44-191, SURVEY.md §2.3/§5.7): each device
routes only its own edge slots and exchanges cross-shard values with ONE
``all_to_all``:

    1. locally sort each VALID source slot by (destination device,
       destination slot) — cross-device traffic becomes contiguous
       buckets; invalid slots never enter the exchange (their value is
       the local identity, merged back in step 3 — routing them would
       concentrate on the diagonal bucket and blow its capacity);
    2. pad each bucket to a static capacity and ``all_to_all`` them
       (the MoE capacity-factor pattern: random underlays spread valid
       edges ~uniformly over device pairs, so capacity 4x the mean
       covers the tails; a bucket overflow POISONS the routed keys so
       trajectory tests fail loudly instead of silently dropping edges);
    3. locally sort received pairs together with the local
       invalid-slot identities — ascending global destination key
       restricted to one shard IS the shard's flat order in both
       layouts.

Wall-clock: two local sorts of ~L/D + one all_to_all of ~4L/D² per
device pair, vs one replicated global sort of L. Enabled by
``SimConfig.sharded_route="halo"`` under an active kernel mesh; the
default ("replicated") keeps the global sort. Bit-exact vs the
unsharded trajectory either way (tests/test_sharding.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .kernel_context import (
    PEER,
    current_kernel_mesh,
    note_halo_overflow,
    peer_shards,
    shard_kernel,
)

# CAPACITY RULE: each per-(src,dst)-device bucket holds
#     cap = min(Ld, factor * ceil(Ld / D))      (Ld = local slots = N*K/D)
# A uniformly-random underlay puts ~(valid Ld)/D slots in each of a
# device's D buckets, so factor x the mean covers the tails (factor=4
# default, SimConfig.halo_capacity_factor). The rule is EXACTLY checkable
# per underlay before running: `required_capacity_factor(neighbors,
# reverse_slot, d)` computes the worst bucket offline — bench underlays
# (sparse random, incl. the beacon config's) measure <= ~1.3x
# (tests/test_sharding.py capacity sweep); clustered/star-like underlays
# can exceed 4x and must raise the config knob to that function's answer.
# On overflow the routed keys are POISONED (-1 -> garbage everywhere, so
# trajectory tests fail loudly rather than dropping edges silently) AND
# the per-tick overflow count is surfaced in SimState.halo_overflow via
# the kernel-context notes (engine.step drains them) — a production run
# can alarm on halo_overflow > 0 without diffing trajectories. The
# counter also folds into the SimState.fault_flags health word
# (sim/invariants.py FLAG_HALO_OVERFLOW), so every bench metric line and
# trace export carries the poison marker alongside the count.
# numpy scalar, not jnp (see sim/state.py NEVER: module-level jax
# Arrays leak stale tracers across fleet-group retraces)
_BIG = np.int32(2_147_483_647)


def _capacity_factor() -> int:
    ctx = current_kernel_mesh()
    return ctx.capacity_factor if ctx is not None else 4


def _bucket_capacity(ld: int, n_dev: int) -> int:
    """The static per-(src,dst)-device bucket capacity: the context's
    EXACT ``bucket_capacity`` when set (degree-aware pricing —
    :func:`required_bucket_capacity`'s answer for the actual underlay),
    else the uniform-degree factor rule."""
    ctx = current_kernel_mesh()
    exact = ctx.bucket_capacity if ctx is not None else 0
    if exact > 0:
        return min(ld, exact)
    return min(ld, _capacity_factor() * (-(-ld // n_dev)))


def required_capacity_factor(neighbors, reverse_slot, n_dev: int) -> int:
    """The smallest INTEGER capacity factor that fits every (src,dst)
    bucket of this underlay on an ``n_dev``-way peer sharding — host-side
    numpy, directly assignable to ``SimConfig.halo_capacity_factor``
    before a run (already ceiled: cap = factor * ceil(Ld/D) >= the worst
    bucket)."""
    nbr = np.asarray(neighbors)
    rks = np.asarray(reverse_slot)
    n, k = nbr.shape
    if n_dev <= 0 or n % n_dev:
        # fail loudly like the sharded step does: with n % n_dev != 0 the
        # src/dest device attribution below is wrong and the returned
        # factor would be silently misleading (ADVICE r5)
        raise ValueError(
            f"required_capacity_factor: n_peers={n} must divide evenly "
            f"over n_dev={n_dev} (the peer sharding asserts the same)")
    nl = n // n_dev
    valid = (nbr >= 0) & (rks >= 0)
    src_dev = np.repeat(np.arange(n) // nl, k).reshape(n, k)
    dest_dev = np.clip(nbr, 0, n - 1) // nl
    pair = (src_dev * n_dev + dest_dev)[valid]
    counts = np.bincount(pair, minlength=n_dev * n_dev)
    mean_cap = -(-nl * k // n_dev)                  # ceil(Ld / D)
    return math.ceil(int(counts.max()) / mean_cap) if mean_cap else 0


def required_bucket_capacity(neighbors, reverse_slot, n_dev: int,
                             buckets=None) -> int:
    """The EXACT worst (src,dst)-device bucket population of this underlay
    on an ``n_dev``-way peer sharding — the degree-aware price, directly
    assignable to ``SimConfig.halo_bucket_capacity``. Where the factor
    rule prices ``factor * ceil(Ld/D)`` from a UNIFORM-degree assumption
    (over-allocating on heavy-tailed underlays, overflowing on clustered
    ones), this is the degree histogram's own answer: the padded exchange
    ships ``D * max_bucket`` entries per device instead of
    ``D * factor * ceil(Ld/D)`` — for a star-like underlay that is the
    difference between an exact fit and a poisoned run at any factor a
    config would dare set.

    With ``buckets`` (a ``cfg.degree_buckets`` partition, every bucket's
    rows tiling ``n_dev`` — :func:`sim.topology.align_degree_buckets`),
    the price is for :func:`route_bucketed_flat`'s DEGREE-BUCKETED flat
    space instead: sources live at each bucket's own K-ceiling and
    destinations are flat reverse slots in the concatenated ΣD space, so
    each (src,dst) pair is counted exactly as the row-sharded bucketed
    exchange routes it. ``n_dev`` is the FULL device count — on a 2-D
    ``{'dcn', 'peers'}`` mesh the halo all_to_alls over the joint axis
    tuple, so the joint pair count IS the per-axis worst case (any
    single-axis slice of a joint bucket is no larger)."""
    nbr = np.asarray(neighbors)
    rks = np.asarray(reverse_slot)
    n, k = nbr.shape
    if n_dev <= 0 or n % n_dev:
        raise ValueError(
            f"required_bucket_capacity: n_peers={n} must divide evenly "
            f"over n_dev={n_dev} (the peer sharding asserts the same)")
    if buckets is None:
        nl = n // n_dev
        valid = (nbr >= 0) & (rks >= 0)
        src_dev = np.repeat(np.arange(n) // nl, k).reshape(n, k)
        dest_dev = np.clip(nbr, 0, n - 1) // nl
        pair = (src_dev * n_dev + dest_dev)[valid]
        counts = np.bincount(pair, minlength=n_dev * n_dev)
        return int(counts.max()) if counts.size else 0
    bks = [(int(r), int(kb)) for r, kb in buckets]
    if sum(r for r, _ in bks) != n:
        raise ValueError(
            f"required_bucket_capacity: buckets cover "
            f"{sum(r for r, _ in bks)} rows but the underlay has {n}")
    for b, (r, kb) in enumerate(bks):
        if r % n_dev:
            raise ValueError(
                f"required_bucket_capacity: bucket {b} ({r} rows x k_ceil "
                f"{kb}) does not tile the {n_dev}-device mesh — realign "
                "the partition with topology.align_degree_buckets")
    starts = np.cumsum([0] + [r for r, _ in bks])[:-1]
    kbs = np.array([kb for _, kb in bks], np.int64)
    nbl = np.array([r // n_dev for r, _ in bks], np.int64)
    bases = np.cumsum([0] + [r * kb for r, kb in bks])[:-1].astype(np.int64)
    seg = nbl * kbs
    rows = np.arange(n)
    rb = np.searchsorted(starts, rows, side="right") - 1
    src_dev = ((rows - starts[rb]) // nbl[rb])[:, None]
    in_width = np.arange(k)[None, :] < kbs[rb][:, None]
    valid = (nbr >= 0) & (rks >= 0) & in_width
    jn = np.clip(nbr, 0, n - 1)
    cb = np.searchsorted(starts, jn, side="right") - 1
    flat = bases[cb] + (jn - starts[cb]) * kbs[cb] + np.clip(rks, 0, None)
    dest_dev = (flat - bases[cb]) // seg[cb]
    pair = (src_dev * n_dev + dest_dev)[valid]
    counts = np.bincount(pair, minlength=n_dev * n_dev)
    return int(counts.max()) if counts.size else 0


def _route_local(keys, dest_dev, valid, vals, ld, n_dev, axis_name):
    """keys [Ld]: global destination key per local source slot (valid
    slots: the involution target; invalid: the slot's own global index —
    both bijective, disjoint). vals: list of [Ld] payloads. Returns
    (payloads in local destination-flat order, overflowed-bucket count)."""
    cap = _bucket_capacity(ld, n_dev)
    dd_ext = jnp.where(valid, dest_dev, n_dev)              # invalid -> tail
    srt = jax.lax.sort((dd_ext, keys, *vals), num_keys=2)
    dd_s, keys_s = srt[0], srt[1]
    vals_s = list(srt[2:])
    counts = jnp.bincount(dd_s, length=n_dev)               # valid only
    offsets = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                               jnp.cumsum(counts)[:-1]])
    idx = offsets[:, None] + jnp.arange(cap)[None, :]       # [D, CAP]
    in_bucket = jnp.arange(cap)[None, :] < counts[:, None]
    overflow = jnp.any(counts > cap)
    idx_c = jnp.clip(idx, 0, ld - 1)
    send_keys = jnp.where(in_bucket & ~overflow,
                          jnp.take(keys_s, idx_c.reshape(-1)
                                   ).reshape(n_dev, cap), -1)
    send_vals = [jnp.where(in_bucket,
                           jnp.take(v, idx_c.reshape(-1)
                                    ).reshape(n_dev, cap), 0)
                 for v in vals_s]
    recv_keys = jax.lax.all_to_all(send_keys, axis_name, 0, 0)
    # payloads of one dtype stack into a single exchange (mixed-dtype
    # callers, e.g. the flood scores+direct pair, get one per dtype)
    by_dtype: dict = {}
    for i, v in enumerate(send_vals):
        by_dtype.setdefault(v.dtype, []).append(i)
    recv_vals = [None] * len(send_vals)
    for dt, idxs in by_dtype.items():
        stacked = jnp.stack([send_vals[i] for i in idxs])    # [P, D, CAP]
        r = jax.lax.all_to_all(stacked, axis_name, 1, 1)
        for j, i in enumerate(idxs):
            recv_vals[i] = r[j]
    # merge: received valid-routed pairs + the local invalid identities
    # (key BIG for everything that must not land: padding and local
    # valid slots, which arrived via the exchange already)
    mk = jnp.where(recv_keys.reshape(-1) < 0, _BIG, recv_keys.reshape(-1))
    lk = jnp.where(valid, _BIG, keys)
    all_keys = jnp.concatenate([mk, lk])
    out = jax.lax.sort(
        (all_keys, *[jnp.concatenate([rv.reshape(-1), v])
                     for rv, v in zip(recv_vals, vals)]), num_keys=1)
    return [o[:ld] for o in out[1:]], jnp.sum(counts > cap, dtype=jnp.int32)


def _axis_tuple():
    axes = current_kernel_mesh().peer_axes
    return axes if len(axes) > 1 else axes[0]


def route_words_halo(x_w, neighbors, reverse_slot):
    """Sharded words gather: out[w, k, n] = x_w[w, neighbors[n, k]] via the
    per-shard halo route (k-major destination layout). Inputs are the
    GLOBAL arrays; shard_map applies the sharding."""
    if current_kernel_mesh() is None:
        # not assert: -O must not strip the dispatch contract — outside a
        # kernel mesh there is no axis to all_to_all over
        raise ValueError("route_words_halo outside a kernel_mesh context")
    w, n = x_w.shape
    k = neighbors.shape[1]
    n_dev = peer_shards()
    nl = n // n_dev
    axis = _axis_tuple()

    def body(x_l, nbr_l, rks_l):
        d = jax.lax.axis_index(axis)
        n0 = d * nl
        valid = ((nbr_l >= 0) & (rks_l >= 0)).reshape(-1)
        jn = jnp.clip(nbr_l, 0, n - 1)
        rk = jnp.clip(rks_l, 0, k - 1)
        own = (jnp.arange(k)[None, :] * n
               + (n0 + jnp.arange(nl))[:, None])            # k-major self
        keys = jnp.where(valid.reshape(nl, k), rk * n + jn, own).reshape(-1)
        dest = (keys % n) // nl
        vals = [jnp.broadcast_to(x_l[i][:, None], (nl, k)).reshape(-1)
                for i in range(w)]
        outs, ovf = _route_local(keys, dest, valid, vals, nl * k, n_dev, axis)
        return (jnp.stack([o.reshape(k, nl) for o in outs]),
                jax.lax.psum(ovf, axis))

    out, overflow = shard_kernel(
        body,
        in_specs=[(None, PEER), (PEER, None), (PEER, None)],
        out_specs=[(None, None, PEER), ()],
    )(x_w, neighbors, reverse_slot)
    note_halo_overflow(overflow)
    return out


def route_payloads_halo(payloads, neighbors, reverse_slot):
    """Sharded packed-edge exchange: out[n, k] = payload[jn[n,k], rk[n,k]]
    for each [N, K] payload plane (n-major destination layout), all planes
    riding one halo."""
    if current_kernel_mesh() is None:
        raise ValueError("route_payloads_halo outside a kernel_mesh context")
    n, k = neighbors.shape
    n_dev = peer_shards()
    nl = n // n_dev
    axis = _axis_tuple()
    n_pl = len(payloads)

    def body(nbr_l, rks_l, *pl_l):
        d = jax.lax.axis_index(axis)
        n0 = d * nl
        valid = ((nbr_l >= 0) & (rks_l >= 0)).reshape(-1)
        jn = jnp.clip(nbr_l, 0, n - 1)
        rk = jnp.clip(rks_l, 0, k - 1)
        own = ((n0 + jnp.arange(nl))[:, None] * k
               + jnp.arange(k)[None, :])                    # n-major self
        keys = jnp.where(valid.reshape(nl, k), jn * k + rk, own).reshape(-1)
        dest = (keys // k) // nl
        vals = [p.reshape(-1) for p in pl_l]
        outs, ovf = _route_local(keys, dest, valid, vals, nl * k, n_dev, axis)
        return (*[o.reshape(nl, k) for o in outs], jax.lax.psum(ovf, axis))

    res = shard_kernel(
        body,
        in_specs=[(PEER, None), (PEER, None)] + [(PEER, None)] * n_pl,
        out_specs=[(PEER, None)] * n_pl + [()],
    )(neighbors, reverse_slot, *payloads)
    note_halo_overflow(res[-1])
    return list(res[:-1])


def route_bucketed_flat(payloads, revs):
    """Sharded flat reverse-edge exchange for the DEGREE-BUCKETED layout
    (sim/bucketed._exchange_flat under a kernel mesh): ``payloads[b]`` /
    ``revs[b]`` are the [Nb, Kb] bucket planes at each bucket's OWN
    K-ceiling, ``revs`` the flat ΣD-space reverse indices (invalid slots
    point at themselves). Each device owns every bucket's row slice
    ``[d*Nb/D, (d+1)*Nb/D)`` and PUSHES its valid slots' payloads to the
    device owning the reverse slot — the rev involution makes push-to-rev
    identical to gather-from-rev, so the result is bit-exact against the
    replicated ``concat + flat[rev]`` while the cross-device traffic is
    capacity-padded all_to_alls of ~ΣD/D² per device pair at each
    (src-bucket, dst-bucket) pair's own width: nothing here is sized
    N·K_max, and nothing all-gathers the ΣD space.

    Ascending flat keys restricted to one device's owned slots ARE that
    device's bucket-major local order (bucket bases increase, row blocks
    are contiguous), so the merged [ld] vector slices per bucket at the
    static segment offsets."""
    if current_kernel_mesh() is None:
        raise ValueError("route_bucketed_flat outside a kernel_mesh context")
    n_dev = peer_shards()
    shapes = [tuple(int(x) for x in p.shape) for p in payloads]
    if len({p.dtype for p in payloads}) > 1:
        raise ValueError(
            "route_bucketed_flat: all bucket payloads must share one dtype "
            f"(got {[str(p.dtype) for p in payloads]}) — they concatenate "
            "into one flat exchange vector")
    for b, (nb, kb) in enumerate(shapes):
        if nb % n_dev:
            raise ValueError(
                f"route_bucketed_flat: bucket {b} ({nb} rows x k_ceil {kb}) "
                f"does not tile the {n_dev}-device mesh — realign the "
                "partition with topology.align_degree_buckets")
    nbl = [nb // n_dev for nb, _ in shapes]
    seg = [nl * kb for nl, (_, kb) in zip(nbl, shapes)]
    ld = sum(seg)
    bases = np.cumsum([0] + [nb * kb for nb, kb in shapes]).astype(np.int64)
    if bases[-1] > int(_BIG):
        raise ValueError(
            f"route_bucketed_flat: flat edge space of {int(bases[-1])} "
            "slots exceeds the int32 key range")
    bases32 = bases[:-1].astype(np.int32)
    seg32 = np.array(seg, np.int32)
    axis = _axis_tuple()
    B = len(payloads)

    def body(*args):
        pl_l, rv_l = args[:B], args[B:]
        d = jax.lax.axis_index(axis)
        own = jnp.concatenate([
            bases32[b] + d.astype(jnp.int32) * seg32[b]
            + jnp.arange(seg[b], dtype=jnp.int32)
            for b in range(B)])
        keys = jnp.concatenate([r.reshape(-1) for r in rv_l])
        valid = keys != own
        jb = jnp.asarray(bases32)
        js = jnp.asarray(seg32)
        cbk = jnp.searchsorted(jb, keys, side="right") - 1
        dest = (keys - jb[cbk]) // js[cbk]
        vals = [jnp.concatenate([p.reshape(-1) for p in pl_l])]
        outs, ovf = _route_local(keys, dest, valid, vals, ld, n_dev, axis)
        flat = outs[0]
        res, off = [], 0
        for b in range(B):
            res.append(flat[off:off + seg[b]].reshape(nbl[b], shapes[b][1]))
            off += seg[b]
        return (*res, jax.lax.psum(ovf, axis))

    res = shard_kernel(
        body,
        in_specs=[(PEER, None)] * (2 * B),
        out_specs=[(PEER, None)] * B + [()],
    )(*payloads, *revs)
    note_halo_overflow(res[-1])
    return list(res[:-1])
