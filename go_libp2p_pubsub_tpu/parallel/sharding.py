"""Peer-axis sharding over a device mesh.

The scaling axis of this framework is the peer dimension (SURVEY.md §5.7):
all [N, ...] state shards along a 1-D ``peers`` mesh axis the way sequence-
parallel schemes shard the sequence axis. Cross-shard mesh edges surface as
gathers over the neighbor table; under jit's SPMD partitioner those lower to
XLA collectives riding ICI (the TPU-native replacement for the reference's
libp2p streams, SURVEY.md §2.3).

The XLA-formulation kernels need no shard_map: annotate in/out shardings and
let the compiler insert all_gathers/collective-permutes for the (sparse,
Dhi-bounded) cross-shard edges. The Pallas kernels DO — the partitioner
cannot split an opaque pallas_call — so ``make_sharded_step`` activates
``kernel_context.kernel_mesh`` while tracing and the kernel dispatch sites
shard_map themselves (tables replicated, receiver rows local).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.config import SimConfig, TopicParams
from ..sim.state import SimState

PEER_AXIS = "peers"
DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PEER_AXIS,))


def make_mesh_2d(n_hosts: int, devices=None) -> Mesh:
    """A (dcn, peers) mesh for multi-host runs: the peer axis shards over
    BOTH axes (hosts-major), so a contiguous block of peers lives on each
    host and the bulk of the per-hop exchange — neighbor-plane all-gathers
    between chips of one host — rides ICI, with only the host-boundary
    slices crossing DCN. This is the layout SURVEY.md §2.3 prescribes as
    the stand-in for the reference's per-connection streams (comm.go:44-191)
    scaled past one host."""
    devices = devices if devices is not None else jax.devices()
    devices = np.array(devices)
    if n_hosts <= 0 or devices.size % n_hosts:
        # not assert: -O must not strip the mesh-shape contract, and a bad
        # host count must fail by name before any collective compiles
        raise ValueError(
            f"make_mesh_2d: {devices.size} devices do not split over "
            f"{n_hosts} hosts")
    return Mesh(devices.reshape(n_hosts, -1), (DCN_AXIS, PEER_AXIS))


def state_partition_specs(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of PartitionSpecs derived from the single
    layout source of truth (``sim.state.state_spec``): peer-major arrays
    shard their leading axis over the peer mesh axes, the global message
    table and scalars replicate. The spec form (no mesh binding per leaf)
    is what ``multihost_utils.host_local_array_to_global_array`` consumes
    (parallel/multihost.py)."""
    from ..sim.state import state_spec

    # on a 2-D (dcn, peers) mesh the peer axis shards over both axes,
    # hosts-major (see make_mesh_2d)
    peer_axes = (DCN_AXIS, PEER_AXIS) if DCN_AXIS in mesh.axis_names \
        else PEER_AXIS
    spec = state_spec(cfg)
    return SimState(**{
        f: P(peer_axes, *([None] * (len(shape) - 1))) if peer_major
        else P(*([None] * len(shape)))
        for f, (shape, _dtype, peer_major) in spec.items()})


def state_shardings(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of NamedShardings (see
    :func:`state_partition_specs`)."""
    n = cfg.n_peers
    if mesh.devices.size <= 0 or n % mesh.devices.size:
        # fail loudly by name (repo convention): a non-divisible peer count
        # would otherwise surface as an opaque sharding error mid-trace
        raise ValueError(
            f"state_shardings: n_peers {n} must divide the "
            f"{mesh.devices.size}-device mesh")
    specs = state_partition_specs(mesh, cfg)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_state(state: SimState, mesh: Mesh, cfg: SimConfig) -> SimState:
    shardings = state_shardings(mesh, cfg)
    return jax.tree.map(jax.device_put, state, shardings)


def make_sharded_step(mesh: Mesh, cfg: SimConfig, tp: TopicParams):
    """jit the full network step with explicit peer-sharded in/out state.

    Delegates to :func:`parallel.compile_plan.sharded_step_plan` — the
    centralized compile plan owns every plane's shardings/donation/AOT
    caching (ISSUE 12); this name survives as the public factory."""
    from .compile_plan import sharded_step_plan
    return sharded_step_plan(mesh, cfg, tp)


def make_sharded_run_keys(mesh: Mesh, cfg: SimConfig, tp: TopicParams,
                          telemetry: bool = False):
    """jit a whole chunk — ``lax.scan`` of the sharded step over explicit
    per-tick keys — with the peer-sharded in/out state, the multi-host
    execution unit. Delegates to
    :func:`parallel.compile_plan.sharded_chunk_plan` (see there for the
    telemetry lane and donation flavor); this name survives as the
    public factory."""
    from .compile_plan import sharded_chunk_plan
    return sharded_chunk_plan(mesh, cfg, tp, telemetry=telemetry)


# ---------------------------------------------------------------------------
# the row-sharded bucketed plane (heavy-tailed underlays at ΣD cost)


def bucketed_partition_specs(mesh: Mesh, cfg: SimConfig):
    """A BucketedState-shaped pytree of PartitionSpecs: the global half
    takes the dense state's specs (its zero-width edge placeholders keep
    the leading N axis, so the peer-major specs still apply leaf for
    leaf), and every bucket's edge/rev plane shards its OWN leading row
    axis over the peer mesh axes — each device owns the same row
    fraction of EVERY degree class, so hub buckets spread over the whole
    mesh instead of piling onto rank 0."""
    from ..sim.bucketed import EDGE_FIELDS, BucketedState, EdgePlanes
    from ..sim.state import state_spec

    peer_axes = (DCN_AXIS, PEER_AXIS) if DCN_AXIS in mesh.axis_names \
        else PEER_AXIS
    spec = state_spec(cfg)
    n_buckets = len(cfg.degree_buckets)
    edge = EdgePlanes(**{
        f: P(peer_axes, *([None] * (len(spec[f][0]) - 1)))
        for f in EDGE_FIELDS})
    return BucketedState(
        g=state_partition_specs(mesh, cfg),
        e=(edge,) * n_buckets,
        rev=(P(peer_axes, None),) * n_buckets)


def bucketed_state_shardings(mesh: Mesh, cfg: SimConfig):
    """A BucketedState-shaped pytree of NamedShardings. Refuses, by
    bucket, any degree class whose rows do not tile the mesh — the
    row-sharded plane needs every bucket aligned
    (:func:`sim.topology.align_degree_buckets`)."""
    from ..sim.bucketed import check_bucketable

    check_bucketable(cfg)
    n_dev = mesh.devices.size
    for b, (n_rows, kb) in enumerate(cfg.degree_buckets):
        if int(n_rows) % n_dev:
            raise ValueError(
                f"bucketed_state_shardings: bucket {b} ({int(n_rows)} rows "
                f"x k_ceil {int(kb)}) does not tile the {n_dev}-device "
                "mesh — realign the partition with "
                "topology.align_degree_buckets")
    specs = bucketed_partition_specs(mesh, cfg)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_bucketed_state(bs, mesh: Mesh, cfg: SimConfig):
    shardings = bucketed_state_shardings(mesh, cfg)
    return jax.tree.map(jax.device_put, bs, shardings)


def make_sharded_bucketed_run(mesh: Mesh, cfg: SimConfig, tp: TopicParams,
                              donate: bool = False):
    """jit a whole chunk of the DEGREE-BUCKETED step with every bucket's
    rows sharded over the mesh — the heavy-tailed multi-host execution
    unit (ΣD cost per tick, halo-routed flat exchange, zero N·D_max
    collectives). Delegates to
    :func:`parallel.compile_plan.bucketed_chunk_plan`; this name is the
    public factory ``SupervisorConfig.run_fn`` and
    ``scripts/run_multihost.py --engine bucketed`` wire through."""
    from .compile_plan import bucketed_chunk_plan
    return bucketed_chunk_plan(mesh, cfg, tp, donate=donate)
