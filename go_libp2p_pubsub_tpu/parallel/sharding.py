"""Peer-axis sharding over a device mesh.

The scaling axis of this framework is the peer dimension (SURVEY.md §5.7):
all [N, ...] state shards along a 1-D ``peers`` mesh axis the way sequence-
parallel schemes shard the sequence axis. Cross-shard mesh edges surface as
gathers over the neighbor table; under jit's SPMD partitioner those lower to
XLA collectives riding ICI (the TPU-native replacement for the reference's
libp2p streams, SURVEY.md §2.3).

The XLA-formulation kernels need no shard_map: annotate in/out shardings and
let the compiler insert all_gathers/collective-permutes for the (sparse,
Dhi-bounded) cross-shard edges. The Pallas kernels DO — the partitioner
cannot split an opaque pallas_call — so ``make_sharded_step`` activates
``kernel_context.kernel_mesh`` while tracing and the kernel dispatch sites
shard_map themselves (tables replicated, receiver rows local).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.config import SimConfig, TopicParams
from ..sim.state import SimState

PEER_AXIS = "peers"
DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PEER_AXIS,))


def make_mesh_2d(n_hosts: int, devices=None) -> Mesh:
    """A (dcn, peers) mesh for multi-host runs: the peer axis shards over
    BOTH axes (hosts-major), so a contiguous block of peers lives on each
    host and the bulk of the per-hop exchange — neighbor-plane all-gathers
    between chips of one host — rides ICI, with only the host-boundary
    slices crossing DCN. This is the layout SURVEY.md §2.3 prescribes as
    the stand-in for the reference's per-connection streams (comm.go:44-191)
    scaled past one host."""
    devices = devices if devices is not None else jax.devices()
    devices = np.array(devices)
    if n_hosts <= 0 or devices.size % n_hosts:
        # not assert: -O must not strip the mesh-shape contract, and a bad
        # host count must fail by name before any collective compiles
        raise ValueError(
            f"make_mesh_2d: {devices.size} devices do not split over "
            f"{n_hosts} hosts")
    return Mesh(devices.reshape(n_hosts, -1), (DCN_AXIS, PEER_AXIS))


def state_partition_specs(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of PartitionSpecs derived from the single
    layout source of truth (``sim.state.state_spec``): peer-major arrays
    shard their leading axis over the peer mesh axes, the global message
    table and scalars replicate. The spec form (no mesh binding per leaf)
    is what ``multihost_utils.host_local_array_to_global_array`` consumes
    (parallel/multihost.py)."""
    from ..sim.state import state_spec

    # on a 2-D (dcn, peers) mesh the peer axis shards over both axes,
    # hosts-major (see make_mesh_2d)
    peer_axes = (DCN_AXIS, PEER_AXIS) if DCN_AXIS in mesh.axis_names \
        else PEER_AXIS
    spec = state_spec(cfg)
    return SimState(**{
        f: P(peer_axes, *([None] * (len(shape) - 1))) if peer_major
        else P(*([None] * len(shape)))
        for f, (shape, _dtype, peer_major) in spec.items()})


def state_shardings(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of NamedShardings (see
    :func:`state_partition_specs`)."""
    n = cfg.n_peers
    if mesh.devices.size <= 0 or n % mesh.devices.size:
        # fail loudly by name (repo convention): a non-divisible peer count
        # would otherwise surface as an opaque sharding error mid-trace
        raise ValueError(
            f"state_shardings: n_peers {n} must divide the "
            f"{mesh.devices.size}-device mesh")
    specs = state_partition_specs(mesh, cfg)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_state(state: SimState, mesh: Mesh, cfg: SimConfig) -> SimState:
    shardings = state_shardings(mesh, cfg)
    return jax.tree.map(jax.device_put, state, shardings)


def make_sharded_step(mesh: Mesh, cfg: SimConfig, tp: TopicParams):
    """jit the full network step with explicit peer-sharded in/out state.

    Delegates to :func:`parallel.compile_plan.sharded_step_plan` — the
    centralized compile plan owns every plane's shardings/donation/AOT
    caching (ISSUE 12); this name survives as the public factory."""
    from .compile_plan import sharded_step_plan
    return sharded_step_plan(mesh, cfg, tp)


def make_sharded_run_keys(mesh: Mesh, cfg: SimConfig, tp: TopicParams,
                          telemetry: bool = False):
    """jit a whole chunk — ``lax.scan`` of the sharded step over explicit
    per-tick keys — with the peer-sharded in/out state, the multi-host
    execution unit. Delegates to
    :func:`parallel.compile_plan.sharded_chunk_plan` (see there for the
    telemetry lane and donation flavor); this name survives as the
    public factory."""
    from .compile_plan import sharded_chunk_plan
    return sharded_chunk_plan(mesh, cfg, tp, telemetry=telemetry)
