"""Peer-axis sharding over a device mesh.

The scaling axis of this framework is the peer dimension (SURVEY.md §5.7):
all [N, ...] state shards along a 1-D ``peers`` mesh axis the way sequence-
parallel schemes shard the sequence axis. Cross-shard mesh edges surface as
gathers over the neighbor table; under jit's SPMD partitioner those lower to
XLA collectives riding ICI (the TPU-native replacement for the reference's
libp2p streams, SURVEY.md §2.3).

The XLA-formulation kernels need no shard_map: annotate in/out shardings and
let the compiler insert all_gathers/collective-permutes for the (sparse,
Dhi-bounded) cross-shard edges. The Pallas kernels DO — the partitioner
cannot split an opaque pallas_call — so ``make_sharded_step`` activates
``kernel_context.kernel_mesh`` while tracing and the kernel dispatch sites
shard_map themselves (tables replicated, receiver rows local).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.config import SimConfig, TopicParams
from ..sim.state import SimState

PEER_AXIS = "peers"
DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PEER_AXIS,))


def make_mesh_2d(n_hosts: int, devices=None) -> Mesh:
    """A (dcn, peers) mesh for multi-host runs: the peer axis shards over
    BOTH axes (hosts-major), so a contiguous block of peers lives on each
    host and the bulk of the per-hop exchange — neighbor-plane all-gathers
    between chips of one host — rides ICI, with only the host-boundary
    slices crossing DCN. This is the layout SURVEY.md §2.3 prescribes as
    the stand-in for the reference's per-connection streams (comm.go:44-191)
    scaled past one host."""
    devices = devices if devices is not None else jax.devices()
    devices = np.array(devices)
    if n_hosts <= 0 or devices.size % n_hosts:
        # not assert: -O must not strip the mesh-shape contract, and a bad
        # host count must fail by name before any collective compiles
        raise ValueError(
            f"make_mesh_2d: {devices.size} devices do not split over "
            f"{n_hosts} hosts")
    return Mesh(devices.reshape(n_hosts, -1), (DCN_AXIS, PEER_AXIS))


def state_partition_specs(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of PartitionSpecs derived from the single
    layout source of truth (``sim.state.state_spec``): peer-major arrays
    shard their leading axis over the peer mesh axes, the global message
    table and scalars replicate. The spec form (no mesh binding per leaf)
    is what ``multihost_utils.host_local_array_to_global_array`` consumes
    (parallel/multihost.py)."""
    from ..sim.state import state_spec

    # on a 2-D (dcn, peers) mesh the peer axis shards over both axes,
    # hosts-major (see make_mesh_2d)
    peer_axes = (DCN_AXIS, PEER_AXIS) if DCN_AXIS in mesh.axis_names \
        else PEER_AXIS
    spec = state_spec(cfg)
    return SimState(**{
        f: P(peer_axes, *([None] * (len(shape) - 1))) if peer_major
        else P(*([None] * len(shape)))
        for f, (shape, _dtype, peer_major) in spec.items()})


def state_shardings(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of NamedShardings (see
    :func:`state_partition_specs`)."""
    n = cfg.n_peers
    if mesh.devices.size <= 0 or n % mesh.devices.size:
        # fail loudly by name (repo convention): a non-divisible peer count
        # would otherwise surface as an opaque sharding error mid-trace
        raise ValueError(
            f"state_shardings: n_peers {n} must divide the "
            f"{mesh.devices.size}-device mesh")
    specs = state_partition_specs(mesh, cfg)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_state(state: SimState, mesh: Mesh, cfg: SimConfig) -> SimState:
    shardings = state_shardings(mesh, cfg)
    return jax.tree.map(jax.device_put, state, shardings)


def make_sharded_step(mesh: Mesh, cfg: SimConfig, tp: TopicParams):
    """jit the full network step with explicit peer-sharded in/out state.

    Entering :func:`kernel_context.kernel_mesh` while the step traces makes
    the Pallas kernel dispatch sites (ops/permgather, ops/hopkernel) wrap
    themselves in shard_map — without it the SPMD partitioner could only
    replicate the pallas_calls (full-size kernel on every device). The
    XLA-formulation paths ignore the context and auto-partition as before.
    """
    from ..sim.engine import step
    from .kernel_context import kernel_mesh

    if cfg.sharded_route not in ("replicated", "halo"):
        raise ValueError(f"unknown sharded_route {cfg.sharded_route!r}; "
                         "expected 'replicated' or 'halo'")
    shardings = state_shardings(mesh, cfg)
    key_sh = NamedSharding(mesh, P())
    repl = NamedSharding(mesh, P())
    tp_sh = jax.tree.map(lambda _: repl, tp)
    peer_axes = tuple(ax for ax in (DCN_AXIS, PEER_AXIS)
                      if ax in mesh.axis_names)

    # tp is passed as a traced ARGUMENT, not closed over: closure arrays
    # become hoisted constants, and round 4 hit a jit AOT/dispatch
    # disagreement about them ("compiled for 60 inputs but called with
    # 41" whenever a .lower().compile() of the program preceded a regular
    # dispatch anywhere in the process). With no captured arrays the
    # lowered parameter list equals the explicit arguments and both
    # execution paths agree.
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, key_sh), out_shardings=shardings)
    def _step(state: SimState, tp_arg: TopicParams,
              key: jax.Array) -> SimState:
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor):
            return step(state, cfg, tp_arg, key)

    def sharded_step(state: SimState, key: jax.Array) -> SimState:
        # commit the key before dispatch: the jit fast path was observed
        # re-sharding an uncommitted PRNG key with a STATE leaf's spec
        return _step(state, tp, jax.device_put(key, key_sh))

    # stale-id protection, both directions: the dispatch cache keys on
    # function identity, and a garbage-collected closure's id() can be
    # REUSED by the next factory call, hitting a stale executable.
    # (a) pin _step to the returned wrapper — a STILL-REFERENCED step can
    #     never be evicted out from under its caller (the old deque's
    #     65th-call hazard, round-4 advisor finding);
    # (b) the bounded deque ALSO retains the last 64 steps so a
    #     drop-and-recreate config sweep (wrapper rebound each iteration)
    #     cannot recycle a dead closure's id into a live cache entry.
    sharded_step._step = _step
    _LIVE_STEPS.append(_step)
    sharded_step.lower = lambda st, k: _step.lower(
        st, tp, jax.device_put(k, key_sh))
    return sharded_step


def make_sharded_run_keys(mesh: Mesh, cfg: SimConfig, tp: TopicParams,
                          telemetry: bool = False):
    """jit a whole chunk — ``lax.scan`` of the sharded step over explicit
    per-tick keys — with the peer-sharded in/out state, the multi-host
    execution unit (parallel/multihost.py drives supervised chunks through
    this instead of ``engine.run_keys``, whose unsharded trace would lower
    the halo routes away). Same key discipline as ``engine.run_keys``:
    the caller pre-splits one master key and scans contiguous windows, so
    the chunked sharded trajectory is bit-identical to the single-scan
    unsharded one (tests/test_sharding.py, tests/test_multihost.py).

    ``telemetry=True`` is the sharded flavor of the streaming-telemetry
    lane (sim/telemetry.py): the scan stacks per-tick ``HealthRecord``
    aggregates whose reductions the SPMD partitioner lowers over the
    same peer sharding as the step (cross-shard sums become the scan's
    collectives), emitted REPLICATED — every rank holds the full ``[C]``
    record buffer, so rank 0 can journal without any extra gather. The
    runner then returns ``(state, HealthRecord)``."""
    from ..sim.engine import step
    from ..sim.telemetry import health_record
    from .kernel_context import kernel_mesh

    if cfg.sharded_route not in ("replicated", "halo"):
        raise ValueError(f"unknown sharded_route {cfg.sharded_route!r}; "
                         "expected 'replicated' or 'halo'")
    shardings = state_shardings(mesh, cfg)
    repl = NamedSharding(mesh, P())         # keys and tp both replicate
    tp_sh = jax.tree.map(lambda _: repl, tp)
    peer_axes = tuple(ax for ax in (DCN_AXIS, PEER_AXIS)
                      if ax in mesh.axis_names)
    # health aggregates replicate (repl is a pytree PREFIX spec for the
    # whole HealthRecord subtree)
    out_sh = (shardings, repl) if telemetry else shardings

    # tp rides as a traced argument, not a closure, for the same AOT/
    # dispatch-agreement reason documented on make_sharded_step
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, repl), out_shardings=out_sh)
    def _run(state: SimState, tp_arg: TopicParams, keys: jax.Array):
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor):
            def body(carry, k):
                nxt = step(carry, cfg, tp_arg, k)
                return nxt, health_record(nxt, cfg, tp_arg) \
                    if telemetry else None
            out, health = jax.lax.scan(body, state, keys)
        return (out, health) if telemetry else out

    def sharded_run_keys(state: SimState, keys: jax.Array,
                         tp_arg: TopicParams | None = None):
        # tp is a traced argument of the compiled scan, so a caller may
        # swap it per call (the supervisor run_fn hook hands one) without
        # invalidating the executable; default is the build-time tp
        return _run(state, tp if tp_arg is None else tp_arg,
                    jax.device_put(keys, repl))

    # same stale-id protection as make_sharded_step
    sharded_run_keys._run = _run
    _LIVE_STEPS.append(_run)
    sharded_run_keys.lower = lambda st, keys: _run.lower(
        st, tp, jax.device_put(keys, repl))
    return sharded_run_keys


from collections import deque                                  # noqa: E402

_LIVE_STEPS: deque = deque(maxlen=64)
