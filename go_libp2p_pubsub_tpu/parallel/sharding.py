"""Peer-axis sharding over a device mesh.

The scaling axis of this framework is the peer dimension (SURVEY.md §5.7):
all [N, ...] state shards along a 1-D ``peers`` mesh axis the way sequence-
parallel schemes shard the sequence axis. Cross-shard mesh edges surface as
gathers over the neighbor table; under jit's SPMD partitioner those lower to
XLA collectives riding ICI (the TPU-native replacement for the reference's
libp2p streams, SURVEY.md §2.3).

The XLA-formulation kernels need no shard_map: annotate in/out shardings and
let the compiler insert all_gathers/collective-permutes for the (sparse,
Dhi-bounded) cross-shard edges. The Pallas kernels DO — the partitioner
cannot split an opaque pallas_call — so ``make_sharded_step`` activates
``kernel_context.kernel_mesh`` while tracing and the kernel dispatch sites
shard_map themselves (tables replicated, receiver rows local).
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.config import SimConfig, TopicParams
from ..sim.state import SimState

PEER_AXIS = "peers"
DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (PEER_AXIS,))


def make_mesh_2d(n_hosts: int, devices=None) -> Mesh:
    """A (dcn, peers) mesh for multi-host runs: the peer axis shards over
    BOTH axes (hosts-major), so a contiguous block of peers lives on each
    host and the bulk of the per-hop exchange — neighbor-plane all-gathers
    between chips of one host — rides ICI, with only the host-boundary
    slices crossing DCN. This is the layout SURVEY.md §2.3 prescribes as
    the stand-in for the reference's per-connection streams (comm.go:44-191)
    scaled past one host."""
    devices = devices if devices is not None else jax.devices()
    devices = np.array(devices)
    assert devices.size % n_hosts == 0, \
        f"{devices.size} devices do not split over {n_hosts} hosts"
    return Mesh(devices.reshape(n_hosts, -1), (DCN_AXIS, PEER_AXIS))


def state_shardings(mesh: Mesh, cfg: SimConfig) -> SimState:
    """A SimState-shaped pytree of NamedShardings: peer-major arrays shard on
    axis 0, the global message table replicates, scalars replicate."""
    n = cfg.n_peers
    # on a 2-D (dcn, peers) mesh the peer axis shards over both axes,
    # hosts-major (see make_mesh_2d)
    peer_axes = (DCN_AXIS, PEER_AXIS) if DCN_AXIS in mesh.axis_names \
        else PEER_AXIS

    def spec_for(leaf_name: str, ndim: int, leading_n: bool):
        if leading_n:
            return NamedSharding(mesh, P(peer_axes, *([None] * (ndim - 1))))
        return NamedSharding(mesh, P(*([None] * ndim)))

    # field -> (ndim, leading axis is N)
    layout = dict(
        tick=(0, False), neighbors=(2, True), connected=(2, True),
        outbound=(2, True), reverse_slot=(2, True), subscribed=(2, True),
        nbr_subscribed=(3, True), disconnect_tick=(2, True),
        direct=(2, True), ip_group=(1, True), app_score=(1, True),
        malicious=(1, True),
        mesh=(3, True), fanout=(3, True), fanout_lastpub=(2, True),
        backoff=(3, True), graft_tick=(3, True), mesh_active=(3, True),
        first_message_deliveries=(3, True), mesh_message_deliveries=(3, True),
        mesh_failure_penalty=(3, True), invalid_message_deliveries=(3, True),
        behaviour_penalty=(2, True),
        gater_validate=(1, True), gater_throttle=(1, True),
        gater_last_throttle=(1, True), gater_deliver=(2, True),
        gater_duplicate=(2, True), gater_ignore=(2, True),
        gater_reject=(2, True),
        msg_topic=(1, False),
        msg_publish_tick=(1, False), msg_invalid=(1, False),
        msg_ignored=(1, False), msg_publisher=(1, False),
        have=(2, True), deliver_tick=(2, True), deliver_from=(2, True),
        iwant_pending=(2, True), delivered_total=(0, False),
        halo_overflow=(0, False), fault_flags=(0, False),
    )
    assert set(layout) == set(SimState._fields), "layout drifted from SimState"
    assert n % mesh.devices.size == 0, \
        f"n_peers {n} must divide the {mesh.devices.size}-device mesh"
    return SimState(**{f: spec_for(f, nd, ln) for f, (nd, ln) in layout.items()})


def shard_state(state: SimState, mesh: Mesh, cfg: SimConfig) -> SimState:
    shardings = state_shardings(mesh, cfg)
    return jax.tree.map(jax.device_put, state, shardings)


def make_sharded_step(mesh: Mesh, cfg: SimConfig, tp: TopicParams):
    """jit the full network step with explicit peer-sharded in/out state.

    Entering :func:`kernel_context.kernel_mesh` while the step traces makes
    the Pallas kernel dispatch sites (ops/permgather, ops/hopkernel) wrap
    themselves in shard_map — without it the SPMD partitioner could only
    replicate the pallas_calls (full-size kernel on every device). The
    XLA-formulation paths ignore the context and auto-partition as before.
    """
    from ..sim.engine import step
    from .kernel_context import kernel_mesh

    if cfg.sharded_route not in ("replicated", "halo"):
        raise ValueError(f"unknown sharded_route {cfg.sharded_route!r}; "
                         "expected 'replicated' or 'halo'")
    shardings = state_shardings(mesh, cfg)
    key_sh = NamedSharding(mesh, P())
    repl = NamedSharding(mesh, P())
    tp_sh = jax.tree.map(lambda _: repl, tp)
    peer_axes = tuple(ax for ax in (DCN_AXIS, PEER_AXIS)
                      if ax in mesh.axis_names)

    # tp is passed as a traced ARGUMENT, not closed over: closure arrays
    # become hoisted constants, and round 4 hit a jit AOT/dispatch
    # disagreement about them ("compiled for 60 inputs but called with
    # 41" whenever a .lower().compile() of the program preceded a regular
    # dispatch anywhere in the process). With no captured arrays the
    # lowered parameter list equals the explicit arguments and both
    # execution paths agree.
    @partial(jax.jit,
             in_shardings=(shardings, tp_sh, key_sh), out_shardings=shardings)
    def _step(state: SimState, tp_arg: TopicParams,
              key: jax.Array) -> SimState:
        with kernel_mesh(mesh, peer_axes, route=cfg.sharded_route,
                         capacity_factor=cfg.halo_capacity_factor):
            return step(state, cfg, tp_arg, key)

    def sharded_step(state: SimState, key: jax.Array) -> SimState:
        # commit the key before dispatch: the jit fast path was observed
        # re-sharding an uncommitted PRNG key with a STATE leaf's spec
        return _step(state, tp, jax.device_put(key, key_sh))

    # stale-id protection, both directions: the dispatch cache keys on
    # function identity, and a garbage-collected closure's id() can be
    # REUSED by the next factory call, hitting a stale executable.
    # (a) pin _step to the returned wrapper — a STILL-REFERENCED step can
    #     never be evicted out from under its caller (the old deque's
    #     65th-call hazard, round-4 advisor finding);
    # (b) the bounded deque ALSO retains the last 64 steps so a
    #     drop-and-recreate config sweep (wrapper rebound each iteration)
    #     cannot recycle a dead closure's id into a live cache entry.
    sharded_step._step = _step
    _LIVE_STEPS.append(_step)
    sharded_step.lower = lambda st, k: _step.lower(
        st, tp, jax.device_put(k, key_sh))
    return sharded_step


from collections import deque                                  # noqa: E402

_LIVE_STEPS: deque = deque(maxlen=64)
