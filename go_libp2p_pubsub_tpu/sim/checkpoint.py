"""Exact network checkpoints: save/restore the whole SimState pytree.

The reference has no checkpoint/resume — all router state is soft and
rebuilt from the network (SURVEY.md §5.4); the only deliberate persistence
is in-RAM score retention (score.go:611-644). The simulator gains what the
reference lacks: the entire N-peer network is one pytree of arrays, so a
checkpoint is an orbax save and resume is bit-exact — a paused 100k-peer
simulation continues as if never stopped (tests/test_checkpoint.py proves
trajectory equality).

orbax is the primary backend; a .npz fallback keeps the feature alive in
minimal environments.

Validation contract: ``restore`` checks every restored array against the
``like`` pytree and raises ``ValueError`` naming the offending field on a
shape/dtype mismatch — a checkpoint from a different config silently
resuming (wrong N/K/T/msg_window broadcasting or crashing deep inside the
step) was the round-5 class of failure this guards. Fields genuinely
MISSING from an old checkpoint still restore from ``like`` (the documented
forward-compat path for fields added later, e.g. provenance buffers or
``fault_flags``). ``save(path, state, cfg=...)`` additionally stamps a
config fingerprint in a ``<path>.fingerprint`` sidecar; ``restore(...,
cfg=...)`` compares and raises on mismatch (a missing sidecar — an older
checkpoint — is tolerated).

Crash atomicity: ``save`` writes BOTH artifacts (orbax dir / .npz payload
and the fingerprint sidecar) to temp paths and renames them into place,
payload first — a kill mid-save leaves either the previous checkpoint
intact or nothing at the target path, never a torn payload that
``restore`` half-accepts. A checkpoint that IS torn some other way
(truncated file, gutted orbax dir) raises :class:`CheckpointCorrupt`
(a ``ValueError``) rather than surfacing a backend internal — the
supervisor (sim/supervisor.py) catches it and falls back to the previous
checkpoint.
"""

from __future__ import annotations

import glob
import hashlib
import os
import shutil

import jax
import jax.numpy as jnp
import numpy as np

from .state import SimState


class CheckpointCorrupt(ValueError):
    """The checkpoint payload is unreadable (torn write, truncation,
    missing files) — distinct from a *mismatched* checkpoint (plain
    ``ValueError``), though both are ValueErrors so existing callers'
    handling is unchanged."""

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    _HAVE_ORBAX = False


def config_fingerprint(cfg, fleet: int | None = None) -> str:
    """Deterministic digest of a SimConfig: the frozen dataclass repr
    enumerates every field in definition order (including the fault plan),
    so any knob drift changes the digest. ``fleet`` folds a leading
    fleet/batch axis (sim/fleet.py stacks B member states) into the
    digest: a B=4 fleet journal must never resume into a B=8 run — the
    mismatch is caught HERE by name, not as a shape crash deep in the
    scan. ``fleet=None`` (an unbatched state) reproduces the historical
    digest, so existing checkpoints stay valid."""
    base = repr(cfg)
    if fleet is not None:
        base += f"|fleet_axis B={int(fleet)}"
    return hashlib.sha256(base.encode()).hexdigest()


def fleet_axis(state) -> int | None:
    """Leading fleet/batch axis of a SimState, or None when unbatched.
    ``state.tick`` is the discriminator: scalar for a single simulation,
    [B] for a fleet-stacked state (sim/fleet.py)."""
    tick = state.tick
    return int(np.shape(tick)[0]) if np.ndim(tick) >= 1 else None


def _sidecar(path: str) -> str:
    return path + ".fingerprint"


def _named_leaves(state) -> list:
    """``[(name, leaf), ...]`` for a checkpointable state pytree, in the
    SAME order ``jax.tree.flatten`` yields leaves — so a restore can
    rebuild any state via ``jax.tree.unflatten``. SimState names its
    fields; a BucketedState (sim/bucketed.py) names ``g.<field>``, then
    per bucket ``e<b>.<field>``, then ``rev<b>`` — flat, collision-free
    npz keys that also make a torn-field error self-describing."""
    if isinstance(state, SimState):
        return list(zip(SimState._fields, state))
    from .bucketed import BucketedState, EdgePlanes
    if isinstance(state, BucketedState):
        out = [(f"g.{f}", v) for f, v in zip(SimState._fields, state.g)]
        for b, ep in enumerate(state.e):
            out.extend((f"e{b}.{f}", v)
                       for f, v in zip(EdgePlanes._fields, ep))
        out.extend((f"rev{b}", r) for b, r in enumerate(state.rev))
        return out
    raise TypeError(
        f"checkpoint: unsupported state type {type(state).__name__}; "
        "expected SimState or BucketedState")


def _bucket_string(cfg) -> str:
    """Canonical clear-text form of a degree-bucket partition for the
    sidecar: ``"512x64,512x32,..."`` (rows x k_ceil, hubs first)."""
    bks = getattr(cfg, "degree_buckets", None)
    if bks is None:
        return ""
    return ",".join(f"{int(r)}x{int(k)}" for r, k in bks)


def _replace_path(tmp: str, final: str) -> None:
    """Atomically move ``tmp`` into place at ``final`` (file or dir)."""
    if os.path.isdir(final) and not os.path.islink(final):
        shutil.rmtree(final)
    elif os.path.lexists(final):
        os.remove(final)
    os.replace(tmp, final)


def save(path: str, state: SimState, cfg=None, processes=None,
         extra: dict | None = None) -> None:
    """Write a checkpoint directory (orbax) or .npz file (fallback); with
    ``cfg``, stamp its fingerprint in a sidecar for restore to verify.

    Crash-atomic (module docstring): payload and sidecar each land via
    temp-path + rename, payload before sidecar, so an interrupted save
    can never leave a torn checkpoint at ``path``.

    ``processes`` stamps the process count the (gathered, host-complete)
    state was taken at as a clear ``processes=P`` sidecar line — default
    ``jax.process_count()``. Deliberately NOT part of the digest: a
    multihost checkpoint is host-complete, so restoring it at a DIFFERENT
    process count is the supported elastic-resume path (each rank slices
    its own rows with the CURRENT count — parallel/multihost.py
    local_rows_state); the line is provenance for dashboards and the
    supervisor's ``resume_elastic`` marker, not a refusal key."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp{os.getpid()}"
    # sweep stale temps from killed saves — ANY pid's, not just ours: a
    # kill-resume cycle runs under a fresh pid each time, and orphaned
    # full-state payloads would otherwise accumulate unboundedly across a
    # long unattended session (one checkpoint path has one writer at a
    # time, so the sweep cannot race a live save)
    for stale in glob.glob(f"{path}.tmp*") + \
            glob.glob(f"{_sidecar(path)}.tmp*"):
        if os.path.isdir(stale):
            shutil.rmtree(stale, ignore_errors=True)
        else:
            try:
                os.remove(stale)
            except OSError:
                pass
    # multi-process runs take the npz branch even with orbax available:
    # orbax's save path runs its own cross-host sync barriers, and the
    # rank-0-ONLY write discipline (parallel/multihost.py — the state is
    # already gathered host-complete, only the coordinator writes) would
    # deadlock a collective that the other ranks never enter
    # bucketed states always take the npz branch too: orbax's
    # StandardCheckpointer round-trips the nested namedtuple as dicts and
    # the missing-field fallback below is SimState-specific — the flat
    # _named_leaves npz layout is the bucketed format
    if _HAVE_ORBAX and not path.endswith(".npz") \
            and jax.process_count() == 1 and isinstance(state, SimState):
        with ocp.StandardCheckpointer() as ckpt:
            ckpt.save(tmp, jax.device_get(state))
        # the context exit waits out any async write; only a fully
        # materialized payload ever reaches the final name
        _replace_path(tmp, path)
    else:
        arrs = {f: np.asarray(v) for f, v in _named_leaves(state)}
        final = path if path.endswith(".npz") else path + ".npz"
        with open(tmp, "wb") as fh:      # file handle: savez can't rename it
            np.savez_compressed(fh, **arrs)
            fh.flush()
            os.fsync(fh.fileno())
        _replace_path(tmp, final)
    if cfg is not None:
        fleet = fleet_axis(state)
        side_tmp = f"{_sidecar(path)}.tmp{os.getpid()}"
        with open(side_tmp, "w") as f:
            f.write(config_fingerprint(cfg, fleet=fleet) + "\n")
            if fleet is not None:
                # the fleet axis travels in clear alongside the digest so
                # a mismatched resume can be REJECTED BY NAME (restore
                # below) instead of as an anonymous digest mismatch
                f.write(f"fleet={fleet}\n")
            # storage precision travels in clear for the same reason: a
            # compact checkpoint restored under f32 (or vice versa) is a
            # layout change, not a knob tweak — name it
            precision = getattr(cfg, "state_precision", None)
            if precision is not None:
                f.write(f"state_precision={precision}\n")
            # the degree-bucket partition travels in clear: a bucketed
            # checkpoint's planes only mean anything under the SAME
            # partition, and an elastic P -> P' resume must be refused BY
            # NAME when the partitions drifted (restore below)
            bks = _bucket_string(cfg)
            if bks:
                f.write(f"degree_buckets={bks}\n")
            p = jax.process_count() if processes is None else int(processes)
            f.write(f"processes={p}\n")
            # caller-supplied clear lines (sidecar_meta parses any
            # key=value) — provenance, never a restore refusal. The live
            # command plane stamps its consumed ``stream_offset`` here:
            # the exactly-once ingestion cursor a relaunch resumes from
            for k, v in (extra or {}).items():
                f.write(f"{k}={v}\n")
            f.flush()
            os.fsync(f.fileno())
        _replace_path(side_tmp, _sidecar(path))


def sidecar_meta(path: str) -> dict:
    """Parse a checkpoint's fingerprint sidecar into
    ``{"fingerprint": <digest>, <key>: <value>, ...}`` (the clear
    ``fleet=`` / ``state_precision=`` / ``processes=`` lines); ``{}`` when
    no sidecar exists. Read-only provenance — restore() does its own
    verification."""
    side = _sidecar(os.path.abspath(path))
    if not os.path.exists(side):
        return {}
    with open(side) as f:
        lines = f.read().split()
    out: dict = {}
    if lines:
        out["fingerprint"] = lines[0]
    out.update(ln.split("=", 1) for ln in lines[1:] if "=" in ln)
    return out


def _dtype_of(x):
    """dtype WITHOUT materializing values: a multi-process ``like`` leaf
    spans non-addressable devices and cannot be fetched — but shape/dtype
    are metadata."""
    dt = getattr(x, "dtype", None)
    return np.dtype(dt) if dt is not None else np.asarray(x).dtype


def _validate(field: str, got, want) -> None:
    g_shape, g_dtype = tuple(np.shape(got)), _dtype_of(got)
    w_shape, w_dtype = tuple(np.shape(want)), _dtype_of(want)
    if g_shape != w_shape or g_dtype != w_dtype:
        raise ValueError(
            f"checkpoint field {field!r}: restored {g_dtype}{list(g_shape)} "
            f"does not match expected {w_dtype}{list(w_shape)} — the "
            "checkpoint was written under a different config (peer count / "
            "slot capacity / topic count / msg window); pass the matching "
            "`like` state or re-run from scratch")


def restore(path: str, like: SimState, cfg=None) -> SimState:
    """Load a checkpoint; ``like`` supplies the shapes/dtypes (and, for
    sharded states, the target shardings via its arrays). Every restored
    array is validated against ``like`` (module docstring); with ``cfg``,
    the saved config fingerprint is verified too.

    The sidecar's ``processes=`` line is informational, never a refusal:
    a gathered (host-complete) multihost checkpoint restores at ANY
    process count — each rank then re-slices its rows with the CURRENT
    count (the elastic-resume path; see ``save`` and
    ``parallel/multihost.local_rows_state``)."""
    path = os.path.abspath(path)
    if cfg is not None and os.path.exists(_sidecar(path)):
        with open(_sidecar(path)) as f:
            lines = f.read().split()
        stamped = lines[0] if lines else ""
        meta = dict(ln.split("=", 1) for ln in lines[1:] if "=" in ln)
        fleet = fleet_axis(like)
        want = config_fingerprint(cfg, fleet=fleet)
        if stamped != want:
            saved_bks = meta.get("degree_buckets", "")
            want_bks = _bucket_string(cfg)
            if saved_bks != want_bks:
                def _part(s):
                    return f"buckets [{s}]" if s else "the dense layout"
                raise ValueError(
                    f"checkpoint {path!r} bucket-partition mismatch: saved "
                    f"under {_part(saved_bks)} but this run expects "
                    f"{_part(want_bks)} — a bucketed checkpoint only "
                    "resumes under its own bucket partition (realign with "
                    "topology.align_degree_buckets BEFORE the first run, "
                    "not between resumes)")
            saved_fleet = meta.get("fleet")
            if saved_fleet != (None if fleet is None else str(fleet)):
                def _axis(b):
                    return "an unbatched state" if b is None else f"B={b}"
                raise ValueError(
                    f"checkpoint {path!r} fleet-axis mismatch: saved with "
                    f"{_axis(saved_fleet)} but this run expects "
                    f"{_axis(fleet)} — a fleet journal can only resume at "
                    "its own batch size (sim/fleet.py)")
            saved_prec = meta.get("state_precision")
            want_prec = getattr(cfg, "state_precision", None)
            if saved_prec is not None and want_prec is not None \
                    and saved_prec != want_prec:
                raise ValueError(
                    f"checkpoint {path!r} state_precision mismatch: saved "
                    f"under {saved_prec!r} but this run expects "
                    f"{want_prec!r} — the storage layouts differ "
                    "(sim/state.py codecs); resume under the saved "
                    "precision, or round-trip through decode_state/"
                    "encode_state explicitly")
            raise ValueError(
                f"checkpoint {path!r} was saved under a different config "
                f"(fingerprint {stamped[:12]}… != {want[:12]}…); restoring "
                "it under this config would silently mis-resume")
    if _HAVE_ORBAX and os.path.isdir(path) and isinstance(like, SimState):
        with ocp.StandardCheckpointer() as ckpt:
            try:
                try:
                    out = ckpt.restore(path, jax.device_get(like))
                except ValueError:
                    # a checkpoint written before a SimState field existed
                    # fails the full-target structure match ("Dict key
                    # mismatch") — restore as-saved (orbax stores the
                    # namedtuple as a field-keyed dict) and fill the missing
                    # fields from ``like``, exactly like the npz branch
                    raw = ckpt.restore(path)
                    out = SimState(*[raw[f] if f in raw else getattr(like, f)
                                     for f in SimState._fields])
            except ValueError:
                raise                   # mismatch diagnostics pass through
            except Exception as e:
                # gutted dir / torn metadata: a clean, catchable error
                # instead of an orbax internal (supervisor fallback path)
                raise CheckpointCorrupt(
                    f"checkpoint {path!r} is unreadable (torn or "
                    f"incomplete write): {type(e).__name__}: {e}") from e
        for f, got, want in zip(SimState._fields, out, like):
            _validate(f, got, want)
        return SimState(*[jnp.asarray(x) for x in out])
    npz_path = path if path.endswith(".npz") else path + ".npz"
    try:
        npz = np.load(npz_path)
    except Exception as e:
        # zipfile.BadZipFile / EOFError / OSError on a truncated or missing
        # file — normalize to the one catchable corruption error
        raise CheckpointCorrupt(
            f"checkpoint {npz_path!r} is unreadable (torn or incomplete "
            f"write): {type(e).__name__}: {e}") from e
    # fields added after a checkpoint was written restore from ``like``
    # (new fields carry inert defaults, e.g. provenance buffers at -1);
    # fields PRESENT must match ``like`` exactly — no silent acceptance.
    # _named_leaves walks ``like`` in jax.tree.flatten order, so the same
    # loop restores SimState and BucketedState checkpoints alike
    vals = []
    for f, want in _named_leaves(like):
        if f in npz.files:
            try:
                arr = npz[f]
            except ValueError:
                raise
            except Exception as e:      # member truncated mid-archive
                raise CheckpointCorrupt(
                    f"checkpoint {npz_path!r} field {f!r} is unreadable "
                    f"(torn write): {type(e).__name__}: {e}") from e
            _validate(f, arr, want)
            vals.append(jnp.asarray(arr))
        else:
            vals.append(want)
    return jax.tree.unflatten(jax.tree.structure(like), vals)
