"""Exact network checkpoints: save/restore the whole SimState pytree.

The reference has no checkpoint/resume — all router state is soft and
rebuilt from the network (SURVEY.md §5.4); the only deliberate persistence
is in-RAM score retention (score.go:611-644). The simulator gains what the
reference lacks: the entire N-peer network is one pytree of arrays, so a
checkpoint is an orbax save and resume is bit-exact — a paused 100k-peer
simulation continues as if never stopped (tests/test_checkpoint.py proves
trajectory equality).

orbax is the primary backend; a .npz fallback keeps the feature alive in
minimal environments.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .state import SimState

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    _HAVE_ORBAX = False


def save(path: str, state: SimState) -> None:
    """Write a checkpoint directory (orbax) or .npz file (fallback)."""
    path = os.path.abspath(path)
    if _HAVE_ORBAX and not path.endswith(".npz"):
        with ocp.StandardCheckpointer() as ckpt:
            ckpt.save(path, jax.device_get(state))
        return
    arrs = {f: np.asarray(v) for f, v in zip(SimState._fields, state)}
    np.savez_compressed(path if path.endswith(".npz") else path + ".npz",
                        **arrs)


def restore(path: str, like: SimState) -> SimState:
    """Load a checkpoint; ``like`` supplies the shapes/dtypes (and, for
    sharded states, the target shardings via its arrays)."""
    path = os.path.abspath(path)
    if _HAVE_ORBAX and os.path.isdir(path):
        with ocp.StandardCheckpointer() as ckpt:
            out = ckpt.restore(path, jax.device_get(like))
        return SimState(*[jnp.asarray(x) for x in out])
    npz = np.load(path if path.endswith(".npz") else path + ".npz")
    # fields added after a checkpoint was written restore from ``like``
    # (new fields carry inert defaults, e.g. provenance buffers at -1)
    return SimState(*[jnp.asarray(npz[f]) if f in npz.files else getattr(like, f)
                      for f in SimState._fields])
