"""Supervised execution plane: chunked scans with checkpoints, a
wall-clock watchdog, retry/backoff down a degraded-mode ladder, and
replayable crash dumps — run as a latency-hiding pipeline.

PR 4 made the *simulated network* fault-tolerant (FaultPlan + invariant
sentinel); this module makes the *runner itself* fault-tolerant — the
preemption-safe checkpoint/resume + watchdog/backoff shape production
training stacks depend on, applied to long engine runs on scarce,
unattended TPU windows (round 5 lost its record of record to an unguarded
timeout; the protocol itself applies the same shape via PRUNE backoff and
promise timeouts, gossipsub v1.1 hardening).

:func:`supervised_run` wraps ``engine.run`` (or, with ``traced=True``,
``trace_export.run_traced``) as a sequence of chunked scans:

- **bit-identical chunking**: under the default ``key_schedule="host"``
  ONE master key is pre-split into per-tick keys exactly as
  ``engine.run`` does internally, and each chunk scans a contiguous
  window of that key array (``engine.run_keys``); under
  ``key_schedule="fold_in"`` the per-tick keys derive ON DEVICE from the
  master key and the carried absolute tick (``engine.run_window``), so
  no key window ships at all. Either way the chunked trajectory equals
  the single-scan trajectory bit for bit, checkpoints or not, faults or
  not (tests/test_supervisor.py, the core correctness claim).
- **latency-hiding pipeline** (``async_chunks``, default on): JAX arrays
  are futures — dispatch returns immediately and only the *fetch*
  blocks — so chunk k+1's AOT executable launches the moment chunk k's
  dispatch returns, and chunk k's confirmation, telemetry fetch, and
  checkpoint staging happen while k+1 runs on device::

      dispatch k ──► speculate k+1 ──► confirm k ──► fold k in ──► ...
                     (device: k)       (blocks on k)  (writer thread:
                                                       journal + ckpt)

  The watchdog re-anchors each chunk's deadline to its dispatch-complete
  time; any failure of chunk k discards the in-flight k+1 result and
  retries from the last good state — bit-exact retry semantics
  unchanged. A mid-cadence chunk's input state may be DONATED into its
  successor's dispatch (in-place XLA aliasing, parallel/compile_plan.py
  owns the flavors); retries that land on a donated input silently
  replay the already-confirmed gap from the last undonated anchor with
  the same keys. Checkpoint serialization, journal encode+fsync, and
  terminal notes run on ONE bounded-queue writer thread off the critical
  path (``writer_queue``); a ``drain()`` barrier at window end, failure,
  and KeyboardInterrupt keeps the crash-atomicity guarantees — a chunk
  is journaled/checkpointed only after its device result was confirmed
  good. Traced and ``invariant_mode="raise"`` chunks are host-blocking
  calls with nothing to overlap: they keep the fully synchronous
  discipline (which ``async_chunks=False`` forces everywhere — the
  positive control bench.py measures).
- **checkpoints**: every ``checkpoint_every_ticks`` (default: every chunk
  boundary) the state lands in ``checkpoint_dir`` through the
  crash-atomic ``sim/checkpoint.save`` with the caller's config
  fingerprint stamped; a re-invocation resumes from the newest checkpoint
  that restores cleanly, falling back past torn ones
  (``CheckpointCorrupt``).
- **watchdog**: each chunk's dispatch runs under a wall-clock
  ``deadline_s`` in a worker thread, and its confirmation (the real
  sync-by-value fetch) runs under the remainder of that budget
  re-anchored to the dispatch-complete time; an overrun abandons the
  work (device work cannot be cancelled — the result is discarded) and
  counts as a transient failure.
- **retry + degraded-mode ladder**: transient failures back off
  exponentially and escalate — first ``hop_mode``/``edge_gather_mode``
  fall back to the conservative XLA formulations (bit-identical by the
  mode-parity suites), then the chunk size halves down to
  ``min_chunk_ticks`` — before giving up.
- **crash dumps**: an unrecoverable failure (retries exhausted, or an
  ``invariant_mode="raise"`` checkify trip, which is never retried —
  the trajectory itself is poisoned) writes the last-good checkpoint,
  the failing window's per-tick keys, the config fingerprint, and the
  decoded ``fault_flags`` to a crash directory, then raises
  :class:`SupervisorCrash`. ``scripts/replay_crash.py`` re-runs exactly
  that window from the dump with invariants raised. Registered trace
  sinks get ``hard_flush()``ed (flush + fsync) on every failure so a
  crashed traced run leaves a readable partial trace.

Env knobs (``SupervisorConfig.from_env``): ``GRAFT_CHUNK_TICKS``,
``GRAFT_DEADLINE_S``, ``GRAFT_CRASH_DIR``, ``GRAFT_CHECKPOINT_DIR``,
``GRAFT_HEALTH_STREAM``, ``GRAFT_ASYNC_CHUNKS`` (``0`` disables the
pipeline), ``GRAFT_WRITER_QUEUE``, ``GRAFT_VERDICT_POLICY`` (the live
contract-verdict FAIL response: journal | snapshot | abort).

The fleet plane (sim/fleet.py) builds its batched-run supervision on the
SAME primitives — ``SupervisorConfig``/``SupervisorReport``, the
``_with_deadline`` watchdog, the ``_degrade`` ladder, and the
checkpoint listing/pruning helpers — so a fleet window and a single-run
chunk share one retry/degrade/checkpoint discipline.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import re
import shutil
import threading
import time
from typing import Callable

import jax

from . import checkpoint
from .config import SimConfig, TopicParams
# the one host-transfer utility module (sim/hostio.py): the
# addressable-shard unwrap and the typed-key unwrap used to live here
# and in sim/telemetry.py as separate copies — the names stay importable
# (sim/fleet.py imports both) but are now aliases
from .hostio import fetch_local as _fetch_scalar  # noqa: F401
from .hostio import is_deleted as _is_deleted
from .hostio import key_data as _key_data  # noqa: F401
from .state import SimState

_CKPT_RE = re.compile(r"^ckpt_t(\d+)(?:\.npz)?$")

# confirmation never gets less than this much wall clock, even when the
# writer or speculation ate most of the chunk's re-anchored budget: a
# finished device result fetches in microseconds, so the floor only
# matters when the device is genuinely still running AND the host fell
# behind — and failing the chunk for HOST lateness would retry work the
# device already did
_CONFIRM_GRACE_S = 0.2


class SupervisorCrash(RuntimeError):
    """Unrecoverable supervised-run failure. ``dump_dir`` holds the crash
    dump (last-good checkpoint + crash.json), ``report`` the run log up to
    the failure."""

    def __init__(self, msg: str, dump_dir: str | None = None,
                 report: "SupervisorReport | None" = None):
        super().__init__(msg)
        self.dump_dir = dump_dir
        self.report = report


class ChunkDeadline(RuntimeError):
    """A chunk overran its wall-clock deadline (transient: retried)."""


class VerdictAbort(RuntimeError):
    """A live behavior contract FAILED under ``verdict_policy="abort"``:
    the run tore down cleanly at the chunk boundary that detected the
    breach (checkpoint written, every verdict note drained to the
    journal). ``event`` is the failing verdict event (contract index,
    kind, breach tick, detail), ``report`` the run log up to the
    teardown. Deliberately NOT a SupervisorCrash: nothing malfunctioned
    — the simulated network broke its contract and the supervisor
    responded as configured."""

    def __init__(self, msg: str, event: dict | None = None,
                 report: "SupervisorReport | None" = None):
        super().__init__(msg)
        self.event = event
        self.report = report


@dataclasses.dataclass
class SupervisorConfig:
    """Host-side supervision knobs (NOT jit-static — execution shape only;
    none of these can change the trajectory)."""

    chunk_ticks: int = 64             # ticks per scan dispatch
    deadline_s: float | None = None   # per-chunk wall-clock watchdog
    # separate bound for first-use compilation of a (config, chunk-shape):
    # compile time is not execution time — a steady-state deadline tuned to
    # chunk runtime would otherwise trip on every new shape the ladder
    # introduces and thrash. None = compilation is unbounded.
    compile_deadline_s: float | None = None
    checkpoint_dir: str | None = None
    checkpoint_every_ticks: int = 0   # 0 = at every chunk boundary
    keep_checkpoints: int = 2         # newest N kept; older pruned
    max_retries: int = 4              # consecutive failures before giving up
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_cap_s: float = 30.0
    min_chunk_ticks: int = 1          # ladder floor for chunk shrinking
    crash_dir: str | None = None      # default: $GRAFT_CRASH_DIR or ./graft_crash
    scenario: str | None = None       # sim.scenarios.SCENARIOS key, stamped
    scenario_kwargs: dict | None = None   # into crash.json for replay_crash
    sinks: tuple = ()                 # trace sinks hard_flush()ed on failure
    # injectable for tests/smoke (real backoff sleeps are pointless there)
    sleep: Callable[[float], None] = time.sleep
    # --- latency-hiding pipeline (module docstring) ---
    # double-buffered async dispatch: chunk k+1 launches while chunk k is
    # still on device, and checkpoint/journal writes move to a background
    # writer thread. Failure semantics are unchanged (in-flight work is
    # discarded, retries are bit-exact). Traced and "raise" chunks always
    # run synchronously regardless. Env: GRAFT_ASYNC_CHUNKS=0 disables.
    async_chunks: bool = True
    # bounded writer-queue depth: a full queue blocks the main loop
    # (backpressure — staged checkpoint/journal memory stays bounded
    # instead of growing with device/host skew). Env: GRAFT_WRITER_QUEUE.
    writer_queue: int = 4
    # --- multi-process hooks (parallel/multihost.py) ---
    # custom chunk runner (state, exec_cfg, tp, keys) -> state, replacing
    # engine.run_keys: the multihost launcher dispatches the SHARDED scan
    # (parallel.sharding.make_sharded_run_keys) here, whose trace keeps
    # the halo routes; the degrade ladder still swaps exec_cfg modes, so
    # the runner must honor the config it is handed. With >1 process a
    # chunk failure is FATAL (no rank-local retry/degrade — the ladder
    # cannot be rank-symmetric; see supervised_run): recovery is
    # relaunch-all-ranks + checkpoint resume
    run_fn: Callable | None = None
    # state -> host-complete state for checkpoint/crash writes. COLLECTIVE
    # when set (multihost.gather_state all-gathers non-addressable
    # shards): every process must reach the checkpoint boundary, while
    # only write_files=True processes (rank 0) touch the filesystem.
    # Collectives must stay rank-symmetric, so the gather runs on the
    # MAIN thread at boundaries — only the file serialization that
    # follows it rides the writer thread
    state_to_host: Callable | None = None
    # host-complete state -> this process's sharded state (resume path:
    # every process restores rank 0's checkpoint from the shared
    # filesystem, slices its rows, and re-assembles)
    state_from_host: Callable | None = None
    # False on non-coordinator ranks: checkpoint/crash-dump writes are
    # skipped (rank-0-only write discipline), resume still READS
    write_files: bool = True
    # window-bounded execution: stop cleanly after this many successful
    # chunks (checkpoint written if a dir is set) and return the partial
    # state — run as much as fits a bounded TPU window, resume the SAME
    # (key, n_ticks) schedule next window. None = run to n_ticks.
    max_chunks: int | None = None
    # streaming-telemetry lane (sim/telemetry.py): when set, chunks run
    # with the device-side health reduction ON (engine.run_keys
    # telemetry=True — aggregates stacked on device, ONE fetch per chunk
    # boundary) and every successful chunk's records stream crash-
    # atomically to this fsync'd NDJSON journal, which
    # scripts/dashboard.py tails live. write_files=False ranks compute
    # the (collective) reduction but skip the journal — the multihost
    # rank-0-only write discipline. Env: GRAFT_HEALTH_STREAM=path.
    health_path: str | None = None
    # extra keys stamped into the health journal's run header (JSON-able
    # dict) — adversary scenarios stamp their declared behavior contracts
    # here (sim/adversary.py contracts_to_json) so the dashboard can
    # evaluate the SCENARIO's contracts, not just the schedule defaults
    health_meta: dict | None = None
    # --- distributed resilience plane (parallel/resilience.py) ---
    # RankLiveness (or any object with beat/check): the chunk loop stamps
    # progress beats and polls check() at the pre-dispatch safe point —
    # BEFORE the next chunk's collectives — so a dead peer aborts this
    # rank's window cleanly at a chunk boundary (through the multi-process
    # fail-fast crash path) instead of blocking forever in a gather
    liveness: object | None = None
    # --- live command plane (sim/commands.py) ---
    # a CommandQueue (or multihost BroadcastCommands): each chunk
    # dispatch drains one fixed-shape directive frame at the boundary
    # and injects it through the jitted replay scan before the chunk
    # runs. The consumed stream offset is stamped into every checkpoint
    # sidecar (``stream_offset=``) and the queue is start()ed at the
    # stamped offset on resume — directive application is exactly-once
    # across SIGKILL→relaunch. Frames are cached per chunk_start, so
    # retries re-apply the SAME frame to the SAME pre-apply input
    # (dispatch re-anchors _Pending.src below); speculative-input
    # donation is disabled while a command plane is attached, because a
    # donated-input catch-up replays from keys alone and would lose the
    # injected directives.
    commands: object | None = None
    # --- live contract verdict plane (sim/adversary.py monitors) ---
    # behavior contracts evaluated over the LIVE telemetry stream: each
    # confirmed chunk's rows fold into O(1)-state ContractMonitors on
    # the main thread (host-side — the fold never touches the chip's
    # critical path) and every status transition journals a
    # `contract_verdict` note through the SAME FIFO writer, BEFORE the
    # boundary's checkpoint save. The monitor state rides the checkpoint
    # sidecar (``monitors=``), so a SIGKILL→relaunch re-derives at most
    # the not-yet-checkpointed transitions — whose deterministic ids the
    # journal readers dedup: each verdict lands exactly once, no
    # double-fires, no silently skipped window. Requires the telemetry
    # lane (health_path) — refused by name otherwise.
    contracts: tuple = ()
    # FAIL response policy — never a silent continue, never a retrace:
    #   "journal"  (default) verdict + contract_alarm note; the
    #              dashboard raises a banner off the journaled stream
    #   "snapshot" force an off-cadence checkpoint capturing the breach
    #              state (named note when no checkpoint_dir is set)
    #   "abort"    clean named teardown at the boundary that detected
    #              the breach: checkpoint + verdict_abort note, then
    #              raise VerdictAbort. Env: GRAFT_VERDICT_POLICY.
    verdict_policy: str = "journal"
    # parallel/resilience.ChaosPlan (or any object with fire_verdict):
    # the verdict_kill@TICK drill fires between detecting a transition
    # and journaling its note
    chaos: object | None = None
    # rungs of the degrade ladder applied BEFORE the first chunk. The
    # relaunch supervisor (scripts/mh_supervisor.py) records the agreed
    # rung in its run journal and hands it to every rank via
    # GRAFT_MH_RUNG, so after a relaunch all ranks compile the SAME
    # program — the rank-symmetric form of the ladder that rank-local
    # retry can't provide. Applied to the run's exec_cfg only: checkpoints
    # keep stamping the BASE cfg, so resume across rungs never refuses.
    initial_degrade: int = 0

    @staticmethod
    def from_env(**overrides) -> "SupervisorConfig":
        kw: dict = {}
        if os.environ.get("GRAFT_CHUNK_TICKS"):
            kw["chunk_ticks"] = int(os.environ["GRAFT_CHUNK_TICKS"])
        if os.environ.get("GRAFT_DEADLINE_S"):
            kw["deadline_s"] = float(os.environ["GRAFT_DEADLINE_S"])
        if os.environ.get("GRAFT_CRASH_DIR"):
            kw["crash_dir"] = os.environ["GRAFT_CRASH_DIR"]
        if os.environ.get("GRAFT_CHECKPOINT_DIR"):
            kw["checkpoint_dir"] = os.environ["GRAFT_CHECKPOINT_DIR"]
        if os.environ.get("GRAFT_HEALTH_STREAM"):
            kw["health_path"] = os.environ["GRAFT_HEALTH_STREAM"]
        if os.environ.get("GRAFT_ASYNC_CHUNKS"):
            kw["async_chunks"] = os.environ["GRAFT_ASYNC_CHUNKS"].lower() \
                not in ("0", "false", "no", "off")
        if os.environ.get("GRAFT_WRITER_QUEUE"):
            kw["writer_queue"] = int(os.environ["GRAFT_WRITER_QUEUE"])
        if os.environ.get("GRAFT_MH_RUNG"):
            kw["initial_degrade"] = int(os.environ["GRAFT_MH_RUNG"])
        if os.environ.get("GRAFT_VERDICT_POLICY"):
            kw["verdict_policy"] = os.environ["GRAFT_VERDICT_POLICY"]
        kw.update(overrides)
        return SupervisorConfig(**kw)


@dataclasses.dataclass
class SupervisorReport:
    """What the supervised run did — chunk counts, the retry/degrade
    trail, checkpoint/resume provenance, and the crash dump path (set only
    when :class:`SupervisorCrash` was raised; reach it via the
    exception's ``report``)."""

    chunks_run: int = 0
    ticks_run: int = 0
    retries: int = 0
    degrade_level: int = 0
    checkpoints: list = dataclasses.field(default_factory=list)
    resumed_from: str | None = None
    resumed_tick: int | None = None
    crash_dump: str | None = None
    events: list = dataclasses.field(default_factory=list)

    def log(self, event: str, **info) -> None:
        self.events.append({"event": event, **info})


def _hard_flush(sinks) -> None:
    for s in sinks:
        try:
            if hasattr(s, "hard_flush"):
                s.hard_flush()
            elif hasattr(s, "flush"):
                s.flush()
        except Exception:
            pass        # the failure path must never mask the failure


def _is_invariant_trip(err: BaseException) -> bool:
    # the checkify message format of sim/invariants.record_flags
    return "invariant violation" in str(err)


def _ckpt_path(ckpt_dir: str, tick: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_t{tick:09d}")


def list_checkpoints(ckpt_dir: str) -> list:
    """Supervisor checkpoints in ``ckpt_dir`` as ``[(path, tick)]``,
    ascending tick. ``path`` is the bare name ``checkpoint.restore``
    accepts for both backends (the ``.npz`` suffix of the fallback is
    stripped)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = {}
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            bare = name[:-4] if name.endswith(".npz") else name
            out[bare] = int(m.group(1))
    return sorted(((os.path.join(ckpt_dir, b), t) for b, t in out.items()),
                  key=lambda pt: pt[1])


def _prune_checkpoints(ckpt_dir: str, keep: int) -> None:
    for path, _tick in list_checkpoints(ckpt_dir)[:-keep or None]:
        for victim in (path, path + ".npz", path + ".fingerprint"):
            try:
                if os.path.isdir(victim):
                    shutil.rmtree(victim)
                elif os.path.lexists(victim):
                    os.remove(victim)
            except OSError:
                pass    # pruning is best-effort; never fail the run for it


def _try_resume(sup: SupervisorConfig, cfg: SimConfig, like: SimState,
                start_tick: int, n_ticks: int,
                report: SupervisorReport) -> tuple:
    """Newest checkpoint in the run's tick window that restores cleanly,
    falling back past torn/mismatched ones; (state, ticks_done)."""
    for path, tick in reversed(list_checkpoints(sup.checkpoint_dir)):
        if not (start_tick < tick <= start_tick + n_ticks):
            continue
        try:
            st = checkpoint.restore(path, like, cfg=cfg)
        except ValueError as e:     # CheckpointCorrupt or mismatch
            report.log("resume_skip", path=path, error=str(e)[:200])
            continue
        if sup.state_from_host is not None:
            # multihost: the checkpoint restores host-complete; every
            # process re-slices its rows and re-assembles the global
            # sharded state (collective — all ranks walk the same
            # shared-filesystem checkpoint list, so they agree). The
            # slice uses the CURRENT process count, so a checkpoint
            # gathered at P processes resumes at P' — elastic resume
            # (checkpoint.py sidecar stamps the count it was taken at)
            saved_p = checkpoint.sidecar_meta(path).get("processes")
            if saved_p is not None and int(saved_p) != jax.process_count():
                report.log("resume_elastic", saved_processes=int(saved_p),
                           processes=jax.process_count())
            st = sup.state_from_host(st)
        done = int(_fetch_scalar(st.tick)) - start_tick
        if done != tick - start_tick:   # name/state tick disagreement
            report.log("resume_skip", path=path,
                       error=f"state tick {done + start_tick} != {tick}")
            continue
        report.resumed_from = path
        report.resumed_tick = tick
        report.log("resume", path=path, tick=tick)
        return st, done
    return like, 0


# the explicit conservative fallback per mode FAMILY. Every mode name the
# engine can carry — including the blocked-onehot/mxu-extras formulations
# and any future/unknown string (which would raise in its resolver and
# land here as a chunk failure) — maps to the same safe floor, so an
# unrecognized mode can never dead-end a retry: the ladder's first rung
# always produces a config that compiles everywhere. NOT "auto": auto
# resolves right back to the failing mode on its home backend.
_CONSERVATIVE_MODES = {"hop_mode": "xla", "edge_gather_mode": "scalar",
                       "selection_mode": "sort"}


def _degrade(exec_cfg: SimConfig, chunk_ticks: int, sup: SupervisorConfig,
             report: SupervisorReport) -> tuple:
    """One rung down the ladder: kernel modes first (pallas-mxu / mxu /
    sort / unknown → the EXPLICIT conservative formulations
    ``_CONSERVATIVE_MODES``, bit-identical per the mode-parity suites),
    then chunk shrinking. Sticky for the rest of the run — a chunk that
    needed the fallback would need it again."""
    current = {f: getattr(exec_cfg, f) for f in _CONSERVATIVE_MODES}
    if current != _CONSERVATIVE_MODES:
        exec_cfg = dataclasses.replace(exec_cfg, **_CONSERVATIVE_MODES)
        report.degrade_level = max(report.degrade_level, 1)
        report.log("degrade", **_CONSERVATIVE_MODES)
    elif chunk_ticks > sup.min_chunk_ticks:
        chunk_ticks = max(sup.min_chunk_ticks, chunk_ticks // 2)
        report.degrade_level += 1
        report.log("degrade", chunk_ticks=chunk_ticks)
    return exec_cfg, chunk_ticks


def _write_crash_dump(sup: SupervisorConfig, cfg: SimConfig,
                      last_good: SimState, keys_chunk, start_tick: int,
                      done: int, this_chunk: int, n_ticks: int,
                      err: BaseException,
                      report: SupervisorReport) -> str:
    from .invariants import FLAGS_VERSION, decode_flags

    base = sup.crash_dir or os.environ.get("GRAFT_CRASH_DIR") \
        or os.path.join(os.getcwd(), "graft_crash")
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    dump = os.path.join(base, f"crash_{stamp}_p{os.getpid()}")
    os.makedirs(dump, exist_ok=True)
    checkpoint.save(os.path.join(dump, "last_good"), last_good, cfg=cfg)
    flags = int(_fetch_scalar(last_good.fault_flags))
    meta = {
        "error": str(err)[:2000],
        "error_type": type(err).__name__,
        "tick_start": start_tick + done,
        "tick_end": start_tick + done + this_chunk,
        "run_start_tick": start_tick,
        "n_ticks": n_ticks,
        "config_fingerprint": checkpoint.config_fingerprint(cfg),
        "invariant_mode": cfg.invariant_mode,
        "fault_flags": flags,
        # bit-layout version of the fault_flags word (sim/invariants.py):
        # decoders REFUSE by name rather than misread a pre-move word's
        # violation bits 8–9 as FAULT_CENSOR/FAULT_WAVE
        "flags_version": FLAGS_VERSION,
        "fault_flag_names": decode_flags(flags),
        # the failing window's exact per-tick keys: replay_crash.py feeds
        # these straight back into engine.run_checked_keys (under
        # key_schedule="fold_in" the window is re-derived on the host —
        # engine.window_keys — so the dump format is schedule-agnostic)
        "window_key_data": _key_data(keys_chunk).tolist(),
        "degrade_level": report.degrade_level,
        "retries": report.retries,
        "scenario": sup.scenario,
        "scenario_kwargs": sup.scenario_kwargs,
    }
    tmp = os.path.join(dump, f"crash.json.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dump, "crash.json"))
    report.log("crash_dump", path=dump)
    return dump


def _with_deadline(fn, deadline_s, what: str, info: dict):
    """Run ``fn`` under a wall-clock deadline on a DAEMON thread. A
    timed-out dispatch cannot be cancelled — the thread is abandoned and
    its result discarded (a retry re-runs the same keys from the same
    last-good state, so nothing is lost but time). Daemon is load-bearing:
    concurrent.futures workers are non-daemon and joined at interpreter
    exit, so a truly wedged dispatch (the axon-tunnel failure class) would
    hang the process at shutdown — after the supervisor already crashed
    out — and burn the rest of an unattended window."""
    if deadline_s is None:
        return fn()
    box: list = []

    def runner():
        try:
            box.append((True, fn()))
        except BaseException as e:      # rethrown on the caller thread
            box.append((False, e))

    # two callers share this watchdog with different info schemas: the
    # single-run supervisor (chunk_start/chunk_ticks) and the fleet plane
    # (window_start/window_ticks, sim/fleet.py) — resolve either
    start = info.get("chunk_start", info.get("window_start", "?"))
    ticks = info.get("chunk_ticks", info.get("window_ticks", "?"))
    t = threading.Thread(target=runner, daemon=True,
                         name=f"graft-chunk-t{start}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        raise ChunkDeadline(
            f"{what} at tick {start} ({ticks} ticks) overran the "
            f"{deadline_s}s deadline")
    ok, val = box[0]
    if not ok:
        raise val
    return val


class _Writer:
    """The off-critical-path writer: checkpoint serialization, journal
    encode+fsync, and terminal notes run as FIFO callables on ONE
    background daemon thread behind a bounded queue, so a chunk boundary
    costs the main loop a queue put instead of a multi-hundred-ms fsync.

    ``threaded=False`` (the synchronous path: ``async_chunks=False``,
    traced, or ``invariant_mode="raise"``) executes every task inline at
    submit — today's write-at-the-site discipline, the bench's positive
    control. The queue bound is backpressure, not loss: a full queue
    blocks ``submit`` (the main loop) until the writer catches up, so
    host memory staged for checkpoints/records stays bounded however far
    the device runs ahead. ``flush`` (the journal's batched fsync,
    HealthJournal.sync) fires whenever the queue runs dry and at every
    :meth:`drain` — crash-atomicity keeps its marker discipline because
    tasks are only ever submitted AFTER their chunk's device result was
    confirmed good. The first task error is stored and re-raised at the
    next submit or drain, where the synchronous path would have raised
    it at the write site."""

    def __init__(self, maxsize: int = 4, flush=None, threaded: bool = True):
        self._flush = flush
        self._threaded = threaded
        self._err: BaseException | None = None
        self._thread = None
        if threaded:
            self._q: queue.Queue = queue.Queue(max(1, int(maxsize)))
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="graft-writer")
            self._thread.start()

    def _loop(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is None:
                    return
                if self._err is None:   # first error wins; skip the rest
                    task()
                    if self._q.empty() and self._flush is not None:
                        self._flush()
            except BaseException as e:
                self._err = e
            finally:
                self._q.task_done()

    def _reraise(self) -> None:
        err, self._err = self._err, None
        raise err

    def submit(self, task: Callable[[], None]) -> None:
        if self._err is not None:
            self._reraise()
        if not self._threaded:
            task()
            if self._flush is not None:
                self._flush()
            return
        while True:     # interruptible bounded put (backpressure point)
            try:
                self._q.put(task, timeout=0.2)
                return
            except queue.Full:
                if self._err is not None:
                    self._reraise()

    def drain(self, raise_errors: bool = True) -> None:
        """Barrier: every submitted task has fully executed — and the
        journal is fsync'd — when this returns."""
        if self._threaded:
            self._q.join()
        if raise_errors and self._err is not None:
            self._reraise()

    def close(self) -> None:
        """Drain and stop the thread. Errors stay stored — close runs in
        ``finally`` and must not mask the in-flight exception; the
        caller's drain/submit already surfaced anything actionable."""
        if not self._threaded or self._thread is None:
            return
        try:
            self._q.put(None)
            self._thread.join(timeout=30.0)
        finally:
            self._thread = None


@dataclasses.dataclass
class _Pending:
    """One dispatched-but-unconfirmed chunk. JAX arrays are futures, so
    ``out`` exists the moment dispatch returns while the device still
    computes; ``tick_ref`` is an independently-buffered copy of
    ``out.tick`` (a tiny jit, fresh output buffer) so confirmation can
    still block on the device result after ``out``'s own buffers were
    donated into the NEXT chunk's dispatch. ``src`` is the input state
    the chunk ran from — the retry anchor, unless a donating speculative
    dispatch consumed it (then ``hostio.is_deleted`` flags it and the
    retry replays from the last undonated anchor instead)."""

    out: SimState
    tick_ref: object
    records: object             # device-stacked HealthRecord | None
    events: list                # traced-mode per-chunk events
    health: list                # traced-mode per-tick host rows
    info: dict
    ticks: int
    src: SimState
    dispatched_at: float        # monotonic stamp at dispatch-complete


_TICK_VIEW = None


def _tick_view(tick):
    global _TICK_VIEW
    if _TICK_VIEW is None:
        # t + 0 (not identity): jit may forward an untouched input buffer,
        # and the whole point is a buffer that survives donation of the
        # parent state
        _TICK_VIEW = jax.jit(lambda t: t + 0)
    return _TICK_VIEW(tick)


def _dispatch_chunk(state: SimState, exec_cfg: SimConfig, tp: TopicParams,
                    keys_chunk, master_key, sup: SupervisorConfig,
                    traced: bool, hook, info: dict, *,
                    donate: bool = False) -> _Pending:
    """Dispatch one chunk attempt WITHOUT waiting for the device: compile
    (its own deadline, parallel/compile_plan.py's AOT cache), run the
    fault-injection hook + enqueue under the run deadline, and capture an
    independent tick future for later confirmation. ``keys_chunk is
    None`` selects the ``key_schedule="fold_in"`` window executable (no
    key window ships — the master key and the carried tick derive them on
    device); ``donate=True`` hands the input state's buffers to XLA (the
    caller guarantees it owns them and will never retry from them
    directly)."""
    telemetry = sup.health_path is not None and not traced
    engine_lane = not traced and exec_cfg.invariant_mode != "raise" \
        and sup.run_fn is None
    exe = None
    if engine_lane:
        from ..parallel import compile_plan
        if keys_chunk is None:
            exe = _with_deadline(
                lambda: compile_plan.engine_window(
                    exec_cfg, state, tp, master_key,
                    int(info["chunk_ticks"]),
                    telemetry=telemetry, donate=donate),
                sup.compile_deadline_s, "compile", info)
        else:
            exe = _with_deadline(
                lambda: compile_plan.engine_chunk(
                    exec_cfg, state, tp, keys_chunk,
                    telemetry=telemetry, donate=donate),
                sup.compile_deadline_s, "compile", info)

    cancelled = threading.Event()
    events: list = []
    health: list = []

    def worker():
        if hook is not None:        # test/smoke fault-injection point
            hook(info)
        if cancelled.is_set():
            # the watchdog already abandoned this attempt: a late dispatch
            # from the orphaned thread must not donate buffers the retry
            # is about to re-run from
            return None
        rec = None
        if sup.run_fn is not None:
            # custom chunk runner (multihost sharded scan); it owns its
            # own compile caching, so first use rides the run deadline.
            # A telemetry-aware runner (scripts/run_multihost.py with a
            # health stream) returns (state, HealthRecord); a plain one
            # returns the state alone — both are honored
            out = sup.run_fn(state, exec_cfg, tp, keys_chunk)
            # EXACT tuple check: SimState itself is a NamedTuple (a tuple
            # subclass), so isinstance would mis-unpack a plain runner's
            # bare state into 2-of-30 fields
            if type(out) is tuple:
                out, rec = out
        elif traced:
            from .trace_export import run_traced
            out, evs = run_traced(state, exec_cfg, tp, None, 0,
                                  health_out=health, keys=keys_chunk)
            events.extend(evs)
        elif exe is not None:
            out = exe(state, tp, master_key if keys_chunk is None
                      else keys_chunk)
            if telemetry:
                out, rec = out
        else:
            # "raise" mode: per-call checkify transform (the debugging
            # path — compile rides the run deadline here)
            from .engine import run_checked_keys
            out = run_checked_keys(state, exec_cfg, tp, keys_chunk,
                                   telemetry=telemetry)
            if telemetry:
                out, rec = out
        return out, rec

    try:
        res = _with_deadline(worker, sup.deadline_s, "chunk", info)
    except BaseException:
        cancelled.set()
        raise
    if res is None:     # defensive: cancelled is only set above, post-raise
        raise ChunkDeadline(f"chunk at tick {info.get('chunk_start', '?')} "
                            "was cancelled before dispatch")
    out, rec = res
    # engine-lane outputs may later be donated into the next dispatch;
    # the traced/"raise"/run_fn lanes never donate, so the leaf itself is
    # a fine confirmation handle there
    tick_ref = _tick_view(out.tick) if engine_lane else out.tick
    return _Pending(out=out, tick_ref=tick_ref, records=rec, events=events,
                    health=health, info=info,
                    ticks=int(info["chunk_ticks"]), src=state,
                    dispatched_at=time.monotonic())


def _confirm(pend: _Pending, sup: SupervisorConfig,
             scale: float = 1.0) -> None:
    """Block until the chunk's device result is real: the sync-by-value
    fetch of the tick future (async dispatch — and the axon tunnel, which
    block_until_ready does not block through — must not let a wedged
    chunk slide past the watchdog). The deadline is the chunk's budget
    RE-ANCHORED to its dispatch-complete time: however long the host
    spent speculating/writing since dispatch comes out of the same
    ``deadline_s`` the synchronous path would have charged, floored at
    ``_CONFIRM_GRACE_S``."""
    deadline = None
    if sup.deadline_s is not None:
        deadline = max(_CONFIRM_GRACE_S, sup.deadline_s * scale
                       - (time.monotonic() - pend.dispatched_at))
    _with_deadline(lambda: _fetch_scalar(pend.tick_ref), deadline,
                   "chunk", pend.info)


def supervised_run(state: SimState, cfg: SimConfig, tp: TopicParams,
                   key, n_ticks: int,
                   sup: SupervisorConfig | None = None, *,
                   traced: bool = False,
                   events_out: list | None = None,
                   health_out: list | None = None,
                   _chunk_hook=None) -> tuple:
    """Run ``n_ticks`` engine ticks under supervision (module docstring).

    Returns ``(final_state, report)``; the final state is bit-identical to
    ``engine.run(state, cfg, tp, key, n_ticks)`` regardless of chunking,
    checkpointing, resumption, retries, degraded modes, or the async
    pipeline (``sup.async_chunks`` — speculation is discarded on any
    failure, so the confirmed carry chain IS the synchronous one). Raises
    :class:`SupervisorCrash` after writing a crash dump when the run
    cannot make progress.

    ``traced=True`` routes chunks through ``trace_export.run_traced``
    (requires ``cfg.record_provenance``); successful chunks append their
    events/health records to ``events_out``/``health_out`` — a failed
    attempt's partial records are discarded, so the collected stream never
    double-counts a retried tick. ``_chunk_hook(info)`` is a test/smoke
    fault-injection point called at the top of every chunk attempt.
    """
    sup = sup or SupervisorConfig.from_env()
    report = SupervisorReport()
    start_tick = int(_fetch_scalar(state.tick))
    fold = cfg.key_schedule == "fold_in"
    # "host": ONE master pre-split, run's exact discipline. "fold_in":
    # keys derive on device inside the scan — nothing to pre-split (crash
    # dumps and the traced/"raise" chunk paths materialize their windows
    # lazily through engine.window_keys).
    all_keys = None if fold else jax.random.split(key, n_ticks)
    # the pipeline lane. Traced and checkified chunks are host-blocking
    # calls with nothing to overlap — they keep the synchronous
    # discipline, writer inline (per-write fsync), no speculation.
    pipelined = bool(sup.async_chunks) and not traced \
        and cfg.invariant_mode != "raise"

    done = 0
    if sup.checkpoint_dir:
        state, done = _try_resume(sup, cfg, state, start_tick, n_ticks,
                                  report)

    # live command plane: begin tailing at the stamped stream offset —
    # the exactly-once cursor a resumed run replays ingestion from
    ingest = sup.commands
    if ingest is not None:
        ing_off = 0
        if report.resumed_from:
            try:
                ing_off = int(checkpoint.sidecar_meta(report.resumed_from)
                              .get("stream_offset") or 0)
            except Exception:
                ing_off = 0
        ingest.start(ing_off)
        report.log("ingest_start", offset=ing_off)

    # live contract verdict plane: O(1)-state monitors folding each
    # confirmed chunk's telemetry rows (SupervisorConfig.contracts). On
    # resume the sidecar's ``monitors=`` token restores the verdict
    # state exactly where the checkpoint left it — every transition the
    # checkpointed run already journaled is past those seq counters, so
    # the relaunch re-derives only the not-yet-durable verdicts.
    monitors = None
    if sup.contracts:
        from .adversary import ContractMonitors
        if sup.verdict_policy not in ("journal", "snapshot", "abort"):
            raise ValueError(
                f"verdict_policy {sup.verdict_policy!r} unknown "
                "(supported: 'journal', 'snapshot', 'abort')")
        if sup.health_path is None and not traced:
            raise ValueError(
                "live contracts need the telemetry lane: set health_path "
                "(GRAFT_HEALTH_STREAM) so chunks carry the rows the "
                "monitors fold")
        monitors = ContractMonitors(tuple(sup.contracts))
        if report.resumed_from:
            tok = checkpoint.sidecar_meta(report.resumed_from) \
                .get("monitors")
            if tok:
                # a contract-set mismatch REFUSES here (from_token) —
                # never a silent verdict reset
                monitors = ContractMonitors.from_token(
                    tok, tuple(sup.contracts))
                report.log("verdict_resume",
                           statuses=list(monitors.statuses))

    def beat(tick: int, chunk: int) -> None:
        # liveness progress stamp (parallel/resilience.RankLiveness): a
        # shared-fs hiccup must never fail the run itself — the beater
        # thread keeps the wall stamp fresh regardless
        if sup.liveness is not None:
            try:
                sup.liveness.beat(tick=tick, chunk=chunk)
            except Exception:
                pass

    beat(start_tick + done, 0)

    # streaming-telemetry journal (sim/telemetry.py): rank-0-only under
    # multihost (write_files); rank>0 still EXECUTES the telemetry lane —
    # the reduction is part of the compiled program all ranks share
    journal = None
    if sup.health_path and sup.write_files:
        from .telemetry import HealthJournal
        # pipelined: ONE fsync per writer-queue drain instead of one per
        # line (the torn-tail-tolerant reader copes either way); inline:
        # the historical per-write fsync
        journal = HealthJournal(sup.health_path,
                                sync_every_write=not pipelined)
        journal.header(cfg, scenario=sup.scenario, start_tick=start_tick,
                       n_ticks=n_ticks, resumed_tick=report.resumed_tick,
                       traced=traced, **(sup.health_meta or {}))

    writer = _Writer(maxsize=sup.writer_queue,
                     flush=journal.sync if journal is not None else None,
                     threaded=pipelined)

    exec_cfg = cfg
    chunk_ticks = max(1, int(sup.chunk_ticks))
    # rank-symmetric relaunch rung (SupervisorConfig.initial_degrade):
    # walk the same ladder a failing single-process run would, before the
    # first dispatch — every rank handed the same GRAFT_MH_RUNG compiles
    # the same program
    for _ in range(max(0, int(sup.initial_degrade))):
        exec_cfg, chunk_ticks = _degrade(exec_cfg, chunk_ticks, sup, report)
    every = sup.checkpoint_every_ticks or chunk_ticks
    next_ckpt = done + every
    failures = 0            # consecutive; reset on every successful chunk
    # retry/dump anchor: the newest confirmed state NEVER handed to a
    # donating dispatch, and the progress offset it holds. Mid-cadence
    # chunk inputs may be donated into their successor; a retry that
    # lands on a deleted input silently replays [anchor_done, done) from
    # here — same keys, bit-exact — to rebuild its starting state.
    anchor_state, anchor_done = state, done
    # multihost: the newest HOST-COMPLETE copy and the tick offset it was
    # gathered at, refreshed at every checkpoint-cadence boundary (where
    # state_to_host — a collective — legally runs on every rank's MAIN
    # thread; NEVER in the error path, where a one-rank failure would
    # deadlock it, and never on the writer thread, where rank-asymmetric
    # timing would misorder collectives). The crash path dumps THIS with
    # its key window re-anchored to the gathered tick.
    last_host_state, last_host_done = None, done
    if sup.state_to_host is not None:
        # run-start gather: a first-window crash still has a dumpable
        # copy (and a run with no checkpoint_dir dumps at all)
        last_host_state = sup.state_to_host(state)

    def chunk_keys(lo: int, hi: int):
        if all_keys is not None:
            return all_keys[lo:hi]
        from .engine import window_keys
        return window_keys(cfg, key, start_tick, lo, hi, n_ticks)

    def dispatch(src, c_done: int, ticks: int, info: dict, donate: bool,
                 hook=_chunk_hook) -> _Pending:
        anchor_src = src
        frame = None
        if ingest is not None and not info.get("catchup"):
            # boundary drain (cached per chunk_start — a retry gets the
            # SAME frame) injected through the jitted replay scan with
            # the BASE cfg as the static key, so the apply compiles once
            # for the whole run, degrade rungs included
            frame = ingest.frame_for(start_tick + c_done, ticks)
            if frame.count:
                src = ingest.apply(src, cfg, tp, frame)
            info["directives"] = int(frame.count)
            info["ingest_frame"] = frame
        keys_chunk = None
        if not (fold and not traced and cfg.invariant_mode != "raise"
                and sup.run_fn is None):
            keys_chunk = chunk_keys(c_done, c_done + ticks)
        p = _dispatch_chunk(src, exec_cfg, tp, keys_chunk, key, sup,
                            traced, hook, info, donate=donate)
        if frame is not None:
            # retries reset the carry to _Pending.src and re-dispatch,
            # which re-applies the cached frame — so the recorded input
            # must be the PRE-apply state or the frame applies twice
            p.src = anchor_src
        return p

    def handle_failure(e: Exception, info: dict, fail_done: int,
                       this_chunk: int, last_good, good_done: int) -> None:
        """The retry/degrade/crash ladder, shared by every failure site
        (fresh dispatch, speculative dispatch, confirmation, catch-up).
        Raises :class:`SupervisorCrash` or records retry bookkeeping and
        sleeps the backoff."""
        nonlocal exec_cfg, chunk_ticks, failures
        _hard_flush(sup.sinks)
        failures += 1
        # a MULTI-PROCESS run fails fast: the retry/degrade ladder is
        # rank-LOCAL, so one rank re-dispatching a degraded (different
        # collective sequence) or re-sized program while its peers sit
        # in the original chunk's collectives would deadlock or pair
        # wrong collectives. Recovery that IS rank-symmetric by
        # construction: crash, relaunch every rank, resume from the
        # last checkpoint (scripts/run_multihost.py).
        multiproc = sup.run_fn is not None and jax.process_count() > 1
        if _is_invariant_trip(e) or multiproc or failures > sup.max_retries:
            # invariant trips are never retried: the trajectory itself
            # is poisoned and would trip again on the same keys
            writer.drain(raise_errors=False)    # pending checkpoints land
            dump = None
            if sup.write_files and sup.state_to_host is None:
                if last_good is None or _is_deleted(last_good):
                    # the failing chunk's direct input was donated away;
                    # the anchor is the newest state a replay can feed —
                    # re-anchor the dumped window to ITS tick so
                    # replay_crash.py advances it into the failure
                    last_good, good_done = anchor_state, anchor_done
                w0, w1 = good_done, fail_done + this_chunk
                dump = _write_crash_dump(sup, cfg, last_good,
                                         chunk_keys(w0, w1), start_tick,
                                         w0, w1 - w0, n_ticks, e, report)
            elif sup.write_files and last_host_state is not None:
                # the gathered copy may be chunks old: same re-anchoring
                w0, w1 = last_host_done, fail_done + this_chunk
                dump = _write_crash_dump(sup, cfg, last_host_state,
                                         chunk_keys(w0, w1), start_tick,
                                         w0, w1 - w0, n_ticks, e, report)
            report.crash_dump = dump
            if journal is not None:
                # the dashboard's post-mortem hook: the journal ends
                # with WHERE it died and which dump replays it
                writer.submit(lambda: journal.note(
                    "crash", tick=start_tick + fail_done, dump=dump,
                    error=str(e)[:200]))
                writer.drain(raise_errors=False)
            raise SupervisorCrash(
                f"supervised run gave up at tick {start_tick + fail_done} "
                f"({failures} consecutive failure(s)); crash dump: "
                f"{dump}", dump_dir=dump, report=report) from e
        report.retries += 1
        report.log("chunk_failed",
                   kind="deadline" if isinstance(e, ChunkDeadline)
                   else "error", error=str(e)[:200], **info)
        exec_cfg, chunk_ticks = _degrade(exec_cfg, chunk_ticks, sup, report)
        delay = min(sup.backoff_cap_s, sup.backoff_base_s
                    * sup.backoff_factor ** (failures - 1))
        report.log("backoff", delay_s=round(delay, 3))
        sup.sleep(delay)

    carry, carry_done = state, done     # confirmed head of the carry chain
    pend: _Pending | None = None
    window_end_hit = False

    def process(p: _Pending) -> None:
        """Fold a CONFIRMED chunk into the run: counters, journal rows
        (through the writer, off the critical path), the boundary
        gather/checkpoint/anchor, window accounting. Main thread only."""
        nonlocal done, carry, carry_done, next_ckpt, failures
        nonlocal anchor_state, anchor_done, last_host_state, last_host_done
        nonlocal window_end_hit
        # dispatch-complete stamp at confirm time: the honest hb/s clock
        # for overlapped runs (wall stamps at ENQUEUE time would credit a
        # chunk before the device ran it — scripts/dashboard.py prefers
        # this field and falls back to wall for old journals)
        done_wall = time.time()
        fr = p.info.pop("ingest_frame", None)
        failures = 0
        done += p.ticks
        carry, carry_done = p.out, done
        report.chunks_run += 1
        report.ticks_run += p.ticks
        beat(start_tick + done, report.chunks_run)
        report.log("chunk_ok", **p.info)
        if events_out is not None:
            events_out.extend(p.events)
        if health_out is not None:
            health_out.extend(p.health)
        if journal is not None:
            # stream the SUCCESSFUL chunk (a failed attempt's records died
            # with its discarded output — the journal never double-counts
            # a retried tick): one fetch of the [C]-stacked device buffer,
            # encoded native-first — on the writer thread, while the next
            # chunk runs
            t0, tks = start_tick + done - p.ticks, p.ticks
            if p.records is not None:
                writer.submit(lambda rec=p.records: journal.append_records(
                    rec, tick_start=t0, ticks=tks, done_wall=done_wall))
            elif traced and p.health:
                writer.submit(lambda rows=list(p.health):
                              journal.append_dicts(
                                  rows, tick_start=t0, ticks=tks,
                                  done_wall=done_wall))
            else:
                # a runner that yields no records (a plain custom
                # run_fn) still marks progress: the dashboard's hb/s
                # and chunk cadence come from these markers
                writer.submit(lambda: journal.note(
                    "chunk", rows=0, tick_start=t0, ticks=tks,
                    done_wall=done_wall))
        if journal is not None and fr is not None:
            # ingest markers ride the writer AFTER the chunk that
            # carried them confirmed — a discarded speculative chunk's
            # refusals/stall markers journal when its retry lands, never
            # twice (the frame cache hands the retry the same notes)
            for kind, meta in fr.notes:
                writer.submit(lambda k=kind, m=dict(meta):
                              journal.note(k, **m))
            writer.submit(lambda f=fr, t=start_tick + done: journal.note(
                "ingest", tick=t, directives=f.count, shed=f.shed,
                shed_total=f.shed_total, refused_total=f.refused_total,
                queue_depth=f.depth, lag_ticks=f.lag, offset=f.offset,
                coasting=f.coasting))
        # ---- live contract verdicts: fold THIS chunk's rows into the
        # monitors (host-side, main thread — the device is already
        # running the next chunk), journal every status transition, and
        # arm the configured FAIL response. Ordering is the exactly-once
        # story: verdict notes enter the FIFO writer BEFORE the
        # boundary's checkpoint save, so a checkpoint whose sidecar says
        # "these verdicts happened" can only exist AFTER their notes
        # were durably journaled; a kill in between re-derives the same
        # transitions (same rows, same seqs → same deterministic ids)
        # and the readers dedup.
        force_ckpt = False
        abort_ev = None
        if monitors is not None:
            rows = None
            if p.records is not None:
                from .telemetry import records_to_rows, rows_to_dicts
                mat, cols = records_to_rows(p.records)
                rows = rows_to_dicts(mat, cols)
            elif traced and p.health:
                rows = list(p.health)
            new_events = monitors.fold_rows(rows) if rows else []
            if done >= n_ticks:
                # TRUE run end only (a bounded window resumes later):
                # the stream is final — pending contracts settle, the
                # pending→fail transitions included
                new_events = new_events + monitors.finalize()
            if new_events and sup.chaos is not None:
                # verdict_kill@TICK drill: die between the breach and
                # its journaled verdict (parallel/resilience.ChaosPlan)
                fire = getattr(sup.chaos, "fire_verdict", None)
                if fire is not None:
                    fire(start_tick + done)
            for ev in new_events:
                report.log("contract_verdict", contract=ev["contract"],
                           kind=ev["kind"], status=ev["status"],
                           tick=ev["tick"], id=ev["id"])
                if journal is not None:
                    # the event's contract kind travels as contract_kind
                    # in the note: "kind" is the note's own type tag
                    writer.submit(lambda e=dict(ev): journal.note(
                        "contract_verdict",
                        **{("contract_kind" if k == "kind" else k): v
                           for k, v in e.items()}))
            failed = [ev for ev in new_events if ev["status"] == "fail"]
            if failed:
                # never a silent continue: every policy leaves a named
                # trail, and only "abort" stops the run
                if sup.verdict_policy == "abort":
                    abort_ev = dict(failed[0])
                    force_ckpt = True   # breach state lands durably
                elif sup.verdict_policy == "snapshot":
                    force_ckpt = True   # off-cadence breach checkpoint
                    if not sup.checkpoint_dir and journal is not None:
                        writer.submit(lambda e=dict(failed[0]):
                                      journal.note(
                            "contract_snapshot_skipped",
                            reason="no checkpoint_dir",
                            contract=e["contract"],
                            contract_kind=e["kind"], tick=e["tick"]))
                elif journal is not None:       # "journal"
                    for ev in failed:
                        writer.submit(lambda e=dict(ev): journal.note(
                            "contract_alarm", policy="journal",
                            contract=e["contract"],
                            contract_kind=e["kind"], tick=e["tick"],
                            id=e["id"], detail=e["detail"]))
        window_end = sup.max_chunks is not None \
            and report.chunks_run >= sup.max_chunks and done < n_ticks
        # a window end is ALWAYS a boundary: the max_chunks contract says
        # "stop cleanly (checkpoint written if a dir is set)" — without
        # this, a stop off the checkpoint cadence would discard the whole
        # window's progress on resume
        at_boundary = done >= next_ckpt or done >= n_ticks or window_end \
            or force_ckpt
        if at_boundary:
            pause_t0 = time.perf_counter()
            # a boundary output is never donated (speculation held its
            # input back, see the donate policy below): it anchors
            # retries/crash dumps and the writer can still fetch it
            anchor_state, anchor_done = p.out, done
            if sup.state_to_host is not None:
                # collective on EVERY rank (multihost.gather_state) at the
                # checkpoint cadence even with no checkpoint_dir — the
                # crash dump's freshness rides this; main thread only
                last_host_state = sup.state_to_host(p.out)
                last_host_done = done
            if sup.checkpoint_dir and sup.write_files:
                to_save = p.out if sup.state_to_host is None \
                    else last_host_state
                path = _ckpt_path(sup.checkpoint_dir, start_tick + done)
                report.checkpoints.append(path)
                report.log("checkpoint", tick=start_tick + done, path=path)

                # exactly-once stamps: the consumed stream offset as of
                # THIS chunk's frame and the verdict-monitor state AFTER
                # this chunk's fold ride the sidecar, so a relaunch
                # replays ingestion AND verdict evaluation from
                # precisely here (the token is whitespace-free base64 —
                # sidecar_meta splits on whitespace)
                extra = {}
                if fr is not None:
                    extra["stream_offset"] = fr.offset
                if monitors is not None:
                    extra["monitors"] = monitors.state_token()
                extra = extra or None

                def save(to_save=to_save, path=path, extra=extra):
                    os.makedirs(sup.checkpoint_dir, exist_ok=True)
                    checkpoint.save(path, to_save, cfg=cfg,
                                    extra=extra)  # crash-atomic
                    _prune_checkpoints(sup.checkpoint_dir,
                                       sup.keep_checkpoints)
                writer.submit(save)
                if journal is not None:
                    writer.submit(lambda t=start_tick + done, pth=path:
                                  journal.note("checkpoint", tick=t,
                                               path=pth))
            next_ckpt = done + every
            # the main-thread stall this boundary cost (bench.py's
            # per-checkpoint visible pause): submits under the async
            # writer, the full serialization+fsync inline otherwise
            report.log("boundary", tick=start_tick + done,
                       pause_ms=round((time.perf_counter() - pause_t0)
                                      * 1e3, 3))
        if window_end:
            # clean window end: the caller resumes the same (key, n_ticks)
            # schedule later — the per-tick keys are a function of BOTH,
            # so a resumed run must re-request the full n_ticks
            report.log("window_end", chunks=report.chunks_run,
                       tick=start_tick + done)
            window_end_hit = True
        if abort_ev is not None:
            # policy "abort": THIS boundary is the safe point — the
            # breach checkpoint was submitted above, the named teardown
            # note carries the failing contract + breach tick, and the
            # drain makes both durable before the raise. Rank-symmetric
            # under multihost: telemetry records are replicated, every
            # rank folded the same rows and raises here together.
            if journal is not None:
                writer.submit(lambda e=dict(abort_ev): journal.note(
                    "verdict_abort", policy="abort",
                    contract=e["contract"], contract_kind=e["kind"],
                    tick=e["tick"], id=e["id"], detail=e["detail"]))
            writer.drain(raise_errors=False)
            report.log("verdict_abort", contract=abort_ev["contract"],
                       kind=abort_ev["kind"], tick=abort_ev["tick"])
            raise VerdictAbort(
                f"contract {abort_ev['contract']} "
                f"({abort_ev['kind']}) FAILED at tick "
                f"{abort_ev['tick']} under verdict_policy='abort': "
                f"{abort_ev['detail']}", event=abort_ev, report=report)

    try:
        while done < n_ticks and not window_end_hit:
            # ---- refill: nothing in flight → dispatch the next chunk
            if pend is None:
                if sup.liveness is not None:
                    # dead-peer poll at the PRE-DISPATCH safe point: the
                    # last place this rank can abort without abandoning a
                    # peer inside a collective it already entered. Routed
                    # through handle_failure, where the multi-process
                    # fail-fast branch writes the crash dump + journal
                    # marker and raises SupervisorCrash — the relaunch
                    # supervisor observes the exit and restarts the group
                    try:
                        sup.liveness.check()
                    except Exception as e:
                        info = {"chunk_start": start_tick + done,
                                "chunk_ticks": 0, "attempt": failures,
                                "liveness": True}
                        handle_failure(e, info, done, 0, carry, done)
                        continue
                if _is_deleted(carry):
                    # a donating dispatch consumed the carry before its
                    # chunk failed: fall back to the undonated anchor
                    carry, carry_done = anchor_state, anchor_done
                if carry_done < done:
                    # replay the already-confirmed gap a retry left when
                    # it landed on a donated input: same keys, bit-exact,
                    # NO journal/report side effects (those ticks are
                    # already counted) and no fault hook (not an attempt)
                    cu_info = {"chunk_start": start_tick + carry_done,
                               "chunk_ticks": done - carry_done,
                               "attempt": failures, "catchup": True,
                               "degrade_level": report.degrade_level}
                    try:
                        cu = dispatch(carry, carry_done, done - carry_done,
                                      cu_info, donate=False, hook=None)
                        _confirm(cu, sup, scale=max(
                            1.0, (done - carry_done) / chunk_ticks))
                    except Exception as e:
                        handle_failure(e, cu_info, carry_done,
                                       done - carry_done, carry, carry_done)
                        continue
                    report.log("catchup", **cu_info)
                    carry, carry_done = cu.out, done
                this_chunk = min(chunk_ticks, n_ticks - done)
                info = {"chunk_start": start_tick + done,
                        "chunk_ticks": this_chunk, "attempt": failures,
                        "degrade_level": report.degrade_level}
                try:
                    pend = dispatch(carry, done, this_chunk, info,
                                    donate=False)
                except Exception as e:
                    handle_failure(e, info, done, this_chunk, carry, done)
                    continue

            # ---- speculate: launch chunk k+1 while chunk k is in flight
            spec: _Pending | None = None
            spec_exc = None
            p_end = done + pend.ticks
            window_after = sup.max_chunks is not None \
                and report.chunks_run + 1 >= sup.max_chunks
            # the input of a boundary-ending chunk stays undonated: its
            # output is the checkpoint/anchor the writer fetches off-path
            p_boundary = p_end >= next_ckpt or p_end >= n_ticks \
                or window_after
            if pipelined and failures == 0 and p_end < n_ticks \
                    and not window_after:
                s_ticks = min(chunk_ticks, n_ticks - p_end)
                s_info = {"chunk_start": start_tick + p_end,
                          "chunk_ticks": s_ticks, "attempt": 0,
                          "degrade_level": report.degrade_level}
                # live contracts can force an off-cadence breach
                # checkpoint at ANY confirm (verdict_policy snapshot/
                # abort), so no chunk output is safe to donate away
                donate = not p_boundary and sup.run_fn is None \
                    and sup.commands is None and not sup.contracts
                try:
                    spec = dispatch(pend.out, p_end, s_ticks, s_info,
                                    donate=donate)
                except Exception as e:
                    spec_exc = (e, s_info, s_ticks)
                except BaseException:
                    # KeyboardInterrupt/SystemExit mid-overlap: chunk k is
                    # still good — confirm it and push its journal rows
                    # and checkpoint through the writer so a kill resumes
                    # from the last DRAINED checkpoint, then let the
                    # interrupt go (the finally below stops the writer)
                    try:
                        _confirm(pend, sup)
                        process(pend)
                        writer.drain(raise_errors=False)
                    except Exception:
                        pass
                    raise

            # ---- confirm chunk k (re-anchored deadline) and fold it in
            try:
                _confirm(pend, sup)
            except Exception as e:
                if spec is not None or spec_exc is not None:
                    # the in-flight k+1 consumed a poisoned input: its
                    # result is discarded unseen (bit-exact retry — the
                    # confirmed carry chain never includes it)
                    report.log("spec_discarded",
                               chunk_start=start_tick + p_end)
                info, ticks, src = pend.info, pend.ticks, pend.src
                pend, spec, spec_exc = None, None, None
                handle_failure(e, info, done, ticks, src, done)
                # reset the carry for the retry: the direct input when it
                # survived, else the anchor (+ silent catch-up above)
                if src is not None and not _is_deleted(src):
                    carry, carry_done = src, done
                else:
                    carry, carry_done = anchor_state, anchor_done
                continue
            process(pend)
            pend = None
            if spec_exc is not None:
                e, s_info, s_ticks = spec_exc
                handle_failure(e, s_info, done, s_ticks, carry, done)
                continue
            pend = spec

        if journal is not None:
            # terminal marker: a bounded-window stop (max_chunks) is a
            # PAUSE the caller resumes — the dashboard keeps tailing a
            # "window_end" journal; only true completion is "run_end"
            # retries/degrade_level ride the terminal marker so post-hoc
            # analysis (dashboard, banked-window reports) can see what a
            # number cost without parsing the whole event trail
            ing_meta = {}
            if ingest is not None:
                ing_meta = {
                    "commands_applied": int(
                        getattr(ingest, "applied_total", 0)),
                    "commands_shed": int(getattr(ingest, "shed_total", 0)),
                    "commands_refused": int(
                        getattr(ingest, "refused_total", 0)),
                    "ingest_offset": int(
                        getattr(ingest, "consumed_offset", 0))}
            writer.submit(lambda: journal.note(
                "window_end" if done < n_ticks else "run_end",
                tick=start_tick + done, chunks=report.chunks_run,
                retries=report.retries,
                degrade_level=report.degrade_level, **ing_meta))
        # drain barrier at window end: every checkpoint is durable and the
        # journal fsync'd before the caller sees the final state (a
        # deferred writer error — failed checkpoint save — raises here,
        # where the synchronous path would have raised at the site)
        writer.drain()
    finally:
        # stop the writer and close the journal no matter how the loop
        # left — a checkpoint-save error or a KeyboardInterrupt in a
        # backoff sleep must not leak the thread or the fd (the crash
        # branch already drained and noted its marker before raising)
        writer.close()
        if journal is not None:
            journal.close()
    return carry, report
