"""Supervised execution plane: chunked scans with checkpoints, a
wall-clock watchdog, retry/backoff down a degraded-mode ladder, and
replayable crash dumps.

PR 4 made the *simulated network* fault-tolerant (FaultPlan + invariant
sentinel); this module makes the *runner itself* fault-tolerant — the
preemption-safe checkpoint/resume + watchdog/backoff shape production
training stacks depend on, applied to long engine runs on scarce,
unattended TPU windows (round 5 lost its record of record to an unguarded
timeout; the protocol itself applies the same shape via PRUNE backoff and
promise timeouts, gossipsub v1.1 hardening).

:func:`supervised_run` wraps ``engine.run`` (or, with ``traced=True``,
``trace_export.run_traced``) as a sequence of chunked scans:

- **bit-identical chunking**: ONE master key is pre-split into per-tick
  keys exactly as ``engine.run`` does internally, and each chunk scans a
  contiguous window of that key array (``engine.run_keys``) — the chunked
  trajectory equals the single-scan trajectory bit for bit, checkpoints
  or not, faults or not (tests/test_supervisor.py, the core correctness
  claim).
- **checkpoints**: every ``checkpoint_every_ticks`` (default: every chunk
  boundary) the state lands in ``checkpoint_dir`` through the
  crash-atomic ``sim/checkpoint.save`` with the caller's config
  fingerprint stamped; a re-invocation resumes from the newest checkpoint
  that restores cleanly, falling back past torn ones
  (``CheckpointCorrupt``).
- **watchdog**: each chunk runs under a wall-clock ``deadline_s`` in a
  worker thread; an overrun abandons the dispatch (device work cannot be
  cancelled — the result is discarded) and counts as a transient failure.
- **retry + degraded-mode ladder**: transient failures back off
  exponentially and escalate — first ``hop_mode``/``edge_gather_mode``
  fall back to the conservative XLA formulations (bit-identical by the
  mode-parity suites), then the chunk size halves down to
  ``min_chunk_ticks`` — before giving up.
- **crash dumps**: an unrecoverable failure (retries exhausted, or an
  ``invariant_mode="raise"`` checkify trip, which is never retried —
  the trajectory itself is poisoned) writes the last-good checkpoint,
  the failing window's per-tick keys, the config fingerprint, and the
  decoded ``fault_flags`` to a crash directory, then raises
  :class:`SupervisorCrash`. ``scripts/replay_crash.py`` re-runs exactly
  that window from the dump with invariants raised. Registered trace
  sinks get ``hard_flush()``ed (flush + fsync) on every failure so a
  crashed traced run leaves a readable partial trace.

Env knobs (``SupervisorConfig.from_env``): ``GRAFT_CHUNK_TICKS``,
``GRAFT_DEADLINE_S``, ``GRAFT_CRASH_DIR``, ``GRAFT_CHECKPOINT_DIR``.

The fleet plane (sim/fleet.py) builds its batched-run supervision on the
SAME primitives — ``SupervisorConfig``/``SupervisorReport``, the
``_with_deadline`` watchdog, the ``_degrade`` ladder, and the
checkpoint listing/pruning helpers — so a fleet window and a single-run
chunk share one retry/degrade/checkpoint discipline.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import threading
import time
from typing import Callable

import jax
import numpy as np

from . import checkpoint
from .config import SimConfig, TopicParams
from .state import SimState

_CKPT_RE = re.compile(r"^ckpt_t(\d+)(?:\.npz)?$")


class SupervisorCrash(RuntimeError):
    """Unrecoverable supervised-run failure. ``dump_dir`` holds the crash
    dump (last-good checkpoint + crash.json), ``report`` the run log up to
    the failure."""

    def __init__(self, msg: str, dump_dir: str | None = None,
                 report: "SupervisorReport | None" = None):
        super().__init__(msg)
        self.dump_dir = dump_dir
        self.report = report


class ChunkDeadline(RuntimeError):
    """A chunk overran its wall-clock deadline (transient: retried)."""


@dataclasses.dataclass
class SupervisorConfig:
    """Host-side supervision knobs (NOT jit-static — execution shape only;
    none of these can change the trajectory)."""

    chunk_ticks: int = 64             # ticks per scan dispatch
    deadline_s: float | None = None   # per-chunk wall-clock watchdog
    # separate bound for first-use compilation of a (config, chunk-shape):
    # compile time is not execution time — a steady-state deadline tuned to
    # chunk runtime would otherwise trip on every new shape the ladder
    # introduces and thrash. None = compilation is unbounded.
    compile_deadline_s: float | None = None
    checkpoint_dir: str | None = None
    checkpoint_every_ticks: int = 0   # 0 = at every chunk boundary
    keep_checkpoints: int = 2         # newest N kept; older pruned
    max_retries: int = 4              # consecutive failures before giving up
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_cap_s: float = 30.0
    min_chunk_ticks: int = 1          # ladder floor for chunk shrinking
    crash_dir: str | None = None      # default: $GRAFT_CRASH_DIR or ./graft_crash
    scenario: str | None = None       # sim.scenarios.SCENARIOS key, stamped
    scenario_kwargs: dict | None = None   # into crash.json for replay_crash
    sinks: tuple = ()                 # trace sinks hard_flush()ed on failure
    # injectable for tests/smoke (real backoff sleeps are pointless there)
    sleep: Callable[[float], None] = time.sleep
    # --- multi-process hooks (parallel/multihost.py) ---
    # custom chunk runner (state, exec_cfg, tp, keys) -> state, replacing
    # engine.run_keys: the multihost launcher dispatches the SHARDED scan
    # (parallel.sharding.make_sharded_run_keys) here, whose trace keeps
    # the halo routes; the degrade ladder still swaps exec_cfg modes, so
    # the runner must honor the config it is handed. With >1 process a
    # chunk failure is FATAL (no rank-local retry/degrade — the ladder
    # cannot be rank-symmetric; see supervised_run): recovery is
    # relaunch-all-ranks + checkpoint resume
    run_fn: Callable | None = None
    # state -> host-complete state for checkpoint/crash writes. COLLECTIVE
    # when set (multihost.gather_state all-gathers non-addressable
    # shards): every process must reach the checkpoint boundary, while
    # only write_files=True processes (rank 0) touch the filesystem
    state_to_host: Callable | None = None
    # host-complete state -> this process's sharded state (resume path:
    # every process restores rank 0's checkpoint from the shared
    # filesystem, slices its rows, and re-assembles)
    state_from_host: Callable | None = None
    # False on non-coordinator ranks: checkpoint/crash-dump writes are
    # skipped (rank-0-only write discipline), resume still READS
    write_files: bool = True
    # window-bounded execution: stop cleanly after this many successful
    # chunks (checkpoint written if a dir is set) and return the partial
    # state — run as much as fits a bounded TPU window, resume the SAME
    # (key, n_ticks) schedule next window. None = run to n_ticks.
    max_chunks: int | None = None
    # streaming-telemetry lane (sim/telemetry.py): when set, chunks run
    # with the device-side health reduction ON (engine.run_keys
    # telemetry=True — aggregates stacked on device, ONE fetch per chunk
    # boundary) and every successful chunk's records stream crash-
    # atomically to this fsync'd NDJSON journal, which
    # scripts/dashboard.py tails live. write_files=False ranks compute
    # the (collective) reduction but skip the journal — the multihost
    # rank-0-only write discipline. Env: GRAFT_HEALTH_STREAM=path.
    health_path: str | None = None
    # extra keys stamped into the health journal's run header (JSON-able
    # dict) — adversary scenarios stamp their declared behavior contracts
    # here (sim/adversary.py contracts_to_json) so the dashboard can
    # evaluate the SCENARIO's contracts, not just the schedule defaults
    health_meta: dict | None = None

    @staticmethod
    def from_env(**overrides) -> "SupervisorConfig":
        kw: dict = {}
        if os.environ.get("GRAFT_CHUNK_TICKS"):
            kw["chunk_ticks"] = int(os.environ["GRAFT_CHUNK_TICKS"])
        if os.environ.get("GRAFT_DEADLINE_S"):
            kw["deadline_s"] = float(os.environ["GRAFT_DEADLINE_S"])
        if os.environ.get("GRAFT_CRASH_DIR"):
            kw["crash_dir"] = os.environ["GRAFT_CRASH_DIR"]
        if os.environ.get("GRAFT_CHECKPOINT_DIR"):
            kw["checkpoint_dir"] = os.environ["GRAFT_CHECKPOINT_DIR"]
        if os.environ.get("GRAFT_HEALTH_STREAM"):
            kw["health_path"] = os.environ["GRAFT_HEALTH_STREAM"]
        kw.update(overrides)
        return SupervisorConfig(**kw)


@dataclasses.dataclass
class SupervisorReport:
    """What the supervised run did — chunk counts, the retry/degrade
    trail, checkpoint/resume provenance, and the crash dump path (set only
    when :class:`SupervisorCrash` was raised; reach it via the
    exception's ``report``)."""

    chunks_run: int = 0
    ticks_run: int = 0
    retries: int = 0
    degrade_level: int = 0
    checkpoints: list = dataclasses.field(default_factory=list)
    resumed_from: str | None = None
    resumed_tick: int | None = None
    crash_dump: str | None = None
    events: list = dataclasses.field(default_factory=list)

    def log(self, event: str, **info) -> None:
        self.events.append({"event": event, **info})


def _fetch_scalar(x) -> np.ndarray:
    """Host value of a (possibly multi-process global) scalar array: a
    replicated leaf of a multihost state is not fully addressable, so
    ``np.asarray`` raises — read the local replica instead (every process
    holds the same value by construction)."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_shards[0].data)


def _key_data(keys) -> np.ndarray:
    """uint32 view of a key array, old-style (raw uint32) or typed (typed
    keys refuse direct np.asarray; unwrap them first)."""
    try:
        if jax.dtypes.issubdtype(keys.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(keys))
    except (AttributeError, TypeError):
        pass
    return np.asarray(keys)


def _hard_flush(sinks) -> None:
    for s in sinks:
        try:
            if hasattr(s, "hard_flush"):
                s.hard_flush()
            elif hasattr(s, "flush"):
                s.flush()
        except Exception:
            pass        # the failure path must never mask the failure


def _is_invariant_trip(err: BaseException) -> bool:
    # the checkify message format of sim/invariants.record_flags
    return "invariant violation" in str(err)


def _ckpt_path(ckpt_dir: str, tick: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_t{tick:09d}")


def list_checkpoints(ckpt_dir: str) -> list:
    """Supervisor checkpoints in ``ckpt_dir`` as ``[(path, tick)]``,
    ascending tick. ``path`` is the bare name ``checkpoint.restore``
    accepts for both backends (the ``.npz`` suffix of the fallback is
    stripped)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = {}
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            bare = name[:-4] if name.endswith(".npz") else name
            out[bare] = int(m.group(1))
    return sorted(((os.path.join(ckpt_dir, b), t) for b, t in out.items()),
                  key=lambda pt: pt[1])


def _prune_checkpoints(ckpt_dir: str, keep: int) -> None:
    for path, _tick in list_checkpoints(ckpt_dir)[:-keep or None]:
        for victim in (path, path + ".npz", path + ".fingerprint"):
            try:
                if os.path.isdir(victim):
                    shutil.rmtree(victim)
                elif os.path.lexists(victim):
                    os.remove(victim)
            except OSError:
                pass    # pruning is best-effort; never fail the run for it


def _try_resume(sup: SupervisorConfig, cfg: SimConfig, like: SimState,
                start_tick: int, n_ticks: int,
                report: SupervisorReport) -> tuple:
    """Newest checkpoint in the run's tick window that restores cleanly,
    falling back past torn/mismatched ones; (state, ticks_done)."""
    for path, tick in reversed(list_checkpoints(sup.checkpoint_dir)):
        if not (start_tick < tick <= start_tick + n_ticks):
            continue
        try:
            st = checkpoint.restore(path, like, cfg=cfg)
        except ValueError as e:     # CheckpointCorrupt or mismatch
            report.log("resume_skip", path=path, error=str(e)[:200])
            continue
        if sup.state_from_host is not None:
            # multihost: the checkpoint restores host-complete; every
            # process re-slices its rows and re-assembles the global
            # sharded state (collective — all ranks walk the same
            # shared-filesystem checkpoint list, so they agree)
            st = sup.state_from_host(st)
        done = int(_fetch_scalar(st.tick)) - start_tick
        if done != tick - start_tick:   # name/state tick disagreement
            report.log("resume_skip", path=path,
                       error=f"state tick {done + start_tick} != {tick}")
            continue
        report.resumed_from = path
        report.resumed_tick = tick
        report.log("resume", path=path, tick=tick)
        return st, done
    return like, 0


# the explicit conservative fallback per mode FAMILY. Every mode name the
# engine can carry — including the blocked-onehot/mxu-extras formulations
# and any future/unknown string (which would raise in its resolver and
# land here as a chunk failure) — maps to the same safe floor, so an
# unrecognized mode can never dead-end a retry: the ladder's first rung
# always produces a config that compiles everywhere. NOT "auto": auto
# resolves right back to the failing mode on its home backend.
_CONSERVATIVE_MODES = {"hop_mode": "xla", "edge_gather_mode": "scalar",
                       "selection_mode": "sort"}


def _degrade(exec_cfg: SimConfig, chunk_ticks: int, sup: SupervisorConfig,
             report: SupervisorReport) -> tuple:
    """One rung down the ladder: kernel modes first (pallas-mxu / mxu /
    sort / unknown → the EXPLICIT conservative formulations
    ``_CONSERVATIVE_MODES``, bit-identical per the mode-parity suites),
    then chunk shrinking. Sticky for the rest of the run — a chunk that
    needed the fallback would need it again."""
    current = {f: getattr(exec_cfg, f) for f in _CONSERVATIVE_MODES}
    if current != _CONSERVATIVE_MODES:
        exec_cfg = dataclasses.replace(exec_cfg, **_CONSERVATIVE_MODES)
        report.degrade_level = max(report.degrade_level, 1)
        report.log("degrade", **_CONSERVATIVE_MODES)
    elif chunk_ticks > sup.min_chunk_ticks:
        chunk_ticks = max(sup.min_chunk_ticks, chunk_ticks // 2)
        report.degrade_level += 1
        report.log("degrade", chunk_ticks=chunk_ticks)
    return exec_cfg, chunk_ticks


def _write_crash_dump(sup: SupervisorConfig, cfg: SimConfig,
                      last_good: SimState, keys_chunk, start_tick: int,
                      done: int, this_chunk: int, n_ticks: int,
                      err: BaseException,
                      report: SupervisorReport) -> str:
    from .invariants import decode_flags

    base = sup.crash_dir or os.environ.get("GRAFT_CRASH_DIR") \
        or os.path.join(os.getcwd(), "graft_crash")
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    dump = os.path.join(base, f"crash_{stamp}_p{os.getpid()}")
    os.makedirs(dump, exist_ok=True)
    checkpoint.save(os.path.join(dump, "last_good"), last_good, cfg=cfg)
    flags = int(_fetch_scalar(last_good.fault_flags))
    meta = {
        "error": str(err)[:2000],
        "error_type": type(err).__name__,
        "tick_start": start_tick + done,
        "tick_end": start_tick + done + this_chunk,
        "run_start_tick": start_tick,
        "n_ticks": n_ticks,
        "config_fingerprint": checkpoint.config_fingerprint(cfg),
        "invariant_mode": cfg.invariant_mode,
        "fault_flags": flags,
        "fault_flag_names": decode_flags(flags),
        # the failing window's exact per-tick keys: replay_crash.py feeds
        # these straight back into engine.run_checked_keys
        "window_key_data": _key_data(keys_chunk).tolist(),
        "degrade_level": report.degrade_level,
        "retries": report.retries,
        "scenario": sup.scenario,
        "scenario_kwargs": sup.scenario_kwargs,
    }
    tmp = os.path.join(dump, f"crash.json.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dump, "crash.json"))
    report.log("crash_dump", path=dump)
    return dump


# AOT-compiled chunk executables, keyed by (exec_cfg, chunk_len, key
# dtype): compiling through .lower().compile() ahead of the watchdog keeps
# compile time out of the run deadline, and re-dispatching the SAME
# executable across chunks/retries skips the jit cache lookup entirely.
# SimConfig is frozen/hashable, so the dict stays small (one entry per
# ladder rung per tail-chunk shape).
_AOT_CACHE: dict = {}


def _chunk_executable(exec_cfg: SimConfig, state: SimState, tp: TopicParams,
                      keys_chunk, telemetry: bool = False):
    from .engine import run_keys
    cache_key = (exec_cfg, int(keys_chunk.shape[0]), str(keys_chunk.dtype),
                 telemetry)
    exe = _AOT_CACHE.get(cache_key)
    if exe is None:
        exe = run_keys.lower(state, exec_cfg, tp, keys_chunk,
                             telemetry=telemetry).compile()
        _AOT_CACHE[cache_key] = exe
    return exe


def _with_deadline(fn, deadline_s, what: str, info: dict):
    """Run ``fn`` under a wall-clock deadline on a DAEMON thread. A
    timed-out dispatch cannot be cancelled — the thread is abandoned and
    its result discarded (a retry re-runs the same keys from the same
    last-good state, so nothing is lost but time). Daemon is load-bearing:
    concurrent.futures workers are non-daemon and joined at interpreter
    exit, so a truly wedged dispatch (the axon-tunnel failure class) would
    hang the process at shutdown — after the supervisor already crashed
    out — and burn the rest of an unattended window."""
    if deadline_s is None:
        return fn()
    box: list = []

    def runner():
        try:
            box.append((True, fn()))
        except BaseException as e:      # rethrown on the caller thread
            box.append((False, e))

    # two callers share this watchdog with different info schemas: the
    # single-run supervisor (chunk_start/chunk_ticks) and the fleet plane
    # (window_start/window_ticks, sim/fleet.py) — resolve either
    start = info.get("chunk_start", info.get("window_start", "?"))
    ticks = info.get("chunk_ticks", info.get("window_ticks", "?"))
    t = threading.Thread(target=runner, daemon=True,
                         name=f"graft-chunk-t{start}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        raise ChunkDeadline(
            f"{what} at tick {start} ({ticks} ticks) overran the "
            f"{deadline_s}s deadline")
    ok, val = box[0]
    if not ok:
        raise val
    return val


def _run_chunk(state: SimState, exec_cfg: SimConfig, tp: TopicParams,
               keys_chunk, sup: SupervisorConfig, traced: bool,
               chunk_events: list, chunk_health: list,
               chunk_hook, info: dict) -> tuple:
    """One chunk attempt: compile (its own deadline) then run (the
    watchdog deadline). Returns ``(state, HealthRecord | None)`` — the
    chunk's device-stacked telemetry records when ``sup.health_path``
    turned the lane on (sim/telemetry.py); the traced path keeps its
    per-tick dict rows in ``chunk_health`` instead."""
    telemetry = sup.health_path is not None and not traced
    exe = None
    if not traced and exec_cfg.invariant_mode != "raise" \
            and sup.run_fn is None:
        exe = _with_deadline(
            lambda: _chunk_executable(exec_cfg, state, tp, keys_chunk,
                                      telemetry=telemetry),
            sup.compile_deadline_s, "compile", info)

    def worker():
        if chunk_hook is not None:      # test/smoke fault-injection point
            chunk_hook(info)
        health = None
        if sup.run_fn is not None:
            # custom chunk runner (multihost sharded scan); it owns its
            # own compile caching, so first use rides the run deadline.
            # A telemetry-aware runner (scripts/run_multihost.py with a
            # health stream) returns (state, HealthRecord); a plain one
            # returns the state alone — both are honored
            out = sup.run_fn(state, exec_cfg, tp, keys_chunk)
            # EXACT tuple check: SimState itself is a NamedTuple (a tuple
            # subclass), so isinstance would mis-unpack a plain runner's
            # bare state into 2-of-30 fields
            if type(out) is tuple:
                out, health = out
        elif traced:
            from .trace_export import run_traced
            out, evs = run_traced(state, exec_cfg, tp, None, 0,
                                  health_out=chunk_health, keys=keys_chunk)
            chunk_events.extend(evs)
        elif exe is not None:
            if telemetry:
                out, health = exe(state, tp, keys_chunk)
            else:
                out = exe(state, tp, keys_chunk)
        else:
            # "raise" mode: per-call checkify transform (the debugging
            # path — compile rides the run deadline here)
            from .engine import run_checked_keys
            out = run_checked_keys(state, exec_cfg, tp, keys_chunk,
                                   telemetry=telemetry)
            if telemetry:
                out, health = out
        # real sync by value fetch: async dispatch (and the axon tunnel,
        # which block_until_ready does not block through) must not let a
        # wedged chunk slide past the deadline
        _fetch_scalar(out.tick)
        return out, health

    return _with_deadline(worker, sup.deadline_s, "chunk", info)


def supervised_run(state: SimState, cfg: SimConfig, tp: TopicParams,
                   key, n_ticks: int,
                   sup: SupervisorConfig | None = None, *,
                   traced: bool = False,
                   events_out: list | None = None,
                   health_out: list | None = None,
                   _chunk_hook=None) -> tuple:
    """Run ``n_ticks`` engine ticks under supervision (module docstring).

    Returns ``(final_state, report)``; the final state is bit-identical to
    ``engine.run(state, cfg, tp, key, n_ticks)`` regardless of chunking,
    checkpointing, resumption, retries, or degraded modes. Raises
    :class:`SupervisorCrash` after writing a crash dump when the run
    cannot make progress.

    ``traced=True`` routes chunks through ``trace_export.run_traced``
    (requires ``cfg.record_provenance``); successful chunks append their
    events/health records to ``events_out``/``health_out`` — a failed
    attempt's partial records are discarded, so the collected stream never
    double-counts a retried tick. ``_chunk_hook(info)`` is a test/smoke
    fault-injection point called at the top of every chunk attempt.
    """
    sup = sup or SupervisorConfig.from_env()
    report = SupervisorReport()
    start_tick = int(_fetch_scalar(state.tick))
    all_keys = jax.random.split(key, n_ticks)   # run's exact discipline

    done = 0
    if sup.checkpoint_dir:
        state, done = _try_resume(sup, cfg, state, start_tick, n_ticks,
                                  report)

    # streaming-telemetry journal (sim/telemetry.py): rank-0-only under
    # multihost (write_files); rank>0 still EXECUTES the telemetry lane —
    # the reduction is part of the compiled program all ranks share
    journal = None
    if sup.health_path and sup.write_files:
        from .telemetry import HealthJournal
        journal = HealthJournal(sup.health_path)
        journal.header(cfg, scenario=sup.scenario, start_tick=start_tick,
                       n_ticks=n_ticks, resumed_tick=report.resumed_tick,
                       traced=traced, **(sup.health_meta or {}))

    exec_cfg = cfg
    chunk_ticks = max(1, int(sup.chunk_ticks))
    every = sup.checkpoint_every_ticks or chunk_ticks
    next_ckpt = done + every
    failures = 0            # consecutive; reset on every successful chunk
    # multihost: the newest HOST-COMPLETE copy and the tick offset it was
    # gathered at, refreshed at every checkpoint-cadence boundary (where
    # state_to_host — a collective — legally runs on every rank; NEVER in
    # the error path, where a one-rank failure would deadlock it). The
    # crash path dumps THIS with its key window re-anchored to the
    # gathered tick, so last_good + keys stay a replayable pair even when
    # the gather is chunks old.
    last_host_state, last_host_done = None, done
    if sup.state_to_host is not None:
        # run-start gather: a first-window crash still has a dumpable
        # copy (and a run with no checkpoint_dir dumps at all)
        last_host_state = sup.state_to_host(state)
    try:
        while done < n_ticks:
            this_chunk = min(chunk_ticks, n_ticks - done)
            keys_chunk = all_keys[done:done + this_chunk]
            info = {"chunk_start": start_tick + done, "chunk_ticks": this_chunk,
                    "attempt": failures, "degrade_level": report.degrade_level}
            chunk_events: list = []
            chunk_health: list = []
            try:
                out, chunk_records = _run_chunk(state, exec_cfg, tp, keys_chunk,
                                                sup, traced, chunk_events,
                                                chunk_health, _chunk_hook, info)
            except Exception as e:
                _hard_flush(sup.sinks)
                failures += 1
                # a MULTI-PROCESS run fails fast: the retry/degrade ladder is
                # rank-LOCAL, so one rank re-dispatching a degraded (different
                # collective sequence) or re-sized program while its peers sit
                # in the original chunk's collectives would deadlock or pair
                # wrong collectives. Recovery that IS rank-symmetric by
                # construction: crash, relaunch every rank, resume from the
                # last checkpoint (scripts/run_multihost.py).
                multiproc = sup.run_fn is not None and jax.process_count() > 1
                if _is_invariant_trip(e) or multiproc \
                        or failures > sup.max_retries:
                    # invariant trips are never retried: the trajectory itself
                    # is poisoned and would trip again on the same keys
                    dump = None
                    if sup.write_files and sup.state_to_host is None:
                        dump = _write_crash_dump(sup, cfg, state,
                                                 keys_chunk, start_tick, done,
                                                 this_chunk, n_ticks, e, report)
                    elif sup.write_files and last_host_state is not None:
                        # the gathered copy may be chunks old: re-anchor the
                        # dumped window to ITS tick so replay_crash.py feeds
                        # last_good exactly the keys that advance it into the
                        # failure
                        w0, w1 = last_host_done, done + this_chunk
                        dump = _write_crash_dump(sup, cfg, last_host_state,
                                                 all_keys[w0:w1], start_tick,
                                                 w0, w1 - w0, n_ticks, e,
                                                 report)
                    report.crash_dump = dump
                    if journal is not None:
                        # the dashboard's post-mortem hook: the journal ends
                        # with WHERE it died and which dump replays it
                        journal.note("crash", tick=start_tick + done,
                                     dump=dump, error=str(e)[:200])
                    raise SupervisorCrash(
                        f"supervised run gave up at tick {start_tick + done} "
                        f"({failures} consecutive failure(s)); crash dump: "
                        f"{dump}", dump_dir=dump, report=report) from e
                report.retries += 1
                report.log("chunk_failed",
                           kind="deadline" if isinstance(e, ChunkDeadline)
                           else "error", error=str(e)[:200], **info)
                exec_cfg, chunk_ticks = _degrade(exec_cfg, chunk_ticks, sup,
                                                 report)
                delay = min(sup.backoff_cap_s, sup.backoff_base_s
                            * sup.backoff_factor ** (failures - 1))
                report.log("backoff", delay_s=round(delay, 3))
                sup.sleep(delay)
                continue
            failures = 0
            state = out
            done += this_chunk
            report.chunks_run += 1
            report.ticks_run += this_chunk
            report.log("chunk_ok", **info)
            if events_out is not None:
                events_out.extend(chunk_events)
            if health_out is not None:
                health_out.extend(chunk_health)
            if journal is not None:
                # stream the SUCCESSFUL chunk (a failed attempt's records died
                # with its discarded output — the journal never double-counts
                # a retried tick): one fetch of the [C]-stacked device buffer,
                # encoded native-first, fsync'd before the loop moves on
                if chunk_records is not None:
                    journal.append_records(chunk_records,
                                           tick_start=start_tick + done
                                           - this_chunk, ticks=this_chunk)
                elif traced and chunk_health:
                    journal.append_dicts(chunk_health,
                                         tick_start=start_tick + done
                                         - this_chunk, ticks=this_chunk)
                else:
                    # a runner that yields no records (a plain custom
                    # run_fn) still marks progress: the dashboard's hb/s
                    # and chunk cadence come from these markers
                    journal.note("chunk", rows=0,
                                 tick_start=start_tick + done - this_chunk,
                                 ticks=this_chunk)
            window_end = sup.max_chunks is not None \
                and report.chunks_run >= sup.max_chunks and done < n_ticks
            # a window end is ALWAYS a boundary: the max_chunks contract says
            # "stop cleanly (checkpoint written if a dir is set)" — without
            # this, a stop off the checkpoint cadence would discard the whole
            # window's progress on resume
            at_boundary = done >= next_ckpt or done >= n_ticks or window_end
            if at_boundary and sup.state_to_host is not None:
                # collective on EVERY rank (multihost.gather_state) at the
                # checkpoint cadence even with no checkpoint_dir — the crash
                # dump's freshness rides this; only write_files ranks then
                # touch the filesystem
                last_host_state, last_host_done = sup.state_to_host(state), done
            if at_boundary and sup.checkpoint_dir:
                to_save = state if sup.state_to_host is None else last_host_state
                if sup.write_files:
                    path = _ckpt_path(sup.checkpoint_dir, start_tick + done)
                    os.makedirs(sup.checkpoint_dir, exist_ok=True)
                    checkpoint.save(path, to_save, cfg=cfg)   # crash-atomic
                    report.checkpoints.append(path)
                    report.log("checkpoint", tick=start_tick + done, path=path)
                    if journal is not None:
                        journal.note("checkpoint", tick=start_tick + done,
                                     path=path)
                    _prune_checkpoints(sup.checkpoint_dir, sup.keep_checkpoints)
            if at_boundary:
                next_ckpt = done + every
            if window_end:
                # clean window end: the caller resumes the same (key, n_ticks)
                # schedule later — the per-tick keys are a function of BOTH,
                # so a resumed run must re-request the full n_ticks
                report.log("window_end", chunks=report.chunks_run,
                           tick=start_tick + done)
                break
        if journal is not None:
            # terminal marker: a bounded-window stop (max_chunks) is a
            # PAUSE the caller resumes — the dashboard keeps tailing a
            # "window_end" journal; only true completion is "run_end"
            journal.note("window_end" if done < n_ticks else "run_end",
                         tick=start_tick + done, chunks=report.chunks_run)
    finally:
        # close no matter how the loop left — a checkpoint-save error or
        # a KeyboardInterrupt in a backoff sleep must not leak the fd
        # (the crash branch already noted its marker before raising)
        if journal is not None:
            journal.close()
    return state, report
