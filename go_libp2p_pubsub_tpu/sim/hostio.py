"""Host-transfer utilities shared by every plane that fetches device
values (ISSUE 12 satellite: the addressable-shard unwrap used to live as
private copies in ``sim/supervisor.py`` and ``sim/telemetry.py``; this
module is the single home).

Two cases make a plain ``np.asarray`` insufficient:

- a **multi-process replicated global** array is not fully addressable,
  so ``np.asarray`` raises — read the local replica instead (every
  process holds the same value by construction);
- a **typed PRNG key** array refuses direct ``np.asarray`` — unwrap to
  its uint32 key data first.
"""

from __future__ import annotations

import jax
import numpy as np


def fetch_local(x) -> np.ndarray:
    """Host value of a (possibly multi-process global) array. Replicated
    leaves of a multihost state are not fully addressable, so
    ``np.asarray`` raises — read the local replica (every process holds
    the same value by construction). This is also the supervisor's
    real-sync primitive: fetching by VALUE blocks through async dispatch
    and the axon tunnel, which ``block_until_ready`` does not."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_shards[0].data)


def key_data(keys) -> np.ndarray:
    """uint32 view of a key array, old-style (raw uint32) or typed (typed
    keys refuse direct np.asarray; unwrap them first)."""
    try:
        if jax.dtypes.issubdtype(keys.dtype, jax.dtypes.prng_key):
            return fetch_local(jax.random.key_data(keys))
    except (AttributeError, TypeError):
        pass
    return fetch_local(keys)


def is_deleted(tree) -> bool:
    """True when any leaf of a state pytree has been consumed by a
    donated executable. The async supervisor pipeline donates carried
    chunk inputs; on a failure it must know whether the failing chunk's
    input still exists (retry in place) or was consumed by the
    speculative next dispatch (replay from the host anchor)."""
    for leaf in jax.tree.leaves(tree):
        fn = getattr(leaf, "is_deleted", None)
        if fn is not None and fn():
            return True
    return False
