"""Device-resident simulation state (SURVEY.md §7 "State layout").

One ``SimState`` holds the entire N-peer network as a pytree of arrays —
peer-major, fixed-capacity, mask-annotated. Checkpointing the network is
saving this pytree (SURVEY.md §5.4: the simulator gains what the reference
lacks — exact, free checkpoints).

Array roles (reference state being modeled):
- mesh/fanout/backoff per (peer, topic, slot): gossipsub.go:424-432 maps
- score counters per (peer, topic, slot): score.go:17-62 topicStats, kept by
  the *observing* peer about the neighbor in that slot
- message window: mcache.go ring + timecache seen-set, modeled as per-peer
  deliver-tick over a rotating window of message slots
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig
from .topology import Topology

# sentinel for "never" ticks. A NUMPY scalar, deliberately not
# jnp.int32(...): a module-level concrete jax Array closed over by traced
# code is lifted by pjit as a constant ARGUMENT under the fleet plane's
# vmapped scan (sim/fleet.py), and its per-trace tracer is cached by
# object identity — a second trace (another fleet group's config) then
# sees the FIRST trace's stale tracer and dies with UnexpectedTracerError
# / "compiled for 61 inputs but called with 59". A numpy scalar has the
# same dtype/semantics everywhere this is used and lowers as an inline
# literal with no cross-trace identity.
NEVER = np.int32(2**30)


class SimState(NamedTuple):
    tick: jnp.ndarray                 # scalar int32 heartbeat counter

    # --- static-ish topology (churn applied between steps) ---
    neighbors: jnp.ndarray            # [N, K] int32, -1 padded
    connected: jnp.ndarray            # [N, K] bool
    outbound: jnp.ndarray             # [N, K] bool
    reverse_slot: jnp.ndarray         # [N, K] int32
    subscribed: jnp.ndarray           # [N, T] bool
    nbr_subscribed: jnp.ndarray       # [N, T, K] bool cached receiver view:
                                      #   slot s's peer subscribes topic t
                                      #   (invalid slots False). The topology
                                      #   is fixed, so this changes ONLY when
                                      #   `subscribed` does — every mutation
                                      #   of `subscribed` must go through
                                      #   refresh_nbr_subscribed(); reading
                                      #   it replaces a per-tick neighbor
                                      #   gather in heartbeat/randomsub
    disconnect_tick: jnp.ndarray      # [N, K] int32 tick the edge went down,
                                      #   NEVER if up/never-connected; drives
                                      #   RetainScore expiry (score.go:611-644)
    direct: jnp.ndarray               # [N, K] bool (direct peers, gossipsub.go:425)
    ip_group: jnp.ndarray             # [N] int32 (P6 colocation groups)
    app_score: jnp.ndarray            # [N] float32 (P5 per-peer app score)
    malicious: jnp.ndarray            # [N] bool: sybil/spam actors (the
                                      #   gossipsub_spam_test.go adversary
                                      #   roles as a peer attribute): publish
                                      #   invalid messages, advertise the
                                      #   whole window, never answer IWANTs

    # --- router state ---
    mesh: jnp.ndarray                 # [N, T, K] bool
    fanout: jnp.ndarray               # [N, T, K] bool
    fanout_lastpub: jnp.ndarray       # [N, T] int32 tick, NEVER if none
    backoff: jnp.ndarray              # [N, T, K] int32 expiry tick

    # --- score state (observer-major: what peer n thinks of slot k) ---
    graft_tick: jnp.ndarray           # [N, T, K] int32
    mesh_active: jnp.ndarray          # [N, T, K] bool (P3 activation latch)
    first_message_deliveries: jnp.ndarray   # [N, T, K] f32
    mesh_message_deliveries: jnp.ndarray    # [N, T, K] f32
    mesh_failure_penalty: jnp.ndarray       # [N, T, K] f32
    invalid_message_deliveries: jnp.ndarray # [N, T, K] f32
    behaviour_penalty: jnp.ndarray    # [N, K] f32

    # --- peer gater (peer_gater.go:119-151) ---
    # global per-receiver counters; per-source stats live per neighbor slot
    # (the reference keys them by source IP: slots sharing an IP share stats
    # there; the sim keeps them per-slot and leans on P6 for colocation)
    gater_validate: jnp.ndarray       # [N] f32 validated count (global)
    gater_throttle: jnp.ndarray       # [N] f32 throttled count (global)
    gater_last_throttle: jnp.ndarray  # [N] int32 tick of last throttle event
    gater_deliver: jnp.ndarray        # [N, K] f32
    gater_duplicate: jnp.ndarray      # [N, K] f32
    gater_ignore: jnp.ndarray         # [N, K] f32
    gater_reject: jnp.ndarray         # [N, K] f32

    # --- message window (rotating slots) ---
    msg_topic: jnp.ndarray            # [M] int32 topic of message slot, -1 idle
    msg_publish_tick: jnp.ndarray     # [M] int32
    msg_invalid: jnp.ndarray          # [M] bool: fails validation (honest
                                      #   receivers reject + count P4)
    msg_ignored: jnp.ndarray          # [M] bool: validation verdict IGNORE
                                      #   (dropped + seen, no P4, gater
                                      #   counts ignore — validation.go:344-370)
    msg_publisher: jnp.ndarray        # [M] int32 origin peer, -1 idle
    have: jnp.ndarray                 # [N, ceil(M/32)] u32 seen-set, bit
                                      #   m%32 of word m//32 (ops/bits.py
                                      #   little-endian order, pack_bool
                                      #   compatible). Stored PACKED — the
                                      #   hop loop consumes [W, N] words
                                      #   anyway (have.T), so the per-tick
                                      #   pack_words/unpack_words round
                                      #   trip is gone and the plane is 8x
                                      #   smaller than the old [N, M] bool
                                      #   (the 1M-peer budget line in
                                      #   PERF_MODEL.md). Read it through
                                      #   unpack_have(); set single bits
                                      #   with have_set_bit()
    deliver_tick: jnp.ndarray         # [N, M] int32, NEVER if not delivered
    deliver_from: jnp.ndarray         # [N, M] int32 neighbor slot the first
                                      #   delivery came from, -1 (self/none);
                                      #   maintained only under
                                      #   cfg.record_provenance (trace export)
    iwant_pending: jnp.ndarray        # [N, M] int32 source peer for pending
                                      #   gossip pull, -1 if none

    # --- stats accumulated per step (observability) ---
    delivered_total: jnp.ndarray      # scalar int64-ish f32 count
    halo_overflow: jnp.ndarray        # scalar int32: halo-route bucket
                                      #   overflows observed (parallel/halo.py
                                      #   capacity rule). > 0 means routed
                                      #   trajectories are POISONED — raise
                                      #   SimConfig.halo_capacity_factor to
                                      #   required_capacity_factor()'s answer
    fault_flags: jnp.ndarray          # scalar uint32 health word
                                      #   (sim/invariants.py bit layout):
                                      #   low byte = which FaultPlan faults
                                      #   fired; bits 8+ = invariant
                                      #   violations (any set => trajectory
                                      #   suspect). Sticky across the scan;
                                      #   emitted with every bench metric
                                      #   line and trace export


def n_msg_words(cfg: SimConfig) -> int:
    """Words of the packed per-peer message seen-set (``have``)."""
    return (cfg.msg_window + 31) // 32


def unpack_have(state: SimState, m: int) -> jnp.ndarray:
    """The seen-set as [N, M] bool (census/observability reads; the hot
    path consumes the packed words directly)."""
    from ..ops.bits import unpack_words
    return unpack_words(state.have.T, m)


def have_set_bit(have: jnp.ndarray, peer, slot) -> jnp.ndarray:
    """``have`` with bit ``slot`` of row ``peer`` set (trace replay's
    single-delivery updates; indices may be traced scalars)."""
    w = jnp.asarray(slot) // 32
    bit = jnp.uint32(1) << (jnp.asarray(slot) % 32).astype(jnp.uint32)
    return have.at[peer, w].set(have[peer, w] | bit)


# --- compact storage codecs (cfg.state_precision="compact") ------------
#
# Every SimState field names its storage codec here; the tier-1 audit
# (tests/test_state_precision.py) FAILS if a field is missing, so a new
# plane cannot land without a precision decision AND a byte ceiling.
# Compute always happens in the historical f32/i32 layout — engine.step
# decodes at entry and re-encodes at exit, so no op ever sees a narrow
# type; "f32" precision bypasses both directions entirely (bit-exact).
#
#   bf16    f32 counter -> bfloat16, STORED as its uint16 bit pattern
#           (bitcast_convert_type) so checkpoints / np.savez / gathers
#           never meet an ml_dtypes array — 2x smaller, ~3 decimal
#           digits of mantissa (the score counters are decayed
#           magnitudes; tolerance pinned in tests/test_state_precision)
#   tick16  bounded i32 tick plane -> int16 RELATIVE to state.tick;
#           NEVER maps to the reserved +32767 and round-trips exactly,
#           other deltas saturate at +/-32766 (safe: every consumer asks
#           expired-vs-tick questions and |delta| < 32766 for any
#           horizon the planes encode — backoffs, RetainScore windows,
#           the msg_window, gater quiet periods are all << 32766 ticks;
#           gater_last_throttle's -NEVER fill saturates to "throttled
#           32766 ticks ago", which every quiet-period compare treats
#           exactly like -NEVER)
#   packK   bool [..., K] slot plane -> u32 [..., ceil(K/32)] words,
#           the `have` discipline (ops/bits.py pack_bool/unpack_bool) —
#           lossless, 8x (bit-exact round trip pinned in tests)
#   slot8   neighbor-slot index i32 -> int8 (values in [-1, k_slots);
#           compact refuses k_slots > 127 by name) — lossless, 4x
#   None    stored as-is (peer ids need 24+ bits at 10M peers; tiny /
#           replicated / scalar planes are not worth a codec)
_COMPACT_CODECS = dict(
    tick=None,
    neighbors=None, connected="packK", outbound="packK",
    reverse_slot="slot8", subscribed=None, nbr_subscribed="packK",
    disconnect_tick="tick16", direct="packK",
    ip_group=None, app_score=None, malicious=None,
    mesh="packK", fanout="packK", fanout_lastpub="tick16",
    backoff="tick16", graft_tick="tick16", mesh_active="packK",
    first_message_deliveries="bf16", mesh_message_deliveries="bf16",
    mesh_failure_penalty="bf16", invalid_message_deliveries="bf16",
    behaviour_penalty="bf16",
    gater_validate=None, gater_throttle=None,
    gater_last_throttle="tick16",
    gater_deliver="bf16", gater_duplicate="bf16",
    gater_ignore="bf16", gater_reject="bf16",
    msg_topic=None, msg_publish_tick=None, msg_invalid=None,
    msg_ignored=None, msg_publisher=None,
    have=None, deliver_tick="tick16", deliver_from="slot8",
    iwant_pending=None,
    delivered_total=None, halo_overflow=None, fault_flags=None,
)

_TICK16_NEVER = 32767     # reserved int16 encoding of the NEVER sentinel
_TICK16_SAT = 32766       # saturation bound for live relative ticks


def _check_compact(cfg: SimConfig) -> None:
    if cfg.state_precision != "compact":
        raise ValueError(
            f"state_precision={cfg.state_precision!r}: expected 'f32' or "
            "'compact'")
    if cfg.k_slots > 127:
        raise ValueError(
            f"state_precision='compact': the slot8 codec stores neighbor "
            f"slots as int8, so k_slots={cfg.k_slots} > 127 is refused")
    if set(_COMPACT_CODECS) != set(SimState._fields):
        raise RuntimeError("_COMPACT_CODECS drifted from SimState._fields")


def _compact_entry(codec, shape, dtype):
    """(shape, dtype) a codec stores the f32-layout (shape, dtype) as."""
    if codec == "bf16":
        return shape, np.uint16
    if codec == "tick16":
        return shape, np.int16
    if codec == "slot8":
        return shape, np.int8
    if codec == "packK":
        return shape[:-1] + ((shape[-1] + 31) // 32,), np.uint32
    return shape, dtype


def state_spec(cfg: SimConfig) -> dict:
    """field -> (shape, dtype, peer_major): the single source of truth for
    the SimState layout AS STORED (scan carry, checkpoints, shardings)
    under ``cfg.state_precision``. ``peer_major`` fields shard their
    leading N axis over the peer mesh (parallel/sharding.state_shardings);
    the rest (message tables, scalars) replicate. state_nbytes prices
    exactly these shapes; init builds them."""
    n, k, t, m = cfg.n_peers, cfg.k_slots, cfg.n_topics, cfg.msg_window
    w = n_msg_words(cfg)
    i32, f32, b, u32 = np.int32, np.float32, np.bool_, np.uint32
    spec = dict(
        tick=((), i32, False),
        neighbors=((n, k), i32, True), connected=((n, k), b, True),
        outbound=((n, k), b, True), reverse_slot=((n, k), i32, True),
        subscribed=((n, t), b, True), nbr_subscribed=((n, t, k), b, True),
        disconnect_tick=((n, k), i32, True), direct=((n, k), b, True),
        ip_group=((n,), i32, True), app_score=((n,), f32, True),
        malicious=((n,), b, True),
        mesh=((n, t, k), b, True), fanout=((n, t, k), b, True),
        fanout_lastpub=((n, t), i32, True), backoff=((n, t, k), i32, True),
        graft_tick=((n, t, k), i32, True), mesh_active=((n, t, k), b, True),
        first_message_deliveries=((n, t, k), f32, True),
        mesh_message_deliveries=((n, t, k), f32, True),
        mesh_failure_penalty=((n, t, k), f32, True),
        invalid_message_deliveries=((n, t, k), f32, True),
        behaviour_penalty=((n, k), f32, True),
        gater_validate=((n,), f32, True), gater_throttle=((n,), f32, True),
        gater_last_throttle=((n,), i32, True),
        gater_deliver=((n, k), f32, True),
        gater_duplicate=((n, k), f32, True),
        gater_ignore=((n, k), f32, True), gater_reject=((n, k), f32, True),
        msg_topic=((m,), i32, False), msg_publish_tick=((m,), i32, False),
        msg_invalid=((m,), b, False), msg_ignored=((m,), b, False),
        msg_publisher=((m,), i32, False),
        have=((n, w), u32, True), deliver_tick=((n, m), i32, True),
        deliver_from=((n, m), i32, True), iwant_pending=((n, m), i32, True),
        delivered_total=((), f32, False), halo_overflow=((), i32, False),
        fault_flags=((), u32, False),
    )
    if set(spec) != set(SimState._fields):
        raise RuntimeError("state_spec drifted from SimState._fields")
    if cfg.state_precision == "f32":
        return spec
    _check_compact(cfg)
    return {f: _compact_entry(_COMPACT_CODECS[f], shape, dtype)
            + (peer_major,)
            for f, (shape, dtype, peer_major) in spec.items()}


def encode_state(state: SimState, cfg: SimConfig) -> SimState:
    """The STORED representation of a compute-layout state (the scan
    carry, checkpoints, HBM-resident planes). Identity under
    ``state_precision="f32"``; under "compact" applies _COMPACT_CODECS
    field by field. engine.step calls this at exit; callers holding a
    decoded state (init paths, trace replay) must encode before handing
    the state to a scan."""
    if cfg.state_precision == "f32":
        return state
    _check_compact(cfg)
    if state.mesh.dtype != jnp.bool_:
        raise TypeError(
            "encode_state: state is already in the compact storage "
            f"layout (mesh dtype {state.mesh.dtype})")
    from ..ops.bits import pack_bool
    tick = state.tick
    out = {}
    for f, codec in _COMPACT_CODECS.items():
        if codec is None:
            continue
        v = getattr(state, f)
        if codec == "bf16":
            out[f] = jax.lax.bitcast_convert_type(
                v.astype(jnp.bfloat16), jnp.uint16)
        elif codec == "tick16":
            rel = jnp.clip(v - tick, -_TICK16_SAT, _TICK16_SAT)
            out[f] = jnp.where(v == NEVER, _TICK16_NEVER,
                               rel).astype(jnp.int16)
        elif codec == "packK":
            out[f] = pack_bool(v)
        else:                                   # slot8
            out[f] = v.astype(jnp.int8)
    return state._replace(**out)


def decode_state(state: SimState, cfg: SimConfig) -> SimState:
    """Inverse of :func:`encode_state`: the f32/i32 compute layout every
    op consumes. Identity under "f32". The tick16 planes decode relative
    to ``state.tick``, so decode must see the SAME tick the encode saw —
    engine.step's decode-at-entry / encode-at-exit bracketing guarantees
    it (the tick increments inside the bracket)."""
    if cfg.state_precision == "f32":
        return state
    _check_compact(cfg)
    if state.mesh.dtype == jnp.bool_:
        raise TypeError(
            "decode_state: state is already in the compute layout")
    from ..ops.bits import unpack_bool
    tick = state.tick
    out = {}
    for f, codec in _COMPACT_CODECS.items():
        if codec is None:
            continue
        v = getattr(state, f)
        if codec == "bf16":
            out[f] = jax.lax.bitcast_convert_type(
                v, jnp.bfloat16).astype(jnp.float32)
        elif codec == "tick16":
            e = v.astype(jnp.int32)
            out[f] = jnp.where(e == _TICK16_NEVER, jnp.int32(int(NEVER)),
                               tick + e)
        elif codec == "packK":
            out[f] = unpack_bool(v, cfg.k_slots)
        else:                                   # slot8
            out[f] = v.astype(jnp.int32)
    return state._replace(**out)


def per_peer_byte_ceilings(cfg: SimConfig) -> dict:
    """field -> MAX bytes-per-peer each peer-major plane may price under
    ``cfg.state_precision`` — the audit contract
    (tests/test_state_precision.py walks state_spec against this). The
    ceilings are written as independent formulas, NOT derived from
    state_spec: a layout regression moves the spec, trips the audit, and
    must be re-priced here deliberately."""
    k, t, m = cfg.k_slots, cfg.n_topics, cfg.msg_window
    w, kw = (m + 31) // 32, (k + 31) // 32
    if cfg.state_precision == "compact":
        return dict(
            neighbors=4 * k, connected=4 * kw, outbound=4 * kw,
            reverse_slot=k, subscribed=t, nbr_subscribed=4 * t * kw,
            disconnect_tick=2 * k, direct=4 * kw, ip_group=4,
            app_score=4, malicious=1,
            mesh=4 * t * kw, fanout=4 * t * kw, fanout_lastpub=2 * t,
            backoff=2 * t * k, graft_tick=2 * t * k,
            mesh_active=4 * t * kw,
            first_message_deliveries=2 * t * k,
            mesh_message_deliveries=2 * t * k,
            mesh_failure_penalty=2 * t * k,
            invalid_message_deliveries=2 * t * k,
            behaviour_penalty=2 * k,
            gater_validate=4, gater_throttle=4, gater_last_throttle=2,
            gater_deliver=2 * k, gater_duplicate=2 * k,
            gater_ignore=2 * k, gater_reject=2 * k,
            have=4 * w, deliver_tick=2 * m, deliver_from=m,
            iwant_pending=4 * m,
        )
    return dict(
        neighbors=4 * k, connected=k, outbound=k, reverse_slot=4 * k,
        subscribed=t, nbr_subscribed=t * k, disconnect_tick=4 * k,
        direct=k, ip_group=4, app_score=4, malicious=1,
        mesh=t * k, fanout=t * k, fanout_lastpub=4 * t,
        backoff=4 * t * k, graft_tick=4 * t * k, mesh_active=t * k,
        first_message_deliveries=4 * t * k,
        mesh_message_deliveries=4 * t * k,
        mesh_failure_penalty=4 * t * k,
        invalid_message_deliveries=4 * t * k,
        behaviour_penalty=4 * k,
        gater_validate=4, gater_throttle=4, gater_last_throttle=4,
        gater_deliver=4 * k, gater_duplicate=4 * k, gater_ignore=4 * k,
        gater_reject=4 * k,
        have=4 * w, deliver_tick=4 * m, deliver_from=4 * m,
        iwant_pending=4 * m,
    )


def bucketed_edge_nbytes(cfg: SimConfig, per_bucket: bool = False):
    """field -> bytes of each K-axis edge plane under the degree-bucketed
    layout (sim/bucketed.py): the sum over buckets of the SAME field
    priced at the bucket's ``(n_rows, k_ceil)`` — so the codec
    (f32/compact) prices each bucket exactly as state_spec prices a
    dense graph of that shape — plus ``bucket_rev``, the flat int32
    reverse-index planes the packed exchanges gather through.

    ``per_bucket=True`` returns the UNsummed list instead: one
    ``{"rows": n_b, "k_ceil": k_b, <field>: bytes, ..., "bucket_rev": b}``
    per bucket — what the per-(bucket x shard) HBM gate and the dashboard
    price from (:func:`state_nbytes`, :func:`check_hbm_budget`)."""
    from .bucketed import EDGE_FIELDS, _buckets, check_bucketable
    check_bucketable(cfg)
    out = {f: 0 for f in EDGE_FIELDS}
    rev = 0
    buckets = []
    for _, n_b, k_b in _buckets(cfg):
        sub = dataclasses.replace(cfg, n_peers=n_b, k_slots=k_b,
                                  degree_buckets=None)
        sub_spec = state_spec(sub)
        entry = {"rows": n_b, "k_ceil": k_b}
        for f in EDGE_FIELDS:
            shape, dtype, _ = sub_spec[f]
            nb = int(np.prod(shape, dtype=np.int64)) \
                * np.dtype(dtype).itemsize
            out[f] += nb
            entry[f] = nb
        rev += n_b * k_b * 4
        entry["bucket_rev"] = n_b * k_b * 4
        buckets.append(entry)
    out["bucket_rev"] = rev
    return buckets if per_bucket else out


def state_nbytes(cfg: SimConfig, n_dev: int | dict = 1) -> dict:
    """Host-side accounting of the SimState HBM footprint: per-field bytes,
    the global total, and the per-shard bytes on an ``n_dev``-way peer
    sharding (peer-major fields divide their leading N; message tables and
    scalars replicate onto every shard). ``n_dev`` may also be a mesh dict
    like ``{'dcn': 2, 'peers': 4}`` (parallel/sharding.make_mesh_2d): the
    peer-major leading axis shards over EVERY mesh axis
    (state_partition_specs names them all), so per-shard divides by the
    product. This is the number a frontier config must fit under the
    per-chip HBM budget BEFORE anything is allocated — bench.py records
    it next to the measured peak."""
    mesh = None
    if isinstance(n_dev, dict):
        mesh = dict(n_dev)
        n_dev = int(np.prod(list(mesh.values()), dtype=np.int64))
    n = cfg.n_peers
    if n_dev <= 0 or n % n_dev:
        raise ValueError(
            f"state_nbytes: n_peers={n} must divide evenly over "
            f"n_dev={n_dev} (the peer sharding raises the same)")
    fields, total, per_shard = {}, 0, 0
    for f, (shape, dtype, peer_major) in state_spec(cfg).items():
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        fields[f] = nbytes
        total += nbytes
        per_shard += nbytes // n_dev if peer_major else nbytes
    bucket_shards = None
    if cfg.degree_buckets is not None:
        # reprice the K-axis planes at the bucketed layout: each edge
        # plane is padded to its bucket's ceiling instead of k_slots, so
        # resting bytes scale with sum-of-degrees, not N * D_max. The
        # row-sharded plane splits EVERY bucket's rows over the mesh
        # (parallel/sharding.bucketed_partition_specs), so per-shard sums
        # each (bucket x field) plane's own ceiling split — exact when
        # the partition is aligned (topology.align_degree_buckets),
        # a one-row ceiling otherwise.
        agg = {f: 0 for f in bucketed_edge_nbytes(cfg)}
        bucket_shards = []
        for entry in bucketed_edge_nbytes(cfg, per_bucket=True):
            shard_entry = {"rows": entry["rows"], "k_ceil": entry["k_ceil"]}
            for f, nb in entry.items():
                if f in ("rows", "k_ceil"):
                    continue
                agg[f] += nb
                shard_entry[f] = -(-nb // n_dev)
                per_shard += shard_entry[f]
            bucket_shards.append(shard_entry)
        for f, nbytes in agg.items():
            old = fields.get(f, 0)
            fields[f] = nbytes
            total += nbytes - old
            per_shard -= old // n_dev
    out = {"total": total, "per_shard": per_shard, "n_dev": n_dev,
           "fields": fields}
    if bucket_shards is not None:
        out["bucket_shards"] = bucket_shards
    if mesh is not None:
        out["mesh"] = mesh
    return out


def hbm_budget_bytes() -> int | None:
    """The ``GRAFT_HBM_BUDGET`` gate value in bytes (suffixes KiB / MiB /
    GiB / K / M / G accepted, case-insensitive); None when unset/empty."""
    raw = os.environ.get("GRAFT_HBM_BUDGET", "").strip()
    if not raw:
        return None
    low = raw.lower()
    mult = 1
    for suf, m in (("kib", 2 ** 10), ("mib", 2 ** 20), ("gib", 2 ** 30),
                   ("k", 2 ** 10), ("m", 2 ** 20), ("g", 2 ** 30)):
        if low.endswith(suf):
            low, mult = low[: -len(suf)], m
            break
    try:
        return int(float(low) * mult)
    except ValueError as e:
        raise ValueError(
            f"GRAFT_HBM_BUDGET={raw!r}: expected bytes with an optional "
            "KiB/MiB/GiB suffix") from e


def check_hbm_budget(cfg: SimConfig, n_dev: int | dict = 1,
                     budget: int | None = None, what: str = "state") -> dict:
    """Price the state and REFUSE (ValueError naming the worst planes)
    when the per-shard bytes exceed the budget — accounting BEFORE
    allocation, so a 10M launch fails by name instead of OOMing the host
    it was going to kill anyway. ``budget=None`` reads GRAFT_HBM_BUDGET;
    with no gate set the pricing is returned and nothing raises.
    Launchers (scripts/run_multihost.py, bench.py) call this before
    building a single array."""
    acct = state_nbytes(cfg, n_dev)
    if budget is None:
        budget = hbm_budget_bytes()
    if budget is None or acct["per_shard"] <= budget:
        return acct
    if "bucket_shards" in acct:
        # name the worst (field x bucket) plane: the row-sharded bucketed
        # plane prices each bucket's rows across the mesh, so the refusal
        # points at the exact slab to re-partition, not an aggregate.
        per_bucket = []
        for b, entry in enumerate(acct["bucket_shards"]):
            tag = f"b{b} {entry['rows']}x{entry['k_ceil']}"
            per_bucket += [(f"{f}[{tag}]", nb) for f, nb in entry.items()
                           if f not in ("rows", "k_ceil")]
        spec = state_spec(cfg)
        edge = set(f for e in acct["bucket_shards"] for f in e)
        per_bucket += [(f, b // acct["n_dev"] if f not in spec or spec[f][2]
                        else b)
                       for f, b in acct["fields"].items() if f not in edge]
        worst = sorted(per_bucket, key=lambda kv: -kv[1])[:4]
    else:
        spec = state_spec(cfg)
        # fields absent from the spec (the bucketed layout's synthetic
        # bucket_rev plane) are peer-major by construction
        shard_fields = {f: (b // acct["n_dev"]
                            if f not in spec or spec[f][2] else b)
                        for f, b in acct["fields"].items()}
        worst = sorted(shard_fields.items(), key=lambda kv: -kv[1])[:4]
    names = ", ".join(f"{f}={b / 2 ** 20:.1f}MiB" for f, b in worst)
    raise ValueError(
        f"GRAFT_HBM_BUDGET: {what} prices "
        f"{acct['per_shard'] / 2 ** 30:.2f} GiB/shard on {acct['n_dev']} "
        f"shards, over the {budget / 2 ** 30:.2f} GiB budget "
        f"(n_peers={cfg.n_peers}, "
        f"state_precision={cfg.state_precision!r}); worst fields: "
        f"{names}. Shrink the config, raise the budget, or set "
        "state_precision='compact'.")


def init_state(cfg: SimConfig, topo: Topology,
               subscribed: np.ndarray | None = None,
               ip_group: np.ndarray | None = None,
               app_score: np.ndarray | None = None,
               malicious: np.ndarray | None = None) -> SimState:
    """Assemble the host-side inputs, then build the full state pytree ON
    DEVICE in one jitted program: seven input transfers instead of ~30
    per-leaf transfers, and every zeros/full leaf is allocated by the
    compiled program rather than pushed over the host link."""
    n, t = cfg.n_peers, cfg.n_topics
    if subscribed is None:
        subscribed = np.ones((n, t), dtype=bool)
    if ip_group is None:
        ip_group = np.zeros(n, np.int32)
    if app_score is None:
        app_score = np.zeros(n, np.float32)
    if malicious is None:
        malicious = np.zeros(n, bool)
    return _device_init(
        cfg, jnp.asarray(topo.neighbors), jnp.asarray(topo.outbound),
        jnp.asarray(topo.reverse_slot), jnp.asarray(subscribed),
        jnp.asarray(ip_group), jnp.asarray(app_score), jnp.asarray(malicious))


def refresh_nbr_subscribed(state: SimState) -> SimState:
    """Recompute the cached neighbor-subscription receiver view. MUST be
    called after any mutation of ``state.subscribed`` (topic join/leave)."""
    n = state.subscribed.shape[0]
    nbr = jnp.clip(state.neighbors, 0, n - 1)
    view = jnp.transpose(state.subscribed[nbr], (0, 2, 1)) \
        & (state.neighbors >= 0)[:, None, :]
    return state._replace(nbr_subscribed=view)


@partial(jax.jit, static_argnames=("cfg", "n_rows"))
def _device_init(cfg: SimConfig, neighbors, outbound, reverse_slot,
                 subscribed, ip_group, app_score, malicious,
                 nbr_subscribed=None, n_rows: int | None = None) -> SimState:
    # n_rows < n_peers builds a host-local shard: only that many peer rows
    # of every peer-major plane (parallel/multihost.init_state_local), with
    # the receiver view arriving PRECOMPUTED (it indexes the full
    # subscription table, which only exists host-side there)
    n = cfg.n_peers if n_rows is None else n_rows
    k, t, m = cfg.k_slots, cfg.n_topics, cfg.msg_window
    f32 = lambda *shape: jnp.zeros(shape, jnp.float32)  # noqa: E731
    i32 = lambda *shape, fill=0: jnp.full(shape, fill, jnp.int32)  # noqa: E731
    b = lambda *shape: jnp.zeros(shape, bool)  # noqa: E731
    if nbr_subscribed is None:
        nbr_subscribed = jnp.transpose(
            subscribed[jnp.clip(neighbors, 0, cfg.n_peers - 1)], (0, 2, 1)) \
            & (neighbors >= 0)[:, None, :]
    raw = SimState(
        tick=jnp.int32(0),
        neighbors=neighbors,
        connected=neighbors >= 0,
        outbound=outbound,
        reverse_slot=reverse_slot,
        subscribed=subscribed,
        nbr_subscribed=nbr_subscribed,
        disconnect_tick=i32(n, k, fill=int(NEVER)),
        direct=b(n, k),
        ip_group=ip_group,
        app_score=app_score,
        malicious=malicious,
        mesh=b(n, t, k),
        fanout=b(n, t, k),
        fanout_lastpub=i32(n, t, fill=int(NEVER)),
        backoff=i32(n, t, k),
        graft_tick=i32(n, t, k, fill=int(NEVER)),
        mesh_active=b(n, t, k),
        first_message_deliveries=f32(n, t, k),
        mesh_message_deliveries=f32(n, t, k),
        mesh_failure_penalty=f32(n, t, k),
        invalid_message_deliveries=f32(n, t, k),
        behaviour_penalty=f32(n, k),
        gater_validate=f32(n),
        gater_throttle=f32(n),
        gater_last_throttle=i32(n, fill=-int(NEVER)),
        gater_deliver=f32(n, k),
        gater_duplicate=f32(n, k),
        gater_ignore=f32(n, k),
        gater_reject=f32(n, k),
        msg_topic=i32(m, fill=-1),
        msg_publish_tick=i32(m, fill=int(NEVER)),
        msg_invalid=b(m),
        msg_ignored=b(m),
        msg_publisher=i32(m, fill=-1),
        have=jnp.zeros((n, n_msg_words(cfg)), jnp.uint32),
        deliver_tick=i32(n, m, fill=int(NEVER)),
        deliver_from=i32(n, m, fill=-1),
        iwant_pending=i32(n, m, fill=-1),
        delivered_total=jnp.float32(0.0),
        halo_overflow=jnp.int32(0),
        fault_flags=jnp.uint32(0),
    )
    # the state ships in its STORED layout (identity under "f32"): every
    # consumer — scans, checkpoints, shardings — holds encoded planes
    return encode_state(raw, cfg)
