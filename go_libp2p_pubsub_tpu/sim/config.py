"""Static configuration for the batched simulation engine.

``SimConfig`` holds jit-static integers/floats (shapes, degree bounds, tick
conversions) derived from GossipSubParams (gossipsub.go:32-60) plus the
simulation capacities (SURVEY.md §7 "Dynamic sparse structures on TPU":
fixed-capacity padded buffers with occupancy masks everywhere).

``TopicParams`` holds the per-topic score parameters as [T]-shaped device
arrays (score_params.go:117-170 vectorized over topics).

All durations are expressed in heartbeat ticks: the virtual-clock domain is
quantized so DecayInterval (1s default) == HeartbeatInterval == 1 tick
(score_params.go:401, SURVEY.md §7 "Time").
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..core.params import GossipSubParams, PeerScoreThresholds, TopicScoreParams


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Jit-static simulation shape + gossipsub knobs (in ticks)."""

    n_peers: int
    k_slots: int              # max neighbors per peer (adjacency capacity)
    n_topics: int = 1
    msg_window: int = 128     # active message slots (rotating)
    publishers_per_tick: int = 4
    # router variant: "gossipsub" (mesh), "floodsub" (all topic peers,
    # floodsub.go:76-100), "randomsub" (random max(D, sqrt N), randomsub.go:99-160)
    router: str = "gossipsub"
    # WithFloodPublish (gossipsub.go:321-327): a publisher sends its OWN
    # messages to every topic peer it scores >= publish_threshold, not just
    # its mesh (gossipsub.go:989-1004); forwarding stays mesh-only
    flood_publish: bool = False
    prop_substeps: int = 8    # intra-tick forwarding hops (mesh diameter bound)

    # overlay degree bounds (gossipsub.go:32-40)
    d: int = 6
    dlo: int = 5
    dhi: int = 12
    dscore: int = 4
    dout: int = 2
    dlazy: int = 6
    gossip_factor: float = 0.25

    # windows, in ticks (gossipsub.go:37-58 durations / 1s heartbeat)
    history_length: int = 5
    history_gossip: int = 3
    fanout_ttl_ticks: int = 60
    prune_backoff_ticks: int = 60
    unsubscribe_backoff_ticks: int = 10
    opportunistic_graft_ticks: int = 60
    opportunistic_graft_peers: int = 2
    graft_flood_ticks: int = 10
    # IHAVE flood protection (gossipsub.go:57-58, 654-676): cap on message
    # ids a peer will IWANT per heartbeat (the ``iasked`` counter vs
    # MaxIHaveLength; counters reset every tick, gossipsub.go:1608-1618)
    max_iwant_per_tick: int = 5000

    # score thresholds (score_params.go:12-35)
    gossip_threshold: float = 0.0
    publish_threshold: float = 0.0
    graylist_threshold: float = 0.0
    accept_px_threshold: float = 0.0
    opportunistic_graft_threshold: float = 0.0

    # global score params (score_params.go:66-115)
    topic_score_cap: float = 0.0
    app_specific_weight: float = 0.0
    ip_colocation_factor_weight: float = 0.0
    ip_colocation_factor_threshold: int = 1
    n_ip_groups: int = 1      # static bound for colocation bincount
    behaviour_penalty_weight: float = 0.0
    behaviour_penalty_threshold: float = 0.0
    behaviour_penalty_decay: float = 0.999
    decay_to_zero: float = 0.01
    retain_score_ticks: int = 0

    # P3 window in ticks; default 10ms << 1 tick -> same-round only
    mesh_message_deliveries_window_ticks: int = 0

    scoring_enabled: bool = True

    # reverse-edge permutation gather formulation (ops/permgather.py):
    # "auto" (measured cost-model dispatch, ops/dispatch.py) | "scalar" |
    # "rows" | "sort" | "pallas" | "mxu" — "mxu" routes EVERY gather
    # through the gather-free two-level MXU take (ops/mxutake.py): the
    # word tables (hop gathers, the packed edge exchange via its
    # bit-table, the IWANT answer table riding the exchange as extra word
    # rows) AND the generic [N, K] payload permute (the blocked one-hot
    # take) — zero serialized scalar HBM gathers, the one formulation the
    # Mosaic 128-lane gather wall cannot block. "auto" ranks candidates
    # by the dispatch table (GRAFT_DISPATCH_TABLE loads a calibrated one;
    # the shipped default reproduces the measured sort-era picks)
    edge_gather_mode: str = "auto"

    # masked selection formulation (ops/selection.py):
    # "auto" (cost-model dispatch) | "ranks" | "sort" | "iter"
    selection_mode: str = "auto"

    # forwarding-hop formulation (ops/hopkernel.py): "auto" | "xla" |
    # "pallas" | "pallas-mxu" — the fused Pallas hop needs cap-free/
    # gater-free/provenance-free configs and falls back to the XLA hop
    # otherwise; "auto" ranks through ops/dispatch.py (xla everywhere
    # under the shipped conservative table: the Mosaic gather wall
    # quarantines "pallas", and "pallas-mxu" is priced at its streamed
    # worst case until a live window calibrates). "pallas-mxu" is the
    # fused design with the in-kernel gathers rewritten as the
    # gather-free two-level one-hot select (ops/mxutake.py) — the S1-S7
    # resurrection candidate; any peer count works (out-of-kernel pad
    # seam), subject to the VMEM block gates (GRAFT_HOP_MODE sweep knob)
    hop_mode: str = "auto"

    # sort-mode routing under a sharded step (parallel/halo.py):
    # "replicated" lowers the global sorts via all-gathers (correct,
    # unscaled); "halo" routes per-shard with one all_to_all of padded
    # buckets (scales with devices; capacity-factor assumption on random
    # underlays, overflow poisons rather than drops)
    sharded_route: str = "replicated"
    # halo bucket capacity over the uniform mean (parallel/halo.py
    # CAPACITY RULE). 4 covers random underlays ~3x over their measured
    # worst bucket; clustered underlays must set this to
    # halo.required_capacity_factor(neighbors, reverse_slot, n_dev)
    halo_capacity_factor: int = 4

    # dtype of the per-hop delivery-event count accumulators
    # (ops/propagate.py, PERF_MODEL.md S3): "uint8" minimizes HBM bytes;
    # "int32" trades 4x bytes for native 32-bit vector ops — TPU emulates
    # sub-word lanes with masking, a live-window ablation candidate for
    # the ~16 ms/hop of non-gather math. Trajectories are bit-identical
    # either way (counts are bounded by msg_window and land in f32).
    count_dtype: str = "uint8"

    # record delivery provenance (msg_publisher / deliver_from) so a run can
    # be exported as a pb/trace event stream (sim/trace_export.py); when on
    # it costs a bit-plane decode + two scatters per tick, when off
    # deliver_from is a dormant buffer no hot-path op touches
    record_provenance: bool = False

    # --- peer gater (peer_gater.go:19-116), ticks domain; off by default so
    # non-gater configs compile the same op graph (RNG streams shifted by
    # the extra key splits, so trajectories differ from round-1 builds) ---
    gater_enabled: bool = False
    gater_threshold: float = 0.33          # throttled/validated RED trigger
    gater_global_decay: float = 0.9928     # ScoreParameterDecay(2 min) @ 1s ticks
    gater_source_decay: float = 0.999808   # ScoreParameterDecay(1 hour)
    gater_quiet_ticks: int = 60            # auto-off after quiet period
    gater_duplicate_weight: float = 0.125
    gater_ignore_weight: float = 1.0
    gater_reject_weight: float = 16.0
    # validation pipeline admission cap (validation.go:13-17 queue sizes):
    # max NEW messages a receiver admits per tick; excess is throttled —
    # dropped unseen and counted into the gater's throttle stat
    # (validation.go:246-260 Push drop-on-full). 0 = unbounded.
    validation_queue_cap: int = 0
    # fraction of honest publishes whose validation verdict is IGNORE
    # (validation.go:344-370 ValidationIgnore: dropped + marked seen, no P4)
    ignore_fraction: float = 0.0
    # per-edge data-plane capacity (comm.go:156-191: the 32-RPC per-peer
    # queue, drop-on-full traced at gossipsub.go:1195-1202): max messages an
    # edge carries per tick; a hop whose RPC would blow the budget is dropped
    # whole (the reference drops entire RPCs). 0 = unbounded.
    edge_queue_cap: int = 0

    # connection churn per tick (0.0 = off; ops/churn.py). Models the
    # dead-peer / reconnect lifecycle (pubsub.go:711-757, notify.go:11-75).
    churn_disconnect_prob: float = 0.0
    churn_reconnect_prob: float = 0.0
    # PX-seeded reconnects (gossipsub.go:893-973 pxConnect): a down edge
    # whose remote side the reconnecting peer scores >= accept_px_threshold
    # reconnects at churn_reconnect_prob (a PX referral re-seeds the dial);
    # below-threshold edges fall back to px_low_score_factor of that rate
    # (no referral — only slow ambient discovery brings them back).
    px_enabled: bool = False
    px_low_score_factor: float = 0.1
    # forced redial cadence for direct peers (gossipsub.go:1648-1670), ticks
    direct_connect_ticks: int = 300
    # subscription churn per tick (0.0 = off): peers Leave topics (PRUNE all
    # mesh members with the unsubscribe backoff, gossipsub.go:1104-1124) and
    # Join them back (promoting live fanout edges, gossipsub.go:1047-1102)
    sub_leave_prob: float = 0.0
    sub_join_prob: float = 0.0

    # declarative fault injection (sim/faults.py FaultPlan): link drop/
    # duplication, partition + outage tick schedules, honest-publish
    # corruption — applied by engine.step each tick. None (default)
    # compiles the identical plan-free program with the identical RNG
    # stream; the plan is frozen/hashable, so it rides the jit-static
    # config like every other knob
    fault_plan: object | None = None
    # invariant sentinel escalation (sim/invariants.py): "record" ORs
    # injected-fault + violation bits into SimState.fault_flags each tick
    # (default — the flags travel with every bench line); "raise"
    # additionally escalates violations via jax.experimental.checkify
    # (callers must use engine.run_checked); "off" skips checks and flag
    # writes entirely
    invariant_mode: str = "record"
    # per-tick key derivation schedule (ISSUE 12): "host" pre-splits ONE
    # master key into [n_ticks] per-tick keys on the host and ships the
    # window in (the historical discipline — kept as default because
    # fold_in provably CANNOT reproduce the split tree's streams);
    # "fold_in" derives each tick's key inside the scan as
    # jax.random.fold_in(master, state.tick) — no host pre-split, no
    # shipped [C, 2] key window, and chunking-invariance/resume-
    # consistency by construction (the key depends only on the master
    # and the ABSOLUTE tick the state carries). Parity is pinned PER
    # schedule (tests/test_overlap.py); the schedules' trajectories
    # intentionally differ from each other.
    key_schedule: str = "host"
    # stored precision of the scan carry (sim/state.py codec tables):
    # "f32" keeps the historical layout bit-exact; "compact" stores the
    # f32 score-counter planes as bf16 bit patterns (u16), the bounded
    # tick planes as i16 relative-to-current-tick, the [N,*,K] bool
    # planes bit-packed into u32 words (the `have` discipline), and the
    # slot-index planes as i8 — compute stays f32/i32: engine.step
    # decodes at entry and re-encodes at exit, so ops never see the
    # narrow types. Roughly halves the per-peer HBM bytes (PERF_MODEL
    # "Frontier memory budget"); trajectories agree within the
    # documented tolerance (tests/test_state_precision.py)
    state_precision: str = "f32"
    # exact halo bucket capacity (entries per (src_dev, dest_dev)
    # bucket). 0 = derive from halo_capacity_factor's uniform-degree
    # rule; a positive value (e.g. halo.required_bucket_capacity's
    # answer for a heavy-tailed underlay) overrides the factor rule so
    # clustered topologies neither overflow nor over-allocate
    halo_bucket_capacity: int = 0
    # degree-bucketed edge planes (sim/bucketed.py, ISSUE 15): peers are
    # partitioned host-side at topology build into contiguous id-ordered
    # degree classes, ``((n_rows, k_ceil), ...)`` with Σ n_rows ==
    # n_peers and k_slots == the first (hub) bucket's ceiling; every
    # [N, K]-adjacent edge plane is stored per bucket padded only to
    # that bucket's ceiling, and the per-edge ops run once per bucket at
    # the bucket's width — per-tick cost and resting HBM scale with the
    # true edge count ΣD = Σ_b n_rows_b·k_ceil_b instead of N·D_max.
    # None (default) is the dense-uniform fast path: byte-identical
    # state layout, HLO, and RNG stream to every pre-bucketing build.
    # topology.powerlaw_buckets derives the partition a powerlaw graph
    # induces.
    degree_buckets: tuple | None = None
    # RNG discipline for the bucketed step's K-shaped draws (selection
    # noise, churn, gater, link faults): "dense" draws them at the full
    # [N, ..., k_slots] shape and slices per bucket — the bucketed
    # trajectory is then BIT-EXACT vs the dense-padded reference on the
    # same graph (what tests/test_bucketed.py pins), at dense-RNG cost;
    # "bucket" folds the bucket index into the key and draws at bucket
    # width — ΣD-scaling cost (the perf configuration), statistically
    # equivalent but a different stream, so trajectories diverge from
    # the dense reference. Ignored when degree_buckets is None.
    bucketed_rng: str = "dense"

    @staticmethod
    def from_params(n_peers: int, k_slots: int, n_topics: int = 1,
                    params: GossipSubParams | None = None,
                    thresholds: PeerScoreThresholds | None = None,
                    **overrides) -> "SimConfig":
        p = params or GossipSubParams()
        th = thresholds or PeerScoreThresholds()
        hb = p.heartbeat_interval
        kw = dict(
            n_peers=n_peers, k_slots=k_slots, n_topics=n_topics,
            d=p.d, dlo=p.dlo, dhi=p.dhi, dscore=p.dscore, dout=p.dout,
            dlazy=p.dlazy, gossip_factor=p.gossip_factor,
            history_length=p.history_length, history_gossip=p.history_gossip,
            fanout_ttl_ticks=max(1, int(p.fanout_ttl / hb)),
            prune_backoff_ticks=max(1, int(p.prune_backoff / hb)),
            unsubscribe_backoff_ticks=max(1, int(p.unsubscribe_backoff / hb)),
            opportunistic_graft_ticks=int(p.opportunistic_graft_ticks),
            opportunistic_graft_peers=p.opportunistic_graft_peers,
            graft_flood_ticks=max(1, int(p.graft_flood_threshold / hb)),
            max_iwant_per_tick=p.max_ihave_length,
            gossip_threshold=th.gossip_threshold,
            publish_threshold=th.publish_threshold,
            graylist_threshold=th.graylist_threshold,
            accept_px_threshold=th.accept_px_threshold,
            opportunistic_graft_threshold=th.opportunistic_graft_threshold,
        )
        kw.update(overrides)
        return SimConfig(**kw)


class TopicParams(NamedTuple):
    """[T]-shaped per-topic score parameters (score_params.go:117-170)."""

    topic_weight: jnp.ndarray
    time_in_mesh_weight: jnp.ndarray
    time_in_mesh_quantum_ticks: jnp.ndarray   # >=1, integer ticks
    time_in_mesh_cap: jnp.ndarray
    first_message_deliveries_weight: jnp.ndarray
    first_message_deliveries_decay: jnp.ndarray
    first_message_deliveries_cap: jnp.ndarray
    mesh_message_deliveries_weight: jnp.ndarray
    mesh_message_deliveries_decay: jnp.ndarray
    mesh_message_deliveries_cap: jnp.ndarray
    mesh_message_deliveries_threshold: jnp.ndarray
    mesh_message_deliveries_activation_ticks: jnp.ndarray
    mesh_failure_penalty_weight: jnp.ndarray
    mesh_failure_penalty_decay: jnp.ndarray
    invalid_message_deliveries_weight: jnp.ndarray
    invalid_message_deliveries_decay: jnp.ndarray

    @staticmethod
    def from_topic_params(topics: list[TopicScoreParams],
                          heartbeat_interval: float = 1.0) -> "TopicParams":
        """Pack a list of per-topic params into [T] arrays (ticks domain).

        All 16 rows travel to the device as ONE [16, T] transfer (one host
        link round-trip instead of sixteen tiny ones)."""
        hb = heartbeat_interval
        getters = [
            lambda t: t.topic_weight,
            lambda t: t.time_in_mesh_weight,
            lambda t: max(t.time_in_mesh_quantum / hb, 1e-9),
            lambda t: t.time_in_mesh_cap,
            lambda t: t.first_message_deliveries_weight,
            lambda t: t.first_message_deliveries_decay if t.first_message_deliveries_decay else 1.0,
            lambda t: t.first_message_deliveries_cap if t.first_message_deliveries_cap else math.inf,
            lambda t: t.mesh_message_deliveries_weight,
            lambda t: t.mesh_message_deliveries_decay if t.mesh_message_deliveries_decay else 1.0,
            lambda t: t.mesh_message_deliveries_cap if t.mesh_message_deliveries_cap else math.inf,
            lambda t: t.mesh_message_deliveries_threshold,
            lambda t: t.mesh_message_deliveries_activation / hb,
            lambda t: t.mesh_failure_penalty_weight,
            lambda t: t.mesh_failure_penalty_decay if t.mesh_failure_penalty_decay else 1.0,
            lambda t: t.invalid_message_deliveries_weight,
            lambda t: t.invalid_message_deliveries_decay if t.invalid_message_deliveries_decay else 1.0,
        ]
        mat = jnp.asarray(np.array([[g(t) for t in topics] for g in getters],
                                   dtype=np.float32))
        return TopicParams(*mat)

    @staticmethod
    def disabled(n_topics: int) -> "TopicParams":
        """All-zero-weight params (scoring effectively off) for T topics."""
        return TopicParams.from_topic_params(
            [TopicScoreParams(skip_atomic_validation=True, time_in_mesh_quantum=1.0)
             for _ in range(n_topics)])


# ---------------------------------------------------------------------------
# P1–P7 score-weight override helper (sweeps, tests, fleets)

# short P-names → the per-topic TopicParams weight rows (score.go P1–P4;
# P3b is the mesh failure penalty leg of P3)
_TP_WEIGHT_ALIASES = {
    "p1": "time_in_mesh_weight",
    "p2": "first_message_deliveries_weight",
    "p3": "mesh_message_deliveries_weight",
    "p3b": "mesh_failure_penalty_weight",
    "p4": "invalid_message_deliveries_weight",
}
# short P-names → the GLOBAL SimConfig weights (score.go P5–P7). These are
# jit-STATIC floats: varying one forks the compiled program, so a fleet
# sweep batches P1–P4 variants in one vmapped scan while P5–P7 variants
# land in separate fleet groups (sim/fleet.py grouping).
_CFG_WEIGHT_ALIASES = {
    "p5": "app_specific_weight",
    "p6": "ip_colocation_factor_weight",
    "p7": "behaviour_penalty_weight",
}
# every key with_score_weights accepts (aliases + full field names) —
# consulted by scripts/sweep_scores.py to split a variant spec into
# weight overrides vs. plain config overrides
SCORE_WEIGHT_KEYS = frozenset(
    list(_TP_WEIGHT_ALIASES) + list(_TP_WEIGHT_ALIASES.values())
    + list(_CFG_WEIGHT_ALIASES) + list(_CFG_WEIGHT_ALIASES.values()))


def with_score_weights(base: TopicParams, cfg: SimConfig | None = None,
                       **overrides):
    """``base`` with P1–P7 score-weight overrides applied — the sweep/test
    constructor that replaces hand-editing weight arrays.

    Keys are short P-names (``p1``/``p2``/``p3``/``p3b``/``p4`` →
    TopicParams rows, ``p5``/``p6``/``p7`` → SimConfig globals) or the
    full field names. Topic-level values may be scalars (broadcast over
    all T topics) or [T] sequences. Returns the new TopicParams, or
    ``(TopicParams, SimConfig)`` when ``cfg`` is passed; overriding a
    P5–P7 weight WITHOUT ``cfg`` raises (those weights live on SimConfig,
    and silently dropping them would fake a sweep variant)."""
    tp_kw: dict = {}
    cfg_kw: dict = {}
    t = base.topic_weight.shape[0]
    for key, val in overrides.items():
        field = _TP_WEIGHT_ALIASES.get(key, key)
        if field in TopicParams._fields:
            arr = jnp.broadcast_to(
                jnp.asarray(val, jnp.float32), (t,))
            tp_kw[field] = arr
            continue
        field = _CFG_WEIGHT_ALIASES.get(key, key)
        if field in _CFG_WEIGHT_ALIASES.values():
            if cfg is None:
                raise ValueError(
                    f"score weight {key!r} is the jit-static SimConfig "
                    f"field {field!r}; pass cfg= to override it "
                    "(with_score_weights(tp, cfg=cfg, ...))")
            cfg_kw[field] = float(val)
            continue
        raise ValueError(
            f"unknown score weight {key!r}; expected one of "
            f"{sorted(SCORE_WEIGHT_KEYS)}")
    out_tp = base._replace(**tp_kw) if tp_kw else base
    if cfg is None:
        return out_tp
    out_cfg = dataclasses.replace(cfg, **cfg_kw) if cfg_kw else cfg
    return out_tp, out_cfg
