"""Streaming telemetry plane: device-side health reduction + journal sink.

ROADMAP item 5: a 100k-peer run at the 1000 hb/s bar emits far more trace
events than the Python JSON sinks can swallow, so analysis has been
post-hoc files and unattended TPU windows ran blind. This module turns L5
into a streaming pipeline built on one idea: **reduce on device, ship
aggregates**. A :class:`HealthRecord` — per-topic delivery fraction, mesh
degree min/mean/max, backoff/graylist census, score stats, publish and
deliver counters, ``halo_overflow`` and the ``fault_flags`` health word —
is computed INSIDE the scan for every tick and stacked into a ``[C, ...]``
device buffer, so one ``device_get`` per chunk boundary replaces a
per-tick state diff (the ``run_traced`` event export syncs the host every
tick; PERF_MODEL.md "Tracing overhead" prices the difference).

The wiring (one record schema, every execution plane):

- ``engine.run_keys(..., telemetry=True)`` / ``run_checked_keys`` return
  ``(state, HealthRecord)`` with ``[C]``-stacked leaves;
- ``sim.fleet`` stacks a fleet axis: ``[C, B]`` leaves, per-member rows;
- ``parallel.sharding.make_sharded_run_keys(..., telemetry=True)`` emits
  the records REPLICATED from the sharded scan (the reductions ride the
  same collectives as the step; every rank holds the aggregates, only
  rank 0 writes — the multihost journal discipline);
- ``sim.supervisor`` streams each successful chunk's records to a fsync'd
  ``health.jsonl`` journal (``SupervisorConfig.health_path`` /
  ``GRAFT_HEALTH_STREAM``), with run/chunk/checkpoint marker lines, so a
  crashed run leaves a readable stream up to its last good chunk;
- ``scripts/dashboard.py`` tails that journal live (``--once`` for a
  snapshot).

The sink hot path rides the native codec (``native/trace_codec.cpp``
``trace_codec_health_json``) when it loads — one C call formats a whole
chunk's rows to NDJSON — with the pure-Python encoder as fallback
(identical parsed values; tests pin parity).

Parity contract (tests/test_telemetry.py): the streamed records are
bit-identical to :func:`health_record` applied post-hoc to the state
trajectory — same function, same inputs, whether the scan stacked it or
vmap batched it. Under the SPMD-sharded step one column is exempt:
``score_mean`` sums arbitrary f32 values across shards, and per-shard
partial sums reassociate (~1 ulp vs the unsharded order). Every other
column stays exact even sharded — the censuses are integer counts, the
delivery/mesh sums are integer-valued f32 accumulations (exact below
2^24 regardless of order), and min/max are order-free.
"""

from __future__ import annotations

import json
import os
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import SimConfig, TopicParams
from .state import SimState, unpack_have

# sentinels as numpy scalars (module-level jnp constants leak stale
# tracers across fleet-group retraces — sim/state.py NEVER rationale)
_BIG_I32 = np.int32(2**30)
_BIG_F32 = np.float32(3.0e38)


class HealthRecord(NamedTuple):
    """Per-tick device-side aggregates. Every leaf is a scalar except
    ``delivery_frac`` (``[T]``); the scan stacks a leading ``[C]`` axis
    and the fleet plane a ``[C, B]`` axis. ``tick`` is the tick that RAN
    (the record describes the state AFTER that tick — the same numbering
    ``run_traced``'s health rows always used)."""

    tick: jnp.ndarray             # i32: the tick this record closes
    delivery_frac: jnp.ndarray    # [T] f32 per-topic settled delivery
    mesh_deg_min: jnp.ndarray     # i32 over subscribed (peer, topic) pairs
    mesh_deg_mean: jnp.ndarray    # f32
    mesh_deg_max: jnp.ndarray     # i32
    backoff_count: jnp.ndarray    # i32 live backoff entries (expiry > tick)
    graylist_count: jnp.ndarray   # i32 connected edges scored below
                                  #   graylist_threshold (AcceptFrom gate)
    connected_edges: jnp.ndarray  # i32 connected neighbor slots (total)
    attacker_edges: jnp.ndarray   # i32 connected slots whose REMOTE peer
                                  #   is an attacker (sim/faults.py
                                  #   attacker_mask: sybils + censor
                                  #   cohorts) — the score-response
                                  #   contract's denominator
    attacker_graylisted: jnp.ndarray  # i32 attacker edges below the
                                  #   graylist threshold (the response)
    honest_graylisted: jnp.ndarray    # i32 graylisted edges to HONEST
                                  #   peers (collateral damage — the
                                  #   contract's "honest peers not" leg)
    score_mean: jnp.ndarray       # f32 over connected slots
    score_min: jnp.ndarray        # f32
    published_window: jnp.ndarray  # i32 live slots of the message window
    delivered_total: jnp.ndarray  # f32 cumulative delivery counter
    halo_overflow: jnp.ndarray    # i32 (poisoned-route counter)
    fault_flags: jnp.ndarray      # u32 health word (sim/invariants.py)


def health_record(state: SimState, cfg: SimConfig,
                  tp: TopicParams) -> HealthRecord:
    """The device-side reduction: one :class:`HealthRecord` for the state
    a just-completed tick left behind. Pure jnp over arrays the tick
    already touched — the cost is one fused reduce pass per plane plus
    one ``compute_scores`` read (the telemetry analogue of the heartbeat's
    own score pass; measured in PERF_MODEL.md "Tracing overhead"). The
    SAME function is the post-hoc path: applied to a stored trajectory it
    must reproduce the streamed records bit for bit."""
    from ..ops.score_ops import compute_scores

    if state.mesh.dtype != jnp.bool_:
        # the scan hands in the post-step carry, which travels in the
        # STORED layout (sim/state.py); reduce over the compute layout
        from .state import decode_state
        state = decode_state(state, cfg)
    n, t_topics, k = state.mesh.shape
    tick = state.tick

    # --- per-topic settled delivery fraction (delivery_fraction, split
    # by topic via a segment-sum over the message window). The census
    # counts DELIVERABLE traffic only: invalid (sybil/corrupted) and
    # ignore-verdict messages are structurally undeliverable to honest
    # receivers (validation.go:293-370 — rejected messages never enter
    # the mcache), so counting them would fake a delivery deficit
    # proportional to the attacker publish share in every adversarial
    # scenario. A topic with an EMPTY census this tick reads 1.0
    # (vacuously delivered), not 0.0 — a storm that crowds topic B out
    # of the window must not report topic B as a delivery catastrophe
    # (the empty-census-is-not-zero rule of scripts/sweep_scores.py).
    # ATTACKER receivers (sim/faults.py attacker_mask) are excluded too:
    # a graylisted sybil that no honest peer still serves is the defense
    # WORKING — counting its starved rows would read every successful
    # eviction as a delivery failure. ---
    from .faults import attacker_mask

    age = tick - state.msg_publish_tick                       # [M]
    alive = (age < cfg.history_length) & (age >= 0)
    valid = state.msg_topic >= 0
    deliverable = valid & alive & ~state.msg_invalid & ~state.msg_ignored
    t_m = jnp.clip(state.msg_topic, 0, t_topics - 1)
    att = attacker_mask(state, cfg)                           # [N]
    should = state.subscribed[:, t_m] & ~att[:, None] \
        & deliverable[None, :]                                     # [N, M]
    got = unpack_have(state, cfg.msg_window) & should
    got_m = jnp.sum(got, axis=0).astype(jnp.float32)          # [M]
    should_m = jnp.sum(should, axis=0).astype(jnp.float32)
    zeros_t = jnp.zeros((t_topics,), jnp.float32)
    got_t = zeros_t.at[t_m].add(jnp.where(deliverable, got_m, 0.0))
    should_t = zeros_t.at[t_m].add(jnp.where(deliverable, should_m, 0.0))
    delivery_frac = jnp.where(should_t > 0.0,
                              got_t / jnp.maximum(should_t, 1.0), 1.0)

    # --- mesh degree over subscribed (peer, topic) pairs ---
    deg = jnp.sum(state.mesh, axis=-1).astype(jnp.int32)      # [N, T]
    sub = state.subscribed
    n_sub = jnp.sum(sub)
    any_sub = n_sub > 0
    deg_min = jnp.where(
        any_sub, jnp.min(jnp.where(sub, deg, _BIG_I32)), 0).astype(jnp.int32)
    deg_max = jnp.where(
        any_sub, jnp.max(jnp.where(sub, deg, -1)), 0).astype(jnp.int32)
    deg_mean = jnp.sum(jnp.where(sub, deg, 0)).astype(jnp.float32) \
        / jnp.maximum(n_sub, 1).astype(jnp.float32)

    # --- backoff / graylist census, split by the attacker mask ---
    # (`att` above — the score-response contract needs "attackers
    # graylisted, honest peers not" as two integer counts; integer sums
    # stay exact under the sharded step)
    backoff_count = jnp.sum(state.backoff > tick, dtype=jnp.int32)
    scores = compute_scores(state, cfg, tp, apply_decay=True)  # [N, K]
    gray = state.connected & (scores < cfg.graylist_threshold)
    graylist_count = jnp.sum(gray, dtype=jnp.int32)
    nbr_att = att[jnp.clip(state.neighbors, 0, n - 1)] \
        & (state.neighbors >= 0)                               # [N, K]
    attacker_edges = jnp.sum(state.connected & nbr_att, dtype=jnp.int32)
    attacker_graylisted = jnp.sum(gray & nbr_att, dtype=jnp.int32)
    honest_graylisted = graylist_count - attacker_graylisted

    # --- score stats over connected slots ---
    conn = state.connected
    n_conn = jnp.sum(conn)
    any_conn = n_conn > 0
    score_mean = jnp.sum(jnp.where(conn, scores, 0.0)) \
        / jnp.maximum(n_conn, 1).astype(jnp.float32)
    score_min = jnp.where(
        any_conn, jnp.min(jnp.where(conn, scores, _BIG_F32)), 0.0
    ).astype(jnp.float32)

    return HealthRecord(
        tick=(tick - 1).astype(jnp.int32),   # the tick that ran
        delivery_frac=delivery_frac,
        mesh_deg_min=deg_min,
        mesh_deg_mean=deg_mean,
        mesh_deg_max=deg_max,
        backoff_count=backoff_count,
        graylist_count=graylist_count,
        connected_edges=n_conn.astype(jnp.int32),
        attacker_edges=attacker_edges,
        attacker_graylisted=attacker_graylisted,
        honest_graylisted=honest_graylisted,
        score_mean=score_mean,
        score_min=score_min,
        published_window=jnp.sum(valid, dtype=jnp.int32),
        delivered_total=state.delivered_total,
        halo_overflow=state.halo_overflow,
        fault_flags=state.fault_flags,
    )


health_record_jit = jax.jit(health_record, static_argnames=("cfg",))


# ---------------------------------------------------------------------------
# row schema: one FLAT numeric row per (tick[, member]) — the same columns
# whether the run was plain, chunked, fleet-batched, or multihost, so one
# encoder (native or Python) and one dashboard read every journal

_INT_COLS = {"tick", "member", "mesh_deg_min", "mesh_deg_max",
             "backoff_count", "graylist_count", "connected_edges",
             "attacker_edges", "attacker_graylisted", "honest_graylisted",
             "published_window", "halo_overflow", "fault_flags"}


# first-class ingest vitals (the live command plane, sim/commands.py):
# one ``{"kind": "ingest", ...}`` journal marker per chunk with exactly
# these fields — the dashboard's ingest rows, bench.py's sustained-rate
# line, and the contract tests all read this schema, never ad-hoc keys.
# ``offset`` is the consumed stream byte cursor (the exactly-once resume
# stamp); ``coasting`` flags the stalled-producer degradation mode
INGEST_COLUMNS = ("tick", "directives", "shed", "shed_total",
                  "refused_total", "queue_depth", "lag_ticks", "offset",
                  "coasting")


def health_columns(n_topics: int) -> list:
    """Ordered ``(name, is_int)`` column schema of a journal health row.
    ``member`` is the fleet input index (-1 for an unbatched run);
    ``delivery_frac`` flattens to one column per topic."""
    names = ["tick", "member"] \
        + [f"delivery_frac_t{j}" for j in range(n_topics)] \
        + ["mesh_deg_min", "mesh_deg_mean", "mesh_deg_max", "backoff_count",
           "graylist_count", "connected_edges", "attacker_edges",
           "attacker_graylisted", "honest_graylisted",
           "score_mean", "score_min", "published_window",
           "delivered_total", "halo_overflow", "fault_flags"]
    return [(nm, nm in _INT_COLS) for nm in names]


# host value of a record leaf (sim/hostio.py is the shared unwrap: a
# multi-process replicated global array is not fully addressable, so the
# local replica is read instead)
from .hostio import fetch_local as _fetch  # noqa: E402


def records_to_rows(records: HealthRecord,
                    member_ids=None) -> tuple[np.ndarray, list]:
    """ONE host transfer for a whole chunk: fetch the stacked record
    leaves and lay them out as a float64 row matrix (tick-major; fleet
    members interleave within a tick). ``member_ids`` maps the fleet lane
    position to the member's input index (rows of an unbatched run carry
    member=-1). Returns ``(matrix [R, ncols], columns)``."""
    leaves = jax.tree.map(_fetch, records)
    tick = leaves.tick
    batched = tick.ndim == 2                    # [C, B] vs [C]
    c = tick.shape[0]
    b = tick.shape[1] if batched else 1
    t_topics = leaves.delivery_frac.shape[-1]
    cols = health_columns(t_topics)
    if member_ids is None:
        member_ids = list(range(b)) if batched else [-1]
    if len(member_ids) != b:
        raise ValueError(
            f"records_to_rows: {len(member_ids)} member ids for a "
            f"B={b} record batch")

    mat = np.empty((c * b, len(cols)), np.float64)
    # [C] and [C, B] both flatten tick-major (members interleave in-tick)
    mat[:, 0] = np.asarray(tick, np.float64).reshape(-1)
    mat[:, 1] = np.tile(np.asarray(member_ids, np.float64), c)
    mat[:, 2:2 + t_topics] = np.asarray(
        leaves.delivery_frac, np.float64).reshape(c * b, t_topics)
    scalar_fields = ["mesh_deg_min", "mesh_deg_mean", "mesh_deg_max",
                     "backoff_count", "graylist_count", "connected_edges",
                     "attacker_edges", "attacker_graylisted",
                     "honest_graylisted", "score_mean",
                     "score_min", "published_window", "delivered_total",
                     "halo_overflow", "fault_flags"]
    for i, f in enumerate(scalar_fields):
        mat[:, 2 + t_topics + i] = np.asarray(
            getattr(leaves, f), np.float64).reshape(c * b)
    return mat, cols


def record_to_row(record: HealthRecord, member: int = -1) -> dict:
    """One unstacked record as a flat row dict (run_traced's per-tick
    host path; the streamed path goes through :func:`records_to_rows`)."""
    stacked = jax.tree.map(lambda x: jnp.asarray(x)[None], record)
    mat, cols = records_to_rows(stacked, member_ids=[member])
    return rows_to_dicts(mat, cols)[0]


def rows_to_dicts(matrix: np.ndarray, columns: list) -> list:
    """Row matrix -> list of plain dicts (tests, dashboard, fallbacks)."""
    out = []
    for r in np.asarray(matrix, np.float64):
        out.append({nm: (int(v) if is_int else float(v))
                    for (nm, is_int), v in zip(columns, r)})
    return out


# ---------------------------------------------------------------------------
# NDJSON encoders: native hot path, Python fallback


def encode_rows_py(matrix: np.ndarray, columns: list) -> bytes:
    """Pure-Python NDJSON encoder (the fallback sink). Non-finite floats
    encode as null — NaN is not JSON and a reader must never choke on a
    degraded row."""
    lines = []
    for d in rows_to_dicts(matrix, columns):
        for k, v in d.items():
            if isinstance(v, float) and not np.isfinite(v):
                d[k] = None
        lines.append(json.dumps({"kind": "health", **d}))
    return ("\n".join(lines) + "\n").encode() if lines else b""


def encode_rows(matrix: np.ndarray, columns: list,
                prefer_native: bool = True) -> tuple[bytes, str]:
    """Encode a chunk's rows; ``(payload, encoder_name)``. The native
    codec formats the whole matrix in one C call; values parse back equal
    to the Python encoder's (float text differs — %.17g vs repr — but
    round-trips to the same doubles)."""
    if prefer_native:
        from ..trace.native import encode_health_json
        payload = encode_health_json(matrix, columns)
        if payload is not None:
            return payload, "native"
    return encode_rows_py(matrix, columns), "python"


# ---------------------------------------------------------------------------
# the journal sink


class HealthJournal:
    """Append-only fsync'd NDJSON health journal.

    Line kinds: ``run`` (header: config fingerprint, shape, schema),
    ``chunk`` (one per streamed chunk: window bounds + wall-clock stamp —
    the dashboard's hb/s source), ``health`` (the record rows),
    ``checkpoint`` / ``crash`` markers. By default every append ends in
    flush+fsync, so a kill leaves at most one torn tail line —
    :func:`read_journal` skips it and a resume keeps appending (readers
    dedup health rows by ``(member, tick)``, last wins).

    ``sync_every_write=False`` is the async supervisor's writer-thread
    mode (ISSUE 12): appends still flush to the OS in order (the marker
    discipline — a chunk line only exists once its device result was
    confirmed good), but the fsync is batched into an explicit
    :func:`sync` the writer issues once per queue drain instead of per
    chunk line. A crash between drains loses at most the un-synced tail,
    which the torn-tail reader and the ``(member, tick)`` dedup already
    absorb — the same contract a single torn line always had."""

    def __init__(self, path: str, prefer_native: bool = True,
                 sync_every_write: bool = True):
        self.path = path
        self.prefer_native = prefer_native
        self.sync_every_write = sync_every_write
        self.encoder = "python"
        self._dirty = False
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fh = open(path, "ab")

    def _write(self, payload: bytes) -> None:
        self._fh.write(payload)
        self._fh.flush()
        if self.sync_every_write:
            os.fsync(self._fh.fileno())
        else:
            self._dirty = True

    def sync(self) -> None:
        """fsync everything appended since the last sync (the batched
        counterpart of the default per-write fsync)."""
        if self._dirty and not self._fh.closed:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._dirty = False

    def note(self, kind: str, **meta) -> None:
        self._write((json.dumps({"kind": kind, "wall": time.time(),
                                 **meta}) + "\n").encode())

    def header(self, cfg: SimConfig, **meta) -> None:
        from . import checkpoint
        from .faults import attack_schedule
        from .invariants import FLAGS_VERSION

        # every journal records which fault_flags bit layout wrote it:
        # readers (dashboard, replay) refuse BY NAME to decode another
        # version's words instead of misreading moved bits
        meta.setdefault("flags_version", FLAGS_VERSION)
        sched = attack_schedule(getattr(cfg, "fault_plan", None))
        if sched:
            # attack scenarios stamp their schedule into the run header
            # so the dashboard can render active windows and evaluate the
            # default behavior contracts without the (jit-static) config
            meta.setdefault("attack_windows", sched)
        if getattr(cfg, "degree_buckets", None):
            # heavy-tailed underlays stamp their bucket partition (and
            # callers pass degree_stats=... for the realized degrees) so
            # the dashboard header states the graph shape the run is on
            meta.setdefault("degree_buckets",
                            [list(b) for b in cfg.degree_buckets])
        self.note("run",
                  fingerprint=checkpoint.config_fingerprint(cfg),
                  n_peers=cfg.n_peers, n_topics=cfg.n_topics,
                  invariant_mode=cfg.invariant_mode,
                  columns=[nm for nm, _ in health_columns(cfg.n_topics)],
                  **meta)

    def append_records(self, records: HealthRecord, member_ids=None,
                       **chunk_meta) -> int:
        """Stream one chunk: a ``chunk`` marker then the health rows,
        one fsync'd write each. Returns the row count."""
        mat, cols = records_to_rows(records, member_ids=member_ids)
        payload, self.encoder = encode_rows(mat, cols, self.prefer_native)
        self.note("chunk", rows=int(mat.shape[0]), encoder=self.encoder,
                  **chunk_meta)
        self._write(payload)
        return int(mat.shape[0])

    def append_dicts(self, rows: list, **chunk_meta) -> int:
        """Pre-built row dicts (the traced path's per-tick host records
        ride this; ``None`` values pass through as JSON null)."""
        self.note("chunk", rows=len(rows), encoder="python", **chunk_meta)
        if rows:
            self._write(("\n".join(
                json.dumps({"kind": "health", **r}) for r in rows)
                + "\n").encode())
        return len(rows)

    def close(self) -> None:
        if not self._fh.closed:
            self.sync()
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_journal(path: str) -> dict:
    """Tolerant journal read: ``{"runs", "chunks", "notes", "rows"}``.
    Torn tail lines (kill mid-append) are skipped; health rows dedup by
    ``(member, tick)`` with the LAST occurrence winning (a resumed run
    legitimately re-streams ticks after its restore point). The same
    last-wins discipline dedups ``contract_verdict`` notes by their
    deterministic id — a relaunch that re-derives a verdict already
    journaled before the crash (ISSUE 20 exactly-once) collapses to one
    note, in first-fired order."""
    runs, chunks, notes = [], [], []
    rows: dict = {}
    verdict_ids: dict = {}
    if not os.path.exists(path):
        return {"runs": runs, "chunks": chunks, "notes": notes, "rows": []}
    with open(path, "rb") as f:
        for raw in f:
            try:
                d = json.loads(raw)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue                        # torn tail line
            kind = d.get("kind")
            if kind == "health":
                rows[(d.get("member", -1), d.get("tick"))] = d
            elif kind == "run":
                runs.append(d)
            elif kind == "chunk":
                chunks.append(d)
            elif kind == "contract_verdict" and d.get("id") is not None:
                vid = d["id"]
                if vid in verdict_ids:
                    notes[verdict_ids[vid]] = d     # keep first position
                else:
                    verdict_ids[vid] = len(notes)
                    notes.append(d)
            else:
                notes.append(d)
    ordered = sorted(rows.values(),
                     key=lambda r: (r.get("tick", 0), r.get("member", -1)))
    return {"runs": runs, "chunks": chunks, "notes": notes, "rows": ordered}
