"""Adjacency constructors for the simulated swarm.

Mirrors the reference test harness's topology builders (floodsub_test.go:58-100
``connect/sparseConnect/denseConnect/connectAll`` and the star topologies in
gossipsub_test.go:1044-1127) as padded CSR-ish arrays:

- ``neighbors [N, K] int32``: peer index per slot, -1 for empty
- ``outbound  [N, K] bool``: True where this side dialed (gossipsub.go:467-476
  outbound-direction tracking feeds the Dout quota)
- ``reverse_slot [N, K] int32``: slot of me in my neighbor's table, -1 padding
  (precomputed inverse so cross-peer effects are scatter-able on device)

Builders are host-side numpy (topology churn is a scenario event, not a hot
op); results go to device once per scenario.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Topology(NamedTuple):
    neighbors: np.ndarray      # [N, K] int32, -1 padded
    outbound: np.ndarray       # [N, K] bool
    reverse_slot: np.ndarray   # [N, K] int32, -1 padded
    degree: np.ndarray         # [N] int32


def _finalize(n: int, k: int, adj: list[set[int]], dialed: set[tuple[int, int]]) -> Topology:
    neighbors = np.full((n, k), -1, dtype=np.int32)
    outbound = np.zeros((n, k), dtype=bool)
    slot_of: dict[tuple[int, int], int] = {}
    degree = np.zeros(n, dtype=np.int32)
    for i in range(n):
        nbrs = sorted(adj[i])[:k]
        degree[i] = len(nbrs)
        for s, j in enumerate(nbrs):
            neighbors[i, s] = j
            outbound[i, s] = (i, j) in dialed
            slot_of[(i, j)] = s
    reverse_slot = np.full((n, k), -1, dtype=np.int32)
    for (i, j), s in slot_of.items():
        rs = slot_of.get((j, i))
        if rs is not None:
            reverse_slot[i, s] = rs
    # capacity truncation can orphan one side of an edge; drop such slots so
    # every surviving edge is symmetric (one-sided edges would silently never
    # carry traffic through edge_gather)
    orphan = (neighbors >= 0) & (reverse_slot < 0)
    if orphan.any():
        neighbors[orphan] = -1
        outbound[orphan] = False
        degree = (neighbors >= 0).sum(axis=1).astype(np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree)


def sparse(n: int, k: int, degree: int = 3, seed: int = 314159) -> Topology:
    """Random graph, ``degree`` dials per peer (floodsub_test.go:75-82)."""
    rng = np.random.default_rng(seed)
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed: set[tuple[int, int]] = set()
    for i in range(n):
        choices = rng.permutation(n)
        added = 0
        for j in choices:
            j = int(j)
            if j == i or j in adj[i]:
                continue
            if len(adj[i]) >= k or len(adj[j]) >= k:
                continue
            adj[i].add(j)
            adj[j].add(i)
            dialed.add((i, j))
            added += 1
            if added >= degree:
                break
    return _finalize(n, k, adj, dialed)


def dense(n: int, k: int, degree: int = 10, seed: int = 314159) -> Topology:
    """Random graph, 10 dials per peer (floodsub_test.go:84-91)."""
    return sparse(n, k, degree=degree, seed=seed)


def sparse_fast(n: int, k: int, degree: int = 8,
                seed: int = 314159) -> Topology:
    """Vectorized random underlay for frontier-scale networks.

    :func:`sparse` walks a Python loop with an O(N) permutation per peer —
    O(N²) work that takes hours at 1M peers. This builder produces the
    same KIND of graph (each peer dials ``degree`` random targets, edges
    symmetric, per-peer degree capped at ``k``, ``reverse_slot`` a true
    involution, sorted-neighbor slot order exactly like ``_finalize``) in
    a handful of numpy passes: ~14 s at 1M×32 host-side (measured, see
    ROADMAP item 4 — and O(N·degree) host RAM: the build is global, so
    10M needs :func:`sparse_hash` instead). It is NOT
    sample-identical to ``sparse`` for the same seed — the frontier
    scenario family (sim/scenarios.py) owns it; the BASELINE scenarios
    keep their historical builder and seeds.

    Construction: draw N·degree dials, dedupe unordered pairs, drop the
    (rare: Poisson tail) edges that would push an endpoint past ``k`` —
    whole edges, so symmetry is preserved — then assign slots per peer in
    sorted-neighbor order and pair the two directions of each edge for
    ``reverse_slot``.
    """
    if n < 2:
        raise ValueError(f"sparse_fast needs n >= 2, got {n}")
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n, dtype=np.int64), degree)
    dst = rng.integers(0, n - 1, n * degree, dtype=np.int64)
    dst += dst >= src                                   # never self
    a, b = np.minimum(src, dst), np.maximum(src, dst)
    pair_key, first_idx = np.unique(a * n + b, return_index=True)
    a, b = pair_key // n, pair_key % n
    # dial direction: the first drawn occurrence of the pair keeps its
    # orientation (that endpoint dialed -> outbound on its side)
    dialed_by_a = src[first_idx] == a

    # capacity: arrival rank of each edge within its endpoint's FULL
    # incidence list (both roles — a node's degree counts every edge it
    # touches), edges in pair-key order — deterministic; drop edges where
    # either endpoint is already at k
    ec = len(a)
    ends = np.concatenate([a, b])                       # [2E] endpoint ids
    eidx = np.concatenate([np.arange(ec), np.arange(ec)])
    order = np.lexsort((eidx, ends))
    starts = np.searchsorted(ends[order], ends[order])
    rank = np.empty(2 * ec, np.int64)
    rank[order] = np.arange(2 * ec) - starts
    keep = (rank[:ec] < k) & (rank[ec:] < k)
    a, b, dialed_by_a = a[keep], b[keep], dialed_by_a[keep]

    # directed views: edge e appears as (a->b) and (b->a)
    e = len(a)
    u = np.concatenate([a, b])                          # [2E] source
    v = np.concatenate([b, a])                          # [2E] target
    outbound_dir = np.concatenate([dialed_by_a, ~dialed_by_a])
    # slot per directed edge: position of v among u's sorted neighbors
    order = np.lexsort((v, u))
    starts = np.searchsorted(u[order], u[order])
    slot = np.empty(2 * e, np.int64)
    slot[order] = np.arange(2 * e) - starts
    # the reverse direction of directed edge i is i±E by construction
    rev = np.concatenate([slot[e:], slot[:e]])

    neighbors = np.full((n, k), -1, np.int32)
    outbound = np.zeros((n, k), bool)
    reverse_slot = np.full((n, k), -1, np.int32)
    neighbors[u, slot] = v.astype(np.int32)
    outbound[u, slot] = outbound_dir
    reverse_slot[u, slot] = rev.astype(np.int32)
    degree_arr = (neighbors >= 0).sum(axis=1).astype(np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree_arr)


def hash_offsets(n: int, degree: int, seed: int = 314159) -> np.ndarray:
    """The ``degree`` seed-derived circulant offsets :func:`sparse_hash`
    builds from — distinct, never 0 or n/2, and no two complements mod n
    (rejection-sampled), so every peer's 2·degree targets are distinct
    and every edge appears exactly once per direction."""
    if degree < 1 or degree > max(0, (n - 1) // 2):
        raise ValueError(
            f"sparse_hash: degree={degree} needs 1 <= degree <= "
            f"(n-1)//2 = {(n - 1) // 2} distinct offset classes at n={n}")
    rng = np.random.default_rng(seed)
    offs: list[int] = []
    taken: set[int] = set()
    while len(offs) < degree:
        o = int(rng.integers(1, n))
        if o in taken or (n - o) in taken or 2 * o == n:
            continue
        taken.add(o)
        offs.append(o)
    return np.array(sorted(offs), np.int64)


def sparse_hash(n: int, k: int, degree: int = 8, seed: int = 314159,
                rows: tuple[int, int] | None = None,
                chunk_rows: int = 16384) -> Topology:
    """Shard-constructible pseudo-random underlay: a circulant graph on
    seeded-hash offsets, where EVERY row is a pure function of
    ``(n, degree, seed, row)`` — no global table, ever.

    ``sparse_fast``'s pair-dedup / capacity-rank passes are global (row
    i's slots depend on every other row's draws), so a 1M×32 build costs
    ~14 s and O(N·degree) host RAM on ONE host — ~10x worse at 10M, the
    wall ROADMAP item 4 names. Here peer i's neighbors are
    ``{(i ± o_d) mod n}`` for ``degree`` offsets drawn once from the
    seed (:func:`hash_offsets`): each multihost process materializes
    ONLY its ``rows=(start, count)`` shard of every ``[N, K]`` plane
    (``parallel.multihost.init_state_local(..., topo_local=True)``
    consumes it directly), and the concat across processes equals the
    single-host build bit for bit BY CONSTRUCTION
    (tests/test_topology_sharded.py pins parity at P∈{2,4} plus a
    peak-RSS ceiling on the shard build).

    Graph shape: 2·degree-regular (uniform — the degree-histogram
    analogue of ``sparse_fast``'s Poisson spread), symmetric, slots in
    sorted-neighbor order like ``_finalize``; the "+" offset direction
    is the dialed (outbound) side. ``reverse_slot`` is computed locally
    by ranking ``i`` inside its neighbor's formulaic neighbor set —
    [chunk, 2·degree, 2·degree] comparisons per chunk, never a global
    pass. Like ``sparse_fast`` it is not sample-identical to ``sparse``.
    """
    if n < 2:
        raise ValueError(f"sparse_hash needs n >= 2, got {n}")
    if 2 * degree > k:
        raise ValueError(
            f"sparse_hash: 2*degree={2 * degree} slots needed > k={k}")
    offs = hash_offsets(n, degree, seed)
    r0, cnt = (0, n) if rows is None else rows
    if r0 < 0 or cnt < 0 or r0 + cnt > n:
        raise ValueError(f"sparse_hash: rows=({r0}, {cnt}) outside [0, {n})")
    neighbors = np.full((cnt, k), -1, np.int32)
    outbound = np.zeros((cnt, k), bool)
    reverse_slot = np.full((cnt, k), -1, np.int32)
    d2 = 2 * degree
    for c0 in range(0, cnt, chunk_rows):
        c1 = min(c0 + chunk_rows, cnt)
        i = np.arange(r0 + c0, r0 + c1, dtype=np.int64)[:, None]   # [R, 1]
        nbrs = np.concatenate([(i + offs) % n, (i - offs) % n], 1)  # [R, 2D]
        dialed = np.concatenate([np.ones_like(offs, bool),
                                 np.zeros_like(offs, bool)])        # [2D]
        order = np.argsort(nbrs, axis=1, kind="stable")
        nb_s = np.take_along_axis(nbrs, order, 1)
        out_s = np.take_along_axis(np.broadcast_to(dialed, nbrs.shape),
                                   order, 1)
        # my slot in neighbor j's table = rank of i among j's OWN sorted
        # neighbor set {(j ± o) mod n} — formulaic, so strictly local
        j_nbrs = np.concatenate([(nb_s[:, :, None] + offs) % n,
                                 (nb_s[:, :, None] - offs) % n], 2)
        rev = np.sum(j_nbrs < i[:, :, None], axis=2, dtype=np.int64)
        neighbors[c0:c1, :d2] = nb_s.astype(np.int32)
        outbound[c0:c1, :d2] = out_s
        reverse_slot[c0:c1, :d2] = rev.astype(np.int32)
    degree_arr = np.full(cnt, d2, np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree_arr)


def powerlaw_levels(n: int, d_min: int = 8, d_max: int = 64,
                    alpha: float = 2.0) -> list[tuple[int, int]]:
    """The prefix-nested ring schedule realizing a truncated power-law
    degree sequence: ``[(m_l, c_l)]`` where level ``l`` is a circulant on
    the id-prefix ``[0, m_l)`` with ``c_l`` seed-drawn offsets (2 edges
    per offset per member). Peer ``i``'s degree is ``2 * sum(c_l for
    m_l > i)`` — non-increasing with id, so the hubs are the LOW ids
    (the contiguous region eclipse windows target, sim/faults.py).

    Level ``l`` covers the prefix ``m_l ~ n * 2^(-l*(alpha-1))`` and
    doubles the prefix's degree, which realizes the complementary-CDF
    ``P(D >= x) ~ (x/d_min)^-(alpha-1)`` of a truncated power law with
    tail exponent ``alpha`` (alpha=2 halves the prefix per doubling).
    The last level is trimmed so the hub degree lands on ``d_max``
    exactly when the prefix has room for its offset classes; levels
    whose prefix gets too small for distinct offset classes are dropped
    (the realized hub degree is then below ``d_max`` — callers read the
    realized ceiling off ``powerlaw_buckets``/``degree_stats``)."""
    if n < 4:
        raise ValueError(f"powerlaw needs n >= 4, got {n}")
    if d_min < 2 or d_min % 2:
        raise ValueError(f"powerlaw: d_min must be even >= 2, got {d_min}")
    if d_max < d_min:
        raise ValueError(f"powerlaw: d_max={d_max} < d_min={d_min}")
    if alpha <= 1.0:
        raise ValueError(f"powerlaw: alpha={alpha} needs alpha > 1")
    levels = [(n, d_min // 2)]
    deg = 2 * (d_min // 2)
    lev = 1
    while deg < d_max:
        m = int(np.ceil(n * 2.0 ** (-lev * (alpha - 1.0))))
        c = min((d_max - deg) // 2, deg // 2)       # doubling, d_max-trimmed
        if c < 1 or m < 4 * c + 4:
            break                # prefix too small for c distinct classes
        levels.append((m, c))
        deg += 2 * c
        lev += 1
    return levels


def _powerlaw_offsets(levels: list[tuple[int, int]],
                      seed: int) -> list[np.ndarray]:
    """Per-level circulant offsets with GLOBALLY disjoint difference
    classes: an accepted offset ``o`` of level ``l`` reserves the integer
    class ``{o, m_l - o}``, and every candidate colliding with any
    reserved value (its own level's or another's) is rejected. Disjoint
    classes mean two levels can never produce the same (i, j) pair — the
    construction is duplicate-free WITHOUT a dedup pass, so every row's
    slot count is exactly its formulaic degree and ``reverse_slot``
    ranks against a formulaic (never materialized) neighbor set."""
    rng = np.random.default_rng(seed)
    taken: set[int] = set()
    out: list[np.ndarray] = []
    for m, c in levels:
        offs: list[int] = []
        tries = 0
        while len(offs) < c:
            tries += 1
            if tries > 1000 * c:
                raise ValueError(
                    f"powerlaw: could not draw {c} disjoint offset "
                    f"classes in a ring of {m} (degree schedule too "
                    "dense for this n — lower d_max or raise n)")
            o = int(rng.integers(1, m))
            if o in taken or (m - o) in taken or 2 * o == m:
                continue
            taken.add(o)
            taken.add(m - o)
            offs.append(o)
        out.append(np.array(sorted(offs), np.int64))
    return out


def powerlaw(n: int, k: int, d_min: int = 8, d_max: int = 64,
             alpha: float = 2.0, seed: int = 314159,
             rows: tuple[int, int] | None = None,
             chunk_elems: int = 1 << 22) -> Topology:
    """Shard-constructible heavy-tailed underlay: a truncated power-law
    degree sequence (tail exponent ``alpha``, degrees in
    ``[d_min, ~d_max]``, non-increasing with peer id) realized as
    prefix-nested seeded circulants — the configuration-model analogue
    of :func:`sparse_hash`, where every row is a pure function of
    ``(n, d_min, d_max, alpha, seed, row)`` and a ``rows=(start,
    count)`` build materializes only that shard of every plane (concat
    across shards equals the full build bit for bit;
    tests/test_topology_powerlaw.py pins ragged splits).

    Graph shape: symmetric, duplicate-free (disjoint difference classes
    across levels — :func:`_powerlaw_offsets`), slots in sorted-neighbor
    order like ``_finalize``, the "+" offset direction dialed
    (outbound). Hubs are the LOW ids: the contiguous region
    :class:`sim.faults.EclipseWindow` targets, which is what makes the
    ``heavytail_eclipse`` scenario expressible. ``reverse_slot`` ranks
    ``i`` inside each neighbor's formulaic candidate set — strictly
    local, chunk cost ``[R, D_row, D_max]`` with ``R`` auto-shrunk near
    the hubs (``chunk_elems`` bounds the temporary)."""
    levels = powerlaw_levels(n, d_min=d_min, d_max=d_max, alpha=alpha)
    offs = _powerlaw_offsets(levels, seed)
    dmax_real = 2 * sum(c for _, c in levels)
    if k < dmax_real:
        raise ValueError(
            f"powerlaw: hub degree {dmax_real} needs k >= {dmax_real}, "
            f"got k={k}")
    r0, cnt = (0, n) if rows is None else rows
    if r0 < 0 or cnt < 0 or r0 + cnt > n:
        raise ValueError(f"powerlaw: rows=({r0}, {cnt}) outside [0, {n})")

    # flattened per-level candidate schedule: for each level l and offset
    # o, two signed columns (+o then -o) in canonical (level, offset,
    # sign) order — first occurrence IS the only occurrence (disjoint
    # classes), so direction needs no tie-break
    col_m = np.concatenate([np.full(2 * len(o), m, np.int64)
                            for (m, _), o in zip(levels, offs)])
    # interleave so sign order within (level, offset) is [+, -]
    col_off = np.concatenate([np.stack([o, -o], 1).reshape(-1)
                              for o in offs])
    col_out = np.tile(np.array([True, False]),
                      col_m.size // 2)                  # '+' side dialed
    return _powerlaw_fill(n, k, cnt, r0, levels, offs, col_m, col_off,
                          col_out, chunk_elems)


def _ring_rank_below(j: np.ndarray, i: np.ndarray, offs_sorted: np.ndarray,
                     m: int) -> np.ndarray:
    """#{x in {(j±o) mod m : o in offs_sorted} : x < i} in closed form —
    each of the four (sign, wrap) branches is a contiguous offset
    interval, counted by searchsorted on the SORTED offsets. This is
    what keeps ``reverse_slot`` construction at ``ΣD·levels·log c``
    instead of materializing every neighbor's candidate set
    (``ΣD·D_max``, minutes at 1M)."""
    O = offs_sorted

    def upto(v):                              # #{o in O : o <= v}
        return np.searchsorted(O, v, side="right")

    # '+' no wrap: o <= m-1-j, x = j+o < i          -> o <= min(i-j-1, m-1-j)
    ca = upto(np.minimum(i - j - 1, m - 1 - j))
    # '+' wrap:    o >= m-j,   x = j+o-m < i        -> o <= m-j+i-1
    cb = upto(np.minimum(m - j + i - 1, m - 1)) - upto(m - j - 1)
    # '-' no wrap: o <= j,     x = j-o < i          -> o >= j-i+1
    cc = upto(np.minimum(j, m - 1)) - upto(np.maximum(j - i + 1, 1) - 1)
    # '-' wrap:    o >= j+1,   x = j-o+m < i        -> o >= m+j-i+1
    cd = upto(m - 1) - upto(np.maximum(j + 1, m + j - i + 1) - 1)
    return ca + cb + cc + cd


def _powerlaw_fill(n, k, cnt, r0, levels, offs, col_m, col_off, col_out,
                   chunk_elems) -> Topology:
    neighbors = np.full((cnt, k), -1, np.int32)
    outbound = np.zeros((cnt, k), bool)
    reverse_slot = np.full((cnt, k), -1, np.int32)
    c0 = 0
    while c0 < cnt:
        # the chunk's first row is its widest (degrees non-increasing);
        # drop columns of levels no chunk row belongs to
        act = col_m > r0 + c0
        am, ao, aout = col_m[act], col_off[act], col_out[act]
        width = int(act.sum())
        rchunk = max(64, int(chunk_elems // max(width, 1)))
        c1 = min(c0 + rchunk, cnt)
        i = np.arange(r0 + c0, r0 + c1, dtype=np.int64)[:, None]   # [R, 1]
        member = i < am[None, :]                                   # [R, W]
        cand = np.where(member, (i + ao[None, :]) % am[None, :],
                        np.int64(n))
        order = np.argsort(cand, axis=1, kind="stable")
        nb_s = np.take_along_axis(cand, order, 1)                  # [R, W]
        out_s = np.take_along_axis(
            np.broadcast_to(aout, cand.shape), order, 1)
        valid = nb_s < n
        j = np.where(valid, nb_s, 0)
        # my slot in neighbor j's table = rank of i among j's formulaic
        # candidates, summed over the levels j belongs to (duplicate-free
        # across levels, so rank == sorted-slot index)
        rev = np.zeros_like(j)
        for (m, _), o in zip(levels, offs):
            lvl = j < m                                            # [R, W]
            cnt_l = _ring_rank_below(np.where(lvl, j, 0), i, o, m)
            rev += np.where(lvl, cnt_l, 0)
        take = min(width, k)
        neighbors[c0:c1, :take] = np.where(valid, nb_s, -1)[:, :take]
        outbound[c0:c1, :take] = (valid & out_s)[:, :take]
        reverse_slot[c0:c1, :take] = np.where(valid, rev, -1)[:, :take]
        c0 = c1
    degree_arr = (neighbors >= 0).sum(axis=1).astype(np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree_arr)


def powerlaw_buckets(n: int, d_min: int = 8, d_max: int = 64,
                     alpha: float = 2.0, round_to: int = 8,
                     ) -> tuple[tuple[int, int], ...]:
    """The degree-bucket partition a :func:`powerlaw` graph induces:
    ``((n_rows, k_ceil), ...)`` in id order — one bucket per maximal
    contiguous equal-degree id range (the level-prefix boundaries), each
    ceiling rounded up to ``round_to`` slots (lane friendliness). This
    is the value ``SimConfig.degree_buckets`` takes; ``k_slots`` must
    equal the first (hub) bucket's ceiling — ``sim.bucketed`` validates.
    """
    levels = powerlaw_levels(n, d_min=d_min, d_max=d_max, alpha=alpha)
    bounds = sorted({m for m, _ in levels})             # ascending prefixes
    out = []
    prev = 0
    for m in bounds:
        deg = 2 * sum(c for (ml, c) in levels if ml >= m)
        ceil = -(-max(deg, 1) // round_to) * round_to
        out.append((m - prev, ceil))
        prev = m
    return tuple(out)


def align_degree_buckets(buckets, align: int) -> tuple:
    """``buckets`` (:func:`powerlaw_buckets` output) with every cumulative
    bucket boundary rounded UP to a multiple of ``align`` — the partition
    the ROW-SHARDED bucketed plane needs, where every bucket's rows must
    split evenly over the device mesh (parallel/sharding.
    bucketed_state_shardings refuses unaligned buckets by name).

    Rounding UP moves boundary rows INTO the earlier — wider — bucket,
    which is always safe: ceilings are non-increasing hubs-first, so an
    absorbed row's edges all fit below its new (wider) ceiling; rounding
    DOWN would orphan high-degree rows under a too-narrow ceiling.
    Buckets emptied by the move drop out.

    Pick an ``align`` that is INDEPENDENT of the current process count
    (scenarios.POWERLAW_MH_ALIGN): the partition feeds the checkpoint
    fingerprint, and an elastic P -> P' resume (sim/supervisor.py) must
    see the SAME partition at both sizes — any P' dividing ``align``
    shards the aligned buckets evenly."""
    bks = tuple((int(r), int(k)) for r, k in buckets)
    n = sum(r for r, _ in bks)
    if align <= 0 or n % align:
        raise ValueError(
            f"align_degree_buckets: {n} rows do not tile align={align}; "
            "the id space itself must be a multiple of the alignment")
    out, prev = [], 0
    end = 0
    for r, kb in bks:
        end += r
        new_end = min(n, -(-end // align) * align)
        new_end = max(new_end, prev)             # keep boundaries monotone
        if new_end > prev:
            out.append((new_end - prev, kb))
        prev = new_end
    return tuple(out)


def degree_stats(topo: "Topology | np.ndarray") -> dict:
    """Shape summary of an underlay's degree sequence — stamped into
    bench records and the dashboard header so every banked line states
    the graph it ran on: min/mean/p99/max degree and the Gini
    coefficient of the degree distribution (0 = uniform-degree, ~0.5+
    = heavy-tailed)."""
    deg = np.asarray(topo.degree if isinstance(topo, Topology) else topo,
                     np.int64)
    if deg.size == 0:
        raise ValueError("degree_stats: empty degree sequence")
    srt = np.sort(deg)
    total = int(srt.sum())
    if total > 0:
        cum = np.cumsum(srt, dtype=np.int64)
        gini = float((deg.size + 1 - 2 * (cum.sum() / total)) / deg.size)
    else:
        gini = 0.0
    return {"n": int(deg.size), "sum": total,
            "min": int(srt[0]), "max": int(srt[-1]),
            "mean": round(float(srt.mean()), 3),
            "p99": int(np.percentile(srt, 99, method="lower")),
            "gini": round(gini, 4)}


def full(n: int, k: int) -> Topology:
    """Complete graph (connectAll, floodsub_test.go:93-100). Requires k >= n-1."""
    if k < n - 1:
        raise ValueError(f"full({n=}) needs k >= {n - 1}, got {k}")
    adj = [set(range(n)) - {i} for i in range(n)]
    dialed = {(i, j) for i in range(n) for j in range(i + 1, n)}
    return _finalize(n, k, adj, dialed)


def from_hosts(hosts, k: int) -> tuple[Topology, dict]:
    """Topology mirroring a functional-runtime network's live connections
    (net/network.py Host.conns), plus the peer-id -> index map.

    Slot assignment matches ``_finalize`` (sorted neighbor ids), so a trace
    replayed into this topology addresses the same (peer, slot) cells the
    live routers mutated. Dial direction comes from the substrate's
    "outbound"/"inbound" conn tags (gossipsub.go:467-476 feeds Dout).
    """
    n = len(hosts)
    peer_index = {h.peer_id: i for i, h in enumerate(hosts)}
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed: set[tuple[int, int]] = set()
    for i, h in enumerate(hosts):
        for pid, direction in h.conns.items():
            j = peer_index.get(pid)
            if j is None:
                continue
            adj[i].add(j)
            if direction == "outbound":
                dialed.add((i, j))
    return _finalize(n, k, adj, dialed), peer_index


def star(n: int, k: int) -> Topology:
    """Peer 0 is the hub (gossipsub_test.go:1044-1127)."""
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed = set()
    for i in range(1, n):
        adj[0].add(i)
        adj[i].add(0)
        dialed.add((i, 0))
    return _finalize(n, k, adj, dialed)
