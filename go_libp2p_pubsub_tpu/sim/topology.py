"""Adjacency constructors for the simulated swarm.

Mirrors the reference test harness's topology builders (floodsub_test.go:58-100
``connect/sparseConnect/denseConnect/connectAll`` and the star topologies in
gossipsub_test.go:1044-1127) as padded CSR-ish arrays:

- ``neighbors [N, K] int32``: peer index per slot, -1 for empty
- ``outbound  [N, K] bool``: True where this side dialed (gossipsub.go:467-476
  outbound-direction tracking feeds the Dout quota)
- ``reverse_slot [N, K] int32``: slot of me in my neighbor's table, -1 padding
  (precomputed inverse so cross-peer effects are scatter-able on device)

Builders are host-side numpy (topology churn is a scenario event, not a hot
op); results go to device once per scenario.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Topology(NamedTuple):
    neighbors: np.ndarray      # [N, K] int32, -1 padded
    outbound: np.ndarray       # [N, K] bool
    reverse_slot: np.ndarray   # [N, K] int32, -1 padded
    degree: np.ndarray         # [N] int32


def _finalize(n: int, k: int, adj: list[set[int]], dialed: set[tuple[int, int]]) -> Topology:
    neighbors = np.full((n, k), -1, dtype=np.int32)
    outbound = np.zeros((n, k), dtype=bool)
    slot_of: dict[tuple[int, int], int] = {}
    degree = np.zeros(n, dtype=np.int32)
    for i in range(n):
        nbrs = sorted(adj[i])[:k]
        degree[i] = len(nbrs)
        for s, j in enumerate(nbrs):
            neighbors[i, s] = j
            outbound[i, s] = (i, j) in dialed
            slot_of[(i, j)] = s
    reverse_slot = np.full((n, k), -1, dtype=np.int32)
    for (i, j), s in slot_of.items():
        rs = slot_of.get((j, i))
        if rs is not None:
            reverse_slot[i, s] = rs
    # capacity truncation can orphan one side of an edge; drop such slots so
    # every surviving edge is symmetric (one-sided edges would silently never
    # carry traffic through edge_gather)
    orphan = (neighbors >= 0) & (reverse_slot < 0)
    if orphan.any():
        neighbors[orphan] = -1
        outbound[orphan] = False
        degree = (neighbors >= 0).sum(axis=1).astype(np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree)


def sparse(n: int, k: int, degree: int = 3, seed: int = 314159) -> Topology:
    """Random graph, ``degree`` dials per peer (floodsub_test.go:75-82)."""
    rng = np.random.default_rng(seed)
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed: set[tuple[int, int]] = set()
    for i in range(n):
        choices = rng.permutation(n)
        added = 0
        for j in choices:
            j = int(j)
            if j == i or j in adj[i]:
                continue
            if len(adj[i]) >= k or len(adj[j]) >= k:
                continue
            adj[i].add(j)
            adj[j].add(i)
            dialed.add((i, j))
            added += 1
            if added >= degree:
                break
    return _finalize(n, k, adj, dialed)


def dense(n: int, k: int, degree: int = 10, seed: int = 314159) -> Topology:
    """Random graph, 10 dials per peer (floodsub_test.go:84-91)."""
    return sparse(n, k, degree=degree, seed=seed)


def sparse_fast(n: int, k: int, degree: int = 8,
                seed: int = 314159) -> Topology:
    """Vectorized random underlay for frontier-scale networks.

    :func:`sparse` walks a Python loop with an O(N) permutation per peer —
    O(N²) work that takes hours at 1M peers. This builder produces the
    same KIND of graph (each peer dials ``degree`` random targets, edges
    symmetric, per-peer degree capped at ``k``, ``reverse_slot`` a true
    involution, sorted-neighbor slot order exactly like ``_finalize``) in
    a handful of numpy passes: ~14 s at 1M×32 host-side (measured, see
    ROADMAP item 4 — and O(N·degree) host RAM: the build is global, so
    10M needs :func:`sparse_hash` instead). It is NOT
    sample-identical to ``sparse`` for the same seed — the frontier
    scenario family (sim/scenarios.py) owns it; the BASELINE scenarios
    keep their historical builder and seeds.

    Construction: draw N·degree dials, dedupe unordered pairs, drop the
    (rare: Poisson tail) edges that would push an endpoint past ``k`` —
    whole edges, so symmetry is preserved — then assign slots per peer in
    sorted-neighbor order and pair the two directions of each edge for
    ``reverse_slot``.
    """
    if n < 2:
        raise ValueError(f"sparse_fast needs n >= 2, got {n}")
    rng = np.random.default_rng(seed)
    src = np.repeat(np.arange(n, dtype=np.int64), degree)
    dst = rng.integers(0, n - 1, n * degree, dtype=np.int64)
    dst += dst >= src                                   # never self
    a, b = np.minimum(src, dst), np.maximum(src, dst)
    pair_key, first_idx = np.unique(a * n + b, return_index=True)
    a, b = pair_key // n, pair_key % n
    # dial direction: the first drawn occurrence of the pair keeps its
    # orientation (that endpoint dialed -> outbound on its side)
    dialed_by_a = src[first_idx] == a

    # capacity: arrival rank of each edge within its endpoint's FULL
    # incidence list (both roles — a node's degree counts every edge it
    # touches), edges in pair-key order — deterministic; drop edges where
    # either endpoint is already at k
    ec = len(a)
    ends = np.concatenate([a, b])                       # [2E] endpoint ids
    eidx = np.concatenate([np.arange(ec), np.arange(ec)])
    order = np.lexsort((eidx, ends))
    starts = np.searchsorted(ends[order], ends[order])
    rank = np.empty(2 * ec, np.int64)
    rank[order] = np.arange(2 * ec) - starts
    keep = (rank[:ec] < k) & (rank[ec:] < k)
    a, b, dialed_by_a = a[keep], b[keep], dialed_by_a[keep]

    # directed views: edge e appears as (a->b) and (b->a)
    e = len(a)
    u = np.concatenate([a, b])                          # [2E] source
    v = np.concatenate([b, a])                          # [2E] target
    outbound_dir = np.concatenate([dialed_by_a, ~dialed_by_a])
    # slot per directed edge: position of v among u's sorted neighbors
    order = np.lexsort((v, u))
    starts = np.searchsorted(u[order], u[order])
    slot = np.empty(2 * e, np.int64)
    slot[order] = np.arange(2 * e) - starts
    # the reverse direction of directed edge i is i±E by construction
    rev = np.concatenate([slot[e:], slot[:e]])

    neighbors = np.full((n, k), -1, np.int32)
    outbound = np.zeros((n, k), bool)
    reverse_slot = np.full((n, k), -1, np.int32)
    neighbors[u, slot] = v.astype(np.int32)
    outbound[u, slot] = outbound_dir
    reverse_slot[u, slot] = rev.astype(np.int32)
    degree_arr = (neighbors >= 0).sum(axis=1).astype(np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree_arr)


def hash_offsets(n: int, degree: int, seed: int = 314159) -> np.ndarray:
    """The ``degree`` seed-derived circulant offsets :func:`sparse_hash`
    builds from — distinct, never 0 or n/2, and no two complements mod n
    (rejection-sampled), so every peer's 2·degree targets are distinct
    and every edge appears exactly once per direction."""
    if degree < 1 or degree > max(0, (n - 1) // 2):
        raise ValueError(
            f"sparse_hash: degree={degree} needs 1 <= degree <= "
            f"(n-1)//2 = {(n - 1) // 2} distinct offset classes at n={n}")
    rng = np.random.default_rng(seed)
    offs: list[int] = []
    taken: set[int] = set()
    while len(offs) < degree:
        o = int(rng.integers(1, n))
        if o in taken or (n - o) in taken or 2 * o == n:
            continue
        taken.add(o)
        offs.append(o)
    return np.array(sorted(offs), np.int64)


def sparse_hash(n: int, k: int, degree: int = 8, seed: int = 314159,
                rows: tuple[int, int] | None = None,
                chunk_rows: int = 16384) -> Topology:
    """Shard-constructible pseudo-random underlay: a circulant graph on
    seeded-hash offsets, where EVERY row is a pure function of
    ``(n, degree, seed, row)`` — no global table, ever.

    ``sparse_fast``'s pair-dedup / capacity-rank passes are global (row
    i's slots depend on every other row's draws), so a 1M×32 build costs
    ~14 s and O(N·degree) host RAM on ONE host — ~10x worse at 10M, the
    wall ROADMAP item 4 names. Here peer i's neighbors are
    ``{(i ± o_d) mod n}`` for ``degree`` offsets drawn once from the
    seed (:func:`hash_offsets`): each multihost process materializes
    ONLY its ``rows=(start, count)`` shard of every ``[N, K]`` plane
    (``parallel.multihost.init_state_local(..., topo_local=True)``
    consumes it directly), and the concat across processes equals the
    single-host build bit for bit BY CONSTRUCTION
    (tests/test_topology_sharded.py pins parity at P∈{2,4} plus a
    peak-RSS ceiling on the shard build).

    Graph shape: 2·degree-regular (uniform — the degree-histogram
    analogue of ``sparse_fast``'s Poisson spread), symmetric, slots in
    sorted-neighbor order like ``_finalize``; the "+" offset direction
    is the dialed (outbound) side. ``reverse_slot`` is computed locally
    by ranking ``i`` inside its neighbor's formulaic neighbor set —
    [chunk, 2·degree, 2·degree] comparisons per chunk, never a global
    pass. Like ``sparse_fast`` it is not sample-identical to ``sparse``.
    """
    if n < 2:
        raise ValueError(f"sparse_hash needs n >= 2, got {n}")
    if 2 * degree > k:
        raise ValueError(
            f"sparse_hash: 2*degree={2 * degree} slots needed > k={k}")
    offs = hash_offsets(n, degree, seed)
    r0, cnt = (0, n) if rows is None else rows
    if r0 < 0 or cnt < 0 or r0 + cnt > n:
        raise ValueError(f"sparse_hash: rows=({r0}, {cnt}) outside [0, {n})")
    neighbors = np.full((cnt, k), -1, np.int32)
    outbound = np.zeros((cnt, k), bool)
    reverse_slot = np.full((cnt, k), -1, np.int32)
    d2 = 2 * degree
    for c0 in range(0, cnt, chunk_rows):
        c1 = min(c0 + chunk_rows, cnt)
        i = np.arange(r0 + c0, r0 + c1, dtype=np.int64)[:, None]   # [R, 1]
        nbrs = np.concatenate([(i + offs) % n, (i - offs) % n], 1)  # [R, 2D]
        dialed = np.concatenate([np.ones_like(offs, bool),
                                 np.zeros_like(offs, bool)])        # [2D]
        order = np.argsort(nbrs, axis=1, kind="stable")
        nb_s = np.take_along_axis(nbrs, order, 1)
        out_s = np.take_along_axis(np.broadcast_to(dialed, nbrs.shape),
                                   order, 1)
        # my slot in neighbor j's table = rank of i among j's OWN sorted
        # neighbor set {(j ± o) mod n} — formulaic, so strictly local
        j_nbrs = np.concatenate([(nb_s[:, :, None] + offs) % n,
                                 (nb_s[:, :, None] - offs) % n], 2)
        rev = np.sum(j_nbrs < i[:, :, None], axis=2, dtype=np.int64)
        neighbors[c0:c1, :d2] = nb_s.astype(np.int32)
        outbound[c0:c1, :d2] = out_s
        reverse_slot[c0:c1, :d2] = rev.astype(np.int32)
    degree_arr = np.full(cnt, d2, np.int32)
    return Topology(neighbors, outbound, reverse_slot, degree_arr)


def full(n: int, k: int) -> Topology:
    """Complete graph (connectAll, floodsub_test.go:93-100). Requires k >= n-1."""
    if k < n - 1:
        raise ValueError(f"full({n=}) needs k >= {n - 1}, got {k}")
    adj = [set(range(n)) - {i} for i in range(n)]
    dialed = {(i, j) for i in range(n) for j in range(i + 1, n)}
    return _finalize(n, k, adj, dialed)


def from_hosts(hosts, k: int) -> tuple[Topology, dict]:
    """Topology mirroring a functional-runtime network's live connections
    (net/network.py Host.conns), plus the peer-id -> index map.

    Slot assignment matches ``_finalize`` (sorted neighbor ids), so a trace
    replayed into this topology addresses the same (peer, slot) cells the
    live routers mutated. Dial direction comes from the substrate's
    "outbound"/"inbound" conn tags (gossipsub.go:467-476 feeds Dout).
    """
    n = len(hosts)
    peer_index = {h.peer_id: i for i, h in enumerate(hosts)}
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed: set[tuple[int, int]] = set()
    for i, h in enumerate(hosts):
        for pid, direction in h.conns.items():
            j = peer_index.get(pid)
            if j is None:
                continue
            adj[i].add(j)
            if direction == "outbound":
                dialed.add((i, j))
    return _finalize(n, k, adj, dialed), peer_index


def star(n: int, k: int) -> Topology:
    """Peer 0 is the hub (gossipsub_test.go:1044-1127)."""
    adj: list[set[int]] = [set() for _ in range(n)]
    dialed = set()
    for i in range(1, n):
        adj[0].add(i)
        adj[i].add(0)
        dialed.add((i, 0))
    return _finalize(n, k, adj, dialed)
